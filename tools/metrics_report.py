#!/usr/bin/env python
"""Render / produce raft_tpu observability artifacts.

The same snapshot shape everywhere: ``Session.metrics_snapshot()``,
``bench.py``'s embedded ``metrics_snapshot``, and this CLI all carry
``{metrics, compile_cache, profiler_tree, event_counters}`` (see
docs/OBSERVABILITY.md), so one tool reads them all.

Usage:
    # pretty-print a dumped snapshot (Session.dump_metrics / bench JSON)
    python tools/metrics_report.py snapshot.json
    python tools/metrics_report.py bench.json --format prom
    python tools/metrics_report.py snapshot.json --format json

    # run a tiny instrumented workload (pairwise + knn + allreduce +
    # buffer churn) and report it — the zero-to-numbers smoke path
    python tools/metrics_report.py --demo
    python tools/metrics_report.py --demo --out snapshot.json

    # live mode against an embedded ops plane (Session.serve_ops /
    # OpsPlane; docs/OBSERVABILITY.md "Ops plane"): poll
    # /debug/snapshot every N seconds, re-render in place
    python tools/metrics_report.py --url http://127.0.0.1:9100 --watch 2
    python tools/metrics_report.py --url http://127.0.0.1:9100 --format prom
    python tools/metrics_report.py snapshot.json --watch 5   # re-read file

Formats: ``report`` (default; human-readable tables + span tree),
``json`` (the raw snapshot), ``prom`` (Prometheus text format for the
registry half — available with --demo or --url, since a dumped
snapshot has already flattened the registry).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _fmt_s(v: float) -> str:
    if v >= 1.0:
        return "%.3fs" % v
    if v >= 1e-3:
        return "%.3fms" % (v * 1e3)
    return "%.1fus" % (v * 1e6)


def render_report(snap: dict) -> str:
    lines = []
    metrics = snap.get("metrics", {})
    fleet = _fleet_summary(snap)
    if fleet:
        lines.append("== fleet (router aggregate; docs/FAULT_MODEL.md "
                     "\"Fleet fault domains\") ==")
        lines.extend(fleet)
    timers = {n: f for n, f in metrics.items() if f.get("type") == "timer"}
    if timers:
        lines.append("== timers (count / total / mean / p50 / p95 / max) ==")
        for name, fam in sorted(timers.items()):
            for s in fam["series"]:
                lbl = ",".join("%s=%s" % kv for kv in
                               sorted(s["labels"].items()))
                lines.append(
                    "  %-52s %-24s n=%-7d %s  %s  %s  %s  %s"
                    % (name, lbl, s["count"], _fmt_s(s["total"]),
                       _fmt_s(s["mean"]), _fmt_s(s["p50"]),
                       _fmt_s(s["p95"]), _fmt_s(s["max"])))
    others = {n: f for n, f in metrics.items() if f.get("type") != "timer"}
    if others:
        lines.append("== counters / gauges ==")
        for name, fam in sorted(others.items()):
            for s in fam["series"]:
                lbl = ",".join("%s=%s" % kv for kv in
                               sorted(s["labels"].items()))
                extra = ("  (peak %g)" % s["high_water"]
                         if "high_water" in s else "")
                lines.append("  %-52s %-24s %g%s"
                             % (name, lbl, s["value"], extra))
    serve = _serve_summary(metrics)
    if serve:
        lines.append("== serving (per service: traffic / batching / "
                     "waste / latency) ==")
        lines.extend(serve)
    slo = _serve_slo_summary(metrics, snap.get("flight", {}))
    if slo:
        lines.append("== SLO burn & exemplars (docs/OBSERVABILITY.md "
                     "\"Flight recorder & request tracing\") ==")
        lines.extend(slo)
    persist = _persist_summary(metrics)
    if persist:
        # standalone section (not nested under serving): a persistent
        # service that has not dispatched a batch yet still has
        # durable state worth one screen
        lines.append("== durability (docs/PERSISTENCE.md) ==")
        lines.extend(persist)
    tuning = _tuning_summary(metrics)
    if tuning:
        lines.append("== tuning (docs/TUNING.md \"Bench-driven "
                     "autotuning\") ==")
        lines.extend(tuning)
    inv = _inventory_summary(snap)
    if inv:
        lines.append("== program inventory (XLA cost model; "
                     "docs/OBSERVABILITY.md \"Ops plane\") ==")
        lines.extend(inv)
    ops = _ops_summary(metrics)
    if ops:
        lines.append("== ops plane & anomaly sentinel ==")
        lines.extend(ops)
    cc = snap.get("compile_cache", {})
    if cc:
        lines.append("== jit compile cache (per fn: shapes / hits / "
                     "misses / compile) ==")
        for fn_name, keys in sorted(cc.items()):
            h = sum(st["hits"] for st in keys.values())
            m = sum(st["misses"] for st in keys.values())
            c = sum(st["compile_s"] for st in keys.values())
            lines.append("  %-40s shapes=%-4d hits=%-6d misses=%-4d "
                         "compile=%s" % (fn_name, len(keys), h, m,
                                         _fmt_s(c)))
    ev = snap.get("event_counters", {})
    if ev:
        lines.append("== event counters ==")
        for name, v in sorted(ev.items()):
            lines.append("  %-52s %d" % (name, v))
    report = snap.get("profiler_report")
    tree = snap.get("profiler_tree", {})
    if report:
        lines.append(report)
    elif tree:
        lines.append("== profiler span tree ==")

        def walk(name, node, depth):
            mean = (node["total_s"] / node["count"]) if node["count"] else 0
            lines.append("  %s%-*s n=%-6d total=%s mean=%s"
                         % ("  " * depth, max(1, 36 - 2 * depth), name,
                            node["count"], _fmt_s(node["total_s"]),
                            _fmt_s(mean)))
            for cn, c in sorted(node.get("children", {}).items()):
                walk(cn, c, depth + 1)

        for name, node in sorted(tree.items()):
            walk(name, node, 0)
    return "\n".join(lines) if lines else "(empty snapshot)"


def _fleet_summary(snap: dict) -> list:
    """Fleet digest from a router's ``/debug/snapshot`` payload: one
    row per worker (state / generation / WAL seq / serve digest) plus
    the fleet-wide rollup the router computes from its own end-to-end
    timer — per-worker p50/p95 come from each worker's reservoir; the
    true client p99 only the router sees."""
    fleet = snap.get("fleet")
    if not fleet:
        return []
    rollup = fleet.get("rollup", {})
    lines = ["  mode=%s shards=%s workers=%d (dead %d) uptime=%ss"
             % (fleet.get("mode"), fleet.get("shard_count"),
                rollup.get("workers_total", 0),
                rollup.get("workers_dead", 0),
                rollup.get("uptime_s", 0.0))]
    parts = ["requests=%d" % rollup.get("requests_total", 0),
             "qps=%g" % rollup.get("qps_lifetime", 0.0)]
    for key in sorted(rollup):
        if key.startswith(("p50_", "p99_")):
            parts.append("%s=%gms" % (key[:-3], rollup[key]))
    parts.append("slo_burn_max=%g" % rollup.get("slo_burn_max", 0.0))
    lines.append("  rollup: " + " ".join(parts))
    workers = fleet.get("workers", {})
    if workers:
        lines.append("  %-8s %-9s %-4s %-8s %-6s %-9s %-9s %-8s "
                     "%-10s %-10s %s"
                     % ("worker", "state", "gen", "wal_seq", "queue",
                        "requests", "rejected", "unavail",
                        "exec_p50", "exec_p95", "slo_burn"))
        for wid, d in sorted(workers.items()):
            lines.append(
                "  %-8s %-9s %-4s %-8s %-6s %-9s %-9s %-8s %-10s "
                "%-10s %g"
                % (wid, d.get("state"), d.get("generation", 0),
                   d.get("wal_seq", 0), d.get("queue_depth", 0),
                   d.get("requests_total", "-"),
                   d.get("rejected_total", "-"),
                   d.get("unavailable_total", "-"),
                   "%gms" % d.get("exec_p50_ms", 0.0),
                   "%gms" % d.get("exec_p95_ms", 0.0),
                   d.get("slo_burn", 0.0)))
    stats = fleet.get("stats", {})
    rj = stats.get("last_rejoin") or None
    if rj:
        lines.append("  last rejoin: %s gen=%s replayed=%s "
                     "restore=%ss"
                     % (rj.get("worker_id"), rj.get("generation"),
                        rj.get("replayed_records"),
                        rj.get("restore_s")))
    return lines


def _serve_summary(metrics: dict) -> list:
    """Per-service serving digest from the raw ``raft_tpu_serve_*``
    families: request/batch counts, mean fill, padding-waste ratio
    (padded / dispatched rows), queue-wait and device-call latency.
    The generic tables above still show every series; this section does
    the cross-family arithmetic a dashboard would."""

    def per_service(name):
        fam = metrics.get(name, {})
        out = {}
        for s in fam.get("series", []):
            svc = s["labels"].get("service")
            if svc is not None:
                out[svc] = s
        return out

    requests = per_service("raft_tpu_serve_requests_total")
    if not requests:
        return []
    batches = per_service("raft_tpu_serve_batches_total")
    payload = per_service("raft_tpu_serve_payload_rows_total")
    padded = per_service("raft_tpu_serve_padded_rows_total")
    rejected = per_service("raft_tpu_serve_rejected_total")
    expired = per_service("raft_tpu_serve_expired_total")
    waits = per_service("raft_tpu_serve_wait_seconds")
    execs = per_service("raft_tpu_serve_exec_seconds")
    shard_devs = per_service("raft_tpu_serve_shard_devices")
    reparts = per_service("raft_tpu_serve_repartitions_total")
    lines = []
    for svc in sorted(requests):
        nb = batches.get(svc, {}).get("value", 0)
        pay = payload.get(svc, {}).get("value", 0)
        pad = padded.get(svc, {}).get("value", 0)
        total = pay + pad
        sharded = ""
        if svc in shard_devs and shard_devs[svc].get("value", 0):
            sharded = "  shards=%d" % int(shard_devs[svc]["value"])
            nrep = reparts.get(svc, {}).get("value", 0)
            if nrep:
                sharded += " repartitions=%d" % int(nrep)
        lines.append(
            "  %-24s requests=%-8d batches=%-7d mean_fill=%-7.1f "
            "waste=%.1f%%  rejected=%d expired=%d%s"
            % (svc, requests[svc]["value"], nb,
               (pay / nb) if nb else 0.0,
               (100.0 * pad / total) if total else 0.0,
               rejected.get(svc, {}).get("value", 0),
               expired.get(svc, {}).get("value", 0), sharded))
        w, e = waits.get(svc), execs.get(svc)
        if w or e:
            lines.append(
                "  %-24s   queue wait p50=%s p95=%s   exec p50=%s "
                "p95=%s" % ("",
                            _fmt_s(w["p50"]) if w else "-",
                            _fmt_s(w["p95"]) if w else "-",
                            _fmt_s(e["p50"]) if e else "-",
                            _fmt_s(e["p95"]) if e else "-"))
    lines.extend(_serve_traffic_summary(metrics))
    lines.extend(_serve_resilience_summary(metrics))
    lines.extend(_serve_ann_summary(metrics))
    lines.extend(_serve_ooc_summary(metrics))
    return lines


def _persist_summary(metrics: dict) -> list:
    """Durability digest (docs/PERSISTENCE.md): per-service snapshot
    age/bytes/latency, WAL depth and replay history, scrub progress
    and corruption count — the one screen that answers "how much
    acknowledged work would a crash right now lose, and is the durable
    copy still intact"."""

    def per_service(name):
        fam = metrics.get(name, {})
        return {s["labels"].get("service"): s
                for s in fam.get("series", [])
                if s["labels"].get("service") is not None}

    snaps = per_service("raft_tpu_persist_snapshots_total")
    age = per_service("raft_tpu_persist_snapshot_age_seconds")
    sbytes = per_service("raft_tpu_persist_snapshot_bytes")
    stimer = per_service("raft_tpu_persist_snapshot_seconds")
    wal_rec = per_service("raft_tpu_persist_wal_records")
    wal_b = per_service("raft_tpu_persist_wal_bytes")
    replayed = per_service("raft_tpu_persist_wal_replayed_total")
    restores = per_service("raft_tpu_persist_restores_total")
    checked = per_service("raft_tpu_scrub_checked_total")
    corrupt = per_service("raft_tpu_scrub_corruption_total")
    rebuilt = per_service("raft_tpu_scrub_rebuilt_slots_total")
    progress = per_service("raft_tpu_scrub_progress")
    # union: a just-restored service may not have snapshotted yet but
    # its restore/replay rows still belong on this screen
    services = set(snaps) | set(restores) | set(wal_rec)
    if not services:
        return []
    lines = []
    for svc in sorted(services):
        st = stimer.get(svc)
        lines.append(
            "  %-24s snapshots=%-4d age=%-8s bytes=%-10d "
            "write_mean=%s  wal: records=%d bytes=%d"
            % (svc, int(snaps.get(svc, {}).get("value", 0)),
               "%.1fs" % age[svc]["value"] if svc in age else "-",
               int(sbytes.get(svc, {}).get("value", 0)),
               _fmt_s(st["mean"]) if st else "-",
               int(wal_rec.get(svc, {}).get("value", 0)),
               int(wal_b.get(svc, {}).get("value", 0))))
        nres = int(restores.get(svc, {}).get("value", 0))
        nchk = int(checked.get(svc, {}).get("value", 0))
        ncor = int(corrupt.get(svc, {}).get("value", 0))
        if nres or nchk or ncor:
            lines.append(
                "  %-24s   restores=%d replayed=%d  scrub: checked=%d "
                "progress=%.0f%% corruption=%d rebuilt_slots=%d"
                % ("", nres,
                   int(replayed.get(svc, {}).get("value", 0)), nchk,
                   100.0 * progress.get(svc, {}).get("value", 0.0),
                   ncor,
                   int(rebuilt.get(svc, {}).get("value", 0))))
    return lines


def _serve_slo_summary(metrics: dict, flight: dict) -> list:
    """SLO digest: per-(service, tenant) hit ratio, misses, and the
    multi-window burn rates from the ``raft_tpu_serve_slo_*`` gauges,
    plus the slowest-observation exemplars from the snapshot's
    ``flight`` section — each p99 complaint gets the trace_ids to pull
    with ``tools/trace_report.py``."""
    hit = {}
    for s in metrics.get("raft_tpu_serve_slo_hit_ratio",
                         {}).get("series", []):
        key = (s["labels"].get("service"), s["labels"].get("tenant"))
        if key[0] is not None:
            hit[key] = s["value"]
    burns = {}
    for s in metrics.get("raft_tpu_serve_slo_burn_rate",
                         {}).get("series", []):
        key = (s["labels"].get("service"), s["labels"].get("tenant"))
        if key[0] is not None:
            burns.setdefault(key, []).append(
                (s["labels"].get("window"), s["value"]))
    misses = {}
    for s in metrics.get("raft_tpu_serve_slo_misses_total",
                         {}).get("series", []):
        key = (s["labels"].get("service"), s["labels"].get("tenant"))
        if key[0] is not None:
            misses[key] = int(s["value"])
    lines = []
    for key in sorted(set(hit) | set(burns) | set(misses)):
        svc, tenant = key
        burn_s = "  ".join(
            "burn[%s]=%.2f" % bw
            for bw in sorted(burns.get(key, []), key=lambda t: str(t[0])))
        lines.append(
            "  %-24s tenant=%-12s hit_ratio=%-8.4f misses=%-6d %s"
            % (svc, tenant, hit.get(key, 1.0), misses.get(key, 0),
               burn_s))
    for svc, exemplars in sorted((flight or {}).get("exemplars",
                                                    {}).items()):
        if exemplars:
            lines.append(
                "  %-24s   slowest: %s" % (svc, "  ".join(
                    "%.1fms(trace %d)" % (e["latency_ms"],
                                          e["trace_id"])
                    for e in exemplars[:5])))
    bbs = (flight or {}).get("blackboxes", [])
    if bbs:
        lines.append("  black boxes: %s" % "  ".join(
            "%s@%.1f(%s, %d events)"
            % (b["reason"], b["at"], b.get("service") or "-",
               b["n_events"]) for b in bbs))
    return lines


def _serve_traffic_summary(metrics: dict) -> list:
    """Traffic-shaping digest (docs/SERVING.md "Traffic shaping"):
    per-tenant served rows / requests / sheds, hedged-dispatch ledger
    (fired / won / cancelled / failovers), and replica rotation state
    — the one screen that answers "who got the machine, and did the
    tail-latency defenses fire"."""

    def by_tenant(name):
        out = {}
        for s in metrics.get(name, {}).get("series", []):
            key = (s["labels"].get("service"),
                   s["labels"].get("tenant"))
            if key[0] is not None and key[1] is not None:
                out[key] = int(s["value"])
        return out

    def by_service(name):
        out = {}
        for s in metrics.get(name, {}).get("series", []):
            svc = s["labels"].get("service")
            if svc is not None:
                out[svc] = int(s["value"])
        return out

    lines = []
    rows = by_tenant("raft_tpu_serve_tenant_rows_total")
    reqs = by_tenant("raft_tpu_serve_tenant_requests_total")
    sheds = by_tenant("raft_tpu_serve_tenant_rejected_total")
    tenant_keys = sorted(set(rows) | set(reqs) | set(sheds))
    tenants_by_svc = {}
    for svc, tenant in tenant_keys:
        tenants_by_svc.setdefault(svc, []).append(tenant)
    for svc, tenants in sorted(tenants_by_svc.items()):
        if tenants == ["default"]:
            # a lone default tenant is just the single-queue service
            # again — no shaping to report
            continue
        for tenant in tenants:
            key = (svc, tenant)
            lines.append(
                "  %-24s tenant=%-12s rows=%-8d requests=%-7d "
                "sheds=%d"
                % (svc, tenant, rows.get(key, 0), reqs.get(key, 0),
                   sheds.get(key, 0)))
    hedges = by_service("raft_tpu_serve_hedges_total")
    wins = by_service("raft_tpu_serve_hedge_wins_total")
    cancelled = by_service("raft_tpu_serve_hedge_cancelled_total")
    failovers = by_service("raft_tpu_serve_replica_failovers_total")
    healthy = by_service("raft_tpu_serve_replicas_healthy")
    for svc in sorted(set(hedges) | set(failovers) | set(healthy)):
        lines.append(
            "  %-24s hedges: fired=%-4d won=%-4d cancelled=%-4d "
            "failovers=%-3d replicas_healthy=%s"
            % (svc, hedges.get(svc, 0), wins.get(svc, 0),
               cancelled.get(svc, 0), failovers.get(svc, 0),
               healthy.get(svc, "-")))
    state_names = {0: "closed", 1: "OPEN", 2: "half-open"}
    rep_states = {}
    for s in metrics.get("raft_tpu_serve_replica_state",
                         {}).get("series", []):
        svc = s["labels"].get("service")
        rep = s["labels"].get("replica")
        if svc is not None and rep is not None:
            rep_states.setdefault(svc, []).append(
                (str(rep), state_names.get(int(s["value"]), "?")))
    for svc, reps in sorted(rep_states.items()):
        lines.append("  %-24s   rotation: %s" % (
            svc, "  ".join("r%s=%s" % r for r in sorted(reps))))
    # per-replica execution latency (the per-replica split the
    # adaptive hedge threshold anchors on — one slow replica must be
    # VISIBLE here, not averaged into the rung aggregate)
    rep_lat = {}
    for s in metrics.get("raft_tpu_serve_replica_exec_seconds",
                         {}).get("series", []):
        svc = s["labels"].get("service")
        rep = s["labels"].get("replica")
        if svc is not None and rep is not None and s["count"]:
            rep_lat.setdefault(svc, []).append(
                (str(rep), s["p50"], s["p95"], s["count"]))
    for svc, reps in sorted(rep_lat.items()):
        lines.append("  %-24s   replica exec: %s" % (
            svc, "  ".join(
                "r%s p50=%s p95=%s (n=%d)"
                % (r, _fmt_s(p50), _fmt_s(p95), n)
                for r, p50, p95, n in sorted(reps))))
    return lines


def _tuning_summary(metrics: dict) -> list:
    """Autotuner digest: the active table's fingerprint/source (live
    process only), per-knob table-hit vs miss lookup counts, and the
    tuned-vs-default margins the bench rung measured."""
    out = []
    # live table info — meaningful when rendering in-process (--demo /
    # Session.metrics_snapshot callers); a snapshot file rendered
    # elsewhere simply skips it
    try:
        from raft_tpu import config as _config

        info = _config.tuning_table_info()
    except Exception:
        info = None
    if info:
        fp = info["fingerprint"]
        out.append("  table %s  fingerprint=%s/%s/%d  cells=%d"
                   % (info["source"], fp.get("platform"),
                      fp.get("device_kind"),
                      int(fp.get("device_count", 0)), info["cells"]))
    lookups = metrics.get("raft_tpu_tuning_table_lookups_total", {})
    by_knob = {}
    for s in lookups.get("series", []):
        lbl = s.get("labels", {})
        knob = lbl.get("knob", "?")
        d = by_knob.setdefault(knob,
                               {"hit": 0, "miss": 0, "discarded": 0})
        oc = lbl.get("outcome", "miss")
        d[oc] = d.get(oc, 0) + s.get("value", 0)
    for knob, d in sorted(by_knob.items()):
        total = d["hit"] + d["miss"]
        # effective coverage: a "discarded" answer (illegal for the
        # real call ctx) actually resolved to the default
        eff = d["hit"] - d["discarded"]
        line = ("  %-20s lookups=%-7d from_table=%-7d pinned/"
                "default=%d" % (knob, int(total), int(eff),
                                int(d["miss"] + d["discarded"])))
        if d["discarded"]:
            line += "  (discarded=%d)" % int(d["discarded"])
        out.append(line)
    ratios = metrics.get("raft_tpu_tuning_tuned_vs_default_ratio", {})
    for s in ratios.get("series", []):
        lbl = s.get("labels", {})
        out.append("  tuned_vs_default %-16s [%s] = %.2fx"
                   % (lbl.get("op", "?"), lbl.get("cell", "?"),
                      s.get("value", 0.0)))
    return out


def _inventory_summary(snap: dict) -> list:
    """Program cost inventory digest (docs/OBSERVABILITY.md "Ops
    plane"): per-fn program counts, cost-model flops/footprints, the
    summed device-capacity claim, and a roofline-style achieved-
    throughput figure joining the cost model to the measured
    ``raft_tpu_jit_<fn>_seconds`` execution timer (host-side dispatch
    — an upper bound on achieved FLOP/s) and, when the serve layer
    ran, the device-complete ``raft_tpu_serve_device_seconds{fn=...}``
    bracket (closed after ``block_until_ready`` — a firm floor).
    Together the two columns bracket true achieved rate, so kernel
    work starts from firm numbers."""
    inv = snap.get("inventory") or {}
    per_fn = inv.get("per_fn") or {}
    if not per_fn:
        return []
    metrics = snap.get("metrics", {})
    # device-complete serve bracket per fn (aggregated over services;
    # the opsplane join precomputes device_mean_s into the inventory,
    # but a raw-metrics snapshot may carry only the timer — join both)
    device = {}
    for s in metrics.get("raft_tpu_serve_device_seconds",
                         {}).get("series", []):
        fn = s.get("labels", {}).get("fn")
        if fn and s.get("count"):
            agg = device.setdefault(fn, [0, 0.0])
            agg[0] += s["count"]
            agg[1] += s["count"] * s.get("mean", 0.0)
    lines = ["  programs=%d  pinned footprint (args+outs+temps) "
             "= %.1f MB"
             % (inv.get("programs", 0),
                inv.get("total_hbm_bytes", 0.0) / 1e6)]
    for fn, st in sorted(per_fn.items()):
        line = ("  %-32s programs=%-3d max_flops=%.3g  hbm=%.1fMB"
                % (fn, st["programs"], st["max_flops"],
                   st["total_hbm_bytes"] / 1e6))
        timer = metrics.get("raft_tpu_jit_%s_seconds" % fn, {})
        series = timer.get("series") or []
        if series and series[0].get("count"):
            mean_s = series[0]["mean"]
            line += "  exec mean=%s" % _fmt_s(mean_s)
            if mean_s > 0 and st["max_flops"] > 0:
                line += (" -> <=%.1f GFLOP/s"
                         % (st["max_flops"] / mean_s / 1e9))
        dev_mean = st.get("device_mean_s")
        if dev_mean is None:
            agg = device.get(fn)
            if agg and agg[0]:
                dev_mean = agg[1] / agg[0]
        if dev_mean:
            line += "  device mean=%s" % _fmt_s(dev_mean)
            if st["max_flops"] > 0:
                line += (" -> >=%.1f GFLOP/s (device-complete)"
                         % (st["max_flops"] / dev_mean / 1e9))
        lines.append(line)
    return lines


def _ops_summary(metrics: dict) -> list:
    """Ops-plane scrape traffic + anomaly-sentinel ledger."""
    lines = []
    by_ep = {}
    for s in metrics.get("raft_tpu_ops_requests_total",
                         {}).get("series", []):
        ep = s["labels"].get("endpoint", "?")
        d = by_ep.setdefault(ep, {"n": 0, "errors": 0})
        d["n"] += int(s["value"])
        if s["labels"].get("code", "200") not in ("200", "503"):
            d["errors"] += int(s["value"])
    lat = {}
    for s in metrics.get("raft_tpu_ops_request_seconds",
                         {}).get("series", []):
        ep = s["labels"].get("endpoint")
        if ep is not None and s.get("count"):
            lat[ep] = s
    for ep, d in sorted(by_ep.items()):
        line = "  %-32s requests=%-7d" % (ep, d["n"])
        if d["errors"]:
            line += " errors=%d" % d["errors"]
        if ep in lat:
            line += ("  handler p50=%s p95=%s"
                     % (_fmt_s(lat[ep]["p50"]), _fmt_s(lat[ep]["p95"])))
        lines.append(line)
    anomalies = {}
    for s in metrics.get("raft_tpu_anomaly_total",
                         {}).get("series", []):
        anomalies[s["labels"].get("rule", "?")] = int(s["value"])
    active = []
    for s in metrics.get("raft_tpu_anomaly_active",
                         {}).get("series", []):
        if s["value"]:
            active.append("%s/%s" % (s["labels"].get("service", "?"),
                                     s["labels"].get("rule", "?")))
    if anomalies:
        lines.append("  anomalies: %s%s" % (
            "  ".join("%s=%d" % kv for kv in sorted(anomalies.items())),
            ("  ACTIVE: " + " ".join(sorted(active))) if active
            else ""))
    return lines


def _serve_resilience_summary(metrics: dict) -> list:
    """Self-healing digest (docs/FAULT_MODEL.md "Serving failure
    model"): live breaker state plus the outage ledger — trips,
    unavailable sheds, requeued riders, worker restarts, recoveries,
    degraded (browned-out) batches — per service."""
    state = {}
    for s in metrics.get("raft_tpu_serve_breaker_state",
                         {}).get("series", []):
        svc = s["labels"].get("service")
        if svc is not None:
            state[svc] = int(s["value"])
    if not state:
        return []
    names = ("closed", "OPEN", "half-open")

    def per_service(name):
        out = {}
        for s in metrics.get(name, {}).get("series", []):
            svc = s["labels"].get("service")
            if svc is not None:
                out[svc] = int(s["value"])
        return out

    trips = per_service("raft_tpu_serve_breaker_trips_total")
    unavail = per_service("raft_tpu_serve_unavailable_total")
    requeued = per_service("raft_tpu_serve_requeued_total")
    restarts = per_service("raft_tpu_serve_worker_restarts_total")
    recoveries = per_service("raft_tpu_serve_recoveries_total")
    degraded = per_service("raft_tpu_serve_degraded_batches_total")
    maint = per_service("raft_tpu_serve_maintenance_errors_total")
    lines = []
    for svc in sorted(state):
        lines.append(
            "  %-24s breaker=%-9s trips=%-3d unavailable=%-5d "
            "requeued=%-4d recoveries=%d"
            % (svc, names[state[svc]], trips.get(svc, 0),
               unavail.get(svc, 0), requeued.get(svc, 0),
               recoveries.get(svc, 0)))
        extra = []
        if degraded.get(svc):
            extra.append("degraded_batches=%d" % degraded[svc])
        if restarts.get(svc):
            extra.append("worker_restarts=%d" % restarts[svc])
        if maint.get(svc):
            extra.append("maintenance_errors=%d" % maint[svc])
        if extra:
            lines.append("  %-24s   %s" % ("", " ".join(extra)))
    return lines


def _serve_ann_summary(metrics: dict) -> list:
    """ANN-service digest (``raft_tpu_serve_ann_*``): streaming-
    ingestion state (delta rows, inserts, compactions) and the
    per-nprobe dispatch mix, so an operator can see at a glance which
    recall cell traffic is actually served at."""
    delta = metrics.get("raft_tpu_serve_ann_delta_rows", {})
    services = {}
    for s in delta.get("series", []):
        svc = s["labels"].get("service")
        if svc is not None:
            services[svc] = {"delta_rows": s["value"]}
    if not services:
        return []

    def add(name, key):
        for s in metrics.get(name, {}).get("series", []):
            svc = s["labels"].get("service")
            if svc in services:
                services[svc][key] = s["value"]

    add("raft_tpu_serve_ann_inserts_total", "inserts")
    add("raft_tpu_serve_ann_compactions_total", "compactions")
    add("raft_tpu_serve_ann_compacted_rows_total", "compacted_rows")
    calls = {}
    for s in metrics.get("raft_tpu_serve_ann_calls_total",
                         {}).get("series", []):
        svc = s["labels"].get("service")
        if svc in services:
            calls.setdefault(svc, []).append(
                (s["labels"].get("nprobe"), int(s["value"])))
    lines = []
    for svc in sorted(services):
        st = services[svc]
        lines.append(
            "  %-24s ANN: delta_rows=%-6d inserts=%-7d "
            "compactions=%d (rows=%d)"
            % (svc, st.get("delta_rows", 0), st.get("inserts", 0),
               st.get("compactions", 0), st.get("compacted_rows", 0)))
        mix = sorted(calls.get(svc, []), key=lambda t: str(t[0]))
        if mix:
            lines.append("  %-24s   batches by nprobe: %s" % (
                "", "  ".join("nprobe=%s:%d" % t for t in mix)))
    return lines


def _serve_ooc_summary(metrics: dict) -> list:
    """Out-of-core tier digest (docs/SERVING.md "Out-of-core
    serving"): hot-set size, tile hit rate, H2D traffic, and the
    overlap-efficiency number — the *hidden-transfer fraction*
    ``1 - stall/h2d``: how much of the host-to-device copy time was
    buried under the scan by the double-buffered prefetch (1.0 =
    fully hidden, 0.0 = every transfer paid serially, which is what
    the synchronous-prefetch arm measures)."""

    def by_label(name, label):
        out = {}
        for s in metrics.get(name, {}).get("series", []):
            key = s["labels"].get(label)
            if key is not None:
                out[key] = s
        return out

    hits = by_label("raft_tpu_tile_hits_total", "pool")
    misses = by_label("raft_tpu_tile_misses_total", "pool")
    pools = sorted(set(hits) | set(misses))
    if not pools:
        return []
    evictions = by_label("raft_tpu_tile_evictions_total", "pool")
    h2d_bytes = by_label("raft_tpu_h2d_bytes_total", "pool")
    h2d = by_label("raft_tpu_h2d_seconds", "pool")
    stall = by_label("raft_tpu_h2d_stall_seconds", "pool")
    staged = by_label("raft_tpu_tile_staged_bytes", "pool")
    hot_slots = by_label("raft_tpu_ooc_hot_slots", "service")
    hot_bytes = by_label("raft_tpu_ooc_hot_bytes", "service")
    lines = []
    for pool in pools:
        h = hits.get(pool, {}).get("value", 0)
        m = misses.get(pool, {}).get("value", 0)
        rate = h / (h + m) if (h + m) else 0.0
        h2d_t = h2d.get(pool, {}).get("total", 0.0)
        stall_t = stall.get(pool, {}).get("total", 0.0)
        hidden = (1.0 - stall_t / h2d_t) if h2d_t else 0.0
        lines.append(
            "  %-24s OOC: hot_slots=%-6d hot_mb=%-8.1f "
            "tile_hit_rate=%.3f evictions=%d"
            % (pool, hot_slots.get(pool, {}).get("value", 0),
               hot_bytes.get(pool, {}).get("value", 0) / 1e6,
               rate, evictions.get(pool, {}).get("value", 0)))
        lines.append(
            "  %-24s   h2d=%.1fMB in %s (stall %s, hidden-transfer "
            "fraction %.2f)  staged_peak=%.1fMB"
            % ("", h2d_bytes.get(pool, {}).get("value", 0) / 1e6,
               _fmt_s(h2d_t), _fmt_s(stall_t), hidden,
               staged.get(pool, {}).get("high_water", 0) / 1e6))
    return lines


def run_demo() -> dict:
    """Tiny instrumented workload touching every metric layer."""
    import jax.numpy as jnp
    import numpy as np

    from raft_tpu.comms import HostComms
    from raft_tpu.distance.pairwise import pairwise_distance
    from raft_tpu.mr.buffer import DeviceBuffer
    from raft_tpu.session import metrics_snapshot
    from raft_tpu.spatial.knn import brute_force_knn

    rng = np.random.default_rng(0)
    X = jnp.asarray(rng.standard_normal((256, 32)), jnp.float32)
    Q = jnp.asarray(rng.standard_normal((32, 32)), jnp.float32)
    pairwise_distance(Q, X)
    for _ in range(2):  # second call = jit cache hit
        brute_force_knn(X, Q, k=4)
    comms = HostComms()
    size = comms.get_size()
    comms.allreduce(jnp.ones((size, 4), jnp.float32))
    comms.allreduce(jnp.ones((size, 4), jnp.float32))
    with DeviceBuffer((1024, 1024)):
        pass
    # serving layer: a warmed micro-batching service over the same index
    from raft_tpu.serve import KNNService

    svc = KNNService(X, k=4, max_batch_rows=32, max_wait_ms=1.0)
    svc.warmup()
    for f in svc.submit_many([Q[:3], Q[3:8], Q[8:12]]):
        f.result(timeout=30)
    svc.close()
    return metrics_snapshot()


def _load_snapshot(args) -> dict:
    """One snapshot from whichever source the CLI named: the ops
    plane's ``/debug/snapshot`` (``--url``), a dumped JSON file, or
    the --demo workload."""
    if args.demo:
        return run_demo()
    if args.url:
        import urllib.request

        url = args.url.rstrip("/") + "/debug/snapshot"
        with urllib.request.urlopen(url, timeout=10) as resp:
            return json.load(resp)
    with open(args.snapshot, encoding="utf-8") as f:
        snap = json.load(f)
    # bench.py artifact? unwrap to its embedded snapshot
    for path in (("metrics_snapshot",), ("detail", "metrics_snapshot")):
        cur = snap
        for k in path:
            cur = cur.get(k, {}) if isinstance(cur, dict) else {}
        if cur:
            snap = cur
            break
    return snap


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("snapshot", nargs="?",
                    help="snapshot JSON (Session.dump_metrics or bench "
                         "output; bench files are unwrapped automatically)")
    ap.add_argument("--demo", action="store_true",
                    help="run a small instrumented workload instead of "
                         "reading a file")
    ap.add_argument("--url", metavar="URL",
                    help="poll a live ops plane (Session.serve_ops / "
                         "OpsPlane) at URL instead of reading a file — "
                         "fetches /debug/snapshot")
    ap.add_argument("--watch", type=float, default=None, metavar="N",
                    help="live mode: re-fetch (--url) or re-read (a "
                         "snapshot file) every N seconds and re-render "
                         "the digest in place; Ctrl-C exits")
    ap.add_argument("--format", choices=("report", "json", "prom"),
                    default="report")
    ap.add_argument("--out", help="also write the snapshot JSON here")
    args = ap.parse_args(argv)

    n_sources = sum((args.demo, args.snapshot is not None,
                     args.url is not None))
    if n_sources != 1:
        ap.error("pass exactly one of: a snapshot file, --url, or "
                 "--demo")
    if args.watch is not None:
        if args.demo:
            ap.error("--watch needs a re-readable source: --url or a "
                     "snapshot file")
        if args.watch <= 0:
            ap.error("--watch N must be positive seconds")
        import time as _time

        try:
            while True:
                snap = _load_snapshot(args)
                # clear + home, then one full render — the digest
                # redraws in place like `watch(1)` would
                sys.stdout.write("\x1b[2J\x1b[H")
                print("[%s  every %gs  source: %s]" % (
                    _time.strftime("%H:%M:%S"), args.watch,
                    args.url or args.snapshot))
                print(render_report(snap))
                sys.stdout.flush()
                _time.sleep(args.watch)
        except KeyboardInterrupt:
            return 0

    snap = _load_snapshot(args)

    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(snap, f, indent=2, sort_keys=True)
            f.write("\n")

    if args.format == "json":
        print(json.dumps(snap, indent=2, sort_keys=True))
    elif args.format == "prom":
        if args.demo:
            from raft_tpu.core.metrics import default_registry

            print(default_registry().to_prometheus(), end="")
        elif args.url:
            import urllib.request

            with urllib.request.urlopen(
                    args.url.rstrip("/") + "/metrics",
                    timeout=10) as resp:
                sys.stdout.write(resp.read().decode("utf-8"))
        else:
            print("--format prom needs a live registry; use --demo "
                  "or --url (a dumped snapshot is already flattened)",
                  file=sys.stderr)
            return 2
    else:
        print(render_report(snap))
    return 0


if __name__ == "__main__":
    sys.exit(main())
