#!/usr/bin/env python
"""Load generator for raft_tpu serve services (docs/SERVING.md).

Drives a :class:`raft_tpu.serve.KNNService` / ``PairwiseService`` with
synthetic traffic and reports client-observed latency percentiles plus
the padding-waste / batch-fill numbers from the metrics registry — the
two halves of the serving trade (latency vs device efficiency) in one
screen.

Two loops:

- **closed** (``--concurrency N``): N client threads each submit a
  request, wait for its future, submit the next — throughput is
  latency-bound, the classic saturation probe.
- **open** (``--qps Q``): one pacing thread fires submits on a fixed
  schedule regardless of completions — arrival-rate-bound, the loop
  that actually exposes queueing: at overload it measures shed rate
  (``ServiceOverloadError`` count) rather than silently slowing down.

Usage:
    python tools/loadgen.py --mode closed --concurrency 8 --duration 5
    python tools/loadgen.py --mode open --qps 500 --duration 5 \\
        --rows 4 --index-rows 50000 --dim 64 --k 10
    python tools/loadgen.py --service pairwise --mode closed ...

Importable: :func:`run_load` returns the report dict (bench.py's
``serve`` rung and tests reuse it).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _percentile(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def _registry_serve_stats(service_name):
    """Padding-waste / batch-fill numbers for one service, read back
    from the metrics registry (the numbers the scheduler recorded —
    loadgen measures the client side, the registry the server side)."""
    from raft_tpu.core.metrics import default_registry

    reg = default_registry()

    def _value(name):
        fam = reg.get(name)
        if fam is None:
            return 0.0
        for labels, series in fam.series():
            if labels.get("service") == service_name:
                return series.value
        return 0.0

    payload = _value("raft_tpu_serve_payload_rows_total")
    padded = _value("raft_tpu_serve_padded_rows_total")
    batches = _value("raft_tpu_serve_batches_total")
    total = payload + padded
    out = {
        "batches": int(batches),
        "payload_rows": int(payload),
        "padded_rows": int(padded),
        "padding_waste": (padded / total) if total else 0.0,
        "mean_batch_rows": (payload / batches) if batches else 0.0,
    }
    fam = reg.get("raft_tpu_serve_wait_seconds")
    if fam is not None:
        for labels, series in fam.series():
            if labels.get("service") == service_name:
                out["queue_wait_p50_ms"] = series.quantile(0.50) * 1e3
                out["queue_wait_p95_ms"] = series.quantile(0.95) * 1e3
    # the zero-copy proof (docs/ZERO_COPY.md): payload bytes bounced
    # through host numpy anywhere in the process — 0 on the
    # device-resident paths (absent family == nothing ever staged)
    out["host_staged_bytes"] = int(
        reg.family_total("raft_tpu_comms_host_staged_bytes"))
    return out


def _compile_misses():
    """Total compile-cache misses across every profiled_jit wrapper
    (the steady-state proof: zero NEW misses after warmup)."""
    from raft_tpu.core.profiler import compile_cache_stats

    return sum(s["misses"] for fn in compile_cache_stats().values()
               for s in fn.values())


def build_service(kind, index_rows, dim, k, seed=0, **opts):
    """A ready (not yet warmed) service over a synthetic index."""
    import jax.numpy as jnp
    import numpy as np

    from raft_tpu.serve import KNNService, PairwiseService

    rng = np.random.default_rng(seed)
    ref = jnp.asarray(rng.standard_normal((index_rows, dim)), jnp.float32)
    if kind == "knn":
        return KNNService(ref, k=k, **opts)
    if kind == "pairwise":
        return PairwiseService(ref, **opts)
    raise SystemExit("unknown --service %r" % kind)


def run_load(service, *, mode="closed", duration=5.0, concurrency=8,
             qps=100.0, rows=4, seed=0, deadline=None):
    """Drive ``service`` for ``duration`` seconds; returns the report.

    Latencies are client-observed submit→result seconds.  Rejected
    submits (admission control) and expired deadlines are counted, not
    raised — overload behavior is the *measurement*, not a failure.
    """
    import jax.numpy as jnp
    import numpy as np

    from raft_tpu.core.error import ServiceOverloadError

    rng = np.random.default_rng(seed)
    # pre-generated query pool: the generator must not bottleneck on
    # fresh RNG draws mid-flight
    pool = [jnp.asarray(rng.standard_normal((rows, service.dim)),
                        jnp.float32) for _ in range(32)]
    lock = threading.Lock()
    latencies = []
    counts = {"ok": 0, "rejected": 0, "errors": 0}
    stop_t = time.monotonic() + duration

    def one_request(i):
        q = pool[i % len(pool)]
        t0 = time.monotonic()
        try:
            fut = service.submit(q, timeout=deadline)
            fut.result(timeout=max(30.0, duration))
        except ServiceOverloadError:
            with lock:
                counts["rejected"] += 1
            return
        except Exception:
            with lock:
                counts["errors"] += 1
            return
        dt = time.monotonic() - t0
        with lock:
            counts["ok"] += 1
            latencies.append(dt)

    spawned = []  # open-loop per-request threads (joined after the pacer)
    if mode == "closed":
        def client(tid):
            i = tid
            while time.monotonic() < stop_t:
                one_request(i)
                i += concurrency

        threads = [threading.Thread(target=client, args=(t,), daemon=True)
                   for t in range(concurrency)]
    elif mode == "open":
        period = 1.0 / qps

        def pacer():
            i = 0
            next_t = time.monotonic()
            while time.monotonic() < stop_t:
                t = threading.Thread(target=one_request, args=(i,),
                                     daemon=True)
                t.start()
                spawned.append(t)
                i += 1
                next_t += period
                delay = next_t - time.monotonic()
                if delay > 0:
                    time.sleep(delay)

        threads = [threading.Thread(target=pacer, daemon=True)]
    else:
        raise SystemExit("unknown --mode %r" % mode)

    misses0 = _compile_misses()
    t_start = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=duration + 60.0)
    for t in spawned:  # in-flight open-loop requests
        t.join(timeout=60.0)
    wall = time.monotonic() - t_start

    lat = sorted(latencies)
    report = {
        "mode": mode,
        "duration_s": round(wall, 3),
        "requests_ok": counts["ok"],
        "rejected": counts["rejected"],
        "errors": counts["errors"],
        "qps": round(counts["ok"] / wall, 2) if wall else 0.0,
        "p50_ms": round(_percentile(lat, 0.50) * 1e3, 3),
        "p95_ms": round(_percentile(lat, 0.95) * 1e3, 3),
        "p99_ms": round(_percentile(lat, 0.99) * 1e3, 3),
        # compiles observed DURING the load window: a warmed service in
        # steady state reports 0 (docs/ZERO_COPY.md acceptance)
        "post_warmup_compiles": _compile_misses() - misses0,
    }
    report.update(_registry_serve_stats(service.name))
    return report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--service", choices=("knn", "pairwise"),
                    default="knn")
    ap.add_argument("--mode", choices=("closed", "open"), default="closed")
    ap.add_argument("--qps", type=float, default=100.0,
                    help="open-loop arrival rate")
    ap.add_argument("--concurrency", type=int, default=8,
                    help="closed-loop client threads")
    ap.add_argument("--duration", type=float, default=5.0)
    ap.add_argument("--rows", type=int, default=4,
                    help="query rows per request")
    ap.add_argument("--index-rows", type=int, default=20000)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--k", type=int, default=10)
    ap.add_argument("--max-batch-rows", type=int, default=1024)
    ap.add_argument("--max-wait-ms", type=float, default=None)
    ap.add_argument("--queue-cap", type=int, default=None)
    ap.add_argument("--deadline", type=float, default=None,
                    help="per-request deadline seconds")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--json", action="store_true",
                    help="print the raw report dict as JSON")
    args = ap.parse_args(argv)

    opts = {"max_batch_rows": args.max_batch_rows}
    if args.max_wait_ms is not None:
        opts["max_wait_ms"] = args.max_wait_ms
    if args.queue_cap is not None:
        opts["queue_cap"] = args.queue_cap
    service = build_service(args.service, args.index_rows, args.dim,
                            args.k, seed=args.seed, **opts)
    t0 = time.monotonic()
    service.warmup()
    warmup_s = time.monotonic() - t0
    try:
        report = run_load(service, mode=args.mode,
                          duration=args.duration,
                          concurrency=args.concurrency, qps=args.qps,
                          rows=args.rows, seed=args.seed,
                          deadline=args.deadline)
    finally:
        service.close()
    report["warmup_s"] = round(warmup_s, 3)
    report["buckets"] = list(service.policy.rungs)

    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
        return 0
    print("== loadgen: %s %s ==" % (args.service, args.mode))
    for key in ("duration_s", "requests_ok", "rejected", "errors", "qps",
                "p50_ms", "p95_ms", "p99_ms", "queue_wait_p50_ms",
                "queue_wait_p95_ms", "batches", "mean_batch_rows",
                "padding_waste", "post_warmup_compiles",
                "host_staged_bytes", "warmup_s", "buckets"):
        if key in report:
            val = report[key]
            if isinstance(val, float):
                val = "%.3f" % val
            print("  %-20s %s" % (key, val))
    return 0


if __name__ == "__main__":
    sys.exit(main())
