#!/usr/bin/env python
"""Load generator for raft_tpu serve services (docs/SERVING.md).

Drives a :class:`raft_tpu.serve.KNNService` / ``PairwiseService`` with
synthetic traffic and reports client-observed latency percentiles plus
the padding-waste / batch-fill numbers from the metrics registry — the
two halves of the serving trade (latency vs device efficiency) in one
screen.

Two loops:

- **closed** (``--concurrency N``): N client threads each submit a
  request, wait for its future, submit the next — throughput is
  latency-bound, the classic saturation probe.
- **open** (``--qps Q``): one pacing thread fires submits on a fixed
  schedule regardless of completions — arrival-rate-bound, the loop
  that actually exposes queueing: at overload it measures shed rate
  (``ServiceOverloadError`` count) rather than silently slowing down.

Usage:
    python tools/loadgen.py --mode closed --concurrency 8 --duration 5
    python tools/loadgen.py --mode open --qps 500 --duration 5 \\
        --rows 4 --index-rows 50000 --dim 64 --k 10
    python tools/loadgen.py --service pairwise --mode closed ...
    python tools/loadgen.py --service ann --clusters 64 --nlist 64 \\
        --recall-target 0.9 --k 100 ...

``--service ann`` fronts an IVF-Flat index
(:class:`raft_tpu.serve.ANNService`) and ALWAYS reports **recall@k**
against a brute-force ground truth computed once per run — an
approximate index's QPS number is meaningless without its quality
number (``--recall`` adds the same scoring to the exact services,
where it doubles as an end-to-end correctness check: recall 1.0).
``--recall-target`` calibrates ``nprobe`` to the target before the
measured run (recall-targeted dispatch, docs/SERVING.md).
``--ooc --device-budget-mb N`` serves the out-of-core tier instead
(host-resident slot store streamed through an N-MiB device budget,
docs/SERVING.md "Out-of-core serving"); the report then carries
``tile_hit_rate`` / ``h2d_mb`` / ``hidden_transfer_frac`` alongside
recall, and the chaos/steady scenarios compose unchanged — including
the 0-post-warmup-compiles assertion.

``--tenants`` runs the **mixed-tenant traffic-shaping scenario**
(docs/SERVING.md "Traffic shaping"): closed-loop interactive clients
plus an open-loop bulk flood through one weighted-fair service,
reporting per-tenant p50/p95/p99 and shed counts (every shed must be
typed and carry ``retry_after_s`` — exit 1 otherwise).  ``./stress.sh
tenants N`` loops it with rotating seeds.  ``--replicas R`` serves the
kNN index replicated over R disjoint sub-meshes with hedged dispatch;
``--hedge-chaos`` stalls one replica mid-run with a persistent
``Delay`` and asserts exactly-once resolution with hedge wins and zero
post-warmup compiles.

``--chaos`` runs the **seed-rotated chaos scenario** instead
(docs/FAULT_MODEL.md "Serving failure model"): seeded transient faults
at the serve seam for the whole run, a persistent serve-seam outage
(the simulated device loss) injected mid-run, recovery via
:class:`raft_tpu.serve.resilience.RecoveryManager`, and — the
invariant the whole resilience layer exists for — **every submitted
request resolves exactly once**, with a result or a *typed* error
(``RaftError`` taxonomy).  Lost futures or untyped errors fail the run
(exit 1).  ``stress.sh chaos N`` loops it with rotating seeds.

``--crash-restart`` runs the **durability chaos scenario**
(docs/PERSISTENCE.md): a persistent ANN service (``persist_dir``, WAL
``fsync="always"``) under concurrent query + insert traffic dies
mid-run with NO final snapshot, then rebuilds from the persist
directory alone — asserting zero acknowledged-insert loss,
bit-identical post-restore search vs a kept reference, typed-only
errors, and 0 post-warmup compiles after restore (exit 1 otherwise).
``stress.sh chaos N`` rotates it alongside the other chaos arms.

``--ops-port P`` runs the **ops-scrape scenario**
(docs/OBSERVABILITY.md "Ops plane"): a baseline load window, then the
same load with an embedded :class:`raft_tpu.serve.OpsPlane` on port P
(0 = ephemeral) scraped at 1 Hz (``/metrics`` parsed back +
``/healthz``) — asserting every scrape succeeded, the scraped window
performed 0 post-warmup compiles, and QPS stayed within noise of the
baseline (exit 1 otherwise).  ``./stress.sh ops N`` loops it.

``--trace [K]`` captures the flight-recorder timelines of the K
slowest requests (default 3) and prints their waterfalls next to the
p99 row (docs/OBSERVABILITY.md "Flight recorder & request tracing");
``--trace-dump PATH`` writes the whole recorder (ring + black boxes)
for ``tools/trace_report.py``.  A chaos run that FAILS its acceptance
assertion always dumps the black-box buffer to a
``flight_*_seed<N>.json`` file — the postmortem starts from the tape.

Importable: :func:`run_load` / :func:`run_chaos` return the report
dict (bench.py's ``serve`` rungs and tests reuse them).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _percentile(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def _ooc_pool_totals(service_name):
    """Current pool-labeled out-of-core counters for one service
    (pool name == service name) — the baseline :func:`run_load`
    snapshots before the measured window so warmup's forced tile
    streams do not pollute the reported hit rate / hidden fraction
    (the bench's load-window-delta discipline)."""
    from raft_tpu.core.metrics import default_registry

    reg = default_registry()

    def series_for(name):
        fam = reg.get(name)
        if fam is None:
            return None
        for labels, series in fam.series():
            if labels.get("pool") == service_name:
                return series
        return None

    out = {}
    for key, name, attr in (
            ("hits", "raft_tpu_tile_hits_total", "value"),
            ("misses", "raft_tpu_tile_misses_total", "value"),
            ("h2d_bytes", "raft_tpu_h2d_bytes_total", "value"),
            ("h2d_s", "raft_tpu_h2d_seconds", "total"),
            ("stall_s", "raft_tpu_h2d_stall_seconds", "total")):
        s = series_for(name)
        out[key] = float(getattr(s, attr)) if s is not None else 0.0
    out["present"] = any(out[k] for k in ("hits", "misses",
                                          "h2d_bytes"))
    return out


def _registry_serve_stats(service_name, ooc_base=None):
    """Padding-waste / batch-fill numbers for one service, read back
    from the metrics registry (the numbers the scheduler recorded —
    loadgen measures the client side, the registry the server side).
    ``ooc_base`` (a pre-run :func:`_ooc_pool_totals` snapshot) turns
    the out-of-core counters into load-window deltas."""
    from raft_tpu.core.metrics import default_registry

    reg = default_registry()

    def _value(name):
        fam = reg.get(name)
        if fam is None:
            return 0.0
        for labels, series in fam.series():
            if labels.get("service") == service_name:
                return series.value
        return 0.0

    payload = _value("raft_tpu_serve_payload_rows_total")
    padded = _value("raft_tpu_serve_padded_rows_total")
    batches = _value("raft_tpu_serve_batches_total")
    total = payload + padded
    out = {
        "batches": int(batches),
        "payload_rows": int(payload),
        "padded_rows": int(padded),
        "padding_waste": (padded / total) if total else 0.0,
        "mean_batch_rows": (payload / batches) if batches else 0.0,
    }
    fam = reg.get("raft_tpu_serve_wait_seconds")
    if fam is not None:
        for labels, series in fam.series():
            if labels.get("service") == service_name:
                out["queue_wait_p50_ms"] = series.quantile(0.50) * 1e3
                out["queue_wait_p95_ms"] = series.quantile(0.95) * 1e3
    # the zero-copy proof (docs/ZERO_COPY.md): payload bytes bounced
    # through host numpy anywhere in the process — 0 on the
    # device-resident paths (absent family == nothing ever staged)
    out["host_staged_bytes"] = int(
        reg.family_total("raft_tpu_comms_host_staged_bytes"))

    # out-of-core tier (docs/SERVING.md "Out-of-core serving"): tile
    # hit rate, H2D traffic and the hidden-transfer fraction as
    # LOAD-WINDOW deltas against the pre-run baseline (warmup streams
    # tiles too and must not pollute the measured window)
    now = _ooc_pool_totals(service_name)
    if now["present"]:
        base = ooc_base or {k: 0.0 for k in now}
        hits = now["hits"] - base.get("hits", 0.0)
        miss = now["misses"] - base.get("misses", 0.0)
        out["tile_hits"] = int(hits)
        out["tile_misses"] = int(miss)
        out["tile_hit_rate"] = (hits / (hits + miss)
                                if hits + miss else 0.0)
        out["h2d_mb"] = round(
            (now["h2d_bytes"] - base.get("h2d_bytes", 0.0)) / 1e6, 1)
        h2d_t = now["h2d_s"] - base.get("h2d_s", 0.0)
        stall_t = now["stall_s"] - base.get("stall_s", 0.0)
        out["hidden_transfer_frac"] = round(
            1.0 - stall_t / h2d_t, 3) if h2d_t else 0.0
    return out


def _compile_misses():
    """Total compile-cache misses across every profiled_jit wrapper
    (the steady-state proof: zero NEW misses after warmup)."""
    from raft_tpu.core.profiler import compile_cache_stats

    return sum(s["misses"] for fn in compile_cache_stats().values()
               for s in fn.values())


def synth_data(index_rows, dim, seed=0, clusters=0, cluster_std=0.3):
    """Synthetic reference matrix: i.i.d. gaussian rows, or (clusters >
    0) a gaussian mixture — the shape real embedding workloads have and
    the one where an IVF index earns its keep; recall is still measured
    honestly against brute force over the same data either way."""
    import numpy as np

    rng = np.random.default_rng(seed)
    if clusters <= 0:
        return rng.standard_normal((index_rows, dim)).astype(np.float32)
    centers = rng.standard_normal((clusters, dim)).astype(np.float32)
    assign = rng.integers(0, clusters, index_rows)
    return (centers[assign] + cluster_std * rng.standard_normal(
        (index_rows, dim))).astype(np.float32)


def make_query_pool(ref, rows, n=32, seed=1, noise=0.1):
    """Query blocks drawn NEAR the data (perturbed reference rows):
    queries from the served distribution, not from empty space —
    matters for any recall measurement on clustered data."""
    import jax.numpy as jnp
    import numpy as np

    rng = np.random.default_rng(seed)
    picks = rng.integers(0, ref.shape[0], (n, rows))
    base = np.asarray(ref)
    return [jnp.asarray(base[p] + noise * rng.standard_normal(
        (rows, base.shape[1])), jnp.float32) for p in picks]


def build_service(kind, index_rows, dim, k, seed=0, clusters=0,
                  nlist=None, nprobe=None, train_rows=None,
                  mesh_devices=None, replicas=None, ooc=False,
                  device_budget_mb=None, **opts):
    """A ready (not yet warmed) service over a synthetic index.

    ``kind="ann"`` builds an IVF-Flat index over the data first
    (``nlist`` defaults to ~sqrt(rows); ``train_rows`` opts into
    subsampled k-means training) and fronts it with
    :class:`~raft_tpu.serve.ANNService`.  The generated reference
    matrix is attached as ``service.loadgen_ref`` so recall ground
    truth and query pools can reuse it without regeneration.
    ``ooc=True`` serves the OUT-OF-CORE tier instead (docs/SERVING.md
    "Out-of-core serving"): the slot store stays host-resident and the
    device working set is bounded by ``device_budget_mb`` (default:
    one quarter of the store — the oversubscription the tier exists
    for).

    ``mesh_devices=N`` serves SHARDED (docs/SERVING.md "Sharded
    serving"): the index row-/slot-shards over a 1-D mesh spanning the
    first N local devices, and every batch dispatches into the pjit'd
    SPMD search (``merge=`` in ``opts`` picks the topology).  kNN and
    ANN only.  ``replicas=R`` (kNN only) serves REPLICATED with hedged
    dispatch: R disjoint sub-mesh replicas of the index, drawn from
    the ``mesh_devices`` span (default: all local devices).
    """
    import jax.numpy as jnp

    from raft_tpu.serve import ANNService, KNNService, PairwiseService

    if replicas is not None:
        from raft_tpu.comms.host_comms import default_mesh

        if kind != "knn":
            raise SystemExit("--replicas applies to the replicated "
                             "service (knn)")
        mesh = default_mesh(int(mesh_devices)
                            if mesh_devices is not None else None)
        opts = dict(opts, mesh=mesh, axis=mesh.axis_names[0],
                    replicas=int(replicas))
    elif mesh_devices is not None:
        from raft_tpu.comms.host_comms import default_mesh

        if kind not in ("knn", "ann"):
            raise SystemExit(
                "--mesh applies to the sharded services (knn/ann)")
        mesh = default_mesh(int(mesh_devices))
        opts = dict(opts, mesh=mesh, axis=mesh.axis_names[0])
    ref = jnp.asarray(synth_data(index_rows, dim, seed=seed,
                                 clusters=clusters))
    if kind == "knn":
        svc = KNNService(ref, k=k, **opts)
    elif kind == "pairwise":
        svc = PairwiseService(ref, **opts)
    elif kind == "ann":
        from raft_tpu.spatial.ann import IVFFlatParams, ivf_flat_build

        if nlist is None:
            nlist = max(16, min(4096, int(round(index_rows ** 0.5))))
        params = IVFFlatParams(nlist=int(nlist),
                               nprobe=int(nprobe) if nprobe else 8)
        index = ivf_flat_build(ref, params, train_rows=train_rows)
        if ooc:
            import numpy as np

            store_bytes = int(np.asarray(index.slot_vecs).nbytes)
            budget = (int(device_budget_mb) << 20
                      if device_budget_mb else store_bytes // 4)
            opts = dict(opts, ooc=True, device_budget_bytes=budget)
        svc = ANNService(index, k=k, **opts)
    else:
        raise SystemExit("unknown --service %r" % kind)
    svc.loadgen_ref = ref
    return svc


def _ground_truth_for_pool(service, pool, k):
    """Exact per-pool-block neighbor ids, computed ONCE per run (the
    brute-force half of every recall@k number this tool reports).

    Ground truth comes from the service's own content: ``loadgen_ref``
    when :func:`build_service` attached it, else the pinned index
    matrix (KNNService) or the reconstructable store + live delta
    (ANNService.ground_truth_store).
    """
    import jax.numpy as jnp
    import numpy as np

    from raft_tpu.spatial.knn import brute_force_knn

    ref = getattr(service, "loadgen_ref", None)
    if ref is not None:
        vecs, ids = np.asarray(ref), None
    elif hasattr(service, "ground_truth_store"):
        vecs, ids = service.ground_truth_store()
    elif hasattr(service, "index"):
        vecs, ids = np.asarray(service.index), None
    else:
        raise SystemExit(
            "recall requested but %s exposes no reference data"
            % service.name)
    cat = jnp.concatenate(list(pool), axis=0)
    _, rows_idx = brute_force_knn(jnp.asarray(vecs), cat, k)
    rows_idx = np.asarray(rows_idx)
    gt = rows_idx if ids is None else np.asarray(ids)[rows_idx]
    n = pool[0].shape[0]
    return [gt[j * n:(j + 1) * n] for j in range(len(pool))]


def run_load(service, *, mode="closed", duration=5.0, concurrency=8,
             qps=100.0, rows=4, seed=0, deadline=None, recall=False,
             query_pool=None, tenant=None, trace_k=0):
    """Drive ``service`` for ``duration`` seconds; returns the report.

    Latencies are client-observed submit→result seconds.  Rejected
    submits (admission control) and expired deadlines are counted, not
    raised — overload behavior is the *measurement*, not a failure.

    ``recall=True`` computes a brute-force ground truth for the query
    pool once up front and scores every completed request's returned
    ids against it — the report then carries ``recall_at_k`` next to
    p50/p95/p99, so a speed claim cannot shed quality silently.
    ``query_pool`` overrides the default i.i.d. gaussian pool (see
    :func:`make_query_pool` for data-aligned queries).  ``tenant``
    tags every submit (traffic shaping; the per-tenant solo baseline
    the mixed-tenant scenario compares against).

    ``trace_k > 0`` keeps the flight-recorder timelines of the K
    slowest completed requests (docs/OBSERVABILITY.md "Flight recorder
    & request tracing"): the report gains ``slow_traces`` — each with
    its trace_id and full timeline — so the p99 row links directly to
    the requests behind it (``--trace`` prints their waterfalls).
    """
    import heapq
    import itertools

    import jax.numpy as jnp
    import numpy as np

    from raft_tpu.core.error import ServiceOverloadError

    rng = np.random.default_rng(seed)
    # pre-generated query pool: the generator must not bottleneck on
    # fresh RNG draws mid-flight
    if query_pool is not None:
        pool = list(query_pool)
        row_counts = {int(p.shape[0]) for p in pool}
        if len(row_counts) != 1:
            raise SystemExit("query_pool blocks must share a row count")
        rows = row_counts.pop()
    else:
        pool = [jnp.asarray(rng.standard_normal((rows, service.dim)),
                            jnp.float32) for _ in range(32)]
    gt = None
    recall_k = getattr(service, "k", None)
    if recall:
        if recall_k is None:
            raise SystemExit(
                "recall requested but %s has no k (not a kNN-shaped "
                "service)" % service.name)
        gt = _ground_truth_for_pool(service, pool, recall_k)
    lock = threading.Lock()
    latencies = []
    counts = {"ok": 0, "rejected": 0, "errors": 0}
    recall_acc = {"sum": 0.0, "n": 0}
    # slowest-K capture: a min-heap of (latency, seq, future) so the
    # run retains at most K futures (and their traces), not all
    slow_heap = []
    slow_seq = itertools.count()
    stop_t = time.monotonic() + duration

    def one_request(i):
        q = pool[i % len(pool)]
        t0 = time.monotonic()
        try:
            fut = service.submit(q, timeout=deadline, tenant=tenant)
            out = fut.result(timeout=max(30.0, duration))
        except ServiceOverloadError:
            with lock:
                counts["rejected"] += 1
            return
        except Exception:
            with lock:
                counts["errors"] += 1
            return
        dt = time.monotonic() - t0
        if trace_k:
            with lock:
                item = (dt, next(slow_seq), fut)
                if len(slow_heap) < trace_k:
                    heapq.heappush(slow_heap, item)
                elif dt > slow_heap[0][0]:
                    heapq.heapreplace(slow_heap, item)
        r = None
        if gt is not None:
            got = np.asarray(out[1])
            want = gt[i % len(pool)]
            r = float(np.mean([
                len(set(got[j]) & set(want[j])) / recall_k
                for j in range(got.shape[0])]))
        with lock:
            counts["ok"] += 1
            latencies.append(dt)
            if r is not None:
                recall_acc["sum"] += r
                recall_acc["n"] += 1

    spawned = []  # open-loop per-request threads (joined after the pacer)
    if mode == "closed":
        def client(tid):
            i = tid
            while time.monotonic() < stop_t:
                one_request(i)
                i += concurrency

        threads = [threading.Thread(target=client, args=(t,), daemon=True)
                   for t in range(concurrency)]
    elif mode == "open":
        period = 1.0 / qps

        def pacer():
            i = 0
            next_t = time.monotonic()
            while time.monotonic() < stop_t:
                t = threading.Thread(target=one_request, args=(i,),
                                     daemon=True)
                t.start()
                spawned.append(t)
                i += 1
                next_t += period
                delay = next_t - time.monotonic()
                if delay > 0:
                    time.sleep(delay)

        threads = [threading.Thread(target=pacer, daemon=True)]
    else:
        raise SystemExit("unknown --mode %r" % mode)

    misses0 = _compile_misses()
    ooc_base = _ooc_pool_totals(service.name)
    t_start = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=duration + 60.0)
    for t in spawned:  # in-flight open-loop requests
        t.join(timeout=60.0)
    wall = time.monotonic() - t_start

    lat = sorted(latencies)
    report = {
        "mode": mode,
        "duration_s": round(wall, 3),
        "requests_ok": counts["ok"],
        "rejected": counts["rejected"],
        "errors": counts["errors"],
        "qps": round(counts["ok"] / wall, 2) if wall else 0.0,
        # request-level vs row-level throughput: requests carry `rows`
        # query rows each, and the raw-primitive rungs (knn_1m) count
        # rows — cross-rung speedup ratios must compare query_qps
        "query_qps": round(counts["ok"] * rows / wall, 2) if wall
        else 0.0,
        "p50_ms": round(_percentile(lat, 0.50) * 1e3, 3),
        "p95_ms": round(_percentile(lat, 0.95) * 1e3, 3),
        "p99_ms": round(_percentile(lat, 0.99) * 1e3, 3),
        # compiles observed DURING the load window: a warmed service in
        # steady state reports 0 (docs/ZERO_COPY.md acceptance)
        "post_warmup_compiles": _compile_misses() - misses0,
    }
    if gt is not None:
        report["recall_at_k"] = (
            round(recall_acc["sum"] / recall_acc["n"], 4)
            if recall_acc["n"] else 0.0)
        report["recall_k"] = int(recall_k)
    if trace_k:
        slow = []
        for dt, _, fut in sorted(slow_heap, reverse=True):
            tr = fut.trace()
            slow.append({
                "latency_ms": round(dt * 1e3, 3),
                "trace_id": tr.trace_id if tr is not None else None,
                "timeline": tr.timeline() if tr is not None else [],
            })
        report["slow_traces"] = slow
    report.update(_registry_serve_stats(service.name,
                                        ooc_base=ooc_base))
    return report


def run_ops_scrape(service, *, port=0, duration=6.0, concurrency=8,
                   rows=4, seed=0, query_pool=None, scrape_hz=1.0):
    """Steady serve load with a live ops plane being scraped — the
    scrape-safety scenario (docs/OBSERVABILITY.md "Ops plane").

    Two equal windows over one warmed service: a BASELINE window with
    no ops plane traffic, then a SCRAPED window with an embedded
    :class:`~raft_tpu.serve.opsplane.OpsPlane` and a ``scrape_hz``
    scraper thread pulling ``/metrics`` (parsed back — a scrape that
    returns garbage counts as a failure) and ``/healthz``.  Asserts
    (``ops_ok``): every scrape succeeded, the scraped window performed
    0 post-warmup compiles, and its QPS stayed within noise of the
    baseline (>= 0.6x here — a deliberately loose band for the loop
    venue; the ``ops_scrape_overhead`` bench rung measures the strict
    interleaved <= 3% bound).
    """
    import urllib.error
    import urllib.request

    from raft_tpu.core.metrics import parse_prometheus
    from raft_tpu.serve.opsplane import OpsPlane

    per_window = max(1.0, duration / 2)
    baseline = run_load(service, mode="closed", duration=per_window,
                        concurrency=concurrency, rows=rows, seed=seed,
                        query_pool=query_pool)
    scrape_stats = {"n": 0, "failures": 0, "latencies": []}
    stop = threading.Event()
    plane = OpsPlane(services={service.name: service}, port=port)
    bound_port = plane.port   # read before close() drops the socket

    def scraper():
        url = plane.url
        while not stop.is_set():
            t0 = time.monotonic()
            try:
                with urllib.request.urlopen(url + "/metrics",
                                            timeout=5) as resp:
                    parsed = parse_prometheus(
                        resp.read().decode("utf-8"))
                if "raft_tpu_serve_requests_total" not in parsed:
                    raise ValueError("scrape missing serve families")
                # the liveness probe a real scraper would pair it with
                # (503 while degraded still counts as a served scrape)
                try:
                    urllib.request.urlopen(url + "/healthz",
                                           timeout=5).close()
                except urllib.error.HTTPError:
                    pass
            except Exception:
                scrape_stats["failures"] += 1
            scrape_stats["n"] += 1
            scrape_stats["latencies"].append(time.monotonic() - t0)
            stop.wait(timeout=1.0 / scrape_hz)

    thread = threading.Thread(target=scraper, daemon=True)
    thread.start()
    try:
        scraped = run_load(service, mode="closed", duration=per_window,
                           concurrency=concurrency, rows=rows,
                           seed=seed, query_pool=query_pool)
    finally:
        stop.set()
        thread.join(timeout=10.0)
        plane.close()
    lat = sorted(scrape_stats["latencies"])
    ratio = (scraped["qps"] / baseline["qps"]
             if baseline["qps"] else 0.0)
    report = {
        "baseline_qps": baseline["qps"],
        "scraped_qps": scraped["qps"],
        "qps_ratio": round(ratio, 4),
        "scrapes": scrape_stats["n"],
        "scrape_failures": scrape_stats["failures"],
        "scrape_p95_ms": round(_percentile(lat, 0.95) * 1e3, 3),
        "post_warmup_compiles": scraped["post_warmup_compiles"],
        "p99_ms": scraped["p99_ms"],
        "ops_port": bound_port,
        "ops_ok": (scrape_stats["n"] > 0
                   and scrape_stats["failures"] == 0
                   and scraped["post_warmup_compiles"] == 0
                   and ratio >= 0.6),
    }
    report.update({k: v for k, v in scraped.items()
                   if k in ("host_staged_bytes", "requests_ok",
                            "rejected", "errors")})
    return report


def run_mixed_tenants(service, *, duration=5.0,
                      interactive_concurrency=4, bulk_qps=200.0,
                      interactive_rows=4, bulk_rows=32, seed=0,
                      interactive_tenant="interactive",
                      bulk_tenant="bulk", deadline=None):
    """Mixed-class traffic-shaping scenario (docs/SERVING.md "Traffic
    shaping"): **closed-loop interactive clients** (N threads,
    submit→wait→repeat — latency-bound, the user-facing class) run
    concurrently with an **open-loop bulk flood** (fixed arrival rate
    regardless of completions — the batch-pipeline class that would
    starve everyone without weighted-fair admission).  Reports
    per-tenant p50/p95/p99 + shed counts, and verifies every shed was
    *typed* (``ServiceOverloadError``/``ServiceUnavailableError``
    carrying ``retry_after_s`` — ``untyped_sheds`` must be 0).

    The service should be constructed with ``tenant_weights`` naming
    both tenants; the isolation claim (interactive p99 holds while
    bulk saturates its quota) is measured by comparing against an
    interactive-only :func:`run_load` baseline — the
    ``serve_mixed_tenant`` bench rung does exactly that.
    """
    import jax.numpy as jnp
    import numpy as np

    from raft_tpu.core.error import (ServiceOverloadError,
                                     ServiceUnavailableError)

    rng = np.random.default_rng(seed)
    pools = {
        interactive_tenant: [
            jnp.asarray(rng.standard_normal((interactive_rows,
                                             service.dim)), jnp.float32)
            for _ in range(16)],
        bulk_tenant: [
            jnp.asarray(rng.standard_normal((bulk_rows, service.dim)),
                        jnp.float32) for _ in range(16)],
    }
    lock = threading.Lock()
    stats = {t: {"ok": 0, "rejected": 0, "unavailable": 0, "errors": 0,
                 "latencies": []} for t in pools}
    untyped = {"sheds": 0}
    stop_t = time.monotonic() + duration

    def one_request(tenant, i):
        q = pools[tenant][i % 16]
        st = stats[tenant]
        t0 = time.monotonic()
        try:
            fut = service.submit(q, timeout=deadline, tenant=tenant)
            fut.result(timeout=max(30.0, duration))
        except (ServiceOverloadError, ServiceUnavailableError) as e:
            with lock:
                st["rejected" if isinstance(e, ServiceOverloadError)
                   else "unavailable"] += 1
                # the taxonomy audit: an overload shed must carry a
                # REAL drain estimate (the batcher always produces
                # one; 0.0 means a shed site skipped the hint), and a
                # tenant-cap shed must name the tenant
                if isinstance(e, ServiceOverloadError) and (
                        e.retry_after_s <= 0.0 or e.tenant is None):
                    untyped["sheds"] += 1
            return
        except Exception:
            with lock:
                st["errors"] += 1
            return
        dt = time.monotonic() - t0
        with lock:
            st["ok"] += 1
            st["latencies"].append(dt)

    def interactive_client(tid):
        i = tid
        while time.monotonic() < stop_t:
            one_request(interactive_tenant, i)
            i += interactive_concurrency

    spawned = []

    def bulk_pacer():
        period = 1.0 / bulk_qps
        i = 0
        next_t = time.monotonic()
        while time.monotonic() < stop_t:
            t = threading.Thread(target=one_request,
                                 args=(bulk_tenant, i), daemon=True)
            t.start()
            spawned.append(t)
            i += 1
            next_t += period
            delay = next_t - time.monotonic()
            if delay > 0:
                time.sleep(delay)

    threads = ([threading.Thread(target=interactive_client, args=(t,),
                                 daemon=True)
                for t in range(interactive_concurrency)]
               + [threading.Thread(target=bulk_pacer, daemon=True)])
    misses0 = _compile_misses()
    ooc_base = _ooc_pool_totals(service.name)
    t_start = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=duration + 60.0)
    for t in spawned:
        t.join(timeout=60.0)
    wall = time.monotonic() - t_start

    report = {"mode": "mixed-tenants", "duration_s": round(wall, 3),
              "post_warmup_compiles": _compile_misses() - misses0,
              "untyped_sheds": untyped["sheds"], "tenants": {}}
    for tenant, st in stats.items():
        lat = sorted(st["latencies"])
        report["tenants"][tenant] = {
            "requests_ok": st["ok"],
            "rejected": st["rejected"],
            "unavailable": st["unavailable"],
            "errors": st["errors"],
            "qps": round(st["ok"] / wall, 2) if wall else 0.0,
            "p50_ms": round(_percentile(lat, 0.50) * 1e3, 3),
            "p95_ms": round(_percentile(lat, 0.95) * 1e3, 3),
            "p99_ms": round(_percentile(lat, 0.99) * 1e3, 3),
        }
    report.update(_registry_serve_stats(service.name,
                                        ooc_base=ooc_base))
    return report


def run_hedge_chaos(service, *, duration=5.0, concurrency=4, rows=4,
                    seed=0, delay_s=0.4, delay_at=0.25, delay_for=0.5):
    """Hedged-dispatch chaos scenario (docs/FAULT_MODEL.md "Hedged
    dispatch"): closed-loop traffic against a **replicated** service
    while one replica straggles — a persistent ``Delay`` at replica
    0's execute seam for the middle ``delay_for`` fraction of the run.
    Hedges must fire and win (the straggler's batches resolve from the
    other replica), losers must cancel via the commit handshake, and
    the exactly-once/typed-only/zero-compile invariants must all hold.

    ``chaos_ok`` requires: every admitted request resolved exactly
    once with a result or typed error, ``hedge_wins > 0``, 0
    post-warmup compiles, 0 host-staged bytes.
    """
    import jax.numpy as jnp
    import numpy as np

    from raft_tpu.comms import faults
    from raft_tpu.core.error import RaftError
    from raft_tpu.core.metrics import default_registry
    from raft_tpu.serve.replicas import ReplicaFaultInjector

    if getattr(service, "_replica_set", None) is None:
        raise SystemExit("--hedge-chaos needs a replicated service "
                         "(--replicas >= 2)")
    rng = np.random.default_rng(seed)
    pool = [jnp.asarray(rng.standard_normal((rows, service.dim)),
                        jnp.float32) for _ in range(16)]
    lock = threading.Lock()
    admitted = []
    counts = {"submitted": 0, "sheds": 0}
    stop_t = time.monotonic() + duration

    def client(tid):
        i = tid
        while time.monotonic() < stop_t:
            q = pool[i % len(pool)]
            i += concurrency
            try:
                fut = service.submit(q)
            except RaftError:
                with lock:
                    counts["sheds"] += 1
                time.sleep(0.01)
                continue
            with lock:
                counts["submitted"] += 1
                admitted.append(fut)
            fut.wait(timeout=10.0)

    def reg_total(name):
        return int(default_registry().family_total(name))

    hedges0 = reg_total("raft_tpu_serve_hedges_total")
    wins0 = reg_total("raft_tpu_serve_hedge_wins_total")
    cancelled0 = reg_total("raft_tpu_serve_hedge_cancelled_total")
    misses0 = _compile_misses()

    threads = [threading.Thread(target=client, args=(t,), daemon=True)
               for t in range(concurrency)]
    injector = None
    try:
        for t in threads:
            t.start()
        time.sleep(max(0.0, duration * delay_at))
        # the straggling replica: every batch it carries stalls long
        # past the hedge threshold
        injector = ReplicaFaultInjector(service, 0,
                                        [faults.Delay(delay_s)])
        injector.activate()
        time.sleep(duration * delay_for)
        injector.deactivate()
        injector = None
        for t in threads:
            t.join(timeout=duration + 30.0)
    finally:
        if injector is not None:
            injector.deactivate()
    service.drain(timeout=30.0)
    results = {"ok": 0, "typed_errors": 0, "untyped_errors": 0,
               "lost": 0}
    for fut in admitted:
        if not fut.wait(timeout=30.0):
            results["lost"] += 1
            continue
        err = fut.exception(timeout=0)
        if err is None:
            results["ok"] += 1
        elif isinstance(err, RaftError):
            results["typed_errors"] += 1
        else:
            results["untyped_errors"] += 1
    resolved = (results["ok"] + results["typed_errors"]
                + results["untyped_errors"])
    hedges = reg_total("raft_tpu_serve_hedges_total") - hedges0
    wins = reg_total("raft_tpu_serve_hedge_wins_total") - wins0
    report = {
        "seed": seed,
        "duration_s": duration,
        "delay_s": delay_s,
        **counts,
        **results,
        "resolved": resolved,
        "exactly_once": (results["lost"] == 0
                         and resolved == counts["submitted"]),
        "typed_only": results["untyped_errors"] == 0,
        "hedges_fired": hedges,
        "hedge_wins": wins,
        "hedge_cancelled": reg_total(
            "raft_tpu_serve_hedge_cancelled_total") - cancelled0,
        "post_warmup_compiles": _compile_misses() - misses0,
        "host_staged_bytes": int(default_registry().family_total(
            "raft_tpu_comms_host_staged_bytes")),
    }
    report["chaos_ok"] = (report["exactly_once"]
                          and report["typed_only"]
                          and wins > 0
                          and report["post_warmup_compiles"] == 0
                          and report["host_staged_bytes"] == 0)
    return report


def run_chaos(service, *, duration=6.0, concurrency=4, rows=4, seed=0,
              transient_p=0.05, outage_at=0.35, outage_s=0.8,
              manager=None, query_pool=None, kill_shard=False):
    """Chaos scenario: drive ``service`` closed-loop while injecting
    seeded faults at the serve seam, with a simulated device loss
    (persistent outage) mid-run; returns the report.

    Timeline (fractions of ``duration``):

    - ``[0, 1]``  — ``RandomFail(p=transient_p, seed=seed)`` at the
      serve execute seam: every batch may fail transiently; the breaker
      absorbs the noise (and may trip + self-heal through half-open
      probes on an unlucky seed — that IS the scenario).
    - ``[outage_at, outage_at + outage_s/duration]`` — a persistent
      ``FailNth`` (every batch fails): the simulated device loss.  The
      breaker trips, admission sheds ``ServiceUnavailableError``,
      in-flight riders are re-enqueued once.
    - outage end — the fault detaches ("surviving mesh works again");
      ``manager.recover()`` runs if a
      :class:`~raft_tpu.serve.resilience.RecoveryManager` was passed
      (device-loss semantics: re-publish + re-warm + re-admit),
      otherwise the breaker's half-open probe re-closes it alone.
      With ``kill_shard`` (sharded services only) the outage IS a
      shard loss: the serving mesh permanently loses its last device,
      and recovery re-partitions the index over the survivors
      (``service.repartition``) before re-warming — the report then
      carries ``post_recovery_exact``: post-heal results checked
      exactly against a single-device brute-force ground truth.

    The acceptance invariant, asserted into the report: **every
    submitted request resolves exactly once** — ``ok + typed_errors +
    untyped_errors == submitted`` and ``lost == 0`` — and every error
    is typed (``RaftError`` taxonomy; ``untyped_errors == 0``).
    Sheds at admission (overload / unavailable) are counted separately:
    a shed request was never admitted, so it has no future to resolve.
    """
    import jax.numpy as jnp
    import numpy as np

    from raft_tpu.comms import faults
    from raft_tpu.core.error import (RaftError, ServiceOverloadError,
                                     ServiceUnavailableError)
    from raft_tpu.core.metrics import default_registry
    from raft_tpu.serve.resilience import ServeFaultInjector

    rng = np.random.default_rng(seed)
    if query_pool is not None:
        pool = list(query_pool)
        rows = int(pool[0].shape[0])
    else:
        pool = [jnp.asarray(rng.standard_normal((rows, service.dim)),
                            jnp.float32) for _ in range(16)]
    lock = threading.Lock()
    admitted = []          # (future, submit_t) — every future must resolve
    counts = {"submitted": 0, "rejected": 0, "unavailable": 0}
    stop_t = time.monotonic() + duration

    def client(tid):
        i = tid
        while time.monotonic() < stop_t:
            q = pool[i % len(pool)]
            i += concurrency
            try:
                fut = service.submit(q)
            except ServiceUnavailableError:
                with lock:
                    counts["unavailable"] += 1
                time.sleep(0.01)   # shed: back off, as a client would
                continue
            except ServiceOverloadError:
                with lock:
                    counts["rejected"] += 1
                time.sleep(0.01)
                continue
            with lock:
                counts["submitted"] += 1
                admitted.append(fut)
            # closed loop: wait (bounded) so concurrency stays fixed,
            # but resolution is scored in the final sweep either way
            fut.wait(timeout=5.0)

    def reg_total(name):
        return int(default_registry().family_total(name))

    trips0 = reg_total("raft_tpu_serve_breaker_trips_total")
    recov0 = reg_total("raft_tpu_serve_recoveries_total")
    requeue0 = reg_total("raft_tpu_serve_requeued_total")

    threads = [threading.Thread(target=client, args=(t,), daemon=True)
               for t in range(concurrency)]
    transient = ServeFaultInjector(
        service.worker,
        [faults.RandomFail(transient_p, seed=seed)] if transient_p > 0
        else [])
    transient.activate()
    outage = None
    try:
        for t in threads:
            t.start()
        time.sleep(max(0.0, duration * outage_at))
        # the simulated device loss: every batch fails, persistently
        outage = ServeFaultInjector(
            service.worker, [faults.FailNth(1, persistent=True)])
        outage.activate()
        time.sleep(outage_s)
        outage.deactivate()         # survivors work again
        outage = None
        if kill_shard:
            # the outage WAS a shard loss: drop the serving mesh's
            # last device and re-partition its rows/slots across the
            # survivors (quiesced — a swap must never tear a batch)
            if getattr(service, "axis", None) is None:
                raise SystemExit(
                    "--kill-shard needs a sharded service (--mesh N)")
            from jax.sharding import Mesh

            devs = list(service.mesh.devices.ravel())
            if len(devs) < 2:
                raise SystemExit("--kill-shard: nothing to kill on a "
                                 "1-device mesh")
            survivors = Mesh(np.asarray(devs[:-1]),
                             service.mesh.axis_names)
            service.pause()
            service.worker.quiesce(timeout=15.0)
            service.repartition(mesh=survivors)
            service.resume()
        if manager is not None:
            manager.recover()       # orchestrated recovery (+ warmup)
        for t in threads:
            t.join(timeout=duration + 30.0)
    finally:
        if outage is not None:
            outage.deactivate()
        transient.deactivate()
    post_exact = None
    if kill_shard:
        from raft_tpu.serve import KNNService
        from raft_tpu.spatial.knn import brute_force_knn

        if isinstance(service, KNNService):
            # exact post-recovery results: the re-partitioned service
            # must answer identically to single-device brute force
            # over the SAME full index (no rows lost with the shard).
            # A still-cooling breaker (no manager passed) may shed the
            # first probe — wait out the hint and retry once.
            probe_q = pool[0]
            for _attempt in range(2):
                try:
                    out = service.submit(probe_q).result(timeout=30.0)
                    break
                except RaftError:
                    time.sleep(
                        max(0.05, service.breaker.retry_after())
                        if service.breaker is not None else 0.3)
            else:
                out = service.submit(probe_q).result(timeout=30.0)
            _, i_ref = brute_force_knn(service.index, probe_q,
                                       service.k)
            post_exact = bool(
                (np.asarray(out[1]) == np.asarray(i_ref)).all())
    # final sweep: drain what is still queued, then score every future
    service.drain(timeout=30.0)
    results = {"ok": 0, "typed_errors": 0, "untyped_errors": 0,
               "lost": 0}
    for fut in admitted:
        if not fut.wait(timeout=30.0):
            results["lost"] += 1
            continue
        err = fut.exception(timeout=0)
        if err is None:
            results["ok"] += 1
        elif isinstance(err, RaftError):
            results["typed_errors"] += 1
        else:
            results["untyped_errors"] += 1

    resolved = (results["ok"] + results["typed_errors"]
                + results["untyped_errors"])
    report = {
        "seed": seed,
        "duration_s": duration,
        "outage_s": outage_s,
        "transient_p": transient_p,
        **counts,
        **results,
        "resolved": resolved,
        "exactly_once": (results["lost"] == 0
                         and resolved == counts["submitted"]),
        "typed_only": results["untyped_errors"] == 0,
        "breaker_trips": reg_total("raft_tpu_serve_breaker_trips_total")
        - trips0,
        "requeued": reg_total("raft_tpu_serve_requeued_total")
        - requeue0,
        "recoveries": reg_total("raft_tpu_serve_recoveries_total")
        - recov0,
        "breaker_state": (service.breaker.describe()["state"]
                          if service.breaker is not None else None),
        "chaos_ok": (results["lost"] == 0
                     and results["untyped_errors"] == 0
                     and resolved == counts["submitted"]
                     and post_exact is not False),
    }
    if kill_shard:
        report["kill_shard"] = True
        report["post_recovery_exact"] = post_exact
        if getattr(service, "axis", None) is not None:
            report["shard_devices"] = int(
                service.mesh.shape[service.axis])
    return report


def run_crash_restart(persist_dir, *, index_rows=4000, dim=16, k=5,
                      seed=0, duration=4.0, concurrency=3, rows=4,
                      nlist=32, clusters=16, insert_rows=8,
                      svc_opts=None):
    """Crash-restart chaos scenario (docs/PERSISTENCE.md): drive a
    **persistent** ANNService (WAL ``fsync="always"``, short snapshot
    interval) with closed-loop queries plus a concurrent insert
    stream, then simulate **process death mid-run** — drop the live
    service with NO final snapshot — and rebuild a fresh service from
    ``persist_dir`` alone (``ANNService(None, persist_dir=...)``).

    ``crash_ok`` requires ALL of:

    - **zero acknowledged-insert loss** — every id whose ``insert()``
      returned before the crash is present in the restored service's
      ground-truth store (the WAL acknowledge contract);
    - **bit-identical search** — a reference result set captured from
      the live service (after quiescing inserts) matches the restored
      service's answers bit-for-bit, distances and ids;
    - **exactly-once, typed-only** — every admitted future resolved
      exactly once with a result or a typed ``RaftError`` (the crash
      fails in-flight riders with typed errors, never silence);
    - **0 post-warmup compiles** on the restored service — restore +
      ``warmup()`` rebuilds the exact executables, nothing retraces.
    """
    import jax.numpy as jnp
    import numpy as np

    from raft_tpu.core.error import RaftError
    from raft_tpu.serve import ANNService
    from raft_tpu.spatial.ann import IVFFlatParams, ivf_flat_build

    rng = np.random.default_rng(seed)
    ref_data = jnp.asarray(synth_data(index_rows, dim, seed=seed,
                                      clusters=clusters))
    index = ivf_flat_build(ref_data, IVFFlatParams(nlist=nlist,
                                                   nprobe=8))
    opts = dict(max_batch_rows=64, bucket_rungs=(8, 64),
                max_wait_ms=1.0, delta_cap=2048, compact_rows=512,
                nprobe_ladder=(4, 8))
    opts.update(svc_opts or {})
    svc = ANNService(index, k=k, persist_dir=persist_dir,
                     persist_fsync="always",
                     snapshot_interval_s=max(0.5, duration / 4),
                     **opts)
    svc.warmup()
    pool = make_query_pool(ref_data, rows, n=8, seed=seed + 1)

    lock = threading.Lock()
    admitted = []
    counts = {"submitted": 0, "sheds": 0}
    acked_ids = []
    stop_inserts = threading.Event()
    stop_clients = threading.Event()

    def client(tid):
        i = tid
        while not stop_clients.is_set():
            q = pool[i % len(pool)]
            i += concurrency
            try:
                fut = svc.submit(q)
            except RaftError:
                with lock:
                    counts["sheds"] += 1
                time.sleep(0.01)
                continue
            with lock:
                counts["submitted"] += 1
                admitted.append(fut)
            fut.wait(timeout=5.0)

    def inserter():
        base = 10_000_000
        n = 0
        while not stop_inserts.is_set():
            ids = np.arange(base + n, base + n + insert_rows)
            vecs = rng.standard_normal(
                (insert_rows, dim)).astype(np.float32)
            try:
                svc.insert(ids, vecs)
            except RaftError:
                time.sleep(0.02)
                continue
            with lock:
                acked_ids.extend(int(x) for x in ids)
            n += insert_rows
            time.sleep(0.002)

    threads = ([threading.Thread(target=client, args=(t,), daemon=True)
                for t in range(concurrency)]
               + [threading.Thread(target=inserter, daemon=True)])
    for t in threads:
        t.start()
    time.sleep(duration * 0.5)
    # quiesce inserts; then freeze interval snapshotting, take one
    # catch-up snapshot (WAL drains to 0), and append a LAST
    # acknowledged burst that only the WAL holds — the crash below
    # lands with a guaranteed-non-empty WAL tail, so restore MUST
    # exercise replay, not just snapshot load
    stop_inserts.set()
    threads[-1].join(timeout=10.0)
    svc._persist.snapshot_interval_s = 1e9
    time.sleep(0.1)   # let an in-flight maintenance tick finish
    # fold the delta now: the restored service must not cross its own
    # compact_rows threshold mid-probe (a compaction there grows the
    # slot layout and pays a legitimate one-time layout compile,
    # which would muddy the 0-post-warmup-compiles assertion)
    svc.compact()
    svc._persist.snapshot(svc._ann_state)
    burst = np.arange(20_000_000, 20_000_000 + 2 * insert_rows)
    svc.insert(burst, rng.standard_normal(
        (burst.size, dim)).astype(np.float32))
    with lock:
        acked_ids.extend(int(x) for x in burst)
    # the kept reference the restored service must reproduce
    # bit-for-bit (queries only from here on — the served state is
    # frozen; a snapshot would change nothing, and none will run)
    reference = []
    for q in pool:
        out = svc.submit(q).result(timeout=30.0)
        reference.append((np.asarray(out[0]).copy(),
                          np.asarray(out[1]).copy()))
    # keep querying, then die mid-traffic: the simulated process death
    # takes NO final snapshot — restart must recover from the last
    # interval snapshot plus the WAL tail
    time.sleep(duration * 0.25)
    svc.close(drain=False, timeout=2.0, snapshot=False)
    stop_clients.set()
    for t in threads[:-1]:
        t.join(timeout=15.0)

    results = {"ok": 0, "typed_errors": 0, "untyped_errors": 0,
               "lost": 0}
    for fut in admitted:
        if not fut.wait(timeout=10.0):
            results["lost"] += 1
            continue
        err = fut.exception(timeout=0)
        if err is None:
            results["ok"] += 1
        elif isinstance(err, RaftError):
            results["typed_errors"] += 1
        else:
            results["untyped_errors"] += 1
    resolved = (results["ok"] + results["typed_errors"]
                + results["untyped_errors"])

    # rebuild from the persist directory alone
    t0 = time.monotonic()
    svc2 = ANNService(None, k=k, persist_dir=persist_dir,
                      persist_fsync="always", **opts)
    restore_s = time.monotonic() - t0
    pstats = svc2._persist.stats()
    svc2.warmup()
    misses0 = _compile_misses()
    identical = True
    for q, (d_ref, i_ref) in zip(pool, reference):
        out = svc2.submit(q).result(timeout=30.0)
        if not ((np.asarray(out[0]) == d_ref).all()
                and (np.asarray(out[1]) == i_ref).all()):
            identical = False
    post_restore_compiles = _compile_misses() - misses0
    _, gt_ids = svc2.ground_truth_store()
    missing = sorted(set(acked_ids) - set(int(x) for x in gt_ids))
    svc2.close()

    report = {
        "seed": seed,
        "duration_s": duration,
        **counts,
        **results,
        "resolved": resolved,
        "exactly_once": (results["lost"] == 0
                         and resolved == counts["submitted"]),
        "typed_only": results["untyped_errors"] == 0,
        "acked_inserts": len(acked_ids),
        "lost_inserts": len(missing),
        "no_insert_loss": not missing,
        "bit_identical": identical,
        "restore_s": round(restore_s, 3),
        "restored_snapshot_seq": pstats["snapshot_seq"],
        "wal_replayed_records": pstats["replayed_records"],
        "wal_replay_records_per_s": round(
            pstats["replayed_records"] / max(restore_s, 1e-9), 1),
        "post_restore_compiles": post_restore_compiles,
    }
    report["crash_ok"] = (report["exactly_once"]
                          and report["typed_only"]
                          and report["no_insert_loss"]
                          and report["bit_identical"]
                          # the scenario guarantees a WAL tail at the
                          # crash (the post-snapshot burst): a restore
                          # that replayed nothing did not recover it
                          and report["wal_replayed_records"] > 0
                          and post_restore_compiles == 0)
    return report


def run_fleet(root, *, n_workers=2, mode="sharded", index_rows=2000,
              dim=16, k=5, seed=0, duration=6.0, concurrency=4,
              rows=4, nlist=16, clusters=8, insert_rows=8,
              chaos=True, trace_k=0):
    """Fleet chaos scenario (docs/FAULT_MODEL.md "Fleet fault
    domains"): a router + ``n_workers`` worker PROCESSES under
    concurrent closed-loop search traffic plus (sharded mode) an
    insert stream, while a seeded :class:`ChaosSchedule` injects
    process faults — SIGKILL + restart, hang, slow rejoin, dropped/
    garbled frames, fsync stall.  After the schedule drains and the
    fleet heals, ``fleet_ok`` requires ALL of:

    - **zero acknowledged-insert loss** — every id the router reported
      in ``acked_ids`` is findable post-heal (its exact vector returns
      the id in top-k; the WAL-ack contract held across the kill);
    - **exactly-once, typed-only** — terminal outcome counters equal
      admitted calls (client calls minus typed sheds), no request id
      carries two terminal flight events, and no client ever saw an
      untyped error;
    - **healed** — every worker is active again (the killed worker
      rejoined from snapshot+WAL; the hung worker re-registered via
      the heartbeat ``rereg`` handshake), and a process fault that
      actually fired produced a ``fleet_rejoin``.

    The router never crashing is implicit: a dead router fails every
    subsequent call untyped.
    """
    import numpy as np

    from raft_tpu.core import flight
    from raft_tpu.core import metrics as _metrics
    from raft_tpu.core.error import RaftError
    from raft_tpu.fleet import Fleet, Router
    from raft_tpu.fleet.chaos import (ChaosHarness, ChaosSchedule,
                                      FrameFaults)

    rng = np.random.default_rng(seed)
    frame = FrameFaults(seed + 1)
    router = Router(
        mode=mode,
        shard_count=(n_workers if mode == "sharded" else 1),
        transport=frame)
    fleet = Fleet(n_workers, root=root, index_rows=index_rows,
                  dim=dim, k=k, mode=mode, seed=seed,
                  clusters=clusters, nlist=nlist, router=router,
                  service_opts={"delta_cap": 8192})
    report = {"seed": seed, "duration_s": duration, "mode": mode,
              "workers": n_workers}
    harness = None
    try:
        fleet.wait_ready()
        data = synth_data(index_rows, dim, seed=seed,
                          clusters=clusters)
        q_idx = rng.integers(0, index_rows, size=(16, rows))
        qpool = [data[ix] for ix in q_idx]

        lock = threading.Lock()
        counts = {"calls": 0, "search_ok": 0, "degraded": 0,
                  "typed_errors": 0, "untyped_errors": 0,
                  "insert_batches": 0, "insert_partial": 0}
        acked = {}
        stop = threading.Event()

        def client(tid):
            i = tid
            while not stop.is_set():
                q = qpool[i % len(qpool)]
                i += concurrency
                with lock:
                    counts["calls"] += 1
                try:
                    out = router.search(q.tolist(), timeout_s=8.0)
                except RaftError:
                    with lock:
                        counts["typed_errors"] += 1
                    time.sleep(0.01)
                except Exception:
                    with lock:
                        counts["untyped_errors"] += 1
                    time.sleep(0.01)
                else:
                    with lock:
                        counts["search_ok"] += 1
                        if out["degraded"]:
                            counts["degraded"] += 1

        def inserter():
            base = max(1_000_000, index_rows * 10)
            n = 0
            while not stop.is_set():
                ids = list(range(base + n, base + n + insert_rows))
                vecs = rng.standard_normal(
                    (insert_rows, dim)).astype(np.float32)
                with lock:
                    counts["calls"] += 1
                    counts["insert_batches"] += 1
                try:
                    rep = router.insert(
                        ids, [v.tolist() for v in vecs],
                        timeout_s=8.0)
                except RaftError:
                    with lock:
                        counts["typed_errors"] += 1
                    time.sleep(0.05)
                    continue
                except Exception:
                    with lock:
                        counts["untyped_errors"] += 1
                    time.sleep(0.05)
                    continue
                ok_ids = set(rep["acked_ids"])
                with lock:
                    if not rep["ok"]:
                        counts["insert_partial"] += 1
                    for j, iid in enumerate(ids):
                        if iid in ok_ids:
                            acked[iid] = vecs[j]
                n += insert_rows
                time.sleep(0.03)

        threads = [threading.Thread(target=client, args=(t,),
                                    daemon=True)
                   for t in range(concurrency)]
        if mode == "sharded":
            threads.append(threading.Thread(target=inserter,
                                            daemon=True))
        if chaos:
            sched = ChaosSchedule.from_seed(seed, duration_s=duration,
                                            n_workers=n_workers)
            harness = ChaosHarness(fleet, sched,
                                   frame_faults=frame).start()
        for t in threads:
            t.start()
        time.sleep(duration)
        stop.set()
        for t in threads:
            t.join(timeout=30.0)
        if harness is not None:
            harness.join(timeout=30.0)
            harness.stop()
        frame.disarm()

        # heal: every worker active again (restart rejoined, hang
        # re-registered) before the final accounting + verification
        t_heal = time.monotonic()
        while (len(router.active_workers()) < n_workers
               and time.monotonic() - t_heal < 60.0):
            time.sleep(0.1)
        healed = len(router.active_workers()) == n_workers

        # accounting BEFORE verification traffic (the verification
        # searches below are requests too and would shift the counts)
        snap = _metrics.default_registry().snapshot()

        def _total(name, label=None):
            out = {}
            for s in snap.get(name, {}).get("series", []):
                key = s["labels"].get(label) if label else "_"
                out[key] = out.get(key, 0) + int(s["value"])
            return out

        outcomes = _total("raft_tpu_fleet_requests_total", "outcome")
        sheds = outcomes.get("shed", 0)
        terminals = sum(v for o, v in outcomes.items()
                        if o != "shed")
        admitted = counts["calls"] - sheds
        rejoins = sum(_total("raft_tpu_fleet_rejoins_total").values())
        evictions = _total("raft_tpu_fleet_evictions_total", "reason")
        retries = sum(_total("raft_tpu_fleet_retries_total").values())
        frames = _total("raft_tpu_fleet_frame_errors_total", "kind")

        # no rid may carry two terminal flight events (the ring is
        # bounded, so this is a recent-window duplicate check; the
        # counter identity above is the full-run count check)
        rec = flight.default_recorder()
        term_rids = {}
        for kind in ("fleet_resolved", "fleet_failed",
                     "fleet_expired"):
            for ev in rec.events(kind=kind):
                rid = (ev.attrs or {}).get("rid")
                if rid is not None:
                    term_rids[rid] = term_rids.get(rid, 0) + 1
        dup_terminals = sum(1 for v in term_rids.values() if v > 1)

        # zero acked-row loss: every acknowledged insert's exact
        # vector must return its id in top-k from the healed fleet
        lost, verify_errors, verified = [], 0, 0
        items = sorted(acked.items())
        for off in range(0, len(items), 32):
            chunk = items[off:off + 32]
            try:
                out = router.search([v.tolist() for _, v in chunk],
                                    timeout_s=15.0)
            except Exception:
                verify_errors += 1
                lost.extend(iid for iid, _ in chunk)
                continue
            for (iid, _), row in zip(chunk, out["ids"]):
                verified += 1
                if iid not in row:
                    lost.append(iid)

        applied = harness.applied if harness is not None else []
        proc_faults = [e for e in applied
                       if e["kind"] in ("kill", "hang")
                       and "failed" not in e]
        report.update(
            counts,
            sheds=sheds, outcomes=outcomes, admitted=admitted,
            terminals=terminals,
            exactly_once=(terminals == admitted
                          and dup_terminals == 0),
            dup_terminals=dup_terminals,
            typed_only=counts["untyped_errors"] == 0,
            acked_inserts=len(acked), verified=verified,
            lost_inserts=len(lost), no_insert_loss=not lost,
            verify_errors=verify_errors, healed=healed,
            rejoins=rejoins, evictions=evictions, retries=retries,
            frame_errors=frames,
            frame_injected=dict(frame.injected),
            chaos_applied=[e["kind"] for e in applied],
            chaos_failed=[e["kind"] for e in applied
                          if "failed" in e],
            rejoin_seen=(rejoins >= 1 or not proc_faults))
        report["fleet_ok"] = (report["exactly_once"]
                              and report["typed_only"]
                              and report["no_insert_loss"]
                              and report["healed"]
                              and report["rejoin_seen"]
                              and not report["chaos_failed"])
        # cross-process waterfalls must be joined HERE, while the
        # fleet is still alive — the join scrapes each owning worker's
        # /debug/trace endpoint
        if trace_k:
            # router-local trace id -> fleet request id (the exemplar
            # reservoir stores local ids; the join is keyed by rid)
            tid_to_rid = {}
            for fid in rec.fleet_trace_ids():
                for tr in rec.fleet_traces(fid):
                    tid_to_rid[tr.trace_id] = fid
            slow = []
            for ex in flight.exemplars_for("fleet").snapshot():
                rid = tid_to_rid.get(ex["trace_id"])
                if rid is None:
                    continue
                status, joined = router.fleet_trace(rid)
                if status == 200:
                    slow.append({"latency_ms": ex["latency_ms"],
                                 "rid": rid, "joined": joined})
                if len(slow) >= trace_k:
                    break
            report["slow_fleet_traces"] = slow
        offenders = sorted(rid for rid, v in term_rids.items()
                           if v > 1)[:5]
        report["offending_rids"] = offenders
        if not report["fleet_ok"] and offenders:
            # the postmortem artifact a duplicate-terminal failure
            # needs: the joined cross-process view of each offender,
            # captured before the fleet dies
            traces = {}
            for rid in offenders:
                try:
                    traces[rid] = router.fleet_trace(rid)[1]
                except Exception as e:  # noqa: BLE001 — best-effort dump
                    traces[rid] = {"error": str(e)}
            report["offender_traces"] = traces
        return report
    finally:
        if harness is not None:
            harness.stop()
        fleet.close()


def _dump_flight(path):
    """Write the flight recorder's full state (ring + black boxes) to
    ``path`` and say so — the chaos postmortem artifact
    (tools/trace_report.py renders it)."""
    from raft_tpu.core import flight

    flight.default_recorder().dump_to(path)
    print("flight recorder dumped to %s (render with "
          "tools/trace_report.py)" % path, file=sys.stderr)


def _print_waterfalls(slow_traces):
    """The slowest-K waterfalls next to the p99 row (--trace)."""
    from tools.trace_report import render_waterfall

    for entry in slow_traces:
        print("-- slow request: %.3fms (trace %s) --"
              % (entry["latency_ms"], entry["trace_id"]))
        if entry["timeline"]:
            print(render_waterfall(entry["timeline"]))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--service", choices=("knn", "pairwise", "ann"),
                    default="knn")
    ap.add_argument("--clusters", type=int, default=0,
                    help="gaussian-mixture data with this many clusters "
                         "(0 = i.i.d. gaussian)")
    ap.add_argument("--nlist", type=int, default=None,
                    help="ann: IVF list count (default ~sqrt(rows))")
    ap.add_argument("--nprobe", type=int, default=None,
                    help="ann: served probe count (default: knob/index)")
    ap.add_argument("--train-rows", type=int, default=None,
                    help="ann: subsampled k-means training rows")
    ap.add_argument("--ooc", action="store_true",
                    help="ann: serve the OUT-OF-CORE tier — host-"
                         "resident slot store streamed through a "
                         "device budget (docs/SERVING.md); reports "
                         "tile hit rate + hidden-transfer fraction "
                         "alongside recall")
    ap.add_argument("--device-budget-mb", type=int, default=None,
                    metavar="N",
                    help="ooc: device budget in MiB (default: a "
                         "quarter of the slot-store bytes)")
    ap.add_argument("--ooc-sync", action="store_true",
                    help="ooc: synchronous-prefetch baseline arm (no "
                         "double buffering) — the A/B the bench "
                         "measures the overlap win against")
    ap.add_argument("--recall", action="store_true",
                    help="score recall@k against brute-force ground "
                         "truth (automatic for --service ann)")
    ap.add_argument("--recall-target", type=float, default=None,
                    help="ann: calibrate nprobe to this recall@k "
                         "before the load run")
    ap.add_argument("--chaos", action="store_true",
                    help="run the seed-rotated chaos scenario (serve-"
                         "seam faults + simulated device loss + "
                         "recovery) instead of a load run; exits 1 "
                         "unless every submit resolved exactly once "
                         "with a result or typed error")
    ap.add_argument("--crash-restart", action="store_true",
                    help="run the crash-restart chaos scenario "
                         "(docs/PERSISTENCE.md): persistent ANN "
                         "service under query+insert traffic, "
                         "simulated process death mid-run (no final "
                         "snapshot), rebuild from --persist-dir; "
                         "exits 1 unless zero acknowledged-insert "
                         "loss, bit-identical post-restore search, "
                         "typed-only errors, and 0 post-warmup "
                         "compiles after restore all hold")
    ap.add_argument("--persist-dir", default=None, metavar="DIR",
                    help="durability directory for --crash-restart "
                         "(default: a fresh temp dir, removed after)")
    ap.add_argument("--fleet", action="store_true",
                    help="run the multi-process FLEET chaos scenario "
                         "(docs/FAULT_MODEL.md \"Fleet fault "
                         "domains\"): a router + N worker processes "
                         "under search+insert traffic with seeded "
                         "process faults (SIGKILL, hang, slow rejoin, "
                         "frame faults, fsync stall); exits 1 unless "
                         "zero acked-row loss, exactly-once typed "
                         "terminals, and full post-chaos heal hold")
    ap.add_argument("--fleet-workers", type=int, default=2,
                    metavar="N",
                    help="--fleet: worker process count (default 2)")
    ap.add_argument("--fleet-mode", default="sharded",
                    choices=("sharded", "replicated"),
                    help="--fleet: placement mode (replicated is "
                         "query-only)")
    ap.add_argument("--no-chaos", action="store_true",
                    help="--fleet: steady traffic only, no fault "
                         "schedule (scaling/smoke runs)")
    ap.add_argument("--transient-p", type=float, default=0.05,
                    help="chaos: per-batch transient fault probability")
    ap.add_argument("--outage-s", type=float, default=0.8,
                    help="chaos: simulated device-loss duration")
    ap.add_argument("--mesh", type=int, default=None, metavar="N",
                    help="serve SHARDED over the first N local devices "
                         "(docs/SERVING.md sharded serving; knn/ann)")
    ap.add_argument("--merge", default=None,
                    choices=("allgather", "ring", "hierarchical"),
                    help="sharded cross-shard top-k merge topology "
                         "(default: the mnmg_merge knob)")
    ap.add_argument("--kill-shard", action="store_true",
                    help="chaos: the outage permanently kills one "
                         "shard device; recovery re-partitions over "
                         "the survivors (requires --mesh >= 2)")
    ap.add_argument("--replicas", type=int, default=None, metavar="R",
                    help="serve REPLICATED over R disjoint sub-meshes "
                         "with hedged dispatch (knn only; "
                         "docs/SERVING.md traffic shaping)")
    ap.add_argument("--hedge-ms", type=float, default=None,
                    help="fixed hedge threshold ms (default: the "
                         "serve_hedge_ms knob / adaptive p99)")
    ap.add_argument("--hedge-chaos", action="store_true",
                    help="run the hedged-dispatch chaos scenario (one "
                         "replica straggles under a persistent Delay; "
                         "requires --replicas >= 2); exits 1 unless "
                         "exactly-once + hedge wins + 0 compiles hold")
    ap.add_argument("--tenants", action="store_true",
                    help="run the mixed-tenant scenario instead: "
                         "closed-loop interactive clients + open-loop "
                         "bulk flood through weighted-fair admission, "
                         "reporting per-tenant p50/p95/p99 and sheds")
    ap.add_argument("--tenant-weights", default="interactive:4,bulk:1",
                    help="tenant:weight spec for --tenants")
    ap.add_argument("--bulk-qps", type=float, default=300.0,
                    help="--tenants: open-loop bulk arrival rate")
    ap.add_argument("--bulk-rows", type=int, default=32,
                    help="--tenants: query rows per bulk request")
    ap.add_argument("--ops-port", type=int, default=None, metavar="P",
                    help="run the ops-scrape scenario: baseline window,"
                         " then the same load with an embedded ops "
                         "plane on port P (0 = ephemeral) scraped at "
                         "1 Hz — asserts every scrape succeeded, 0 "
                         "post-warmup compiles, and QPS within noise "
                         "of the baseline (exit 1 otherwise; "
                         "docs/OBSERVABILITY.md \"Ops plane\")")
    ap.add_argument("--mode", choices=("closed", "open"), default="closed")
    ap.add_argument("--qps", type=float, default=100.0,
                    help="open-loop arrival rate")
    ap.add_argument("--concurrency", type=int, default=8,
                    help="closed-loop client threads")
    ap.add_argument("--duration", type=float, default=5.0)
    ap.add_argument("--rows", type=int, default=4,
                    help="query rows per request")
    ap.add_argument("--index-rows", type=int, default=20000)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--k", type=int, default=10)
    ap.add_argument("--max-batch-rows", type=int, default=1024)
    ap.add_argument("--max-wait-ms", type=float, default=None)
    ap.add_argument("--queue-cap", type=int, default=None)
    ap.add_argument("--deadline", type=float, default=None,
                    help="per-request deadline seconds")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--trace", type=int, nargs="?", const=3, default=0,
                    metavar="K",
                    help="capture flight timelines for the K slowest "
                         "requests (default 3) and print their "
                         "waterfalls next to the latency rows; with "
                         "--fleet, prints the slowest-K CROSS-PROCESS "
                         "waterfalls (clock-aligned router+worker "
                         "join; docs/OBSERVABILITY.md \"Fleet "
                         "tracing\")")
    ap.add_argument("--trace-dump", metavar="PATH", default=None,
                    help="write the whole flight recorder (ring + "
                         "black boxes) to PATH after the run "
                         "(tools/trace_report.py renders it)")
    ap.add_argument("--json", action="store_true",
                    help="print the raw report dict as JSON")
    args = ap.parse_args(argv)

    if args.fleet:
        import shutil
        import tempfile

        root = args.persist_dir
        cleanup = root is None
        if root is None:
            root = tempfile.mkdtemp(prefix="raft_tpu_fleet_")
        try:
            report = run_fleet(
                root, n_workers=args.fleet_workers,
                mode=args.fleet_mode, index_rows=args.index_rows,
                dim=args.dim, k=args.k, seed=args.seed,
                duration=args.duration,
                concurrency=args.concurrency, rows=args.rows,
                nlist=args.nlist or 16, clusters=args.clusters or 8,
                chaos=not args.no_chaos, trace_k=args.trace)
        finally:
            if cleanup:
                shutil.rmtree(root, ignore_errors=True)
        if args.json:
            print(json.dumps(report, indent=2, sort_keys=True))
        else:
            print("== loadgen: fleet %s x%d (seed=%d) =="
                  % (report["mode"], report["workers"], args.seed))
            for key in ("duration_s", "calls", "search_ok",
                        "degraded", "typed_errors", "untyped_errors",
                        "sheds", "insert_batches", "acked_inserts",
                        "lost_inserts", "no_insert_loss", "admitted",
                        "terminals", "dup_terminals", "exactly_once",
                        "typed_only", "retries", "frame_errors",
                        "frame_injected", "evictions", "rejoins",
                        "chaos_applied", "chaos_failed", "healed",
                        "fleet_ok"):
                if key in report:
                    print("  %-24s %s" % (key, report[key]))
            if report.get("slow_fleet_traces"):
                from tools.trace_report import render_fleet_waterfall
                for entry in report["slow_fleet_traces"]:
                    print("-- slow fleet request: %.3fms (rid %s) --"
                          % (entry["latency_ms"], entry["rid"]))
                    print(render_fleet_waterfall(entry["joined"]))
        if not report["fleet_ok"]:
            _dump_flight("flight_fleet_seed%d.json" % args.seed)
            # joined cross-process traces for the offending request
            # ids, one file each (tools/trace_report.py renders them)
            for rid, joined in sorted(
                    (report.get("offender_traces") or {}).items()):
                path = "fleet_trace_seed%d_%s.json" % (args.seed, rid)
                with open(path, "w", encoding="utf-8") as f:
                    json.dump(joined, f, indent=2, sort_keys=True)
                print("joined fleet trace for offending rid %s -> %s "
                      "(render with tools/trace_report.py)"
                      % (rid, path), file=sys.stderr)
        return 0 if report["fleet_ok"] else 1
    if args.crash_restart:
        if args.service != "ann":
            raise SystemExit("--crash-restart drives the persistent "
                             "ANN service (--service ann)")
        import shutil
        import tempfile

        pdir = args.persist_dir
        cleanup = pdir is None
        if pdir is None:
            pdir = tempfile.mkdtemp(prefix="raft_tpu_persist_")
        svc_opts = {"max_batch_rows": args.max_batch_rows}
        if args.max_wait_ms is not None:
            svc_opts["max_wait_ms"] = args.max_wait_ms
        if args.queue_cap is not None:
            svc_opts["queue_cap"] = args.queue_cap
        try:
            report = run_crash_restart(
                pdir, index_rows=args.index_rows, dim=args.dim,
                k=args.k, seed=args.seed, duration=args.duration,
                concurrency=args.concurrency, rows=args.rows,
                nlist=args.nlist or 32,
                clusters=args.clusters or 16, svc_opts=svc_opts)
        finally:
            if cleanup:
                shutil.rmtree(pdir, ignore_errors=True)
        if args.json:
            print(json.dumps(report, indent=2, sort_keys=True))
        else:
            print("== loadgen: ann crash-restart (seed=%d) =="
                  % args.seed)
            for key in ("duration_s", "submitted", "ok",
                        "typed_errors", "untyped_errors", "lost",
                        "sheds", "acked_inserts", "lost_inserts",
                        "no_insert_loss", "bit_identical",
                        "exactly_once", "typed_only", "restore_s",
                        "restored_snapshot_seq",
                        "wal_replayed_records",
                        "wal_replay_records_per_s",
                        "post_restore_compiles", "crash_ok"):
                if key in report:
                    print("  %-24s %s" % (key, report[key]))
        if not report["crash_ok"]:
            _dump_flight("flight_crash_restart_seed%d.json"
                         % args.seed)
        return 0 if report["crash_ok"] else 1
    opts = {"max_batch_rows": args.max_batch_rows}
    if args.max_wait_ms is not None:
        opts["max_wait_ms"] = args.max_wait_ms
    if args.queue_cap is not None:
        opts["queue_cap"] = args.queue_cap
    if args.service == "ann":
        opts.update(nlist=args.nlist, nprobe=args.nprobe,
                    train_rows=args.train_rows)
        if args.ooc:
            opts.update(ooc=True, device_budget_mb=args.device_budget_mb)
            if args.ooc_sync:
                opts["ooc_overlap"] = False
    if (args.ooc or args.device_budget_mb is not None
            or args.ooc_sync) and args.service != "ann":
        raise SystemExit("--ooc/--device-budget-mb/--ooc-sync apply to "
                         "the out-of-core ANN tier (--service ann)")
    if (args.device_budget_mb is not None or args.ooc_sync) \
            and not args.ooc:
        # a resident run silently ignoring a memory budget would claim
        # out-of-core numbers it never measured — same guard the
        # ANNService constructor applies
        raise SystemExit("--device-budget-mb/--ooc-sync require --ooc")
    if args.ooc and args.mesh is not None:
        raise SystemExit("--ooc does not compose with --mesh (the "
                         "tier trades device memory for host "
                         "streaming; shard the resident path instead)")
    if args.merge is not None:
        if args.mesh is None and args.replicas is None:
            raise SystemExit("--merge is the sharded cross-shard merge "
                             "topology — it requires --mesh N or "
                             "--replicas R")
        opts["merge"] = args.merge
    if args.kill_shard and (args.mesh is None or args.mesh < 2):
        raise SystemExit("--kill-shard requires --mesh >= 2")
    if args.trace and (args.chaos or args.hedge_chaos or args.tenants
                       or args.ops_port is not None):
        # slow-request capture rides the plain load loop only; a
        # silently ignored flag would read as "tracing is broken" to
        # exactly the user debugging a chaos run
        raise SystemExit("--trace applies to plain load runs; chaos/"
                         "tenant scenarios capture the whole recorder "
                         "instead — use --trace-dump PATH (failed "
                         "chaos assertions dump it automatically)")
    if args.hedge_chaos and (args.replicas is None or args.replicas < 2):
        raise SystemExit("--hedge-chaos requires --replicas >= 2")
    if args.ops_port is not None and (args.chaos or args.hedge_chaos
                                      or args.tenants):
        raise SystemExit("--ops-port runs the steady ops-scrape "
                         "scenario; it does not compose with the "
                         "chaos/tenant scenarios")
    if args.hedge_ms is not None:
        if args.replicas is None:
            raise SystemExit("--hedge-ms requires --replicas")
        opts["hedge_ms"] = args.hedge_ms
    if args.tenants:
        opts["tenant_weights"] = args.tenant_weights
    service = build_service(args.service, args.index_rows, args.dim,
                            args.k, seed=args.seed,
                            clusters=args.clusters,
                            mesh_devices=args.mesh,
                            replicas=args.replicas, **opts)
    t0 = time.monotonic()
    service.warmup()
    warmup_s = time.monotonic() - t0
    if args.hedge_chaos:
        try:
            report = run_hedge_chaos(service, duration=args.duration,
                                     concurrency=args.concurrency,
                                     rows=args.rows, seed=args.seed)
        finally:
            service.close()
        report["warmup_s"] = round(warmup_s, 3)
        if args.json:
            print(json.dumps(report, indent=2, sort_keys=True))
        else:
            print("== loadgen: %s hedge-chaos (seed=%d) =="
                  % (args.service, args.seed))
            for key in ("duration_s", "delay_s", "submitted", "ok",
                        "typed_errors", "untyped_errors", "lost",
                        "sheds", "hedges_fired", "hedge_wins",
                        "hedge_cancelled", "exactly_once", "typed_only",
                        "post_warmup_compiles", "host_staged_bytes",
                        "chaos_ok"):
                if key in report:
                    print("  %-20s %s" % (key, report[key]))
        if args.trace_dump:
            _dump_flight(args.trace_dump)
        elif not report["chaos_ok"]:
            # a failed chaos assertion always leaves the tape behind
            _dump_flight("flight_hedge_chaos_seed%d.json" % args.seed)
        return 0 if report["chaos_ok"] else 1
    if args.tenants:
        try:
            report = run_mixed_tenants(
                service, duration=args.duration,
                interactive_concurrency=args.concurrency,
                bulk_qps=args.bulk_qps, interactive_rows=args.rows,
                bulk_rows=args.bulk_rows, seed=args.seed,
                deadline=args.deadline)
        finally:
            service.close()
        report["warmup_s"] = round(warmup_s, 3)
        if args.json:
            print(json.dumps(report, indent=2, sort_keys=True))
        else:
            print("== loadgen: %s mixed-tenants ==" % args.service)
            for key in ("duration_s", "untyped_sheds",
                        "post_warmup_compiles", "host_staged_bytes",
                        "warmup_s"):
                if key in report:
                    print("  %-20s %s" % (key, report[key]))
            for tenant, st in sorted(report["tenants"].items()):
                print("  [%s]" % tenant)
                for key in ("requests_ok", "rejected", "unavailable",
                            "errors", "qps", "p50_ms", "p95_ms",
                            "p99_ms"):
                    print("    %-18s %s" % (key, st[key]))
        return 0 if report["untyped_sheds"] == 0 else 1
    if args.chaos:
        from raft_tpu.serve.resilience import RecoveryManager

        manager = RecoveryManager(services=[service])
        try:
            report = run_chaos(service, duration=args.duration,
                               concurrency=args.concurrency,
                               rows=args.rows, seed=args.seed,
                               transient_p=args.transient_p,
                               outage_s=args.outage_s, manager=manager,
                               kill_shard=args.kill_shard)
        finally:
            service.close()
        report["warmup_s"] = round(warmup_s, 3)
        if args.json:
            print(json.dumps(report, indent=2, sort_keys=True))
        else:
            print("== loadgen: %s chaos (seed=%d) =="
                  % (args.service, args.seed))
            for key in ("duration_s", "outage_s", "transient_p",
                        "submitted", "ok", "typed_errors",
                        "untyped_errors", "lost", "rejected",
                        "unavailable", "requeued", "breaker_trips",
                        "recoveries", "breaker_state", "exactly_once",
                        "typed_only", "kill_shard", "shard_devices",
                        "post_recovery_exact", "chaos_ok"):
                if key in report:
                    print("  %-20s %s" % (key, report[key]))
        if args.trace_dump:
            _dump_flight(args.trace_dump)
        elif not report["chaos_ok"]:
            # a failed chaos assertion always leaves the tape behind
            _dump_flight("flight_chaos_seed%d.json" % args.seed)
        return 0 if report["chaos_ok"] else 1
    if args.ops_port is not None:
        try:
            report = run_ops_scrape(service, port=args.ops_port,
                                    duration=args.duration,
                                    concurrency=args.concurrency,
                                    rows=args.rows, seed=args.seed)
        finally:
            service.close()
        report["warmup_s"] = round(warmup_s, 3)
        if args.json:
            print(json.dumps(report, indent=2, sort_keys=True))
        else:
            print("== loadgen: %s ops-scrape ==" % args.service)
            for key in ("baseline_qps", "scraped_qps", "qps_ratio",
                        "scrapes", "scrape_failures", "scrape_p95_ms",
                        "post_warmup_compiles", "host_staged_bytes",
                        "p99_ms", "ops_port", "warmup_s", "ops_ok"):
                if key in report:
                    print("  %-20s %s" % (key, report[key]))
        return 0 if report["ops_ok"] else 1
    want_recall = args.recall or args.service == "ann"
    pool = None
    if want_recall:
        # queries drawn near the data: recall on clustered data is
        # meaningless for queries sampled from empty space
        pool = make_query_pool(service.loadgen_ref, args.rows,
                               seed=args.seed + 1)
    calibration = None
    if args.recall_target is not None and args.service == "ann":
        import jax.numpy as jnp

        cal_q = jnp.concatenate(pool[:8], axis=0)
        calibration = service.calibrate(cal_q, args.recall_target)
    try:
        report = run_load(service, mode=args.mode,
                          duration=args.duration,
                          concurrency=args.concurrency, qps=args.qps,
                          rows=args.rows, seed=args.seed,
                          deadline=args.deadline, recall=want_recall,
                          query_pool=pool, trace_k=args.trace)
    finally:
        service.close()
    if args.trace_dump:
        _dump_flight(args.trace_dump)
    report["warmup_s"] = round(warmup_s, 3)
    report["buckets"] = list(service.policy.rungs)
    if getattr(service, "axis", None) is not None:
        report["n_devices"] = int(service.mesh.shape[service.axis])
        report["merge"] = service.merge
    if getattr(service, "_replica_set", None) is not None:
        from raft_tpu.core.metrics import default_registry

        report["replicas"] = len(service._replica_set.replicas)
        report["hedges_fired"] = int(default_registry().family_total(
            "raft_tpu_serve_hedges_total"))
    if args.service == "ann":
        report["nprobe"] = service.nprobe
        report["delta_rows"] = service.delta_rows
    if calibration is not None:
        report["calibration"] = calibration

    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
        return 0
    print("== loadgen: %s %s ==" % (args.service, args.mode))
    for key in ("duration_s", "requests_ok", "rejected", "errors", "qps",
                "query_qps", "n_devices", "merge",
                "recall_at_k", "recall_k", "nprobe", "delta_rows",
                "tile_hit_rate", "h2d_mb", "hidden_transfer_frac",
                "p50_ms", "p95_ms", "p99_ms", "queue_wait_p50_ms",
                "queue_wait_p95_ms", "batches", "mean_batch_rows",
                "padding_waste", "post_warmup_compiles",
                "host_staged_bytes", "warmup_s", "buckets"):
        if key in report:
            val = report[key]
            if isinstance(val, float):
                val = "%.3f" % val
            print("  %-20s %s" % (key, val))
    if report.get("slow_traces"):
        _print_waterfalls(report["slow_traces"])
    return 0


if __name__ == "__main__":
    sys.exit(main())
