"""On-chip sweep of the fused kNN kernel's tuning space.

Chained-timing (bench._time_chained: dispatch-latency-cancelling
fori_loop chains) of the Pallas kernel at the 100k timing shape across
merge network x block geometry, against the XLA tile-scan path as the
yardstick.  One flushed JSON line per config; run whenever the backend
answers:

    python tools/knn_kernel_sweep.py > .knn_sweep.log 2>&1
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(REPO, ".jax_cache"))

T0 = time.time()


def emit(rec):
    rec["t"] = round(time.time() - T0, 1)
    print(json.dumps(rec), flush=True)


def main():
    import jax
    import jax.numpy as jnp

    from bench import _time_chained

    dev = jax.devices()[0]
    emit({"config": "init", "device": str(dev.device_kind),
          "platform": dev.platform})

    from raft_tpu.ops.knn_tile import fused_knn_tile
    from raft_tpu.spatial.fused_l2_knn import fused_l2_knn

    n, nq, d, k = 100_000, 1024, 128, 100
    x = jax.random.normal(jax.random.PRNGKey(0), (n, d), jnp.float32)
    q = jax.random.normal(jax.random.PRNGKey(1), (nq, d), jnp.float32)
    jax.block_until_ready((x, q))

    def xla_step(qq):
        return fused_l2_knn(x, qq, k, impl="xla")[0]

    dt = _time_chained(xla_step, q, 2)
    emit({"config": "xla_scan", "seconds_per_batch": round(dt, 4),
          "qps": round(nq / dt, 1)})

    for merge in ("merge", "fullsort"):
        for bq in (64, 128, 256):
            for bn in (1024, 2048):
                def step(qq, merge=merge, bq=bq, bn=bn):
                    return fused_knn_tile(x, qq, k, block_q=bq,
                                          block_n=bn,
                                          merge_impl=merge)[0]
                try:
                    t0 = time.time()
                    dt = _time_chained(step, q, 2)
                    emit({"config": f"pallas_{merge}_bq{bq}_bn{bn}",
                          "seconds_per_batch": round(dt, 4),
                          "qps": round(nq / dt, 1),
                          "t_incl_compile": round(time.time() - t0, 1)})
                except Exception as e:
                    emit({"config": f"pallas_{merge}_bq{bq}_bn{bn}",
                          "error": str(e)[-200:]})
                    # a dead backend fails everything after too
                    if "UNAVAILABLE" in str(e):
                        return


if __name__ == "__main__":
    main()
