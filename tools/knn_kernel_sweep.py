"""On-chip sweep of the fused kNN kernel's tuning space.

Chained-timing (bench._time_chained: dispatch-latency-cancelling
fori_loop chains) of the Pallas kernel at the 100k timing shape across
merge network x block geometry, against the XLA tile-scan path as the
yardstick.  One flushed JSON line per config; run whenever the backend
answers:

    python tools/knn_kernel_sweep.py > .knn_sweep.log 2>&1
"""

import contextlib
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(REPO, ".jax_cache"))
# bench._time_chained budgets itself against the bench deadline env —
# standalone runs get a generous one
os.environ.setdefault("RAFT_TPU_BENCH_DEADLINE", str(time.time() + 3600))

T0 = time.time()


def emit(rec):
    rec["t"] = round(time.time() - T0, 1)
    print(json.dumps(rec), flush=True)


def main():
    import jax
    import jax.numpy as jnp

    from bench import _enable_compile_cache

    _enable_compile_cache()

    from bench import _env_pins, _time_chained

    dev = jax.devices()[0]
    emit({"config": "init", "device": str(dev.device_kind),
          "platform": dev.platform})

    from raft_tpu.ops.knn_tile import fused_knn_tile
    from raft_tpu.spatial.fused_l2_knn import fused_l2_knn

    # RAFT_TPU_SWEEP_SMOKE=1: tiny shapes for a hardware-free wiring
    # check of every variant path (the numbers are meaningless)
    if os.environ.get("RAFT_TPU_SWEEP_SMOKE") == "1":
        n, nq, d, k = 5_000, 128, 64, 50
    else:
        n, nq, d, k = 100_000, 1024, 128, 100
    x = jax.random.normal(jax.random.PRNGKey(0), (n, d), jnp.float32)
    q = jax.random.normal(jax.random.PRNGKey(1), (nq, d), jnp.float32)
    jax.block_until_ready((x, q))

    def xla_step(qq):
        # indices folded in: a d-only step lets XLA dead-code the index
        # half of the selection inside the chained loop (bench.py
        # _time_chained caller contract)
        d2, i2 = fused_l2_knn(x, qq, k, impl="xla")
        return d2 + i2.astype(d2.dtype)

    dt = _time_chained(xla_step, q, 2)
    emit({"config": "xla_scan", "seconds_per_batch": round(dt, 4),
          "qps": round(nq / dt, 1)})

    # bf16 stage-1 + exact f32 re-rank (r5): the candidate-set answer
    # to selection cost — ride the same honest step shape
    from raft_tpu.spatial import brute_force_knn

    for ratio in (2, 4):
        def rstep(qq, ratio=ratio):
            d2, i2 = brute_force_knn([x], qq, k, rerank_ratio=ratio)
            return d2 + i2.astype(d2.dtype)
        try:
            dt = _time_chained(rstep, q, 2)
            emit({"config": f"xla_rerank{ratio}",
                  "seconds_per_batch": round(dt, 4),
                  "qps": round(nq / dt, 1)})
        except Exception as e:
            emit({"config": f"xla_rerank{ratio}", "error": str(e)[-200:]})
            if "UNAVAILABLE" in str(e):
                return

    # XLA-path merge/select variants (same honest step shape);
    # tile_n scan rides on the winner question too
    for name, kw in (("xla_direct", {"merge": "direct"}),
                     ("xla_chunked", {"select": "chunked"}),
                     ("xla_pselect", {"select": "pallas"}),
                     ("xla_tile4k", {"tile_n": 4096}),
                     ("xla_tile16k", {"tile_n": 16384}),
                     ("xla_direct_tile4k",
                      {"merge": "direct", "tile_n": 4096}),
                     ("xla_chunked_tile16k",
                      {"select": "chunked", "tile_n": 16384})):
        def vstep(qq, kw=kw):
            # tile_n passed ONLY when the variant pins it, so the other
            # variants track fused_l2_knn's default and the comparison
            # never hides a tile_n difference
            tn = {"tile_n": kw["tile_n"]} if "tile_n" in kw else {}
            with _env_pins({"RAFT_TPU_TILE_MERGE": kw.get("merge"),
                            "RAFT_TPU_SELECT_IMPL": kw.get("select")}):
                d, i = fused_l2_knn(x, qq, k, impl="xla", **tn)
            return d + i.astype(d.dtype)
        try:
            dt = _time_chained(vstep, q, 2)
            emit({"config": name, "seconds_per_batch": round(dt, 4),
                  "qps": round(nq / dt, 1)})
        except Exception as e:
            emit({"config": name, "error": str(e)[-200:]})
            if "UNAVAILABLE" in str(e):
                return

    # two-phase no-carry kernel (r5): per-tile select in-kernel, one
    # narrow XLA merge outside — zero cross-tile state, both grid dims
    # parallel.  t(twophase) vs t(sorttile) attributes the carry/gate/
    # pipeline share of the r4 80x anomaly directly.
    from raft_tpu.ops.knn_tile import fused_knn_twophase

    from raft_tpu import config as rt_config

    for bq in (64, 256):
        for bn in (1024, 2048):
            for sel in (None, "chunked"):
                def tstep(qq, bq=bq, bn=bn, sel=sel):
                    # sel pins phase 2's merge select (width
                    # n_tiles*kpad): chunked may beat one wide top_k
                    ctx = (rt_config.override(select_impl=sel) if sel
                           else contextlib.nullcontext())
                    with ctx:
                        d, i = fused_knn_twophase(x, qq, k, block_q=bq,
                                                  block_n=bn)
                    return d + i.astype(d.dtype)
                name = (f"pallas_twophase_bq{bq}_bn{bn}"
                        + (f"_{sel}" if sel else ""))
                try:
                    t0 = time.time()
                    dt = _time_chained(tstep, q, 2)
                    emit({"config": name,
                          "seconds_per_batch": round(dt, 4),
                          "qps": round(nq / dt, 1),
                          "t_incl_compile": round(time.time() - t0, 1)})
                except Exception as e:
                    emit({"config": name, "error": str(e)[-200:]})
                    if "UNAVAILABLE" in str(e):
                        return

    # "skip" is the attribution probe (WRONG results by design): its
    # time is the kernel's MXU+DMA+grid+gate floor, so
    # t(variant) - t(skip) isolates each selection network's true cost
    for merge in ("skip", "merge", "fullsort", "sorttile"):
        for bq in (64, 128, 256):
            for bn in (1024, 2048):
                def step(qq, merge=merge, bq=bq, bn=bn):
                    d, i = fused_knn_tile(x, qq, k, block_q=bq,
                                          block_n=bn,
                                          merge_impl=merge)
                    return d + i.astype(d.dtype)
                try:
                    t0 = time.time()
                    dt = _time_chained(step, q, 2)
                    emit({"config": f"pallas_{merge}_bq{bq}_bn{bn}",
                          "seconds_per_batch": round(dt, 4),
                          "qps": round(nq / dt, 1),
                          "t_incl_compile": round(time.time() - t0, 1)})
                except Exception as e:
                    emit({"config": f"pallas_{merge}_bq{bq}_bn{bn}",
                          "error": str(e)[-200:]})
                    # a dead backend fails everything after too
                    if "UNAVAILABLE" in str(e):
                        return


if __name__ == "__main__":
    main()
