"""Steady-state XLA vs Pallas fused-kNN timing at the 100k shape.

Writes progress lines to stdout (run with output redirected to a file;
every line is flushed).  Shapes chosen to hit the compile cache warmed
by tools/onchip_check.py.
"""

import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(REPO, ".jax_cache"))

T0 = time.time()


def log(msg):
    print(f"[{time.time()-T0:7.1f}s] {msg}", flush=True)


def main():
    log("importing jax")
    import jax
    import jax.numpy as jnp

    from bench import _enable_compile_cache

    _enable_compile_cache()

    dev = jax.devices()[0]
    log(f"backend: {dev.platform} ({dev.device_kind})")

    from raft_tpu.spatial.fused_l2_knn import fused_l2_knn

    n, nq, d, k = 100_000, 1024, 128, 100
    x = jax.random.normal(jax.random.PRNGKey(0), (n, d), jnp.float32)
    q = jax.random.normal(jax.random.PRNGKey(1), (nq, d), jnp.float32)
    jax.block_until_ready((x, q))
    log("data ready")

    for impl in ("xla", "pallas"):
        t0 = time.perf_counter()
        jax.block_until_ready(fused_l2_knn(x, q, k, impl=impl))
        log(f"{impl} compile+first: {time.perf_counter()-t0:.2f}s")
        ts = []
        for i in range(5):
            t0 = time.perf_counter()
            jax.block_until_ready(fused_l2_knn(x, q, k, impl=impl))
            ts.append(time.perf_counter() - t0)
            log(f"{impl} iter {i}: {ts[-1]*1e3:.1f} ms")
        dt = min(ts)
        log(f"{impl} steady: {dt*1e3:.2f} ms  {nq/dt:,.0f} QPS")


if __name__ == "__main__":
    main()
