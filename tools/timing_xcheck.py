"""Cross-check the bench's chained timing against plain wall-clock.

Round-4 question (ANSWERED — kept as the reproducer): the full-budget
bench measured the 100k XLA kNN rung at ~98 us/query (nq=4096,
_time_chained), while tools/steady_knn.py measured ~1700 us/query
(nq=1024, plain wall-clock).  Verdict: the timing METHOD — the chained
step returned distances only, so XLA dead-coded the index half of the
selection (see bench._time_chained's caller contract and the
BENCH_TPU_SESSION_r04.md correction).  A part-2 tool that jitted
lambdas closing over the 100k index was retired: the 51 MB
HLO-constant compile wedged the tunnel for hours — pass big arrays as
ARGUMENTS, never closures, when talking to the tunnel.

    python tools/timing_xcheck.py > .timing_xcheck.log 2>&1
"""

import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(REPO, ".jax_cache"))
# bench._time_chained budgets itself against the bench deadline env;
# give this standalone run a generous one
os.environ.setdefault("RAFT_TPU_BENCH_DEADLINE", str(time.time() + 1800))

T0 = time.time()


def log(msg):
    print(f"[{time.time()-T0:7.1f}s] {msg}", flush=True)


def wall(fn, *args):
    """Plain steady-state: warm once, then min over 4 timed calls."""
    import jax

    jax.block_until_ready(fn(*args))
    ts = []
    for _ in range(4):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        ts.append(time.perf_counter() - t0)
    return min(ts)


def main():
    import jax
    import jax.numpy as jnp

    from bench import _enable_compile_cache

    _enable_compile_cache()

    from bench import _time_chained
    from raft_tpu.spatial import brute_force_knn
    from raft_tpu.spatial.fused_l2_knn import fused_l2_knn

    dev = jax.devices()[0]
    log(f"backend: {dev.platform} ({dev.device_kind})")

    n, d, k = 100_000, 128, 100
    x = jax.random.normal(jax.random.PRNGKey(0), (n, d), jnp.float32)
    jax.block_until_ready(x)

    os.environ["RAFT_TPU_FUSED_KNN_IMPL"] = "xla"
    for nq in (1024, 4096):
        q = jax.random.normal(jax.random.PRNGKey(1), (nq, d), jnp.float32)
        jax.block_until_ready(q)

        def f_direct(qq):
            return fused_l2_knn(x, qq, k, impl="xla")[0]

        def f_bf(qq):
            return brute_force_knn([x], qq, k)[0]

        for name, fn in (("fused_l2_knn", f_direct),
                         ("brute_force_knn", f_bf)):
            dt_w = wall(fn, q)
            log(f"nq={nq} {name:16s} wall    {dt_w*1e3:9.1f} ms "
                f"{nq/dt_w:10,.0f} QPS")
            dt_c = _time_chained(fn, q, 2)
            log(f"nq={nq} {name:16s} chained {dt_c*1e3:9.1f} ms "
                f"{nq/dt_c:10,.0f} QPS")


if __name__ == "__main__":
    main()
