"""On-chip cross-validation of the compiled Pallas kernels (round-4 item 2).

Runs on a real TPU backend.  For each config, the compiled
(interpret=False) kernel is checked against an independent reference:
the XLA tile-scan path for fused kNN, a dense numpy evaluation for
pairwise metrics.  Emits one JSON line per check to stdout and a summary
at the end; any failure exits 1.

Tie rule for kNN index comparison: an index mismatch at position p is
accepted iff both kernels report (near-)equal distances there — k-th
boundary ties may legitimately resolve to different ids
(ops/knn_tile.py bitonic payload tie rule).
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(REPO, ".jax_cache"))

import numpy as np  # noqa: E402

T0 = time.time()
RESULTS = []


def emit(rec):
    rec["t"] = round(time.time() - T0, 1)
    RESULTS.append(rec)
    print(json.dumps(rec), flush=True)


def rand(shape, seed, scale=1.0, positive=False):
    import jax
    import jax.numpy as jnp

    x = jax.random.normal(jax.random.PRNGKey(seed), shape, jnp.float32) * scale
    if positive:
        x = jnp.abs(x) + 0.01
    return x


def check_knn(n, nq, d, k, seed=0):
    from raft_tpu.spatial.fused_l2_knn import fused_l2_knn

    x = rand((n, d), seed)
    q = rand((nq, d), seed + 1)
    t0 = time.time()
    # k > 128: an explicit pallas request errors (bitonic width cap), so
    # exercise the default dispatch, which on TPU resolves pallas→xla
    d_p, i_p = fused_l2_knn(x, q, k, impl="pallas" if k <= 128 else None)
    d_p, i_p = np.asarray(d_p), np.asarray(i_p)
    t_pallas = time.time() - t0
    t0 = time.time()
    d_r, i_r = fused_l2_knn(x, q, k, impl="xla")
    d_r, i_r = np.asarray(d_r), np.asarray(i_r)
    t_xla = time.time() - t0
    # distances: rtol 1e-5 on top of an absolute floor for catastrophic
    # cancellation noise in the expanded form near zero
    dist_ok = bool(np.allclose(d_p, d_r, rtol=1e-5, atol=1e-3))
    mism = i_p != i_r
    # every index mismatch must be a genuine tie: RECOMPUTE the distance
    # at the claimed index (comparing claimed values alone would pass a
    # kernel with right values but garbage ids)
    xh, qh = np.asarray(x, np.float64), np.asarray(q, np.float64)
    rows, poss = np.nonzero(mism)
    d_at_claim = ((qh[rows] - xh[i_p[rows, poss]]) ** 2).sum(axis=1)
    tie_ok = bool(np.allclose(d_at_claim, d_r[rows, poss],
                              rtol=1e-4, atol=1e-3))
    rec = {
        "check": "fused_knn", "n": n, "nq": nq, "d": d, "k": k,
        "dist_ok": dist_ok, "idx_mismatch_frac": float(mism.mean()),
        "idx_ties_ok": tie_ok, "ok": dist_ok and tie_ok,
        "t_pallas_incl_compile": round(t_pallas, 2),
        "t_xla_incl_compile": round(t_xla, 2),
    }
    if not rec["ok"]:
        bad = np.argwhere(mism)[:5]
        rec["sample_mismatches"] = [
            {"pos": p.tolist(), "d_pallas": float(d_p[tuple(p)]),
             "d_xla": float(d_r[tuple(p)]),
             "i_pallas": int(i_p[tuple(p)]), "i_xla": int(i_r[tuple(p)])}
            for p in bad]
        rec["max_abs_diff"] = float(np.max(np.abs(d_p - d_r)))
    emit(rec)
    return rec["ok"]


def check_merge_impls(n, nq, d, k, seed=0):
    """A/B the running-top-k merge networks of the fused kNN kernel
    on chip: equality of results AND steady-state timing — the log2-tail
    "merge" network exists because the full log^2 sort of 2*kpad lanes
    was the r4 steady-state suspect (cross-vreg lane rolls);
    "sorttile" removes the while loop + big carry entirely
    (docs/TUNING.md "Open question")."""
    import jax

    from raft_tpu.ops.knn_tile import fused_knn_tile, fused_knn_twophase

    x = rand((n, d), seed)
    q = rand((nq, d), seed + 1)
    rec = {"check": "knn_merge_impls", "n": n, "nq": nq, "d": d, "k": k}
    impls = ["merge", "fullsort", "sorttile"]
    if k <= 128:
        # r5 no-carry kernel (per-tile select + XLA merge) joins the
        # A/B whenever its bitonic-width cap allows
        impls.append("twophase")
    outs = {}
    for impl in impls:
        if impl == "twophase":
            f = jax.jit(lambda xx, qq: fused_knn_twophase(xx, qq, k))
        else:
            f = jax.jit(lambda xx, qq, impl=impl: fused_knn_tile(
                xx, qq, k, merge_impl=impl))
        t0 = time.time()
        dd, ii = f(x, q)
        jax.block_until_ready((dd, ii))
        rec[f"t_{impl}_incl_compile"] = round(time.time() - t0, 2)
        ts = []
        for _ in range(3):
            t0 = time.time()
            dd, ii = f(x, q)
            jax.block_until_ready((dd, ii))
            ts.append(time.time() - t0)
        rec[f"t_{impl}_steady"] = round(min(ts), 4)
        outs[impl] = (np.asarray(dd), np.asarray(ii))
    rec["dist_ok"] = bool(all(
        np.allclose(outs[i][0], outs["fullsort"][0], rtol=1e-5, atol=1e-3)
        for i in impls))
    mism = np.zeros_like(outs["merge"][1], dtype=bool)
    for i in impls:
        mism |= outs[i][1] != outs["fullsort"][1]
    rec["idx_mismatch_frac"] = float(mism.mean())
    # every index mismatch must be a genuine tie: RECOMPUTE the distance
    # at the id EACH network claims (same guard as check_knn — a
    # payload-routing bug with correct distances must not pass, for ANY
    # of the networks)
    xh = np.asarray(x, np.float64)
    qh = np.asarray(q, np.float64)
    rows, poss = np.nonzero(mism)
    ties_ok = True
    for impl in impls:
        d_at_claim = ((qh[rows] - xh[outs[impl][1][rows, poss]]) ** 2
                      ).sum(axis=1)
        ties_ok = ties_ok and bool(np.allclose(
            d_at_claim, outs["fullsort"][0][rows, poss],
            rtol=1e-4, atol=1e-3))
    rec["idx_ties_ok"] = ties_ok
    rec["ok"] = rec["dist_ok"] and rec["idx_ties_ok"]
    rec["speedup_merge_vs_fullsort"] = round(
        rec["t_fullsort_steady"] / max(rec["t_merge_steady"], 1e-9), 2)
    rec["speedup_sorttile_vs_merge"] = round(
        rec["t_merge_steady"] / max(rec["t_sorttile_steady"], 1e-9), 2)
    if "twophase" in impls:
        rec["speedup_twophase_vs_merge"] = round(
            rec["t_merge_steady"] / max(rec["t_twophase_steady"], 1e-9), 2)
    emit(rec)
    return rec["ok"]


def check_select(m, w, k, seed=0):
    """Fused select kernel vs lax.top_k on chip: exact values, ids that
    hold the claimed value, and steady-state timing at the tile shape
    the kNN scan actually selects over."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from raft_tpu.ops.select_tile import select_tile

    keys = rand((m, w), seed)
    rec = {"check": "select_tile", "m": m, "w": w, "k": k}
    sel_f = jax.jit(lambda s: select_tile(s, k))
    t0 = time.time()
    d_p, i_p = sel_f(keys)
    jax.block_until_ready((d_p, i_p))
    rec["t_pallas_incl_compile"] = round(time.time() - t0, 2)
    ts = []
    for _ in range(3):
        t0 = time.time()
        d_p, i_p = sel_f(keys)
        jax.block_until_ready((d_p, i_p))
        ts.append(time.time() - t0)
    rec["t_pallas_steady"] = round(min(ts), 4)

    # ONE jitted callable reused across iterations: a fresh jit(lambda)
    # per call has an empty trace cache and times retrace/lowering, not
    # the kernel (r4 code-review finding)
    topk_f = jax.jit(lambda s: lax.top_k(-s, k))
    t0 = time.time()
    ref = topk_f(keys)
    jax.block_until_ready(ref)
    rec["t_topk_incl_compile"] = round(time.time() - t0, 2)
    ts = []
    for _ in range(3):
        t0 = time.time()
        ref = topk_f(keys)
        jax.block_until_ready(ref)
        ts.append(time.time() - t0)
    rec["t_topk_steady"] = round(min(ts), 4)
    rec["speedup_vs_topk"] = round(
        rec["t_topk_steady"] / max(rec["t_pallas_steady"], 1e-9), 2)

    d_p, i_p = np.asarray(d_p), np.asarray(i_p)
    d_t = -np.asarray(ref[0])
    kh = np.asarray(keys)
    rec["vals_ok"] = bool(np.allclose(d_p, d_t, rtol=1e-6, atol=1e-6))
    got = np.take_along_axis(kh, i_p, axis=1)
    rec["ids_hold_vals_ok"] = bool(np.allclose(got, d_p, rtol=1e-6,
                                               atol=1e-6))
    rec["ok"] = rec["vals_ok"] and rec["ids_hold_vals_ok"]
    emit(rec)
    return rec["ok"]


def check_nn(m, n, d, seed=0):
    """Compiled fused 1-NN kernel vs the XLA scan path."""
    from raft_tpu.distance.fused_l2_nn import fused_l2_nn

    x = rand((m, d), seed)
    y = rand((n, d), seed + 1)
    t0 = time.time()
    v_p, i_p = fused_l2_nn(x, y, impl="pallas")
    v_p, i_p = np.asarray(v_p), np.asarray(i_p)
    t_pallas = time.time() - t0
    t0 = time.time()
    v_r, i_r = fused_l2_nn(x, y, impl="xla")
    v_r, i_r = np.asarray(v_r), np.asarray(i_r)
    t_xla = time.time() - t0
    val_ok = bool(np.allclose(v_p, v_r, rtol=1e-5, atol=1e-3))
    mism = i_p != i_r
    # an index mismatch is only acceptable when the claimed neighbor is
    # genuinely at the minimal distance — RECOMPUTE ||x - y[i_p]||^2 at
    # mismatched rows (comparing the two claimed values would pass a
    # kernel whose values are right but whose ids are garbage)
    xh, yh = np.asarray(x, np.float64), np.asarray(y, np.float64)
    rows = np.nonzero(mism)[0]
    d_at_claim = ((xh[rows] - yh[i_p[rows]]) ** 2).sum(axis=1)
    tie_ok = bool(np.allclose(d_at_claim, v_r[rows], rtol=1e-4, atol=1e-3))
    rec = {"check": "fused_nn", "m": m, "n": n, "d": d,
           "val_ok": val_ok, "idx_mismatch_frac": float(mism.mean()),
           "idx_ties_ok": tie_ok, "ok": val_ok and tie_ok,
           "t_pallas_incl_compile": round(t_pallas, 2),
           "t_xla_incl_compile": round(t_xla, 2)}
    emit(rec)
    return rec["ok"]


def np_pairwise(x, y, metric, p=1.5):
    """Dense numpy reference, blocked over rows to bound memory."""
    out = np.empty((x.shape[0], y.shape[0]), np.float64)
    xe = x.astype(np.float64)
    ye = y.astype(np.float64)
    for i0 in range(0, x.shape[0], 64):
        xv = xe[i0:i0 + 64, None, :]
        yv = ye[None, :, :]
        if metric == "l1":
            out[i0:i0 + 64] = np.abs(xv - yv).sum(-1)
        elif metric == "linf":
            out[i0:i0 + 64] = np.abs(xv - yv).max(-1)
        elif metric == "l2sqrt_unexp":
            out[i0:i0 + 64] = np.sqrt(((xv - yv) ** 2).sum(-1))
        elif metric == "canberra":
            den = np.abs(xv) + np.abs(yv)
            out[i0:i0 + 64] = np.where(
                den == 0, 0.0, np.abs(xv - yv) / np.where(den == 0, 1, den)
            ).sum(-1)
        elif metric == "lp":
            out[i0:i0 + 64] = (np.abs(xv - yv) ** p).sum(-1) ** (1.0 / p)
        elif metric == "hamming":
            out[i0:i0 + 64] = (xv != yv).mean(-1)
        elif metric == "js":
            m = 0.5 * (xv + yv)
            logm = np.log(np.where(m > 0, m, 1.0))

            def term(v):
                return np.where(
                    v > 0, v * (np.log(np.where(v > 0, v, 1.0)) - logm), 0.0)
            out[i0:i0 + 64] = np.sqrt(np.maximum(
                0.5 * (term(xv) + term(yv)).sum(-1), 0.0))
        else:
            raise ValueError(metric)
    return out


_METRIC_MAP = None


def _metric_map():
    global _METRIC_MAP
    if _METRIC_MAP is None:
        from raft_tpu.distance import DistanceType as D
        _METRIC_MAP = {
            "l1": (D.L1, {}),
            "linf": (D.Linf, {}),
            "l2sqrt_unexp": (D.L2SqrtUnexpanded, {}),
            "canberra": (D.Canberra, {}),
            "lp": (D.LpUnexpanded, {"metric_arg": 1.5}),
            "hamming": (D.HammingUnexpanded, {}),
            "js": (D.JensenShannon, {}),
        }
    return _METRIC_MAP


def check_pairwise(m, n, d, metric, seed=0):
    from raft_tpu.distance import pairwise_distance

    positive = metric in ("js",)
    x = rand((m, d), seed, positive=positive)
    y = rand((n, d), seed + 1, positive=positive)
    if metric == "js":  # rows must be distributions
        import jax.numpy as jnp
        x = x / jnp.sum(x, axis=1, keepdims=True)
        y = y / jnp.sum(y, axis=1, keepdims=True)
    if metric == "hamming":
        import jax.numpy as jnp
        x = jnp.round(x)
        y = jnp.round(y)
    mt, kw = _metric_map()[metric]
    t0 = time.time()
    got = np.asarray(pairwise_distance(x, y, mt, **kw))
    dt = time.time() - t0
    ref = np_pairwise(np.asarray(x), np.asarray(y), metric)
    ok = bool(np.allclose(got, ref, rtol=2e-4, atol=2e-4))
    rec = {"check": "pairwise_tile", "metric": metric, "m": m, "n": n,
           "d": d, "ok": ok, "t_incl_compile": round(dt, 2),
           "max_abs_diff": float(np.max(np.abs(got - ref)))}
    emit(rec)
    return ok


def main():
    import jax

    from bench import _enable_compile_cache

    _enable_compile_cache()

    dev = jax.devices()[0]
    emit({"check": "init", "device": str(dev.device_kind),
          "platform": dev.platform, "ok": dev.platform == "tpu"})
    if dev.platform != "tpu":
        print("NOT A TPU BACKEND; aborting", file=sys.stderr)
        return 1

    ok = True
    # fused kNN ladder: k sweep at a fixed shape (k=128 is the Pallas
    # cap — beyond it fused_l2_knn dispatches to XLA, mirroring the
    # reference's fusedL2Knn k<=64 gate), then ragged shapes, then the
    # 100k timing shape.  k=256 exercises the fallback dispatch.
    for k in (8, 64, 100, 128, 256):
        ok &= check_knn(4096, 256, 128, k, seed=k)
    ok &= check_knn(4097, 57, 33, 10, seed=100)     # ragged everything
    ok &= check_knn(1000, 7, 17, 5, seed=101)       # tiny + ragged d
    ok &= check_knn(4096, 256, 384, 64, seed=102)   # d > 128 (k-tiling)
    ok &= check_knn(100_000, 1024, 128, 100, seed=103)

    # merge-network A/B at the timing shape + a small shape: equality
    # and the steady-state cost of the log2-tail merge vs the full sort
    ok &= check_merge_impls(4096, 256, 128, 100, seed=300)
    ok &= check_merge_impls(100_000, 1024, 128, 100, seed=301)

    # standalone fused select kernel vs lax.top_k: the scan path's
    # per-tile selection shape and a ragged one
    ok &= check_select(4096, 8192, 100, seed=400)
    ok &= check_select(1024, 100_000, 100, seed=401)
    ok &= check_select(333, 5000, 17, seed=402)

    # fused 1-NN kernel (fused_l2_nn.cuh analog): aligned, ragged, 100k
    ok &= check_nn(256, 4096, 128, seed=200)
    ok &= check_nn(57, 1000, 17, seed=201)
    ok &= check_nn(1024, 100_000, 128, seed=202)

    # pairwise metrics: aligned, ragged, and k > 128 (cross-k-tile
    # accumulation) shapes
    for metric in ("l1", "linf", "l2sqrt_unexp", "canberra", "lp",
                   "hamming", "js"):
        ok &= check_pairwise(256, 512, 128, metric, seed=1)
    ok &= check_pairwise(193, 257, 77, "l1", seed=2)
    ok &= check_pairwise(193, 257, 77, "canberra", seed=2)
    ok &= check_pairwise(200, 300, 300, "l1", seed=3)
    ok &= check_pairwise(200, 300, 300, "linf", seed=3)

    summary = {"check": "SUMMARY", "ok": bool(ok),
               "n_checks": len(RESULTS) - 1,
               "n_failed": sum(1 for r in RESULTS if not r.get("ok", True))}
    emit(summary)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
