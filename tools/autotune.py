#!/usr/bin/env python
"""Bench-driven autotuner: sweep the candidate registry, persist winners.

The reference hand-specializes its dispatch per GPU arch; CUDA-L2
(PAPERS.md) shows *searched* schedules beating hand-tuned kernels, and
the CUDA-Tile evaluation shows the winner is venue-specific.  raft_tpu
does not need RL for that: the whole impl-choice space is the small
discrete candidate registry (:mod:`raft_tpu.core.tuning`), so an
exhaustive timed sweep per (backend, op, shape-class, dtype) cell
settles every knob with measurements.

For each cell the driver:

1. asks the registry for the candidates *legal to sweep* on this
   backend (``purpose="sweep"`` — interpreted-Pallas-off-TPU and the
   deliberately approximate modes are excluded there, with reasons
   recorded);
2. times each candidate through the library's own instrumentation —
   the workload compiles via :func:`profiled_jit` (compile time
   excluded and accounted separately), executes best-of-N with every
   sample observed into the metrics registry
   (``raft_tpu_autotune_exec_seconds``), and asserts ZERO post-warmup
   compiles (a candidate that recompiles mid-loop is mis-timed and the
   cell records it);
3. persists the winner + measured margins to a versioned JSON table
   keyed by the backend fingerprint (platform, device kind, device
   count) that :func:`raft_tpu.config.tuned` consults between env and
   default (docs/TUNING.md "Bench-driven autotuning").

Conservatism rule: a non-default winner is persisted only when it
beats the config default by at least ``--min-margin`` (default 1.05x)
— below that the default is kept, so the ``tuned_vs_default`` bench
rung can never lose to noise on a coin-flip cell.

Usage
-----
  python tools/autotune.py                   # full sweep -> raft_tpu/tuning/<slug>.json
  python tools/autotune.py --smoke           # one tiny cell per op (CI / bench wiring)
  python tools/autotune.py --op select_k     # filter by op
  python tools/autotune.py --cell k100       # filter by cell-name substring
  python tools/autotune.py --dry-run         # plan only: cells x candidates, no timing
  python tools/autotune.py --out /tmp/t.json # write elsewhere

The CPU-ladder checked-in table is generated under the bench/test
environment (8 virtual devices)::

  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python tools/autotune.py
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import warnings

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

ITERS_FULL = 5
ITERS_SMOKE = 2
MIN_MARGIN = 1.05


# --------------------------------------------------------------------- #
# cell catalog: (op, knob) -> full + smoke cells.  Dims use the SAME
# names as the registry spec's class dims (tuning.KnobSpec.dims) — the
# consumer lookup key and the sweep key must not skew.
# --------------------------------------------------------------------- #
def catalog(smoke: bool):
    """[(op, knob, cell_name, dims, extra)] — ``dims`` feed the shape
    class; ``extra`` holds workload-only sizes (nq, d, ...)."""
    if smoke:
        return [
            ("select_k", "select_impl", "k16_smoke",
             {"n": 4096, "k": 16}, {"nq": 32}),
            ("tiled_knn", "tile_merge", "knn4k_smoke",
             {"n": 4096, "k": 16}, {"nq": 32, "d": 16}),
            ("fused_l2_knn", "fused_knn_impl", "fused2k_smoke",
             {"n": 2048, "k": 8}, {"nq": 32, "d": 16}),
            ("fused_knn_tile", "knn_tile_merge", "ktile2k_smoke",
             {"n": 2048, "k": 8}, {"nq": 32, "d": 16}),
            # Pallas block-shape cells: legal to sweep on EVERY backend
            # (the ladder drives the fast XLA twin's geometry off-TPU),
            # so the CPU smoke path always exercises at least one
            # Pallas cell and the TPU sweep path can't rot here.  The
            # builders run one untimed interpreted-kernel parity check
            # per cell off-TPU (interpreted Pallas never in the timing
            # loop — it is ~1000x slow).
            ("fused_knn_tile", "knn_block_q", "blkq2k_smoke",
             {"n": 2048, "k": 8, "d": 16}, {"nq": 32}),
            ("fused_knn_tile", "knn_block_n", "blkn2k_smoke",
             {"n": 2048, "k": 8, "d": 16}, {"nq": 32}),
            ("fused_nn_tile", "nn_block_n", "nnblk2k_smoke",
             {"n": 2048, "d": 16}, {"nq": 32}),
            ("ivf_flat_search", "ivf_scan_impl", "ivf1k_smoke",
             {"n": 1024, "k": 8, "d": 16},
             {"nlist": 8, "nprobe": 4, "nq": 16}),
            ("csr_spmv", "spmv_impl", "spmv4k_smoke",
             {"rows": 4096, "nnz": 32768}, {}),
            ("ivf_pq_search", "pq_adc", "pq2k_smoke",
             {"n": 2048, "k": 8},
             {"d": 16, "nlist": 16, "M": 4, "nq": 32}),
            ("mnmg_knn", "mnmg_merge", "mnmg1k_smoke",
             {"n": 1024, "k": 8}, {"nq": 16, "d": 16}),
        ]
    return [
        # THE acceptance cell: select at k=100 over a wide row (PR 5
        # measured ~7x spread between impls at k=100)
        ("select_k", "select_impl", "k100",
         {"n": 131072, "k": 100}, {"nq": 256}),
        ("select_k", "select_impl", "k10",
         {"n": 131072, "k": 10}, {"nq": 256}),
        ("tiled_knn", "tile_merge", "knn50k",
         {"n": 50000, "k": 100}, {"nq": 256, "d": 64}),
        ("fused_l2_knn", "fused_knn_impl", "fused20k",
         {"n": 20000, "k": 32}, {"nq": 128, "d": 64}),
        ("fused_knn_tile", "knn_tile_merge", "ktile20k",
         {"n": 20000, "k": 32}, {"nq": 128, "d": 64}),
        # block-shape ladders (integer knobs): timed through the fused
        # Pallas kernel on TPU and the xla_fused reference off-TPU —
        # the SAME block geometry drives both, so every venue gets
        # real timings (interpreted Pallas is never in the loop)
        ("fused_knn_tile", "knn_block_q", "blkq20k",
         {"n": 20000, "k": 32, "d": 64}, {"nq": 128}),
        ("fused_knn_tile", "knn_block_n", "blkn20k",
         {"n": 20000, "k": 32, "d": 64}, {"nq": 128}),
        ("fused_nn_tile", "nn_block_n", "nnblk20k",
         {"n": 20000, "d": 64}, {"nq": 128}),
        ("ivf_flat_search", "ivf_scan_impl", "ivf32k",
         {"n": 32768, "k": 10, "d": 64},
         {"nlist": 64, "nprobe": 8, "nq": 128}),
        ("csr_spmv", "spmv_impl", "spmv200k",
         {"rows": 200000, "nnz": 2000000}, {}),
        ("ivf_pq_search", "pq_adc", "pq32k",
         {"n": 32768, "k": 10},
         {"d": 64, "nlist": 64, "M": 8, "nq": 128}),
        # merge-heavy geometry (small per-shard scan, wide nq*k merge
        # traffic): where the topology choice actually moves the
        # needle — measured 1.2x hierarchical-vs-allgather on the
        # 8-device virtual mesh
        ("mnmg_knn", "mnmg_merge", "mnmg16k",
         {"n": 16384, "k": 100}, {"nq": 512, "d": 32}),
    ]


def _rand(shape, seed=0, scale=1.0):
    import numpy as np

    return (np.random.RandomState(seed).random(shape) * scale).astype(
        "float32")


def _jnp(x):
    import jax.numpy as jnp

    return jnp.asarray(x)


# --------------------------------------------------------------------- #
# per-op workload builders: build data ONCE per cell, return
# make(candidate) -> zero-arg blocking step.  Every workload keeps both
# outputs live (bench lesson r4: a dead output lets XLA delete the
# selection inside the timing loop).
# --------------------------------------------------------------------- #
def _build_select_k(dims, extra, cell):
    import jax

    from raft_tpu.core.profiler import profiled_jit
    from raft_tpu.spatial.select_k import select_k

    keys = _jnp(_rand((extra["nq"], dims["n"])))
    k = dims["k"]

    def make(cand):
        fn = profiled_jit(
            lambda ks: select_k(ks, k, impl=cand),
            name="autotune_select_%s_%s" % (cell, cand))
        return lambda: jax.block_until_ready(fn(keys))
    return make


def _build_tiled_knn(dims, extra, cell):
    import jax
    import jax.numpy as jnp

    from raft_tpu.spatial.fused_l2_knn import _l2_tile_dist
    from raft_tpu.spatial.tiled_knn import tiled_knn

    x = _jnp(_rand((dims["n"], extra["d"])))
    q = _jnp(_rand((extra["nq"], extra["d"]), seed=1))
    qn = jnp.sum(q * q, axis=1)
    tile_dist = jax.tree_util.Partial(_l2_tile_dist("highest"), qn)
    k = dims["k"]

    def make(cand):
        return lambda: jax.block_until_ready(
            tiled_knn(x, q, k, tile_dist, merge=cand))
    return make


def _build_fused_l2_knn(dims, extra, cell):
    import jax

    from raft_tpu.spatial.fused_l2_knn import fused_l2_knn

    x = _jnp(_rand((dims["n"], extra["d"])))
    q = _jnp(_rand((extra["nq"], extra["d"]), seed=1))
    k = dims["k"]

    def make(cand):
        return lambda: jax.block_until_ready(
            fused_l2_knn(x, q, k, impl=cand))
    return make


def _build_fused_knn_tile(dims, extra, cell):
    import jax

    from raft_tpu.ops.knn_tile import fused_knn_tile

    x = _jnp(_rand((dims["n"], extra["d"])))
    q = _jnp(_rand((extra["nq"], extra["d"]), seed=1))
    k = dims["k"]

    def make(cand):
        return lambda: jax.block_until_ready(
            fused_knn_tile(x, q, k, merge_impl=cand))
    return make


def _parity_or_die(got, want, what):
    import numpy as np

    gd, gi = got
    wd, wi = want
    if not (np.array_equal(np.asarray(gi), np.asarray(wi))
            and np.allclose(np.asarray(gd), np.asarray(wd))):
        raise AssertionError(
            "autotune %s: interpreted kernel disagrees with the timed "
            "reference — the sweep would persist a shape the kernel "
            "does not honor" % what)


def _build_knn_block(knob_kw):
    """Builder factory for the knn_block_q / knn_block_n integer
    ladders.  On TPU the candidate block shape is timed through the
    fused Pallas kernel; off-TPU through :func:`fused_knn_xla`, whose
    tile geometry the SAME knob drives — so the sweep times a real
    executable on every backend and interpreted Pallas stays out of
    the timing loop.  Small off-TPU cells additionally run ONE untimed
    interpreted-kernel execution at cell-build time, checked against
    the fast XLA twin (distances exact, ids equal on distinct
    distances) — the CPU smoke sweep exercises the kernel code path
    itself, so the TPU sweep can't rot on this box."""
    def build(dims, extra, cell):
        import jax

        from raft_tpu.core.utils import is_tpu_backend
        from raft_tpu.ops.knn_tile import fused_knn_tile, fused_knn_xla

        x = _jnp(_rand((dims["n"], dims["d"])))
        q = _jnp(_rand((extra["nq"], dims["d"]), seed=1))
        k = dims["k"]
        on_tpu = is_tpu_backend()
        if not on_tpu and dims["n"] <= 2048:
            _parity_or_die(fused_knn_tile(x, q, k, interpret=True),
                           fused_knn_xla(x, q, k),
                           "fused_knn_tile[%s]" % cell)

        def make(cand):
            kw = {knob_kw: int(cand)}
            if on_tpu:
                return lambda: jax.block_until_ready(
                    fused_knn_tile(x, q, k, **kw))
            return lambda: jax.block_until_ready(
                fused_knn_xla(x, q, k, **kw))
        return make
    return build


def _build_nn_block(dims, extra, cell):
    """nn_block_n ladder: fused Pallas NN kernel on TPU; off-TPU the
    candidate drives ``tile_n`` of the XLA scan fallback
    (:func:`fused_l2_nn_min_reduce`) — the same index-tile-width role,
    a real timeable executable.  Small off-TPU cells run one untimed
    interpreted-kernel agreement check at cell-build time."""
    import jax

    from raft_tpu.core.utils import is_tpu_backend
    from raft_tpu.distance.fused_l2_nn import fused_l2_nn_min_reduce
    from raft_tpu.ops.nn_tile import fused_nn_tile

    x = _jnp(_rand((extra["nq"], dims["d"])))
    y = _jnp(_rand((dims["n"], dims["d"]), seed=1))
    on_tpu = is_tpu_backend()
    if not on_tpu and dims["n"] <= 2048:
        _parity_or_die(fused_nn_tile(x, y, interpret=True),
                       fused_l2_nn_min_reduce(x, y),
                       "fused_nn_tile[%s]" % cell)

    def make(cand):
        if on_tpu:
            return lambda: jax.block_until_ready(
                fused_nn_tile(x, y, block_n=int(cand)))
        return lambda: jax.block_until_ready(
            fused_l2_nn_min_reduce(x, y, tile_n=int(cand)))
    return make


def _build_ivf_flat_search(dims, extra, cell):
    import jax

    from raft_tpu.spatial.ann import IVFFlatParams, ivf_flat_build, \
        ivf_flat_search

    x = _rand((dims["n"], dims["d"]))
    q = _jnp(_rand((extra["nq"], dims["d"]), seed=1))
    params = IVFFlatParams(nlist=extra["nlist"],
                           nprobe=extra["nprobe"])
    index = ivf_flat_build(_jnp(x), params)
    k = dims["k"]

    def make(cand):
        # scan_impl is a trace-time static: each candidate compiles
        # its own executable (warmup call pays that, per the
        # time_candidate contract)
        return lambda: jax.block_until_ready(
            ivf_flat_search(index, q, k, scan_impl=cand))
    return make


def _build_csr_spmv(dims, extra, cell):
    import jax
    import numpy as np

    from raft_tpu.core.profiler import profiled_jit
    from raft_tpu.sparse.formats import CSR
    from raft_tpu.sparse.linalg import csr_spmv

    rows = dims["rows"]
    nnz_row = max(1, dims["nnz"] // rows)
    rng = np.random.RandomState(0)
    dense_cols = rows
    indptr = np.arange(rows + 1, dtype=np.int32) * nnz_row
    indices = rng.randint(0, dense_cols,
                          size=rows * nnz_row).astype(np.int32)
    data = rng.random(rows * nnz_row).astype(np.float32)
    csr = CSR(_jnp(indptr), _jnp(indices), _jnp(data),
              (rows, dense_cols))
    x = _jnp(rng.random(dense_cols).astype(np.float32))

    def make(cand):
        fn = profiled_jit(
            lambda c, v: csr_spmv(c, v, impl=cand),
            name="autotune_spmv_%s_%s" % (cell, cand))
        return lambda: jax.block_until_ready(fn(csr, x))
    return make


def _build_ivf_pq_search(dims, extra, cell):
    import jax

    from raft_tpu import config
    from raft_tpu.spatial.ann import IVFPQParams, ivf_pq_build, \
        ivf_pq_search

    x = _rand((dims["n"], extra["d"]))
    q = _jnp(_rand((extra["nq"], extra["d"]), seed=1))
    params = IVFPQParams(nlist=extra["nlist"], nprobe=4,
                         M=extra["M"], n_bits=8)
    index = ivf_pq_build(_jnp(x), params)
    k = dims["k"]

    def make(cand):
        def step():
            # pq_adc resolves at call time from config; candidate
            # pinned via a scoped override (consumed-knob warnings are
            # the sweep's own churn, not a user bug — suppressed)
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                with config.override(pq_adc=cand):
                    return jax.block_until_ready(
                        ivf_pq_search(index, q, k))
        return step
    return make


def _build_mnmg_knn(dims, extra, cell):
    import jax

    from raft_tpu.spatial.mnmg_knn import mnmg_knn

    x = _jnp(_rand((dims["n"], extra["d"])))
    q = _jnp(_rand((extra["nq"], extra["d"]), seed=1))
    k = dims["k"]

    def make(cand):
        return lambda: jax.block_until_ready(
            mnmg_knn(x, q, k, merge=cand))
    return make


BUILDERS = {
    "select_k": _build_select_k,
    "tiled_knn": _build_tiled_knn,
    "fused_l2_knn": _build_fused_l2_knn,
    "fused_knn_tile": _build_fused_knn_tile,
    # knob-keyed entries take precedence over the op key: multi-knob
    # ops (fused_knn_tile sweeps a merge impl AND two block ladders)
    # need per-knob workloads
    ("fused_knn_tile", "knn_block_q"): _build_knn_block("block_q"),
    ("fused_knn_tile", "knn_block_n"): _build_knn_block("block_n"),
    "fused_nn_tile": _build_nn_block,
    "ivf_flat_search": _build_ivf_flat_search,
    "csr_spmv": _build_csr_spmv,
    "ivf_pq_search": _build_ivf_pq_search,
    "mnmg_knn": _build_mnmg_knn,
}


def _builder(op, knob):
    """Knob-keyed builder when registered, else the op's builder."""
    return BUILDERS.get((op, knob)) or BUILDERS[op]


# --------------------------------------------------------------------- #
# timing: profiled_jit owns compile accounting; executes are observed
# into the metrics registry AND reduced best-of-N locally
# --------------------------------------------------------------------- #
def _total_misses():
    from raft_tpu.core.profiler import compile_cache_stats

    return sum(st.get("misses", 0)
               for keys in compile_cache_stats().values()
               for st in keys.values())


def _exec_timer(op, cell, cand):
    from raft_tpu.core.metrics import default_registry

    return default_registry().timer(
        "raft_tpu_autotune_exec_seconds",
        help="autotune sweep execute time (best-of-N per candidate)",
        labels=("op", "cell", "candidate")).labels(
            op=op, cell=cell, candidate=cand)


def time_candidate(step, *, op, cell, cand, iters):
    """(best_seconds, post_warmup_compiles): one warmup call (compile,
    attributed by profiled_jit), then ``iters`` timed executes with a
    zero-new-compiles assertion across the loop.  The tuning table is
    SUSPENDED throughout: the swept candidate is pinned explicitly,
    and any *nested* knob the workload resolves (e.g. tiled_knn's
    internal select_impl) must time at the defaults — or a re-sweep on
    an already-tuned venue would measure candidates under the
    incumbent table's pins and persist winners inconsistent with the
    fresh table they ship in."""
    from raft_tpu import config

    with config.suspend_tuning():
        step()                               # warmup: compile + cache
        m0 = _total_misses()
        timer = _exec_timer(op, cell, cand)
        best = float("inf")
        for _ in range(iters):
            t0 = time.perf_counter()
            step()
            dt = time.perf_counter() - t0
            timer.observe(dt)
            best = min(best, dt)
        return best, _total_misses() - m0


# --------------------------------------------------------------------- #
# the sweep
# --------------------------------------------------------------------- #
def _effective_default(knob):
    """The sweep's comparison baseline: the config default, or — for
    unset-default knobs like fused_knn_impl whose None means a
    consumer-side auto — the registry's declared auto_default.
    Without this, the min-margin conservatism and the tuned_vs_default
    guard would both silently skip such knobs and a noise-level winner
    could be persisted unverified."""
    from raft_tpu import config
    from raft_tpu.core import tuning

    return (config.knob_default(knob)
            or tuning.spec(knob).auto_default)


def _augment_dims(op, dims):
    """Backend-dependent dims resolved at sweep time: the mnmg merge
    cell is keyed on the LIVE device count (the winner flips with the
    mesh size — that is a shape dim, not a fingerprint concern)."""
    if op == "mnmg_knn":
        import jax

        return dict(dims, devices=jax.device_count())
    return dims


def sweep_cell(op, knob, cell_name, dims, extra, *, iters,
               min_margin=MIN_MARGIN):
    """Time every sweep-legal candidate of one cell; returns the table
    entry (winner conservatism: module doc) or None when fewer than
    one candidate is legal."""
    from raft_tpu import config
    from raft_tpu.core import tuning

    dims = _augment_dims(op, dims)
    cands = tuning.legal_candidates(knob, purpose="sweep",
                                    dtype="float32", **dims)
    legal = [c for c, why in cands if why is None]
    skipped = {c: why for c, why in cands if why is not None}
    if not legal:
        return None
    make = _builder(op, knob)(dims, extra, cell_name)
    timings, compiles = {}, {}
    for cand in legal:
        t, extra_compiles = time_candidate(
            make(cand), op=op, cell=cell_name, cand=cand, iters=iters)
        timings[cand] = t
        compiles[cand] = extra_compiles
    ranked = sorted(timings, key=timings.get)
    winner = ranked[0]
    default = _effective_default(knob)
    margin = (timings[ranked[1]] / timings[winner]
              if len(ranked) > 1 else 1.0)
    vs_default = (timings[default] / timings[winner]
                  if default in timings else None)
    reverted_from = None
    if (default in timings and winner != default
            and timings[default] / timings[winner] < min_margin):
        # conservatism: a sub-margin win is noise territory — keep the
        # default so the tuned table can never LOSE to it.  margin is
        # RECOMPUTED for the persisted winner (best alternative over
        # it — honestly < 1 here: the discarded candidate was faster,
        # just inside the noise band)
        reverted_from, winner = winner, default
        vs_default = 1.0
        margin = round(min(t for c, t in timings.items()
                           if c != winner) / timings[winner], 4)
    return {
        "op": op, "knob": knob, "cell": cell_name,
        "shape_class": tuning.shape_class(dims),
        "dtype": "float32",
        "dims": dims,
        "extra": extra,
        "winner": winner,
        "margin": round(margin, 4),
        "reverted_from": reverted_from,
        "vs_default": (round(vs_default, 4)
                       if vs_default is not None else None),
        "timings_s": {c: round(t, 6) for c, t in timings.items()},
        "post_warmup_compiles": compiles,
        "skipped": skipped,
        "iters": iters,
    }


def run_sweep(*, smoke=False, op_filter=None, cell_filter=None,
              iters=None, min_margin=MIN_MARGIN, log=print):
    """Run the sweep; returns the table document (not yet written)."""
    from raft_tpu.core import tuning

    cells = catalog(smoke)
    if op_filter:
        cells = [c for c in cells if c[0] == op_filter
                 or c[1] == op_filter]
    if cell_filter:
        cells = [c for c in cells if cell_filter in c[2]]
    iters = iters or (ITERS_SMOKE if smoke else ITERS_FULL)
    entries = []
    for op, knob, cell_name, dims, extra in cells:
        log("sweep %s/%s cell=%s dims=%s ..." % (op, knob, cell_name,
                                                 dims))
        e = sweep_cell(op, knob, cell_name, dims, extra, iters=iters,
                       min_margin=min_margin)
        if e is None:
            log("  no sweep-legal candidates on this backend; skipped")
            continue
        log("  winner=%s margin=%.2fx vs_default=%s timings=%s" % (
            e["winner"], e["margin"], e["vs_default"],
            {c: "%.4fs" % t for c, t in e["timings_s"].items()}))
        bad = {c: n for c, n in e["post_warmup_compiles"].items() if n}
        if bad:
            log("  WARNING post-warmup compiles: %s (mis-timed "
                "candidates)" % bad)
        entries.append(e)
    # per-(op, knob) wildcard rollup: the winner of the LARGEST swept
    # cell answers shape-less lookups (e.g. serve construction) and
    # unswept classes through the lookup's "*" fallbacks
    by_knob = {}
    for e in entries:
        by_knob.setdefault((e["op"], e["knob"]), []).append(e)
    for (op, knob), group in sorted(by_knob.items()):
        largest = max(group, key=lambda e: _cell_volume(e["dims"]))
        entries.append({
            "op": op, "knob": knob, "cell": "rollup",
            "shape_class": "*", "dtype": "*",
            "winner": largest["winner"],
            "margin": largest["margin"],
            "vs_default": largest["vs_default"],
            "rollup_of": largest["cell"],
        })
    return {
        "version": 1,
        "fingerprint": tuning.backend_fingerprint(),
        "created_unix": int(time.time()),
        "generated_by": "tools/autotune.py",
        "smoke": smoke,
        "min_margin": min_margin,
        "entries": entries,
    }


def _cell_volume(dims):
    v = 1
    for x in dims.values():
        v *= max(int(x), 1)
    return v


def diff_tables(old, new, log=print):
    """Human diff of winners: new vs incumbent, per cell."""
    def key(e):
        return (e["op"], e["knob"], e["shape_class"], e["dtype"])

    old_ix = {key(e): e for e in old.get("entries", [])}
    changes = 0
    for e in new["entries"]:
        inc = old_ix.pop(key(e), None)
        if inc is None:
            log("  NEW   %s/%s [%s] -> %s" % (
                e["op"], e["knob"], e["shape_class"], e["winner"]))
            changes += 1
        elif inc["winner"] != e["winner"]:
            log("  FLIP  %s/%s [%s]: %s -> %s (margin %.2fx)" % (
                e["op"], e["knob"], e["shape_class"], inc["winner"],
                e["winner"], e.get("margin", 1.0)))
            changes += 1
    for k in old_ix:
        log("  GONE  %s/%s [%s]" % (k[0], k[1], k[2]))
        changes += 1
    if not changes:
        log("  no winner changes vs incumbent")
    return changes


# --------------------------------------------------------------------- #
# tuned-vs-default: what is the table worth on this venue?  (the bench
# rung's engine — docs/TUNING.md "Measuring")
# --------------------------------------------------------------------- #
def _time_ab(step_a, step_b, *, iters, op, cell, cand_a, cand_b):
    """Interleaved A/B best-of-N: the arms alternate every iteration
    so a host load spike lands on BOTH, not whichever arm it happened
    to overlap (the serve_trace_overhead rung's discipline — a
    sequential A-then-B on a busy box can invert a real 1.17x margin).
    Returns (best_a, best_b, post_warmup_compiles).  Table suspended
    throughout (the time_candidate rationale: nested knobs time at
    the defaults both arms share)."""
    from raft_tpu import config

    with config.suspend_tuning():
        step_a()
        step_b()                           # warm both: compiles done
        m0 = _total_misses()
        timer_a = _exec_timer(op, cell, cand_a)
        timer_b = _exec_timer(op, cell, cand_b)
        best_a = best_b = float("inf")
        for _ in range(iters):
            t0 = time.perf_counter()
            step_a()
            dt = time.perf_counter() - t0
            timer_a.observe(dt)
            best_a = min(best_a, dt)
            t0 = time.perf_counter()
            step_b()
            dt = time.perf_counter() - t0
            timer_b.observe(dt)
            best_b = min(best_b, dt)
        return best_a, best_b, _total_misses() - m0


def tuned_vs_default(table, *, iters=5, log=print):
    """Re-time winner vs config-default for every exact swept cell of
    ``table``; returns per-op ratios.  winner == default reports 1.0
    without re-timing (same executable — there is nothing to race);
    genuinely different arms race INTERLEAVED (:func:`_time_ab`)."""
    out = {"cells": [], "min_ratio": None, "max_ratio": None,
           "post_warmup_compiles": 0}
    for e in table["entries"]:
        if e.get("shape_class") == "*" or "dims" not in e:
            continue
        default = _effective_default(e["knob"])
        cell_r = {"op": e["op"], "knob": e["knob"], "cell": e["cell"],
                  "winner": e["winner"], "default": default}
        if e["winner"] == default or default not in e.get(
                "timings_s", {e["winner"]: 0}):
            cell_r["ratio"] = 1.0
            cell_r["note"] = "winner is the default"
        else:
            make = _builder(e["op"], e["knob"])(
                e["dims"], e.get("extra", {}), e["cell"] + "_ab")
            tw, td, compiles = _time_ab(
                make(e["winner"]), make(default), iters=iters,
                op=e["op"], cell=e["cell"] + "_ab",
                cand_a=e["winner"], cand_b=default)
            cell_r["ratio"] = round(td / tw, 4)
            cell_r["tuned_s"] = round(tw, 6)
            cell_r["default_s"] = round(td, 6)
            out["post_warmup_compiles"] += compiles
        out["cells"].append(cell_r)
        log("  %s/%s [%s]: tuned/default ratio %.2fx" % (
            e["op"], e["knob"], e["cell"], cell_r["ratio"]))
    ratios = [c["ratio"] for c in out["cells"]]
    if ratios:
        out["min_ratio"] = min(ratios)
        out["max_ratio"] = max(ratios)
    return out


def default_out_path(table):
    from raft_tpu.core import tuning

    return os.path.join(REPO, "raft_tpu", "tuning",
                        tuning.fingerprint_slug(table["fingerprint"])
                        + ".json")


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--op", help="filter: op or knob name")
    p.add_argument("--cell", help="filter: cell-name substring")
    p.add_argument("--smoke", action="store_true",
                   help="one tiny cell per op (seconds, not minutes)")
    p.add_argument("--dry-run", action="store_true",
                   help="plan only: print cells x legal candidates")
    p.add_argument("--iters", type=int, default=None)
    p.add_argument("--min-margin", type=float, default=MIN_MARGIN)
    p.add_argument("--out", help="output path (default: "
                   "raft_tpu/tuning/<fingerprint-slug>.json)")
    args = p.parse_args(argv)

    if args.dry_run:
        from raft_tpu.core import tuning

        for op, knob, cell_name, dims, extra in catalog(args.smoke):
            if args.op and args.op not in (op, knob):
                continue
            if args.cell and args.cell not in cell_name:
                continue
            cands = tuning.legal_candidates(knob, purpose="sweep",
                                            dtype="float32", **dims)
            print("%s/%s cell=%s class=%s" % (
                op, knob, cell_name, tuning.shape_class(dims)))
            for c, why in cands:
                print("    %-12s %s" % (c, "SWEEP" if why is None
                                        else "skip: " + why))
        return 0

    table = run_sweep(smoke=args.smoke, op_filter=args.op,
                      cell_filter=args.cell, iters=args.iters,
                      min_margin=args.min_margin)
    out = args.out or default_out_path(table)
    if os.path.exists(out):
        print("diff vs incumbent %s:" % out)
        try:
            with open(out, encoding="utf-8") as f:
                diff_tables(json.load(f), table)
        except (OSError, ValueError) as e:
            print("  incumbent unreadable (%s); overwriting" % e)
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "w", encoding="utf-8") as f:
        json.dump(table, f, indent=1, sort_keys=True)
        f.write("\n")
    print("wrote %d entries -> %s" % (len(table["entries"]), out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
