"""Profile the hot kNN/pairwise paths on the current backend.

Captures an XLA profiler trace (view with tensorboard or xprof) and
prints per-op wall times for the north-star shapes, so kernel tuning is
driven by measurements instead of guesses.  Usage:

    python tools/profile_knn.py [outdir] [--small]

The trace directory defaults to /tmp/raft_tpu_trace.
"""

import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(REPO, ".jax_cache"))


def main():
    positional = [a for a in sys.argv[1:] if not a.startswith("--")]
    outdir = positional[0] if positional else "/tmp/raft_tpu_trace"
    small = "--small" in sys.argv

    import jax
    import jax.numpy as jnp

    from bench import _enable_compile_cache

    _enable_compile_cache()

    from raft_tpu.spatial.fused_l2_knn import fused_l2_knn

    dev = jax.devices()[0]
    print(f"backend: {dev.platform} ({dev.device_kind})", flush=True)

    n, nq, d, k = (100_000, 1024, 128, 100) if small else \
        (1_000_000, 10_000, 128, 100)
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (n, d), jnp.float32)
    q = jax.random.normal(jax.random.PRNGKey(1), (nq, d), jnp.float32)

    impls = ["xla"]
    if dev.platform == "tpu":
        impls.append("pallas")

    # warm both compiles outside the trace
    for impl in impls:
        t0 = time.time()
        jax.block_until_ready(fused_l2_knn(x, q, k, impl=impl))
        print(f"{impl}: compile+first run {time.time() - t0:.1f}s",
              flush=True)

    with jax.profiler.trace(outdir):
        for impl in impls:
            for _ in range(3):
                t0 = time.time()
                jax.block_until_ready(fused_l2_knn(x, q, k, impl=impl))
                dt = time.time() - t0
                qps = nq / dt
                mfu_flops = 2.0 * nq * n * d / dt
                print(f"{impl}: {dt:.4f}s  {qps:,.0f} QPS  "
                      f"{mfu_flops / 1e12:.2f} TFLOP/s", flush=True)
    print(f"trace written to {outdir}", flush=True)


if __name__ == "__main__":
    main()
