#!/usr/bin/env bash
# Round-5 unattended recovery pipeline.  Session window: ~16:10 UTC
# Jul 31 -> ~04:00 UTC Aug 1.  Probe the accelerator endpoint until it
# answers, then run the measurement sequence in priority order.
#
# Probe policy (r4 wedge forensics): each probe gets 15 min to finish
# or fail BY ITSELF; only a >15 min hang is abandoned (kills mid-RPC
# are the suspected wedge cause, so we avoid them except as backstop).
#
# Priority on recovery: the full bench FIRST (banks rungs
# incrementally, contains every open measurement, and its pallas_check
# cross-validates every kernel — incl. twophase — before any timing is
# trusted), then the kNN selection sweep (VERDICT r4 item 1/2), then
# the full on-chip validation suite and the second-tier timing tools.
#
# Stand-down: past 03:00 UTC (and before 16:00 UTC, i.e. next-day
# morning) the pipeline exits so the driver's round-end bench finds a
# free endpoint and a warm compile cache.  EVERY post-recovery step is
# additionally clamped by `timeout $(secs_left)` so a wedged RPC or a
# step started near the wall cannot occupy the endpoint into the
# driver's window (SIGINT first, KILL 60 s later — the gentlest
# abandonment available once holding the endpoint is the greater harm).
cd /root/repo
LOG=.recovery_r5.log
standdown() {
  NOW=$(date -u +%H%M)
  # session runs 1610 -> ~0400 UTC; stand down in [0300, 1600)
  if [ "$NOW" -ge 0300 ] && [ "$NOW" -lt 1600 ]; then return 0; fi
  return 1
}
secs_left() {  # seconds until the 03:00 UTC stand-down wall
  local now target
  now=$(date -u +%s)
  if [ "$(date -u +%H%M)" -ge 0300 ]; then
    target=$(date -u -d "tomorrow 03:00" +%s)
  else
    target=$(date -u -d "03:00" +%s)
  fi
  echo $(( target - now ))
}
echo "=== r5 pipeline start $(date -u +%H:%M:%S) ===" >> "$LOG"

# never run two probe clients at once: wait out any probe a previous
# pipeline instance left in flight (it dies by itself within 15 min)
while pgrep -f "python tools/tpu_probe.py" > /dev/null 2>&1; do
  echo "$(date -u +%H:%M:%S) older probe still in flight; waiting" >> "$LOG"
  sleep 60
done

while true; do
  if standdown; then
    echo "$(date -u +%H:%M:%S) stand-down window — exit for the driver" >> "$LOG"
    exit 0
  fi
  timeout 900 python tools/tpu_probe.py >> "$LOG" 2>&1
  RC=$?   # capture IMMEDIATELY: `if` compounds and $(date) reset $?
  [ "$RC" -eq 0 ] && break
  echo "$(date -u +%H:%M:%S) probe failed (rc=$RC); sleeping 120" >> "$LOG"
  sleep 120
done
echo "=== BACKEND UP $(date -u +%H:%M:%S) ===" >> "$LOG"

# Leave a marker the interactive session can poll.
touch .backend_up_r5

NOW=$(date -u +%H%M)
# generous budget before midnight UTC; shorter after (wall nears)
if [ "$NOW" -ge 1600 ]; then BUDGET=2700; else BUDGET=1500; fi
LEFT=$(secs_left)
[ "$BUDGET" -gt "$LEFT" ] && BUDGET=$LEFT
if [ "$LEFT" -le 300 ]; then
  echo "=== skip bench: only ${LEFT}s to stand-down ===" >> "$LOG"
else
  echo "=== full bench (budget $BUDGET, wall in ${LEFT}s) ===" >> "$LOG"
  RAFT_TPU_BENCH_BUDGET=$BUDGET timeout -s INT -k 60 "$LEFT" \
    python bench.py > .bench_r05_auto.json 2> .bench_r05_auto.err
  echo "bench rc=$? at $(date -u +%H:%M:%S)" >> "$LOG"
fi

run_tool() {  # run_tool <script> <logfile>
  if standdown; then
    echo "$(date -u +%H:%M:%S) stand-down — skip $1" >> "$LOG"
    return 1
  fi
  local left
  left=$(secs_left)
  if [ "$left" -le 300 ]; then
    echo "$(date -u +%H:%M:%S) only ${left}s to wall — skip $1" >> "$LOG"
    return 1
  fi
  echo "=== $1 (wall in ${left}s) ===" >> "$LOG"
  timeout -s INT -k 60 "$left" python "$1" > "$2" 2>&1
  echo "$1 rc=$? at $(date -u +%H:%M:%S)" >> "$LOG"
}
run_tool tools/knn_kernel_sweep.py .knn_sweep_r5.log
run_tool tools/onchip_check.py .onchip_r05.log
run_tool tools/spectral_probe.py .spectral_probe_r5.log
run_tool tools/select_variants.py .select_variants_r5.log
run_tool tools/steady_knn.py .steady_knn_r5.log
echo "=== r5 pipeline done $(date -u +%H:%M:%S) ===" >> "$LOG"
