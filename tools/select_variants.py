"""Steady-state timing: top_k vs chunked merge-tree selection inside
the XLA tile-scan kNN at the 100k shape, on the live backend.

Decides whether ``chunked`` should be the TPU default for wide
selection.  Output: one line per impl (flushed).
"""

import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(REPO, ".jax_cache"))

T0 = time.time()


def log(msg):
    print(f"[{time.time()-T0:7.1f}s] {msg}", flush=True)


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from bench import _enable_compile_cache

    _enable_compile_cache()

    dev = jax.devices()[0]
    log(f"backend: {dev.platform} ({dev.device_kind})")

    from raft_tpu.spatial.select_k import chunked_top_k
    from raft_tpu.spatial.tiled_knn import tiled_knn

    n, nq, d, k = 100_000, 1024, 128, 100
    x = jax.random.normal(jax.random.PRNGKey(0), (n, d), jnp.float32)
    q = jax.random.normal(jax.random.PRNGKey(1), (nq, d), jnp.float32)
    jax.block_until_ready((x, q))
    log("data ready")

    # standalone selection cost at the tile shape, isolated from the
    # scan: one (nq, 8192) top-k per impl
    sel = jax.random.normal(jax.random.PRNGKey(2), (4096, 8192),
                            jnp.float32)
    jax.block_until_ready(sel)
    from jax import lax

    from raft_tpu.ops.select_tile import select_tile

    # BOTH outputs folded into the timed value: a values-only return
    # lets XLA dead-code the index half under jit (bench.py
    # _time_chained caller contract; r4 finding)
    def _live(pair):
        v, i = pair
        return v + i.astype(v.dtype)

    for name, fn in [("lax.top_k", lambda s: _live(lax.top_k(s, k))),
                     ("chunked", lambda s: _live(chunked_top_k(s, k))),
                     ("pallas", lambda s: _live(select_tile(-s, k))),
                     ("approx95",
                      lambda s: _live(lax.approx_max_k(
                          s, k, recall_target=0.95)))]:
        f = jax.jit(fn)
        t0 = time.perf_counter()
        jax.block_until_ready(f(sel))
        log(f"select {name}: compile+first {time.perf_counter()-t0:.2f}s")
        ts = []
        for _ in range(5):
            t0 = time.perf_counter()
            jax.block_until_ready(f(sel))
            ts.append(time.perf_counter() - t0)
        log(f"select {name}: steady {min(ts)*1e3:.2f} ms over (4096, 8192)")

    # end-to-end scan path per select impl
    def dist(qq, x_t):
        qn = (qq * qq).sum(1)
        xn = (x_t * x_t).sum(1)
        g = jnp.matmul(qq, x_t.T, precision="highest")
        return qn[:, None] + xn[None, :] - 2.0 * g

    for impl in ("topk", "chunked", "pallas"):
        os.environ["RAFT_TPU_SELECT_IMPL"] = impl
        f = jax.jit(lambda qq: _live(tiled_knn(x, qq, k, dist)))
        t0 = time.perf_counter()
        jax.block_until_ready(f(q))
        log(f"scan {impl}: compile+first {time.perf_counter()-t0:.2f}s")
        ts = []
        for _ in range(5):
            t0 = time.perf_counter()
            jax.block_until_ready(f(q))
            ts.append(time.perf_counter() - t0)
        dt = min(ts)
        log(f"scan {impl}: steady {dt*1e3:.2f} ms  {nq/dt:,.0f} QPS")
    os.environ.pop("RAFT_TPU_SELECT_IMPL", None)

    # sanity: every raced impl must produce the reference values
    d_t, _ = tiled_knn(x, q[:64], k, dist)
    for impl in ("chunked", "pallas"):
        os.environ["RAFT_TPU_SELECT_IMPL"] = impl
        d_c, _ = tiled_knn(x, q[:64], k, dist)
        os.environ.pop("RAFT_TPU_SELECT_IMPL", None)
        ok = bool(np.allclose(np.asarray(d_c), np.asarray(d_t),
                              atol=1e-3))
        log(f"values match ({impl} vs topk): {ok}")


if __name__ == "__main__":
    main()
