"""On-chip spectral timing: SpMV impl x operator densification A/B.

VERDICT r4 item 5's hardware half: after the r5 single-jit Lanczos fixed
the CPU retrace pathology, the remaining spectral question is which
matvec shape wins on the TPU — the gather+segment SpMV (``segment``),
the prefix-sum form (``cumsum``), the gather-free sort+scan form
(``sortscan``), or (small graphs only) the densified MXU matvec.  One
flushed JSON line per config; steady-state timed by repeat solves of
the SAME operator (executable cache hits — the honest regime after the
retrace fix).

    python tools/spectral_probe.py > .spectral_probe.log 2>&1
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(REPO, ".jax_cache"))
os.environ.setdefault("RAFT_TPU_BENCH_DEADLINE", str(time.time() + 1800))

T0 = time.time()


def emit(rec):
    rec["t"] = round(time.time() - T0, 1)
    print(json.dumps(rec), flush=True)


def main():
    import jax
    import numpy as np

    from bench import _enable_compile_cache, two_community_graph

    _enable_compile_cache()
    dev = jax.devices()[0]
    emit({"config": "init", "device": str(dev.device_kind),
          "platform": dev.platform})

    from raft_tpu.spectral import partition
    from raft_tpu.spectral.eigen_solvers import (EigenSolverConfig,
                                                 LanczosSolver)
    from raft_tpu.spectral.matrix_wrappers import LaplacianMatrix

    # RAFT_TPU_SWEEP_SMOKE=1: tiny wiring check
    smoke = os.environ.get("RAFT_TPU_SWEEP_SMOKE") == "1"
    shapes = ([(500, 4)] if smoke
              else [(1024, 20), (50_000, 40)])   # (n_half, n_cross)

    for n_half, n_cross in shapes:
        n = 2 * n_half
        csr = two_community_graph(n_half, n_cross,
                                  np.random.default_rng(0))
        solver = LanczosSolver(EigenSolverConfig(
            n_eig_vecs=2, max_iter=6000, restart_iter=80, tol=1e-3,
            seed=42))
        # eigensolver (the hot loop) per matvec shape; densify only
        # where the dense matrix fits the operator budget
        variants = [("segment", False), ("cumsum", False),
                    ("sortscan", False)]
        if n * n <= (1 << 22):
            variants.append(("segment", True))
        for impl, densify in variants:
            name = (f"lanczos_{n}_{impl}" + ("_dense" if densify else ""))
            try:
                # impl pinned ON the operator (aux data -> distinct
                # executables); a config override could not reach an
                # already-compiled solver
                op = LaplacianMatrix(csr, densify=densify,
                                     spmv_impl=impl)
                t0 = time.time()
                vals, _, iters = solver.solve_smallest_eigenvectors(
                    op, n)
                jax.block_until_ready(vals)
                first = time.time() - t0
                ts = []
                for _ in range(3):
                    t0 = time.time()
                    vals, _, iters = (
                        solver.solve_smallest_eigenvectors(op, n))
                    jax.block_until_ready(vals)
                    ts.append(time.time() - t0)
                emit({"config": name, "n_vertices": n,
                      "first_incl_compile_s": round(first, 2),
                      "steady_s": round(min(ts), 4),
                      "iters": int(iters),
                      "fiedler": round(float(np.asarray(vals)[1]), 6)})
            except Exception as e:
                emit({"config": name, "error": str(e)[-200:]})
                if "UNAVAILABLE" in str(e):
                    return
        # public end-to-end path once per graph (auto operator choice)
        try:
            t0 = time.time()
            res = partition(csr, eigen_solver=solver, n_clusters=2)
            wall = time.time() - t0
            truth = np.arange(n) >= n_half
            cl = np.asarray(res.clusters)
            acc = max((cl == truth).mean(), (cl != truth).mean())
            emit({"config": f"partition_{n}_auto", "n_vertices": n,
                  "wall_s": round(wall, 2),
                  "community_accuracy": round(float(acc), 4)})
        except Exception as e:
            emit({"config": f"partition_{n}_auto",
                  "error": str(e)[-200:]})
            if "UNAVAILABLE" in str(e):
                return


if __name__ == "__main__":
    main()
