#!/usr/bin/env bash
# Style gate (reference ci/checks/style.sh).  No linter is baked into
# the image; ci/style_check.py implements the flake8-class checks with
# the stdlib.
set -euo pipefail
cd "$(dirname "$0")"
exec python ci/style_check.py "$@"
