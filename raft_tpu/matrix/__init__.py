"""Matrix manipulation + math helpers.

TPU-native equivalent of cpp/include/raft/matrix/ (matrix.hpp, math.hpp).
"""

from raft_tpu.matrix.matrix import (
    col_reverse,
    copy_rows,
    copy_upper_triangular,
    get_diagonal_inverse_matrix,
    get_l2_norm,
    initialize_diagonal_matrix,
    print_host,
    row_reverse,
    slice_matrix,
    trunc_zero_origin,
)
from raft_tpu.matrix.math import (
    argmax,
    matrix_vector_binary_add,
    matrix_vector_binary_div,
    matrix_vector_binary_div_skip_zero,
    matrix_vector_binary_mult,
    matrix_vector_binary_mult_skip_zero,
    matrix_vector_binary_sub,
    power,
    ratio,
    reciprocal,
    seq_root,
    set_small_values_zero,
    set_value,
    sign_flip,
)

__all__ = [
    "copy_rows",
    "trunc_zero_origin",
    "col_reverse",
    "row_reverse",
    "print_host",
    "slice_matrix",
    "copy_upper_triangular",
    "initialize_diagonal_matrix",
    "get_diagonal_inverse_matrix",
    "get_l2_norm",
    "power",
    "seq_root",
    "set_small_values_zero",
    "reciprocal",
    "set_value",
    "ratio",
    "argmax",
    "sign_flip",
    "matrix_vector_binary_mult",
    "matrix_vector_binary_mult_skip_zero",
    "matrix_vector_binary_div",
    "matrix_vector_binary_div_skip_zero",
    "matrix_vector_binary_add",
    "matrix_vector_binary_sub",
]
