"""Matrix math helpers (reference cpp/include/raft/matrix/math.hpp:38-496).

Elementwise power/sqrt/reciprocal families, ratio, argmax-per-column,
PCA sign stabilization, and the row/column broadcast binary ops.
"""

from __future__ import annotations

import jax.numpy as jnp

from raft_tpu.core.handle import takes_handle


@takes_handle
def power(inp: jnp.ndarray, scalar: float | None = None) -> jnp.ndarray:
    """Elementwise square, optionally scaled: ``scalar * x * x``
    (reference math.hpp:46,95 — "power" means x*x there)."""
    out = inp * inp
    if scalar is not None:
        out = scalar * out
    return out


@takes_handle
def seq_root(inp: jnp.ndarray, scalar: float = 1.0, set_neg_zero: bool = False) -> jnp.ndarray:
    """Elementwise sqrt of ``scalar * x`` (reference math.hpp:113-175
    ``seqRoot``); ``set_neg_zero`` clamps negatives to 0 first like the
    reference's guarded variant."""
    x = scalar * inp
    if set_neg_zero:
        x = jnp.where(x < 0, 0.0, x)
    return jnp.sqrt(x)


@takes_handle
def set_small_values_zero(inp: jnp.ndarray, thres: float = 1e-15) -> jnp.ndarray:
    """Zero out entries with |x| <= thres (reference math.hpp:182,209)."""
    return jnp.where(jnp.abs(inp) <= thres, 0.0, inp)


@takes_handle
def reciprocal(
    inp: jnp.ndarray,
    scalar: float = 1.0,
    setzero: bool = False,
    thres: float = 1e-15,
) -> jnp.ndarray:
    """Elementwise ``scalar / x`` (reference math.hpp:228-294); with
    ``setzero`` entries with |x| < thres produce 0 instead of inf."""
    if setzero:
        small = jnp.abs(inp) < thres
        return jnp.where(small, 0.0, scalar / jnp.where(small, 1.0, inp))
    return scalar / inp


@takes_handle
def set_value(inp: jnp.ndarray, scalar: float) -> jnp.ndarray:
    """Fill with a scalar (reference math.hpp:301 ``setValue``)."""
    return jnp.full_like(inp, scalar)


@takes_handle
def ratio(inp: jnp.ndarray) -> jnp.ndarray:
    """Each element divided by the sum of all (reference math.hpp:318)."""
    return inp / jnp.sum(inp)


@takes_handle
def argmax(inp: jnp.ndarray) -> jnp.ndarray:
    """Row index of the max per column (reference math.hpp:343)."""
    return jnp.argmax(inp, axis=0)


@takes_handle
def sign_flip(inp: jnp.ndarray) -> jnp.ndarray:
    """PCA sign stabilization (reference math.hpp:357 ``signFlip``): for each
    column, if the entry with the largest |value| is negative, negate the
    column."""
    idx = jnp.argmax(jnp.abs(inp), axis=0)
    pivot = inp[idx, jnp.arange(inp.shape[1])]
    return jnp.where(pivot[None, :] < 0, -inp, inp)


def _bcast(vec: jnp.ndarray, along_rows: bool) -> jnp.ndarray:
    return vec[None, :] if along_rows else vec[:, None]


@takes_handle
def matrix_vector_binary_mult(data, vec, bcast_along_rows: bool = True):
    """(reference math.hpp:363)"""
    return data * _bcast(vec, bcast_along_rows)


@takes_handle
def matrix_vector_binary_mult_skip_zero(data, vec, bcast_along_rows: bool = True):
    """Multiply, leaving entries unchanged where vec == 0
    (reference math.hpp:384)."""
    v = _bcast(vec, bcast_along_rows)
    return jnp.where(v == 0, data, data * v)


@takes_handle
def matrix_vector_binary_div(data, vec, bcast_along_rows: bool = True):
    """(reference math.hpp:410)"""
    return data / _bcast(vec, bcast_along_rows)


@takes_handle
def matrix_vector_binary_div_skip_zero(data, vec, bcast_along_rows: bool = True,
                                       return_zero: bool = False):
    """Divide, skipping (or zeroing) where vec == 0 (reference math.hpp:431)."""
    v = _bcast(vec, bcast_along_rows)
    safe = jnp.where(v == 0, 1.0, v)
    if return_zero:
        return jnp.where(v == 0, 0.0, data / safe)
    return jnp.where(v == 0, data, data / safe)


@takes_handle
def matrix_vector_binary_add(data, vec, bcast_along_rows: bool = True):
    """(reference math.hpp:476)"""
    return data + _bcast(vec, bcast_along_rows)


@takes_handle
def matrix_vector_binary_sub(data, vec, bcast_along_rows: bool = True):
    """(reference math.hpp:497)"""
    return data - _bcast(vec, bcast_along_rows)
