"""Matrix manipulation (reference cpp/include/raft/matrix/matrix.hpp:49-284
dispatching into detail/matrix.cuh).  Gathers/slices/reverses lower to XLA
gather/slice/rev ops."""

from __future__ import annotations

import jax.numpy as jnp

from raft_tpu.core.error import expects

from raft_tpu.core.handle import takes_handle


@takes_handle
def copy_rows(inp: jnp.ndarray, indices: jnp.ndarray) -> jnp.ndarray:
    """Gather rows by index (reference matrix.hpp:50 ``copyRows``)."""
    return jnp.take(inp, indices, axis=0)


@takes_handle
def trunc_zero_origin(inp: jnp.ndarray, n_rows: int, n_cols: int) -> jnp.ndarray:
    """Top-left submatrix copy (reference matrix.hpp:87 ``truncZeroOrigin``)."""
    expects(
        n_rows <= inp.shape[0] and n_cols <= inp.shape[1],
        "trunc_zero_origin: target (%d, %d) exceeds source (%d, %d)",
        n_rows, n_cols, inp.shape[0], inp.shape[1],
    )
    return inp[:n_rows, :n_cols]


@takes_handle
def col_reverse(inp: jnp.ndarray) -> jnp.ndarray:
    """Reverse column order (reference matrix.hpp:113 ``colReverse``)."""
    return inp[:, ::-1]


@takes_handle
def row_reverse(inp: jnp.ndarray) -> jnp.ndarray:
    """Reverse row order (reference matrix.hpp:143 ``rowReverse``)."""
    return inp[::-1, :]


@takes_handle
def print_host(inp, h_separator: str = ";", v_separator: str = ",") -> str:
    """Format like the reference's host printer (matrix.hpp:199
    ``printHost``); returns the string instead of writing stdout."""
    import numpy as np

    arr = np.asarray(inp)
    rows = [v_separator.join(str(v) for v in row) for row in arr]
    return h_separator.join(rows)


@takes_handle
def slice_matrix(inp: jnp.ndarray, x1: int, y1: int, x2: int, y2: int) -> jnp.ndarray:
    """Submatrix [x1:x2, y1:y2] (reference matrix.hpp:223 ``sliceMatrix``)."""
    expects(
        0 <= x1 < x2 <= inp.shape[0] and 0 <= y1 < y2 <= inp.shape[1],
        "slice_matrix: invalid bounds (%d,%d)-(%d,%d) for shape (%d,%d)",
        x1, y1, x2, y2, inp.shape[0], inp.shape[1],
    )
    return inp[x1:x2, y1:y2]


@takes_handle
def copy_upper_triangular(src: jnp.ndarray) -> jnp.ndarray:
    """Copy the strictly-upper+diagonal part into the k×k output where
    k = min(rows, cols) (reference matrix.hpp:245 ``copyUpperTriangular``)."""
    k = min(src.shape[0], src.shape[1])
    return jnp.triu(src[:k, :k])


@takes_handle
def initialize_diagonal_matrix(vec: jnp.ndarray) -> jnp.ndarray:
    """Diagonal matrix from vector (reference matrix.hpp:259)."""
    return jnp.diag(vec)


@takes_handle
def get_diagonal_inverse_matrix(mat: jnp.ndarray) -> jnp.ndarray:
    """Invert the diagonal in place (reference matrix.hpp:272); off-diagonal
    entries are preserved, zeros on the diagonal invert to 0 like the
    reference's guarded kernel."""
    d = jnp.diagonal(mat)
    inv = jnp.where(d != 0, 1.0 / jnp.where(d != 0, d, 1.0), 0.0)
    n = mat.shape[0]
    return mat.at[jnp.arange(n), jnp.arange(n)].set(inv)


@takes_handle
def get_l2_norm(mat: jnp.ndarray) -> jnp.ndarray:
    """Frobenius norm (reference matrix.hpp:284 ``getL2Norm``)."""
    return jnp.sqrt(jnp.sum(mat * mat))
