"""Device RNG with the reference's distribution set.

Reference: cpp/include/raft/random/rng.hpp — ``Rng`` class (:66) wrapping
three counter-based device generators (Philox/TapsKiss99,
random/detail/rng_impl.cuh:130,177,242) with distributions
uniform/uniformInt/normal/normalInt/normalTable/fill/bernoulli/
scaled_bernoulli/gumbel/lognormal/logistic/exponential/rayleigh/laplace
(:113-347), weighted ``sampleWithoutReplacement`` (:350), and
``affine_transform_params`` (:96).

TPU redesign: JAX's splittable threefry counter-based PRNG plays the Philox
role (same design family: stateless, counter-based, reproducible across
devices).  The Rng object keeps the reference's stateful-object ergonomics
by splitting its key on every draw.  Weighted sampling without replacement
uses the Gumbel-top-k trick — an exact reformulation that turns the
reference's sort-by-perturbed-weight kernel into one vectorized top-k.
"""

from __future__ import annotations

import enum
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects


class GeneratorType(enum.IntEnum):
    """(reference rng.hpp:34 GenPhilox/GenTaps/GenKiss99; all map to
    threefry on TPU — kept so consumer configs round-trip)."""

    GenPhilox = 0
    GenTaps = 1
    GenKiss99 = 2


class Rng:
    """Stateful-feeling wrapper over JAX's functional PRNG
    (reference rng.hpp:66)."""

    def __init__(self, seed: int, gtype: GeneratorType = GeneratorType.GenPhilox):
        self.gtype = gtype
        self._key = jax.random.PRNGKey(seed)

    def seed(self, s: int) -> None:
        """Re-seed (reference rng.hpp:83)."""
        self._key = jax.random.PRNGKey(s)

    def _next(self) -> jax.Array:
        self._key, sub = jax.random.split(self._key)
        return sub

    def affine_transform_params(self, n: int) -> Tuple[int, int]:
        """Random (a, b) for the affine index transform ``a*i + b (mod n)``
        with a coprime to n (reference rng.hpp:96)."""
        import math

        k1, k2 = jax.random.split(self._next())
        a = int(jax.random.randint(k1, (), 1, max(n, 2)))
        while math.gcd(a, n) != 1:
            a = (a + 1) % n or 1
        b = int(jax.random.randint(k2, (), 0, max(n, 1)))
        return a, b

    # ------------------------------------------------------------------ #
    # distributions (reference rng.hpp:113-347)
    # ------------------------------------------------------------------ #
    def uniform(self, shape, start=0.0, end=1.0, dtype=jnp.float32):
        """(reference rng.hpp:113)"""
        return jax.random.uniform(self._next(), shape, dtype=dtype, minval=start, maxval=end)

    def uniform_int(self, shape, start, end, dtype=jnp.int32):
        """Integers in [start, end) (reference rng.hpp:118)."""
        return jax.random.randint(self._next(), shape, start, end, dtype=dtype)

    def normal(self, shape, mu=0.0, sigma=1.0, dtype=jnp.float32):
        """(reference rng.hpp:136)"""
        return mu + sigma * jax.random.normal(self._next(), shape, dtype=dtype)

    def normal_int(self, shape, mu, sigma, dtype=jnp.int32):
        """Rounded normal (reference rng.hpp:141)."""
        vals = mu + sigma * jax.random.normal(self._next(), shape, dtype=jnp.float32)
        return jnp.round(vals).astype(dtype)

    def normal_table(self, n_rows, mu_vec, sigma_vec=None, sigma=1.0, dtype=jnp.float32):
        """Table of normals: row i ~ N(mu_vec, sigma_vec) per column
        (reference rng.hpp:168 ``normalTable``)."""
        n_cols = mu_vec.shape[0]
        z = jax.random.normal(self._next(), (n_rows, n_cols), dtype=dtype)
        s = sigma_vec[None, :] if sigma_vec is not None else sigma
        return mu_vec[None, :] + s * z

    def fill(self, shape, val, dtype=jnp.float32):
        """(reference rng.hpp:189)"""
        return jnp.full(shape, val, dtype=dtype)

    def bernoulli(self, shape, prob, dtype=jnp.bool_):
        """P(True) = prob (reference rng.hpp:207)."""
        return jax.random.bernoulli(self._next(), prob, shape).astype(dtype)

    def scaled_bernoulli(self, shape, prob, scale, dtype=jnp.float32):
        """±scale with P(+scale) = prob (reference rng.hpp:223: the kernel
        emits ``val > prob ? -scale : scale``, so +scale when u <= prob)."""
        u = jax.random.uniform(self._next(), shape, dtype=dtype)
        return jnp.where(u > prob, -scale, scale).astype(dtype)

    def gumbel(self, shape, mu=0.0, beta=1.0, dtype=jnp.float32):
        """(reference rng.hpp:240)"""
        return mu + beta * jax.random.gumbel(self._next(), shape, dtype=dtype)

    def lognormal(self, shape, mu=0.0, sigma=1.0, dtype=jnp.float32):
        """exp(N(mu, sigma)) (reference rng.hpp:256)."""
        return jnp.exp(self.normal(shape, mu, sigma, dtype=dtype))

    def logistic(self, shape, mu=0.0, scale=1.0, dtype=jnp.float32):
        """(reference rng.hpp:272)"""
        return mu + scale * jax.random.logistic(self._next(), shape, dtype=dtype)

    def exponential(self, shape, lam=1.0, dtype=jnp.float32):
        """Rate-lambda exponential (reference rng.hpp:287)."""
        return jax.random.exponential(self._next(), shape, dtype=dtype) / lam

    def rayleigh(self, shape, sigma=1.0, dtype=jnp.float32):
        """(reference rng.hpp:302)"""
        u = jax.random.uniform(self._next(), shape, dtype=dtype)
        return sigma * jnp.sqrt(-2.0 * jnp.log1p(-u))

    def laplace(self, shape, mu=0.0, scale=1.0, dtype=jnp.float32):
        """(reference rng.hpp:318)"""
        return jax.random.laplace(self._next(), shape, dtype=dtype) * scale + mu

    # ------------------------------------------------------------------ #
    # sampling (reference rng.hpp:350)
    # ------------------------------------------------------------------ #
    def sample_without_replacement(
        self,
        items: jnp.ndarray,
        sampled_len: int,
        weights: Optional[jnp.ndarray] = None,
    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Weighted sampling without replacement via Gumbel-top-k.

        Reference (rng.hpp:350 + detail/rng_impl.cuh): perturbs each weight
        with a random draw and sorts; the Gumbel-top-k trick is the exact
        probabilistic equivalent (keys = log w + Gumbel noise; top-k keys
        are a weighted sample without replacement) and maps to one top-k op.
        Returns ``(sampled_items, sampled_indices)``.
        """
        n = items.shape[0]
        expects(
            0 < sampled_len <= n,
            "sample_without_replacement: sampled_len %d out of range (0, %d]",
            sampled_len, n,
        )
        g = jax.random.gumbel(self._next(), (n,), dtype=jnp.float32)
        if weights is not None:
            keys = jnp.log(jnp.maximum(weights.astype(jnp.float32), 1e-37)) + g
        else:
            keys = g
        _, idx = jax.lax.top_k(keys, sampled_len)
        return jnp.take(items, idx, axis=0), idx
