"""Random number generation (reference cpp/include/raft/random/rng.hpp)."""

from raft_tpu.random.rng import GeneratorType, Rng

__all__ = ["Rng", "GeneratorType"]
