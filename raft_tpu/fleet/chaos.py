"""Fleet chaos harness: seeded process-level fault injection.

Extends the PR 1 fault-injection style (seeded, reproducible, typed
outcomes only) from in-process seams to PROCESS faults:

==============  ======================================================
``kill``        SIGKILL a worker mid-traffic (no goodbye, no
                snapshot), restart it after a scheduled delay — the
                crash-restart rejoin path (PR 14 recovery) under load
``hang``        freeze a worker's data plane AND heartbeats without
                killing it — only the router's lease protocol can
                notice; the worker un-hangs and must rejoin via the
                heartbeat ``rereg`` handshake
``slow_join``   the restart after a kill sleeps before building —
                a straggling rejoin stretching the degraded window
``frame``       a time window in which router→worker frames are
                dropped before send, and idempotent (search/scrape)
                response frames are garbled — both surface as typed
                :class:`CommError` and are absorbed by the router's
                retry policy.  Insert responses are never garbled:
                an insert ack is not idempotent to lose (the row is
                WAL-durable at the worker), so a chaos schedule that
                garbled acks would manufacture false double-insert
                failures rather than test real ones
``fsync_stall`` every WAL fsync at one worker sleeps — the
                acknowledge path slows, backpressure hints grow, and
                the contract under test is typed sheds, not loss
==============  ======================================================

Every schedule derives from ONE integer seed
(:meth:`ChaosSchedule.from_seed`) — any failure reproduces with the
printed seed, same as ``stress.sh faults``.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, List, Optional

from raft_tpu.core.error import CommError
from raft_tpu.fleet import protocol

__all__ = ["FrameFaults", "ChaosSchedule", "ChaosHarness"]


class FrameFaults:
    """Transport wrapper injecting frame faults inside armed windows.
    Drops happen BEFORE the frame is sent (a dropped insert never
    reached the worker, so the router's retry is duplicate-safe);
    garbles corrupt the RESPONSE of idempotent paths only (module
    doc)."""

    _IDEMPOTENT = ("/search", "/metrics", "/healthz", "/statusz",
                   "/debug/snapshot", "/debug/trace", "/debug/flight",
                   "/info")

    def __init__(self, seed: int, base=protocol.http_transport,
                 clock: Callable[[], float] = time.monotonic):
        self._rng = random.Random(seed)
        self._base = base
        self._clock = clock
        self._lock = threading.Lock()
        self._until = 0.0
        self._drop_p = 0.0
        self._garble_p = 0.0
        self.injected = {"drop": 0, "garble": 0}

    def arm(self, *, drop_p: float, garble_p: float,
            duration_s: float) -> None:
        with self._lock:
            self._drop_p = float(drop_p)
            self._garble_p = float(garble_p)
            self._until = self._clock() + float(duration_s)

    def disarm(self) -> None:
        with self._lock:
            self._until = 0.0

    def __call__(self, method: str, url: str, body, timeout: float,
                 headers=None):
        with self._lock:
            active = self._clock() < self._until
            drop = active and self._rng.random() < self._drop_p
            garble = active and self._rng.random() < self._garble_p
        if drop:
            with self._lock:
                self.injected["drop"] += 1
            raise CommError("chaos: injected frame drop (%s %s)"
                            % (method, url))
        if headers:
            status, data = self._base(method, url, body, timeout,
                                      headers)
        else:
            status, data = self._base(method, url, body, timeout)
        if garble and any(url.endswith(p) or ("%s?" % p) in url
                          for p in self._IDEMPOTENT):
            with self._lock:
                self.injected["garble"] += 1
            # flip bytes in the middle of the frame: json.loads fails,
            # protocol raises a typed CommError, the router retries
            data = bytes(b ^ 0xFF for b in data[:16]) + data[16:]
        return status, data


class ChaosSchedule:
    """A seeded, sorted list of timed fault events."""

    def __init__(self, events: List[dict]):
        self.events = sorted(events, key=lambda e: e["at"])

    @classmethod
    def from_seed(cls, seed: int, *, duration_s: float,
                  n_workers: int,
                  kinds=("kill", "hang", "slow_join", "frame",
                         "fsync_stall")) -> "ChaosSchedule":
        rng = random.Random(seed)
        events: List[dict] = []
        # one headline process fault per run (kill / hang /
        # slow_join), placed early enough that recovery is observable
        # before the run ends, plus 1-2 transport/persist faults
        process_kinds = [k for k in ("kill", "hang", "slow_join")
                         if k in kinds]
        headline = rng.choice(process_kinds) if process_kinds else None
        at = (0.15 + 0.25 * rng.random()) * duration_s
        w = rng.randrange(n_workers)
        if headline == "hang":
            events.append({"at": at, "kind": "hang", "worker": w,
                           "duration_s": min(2.0,
                                             0.4 * duration_s)})
        elif headline in ("kill", "slow_join"):
            events.append({
                "at": at, "kind": "kill", "worker": w,
                "restart_after_s": 0.2 + 0.3 * rng.random(),
                "slow_join_s": (0.5 + 0.5 * rng.random()
                                if headline == "slow_join" else 0.0)})
        if "frame" in kinds:
            events.append({
                "at": 0.1 + 0.5 * rng.random() * duration_s,
                "kind": "frame",
                "drop_p": 0.05 + 0.15 * rng.random(),
                "garble_p": 0.05 + 0.10 * rng.random(),
                "duration_s": 0.3 + 0.3 * duration_s * rng.random()})
        if "fsync_stall" in kinds and rng.random() < 0.5:
            events.append({
                "at": 0.1 + 0.6 * rng.random() * duration_s,
                "kind": "fsync_stall",
                "worker": rng.randrange(n_workers),
                "stall_s": 0.01 + 0.04 * rng.random(),
                "duration_s": 0.2 + 0.2 * duration_s})
        return cls(events)


class ChaosHarness:
    """Applies a :class:`ChaosSchedule` against a live
    :class:`~raft_tpu.fleet.supervisor.Fleet` on a background thread;
    owns the restarts its kills require (autoheal stays off during a
    schedule so restart timing — including slow joins — is the
    schedule's, not a healer's)."""

    def __init__(self, fleet, schedule: ChaosSchedule,
                 frame_faults: Optional[FrameFaults] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.fleet = fleet
        self.schedule = schedule
        self.frame_faults = frame_faults
        self._clock = clock
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.applied: List[dict] = []

    def start(self) -> "ChaosHarness":
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="raft-tpu-fleet-chaos")
        self._thread.start()
        return self

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)

    def stop(self) -> None:
        self._stop.set()
        self.join(10.0)
        if self.frame_faults is not None:
            self.frame_faults.disarm()

    def _run(self) -> None:
        t0 = self._clock()
        # expand kills into (kill, restart) pairs up front so the
        # timeline stays a single sorted pass
        timeline: List[dict] = []
        for ev in self.schedule.events:
            timeline.append(ev)
            if ev["kind"] == "kill":
                timeline.append({
                    "at": ev["at"] + ev.get("restart_after_s", 0.3),
                    "kind": "restart", "worker": ev["worker"],
                    "slow_join_s": ev.get("slow_join_s", 0.0)})
        for ev in sorted(timeline, key=lambda e: e["at"]):
            while not self._stop.is_set():
                delay = ev["at"] - (self._clock() - t0)
                if delay <= 0:
                    break
                time.sleep(min(0.05, delay))
            if self._stop.is_set():
                return
            try:
                self._apply(ev)
                self.applied.append(dict(ev))
            except Exception as e:  # noqa: BLE001 — chaos must not
                # crash the driver; a failed injection is recorded
                self.applied.append(dict(ev, failed=str(e)))

    def _apply(self, ev: dict) -> None:
        kind = ev["kind"]
        wid = "w%d" % ev["worker"] if "worker" in ev else None
        if kind == "kill":
            self.fleet.kill(wid)
        elif kind == "restart":
            self.fleet.restart(wid,
                               slow_join_s=ev.get("slow_join_s", 0.0))
        elif kind == "hang":
            self._worker_chaos(wid, {"fault": "hang",
                                     "duration_s": ev["duration_s"]})
        elif kind == "frame":
            if self.frame_faults is not None:
                self.frame_faults.arm(drop_p=ev["drop_p"],
                                      garble_p=ev["garble_p"],
                                      duration_s=ev["duration_s"])
        elif kind == "fsync_stall":
            self._worker_chaos(wid, {"fault": "fsync_stall",
                                     "stall_s": ev["stall_s"],
                                     "duration_s": ev["duration_s"]})

    def _worker_chaos(self, wid: str, payload: dict) -> None:
        reg = self.fleet.router.registry().get(wid) or {}
        port = int(reg.get("data_port", 0) or 0)
        if port:
            protocol.post_json("http://127.0.0.1:%d/chaos" % port,
                               payload, timeout=5.0)
