"""Process-level serving fleet: router + worker processes.

Every capability below this package — ANN serving, WAL-durable state,
the ops plane and sentinel — lives happily in one Python process; this
package is the fault-domain layer that keeps *tenants* alive when a
*process* dies (docs/FAULT_MODEL.md "Fleet fault domains").  The
pieces:

- :mod:`raft_tpu.fleet.protocol` — the JSON-over-HTTP wire format,
  typed-error round-tripping, rendezvous placement, top-k merge.
- :mod:`raft_tpu.fleet.router` — the front-end router (stdlib-only,
  no jax: the ``ops-jax-ban`` lint covers it): placement, admission,
  retry/hedging, shard fan-out + merge, heartbeat leases with typed
  eviction, and the aggregated ``/fleet/metrics`` + ``/fleet/healthz``
  scrape surface.
- :mod:`raft_tpu.fleet.worker` — the worker subprocess entrypoint:
  builds (or crash-restores, PR 14) its service, binds its data plane
  and ops plane on ephemeral ports, registers with the router, and
  heartbeats.
- :mod:`raft_tpu.fleet.supervisor` — spawns/kills/restarts worker
  processes, rolling restart/drain choreography, autoheal.
- :mod:`raft_tpu.fleet.chaos` — the seeded process-fault harness
  (SIGKILL, hang, slow-join, dropped/garbled frames, fsync stall)
  driven from ``tools/loadgen.py --fleet``.

The hierarchical host-group decomposition from HiCCL (PAPERS.md) that
shapes the intra-mesh merge since PR 7 is lifted one level here:
shard-per-worker indexes with a router-side top-k merge.
"""

from raft_tpu.fleet.router import Router
from raft_tpu.fleet.supervisor import Fleet, WorkerSpec

__all__ = ["Router", "Fleet", "WorkerSpec"]
