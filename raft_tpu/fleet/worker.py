"""Fleet worker: one process, one full session's worth of service.

Launched by the supervisor as ``python -m raft_tpu.fleet.worker
<spec.json>``.  The spec (written by :class:`raft_tpu.fleet.supervisor
.Fleet`) tells the worker everything it needs to build — or
crash-restore — its shard deterministically:

- **Build vs rejoin.**  A fresh worker synthesizes the fleet dataset
  from ``(seed, index_rows, dim)``, takes its shard
  (``full[shard_index::shard_count]``), builds the IVF index and
  starts serving.  A RESTARTED worker finds its persist dir non-empty
  and rebuilds from snapshot+WAL instead (PR 14 recovery) — every
  acknowledged insert survives the kill; the replay depth and wall
  time are reported through the registration handshake so the
  router's ``rejoin_lag`` sentinel rule can judge them.
- **Ephemeral ports.**  Both the data plane and the ops plane bind
  port 0; the ACTUAL bound ports travel to the router in the
  ``/register`` payload (nothing about a worker's address is
  preconfigured).
- **Shard-local → global ids.**  ``ivf_flat_build`` assigns
  positional row ids, so a shard's base hits come back shard-local;
  the worker owns the translation table (global id of local row ``j``
  is ``shard_index + j * shard_count``) and translates before
  replying — the router merges already-global ids and stays
  data-blind.  Inserted ids are global by contract (``>=
  index_rows``) and pass through untranslated; auto-compaction is
  disabled in sharded mode so the base/delta id split cannot shift
  under the table.
- **Chaos hooks.**  ``POST /chaos`` arms worker-side faults (hang,
  fsync stall) used by :mod:`raft_tpu.fleet.chaos`; a hang freezes
  both the data plane and the heartbeat thread, so the router's lease
  protocol — not any in-process cooperation — is what notices.

Clean shutdown (SIGTERM or ``POST /admin/shutdown``) drains in-flight
requests and lands a final snapshot before exiting — the quiesce →
snapshot half of the rolling-restart choreography.  SIGKILL is the
crash path: no goodbye, WAL is the contract.
"""

from __future__ import annotations

import http.server
import json
import os
import signal
import sys
import threading
import time
import urllib.parse
from typing import Callable, Dict, Optional

from raft_tpu.core import flight
from raft_tpu.fleet import protocol, tracing

__all__ = ["FleetWorker", "main"]


def _synth(index_rows: int, dim: int, seed: int, clusters: int):
    """The fleet dataset: same shape as tools/loadgen.py synth_data —
    deterministic in the spec fields, so every worker (and the test
    harness computing ground truth) regenerates identical bytes."""
    import numpy as np

    rng = np.random.default_rng(seed)
    if clusters <= 0:
        return rng.standard_normal((index_rows, dim)).astype(np.float32)
    centers = rng.standard_normal((clusters, dim)).astype(np.float32)
    assign = rng.integers(0, clusters, index_rows)
    return (centers[assign] + 0.3 * rng.standard_normal(
        (index_rows, dim))).astype(np.float32)


class FleetWorker:
    """Module-doc worker: owns the service, the data plane, the ops
    plane and the heartbeat thread for one fleet member."""

    def __init__(self, spec: dict, *,
                 clock: Callable[[], float] = time.monotonic):
        self.spec = dict(spec)
        self.worker_id = str(spec["worker_id"])
        self.generation = int(spec.get("generation", 0))
        self.mode = str(spec.get("mode", "sharded"))
        self.shard_index = int(spec.get("shard_index", 0))
        self.shard_count = int(spec.get("shard_count", 1))
        self.router_url = str(spec["router_url"])
        self.lease_interval_s = float(spec.get("lease_interval_s", 0.5))
        self._clock = clock
        self._stop = threading.Event()
        self._hang_until = 0.0
        self._svc = None
        self._plane = None
        self._server = None
        self._server_thread = None
        self._beat_thread = None
        self._data_port: Optional[int] = None
        self._restore: Dict[str, object] = {}
        self._base_rows = 0
        self._global_ids = None
        self._lock = threading.Lock()
        # NTP-style clock alignment vs the router, estimated over the
        # register/heartbeat round trip and reported on the next beat
        # (docs/OBSERVABILITY.md "Fleet tracing")
        self._clock_offset: Optional[float] = None
        self._clock_rtt: Optional[float] = None

    # ------------------------------------------------------------------ #
    # build / restore
    # ------------------------------------------------------------------ #
    def build(self) -> None:
        import numpy as np

        from raft_tpu.serve import ANNService
        from raft_tpu.serve.opsplane import OpsPlane

        spec = self.spec
        index_rows = int(spec["index_rows"])
        dim = int(spec["dim"])
        k = int(spec["k"])
        seed = int(spec.get("seed", 0))
        persist_dir = spec.get("persist_dir")
        self._global_ids = np.arange(self.shard_index, index_rows,
                                     self.shard_count, dtype=np.int64)
        self._base_rows = int(self._global_ids.shape[0])
        has_state = bool(
            persist_dir and os.path.isdir(persist_dir)
            and any(os.scandir(persist_dir)))
        svc_opts = dict(spec.get("service_opts") or {})
        svc_opts.setdefault("name", "ann_%s" % self.worker_id)
        # compaction would fold global-id delta rows into positional
        # base slots and shift the translation table (module doc)
        svc_opts.setdefault("compact_rows", 0)
        if persist_dir:
            svc_opts.setdefault("persist_dir", persist_dir)
            svc_opts.setdefault(
                "persist_fsync", spec.get("persist_fsync", "always"))
            svc_opts.setdefault(
                "snapshot_interval_s",
                float(spec.get("snapshot_interval_s", 2.0)))
        t0 = self._clock()
        if has_state:
            # crash-restart rejoin: snapshot + WAL replay owns the
            # state; the synthetic build is skipped entirely
            svc = ANNService(None, k=k, **svc_opts)
        else:
            from raft_tpu.spatial.ann import IVFFlatParams, \
                ivf_flat_build

            full = _synth(index_rows, dim, seed,
                          int(spec.get("clusters", 0)))
            local = full[self.shard_index::self.shard_count]
            nlist = int(spec.get("nlist")
                        or max(8, min(4096, int(len(local) ** 0.5))))
            params = IVFFlatParams(
                nlist=nlist, nprobe=int(spec.get("nprobe", 8)))
            index = ivf_flat_build(local, params,
                                   train_rows=spec.get("train_rows"))
            svc = ANNService(index, k=k, **svc_opts)
        # restore_s is what feeds the sentinel's ``rejoin_lag``
        # ms-per-record judgement: it must cover snapshot load + WAL
        # replay only — warmup is compile time, constant in the
        # journal depth, and would swamp the ratio on shallow replays
        restore_s = max(0.0, self._clock() - t0)
        t1 = self._clock()
        svc.warmup()
        warmup_s = max(0.0, self._clock() - t1)
        self._svc = svc
        st = self._persist_stats()
        self._restore = {
            "restored": has_state,
            "restore_s": round(restore_s, 6),
            "warmup_s": round(warmup_s, 6),
            "replayed_records": int(st.get("replayed_records", 0) or 0),
            "wal_records": int(st.get("wal_records", 0) or 0),
            "snapshot_seq": int(st.get("snapshot_seq", 0) or 0),
        }
        self._plane = OpsPlane(
            services={svc.name: svc}, port=0,
            sentinel=bool(spec.get("sentinel", True)))

    def _persist_stats(self) -> dict:
        persist = getattr(self._svc, "_persist", None)
        if persist is None:
            return {}
        try:
            return persist.stats()
        except Exception:
            return {}

    # ------------------------------------------------------------------ #
    # data plane
    # ------------------------------------------------------------------ #
    def start_server(self) -> None:
        worker = self

        class _Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *args):  # noqa: D102 — metrics only
                pass

            def do_GET(self):
                worker._handle(self, "GET")

            def do_POST(self):
                worker._handle(self, "POST")

        host = str(self.spec.get("host", "127.0.0.1"))
        self._server = http.server.ThreadingHTTPServer(
            (host, 0), _Handler)
        self._server.daemon_threads = True
        self._data_port = int(self._server.server_address[1])
        self._server_thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="raft-tpu-fleet-%s" % self.worker_id)
        self._server_thread.start()

    def _handle(self, handler, method: str) -> None:
        self._maybe_hang()
        path, _, query = handler.path.partition("?")
        try:
            body = {}
            if method == "POST":
                length = int(handler.headers.get("Content-Length", 0))
                raw = handler.rfile.read(length) if length else b"{}"
                body = json.loads(raw.decode("utf-8"))
            elif query:
                body = {k: v[-1] for k, v in
                        urllib.parse.parse_qs(query).items()}
            route = {
                ("GET", "/info"): self._ep_info,
                ("GET", "/debug/trace"): self._ep_trace,
                ("POST", "/debug/flight"): self._ep_flight,
                ("POST", "/search"): self._ep_search,
                ("POST", "/insert"): self._ep_insert,
                ("POST", "/admin/shutdown"): self._ep_shutdown,
                ("POST", "/chaos"): self._ep_chaos,
            }.get((method, path))
            if route is None:
                self._reply(handler, 404, {"error": "NotFound",
                                           "message": path})
                return
            status, payload = route(body)
        except Exception as e:  # noqa: BLE001 — typed on the wire
            status, payload = protocol.error_response(e)
        self._reply(handler, status, payload)

    @staticmethod
    def _reply(handler, status: int, payload: dict) -> None:
        try:
            data = json.dumps(payload).encode("utf-8")
            handler.send_response(status)
            handler.send_header("Content-Type", "application/json")
            handler.send_header("Content-Length", str(len(data)))
            handler.end_headers()
            handler.wfile.write(data)
        except (BrokenPipeError, ConnectionError, OSError):
            pass  # client gone: its router-side retry owns the outcome

    def _maybe_hang(self) -> None:
        # chaos hang: freeze handler threads until the fault expires
        # (time.sleep, not a busy loop — the process must look wedged,
        # not hot)
        while not self._stop.is_set():
            with self._lock:
                remaining = self._hang_until - self._clock()
            if remaining <= 0:
                return
            time.sleep(min(0.05, remaining))

    # ------------------------------------------------------------------ #
    # endpoints
    # ------------------------------------------------------------------ #
    def info(self) -> dict:
        st = self._persist_stats()
        return {
            "worker_id": self.worker_id,
            "generation": self.generation,
            "pid": os.getpid(),
            "mode": self.mode,
            "shard_index": self.shard_index,
            "shard_count": self.shard_count,
            "data_port": self._data_port,
            "ops_port": (None if self._plane is None
                         else self._plane.port),
            "wal_seq": int(st.get("wal_seq", 0) or 0),
            "wal_records": int(st.get("wal_records", 0) or 0),
            "restore": dict(self._restore),
        }

    def _ep_info(self, body: dict):
        return 200, self.info()

    def _ep_search(self, body: dict):
        import jax.numpy as jnp
        import numpy as np

        t_in = self._clock()
        vectors = body.get("vectors")
        if not isinstance(vectors, list) or not vectors:
            return protocol.error_response(ValueError(
                "search: 'vectors' must be a non-empty list of rows"))
        q = jnp.asarray(np.asarray(vectors, dtype=np.float32))
        timeout = body.get("timeout_s")
        # propagated fleet trace context: binding it here means the
        # local Trace the batcher opens inside submit() — and with it
        # every per-process lifecycle event (admitted, batch_formed,
        # execute bracket, terminal, hedges, breaker trips recorded
        # under batch_scope) — carries the fleet trace id and lands in
        # the recorder's fleet index for /debug/trace to serve
        with flight.trace_context(protocol.parse_trace(
                body.get("trace"))):
            fut = self._svc.submit(
                q, timeout=None if timeout is None else float(timeout),
                tenant=body.get("tenant"))
        dists, ids = fut.result(
            timeout=None if timeout is None else float(timeout) + 5.0)
        dists = np.asarray(dists, dtype=np.float32)
        ids = np.asarray(ids, dtype=np.int64)
        if self.mode == "sharded" and self.shard_count > 1:
            local = (ids >= 0) & (ids < self._base_rows)
            ids = ids.copy()
            ids[local] = self._global_ids[ids[local]]
        # server_seconds lets the router split its RPC wall time into
        # in-worker handling vs network residual (fleet_rpc_recv span)
        return 200, {"worker_id": self.worker_id,
                     "distances": dists.tolist(),
                     "ids": ids.tolist(),
                     "server_seconds": round(
                         max(0.0, self._clock() - t_in), 6)}

    def _ep_trace(self, body: dict):
        fid = body.get("id")
        if not fid:
            return protocol.error_response(ValueError(
                "debug/trace: 'id' query parameter is required"))
        return 200, tracing.local_payload(
            str(fid), worker_id=self.worker_id,
            generation=self.generation, clock=self._clock)

    def _ep_flight(self, body: dict):
        # remote toggle for THIS process's flight recording — the
        # fleet_trace_overhead bench arms its A/B on one warmed fleet
        # (router toggles itself locally; workers need the RPC)
        on = bool(body.get("on", True))
        flight.set_enabled(on)
        return 200, {"worker_id": self.worker_id, "flight_enabled": on}

    def _ep_insert(self, body: dict):
        import numpy as np

        t_in = self._clock()
        ids = body.get("ids")
        vectors = body.get("vectors")
        if not isinstance(ids, list) or not isinstance(vectors, list) \
                or len(ids) != len(vectors) or not ids:
            return protocol.error_response(ValueError(
                "insert: 'ids' and 'vectors' must be equal-length "
                "non-empty lists"))
        id_arr = np.asarray(ids, dtype=np.int64)
        index_rows = int(self.spec["index_rows"])
        if self.mode == "sharded" and int(id_arr.min()) < index_rows:
            # global-id contract (module doc): an insert id below the
            # base row count would collide with the translation table
            return protocol.error_response(ValueError(
                "insert: global ids must be >= index_rows=%d (got "
                "min=%d)" % (index_rows, int(id_arr.min()))))
        acked = self._svc.insert(
            id_arr, np.asarray(vectors, dtype=np.float32))
        st = self._persist_stats()
        return 200, {"worker_id": self.worker_id, "acked": int(acked),
                     "wal_seq": int(st.get("wal_seq", 0) or 0),
                     "server_seconds": round(
                         max(0.0, self._clock() - t_in), 6)}

    def _ep_shutdown(self, body: dict):
        # quiesce → snapshot half of the drain choreography; the reply
        # is sent before the exit so the supervisor sees the ack
        snapshot = bool(body.get("snapshot", True))
        threading.Thread(target=self._shutdown, args=(snapshot,),
                         daemon=True,
                         name="raft-tpu-fleet-%s-shutdown"
                         % self.worker_id).start()
        return 200, {"worker_id": self.worker_id, "stopping": True,
                     "snapshot": snapshot}

    def _ep_chaos(self, body: dict):
        fault = str(body.get("fault", ""))
        duration = float(body.get("duration_s", 0.5))
        if fault == "hang":
            with self._lock:
                self._hang_until = self._clock() + duration
        elif fault == "unhang":
            with self._lock:
                self._hang_until = 0.0
        elif fault == "fsync_stall":
            self._arm_fsync_stall(float(body.get("stall_s", 0.05)),
                                  duration)
        else:
            return protocol.error_response(ValueError(
                "chaos: unknown fault %r" % fault))
        return 200, {"worker_id": self.worker_id, "fault": fault,
                     "duration_s": duration}

    def _arm_fsync_stall(self, stall_s: float, duration: float) -> None:
        from raft_tpu.persist import wal as _wal

        deadline = self._clock() + duration
        clock = self._clock

        def _stall():
            if clock() < deadline:
                time.sleep(stall_s)
            else:
                _wal.FSYNC_HOOK = None

        _wal.FSYNC_HOOK = _stall

    # ------------------------------------------------------------------ #
    # membership
    # ------------------------------------------------------------------ #
    def register(self) -> dict:
        payload = dict(self.info())
        payload["event"] = "register"
        t0 = self._clock()
        reply = protocol.post_json(
            self.router_url.rstrip("/") + "/register", payload,
            timeout=max(5.0, 10.0 * self.lease_interval_s))
        self._note_clock(reply.get("now"), t0, self._clock())
        self.lease_interval_s = float(
            reply.get("lease_interval_s", self.lease_interval_s))
        return reply

    def _note_clock(self, router_now, t0: float, t1: float) -> None:
        """NTP-client midpoint estimate over one router exchange:
        ``offset = router_now - (t0 + t1) / 2`` (router clock = worker
        clock + offset), trustworthy to ~rtt/2.  Samples with a worse
        round trip than the retained best are rejected (a GC pause or
        accept-queue stall would skew the midpoint), but the retained
        rtt decays each beat so the estimate re-learns after a real
        shift instead of pinning a stale fast sample forever."""
        if router_now is None:
            return
        try:
            router_now = float(router_now)
        except (TypeError, ValueError):
            return
        rtt = max(0.0, t1 - t0)
        offset = router_now - 0.5 * (t0 + t1)
        with self._lock:
            best = self._clock_rtt
            if best is None or rtt <= best * 1.25 + 1e-4:
                self._clock_offset = offset
                self._clock_rtt = rtt
            else:
                self._clock_rtt = best * 1.05

    def _beat_loop(self) -> None:
        while not self._stop.wait(self.lease_interval_s):
            with self._lock:
                hung = self._hang_until > self._clock()
            if hung:
                continue  # a hung worker misses its lease — that IS
                # the fault being injected
            st = self._persist_stats()
            batcher = getattr(self._svc, "batcher", None)
            payload = {
                "worker_id": self.worker_id,
                "generation": self.generation,
                "wal_seq": int(st.get("wal_seq", 0) or 0),
                "queue_depth": (0 if batcher is None
                                else int(batcher.depth())),
            }
            with self._lock:
                if self._clock_offset is not None:
                    payload["clock_offset_s"] = round(
                        self._clock_offset, 6)
                    payload["clock_rtt_s"] = round(
                        self._clock_rtt or 0.0, 6)
            t0 = self._clock()
            try:
                reply = protocol.post_json(
                    self.router_url.rstrip("/") + "/heartbeat",
                    payload, timeout=max(2.0,
                                         4.0 * self.lease_interval_s))
            except Exception:  # noqa: BLE001 — beat again next tick;
                continue  # the router's lease timer owns eviction
            self._note_clock(reply.get("now"), t0, self._clock())
            if reply.get("rereg"):
                # the router evicted us (e.g. we hung past the lease)
                # but the process survived: rejoin without a restart
                try:
                    self.register()
                except Exception:  # noqa: BLE001 — retried next beat
                    pass

    # ------------------------------------------------------------------ #
    # lifecycle
    # ------------------------------------------------------------------ #
    def run(self) -> int:
        slow_join = float(self.spec.get("slow_join_s", 0.0))
        if slow_join > 0:
            time.sleep(slow_join)  # chaos: a straggling rejoin
        self.build()
        self.start_server()
        signal.signal(signal.SIGTERM,
                      lambda *_: self._shutdown(True))
        self.register()
        self._beat_thread = threading.Thread(
            target=self._beat_loop, daemon=True,
            name="raft-tpu-fleet-%s-beat" % self.worker_id)
        self._beat_thread.start()
        self._stop.wait()
        return 0

    def _shutdown(self, snapshot: bool) -> None:
        if self._stop.is_set():
            return
        self._stop.set()
        try:
            if self._svc is not None:
                self._svc.close(drain=True, timeout=10.0,
                                snapshot=snapshot)
        finally:
            if self._plane is not None:
                self._plane.close()
            if self._server is not None:
                self._server.shutdown()
                self._server.server_close()


def main(argv) -> int:
    if len(argv) != 1:
        print("usage: python -m raft_tpu.fleet.worker <spec.json>",
              file=sys.stderr)
        return 2
    with open(argv[0], "r", encoding="utf-8") as f:
        spec = json.load(f)
    worker = FleetWorker(spec)
    return worker.run()


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
