"""Fleet router: placement, admission, retries, leases, aggregation.

The front-end of the process fleet (docs/FAULT_MODEL.md "Fleet fault
domains").  One router process faces clients; N worker processes
(:mod:`raft_tpu.fleet.worker`) own the data.  The router:

- **Places.**  Rendezvous hashing over the *stable worker roster* for
  inserts (a row's owner never moves when a worker dies — its WAL is
  the row's home, and the rejoining worker must line back up with the
  traffic the router sends it) and over the *live* membership for
  replicated-query placement.
- **Admits.**  A global in-flight cap sheds with a typed
  :class:`ServiceOverloadError` before any dispatch; per-worker
  ``retry_after_s`` hints from worker-side sheds are honored on the
  retry path (backpressure propagates end-to-end rather than being
  flattened into blind retries).
- **Retries and hedges.**  Deadline-aware retry-with-backoff absorbs
  transient faults (dropped/garbled frames, a worker mid-restart);
  in replicated mode a straggling primary gets a hedged re-dispatch
  to the next worker in rendezvous order after ``fleet_hedge_ms``
  (the PR 8 replica machinery lifted across processes) — first
  success wins, exactly once.
- **Fans out and merges.**  Sharded queries go to every live shard;
  the router merges per-shard top-k by ``(distance, id)``.  A shard
  with no live owner within the deadline yields a PARTIAL result
  carrying an explicit ``degraded`` flag — surviving shards keep
  serving rather than failing closed.
- **Leases.**  Workers heartbeat every ``fleet_lease_interval_s``;
  ``fleet_lease_misses`` missed beats is a typed eviction (flight
  event ``fleet_eviction``, ``raft_tpu_fleet_evictions_total``).  A
  re-registration after eviction is a ``fleet_rejoin`` — its replay
  depth and restore time feed the sentinel's ``rejoin_lag`` rule.
- **Aggregates.**  ``/fleet/metrics`` is one scrape surface: every
  worker's ``/metrics`` with a ``worker=`` label injected, plus the
  router's own registry.  ``/fleet/healthz`` rolls worker health into
  ``ok`` (anything still serving) + ``degraded`` (anything wrong).
  ``/debug/snapshot`` carries a ``fleet`` section so
  ``tools/metrics_report.py --url`` works against a router unchanged.

Exactly-once accounting: every admitted request records
``fleet_admitted`` and EXACTLY one terminal ``fleet_resolved`` /
``fleet_failed`` / ``fleet_expired`` flight event — the chaos suites
assert this over the recorder, not over best-effort client counts.

No jax anywhere in this module: the router is pure host-side routing
state, statically enforced by the same ``ops-jax-ban`` lint that
covers the ops handlers (``ci/style_check.py``).
"""

from __future__ import annotations

import http.server
import itertools
import json
import re
import threading
import time
import urllib.parse
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait
from typing import Callable, Dict, List, Optional, Tuple

from raft_tpu import config
from raft_tpu.core import flight
from raft_tpu.core import metrics as _metrics
from raft_tpu.core.error import (CommError, CommTimeoutError, LogicError,
                                 RaftError, ServiceOverloadError,
                                 ServiceUnavailableError, expects)
from raft_tpu.fleet import protocol, tracing
from raft_tpu.serve import sentinel as _sentinel

__all__ = ["Router"]

_router_seq = itertools.count()

# prometheus exposition line: name{labels} value  |  name value
_PROM_LINE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{(.*)\})?\s+(\S+)\s*$")


def _counter(name: str, help: str, **labels):
    return _metrics.default_registry().counter(
        name, help=help, labels=tuple(sorted(labels))).labels(**labels)


def _gauge(name: str, help: str, **labels):
    return _metrics.default_registry().gauge(
        name, help=help, labels=tuple(sorted(labels))).labels(**labels)


def _relabel_metrics(text: str, worker: str,
                     seen_meta: set) -> List[str]:
    """Inject ``worker="<id>"`` into every sample line of a prometheus
    exposition; de-duplicate ``# HELP``/``# TYPE`` lines across
    workers (one family header per aggregated surface)."""
    out: List[str] = []
    for line in text.splitlines():
        if line.startswith("#"):
            if line not in seen_meta:
                seen_meta.add(line)
                out.append(line)
            continue
        if not line.strip():
            continue
        m = _PROM_LINE.match(line)
        if m is None:
            continue  # never forward a garbled line to a scraper
        name, _, labels, value = m.groups()
        # worker ids are operator input (hostile names included):
        # escape per the prometheus text format or the aggregated
        # surface stops round-tripping through parse_prometheus
        inner = 'worker="%s"' % _metrics._escape(worker)
        if labels:
            inner = "%s,%s" % (labels, inner)
        out.append("%s{%s} %s" % (name, inner, value))
    return out


class _WorkerHandle:
    """Router-side record of one worker process."""

    __slots__ = ("worker_id", "generation", "pid", "host", "data_port",
                 "ops_port", "shard_index", "state", "last_beat",
                 "wal_seq", "queue_depth", "registered_t", "restore",
                 "backpressure_until", "dead_t", "clock_offset",
                 "clock_rtt")

    def __init__(self, worker_id: str):
        self.worker_id = worker_id
        self.generation = 0
        self.pid = 0
        self.host = "127.0.0.1"
        self.data_port = 0
        self.ops_port = 0
        self.shard_index = 0
        self.state = "dead"  # until the first /register lands
        self.last_beat = 0.0
        self.wal_seq = 0
        self.queue_depth = 0
        self.registered_t = 0.0
        self.restore: Dict[str, object] = {}
        self.backpressure_until = 0.0
        self.dead_t = 0.0
        # NTP-style clock alignment, estimated worker-side over the
        # heartbeat ping and reported back: router_clock = worker_clock
        # + clock_offset, trustworthy to ~clock_rtt / 2
        self.clock_offset = 0.0
        self.clock_rtt = 0.0

    @property
    def data_url(self) -> str:
        return "http://%s:%d" % (self.host, self.data_port)

    @property
    def ops_url(self) -> str:
        return "http://%s:%d" % (self.host, self.ops_port)

    def public(self) -> dict:
        return {"worker_id": self.worker_id,
                "generation": self.generation, "pid": self.pid,
                "state": self.state, "shard_index": self.shard_index,
                "data_port": self.data_port, "ops_port": self.ops_port,
                "wal_seq": self.wal_seq,
                "queue_depth": self.queue_depth,
                "clock_offset_s": round(self.clock_offset, 6),
                "clock_rtt_s": round(self.clock_rtt, 6),
                "restore": dict(self.restore)}


class Router:
    """Module-doc router.  ``mode`` picks the fleet topology:
    ``"sharded"`` (disjoint shard per worker, fan-out + merge,
    single-owner inserts) or ``"replicated"`` (full index per worker,
    rendezvous placement + hedged re-dispatch, query-only)."""

    def __init__(self, *, mode: str = "sharded",
                 shard_count: Optional[int] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 lease_interval_s: Optional[float] = None,
                 lease_misses: Optional[int] = None,
                 retry_max: Optional[int] = None,
                 retry_backoff_s: Optional[float] = None,
                 hedge_ms: Optional[float] = None,
                 timeout_s: Optional[float] = None,
                 inflight_cap: Optional[int] = None,
                 sentinel: bool = True,
                 transport=protocol.http_transport,
                 clock: Callable[[], float] = time.monotonic,
                 start: bool = True):
        expects(mode in ("sharded", "replicated"),
                "Router: mode=%r not in ('sharded', 'replicated')",
                mode)
        self.mode = mode
        self.shard_count = int(shard_count or 1)
        self._host = host
        self._want_port = int(port)
        self._lease_interval = (
            config.get_float("fleet_lease_interval_s")
            if lease_interval_s is None else float(lease_interval_s))
        self._lease_misses = (
            config.get_int("fleet_lease_misses")
            if lease_misses is None else int(lease_misses))
        self._retry_max = (config.get_int("fleet_retry_max")
                           if retry_max is None else int(retry_max))
        self._retry_backoff = (
            config.get_float("fleet_retry_backoff_s")
            if retry_backoff_s is None else float(retry_backoff_s))
        self._hedge_s = ((config.get_float("fleet_hedge_ms")
                          if hedge_ms is None else float(hedge_ms))
                         / 1000.0)
        self._timeout = (config.get_float("fleet_timeout_s")
                         if timeout_s is None else float(timeout_s))
        self._inflight_cap = (
            config.get_int("fleet_inflight_cap")
            if inflight_cap is None else int(inflight_cap))
        self._transport = transport
        self._clock = clock
        self._name = "router%d" % next(_router_seq)
        self._lock = threading.Lock()
        self._handles: Dict[str, _WorkerHandle] = {}
        self._roster: List[str] = []
        self._inflight = 0
        self._rid_seq = itertools.count()
        self._last_rejoin: Optional[dict] = None
        self._last_rejoin_t: Optional[float] = None
        self._started_t: Optional[float] = None
        self._server = None
        self._server_thread = None
        self._lease_thread = None
        self._stop = threading.Event()
        self._closed = False
        self._pool = ThreadPoolExecutor(
            max_workers=16,
            thread_name_prefix="raft-tpu-%s" % self._name)
        # fleet-level SLO burn + slowest-K exemplars: the router is
        # the only process that sees true client latency, so the
        # "fleet" service gets its own tracker next to the per-worker
        # ones the aggregation surfaces roll up
        self._slo = flight.slo_for(
            "fleet",
            target_s=config.get_float("serve_slo_target_ms") / 1e3,
            objective=config.get_float("serve_slo_objective"),
            windows_s=tuple(sorted(
                float(w) for w in
                config.get_float_list("serve_slo_windows_s"))),
            clock=clock)
        self._exemplars = flight.exemplars_for("fleet")
        self.sentinel = (_sentinel.AnomalySentinel(
            lambda: {"fleet": self}, clock=clock)
            if sentinel else None)
        if start:
            self.start()

    # ------------------------------------------------------------------ #
    # lifecycle
    # ------------------------------------------------------------------ #
    def start(self) -> "Router":
        expects(not self._closed, "Router %s is closed", self._name)
        if self._server is not None:
            return self
        router = self

        class _Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *args):  # noqa: D102 — metrics only
                pass

            def do_GET(self):
                router._handle(self, "GET")

            def do_POST(self):
                router._handle(self, "POST")

        self._server = http.server.ThreadingHTTPServer(
            (self._host, self._want_port), _Handler)
        self._server.daemon_threads = True
        self._port = int(self._server.server_address[1])
        self._server_thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="raft-tpu-%s" % self._name)
        self._server_thread.start()
        self._started_t = self._clock()
        if self.sentinel is not None:
            _sentinel.register(self.sentinel)
        self._stop.clear()
        self._lease_thread = threading.Thread(
            target=self._lease_loop, daemon=True,
            name="raft-tpu-%s-lease" % self._name)
        self._lease_thread.start()
        return self

    @property
    def port(self) -> Optional[int]:
        return getattr(self, "_port", None)

    @property
    def url(self) -> Optional[str]:
        p = self.port
        return None if p is None else "http://%s:%d" % (self._host, p)

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._stop.set()
        if self.sentinel is not None:
            _sentinel.unregister(self.sentinel)
        srv, self._server = self._server, None
        if srv is not None:
            srv.shutdown()
            srv.server_close()
        for t in (self._server_thread, self._lease_thread):
            if t is not None and t.is_alive():
                t.join(timeout=5.0)
        self._pool.shutdown(wait=False)

    def __enter__(self) -> "Router":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------------------ #
    # membership
    # ------------------------------------------------------------------ #
    def _on_register(self, body: dict) -> Tuple[int, dict]:
        wid = str(body["worker_id"])
        now = self._clock()
        with self._lock:
            h = self._handles.get(wid)
            fresh = h is None
            if fresh:
                h = self._handles[wid] = _WorkerHandle(wid)
                self._roster.append(wid)
                self._roster.sort()
            was_dead = h.state in ("dead", "draining")
            rejoin = (not fresh) and (
                was_dead or int(body.get("generation", 0))
                > h.generation)
            h.generation = int(body.get("generation", 0))
            h.pid = int(body.get("pid", 0))
            h.host = str(body.get("host", self._host))
            h.data_port = int(body.get("data_port", 0))
            h.ops_port = int(body.get("ops_port", 0) or 0)
            h.shard_index = int(body.get("shard_index", 0))
            h.wal_seq = int(body.get("wal_seq", 0))
            h.restore = dict(body.get("restore") or {})
            h.state = "active"
            h.last_beat = now
            h.registered_t = now
            h.backpressure_until = 0.0
        if rejoin:
            _counter("raft_tpu_fleet_rejoins_total",
                     "workers re-registered after eviction/restart"
                     ).inc()
            rj = dict(h.restore)
            rj["worker_id"] = wid
            rj["generation"] = h.generation
            self._last_rejoin = rj
            self._last_rejoin_t = now
            flight.record("fleet_rejoin", service="fleet", worker=wid,
                          generation=h.generation,
                          replayed=rj.get("replayed_records"),
                          restore_s=rj.get("restore_s"))
        else:
            flight.record("fleet_join", service="fleet", worker=wid,
                          generation=h.generation,
                          shard=h.shard_index)
        self._publish_worker_gauges()
        # "now" seeds the worker's clock-offset estimator (NTP-style
        # midpoint over this very exchange) before the first heartbeat
        return 200, {"ok": True,
                     "lease_interval_s": self._lease_interval,
                     "rejoin": bool(rejoin),
                     "now": round(now, 6)}

    def _on_heartbeat(self, body: dict) -> Tuple[int, dict]:
        wid = str(body.get("worker_id", ""))
        now = self._clock()
        with self._lock:
            h = self._handles.get(wid)
            if h is None or h.state == "dead":
                # evicted (or unknown): tell the survivor to rejoin —
                # a long hang must not leave a live-but-unrouted zombie
                return 200, {"ok": False, "rereg": True,
                             "now": round(now, 6)}
            h.last_beat = now
            h.wal_seq = int(body.get("wal_seq", h.wal_seq))
            h.queue_depth = int(body.get("queue_depth", 0))
            if body.get("clock_offset_s") is not None:
                try:
                    h.clock_offset = float(body["clock_offset_s"])
                    h.clock_rtt = float(body.get("clock_rtt_s", 0.0))
                except (TypeError, ValueError):
                    pass  # a garbled estimate must not drop the beat
        _gauge("raft_tpu_fleet_clock_offset_seconds",
               "estimated worker->router monotonic clock offset "
               "(router = worker + offset), NTP-style over the "
               "heartbeat ping", worker=wid).set(h.clock_offset)
        _gauge("raft_tpu_fleet_clock_rtt_seconds",
               "heartbeat round-trip time backing the clock-offset "
               "estimate (alignment is trusted to ~rtt/2)",
               worker=wid).set(h.clock_rtt)
        return 200, {"ok": True, "now": round(now, 6)}

    def _lease_loop(self) -> None:
        while not self._stop.wait(self._lease_interval):
            now = self._clock()
            horizon = self._lease_interval * self._lease_misses
            expired: List[_WorkerHandle] = []
            with self._lock:
                for h in self._handles.values():
                    if (h.state in ("active", "draining")
                            and now - h.last_beat > horizon):
                        expired.append(h)
            for h in expired:
                self._evict(h, "missed_lease")
            if self.sentinel is not None:
                self.sentinel.tick()

    def _evict(self, h: _WorkerHandle, reason: str) -> None:
        with self._lock:
            if h.state == "dead":
                return
            h.state = "dead"
            h.dead_t = self._clock()
        _counter("raft_tpu_fleet_evictions_total",
                 "workers evicted from the fleet, by cause",
                 reason=reason).inc()
        flight.record("fleet_eviction", service="fleet",
                      worker=h.worker_id, reason=reason,
                      generation=h.generation)
        self._publish_worker_gauges()

    def begin_drain(self, worker_id: str) -> dict:
        """Choreography step 1: stop placing NEW inserts on the worker
        (they shed typed, with a rejoin-scaled ``retry_after_s``);
        queries keep routing to it until it actually exits — drain
        narrows the blast radius, it does not widen it."""
        with self._lock:
            h = self._handles.get(worker_id)
            expects(h is not None, "begin_drain: unknown worker %r",
                    worker_id)
            if h.state == "active":
                h.state = "draining"
        flight.record("fleet_drain", service="fleet", worker=worker_id)
        self._publish_worker_gauges()
        return {"worker_id": worker_id, "state": "draining"}

    def note_exit(self, worker_id: str, reason: str = "exit") -> None:
        """Supervisor-observed process exit: immediate typed eviction
        (no need to wait out the lease when the exit was witnessed)."""
        with self._lock:
            h = self._handles.get(worker_id)
        if h is not None:
            self._evict(h, reason)

    def _publish_worker_gauges(self) -> None:
        with self._lock:
            counts = {"active": 0, "draining": 0, "dead": 0}
            for h in self._handles.values():
                counts[h.state] = counts.get(h.state, 0) + 1
        for state, n in counts.items():
            _gauge("raft_tpu_fleet_workers",
                   "fleet workers by lifecycle state",
                   state=state).set(n)

    # ------------------------------------------------------------------ #
    # views
    # ------------------------------------------------------------------ #
    def registry(self) -> Dict[str, dict]:
        with self._lock:
            return {wid: h.public()
                    for wid, h in sorted(self._handles.items())}

    def active_workers(self) -> List[str]:
        with self._lock:
            return sorted(w for w, h in self._handles.items()
                          if h.state == "active")

    def fleet_stats(self) -> dict:
        """The sentinel's view (rules ``worker_dead``/``rejoin_lag``)."""
        with self._lock:
            dead = sum(1 for h in self._handles.values()
                       if h.state == "dead")
            total = len(self._handles)
        rj = None
        if self._last_rejoin is not None:
            rj = dict(self._last_rejoin)
            # age lets the sentinel treat a slow rejoin as an incident
            # that expires (``ops_sentinel_rejoin_hold_s``), not a
            # permanently latched degradation
            if self._last_rejoin_t is not None:
                rj["age_s"] = max(0.0,
                                  self._clock() - self._last_rejoin_t)
        return {"workers_total": total, "workers_dead": dead,
                "last_rejoin": rj}

    # ------------------------------------------------------------------ #
    # data plane: search
    # ------------------------------------------------------------------ #
    def search(self, vectors, *, tenant: Optional[str] = None,
               timeout_s: Optional[float] = None,
               request_id: Optional[str] = None) -> dict:
        expects(isinstance(vectors, (list, tuple)) and len(vectors) > 0,
                "Router.search: vectors must be a non-empty list of "
                "rows")
        timeout = self._timeout if timeout_s is None else float(
            timeout_s)
        rid = request_id or "flt-%08d" % next(self._rid_seq)
        rtrace = self._new_trace(rid, tenant)
        self._admit(rid, "search", rtrace)
        t0 = self._clock()
        deadline = t0 + timeout
        try:
            if self.mode == "replicated":
                out = self._search_replicated(list(vectors), tenant,
                                              deadline, rid, rtrace)
            else:
                out = self._search_sharded(list(vectors), tenant,
                                           deadline, rid, rtrace)
        except CommTimeoutError as e:
            self._terminal(rid, "search", "expired", t0, rtrace,
                           tenant=tenant, error=type(e).__name__)
            raise
        except BaseException as e:
            self._terminal(rid, "search", "failed", t0, rtrace,
                           tenant=tenant, error=type(e).__name__)
            raise
        else:
            self._terminal(rid, "search", "resolved", t0, rtrace,
                           tenant=tenant, degraded=out["degraded"])
            if out["degraded"]:
                _counter("raft_tpu_fleet_degraded_total",
                         "partial (degraded-flagged) fleet responses"
                         ).inc()
            out["request_id"] = rid
            return out
        finally:
            with self._lock:
                self._inflight -= 1

    def _new_trace(self, rid: str, tenant: Optional[str]):
        """The router's own span timeline for one fleet request,
        indexed by the fleet id (= the request id) in the router-local
        flight ring — the half of ``/fleet/debug/trace/<id>`` this
        process owns."""
        return flight.default_recorder().new_trace(
            "fleet", tenant, fleet={"id": rid, "parent": "client"})

    def _admit(self, rid: str, op: str, trace=None) -> None:
        with self._lock:
            if self._closed:
                raise ServiceUnavailableError(
                    "router is closed", "fleet", "worker_dead")
            if self._inflight >= self._inflight_cap:
                _counter("raft_tpu_fleet_requests_total",
                         "fleet requests by terminal outcome",
                         outcome="shed").inc()
                raise ServiceOverloadError(
                    "fleet admission cap reached", self._inflight,
                    self._inflight_cap,
                    retry_after_s=self._lease_interval)
            self._inflight += 1
        flight.record("fleet_admitted", service="fleet", trace=trace,
                      rid=rid, op=op)

    def _terminal(self, rid: str, op: str, outcome: str, t0: float,
                  trace=None, tenant: Optional[str] = None,
                  **attrs) -> None:
        latency = max(0.0, self._clock() - t0)
        flight.record("fleet_%s" % outcome, service="fleet",
                      trace=trace, rid=rid, op=op,
                      latency_s=round(latency, 6), **attrs)
        _counter("raft_tpu_fleet_requests_total",
                 "fleet requests by terminal outcome",
                 outcome=outcome).inc()
        _metrics.default_registry().timer(
            "raft_tpu_fleet_request_seconds",
            help="router end-to-end request latency",
            labels=("op",)).labels(op=op).observe(latency)
        self._slo.observe(tenant, latency,
                          deadline_ok=(outcome == "resolved"))
        if trace is not None:
            self._exemplars.observe(latency, trace.trace_id)

    def _search_sharded(self, vectors, tenant, deadline, rid,
                        trace=None) -> dict:
        shards = list(range(self.shard_count))
        futs = {self._pool.submit(self._query_shard, s, vectors,
                                  tenant, deadline, rid, trace): s
                for s in shards}
        parts, answered = [], []
        remaining = max(0.0, deadline - self._clock())
        done, pending = wait(list(futs), timeout=remaining + 1.0)
        for f in pending:
            f.cancel()
        for f in done:
            part = f.result()  # LogicError propagates: caller bug
            if part is not None:
                parts.append(part)
                answered.append(futs[f])
        if not parts:
            raise ServiceUnavailableError(
                "no fleet shard answered within the deadline",
                "fleet", "no_workers",
                retry_after_s=self._lease_interval)
        k = max(len(row) for d, _ in parts for row in d)
        dists, ids = protocol.merge_topk(parts, k)
        degraded = len(parts) < len(shards)
        flight.record("fleet_merge", service="fleet", trace=trace,
                      rid=rid, parts=len(parts), k=k,
                      degraded=degraded)
        return {"distances": dists, "ids": ids, "degraded": degraded,
                "shards_answered": sorted(answered),
                "shards_total": len(shards), "hedged": False}

    def _shard_owner(self, shard: int) -> Optional[_WorkerHandle]:
        with self._lock:
            for h in self._handles.values():
                if (h.shard_index == shard
                        and h.state in ("active", "draining")):
                    return h
        return None

    def _rpc(self, h: _WorkerHandle, path: str, body: dict,
             remaining: float, rid: str, trace, attempt: int) -> dict:
        """One traced router→worker exchange: the propagated trace
        context rides the body (and the :data:`protocol.TRACE_HEADER`
        mirror), the span pair ``fleet_rpc_send``/``fleet_rpc_recv``
        lands in the router's flight ring, and the network residual
        (wire + queue time outside the worker's own handler clock)
        feeds ``raft_tpu_fleet_network_seconds`` per worker."""
        sent_at = self._clock()
        tctx = protocol.trace_frame(rid, "router", sent_at)
        body = dict(body)
        body["trace"] = tctx
        flight.record("fleet_rpc_send", service="fleet", trace=trace,
                      rid=rid, worker=h.worker_id, path=path,
                      attempt=attempt)
        try:
            rep = protocol.post_json(
                h.data_url + path, body, timeout=remaining + 1.0,
                transport=self._transport, trace=tctx)
        except BaseException as e:
            flight.record("fleet_rpc_fail", service="fleet",
                          trace=trace, rid=rid, worker=h.worker_id,
                          path=path, attempt=attempt,
                          error=type(e).__name__)
            raise
        elapsed = max(0.0, self._clock() - sent_at)
        server_s = rep.get("server_seconds")
        network_s = None
        if server_s is not None:
            try:
                network_s = max(0.0, elapsed - float(server_s))
            except (TypeError, ValueError):
                server_s = None
        # a hedged loser's reply lands AFTER the request already
        # terminated (first success won); tag it so the join keeps
        # the straggler visible without it breaking the RPC-bracket
        # invariants or stretching the merge segment
        late = trace is not None and any(
            e.get("kind") in tracing.ROUTER_TERMINALS
            for e in trace.timeline())
        extra = {"late": True} if late else {}
        flight.record("fleet_rpc_recv", service="fleet", trace=trace,
                      rid=rid, worker=h.worker_id, path=path,
                      attempt=attempt, elapsed_s=round(elapsed, 6),
                      server_s=server_s, network_s=network_s, **extra)
        if network_s is not None:
            _metrics.default_registry().timer(
                "raft_tpu_fleet_network_seconds",
                help="router->worker RPC time outside the worker's "
                     "own handler (wire + accept-queue residual), "
                     "per worker",
                labels=("worker",)).labels(
                    worker=h.worker_id).observe(network_s)
        return rep

    def _query_shard(self, shard, vectors, tenant, deadline,
                     rid, trace=None) -> Optional[tuple]:
        """One shard's retry loop.  Returns ``(distances, ids)`` or
        None when the shard stayed unreachable through the deadline —
        the caller degrades instead of failing closed.  Caller bugs
        (:class:`LogicError`) propagate: they would fail identically
        everywhere."""
        attempt = 0
        backoff = self._retry_backoff
        while True:
            now = self._clock()
            remaining = deadline - now
            if remaining <= 0 or attempt > self._retry_max:
                return None
            h = self._shard_owner(shard)
            wait_s = backoff
            if h is not None:
                try:
                    rep = self._rpc(
                        h, "/search",
                        {"vectors": vectors, "tenant": tenant,
                         "timeout_s": round(remaining, 3)},
                        remaining, rid, trace, attempt)
                    return rep["distances"], rep["ids"]
                except LogicError:
                    raise
                except ServiceOverloadError as e:
                    self._note_backpressure(h, e.retry_after_s)
                    wait_s = max(backoff, e.retry_after_s)
                except ServiceUnavailableError as e:
                    wait_s = max(backoff, e.retry_after_s)
                except CommTimeoutError:
                    self._note_frame_error("timeout")
                except CommError:
                    self._note_frame_error("comm")
            attempt += 1
            _counter("raft_tpu_fleet_retries_total",
                     "per-shard/worker dispatch retries", op="search"
                     ).inc()
            time.sleep(max(0.0, min(wait_s, deadline - self._clock())))
            backoff *= 2.0

    def _search_replicated(self, vectors, tenant, deadline,
                           rid, trace=None) -> dict:
        order = protocol.rendezvous_rank(tenant or rid,
                                         self.active_workers())
        if not order:
            raise ServiceUnavailableError(
                "fleet has no live workers", "fleet", "no_workers",
                retry_after_s=self._lease_interval)
        payload = {"vectors": vectors, "tenant": tenant}
        futs = {self._pool.submit(self._query_worker, order[0],
                                  payload, deadline, rid=rid,
                                  trace=trace): order[0]}
        hedged = False
        last_error: Optional[BaseException] = None
        winner = None
        while True:
            now = self._clock()
            remaining = deadline - now
            if remaining <= 0:
                for f in futs:
                    f.cancel()
                raise CommTimeoutError(
                    "fleet search deadline exceeded (%s)" % rid)
            can_hedge = (not hedged and len(order) > 1
                         and self._hedge_s > 0)
            slice_s = (min(remaining, self._hedge_s) if can_hedge
                       else remaining)
            done, _pending = wait(list(futs), timeout=slice_s,
                                  return_when=FIRST_COMPLETED)
            for f in done:
                wid = futs.pop(f)
                try:
                    rep = f.result()
                except (RaftError, OSError) as e:
                    last_error = e
                    continue
                winner = wid
                if hedged and wid != order[0]:
                    _counter("raft_tpu_fleet_hedge_wins_total",
                             "hedged re-dispatches that beat the "
                             "primary").inc()
                return {"distances": rep["distances"],
                        "ids": rep["ids"], "degraded": False,
                        "worker": winner, "hedged": hedged,
                        "shards_total": 1, "shards_answered": [0]}
            if not futs and (done or last_error is not None):
                if not can_hedge:
                    raise (last_error or ServiceUnavailableError(
                        "all fleet replicas failed", "fleet",
                        "no_workers"))
            if can_hedge:
                hedged = True
                _counter("raft_tpu_fleet_hedges_total",
                         "hedged cross-worker re-dispatches").inc()
                flight.record("fleet_hedge", service="fleet",
                              trace=trace, rid=rid, worker=order[1],
                              primary=order[0])
                futs[self._pool.submit(self._query_worker, order[1],
                                       payload, deadline, rid=rid,
                                       trace=trace)] = order[1]

    def _query_worker(self, worker_id: str, payload: dict,
                      deadline: float, *, path: str = "/search",
                      op: str = "search",
                      rid: Optional[str] = None, trace=None) -> dict:
        """Pinned-worker retry loop (replicated queries, insert
        groups): retries the SAME worker — cross-worker failover is
        the hedger's/owner-contract's decision, not this loop's."""
        attempt = 0
        backoff = self._retry_backoff
        last: Optional[BaseException] = None
        while True:
            now = self._clock()
            remaining = deadline - now
            if remaining <= 0 or attempt > self._retry_max:
                raise (last or CommTimeoutError(
                    "fleet dispatch deadline exceeded for %s"
                    % worker_id))
            with self._lock:
                h = self._handles.get(worker_id)
                live = h is not None and h.state == "active"
            wait_s = backoff
            if live:
                try:
                    body = dict(payload)
                    body["timeout_s"] = round(remaining, 3)
                    if rid is not None:
                        return self._rpc(h, path, body, remaining,
                                         rid, trace, attempt)
                    return protocol.post_json(
                        h.data_url + path,
                        body, timeout=remaining + 1.0,
                        transport=self._transport)
                except LogicError:
                    raise
                except ServiceOverloadError as e:
                    self._note_backpressure(h, e.retry_after_s)
                    last = e
                    wait_s = max(backoff, e.retry_after_s)
                except ServiceUnavailableError as e:
                    last = e
                    wait_s = max(backoff, e.retry_after_s)
                except CommTimeoutError as e:
                    last = e
                    self._note_frame_error("timeout")
                except CommError as e:
                    last = e
                    self._note_frame_error("comm")
            else:
                last = ServiceUnavailableError(
                    "fleet worker %s is not serving" % worker_id,
                    "fleet", "worker_dead",
                    retry_after_s=self._lease_interval)
            attempt += 1
            _counter("raft_tpu_fleet_retries_total",
                     "per-shard/worker dispatch retries", op=op).inc()
            time.sleep(max(0.0, min(wait_s, deadline - self._clock())))
            backoff *= 2.0

    def _note_backpressure(self, h: _WorkerHandle,
                           retry_after_s: float) -> None:
        with self._lock:
            h.backpressure_until = max(
                h.backpressure_until,
                self._clock() + max(0.0, retry_after_s))

    @staticmethod
    def _note_frame_error(kind: str) -> None:
        _counter("raft_tpu_fleet_frame_errors_total",
                 "router<->worker transport faults by kind",
                 kind=kind).inc()

    # ------------------------------------------------------------------ #
    # data plane: insert
    # ------------------------------------------------------------------ #
    def insert(self, ids, vectors, *,
               timeout_s: Optional[float] = None,
               request_id: Optional[str] = None) -> dict:
        """Placed, WAL-acked ingestion.  Returns a result dict rather
        than raising on partial failure: rows in ``acked_ids`` are
        DURABLE at their owner (WAL-acked before the worker replied)
        no matter what the other groups did — collapsing a partial
        ack into an exception would lose exactly that information.
        ``ok`` is True only when every row acked."""
        expects(self.mode == "sharded",
                "Router.insert: the replicated fleet is query-only "
                "(per-replica WALs would diverge); use sharded mode")
        expects(isinstance(ids, (list, tuple)) and len(ids) > 0
                and len(ids) == len(vectors),
                "Router.insert: ids and vectors must be equal-length "
                "non-empty lists")
        timeout = self._timeout if timeout_s is None else float(
            timeout_s)
        rid = request_id or "flt-%08d" % next(self._rid_seq)
        rtrace = self._new_trace(rid, None)
        self._admit(rid, "insert", rtrace)
        t0 = self._clock()
        deadline = t0 + timeout
        try:
            return self._insert_admitted(ids, vectors, rid, t0,
                                         deadline, rtrace)
        except BaseException as e:
            self._terminal(rid, "insert", "failed", t0, rtrace,
                           error=type(e).__name__)
            raise
        finally:
            with self._lock:
                self._inflight -= 1

    def _insert_admitted(self, ids, vectors, rid: str, t0: float,
                         deadline: float, rtrace=None) -> dict:
        with self._lock:
            roster = list(self._roster)
        if not roster:
            raise ServiceUnavailableError(
                "fleet has no registered workers", "fleet",
                "no_workers", retry_after_s=self._lease_interval)
        groups: Dict[str, Tuple[list, list]] = {}
        for i, v in zip(ids, vectors):
            owner = protocol.rendezvous(str(int(i)), roster)
            g = groups.setdefault(owner, ([], []))
            g[0].append(int(i))
            g[1].append(v)
        futs = {self._pool.submit(self._insert_group, wid, g[0],
                                  g[1], deadline, rid,
                                  rtrace): (wid, g[0])
                for wid, g in groups.items()}
        acked: List[int] = []
        errors: List[dict] = []
        wal: Dict[str, int] = {}
        remaining = max(0.0, deadline - self._clock())
        done, pending = wait(list(futs), timeout=remaining + 1.0)
        for f in pending:
            f.cancel()
            wid, gids = futs[f]
            errors.append(protocol.encode_error(CommTimeoutError(
                "insert group for %s missed the deadline" % wid)))
        for f in done:
            wid, gids = futs[f]
            try:
                rep = f.result()
            except BaseException as e:  # noqa: BLE001 — typed out
                errors.append(protocol.encode_error(e))
                continue
            acked.extend(gids)
            wal[wid] = int(rep.get("wal_seq", 0))
        ok = not errors and len(acked) == len(ids)
        self._terminal(rid, "insert",
                       "resolved" if ok else "failed", t0, rtrace,
                       acked=len(acked), failed_groups=len(errors))
        return {"ok": ok, "request_id": rid, "acked_ids": sorted(acked),
                "errors": errors, "wal": wal}

    def _insert_group(self, worker_id: str, gids: list, gvecs: list,
                      deadline: float, rid: Optional[str] = None,
                      trace=None) -> dict:
        with self._lock:
            h = self._handles.get(worker_id)
            if h is not None and h.state == "draining":
                # drain choreography: inserts shed typed with a hint
                # scaled to the restart window; the caller's retry
                # lands after rejoin
                raise ServiceUnavailableError(
                    "fleet worker %s is draining" % worker_id,
                    "fleet", "recovering",
                    retry_after_s=self._lease_interval
                    * self._lease_misses)
            bp = 0.0 if h is None else h.backpressure_until
        now = self._clock()
        if bp > now:
            # worker-side shed hint honored BEFORE dispatch: end-to-end
            # backpressure propagation, not blind hammering
            time.sleep(min(bp - now, max(0.0, deadline - now)))
        return self._query_worker(worker_id,
                                  {"ids": gids, "vectors": gvecs},
                                  deadline, path="/insert",
                                  op="insert", rid=rid, trace=trace)

    # ------------------------------------------------------------------ #
    # aggregation surfaces
    # ------------------------------------------------------------------ #
    def _scrape(self, url: str, timeout: float = 2.0):
        try:
            status, data = self._transport("GET", url, None, timeout)
            return status, data
        except (RaftError, OSError):
            _counter("raft_tpu_fleet_scrape_errors_total",
                     "failed worker metric/health scrapes").inc()
            return None, b""

    def fleet_metrics_text(self) -> str:
        """One scrape surface: every live worker's ``/metrics`` with a
        ``worker=`` label injected, plus the router's own registry."""
        seen_meta: set = set()
        lines: List[str] = []
        lines.extend(_relabel_metrics(
            _metrics.default_registry().to_prometheus(), "router",
            seen_meta))
        for wid, h in sorted(self.registry().items()):
            if h["state"] == "dead" or not h["ops_port"]:
                continue
            status, data = self._scrape(
                "http://%s:%d/metrics"
                % (self._handles[wid].host, h["ops_port"]))
            if status != 200:
                continue
            lines.extend(_relabel_metrics(
                data.decode("utf-8", errors="replace"), wid,
                seen_meta))
        return "\n".join(lines) + "\n"

    def fleet_health(self) -> Tuple[bool, dict]:
        """Aggregate health: ``ok`` while ANYTHING still serves (a
        partial fleet keeps taking traffic — that is the point);
        ``degraded`` is the FAULT-DOMAIN signal — a worker is
        dead/unreachable or a fleet sentinel rule is active.  A worker
        whose own ops ``/healthz`` reads 503 (an internal anomaly —
        say ``wal_depth`` under an ingest burst) is still serving:
        that surfaces as ``workers[wid]["degraded"]`` for drill-down
        but does NOT flip the fleet flag, or any write-heavy fleet
        would page "degraded" while every fault domain is intact."""
        workers: Dict[str, dict] = {}
        alive = 0
        any_bad = False
        for wid, pub in self.registry().items():
            entry = {"state": pub["state"], "ok": False}
            if pub["state"] == "dead" or not pub["ops_port"]:
                any_bad = True
                workers[wid] = entry
                continue
            status, data = self._scrape(
                "http://%s:%d/healthz"
                % (self._handles[wid].host, pub["ops_port"]))
            body = {}
            if status is not None:
                try:
                    body = json.loads(data.decode("utf-8"))
                except ValueError:
                    body = {}
            # liveness = the worker's ops plane answered at all (its
            # /healthz returns 503 while internally degraded)
            entry["ok"] = status is not None
            entry["degraded"] = bool(status != 200
                                     or body.get("degraded", False)
                                     or not body.get("ok", True))
            if not entry["ok"]:
                any_bad = True
            alive += 1 if entry["ok"] else 0
            workers[wid] = entry
        sent_degraded = (self.sentinel is not None
                         and self.sentinel.degraded())
        ok = alive > 0
        return ok, {"ok": ok,
                    "degraded": bool(any_bad or sent_degraded
                                     or not ok),
                    "mode": self.mode, "workers": workers,
                    "sentinel": ({"degraded": sent_degraded,
                                  "active": self.sentinel.active()}
                                 if self.sentinel is not None
                                 else None)}

    def fleet_trace(self, fleet_id: str) -> Tuple[int, dict]:
        """``/fleet/debug/trace/<id>``: the cross-process waterfall —
        the router's own hop spans joined with every involved worker's
        local timeline (fetched live from the worker's ``/debug/trace``
        endpoint), each worker's clock shifted by its heartbeat-
        estimated offset.  The reply carries the joined ``spans``, the
        per-hop summaries, the alignment metadata, and the waterfall
        invariant ``problems`` (empty = monotonic and gapless) —
        ``tools/trace_report.py`` renders it."""
        fleet_id = str(fleet_id)
        router_events: List[dict] = []
        for t in flight.fleet_traces(fleet_id):
            router_events.extend(t.timeline())
        if not router_events:
            _counter("raft_tpu_fleet_trace_joins_total",
                     "cross-process trace joins by outcome",
                     outcome="missing").inc()
            return 404, {"error": "NotFound",
                         "message": "unknown fleet trace %r (evicted "
                                    "or never admitted)" % fleet_id}
        wids = sorted({str(e["worker"]) for e in router_events
                       if e.get("worker") is not None})
        workers: Dict[str, dict] = {}
        partial = False
        for wid in wids:
            with self._lock:
                h = self._handles.get(wid)
                offset = h.clock_offset if h is not None else 0.0
                rtt = h.clock_rtt if h is not None else 0.0
                url = (h.data_url if h is not None and h.data_port
                       else None)
            payload = None
            if url is not None:
                status, data = self._scrape(
                    "%s/debug/trace?id=%s"
                    % (url, urllib.parse.quote(fleet_id, safe="")))
                if status == 200:
                    try:
                        payload = json.loads(data.decode("utf-8"))
                    except ValueError:
                        payload = None
            if payload is None:
                partial = True  # dead/unreachable worker: router half
                payload = {}    # of the hop still renders
            workers[wid] = {"offset_s": offset, "rtt_s": rtt,
                            "payload": payload}
        joined = tracing.join(fleet_id, router_events, workers)
        joined["partial"] = partial
        joined["problems"] = tracing.validate(joined)
        _counter("raft_tpu_fleet_trace_joins_total",
                 "cross-process trace joins by outcome",
                 outcome="partial" if partial else "ok").inc()
        return 200, joined

    def fleet_snapshot(self) -> dict:
        """The ``/debug/snapshot`` payload ``tools/metrics_report.py
        --url`` consumes: router registry + per-worker digests + a
        fleet-wide rollup (p99 from the router's own end-to-end timer
        — the only process that sees true client latency)."""
        digests: Dict[str, dict] = {}
        exemplars: List[dict] = []
        for ex in flight.exemplars_for("fleet").snapshot():
            exemplars.append(dict(ex, worker="router",
                                  service="fleet"))
        for wid, pub in self.registry().items():
            digest = {"state": pub["state"],
                      "generation": pub["generation"],
                      "wal_seq": pub["wal_seq"],
                      "queue_depth": pub["queue_depth"]}
            if pub["state"] != "dead" and pub["ops_port"]:
                status, data = self._scrape(
                    "http://%s:%d/debug/snapshot"
                    % (self._handles[wid].host, pub["ops_port"]))
                if status == 200:
                    try:
                        snap = json.loads(data.decode("utf-8"))
                    except ValueError:
                        snap = {}
                    digest.update(self._digest(
                        snap.get("metrics") or {}))
                    for svc, entries in sorted(
                            ((snap.get("flight") or {})
                             .get("exemplars") or {}).items()):
                        for ex in entries:
                            exemplars.append(dict(
                                ex, worker=wid, service=svc))
            digests[wid] = digest
        # fleet-wide slowest-K with per-worker labels: a p99 number on
        # the rollup links straight to the process that produced it
        exemplars.sort(key=lambda e: -float(e.get("latency_ms", 0.0)))
        del exemplars[8:]
        reg = _metrics.default_registry()
        rollup = {"workers_total": len(digests),
                  "workers_dead": sum(
                      1 for d in digests.values()
                      if d["state"] == "dead"),
                  "slo_burn_max": max(
                      [d.get("slo_burn", 0.0)
                       for d in digests.values()] or [0.0]),
                  "exemplars": exemplars}
        fam = reg.get("raft_tpu_fleet_request_seconds")
        total_reqs = 0
        if fam is not None:
            for labels, series in fam.series():
                total_reqs += int(series.count)
                key = "p99_%s_ms" % labels.get("op", "all")
                rollup[key] = round(
                    1e3 * series.quantile(0.99), 3)
                rollup["p50_%s_ms" % labels.get("op", "all")] = round(
                    1e3 * series.quantile(0.50), 3)
        uptime = (0.0 if self._started_t is None
                  else max(1e-9, self._clock() - self._started_t))
        rollup["uptime_s"] = round(uptime, 3)
        rollup["requests_total"] = total_reqs
        rollup["qps_lifetime"] = round(total_reqs / uptime, 3)
        return {"fleet": {"mode": self.mode,
                          "shard_count": self.shard_count,
                          "workers": digests, "rollup": rollup,
                          "stats": self.fleet_stats()},
                "metrics": reg.snapshot(),
                "flight": flight.flight_snapshot()}

    @staticmethod
    def _digest(metrics_snap: dict) -> dict:
        def _sum(name: str, key: str = "value") -> float:
            fam = metrics_snap.get(name) or {}
            return sum(float(s.get(key, 0) or 0)
                       for s in fam.get("series", []))

        def _max(name: str, key: str) -> float:
            fam = metrics_snap.get(name) or {}
            vals = [float(s.get(key, 0) or 0)
                    for s in fam.get("series", [])]
            return max(vals) if vals else 0.0

        return {
            "requests_total": int(_sum(
                "raft_tpu_serve_requests_total")),
            "rejected_total": int(_sum(
                "raft_tpu_serve_rejected_total")),
            "unavailable_total": int(_sum(
                "raft_tpu_serve_unavailable_total")),
            "exec_p50_ms": round(1e3 * _max(
                "raft_tpu_serve_exec_seconds", "p50"), 3),
            "exec_p95_ms": round(1e3 * _max(
                "raft_tpu_serve_exec_seconds", "p95"), 3),
            "slo_burn": _max("raft_tpu_serve_slo_burn_rate", "value"),
        }

    # ------------------------------------------------------------------ #
    # HTTP plumbing (the ops-plane handler discipline)
    # ------------------------------------------------------------------ #
    def _handle(self, handler, method: str) -> None:
        path = handler.path.split("?", 1)[0]
        endpoint = path if path in (
            "/register", "/heartbeat", "/search", "/insert",
            "/fleet/healthz", "/fleet/metrics", "/fleet/statusz",
            "/healthz", "/metrics", "/debug/snapshot") else "unknown"
        if path.startswith("/fleet/debug/trace/"):
            endpoint = "/fleet/debug/trace"
        try:
            body = {}
            if method == "POST":
                length = int(handler.headers.get("Content-Length", 0))
                raw = handler.rfile.read(length) if length else b"{}"
                body = json.loads(raw.decode("utf-8"))
            status, payload = self._route(method, path, body)
        except Exception as e:  # noqa: BLE001 — typed on the wire
            status, payload = protocol.error_response(e)
        _counter("raft_tpu_fleet_http_requests_total",
                 "router HTTP requests by endpoint and status",
                 endpoint=endpoint, code=str(status)).inc()
        if isinstance(payload, str):
            data = payload.encode("utf-8")
            ctype = "text/plain; version=0.0.4"
        else:
            data = json.dumps(payload).encode("utf-8")
            ctype = "application/json"
        try:
            handler.send_response(status)
            handler.send_header("Content-Type", ctype)
            handler.send_header("Content-Length", str(len(data)))
            handler.end_headers()
            handler.wfile.write(data)
        except (BrokenPipeError, ConnectionError, OSError):
            pass  # scraper gone; nothing to relay

    def _route(self, method: str, path: str, body: dict):
        if method == "POST":
            if path == "/register":
                return self._on_register(body)
            if path == "/heartbeat":
                return self._on_heartbeat(body)
            if path == "/search":
                return 200, self.search(
                    body.get("vectors"),
                    tenant=body.get("tenant"),
                    timeout_s=body.get("timeout_s"),
                    request_id=body.get("request_id"))
            if path == "/insert":
                return 200, self.insert(
                    body.get("ids"), body.get("vectors"),
                    timeout_s=body.get("timeout_s"),
                    request_id=body.get("request_id"))
        elif method == "GET":
            if path in ("/fleet/healthz", "/healthz"):
                ok, payload = self.fleet_health()
                return (200 if ok else 503), payload
            if path in ("/fleet/metrics", "/metrics"):
                return 200, self.fleet_metrics_text()
            if path == "/fleet/statusz":
                return 200, {
                    "mode": self.mode,
                    "shard_count": self.shard_count,
                    "workers": self.registry(),
                    "stats": self.fleet_stats(),
                    "sentinel": (None if self.sentinel is None
                                 else self.sentinel.status())}
            if path == "/debug/snapshot":
                return 200, self.fleet_snapshot()
            if path.startswith("/fleet/debug/trace/"):
                fid = urllib.parse.unquote(
                    path[len("/fleet/debug/trace/"):])
                return self.fleet_trace(fid)
        return 404, {"error": "NotFound", "message": path}
