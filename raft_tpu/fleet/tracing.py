"""Fleet trace aggregation: join router hop spans with worker
timelines into one clock-aligned cross-process waterfall.

This is the read side of fleet tracing (docs/OBSERVABILITY.md "Fleet
tracing").  The write side is distributed: the router records its own
hops (``fleet_admitted`` → ``fleet_rpc_send``/``fleet_rpc_recv`` per
worker → ``fleet_merge`` → ``fleet_resolved``/``failed``/``expired``)
into a router-local flight ring under the fleet request id, while each
worker's :class:`~raft_tpu.core.flight.FlightRecorder` indexes the
local traces created under the propagated context
(:func:`raft_tpu.core.flight.trace_context`).  This module joins the
two halves:

- :func:`local_payload` — a worker's half of the join (its indexed
  traces for a fleet id, stamped with the worker's own clock), served
  by the worker's ``GET /debug/trace`` endpoint.
- :func:`join` — shift each worker's timestamps by the router's
  NTP-style clock-offset estimate for that worker (measured over the
  heartbeat ping: ``offset = router_mid - (t0 + t1) / 2``) and merge
  with the router's spans into one ordered span list plus per-hop
  summaries.
- :func:`hop_segments` — the gapless tiling of a request: router
  dispatch → network out → worker → network back → router merge, per
  hop.  Boundary monotonicity IS the gapless property.
- :func:`validate` — the waterfall invariants a healthy joined trace
  satisfies: exactly one router terminal, per-process monotonic
  timestamps, and every worker span nested inside its RPC bracket
  after alignment (within a tolerance floored by the ping RTT — clock
  alignment can never be better than half the round trip that
  measured it).

Everything here is stdlib-pure and jax-free (``ci/style_check.py``
ops-jax ban): the aggregation path must never compile or block a
worker loop.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from raft_tpu.core import flight

__all__ = [
    "ROUTER_TERMINALS", "local_payload", "align_events", "join",
    "hop_segments", "validate",
]

# the router-side terminal vocabulary (mirrors flight.TERMINAL_KINDS
# with the fleet_ prefix the router records under)
ROUTER_TERMINALS = frozenset(
    ("fleet_resolved", "fleet_failed", "fleet_expired"))

# default nesting tolerance floor, seconds: covers scheduling jitter
# between "event recorded" and "frame on the wire" on loopback
DEFAULT_TOL_S = 0.005


def local_payload(fleet_id: str, worker_id: Optional[str] = None,
                  generation: Optional[int] = None,
                  clock: Callable[[], float] = time.monotonic) -> dict:
    """One process's half of the cross-process join: every local trace
    indexed under ``fleet_id`` (each with its private event list, so
    this works after the global ring wrapped), stamped with this
    process's identity and monotonic clock ``now`` (all event
    timestamps in the payload are THIS clock's seconds — the router
    aligns them)."""
    traces = flight.fleet_traces(str(fleet_id))
    return {
        "fleet": str(fleet_id),
        "worker_id": worker_id,
        "generation": generation,
        "now": clock(),
        "traces": [t.to_dict() for t in traces],
    }


def align_events(events: List[dict], offset_s: float,
                 proc: str) -> List[dict]:
    """Shift a timeline into router-clock seconds (``ts + offset_s``)
    and stamp each event with the process it happened on."""
    out = []
    for ev in events:
        ev = dict(ev)
        ev["ts"] = float(ev["ts"]) + float(offset_s)
        ev["proc"] = proc
        out.append(ev)
    return out


def join(fleet_id: str, router_events: List[dict],
         workers: Dict[str, dict]) -> dict:
    """Join the router's span timeline with the owning workers'
    aligned timelines.

    Parameters
    ----------
    router_events:
        The router-local trace's event dicts for this fleet id
        (router clock).
    workers:
        ``worker_id -> {"offset_s", "rtt_s", "payload"}`` where
        ``payload`` is :func:`local_payload` output fetched from that
        worker and ``offset_s`` is the router's clock-offset estimate
        (router_clock - worker_clock; worker ts + offset = router ts).

    Returns the joined view: ``spans`` (every event, router clock,
    sorted, each stamped with ``proc``), ``hops`` (per-worker RPC
    bracket summaries), ``terminal`` (the router-side terminal kind or
    None), and per-worker alignment metadata.
    """
    spans = align_events(list(router_events), 0.0, "router")
    hops: Dict[str, dict] = {}
    for ev in router_events:
        wid = ev.get("worker")
        if wid is None:
            continue
        hop = hops.setdefault(str(wid), {
            "sends": [], "recvs": [], "late": [],
            "network_s": [], "server_s": []})
        if ev.get("kind") == "fleet_rpc_send":
            hop["sends"].append(float(ev["ts"]))
        elif ev.get("kind") == "fleet_rpc_recv":
            if ev.get("late"):
                # a hedged loser's reply after the terminal: keep it
                # out of the bracket timing (it would stretch the
                # merge segment past the terminal) but count it
                hop["late"].append(float(ev["ts"]))
                continue
            hop["recvs"].append(float(ev["ts"]))
            if ev.get("network_s") is not None:
                hop["network_s"].append(float(ev["network_s"]))
            if ev.get("server_s") is not None:
                hop["server_s"].append(float(ev["server_s"]))
    align: Dict[str, dict] = {}
    for wid, info in sorted(workers.items()):
        payload = info.get("payload") or {}
        offset = float(info.get("offset_s", 0.0) or 0.0)
        align[wid] = {
            "offset_s": round(offset, 6),
            "rtt_s": round(float(info.get("rtt_s", 0.0) or 0.0), 6),
            "traces": len(payload.get("traces", ())),
            "generation": payload.get("generation"),
        }
        for tr in payload.get("traces", ()):
            spans.extend(align_events(tr.get("events", []), offset,
                                      wid))
    spans.sort(key=lambda e: float(e["ts"]))
    terminal = None
    for ev in reversed(router_events):
        if ev.get("kind") in ROUTER_TERMINALS:
            terminal = ev["kind"]
            break
    return {"fleet": str(fleet_id), "terminal": terminal,
            "spans": spans, "hops": {
                wid: {
                    "attempts": len(h["recvs"]) + len(h["late"]),
                    "late_recvs": len(h["late"]),
                    "first_send": min(h["sends"]) if h["sends"] else None,
                    "last_recv": max(h["recvs"]) if h["recvs"] else None,
                    "network_s": round(sum(h["network_s"]), 6),
                    "server_s": round(sum(h["server_s"]), 6),
                } for wid, h in sorted(hops.items())},
            "align": align}


def _proc_events(joined: dict) -> Dict[str, List[dict]]:
    by_proc: Dict[str, List[dict]] = {}
    for ev in joined.get("spans", ()):
        by_proc.setdefault(ev.get("proc", "?"), []).append(ev)
    return by_proc


def hop_segments(joined: dict) -> List[dict]:
    """The gapless tiling of the request per hop, router clock: each
    segment is ``{"proc", "name", "t0", "t1"}`` and consecutive
    boundaries are shared — router dispatch ends exactly where the
    outbound network segment begins.  Rendered by
    ``tools/trace_report.py``; :func:`validate` checks the boundary
    ordering that makes the tiling real."""
    by_proc = _proc_events(joined)
    router = by_proc.get("router", [])
    admitted = next((float(e["ts"]) for e in router
                     if e.get("kind") == "fleet_admitted"), None)
    term_ts = next((float(e["ts"]) for e in reversed(router)
                    if e.get("kind") in ROUTER_TERMINALS), None)
    if admitted is None:
        return []
    segs: List[dict] = []
    sends, recvs = [], []
    for wid, hop in joined.get("hops", {}).items():
        send, recv = hop.get("first_send"), hop.get("last_recv")
        if send is None:
            continue
        sends.append(send)
        wevs = by_proc.get(wid, [])
        w0 = min((float(e["ts"]) for e in wevs), default=None)
        w1 = max((float(e["ts"]) for e in wevs), default=None)
        if w0 is not None and w1 is not None:
            segs.append({"proc": wid, "name": "network_out",
                         "t0": send, "t1": w0})
            segs.append({"proc": wid, "name": "worker",
                         "t0": w0, "t1": w1})
            if recv is not None:
                segs.append({"proc": wid, "name": "network_back",
                             "t0": w1, "t1": recv})
        if recv is not None:
            recvs.append(recv)
    if sends:
        segs.append({"proc": "router", "name": "dispatch",
                     "t0": admitted, "t1": min(sends)})
    if recvs and term_ts is not None:
        segs.append({"proc": "router", "name": "merge_relay",
                     "t0": max(recvs), "t1": term_ts})
    segs.sort(key=lambda s: (s["t0"], s["t1"]))
    return segs


def validate(joined: dict,
             tol_s: float = DEFAULT_TOL_S) -> List[str]:
    """The waterfall invariants (module doc).  Returns human-readable
    problem strings; empty = the joined trace is monotonic and gapless
    after clock alignment with exactly one terminal per process hop.
    The per-worker tolerance is ``tol_s + rtt/2`` — the offset
    estimator's own uncertainty bound."""
    problems: List[str] = []
    by_proc = _proc_events(joined)
    router = by_proc.get("router", [])
    terms = [e for e in router if e.get("kind") in ROUTER_TERMINALS]
    if len(terms) != 1:
        problems.append("router terminal events: %d (want exactly 1: %s)"
                        % (len(terms),
                           [e["kind"] for e in terms] or "none"))
    for proc, evs in sorted(by_proc.items()):
        last = None
        for ev in evs:
            ts = float(ev["ts"])
            if last is not None and ts < last - 1e-9:
                problems.append(
                    "%s: non-monotonic timeline at %r (%.6f < %.6f)"
                    % (proc, ev.get("kind"), ts, last))
                break
            last = ts
    admitted = next((float(e["ts"]) for e in router
                     if e.get("kind") == "fleet_admitted"), None)
    term_ts = float(terms[0]["ts"]) if len(terms) == 1 else None
    for wid, hop in sorted(joined.get("hops", {}).items()):
        send, recv = hop.get("first_send"), hop.get("last_recv")
        tol = tol_s + float(
            joined.get("align", {}).get(wid, {}).get("rtt_s", 0.0)) / 2.0
        if admitted is not None and send is not None \
                and send < admitted - 1e-9:
            problems.append("%s: rpc send %.6f before admission %.6f"
                            % (wid, send, admitted))
        if term_ts is not None and recv is not None \
                and recv > term_ts + tol:
            problems.append("%s: rpc recv %.6f after terminal %.6f"
                            % (wid, recv, term_ts))
        wevs = by_proc.get(wid, [])
        if not wevs:
            continue
        w_terms = [e for e in wevs
                   if e.get("kind") in flight.TERMINAL_KINDS]
        # one terminal per local trace on this hop (a retried hop
        # legitimately has several local traces, each with one)
        per_trace: Dict[Any, int] = {}
        for e in w_terms:
            per_trace[e.get("trace_id")] = per_trace.get(
                e.get("trace_id"), 0) + 1
        for tid, n in sorted(per_trace.items(), key=lambda kv: str(kv)):
            if n != 1:
                problems.append("%s: local trace %s has %d terminals"
                                % (wid, tid, n))
        w0 = min(float(e["ts"]) for e in wevs)
        w1 = max(float(e["ts"]) for e in wevs)
        if send is not None and w0 < send - tol:
            problems.append(
                "%s: worker span starts %.6f before rpc send %.6f "
                "(tol %.6f) — clock alignment gap" % (wid, w0, send, tol))
        if recv is not None and w1 > recv + tol:
            problems.append(
                "%s: worker span ends %.6f after rpc recv %.6f "
                "(tol %.6f) — clock alignment gap" % (wid, w1, recv, tol))
    return problems
