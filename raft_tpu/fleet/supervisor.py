"""Fleet supervisor: spawn, kill, restart, drain — the process hands.

The router (:mod:`raft_tpu.fleet.router`) decides *where traffic
goes*; this module owns *which processes exist*.  It spawns each
worker as ``python -m raft_tpu.fleet.worker <spec.json>`` with its
own persist dir, restarts the dead (bumping the spec's generation so
the router can tell a rejoin from a duplicate), and runs the rolling
restart choreography: quiesce (router stops placing inserts) →
snapshot (worker's clean shutdown lands one) → restart → wait for
rejoin — one worker at a time, so the fleet never loses more than
one fault domain to maintenance.

Worker stdout/stderr land in ``<root>/<worker_id>.log`` — when a
chaos seed kills something in a way the typed errors don't explain,
the log is the black box.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Callable, Dict, List, Optional

from raft_tpu.core.error import expects
from raft_tpu.fleet import protocol
from raft_tpu.fleet.router import Router

__all__ = ["WorkerSpec", "Fleet"]


class WorkerSpec:
    """Everything a worker process needs, JSON-serializable.  The
    supervisor rewrites the spec file on every (re)launch — the
    ``generation`` field is how a rejoin proves it is a new
    incarnation of the same fault domain."""

    def __init__(self, worker_id: str, *, router_url: str,
                 index_rows: int, dim: int, k: int,
                 mode: str = "sharded", shard_index: int = 0,
                 shard_count: int = 1, seed: int = 0,
                 clusters: int = 0, nlist: Optional[int] = None,
                 nprobe: int = 8, persist_dir: Optional[str] = None,
                 persist_fsync: str = "always",
                 snapshot_interval_s: float = 2.0,
                 lease_interval_s: float = 0.5,
                 service_opts: Optional[dict] = None,
                 slow_join_s: float = 0.0, host: str = "127.0.0.1",
                 generation: int = 0):
        self.payload = {
            "worker_id": worker_id, "router_url": router_url,
            "index_rows": int(index_rows), "dim": int(dim),
            "k": int(k), "mode": mode,
            "shard_index": int(shard_index),
            "shard_count": int(shard_count), "seed": int(seed),
            "clusters": int(clusters), "nlist": nlist,
            "nprobe": int(nprobe), "persist_dir": persist_dir,
            "persist_fsync": persist_fsync,
            "snapshot_interval_s": float(snapshot_interval_s),
            "lease_interval_s": float(lease_interval_s),
            "service_opts": dict(service_opts or {}),
            "slow_join_s": float(slow_join_s), "host": host,
            "generation": int(generation),
        }

    @property
    def worker_id(self) -> str:
        return str(self.payload["worker_id"])


class _Member:
    __slots__ = ("spec", "proc", "spec_path", "log_path", "spawns")

    def __init__(self, spec: WorkerSpec, spec_path: str,
                 log_path: str):
        self.spec = spec
        self.proc: Optional[subprocess.Popen] = None
        self.spec_path = spec_path
        self.log_path = log_path
        self.spawns = 0


class Fleet:
    """A router plus N supervised worker processes.

    ``mode="sharded"`` (default): worker *i* owns shard
    ``full[i::n]``; queries fan out and merge; inserts place by
    rendezvous on the row id.  ``mode="replicated"``: every worker
    holds the full index; queries place by rendezvous with hedged
    re-dispatch; query-only.

    Use as a context manager; :meth:`close` tears down workers
    (clean SIGTERM first, SIGKILL stragglers) and the router.
    """

    def __init__(self, n_workers: int, *, root: str, index_rows: int,
                 dim: int, k: int, mode: str = "sharded",
                 seed: int = 0, clusters: int = 0,
                 nlist: Optional[int] = None, nprobe: int = 8,
                 persist: bool = True,
                 persist_fsync: str = "always",
                 snapshot_interval_s: float = 2.0,
                 lease_interval_s: Optional[float] = None,
                 service_opts: Optional[dict] = None,
                 router: Optional[Router] = None,
                 platform: str = "cpu",
                 python: str = sys.executable,
                 clock: Callable[[], float] = time.monotonic,
                 start: bool = True):
        expects(n_workers >= 1, "Fleet: n_workers=%d", n_workers)
        self.n_workers = int(n_workers)
        self.mode = mode
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self._clock = clock
        self._python = python
        self._platform = platform
        self._lock = threading.Lock()
        self._heal_thread: Optional[threading.Thread] = None
        self._heal_stop = threading.Event()
        self._closed = False
        self.router = router or Router(
            mode=mode,
            shard_count=(n_workers if mode == "sharded" else 1),
            lease_interval_s=lease_interval_s)
        self._members: Dict[str, _Member] = {}
        for i in range(self.n_workers):
            wid = "w%d" % i
            spec = WorkerSpec(
                wid, router_url=self.router.url,
                index_rows=index_rows, dim=dim, k=k, mode=mode,
                shard_index=(i if mode == "sharded" else 0),
                shard_count=(n_workers if mode == "sharded" else 1),
                seed=seed, clusters=clusters, nlist=nlist,
                nprobe=nprobe,
                persist_dir=(os.path.join(self.root, wid)
                             if persist else None),
                persist_fsync=persist_fsync,
                snapshot_interval_s=snapshot_interval_s,
                lease_interval_s=self.router._lease_interval,
                service_opts=service_opts)
            self._members[wid] = _Member(
                spec, os.path.join(self.root, "%s.spec.json" % wid),
                os.path.join(self.root, "%s.log" % wid))
        if start:
            self.start()

    # ------------------------------------------------------------------ #
    # process lifecycle
    # ------------------------------------------------------------------ #
    def start(self) -> "Fleet":
        for wid in sorted(self._members):
            self.spawn(wid)
        return self

    def spawn(self, worker_id: str, *,
              slow_join_s: float = 0.0) -> subprocess.Popen:
        m = self._members[worker_id]
        with self._lock:
            if m.proc is not None and m.proc.poll() is None:
                return m.proc
            m.spec.payload["generation"] = m.spawns
            m.spec.payload["slow_join_s"] = float(slow_join_s)
            m.spawns += 1
            with open(m.spec_path, "w", encoding="utf-8") as f:
                json.dump(m.spec.payload, f, indent=1)
            env = dict(os.environ)
            # workers must not fight over an accelerator (or pay a
            # TPU grab per process): pin them to the fleet platform
            # unless the caller already pinned the environment
            env.setdefault("JAX_PLATFORMS", self._platform)
            # the worker resolves `-m raft_tpu.fleet.worker` from its
            # own interpreter: when the supervisor imported raft_tpu
            # off sys.path (checkout, not site-packages), the child
            # needs the same root — a caller's cwd is not it
            pkg_root = os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))))
            parts = env.get("PYTHONPATH", "")
            if pkg_root not in parts.split(os.pathsep):
                env["PYTHONPATH"] = (pkg_root + os.pathsep + parts
                                     if parts else pkg_root)
            log = open(m.log_path, "ab")
            try:
                m.proc = subprocess.Popen(
                    [self._python, "-m", "raft_tpu.fleet.worker",
                     m.spec_path],
                    stdout=log, stderr=subprocess.STDOUT, env=env)
            finally:
                log.close()
            return m.proc

    def wait_ready(self, timeout: float = 120.0,
                   n: Optional[int] = None) -> List[str]:
        """Block until ``n`` (default: all) workers are registered and
        active; returns the active ids.  Raises on timeout — a fleet
        that never formed is a setup failure, not a degraded state."""
        want = self.n_workers if n is None else int(n)
        deadline = self._clock() + timeout
        while True:
            active = self.router.active_workers()
            if len(active) >= want:
                return active
            if self._clock() > deadline:
                raise TimeoutError(
                    "fleet: %d/%d workers active after %.0fs (logs "
                    "under %s)" % (len(active), want, timeout,
                                   self.root))
            time.sleep(0.1)

    def trace(self, request_id: str) -> dict:
        """The joined cross-process waterfall for one fleet request —
        the in-process twin of ``GET /fleet/debug/trace/<id>`` (same
        payload; tests and tools/loadgen.py call it without going
        through HTTP).  Raises ``KeyError`` for an unknown/evicted id
        so callers distinguish "never traced" from "empty join"."""
        status, payload = self.router.fleet_trace(request_id)
        if status != 200:
            raise KeyError("fleet trace %r: %s"
                           % (request_id, payload.get("message")))
        return payload

    def kill(self, worker_id: str,
             sig: int = signal.SIGKILL) -> None:
        """The crash path: no goodbye, no snapshot — the WAL is the
        contract (chaos harness; SIGKILL by default)."""
        m = self._members[worker_id]
        with self._lock:
            proc = m.proc
        if proc is not None and proc.poll() is None:
            proc.send_signal(sig)
            if sig in (signal.SIGKILL, signal.SIGTERM):
                proc.wait(timeout=30.0)

    def restart(self, worker_id: str, *,
                slow_join_s: float = 0.0) -> None:
        """Relaunch a (presumed dead) worker; it crash-restores from
        its persist dir and re-registers — the rejoin half of the
        crash-restart contract."""
        self.spawn(worker_id, slow_join_s=slow_join_s)

    def proc_alive(self, worker_id: str) -> bool:
        m = self._members[worker_id]
        with self._lock:
            proc = m.proc
        return proc is not None and proc.poll() is None

    # ------------------------------------------------------------------ #
    # choreography
    # ------------------------------------------------------------------ #
    def drain_restart(self, worker_id: str,
                      timeout: float = 120.0) -> None:
        """Quiesce → snapshot → handoff → restart for ONE worker:
        the router stops placing new inserts (typed sheds with a
        rejoin-scaled hint), the worker drains in-flight work and
        lands a final snapshot on clean shutdown, the supervisor
        relaunches it, and the router re-admits it on registration.
        The restarted worker replays a near-empty WAL (the snapshot
        just landed) — rolling maintenance costs seconds, not
        replay."""
        m = self._members[worker_id]
        self.router.begin_drain(worker_id)
        reg = self.router.registry().get(worker_id) or {}
        port = int(reg.get("data_port", 0) or 0)
        deadline = self._clock() + timeout
        if port and self.proc_alive(worker_id):
            try:
                protocol.post_json(
                    "http://127.0.0.1:%d/admin/shutdown" % port,
                    {"snapshot": True}, timeout=10.0)
            except Exception:  # noqa: BLE001 — SIGTERM is the backstop
                with self._lock:
                    proc = m.proc
                if proc is not None and proc.poll() is None:
                    proc.terminate()
        with self._lock:
            proc = m.proc
        if proc is not None:
            try:
                proc.wait(timeout=max(1.0, deadline - self._clock()))
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=30.0)
        self.router.note_exit(worker_id, reason="drain")
        self.restart(worker_id)
        self._wait_worker_active(worker_id,
                                 max(1.0, deadline - self._clock()))

    def rolling_restart(self, timeout_per_worker: float = 120.0
                        ) -> None:
        """Drain-restart every worker, one at a time."""
        for wid in sorted(self._members):
            self.drain_restart(wid, timeout=timeout_per_worker)

    def _wait_worker_active(self, worker_id: str,
                            timeout: float) -> None:
        deadline = self._clock() + timeout
        while self._clock() < deadline:
            reg = self.router.registry().get(worker_id) or {}
            if reg.get("state") == "active":
                return
            time.sleep(0.1)
        raise TimeoutError("fleet: %s not active after restart "
                           "(log: %s)" % (worker_id,
                                          self._members[
                                              worker_id].log_path))

    # ------------------------------------------------------------------ #
    # autoheal (the chaos loop's repair hand)
    # ------------------------------------------------------------------ #
    def start_autoheal(self, interval_s: float = 0.25) -> None:
        """Restart any worker whose PROCESS died (crash, chaos kill).
        Eviction of hung-but-alive workers stays with the router's
        lease protocol — healing is for dead processes only."""
        if self._heal_thread is not None:
            return
        self._heal_stop.clear()

        def _loop():
            while not self._heal_stop.wait(interval_s):
                for wid in sorted(self._members):
                    if self._closed:
                        return
                    if not self.proc_alive(wid):
                        self.router.note_exit(wid, reason="crash")
                        try:
                            self.restart(wid)
                        except Exception:  # noqa: BLE001 — retried
                            pass  # next heal tick

        self._heal_thread = threading.Thread(
            target=_loop, daemon=True, name="raft-tpu-fleet-heal")
        self._heal_thread.start()

    def stop_autoheal(self) -> None:
        self._heal_stop.set()
        t, self._heal_thread = self._heal_thread, None
        if t is not None and t.is_alive():
            t.join(timeout=5.0)

    # ------------------------------------------------------------------ #
    # teardown
    # ------------------------------------------------------------------ #
    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self.stop_autoheal()
        procs = []
        with self._lock:
            for m in self._members.values():
                if m.proc is not None and m.proc.poll() is None:
                    m.proc.terminate()
                    procs.append(m.proc)
        for p in procs:
            try:
                p.wait(timeout=15.0)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait(timeout=15.0)
        self.router.close()

    def __enter__(self) -> "Fleet":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
