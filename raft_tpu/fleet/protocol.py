"""Fleet wire protocol: JSON over HTTP, typed errors end-to-end.

Design rules (docs/FAULT_MODEL.md "Fleet fault domains"):

- **JSON only.**  The serialization ban (``ci/style_check.py``) holds
  across the process boundary too: every frame is a JSON object, so a
  garbled frame is a *detected* :class:`CommError`, never silent
  deserialization of attacker/corruption-controlled bytes.  Vectors
  travel as nested float lists — float32 → JSON → float32 round-trips
  exactly (every float32 is representable as a double), which is what
  lets the crash-rejoin tests assert byte-identical results across
  the wire.
- **Typed errors round-trip.**  A worker-side
  :class:`ServiceOverloadError` (with its ``retry_after_s`` hint)
  arrives at the router as the same class with the same hint — the
  backpressure contract (docs/SERVING.md) is preserved end-to-end
  rather than flattened into a status code.
- **Transport faults are typed.**  Connection refused / reset / short
  reads map to :class:`CommError`; a socket timeout maps to
  :class:`CommTimeoutError`.  Both are retryable at the router (same
  taxonomy the comms retry policy uses in-process).

Placement is rendezvous (highest-random-weight) hashing: stable under
membership churn — a worker leaving moves only its own keys, and a
rejoining worker (same worker id, new generation) gets exactly its
old keys back, which is what lets a crash-restored WAL line up with
the traffic the router sends after rejoin.
"""

from __future__ import annotations

import hashlib
import json
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Sequence, Tuple

from raft_tpu.core.error import (CommError, CommTimeoutError, LogicError,
                                 RaftError, ServiceOverloadError,
                                 ServiceUnavailableError)

__all__ = [
    "encode_error", "decode_error", "error_response", "http_transport",
    "post_json", "get_json", "get_text", "rendezvous", "rendezvous_rank",
    "merge_topk", "trace_frame", "parse_trace", "TRACE_HEADER",
]

# status codes the router treats as "the body is a typed raft error"
ERROR_STATUSES = (409, 429, 500, 503, 504)

# HTTP header mirroring the in-body trace context (body is the
# authoritative carrier — the header exists so generic proxies/tcpdump
# sessions can follow a fleet request without parsing JSON bodies)
TRACE_HEADER = "X-Raft-Fleet-Trace"


# ---------------------------------------------------------------------- #
# propagated trace context
# ---------------------------------------------------------------------- #
def trace_frame(fleet_id: str, parent: str,
                sent_at: float) -> dict:
    """The propagated fleet trace context: the fleet-wide request id,
    the span that dispatched this hop (``parent``), and the sender's
    monotonic clock at send time (``sent_at`` — the receiver reports
    its own clocks; alignment happens router-side from the heartbeat
    clock-offset estimate, docs/OBSERVABILITY.md "Fleet tracing")."""
    return {"id": str(fleet_id), "parent": str(parent),
            "sent_at": round(float(sent_at), 6)}


def parse_trace(obj) -> Optional[dict]:
    """Validate a wire-carried trace context.  Accepts the structured
    frame (dict with ``id``) or a legacy opaque id string; anything
    else — including a garbled frame — degrades to None (tracing is
    best-effort; a bad context must never fail the request)."""
    if isinstance(obj, str) and obj:
        return {"id": obj}
    if isinstance(obj, dict) and obj.get("id") is not None:
        out = {"id": str(obj["id"])}
        if obj.get("parent") is not None:
            out["parent"] = str(obj["parent"])
        try:
            if obj.get("sent_at") is not None:
                out["sent_at"] = float(obj["sent_at"])
        except (TypeError, ValueError):
            pass
        return out
    return None


# ---------------------------------------------------------------------- #
# typed-error round-tripping
# ---------------------------------------------------------------------- #
def encode_error(exc: BaseException) -> dict:
    """Wire form of an exception: enough fields to reconstruct the
    typed class (with its backoff hints) on the other side."""
    d = {"error": type(exc).__name__, "message": str(exc)}
    for attr in ("retry_after_s", "queue_depth", "queue_cap", "tenant",
                 "service", "reason"):
        v = getattr(exc, attr, None)
        if v is not None:
            d[attr] = v
    return d


def decode_error(payload: dict, *,
                 default_service: str = "fleet") -> RaftError:
    """Inverse of :func:`encode_error`: rebuild the typed exception.
    Unknown kinds degrade to bare :class:`RaftError` (still typed at
    the taxonomy root, never a silent string)."""
    kind = str(payload.get("error", "RaftError"))
    msg = str(payload.get("message", "remote error"))
    retry = float(payload.get("retry_after_s", 0.0) or 0.0)
    if kind == "ServiceOverloadError":
        return ServiceOverloadError(
            msg, int(payload.get("queue_depth", 0) or 0),
            int(payload.get("queue_cap", 0) or 0),
            tenant=payload.get("tenant"), retry_after_s=retry)
    if kind == "ServiceUnavailableError":
        return ServiceUnavailableError(
            msg, str(payload.get("service") or default_service),
            str(payload.get("reason", "unknown")), retry_after_s=retry)
    if kind == "CommTimeoutError":
        return CommTimeoutError(msg)
    if kind in ("CommError", "CommAbortedError"):
        return CommError(msg)
    if kind in ("LogicError", "TypeError", "ValueError", "IndexError",
                "KeyError"):
        # deterministic caller bugs: never retried on either side
        return LogicError(msg)
    return RaftError(msg)


def error_status(exc: BaseException) -> int:
    """HTTP status a worker replies with for a typed error (the router
    keys retry behavior off the decoded class, not the code — the code
    is for generic scrapers/curl)."""
    if isinstance(exc, ServiceOverloadError):
        return 429
    if isinstance(exc, ServiceUnavailableError):
        return 503
    if isinstance(exc, CommTimeoutError):
        return 504
    if isinstance(exc, LogicError) or isinstance(
            exc, (TypeError, ValueError, IndexError, KeyError)):
        return 409
    return 500


def error_response(exc: BaseException) -> Tuple[int, dict]:
    return error_status(exc), encode_error(exc)


# ---------------------------------------------------------------------- #
# transport
# ---------------------------------------------------------------------- #
def http_transport(method: str, url: str, body: Optional[bytes],
                   timeout: float,
                   headers: Optional[dict] = None) -> Tuple[int, bytes]:
    """One HTTP exchange → ``(status, body_bytes)``.  Transport-layer
    failures raise typed comm errors (module doc); HTTP error statuses
    are RETURNED (the caller decodes the typed body), not raised.
    This is the seam the chaos harness wraps to inject dropped and
    garbled frames.  ``headers`` adds extra request headers (the trace
    context mirror, :data:`TRACE_HEADER`)."""
    req = urllib.request.Request(url, data=body, method=method)
    if body is not None:
        req.add_header("Content-Type", "application/json")
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return int(resp.status), resp.read()
    except urllib.error.HTTPError as e:
        try:
            data = e.read()
        except Exception:
            data = b""
        return int(e.code), data
    except TimeoutError as e:
        raise CommTimeoutError("fleet transport timeout: %s %s (%s)"
                               % (method, url, e)) from e
    except (urllib.error.URLError, ConnectionError, OSError) as e:
        reason = getattr(e, "reason", e)
        if isinstance(reason, TimeoutError) or "timed out" in str(e):
            raise CommTimeoutError("fleet transport timeout: %s %s (%s)"
                                   % (method, url, e)) from e
        raise CommError("fleet transport failure: %s %s (%s)"
                        % (method, url, e)) from e


def _decode_body(status: int, data: bytes, url: str) -> dict:
    try:
        payload = json.loads(data.decode("utf-8"))
    except (UnicodeDecodeError, ValueError) as e:
        # a garbled frame is a typed, retryable comm fault — never a
        # silent parse of corrupted bytes
        raise CommError("fleet frame garbled from %s (status %d): %s"
                        % (url, status, e)) from e
    if not isinstance(payload, dict):
        raise CommError("fleet frame from %s is not an object" % url)
    if status >= 400:
        raise decode_error(payload)
    return payload


def post_json(url: str, payload: dict, *, timeout: float,
              transport=http_transport,
              trace: Optional[dict] = None) -> dict:
    """POST a JSON frame.  ``trace`` mirrors the in-body trace context
    into :data:`TRACE_HEADER`; transports that predate the header
    parameter (injected test doubles) are still accepted — the body
    remains the authoritative carrier."""
    body = json.dumps(payload).encode("utf-8")
    if trace is not None:
        headers = {TRACE_HEADER: json.dumps(trace, sort_keys=True)}
        try:
            status, data = transport("POST", url, body, timeout,
                                     headers)
        except TypeError:
            status, data = transport("POST", url, body, timeout)
    else:
        status, data = transport("POST", url, body, timeout)
    return _decode_body(status, data, url)


def get_json(url: str, *, timeout: float,
             transport=http_transport) -> dict:
    status, data = transport("GET", url, None, timeout)
    return _decode_body(status, data, url)


def get_text(url: str, *, timeout: float,
             transport=http_transport) -> str:
    status, data = transport("GET", url, None, timeout)
    if status >= 400:
        raise CommError("fleet GET %s failed with status %d"
                        % (url, status))
    return data.decode("utf-8", errors="replace")


# ---------------------------------------------------------------------- #
# placement
# ---------------------------------------------------------------------- #
def _hrw_weight(key: str, node: str) -> int:
    h = hashlib.blake2b(("%s|%s" % (key, node)).encode("utf-8"),
                        digest_size=8)
    return int.from_bytes(h.digest(), "big")


def rendezvous_rank(key: str, nodes: Sequence[str]) -> List[str]:
    """All ``nodes`` ordered by highest-random-weight for ``key`` —
    index 0 is the owner, index 1 the first hedge/failover target.
    Deterministic across processes (blake2b, no PYTHONHASHSEED
    dependence)."""
    return sorted(nodes, key=lambda n: _hrw_weight(key, n),
                  reverse=True)


def rendezvous(key: str, nodes: Sequence[str]) -> str:
    if not nodes:
        raise ServiceUnavailableError(
            "fleet has no live workers for placement", "fleet",
            "no_workers")
    return rendezvous_rank(key, nodes)[0]


# ---------------------------------------------------------------------- #
# router-side top-k merge
# ---------------------------------------------------------------------- #
def merge_topk(parts: Sequence[Tuple[Sequence[Sequence[float]],
                                     Sequence[Sequence[int]]]],
               k: int) -> Tuple[List[List[float]], List[List[int]]]:
    """Merge per-shard top-k results into fleet top-k: for each query,
    pool every shard's candidates, drop ``-1`` pad slots, sort by
    ``(distance, id)`` (the id tiebreak makes the merge deterministic
    under equal distances), keep ``k``, pad short results back to
    ``k`` with ``(inf, -1)``.  Shard-local ids must already be
    translated to global ids by the worker (the worker owns the
    translation table; the router stays data-blind)."""
    if not parts:
        raise LogicError("merge_topk: no shard results to merge")
    n_queries = len(parts[0][0])
    for dists, ids in parts:
        if len(dists) != n_queries or len(ids) != n_queries:
            raise LogicError(
                "merge_topk: ragged shard results (%d vs %d queries)"
                % (len(dists), n_queries))
    out_d: List[List[float]] = []
    out_i: List[List[int]] = []
    inf = float("inf")
    for q in range(n_queries):
        pool = []
        for dists, ids in parts:
            for d, i in zip(dists[q], ids[q]):
                if int(i) >= 0:
                    pool.append((float(d), int(i)))
        pool.sort()
        pool = pool[:k]
        pad = k - len(pool)
        out_d.append([d for d, _ in pool] + [inf] * pad)
        out_i.append([i for _, i in pool] + [-1] * pad)
    return out_d, out_i
