"""Versioned, manifest-driven, CRC-checksummed index snapshots.

The serialization half of the durability subsystem
(docs/PERSISTENCE.md).  One snapshot is a directory of **raw
little-endian array files** plus a JSON ``MANIFEST.json`` describing
them — dtype, shape, and a per-chunk CRC32 list per array — written
**atomically**: arrays and manifest land in a hidden temp directory,
every file is fsynced, the directory is renamed into place, and only
then does the ``CURRENT`` pointer file (itself written tmp + fsync +
rename) name it.  A crash at any point leaves either the old snapshot
or the new one fully intact, never a half-written hybrid; stray temp
directories are garbage, ignored by the loader and swept by the next
writer.

No pickle, anywhere (``ci/style_check.py`` bans it across
``raft_tpu/``): every array round-trips as raw C-order little-endian
bytes through the checksummed manifest path, so a snapshot can never
execute code on load and every region of it is integrity-checked.

Per-chunk checksums (default 1 MiB; the out-of-core slot store is
chunked **per slot** so a chunk index IS a slot id) buy two things: a
corruption error names the failing byte offset, not just the file, and
the integrity scrubber (:mod:`raft_tpu.persist.manager`) can re-verify
the snapshot incrementally — a few chunks per maintenance tick —
without ever re-reading whole files on the serving thread.

Load reconstructs the exact index object that was saved (IVF-Flat /
PQ / SQ, or the out-of-core :class:`~raft_tpu.spatial.ooc.OocIVFFlat`
whose bulk ``store`` stays **host-side numpy** — optionally
``np.memmap``-backed, mode ``"c"`` so scrub repairs stay in memory).
Every chunk's CRC is verified during load; any mismatch raises a typed
:class:`~raft_tpu.core.error.DataCorruptionError` naming file, offset,
and expected-vs-actual checksum.  The loader never calls
``jax.device_put`` (the out-of-core style ban extends to this module):
resident metadata re-enters JAX through ``jnp.asarray`` exactly like a
fresh build, and the OOC store never touches the device at all.
"""

from __future__ import annotations

import json
import os
import zlib
from typing import Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from raft_tpu.core.error import DataCorruptionError, expects
from raft_tpu.distance.distance_type import DistanceType

SNAPSHOT_FORMAT = "raft_tpu-snapshot"
SNAPSHOT_VERSION = 1
DEFAULT_CHUNK_BYTES = 1 << 20
MANIFEST_NAME = "MANIFEST.json"
CURRENT_NAME = "CURRENT"
SNAPSHOTS_DIR = "snapshots"

__all__ = ["write_snapshot", "load_current", "current_manifest",
           "snapshot_dir", "SNAPSHOT_VERSION"]


def _fsync_file(f) -> None:
    f.flush()
    os.fsync(f.fileno())


def _fsync_dir(path: str) -> None:
    """Durably record directory-entry changes (the rename)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return  # platforms without O_RDONLY dirs: best effort
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _as_le(arr) -> np.ndarray:
    """Host C-order little-endian view/copy of any array input."""
    a = np.ascontiguousarray(np.asarray(arr))
    if a.dtype.byteorder == ">":
        a = a.astype(a.dtype.newbyteorder("<"))
    return a


def snapshot_dir(root: str, name: str) -> str:
    return os.path.join(root, SNAPSHOTS_DIR, name)


# --------------------------------------------------------------------- #
# array codec
# --------------------------------------------------------------------- #
def _write_array(dirpath: str, name: str, arr,
                 chunk_bytes: int) -> Dict:
    """Stream one array to ``<name>.bin`` computing per-chunk CRC32s;
    returns its manifest entry.  Chunks are sliced from a flat byte
    view, never a ``tobytes()`` copy — snapshotting a host store near
    RAM capacity (the out-of-core tier's whole point) must not double
    its footprint."""
    a = _as_le(arr)
    fname = "%s.bin" % name
    crcs = []
    nbytes = int(a.nbytes)
    view = memoryview(a).cast("B") if nbytes else memoryview(b"")
    with open(os.path.join(dirpath, fname), "wb") as f:
        for off in range(0, max(nbytes, 1), chunk_bytes):
            chunk = view[off:off + chunk_bytes]
            crcs.append(zlib.crc32(chunk) & 0xFFFFFFFF)
            f.write(chunk)
        _fsync_file(f)
    return {"name": name, "file": fname, "dtype": a.dtype.str,
            "shape": list(a.shape), "nbytes": nbytes,
            "chunk_bytes": int(chunk_bytes), "crc32s": crcs}


def _verify_file_chunks(path: str, entry: Dict, *,
                        accumulate: bool = True) -> Optional[bytes]:
    """Read ``path`` verifying every chunk CRC; returns the raw bytes
    (or None with ``accumulate=False`` — the mmap arm verifies
    streaming-only so a huge store never materializes in memory).
    Any mismatch (or a short file) is typed corruption."""
    chunk_bytes = int(entry["chunk_bytes"])
    crcs = entry["crc32s"]
    nbytes = int(entry["nbytes"])
    out = bytearray() if accumulate else None
    read_total = 0
    with open(path, "rb") as f:
        for i, expected in enumerate(crcs):
            want = min(chunk_bytes, max(nbytes - i * chunk_bytes, 0))
            chunk = f.read(chunk_bytes if i < len(crcs) - 1 else want)
            actual = zlib.crc32(chunk) & 0xFFFFFFFF
            if actual != expected or (i < len(crcs) - 1
                                      and len(chunk) < chunk_bytes):
                raise DataCorruptionError(
                    "snapshot array %r failed its chunk checksum"
                    % entry["name"], path, offset=i * chunk_bytes,
                    expected_crc=expected, actual_crc=actual)
            read_total += len(chunk)
            if out is not None:
                out += chunk
    if read_total != nbytes:
        raise DataCorruptionError(
            "snapshot array %r is %d bytes, manifest says %d"
            % (entry["name"], read_total, nbytes), path,
            offset=read_total)
    return bytes(out) if out is not None else None


def _read_array(dirpath: str, entry: Dict, *,
                mmap: bool = False) -> np.ndarray:
    path = os.path.join(dirpath, entry["file"])
    dtype = np.dtype(entry["dtype"])
    shape = tuple(entry["shape"])
    # verification always streams the file (a corrupt store must fail
    # at load, not at first scan); the mmap arm verifies CRC-only —
    # no accumulation, so a huge store never materializes — and keeps
    # the map as the DATA source: lazily paged + copy-on-write (scrub
    # repairs mutate memory, never the snapshot file)
    data = _verify_file_chunks(path, entry, accumulate=not mmap)
    if mmap:
        if not shape or 0 in shape:
            return np.zeros(shape, dtype)
        return np.memmap(path, dtype=dtype, mode="c", shape=shape)
    if not data:
        return np.zeros(shape, dtype)
    return np.frombuffer(data, dtype=dtype).reshape(shape).copy()


# --------------------------------------------------------------------- #
# index kind registry
# --------------------------------------------------------------------- #
def _kind_of(index) -> str:
    return type(index).__name__


def _flat_fields(index):
    arrays = {"centroids": index.centroids, "slot_vecs": index.slot_vecs,
              "slot_ids": index.slot_ids,
              "slot_centroid": index.slot_centroid,
              "cent_slots": index.cent_slots,
              "list_sizes": index.list_sizes}
    if index.slot_norms is not None:
        arrays["slot_norms"] = index.slot_norms
    return arrays, {"metric": int(index.metric),
                    "nprobe": int(index.nprobe)}


def _pq_fields(index):
    arrays = {"centroids": index.centroids, "codebooks": index.codebooks,
              "slot_codes": index.slot_codes, "slot_ids": index.slot_ids,
              "slot_centroid": index.slot_centroid,
              "cent_slots": index.cent_slots,
              "list_sizes": index.list_sizes}
    if index.vectors is not None:
        arrays["vectors"] = index.vectors
    return arrays, {"metric": int(index.metric),
                    "nprobe": int(index.nprobe),
                    "refine_ratio": int(index.refine_ratio)}


def _sq_fields(index):
    arrays = {"centroids": index.centroids, "slot_q": index.slot_q,
              "scale": index.scale, "offset": index.offset,
              "slot_ids": index.slot_ids,
              "slot_centroid": index.slot_centroid,
              "cent_slots": index.cent_slots,
              "list_sizes": index.list_sizes}
    return arrays, {"metric": int(index.metric),
                    "nprobe": int(index.nprobe),
                    "encode_residual": bool(index.encode_residual)}


def _ooc_fields(index):
    arrays = {"centroids": index.centroids, "slot_ids": index.slot_ids,
              "slot_norms": index.slot_norms,
              "cent_slots": index.cent_slots,
              "slot_centroid": index.slot_centroid,
              "list_sizes": index.list_sizes, "store": index.store}
    return arrays, {"metric": int(index.metric),
                    "nprobe": int(index.nprobe)}


_FIELDS = {"IVFFlatIndex": _flat_fields, "IVFPQIndex": _pq_fields,
           "IVFSQIndex": _sq_fields, "OocIVFFlat": _ooc_fields}


def _rebuild_flat(a, meta):
    from raft_tpu.spatial.ann import IVFFlatIndex

    norms = a.get("slot_norms")
    return IVFFlatIndex(
        jnp.asarray(a["centroids"]), jnp.asarray(a["slot_vecs"]),
        jnp.asarray(a["slot_ids"]), jnp.asarray(a["slot_centroid"]),
        jnp.asarray(a["cent_slots"]), jnp.asarray(a["list_sizes"]),
        DistanceType(int(meta["metric"])), int(meta["nprobe"]),
        slot_norms=None if norms is None else jnp.asarray(norms))


def _rebuild_pq(a, meta):
    from raft_tpu.spatial.ann import IVFPQIndex

    vecs = a.get("vectors")
    return IVFPQIndex(
        jnp.asarray(a["centroids"]), jnp.asarray(a["codebooks"]),
        jnp.asarray(a["slot_codes"]), jnp.asarray(a["slot_ids"]),
        jnp.asarray(a["slot_centroid"]), jnp.asarray(a["cent_slots"]),
        jnp.asarray(a["list_sizes"]),
        DistanceType(int(meta["metric"])), int(meta["nprobe"]),
        vectors=None if vecs is None else jnp.asarray(vecs),
        refine_ratio=int(meta.get("refine_ratio", 1)))


def _rebuild_sq(a, meta):
    from raft_tpu.spatial.ann import IVFSQIndex

    return IVFSQIndex(
        jnp.asarray(a["centroids"]), jnp.asarray(a["slot_q"]),
        jnp.asarray(a["scale"]), jnp.asarray(a["offset"]),
        jnp.asarray(a["slot_ids"]), jnp.asarray(a["slot_centroid"]),
        jnp.asarray(a["cent_slots"]), jnp.asarray(a["list_sizes"]),
        DistanceType(int(meta["metric"])), int(meta["nprobe"]),
        bool(meta["encode_residual"]))


def _rebuild_ooc(a, meta):
    from raft_tpu.spatial.ooc import OocIVFFlat

    # the store STAYS host numpy (memmap-backed when the loader was
    # asked to) — only the small metadata re-enters JAX; the full
    # index never lands on device (docs/ZERO_COPY.md §6)
    return OocIVFFlat(
        jnp.asarray(a["centroids"]), jnp.asarray(a["slot_ids"]),
        jnp.asarray(a["slot_norms"]), jnp.asarray(a["cent_slots"]),
        np.asarray(a["slot_centroid"], np.int32),
        jnp.asarray(a["list_sizes"]),
        DistanceType(int(meta["metric"])), int(meta["nprobe"]),
        a["store"])


_REBUILD = {"IVFFlatIndex": _rebuild_flat, "IVFPQIndex": _rebuild_pq,
            "IVFSQIndex": _rebuild_sq, "OocIVFFlat": _rebuild_ooc}


# --------------------------------------------------------------------- #
# write
# --------------------------------------------------------------------- #
def write_snapshot(root: str, index, *, seq: int, wal_seq: int,
                   delta: Optional[Tuple[np.ndarray, np.ndarray]] = None,
                   chunk_bytes: int = DEFAULT_CHUNK_BYTES) -> Dict:
    """Write one atomic snapshot of ``index`` (+ the live delta rows)
    under ``root`` and flip ``CURRENT`` to it; returns the manifest.

    ``wal_seq`` is the last write-ahead-log sequence number whose
    insert is *contained* in this snapshot's state — restart replays
    only records beyond it.  ``delta=(vecs, ids)`` are the delta
    segment's live rows (host arrays, already sliced to the fill
    count).  Older snapshot directories are swept after the flip.
    """
    kind = _kind_of(index)
    expects(kind in _FIELDS,
            "write_snapshot: unsupported index kind %s", kind)
    arrays, meta = _FIELDS[kind](index)
    name = "snapshot-%010d" % int(seq)
    snaps = os.path.join(root, SNAPSHOTS_DIR)
    os.makedirs(snaps, exist_ok=True)
    tmp = os.path.join(snaps, ".tmp-%s" % name)
    if os.path.isdir(tmp):  # stale garbage from a crashed writer
        _rmtree(tmp)
    os.makedirs(tmp)
    entries = []
    total = 0
    for aname, arr in arrays.items():
        cb = chunk_bytes
        if kind == "OocIVFFlat" and aname == "store":
            # chunk the bulk store PER SLOT: a chunk index is a slot
            # id, which is what lets the scrubber verify and rebuild
            # individual slots (docs/PERSISTENCE.md "Scrubbing")
            st = np.asarray(arr)
            cb = max(int(st.shape[1]) * int(st.shape[2])
                     * st.dtype.itemsize, 1)
        e = _write_array(tmp, aname, arr, cb)
        entries.append(e)
        total += e["nbytes"]
    delta_rows = 0
    if delta is not None and delta[0].shape[0]:
        dvecs, dids = delta
        delta_rows = int(dvecs.shape[0])
        for aname, arr in (("delta_vecs", dvecs), ("delta_ids", dids)):
            e = _write_array(tmp, aname, arr, chunk_bytes)
            entries.append(e)
            total += e["nbytes"]
    manifest = {"format": SNAPSHOT_FORMAT, "version": SNAPSHOT_VERSION,
                "kind": kind, "seq": int(seq), "wal_seq": int(wal_seq),
                "meta": meta, "delta_rows": delta_rows,
                "total_bytes": total, "arrays": entries}
    mbytes = json.dumps(manifest, indent=1, sort_keys=True).encode()
    with open(os.path.join(tmp, MANIFEST_NAME), "wb") as f:
        f.write(mbytes)
        _fsync_file(f)
    _fsync_dir(tmp)
    final = os.path.join(snaps, name)
    if os.path.isdir(final):
        # orphan from a crash between a previous writer's directory
        # rename and its CURRENT flip: CURRENT still names the older
        # snapshot, so this seq was re-issued — the orphan is garbage
        # and rename(2) cannot replace a non-empty directory
        _rmtree(final)
    os.replace(tmp, final)
    _fsync_dir(snaps)
    # flip CURRENT (tmp + fsync + rename): its manifest CRC is what
    # lets the loader detect a tampered/corrupt manifest
    cur_tmp = os.path.join(root, CURRENT_NAME + ".tmp")
    with open(cur_tmp, "w", encoding="utf-8") as f:
        f.write("%s %d\n" % (name, zlib.crc32(mbytes) & 0xFFFFFFFF))
        _fsync_file(f)
    os.replace(cur_tmp, os.path.join(root, CURRENT_NAME))
    _fsync_dir(root)
    # sweep superseded snapshots (and crashed writers' temp dirs)
    for other in os.listdir(snaps):
        if other != name:
            _rmtree(os.path.join(snaps, other))
    return manifest


def _rmtree(path: str) -> None:
    try:
        for fname in os.listdir(path):
            os.unlink(os.path.join(path, fname))
        os.rmdir(path)
    except OSError:
        pass  # sweep is best-effort; a leftover dir is inert


# --------------------------------------------------------------------- #
# load
# --------------------------------------------------------------------- #
def _read_current(root: str):
    cur = os.path.join(root, CURRENT_NAME)
    if not os.path.isfile(cur):
        return None
    with open(cur, encoding="utf-8") as f:
        line = f.read().strip()
    parts = line.split()
    if len(parts) != 2 or not parts[1].isdigit():
        raise DataCorruptionError(
            "CURRENT pointer is unparseable: %r" % line, cur)
    return parts[0], int(parts[1])


def current_manifest(root: str) -> Optional[Dict]:
    """Read + verify the CURRENT snapshot's manifest (no array IO);
    None when the directory holds no snapshot."""
    cur = _read_current(root)
    if cur is None:
        return None
    name, crc = cur
    mpath = os.path.join(snapshot_dir(root, name), MANIFEST_NAME)
    try:
        with open(mpath, "rb") as f:
            mbytes = f.read()
    except OSError:
        raise DataCorruptionError(
            "CURRENT names snapshot %s but its manifest is unreadable"
            % name, mpath) from None
    actual = zlib.crc32(mbytes) & 0xFFFFFFFF
    if actual != crc:
        raise DataCorruptionError(
            "snapshot manifest failed its checksum", mpath, offset=0,
            expected_crc=crc, actual_crc=actual)
    try:
        manifest = json.loads(mbytes)
    except ValueError:
        raise DataCorruptionError(
            "snapshot manifest is not valid JSON", mpath) from None
    if (manifest.get("format") != SNAPSHOT_FORMAT
            or manifest.get("version") != SNAPSHOT_VERSION):
        raise DataCorruptionError(
            "snapshot manifest format/version mismatch: %r/%r"
            % (manifest.get("format"), manifest.get("version")), mpath)
    manifest["_dir"] = snapshot_dir(root, name)
    manifest["_name"] = name
    return manifest


def load_current(root: str, *, mmap_store: bool = False):
    """Load the CURRENT snapshot: ``(index, delta_vecs, delta_ids,
    manifest)`` with every chunk CRC verified, or None when no
    snapshot exists.  ``mmap_store`` backs the out-of-core store with
    a copy-on-write ``np.memmap`` instead of reading it into memory
    (verification still streams the file once)."""
    manifest = current_manifest(root)
    if manifest is None:
        return None
    sdir = manifest["_dir"]
    kind = manifest["kind"]
    expects(kind in _REBUILD, "load_current: unknown index kind %s",
            kind)
    arrays = {}
    for entry in manifest["arrays"]:
        use_mmap = (mmap_store and kind == "OocIVFFlat"
                    and entry["name"] == "store")
        arrays[entry["name"]] = _read_array(sdir, entry, mmap=use_mmap)
    delta_vecs = arrays.pop("delta_vecs", None)
    delta_ids = arrays.pop("delta_ids", None)
    index = _REBUILD[kind](arrays, manifest["meta"])
    return index, delta_vecs, delta_ids, manifest
