"""PersistManager: one durability authority per serving index.

Glues the snapshot format (:mod:`raft_tpu.persist.snapshot`) and the
write-ahead log (:mod:`raft_tpu.persist.wal`) into the serving
lifecycle (docs/PERSISTENCE.md):

- :meth:`wal_append` — called by ``ANNService.insert`` under the delta
  lock, BEFORE the insert is acknowledged (the acknowledge contract
  rides the ``persist_fsync`` policy);
- :meth:`maintenance_tick` — rides the serve worker's existing
  maintenance seam (the compaction seam): takes an interval-gated
  snapshot from the service's immutable ``_AnnState`` — so
  snapshotting never pauses admission, never compiles, and never
  tears a batch — then truncates the WAL of everything the snapshot
  now contains, and runs one incremental integrity-scrub step;
- :meth:`restore` — load the CURRENT snapshot (every chunk CRC
  verified), then replay the WAL tail (records newer than the
  snapshot's ``wal_seq``), tolerating a torn trailing record but
  failing loudly (:class:`~raft_tpu.core.error.DataCorruptionError`)
  on interior corruption;
- :meth:`scrub_step` — re-checksum a few snapshot chunks per tick
  against the manifest; for an out-of-core service the store chunks
  are per-slot, and a host-store slot whose in-memory bytes no longer
  match is **quarantined and rebuilt** from the (verified) snapshot
  copy instead of ever serving corrupt distances — every mismatch
  publishes ``raft_tpu_scrub_*`` metrics and a flight-recorder
  black-box snapshot.

All wall-clock reads go through the injected ``clock`` (the owning
service's), so deterministic tests drive snapshot intervals and ages
with a fake clock and the library-wide ad-hoc-timing ban holds.
"""

from __future__ import annotations

import os
import time
import zlib
from typing import NamedTuple, Optional

import numpy as np

from raft_tpu import config
from raft_tpu.core import flight
from raft_tpu.core import metrics as _metrics
from raft_tpu.core.error import DataCorruptionError, expects
from raft_tpu.persist import snapshot as _snap
from raft_tpu.persist import wal as _wal

__all__ = ["PersistManager", "RestoredState"]

WAL_NAME = "wal.log"


class RestoredState(NamedTuple):
    """What :meth:`PersistManager.restore` recovered from disk."""

    index: object                 # rebuilt index, or None (WAL-only)
    delta_vecs: Optional[np.ndarray]
    delta_ids: Optional[np.ndarray]
    delta_rows: int
    wal_seq: int                  # last seq contained in the snapshot
    wal_records: list             # [(seq, ids, vecs)] to replay
    manifest: Optional[dict]


class _ScrubUnit(NamedTuple):
    path: str
    array: str
    offset: int
    length: int
    crc: int
    slot: Optional[int]           # store slot id (ooc) or None


def _labeled_metric(kind: str, name: str, help: str, service: str):
    return getattr(_metrics.default_registry(), kind)(
        name, help=help, labels=("service",)).labels(service=service)


class PersistManager:
    """Durability authority for one service (module doc).

    Parameters
    ----------
    root:
        The persist directory (created if missing): ``snapshots/`` +
        ``CURRENT`` + ``wal.log`` live under it.  One service per
        directory.
    service:
        Metric/flight label (the owning service's name).
    fsync:
        WAL fsync policy (``"always"`` | ``"batch"`` | ``"off"``);
        None resolves the ``persist_fsync`` knob.  See the acknowledge
        contract in docs/PERSISTENCE.md.
    snapshot_interval_s:
        Minimum seconds between interval-driven snapshots (a dirty
        state older than this snapshots on the next maintenance tick);
        None resolves ``persist_snapshot_interval_s``.
    scrub_chunks:
        Integrity-scrub units (snapshot chunks / store slots) verified
        per maintenance tick; ``0`` disables scrubbing.  None resolves
        ``persist_scrub_chunks``.
    clock:
        Monotonic-seconds callable shared with the owning service.
    """

    def __init__(self, root: str, *, service: str,
                 fsync: Optional[str] = None,
                 snapshot_interval_s: Optional[float] = None,
                 scrub_chunks: Optional[int] = None,
                 clock=None):
        self.root = str(root)
        self.service = str(service)
        os.makedirs(os.path.join(self.root, _snap.SNAPSHOTS_DIR),
                    exist_ok=True)
        if fsync is None:
            fsync = config.get("persist_fsync")
        expects(fsync in _wal.FSYNC_POLICIES,
                "PersistManager: persist_fsync=%r not in %r", fsync,
                _wal.FSYNC_POLICIES)
        self.fsync_policy = fsync
        if snapshot_interval_s is None:
            snapshot_interval_s = config.get_float(
                "persist_snapshot_interval_s")
        expects(snapshot_interval_s > 0,
                "PersistManager: snapshot_interval_s=%r",
                snapshot_interval_s)
        self.snapshot_interval_s = float(snapshot_interval_s)
        if scrub_chunks is None:
            scrub_chunks = config.get_int("persist_scrub_chunks")
        expects(scrub_chunks >= 0,
                "PersistManager: scrub_chunks=%d", scrub_chunks)
        self.scrub_chunks = int(scrub_chunks)
        self._clock = clock if clock is not None else time.monotonic
        self._wal_path = os.path.join(self.root, WAL_NAME)
        self._wal: Optional[_wal.WriteAheadLog] = None
        self._wal_depth = 0
        self._base_seq = 0            # seq floor for a fresh WAL file
        self._next_snap_seq = 1
        self._last_snapshot_t: Optional[float] = None
        self._snapshot_bytes = 0
        self._snapshot_seq = 0
        self._dirty = False
        self._replayed = 0
        self._restore_torn = False
        # scrub state
        self._scrub_units: list = []
        self._scrub_cursor = 0
        self._scrub_cycles = 0
        self._store_ref = None        # the ooc store the plan describes
        self._store_dtype = None
        self._store_shape = None
        self.corruption_detected = False
        self.last_scrub: dict = {"checked": 0, "errors": 0,
                                 "rebuilt": 0, "cycles": 0,
                                 "last_error": None}

    @property
    def snapshot_seq(self) -> int:
        """Sequence of the CURRENT snapshot (0 = none on disk yet)."""
        return self._snapshot_seq

    # ------------------------------------------------------------------ #
    # restore
    # ------------------------------------------------------------------ #
    def has_state(self) -> bool:
        return (os.path.isfile(os.path.join(self.root,
                                            _snap.CURRENT_NAME))
                or (os.path.isfile(self._wal_path)
                    and os.path.getsize(self._wal_path) > 0))

    def restore(self, *, mmap_store: bool = False) -> RestoredState:
        """Load snapshot + WAL tail (module doc).  The torn-tail case
        truncates the file so later appends start from a clean end."""
        t0 = self._clock()
        index = None
        dvecs = dids = None
        rows = 0
        wal_seq = 0
        manifest = None
        loaded = _snap.load_current(self.root, mmap_store=mmap_store)
        if loaded is not None:
            index, dvecs, dids, manifest = loaded
            rows = int(manifest["delta_rows"])
            wal_seq = int(manifest["wal_seq"])
            self._next_snap_seq = int(manifest["seq"]) + 1
            self._snapshot_seq = int(manifest["seq"])
            self._snapshot_bytes = int(manifest["total_bytes"])
            self._last_snapshot_t = self._clock()
            self._install_scrub_plan(manifest, index)
        records, info = _wal.replay_wal(self._wal_path,
                                        min_seq=wal_seq)
        records = records or []
        last_seq = wal_seq
        if info is not None:
            if info["torn"]:
                # the tolerated failure: the crash cut the final
                # append short — nothing past valid_end was ever
                # acknowledged, so truncating it loses nothing
                self._restore_torn = True
                os.truncate(self._wal_path, info["valid_end"])
                flight.record("wal_torn", service=self.service,
                              valid_end=int(info["valid_end"]))
            if info["dim"] is not None:
                last_seq = max(wal_seq, int(info["last_seq"]))
                self._wal = _wal.WriteAheadLog(
                    self._wal_path, info["dim"], info["dtype"],
                    fsync=self.fsync_policy, start_seq=last_seq)
                # depth = records NOT yet contained in a snapshot: a
                # crash between write_snapshot and truncate_through
                # leaves already-covered records (seq <= wal_seq) in
                # the file — replay skips them and so must the gauge
                # (counting them would also make final_snapshot write
                # a spurious snapshot for a clean state)
                self._wal_depth = len(records)
        self._base_seq = last_seq
        self._replayed = len(records)
        if records:
            self._dirty = True
        _labeled_metric("counter", "raft_tpu_persist_restores_total",
                    "crash-restart restores from the persist "
                    "directory", self.service).inc()
        if records:
            _labeled_metric("counter",
                        "raft_tpu_persist_wal_replayed_total",
                        "WAL records replayed into the delta segment "
                        "at restore", self.service).inc(len(records))
        _labeled_metric("timer", "raft_tpu_persist_restore_seconds",
                    "snapshot-load + WAL-replay restore latency",
                    self.service).observe(
                        max(0.0, self._clock() - t0))
        self._publish_wal_gauges()
        flight.record("restore", service=self.service,
                      snapshot_seq=self._snapshot_seq,
                      delta_rows=rows, wal_records=len(records),
                      torn=self._restore_torn)
        return RestoredState(index, dvecs, dids, rows, wal_seq,
                             records, manifest)

    # ------------------------------------------------------------------ #
    # WAL
    # ------------------------------------------------------------------ #
    def wal_append(self, ids: np.ndarray, vecs: np.ndarray) -> int:
        """Append one acknowledged-insert record (durable per the
        fsync policy before returning); returns its sequence number.
        The caller (``ANNService.insert``) holds its delta lock, so
        appends are ordered exactly like the delta mirror writes."""
        if self._wal is None:
            v = np.asarray(vecs)
            self._wal = _wal.WriteAheadLog(
                self._wal_path, int(v.shape[1]), v.dtype,
                fsync=self.fsync_policy, start_seq=self._base_seq)
        seq = self._wal.append(np.asarray(ids), np.asarray(vecs))
        self._wal_depth += 1
        self._dirty = True
        _labeled_metric("counter", "raft_tpu_persist_wal_appends_total",
                    "insert batches appended to the write-ahead log",
                    self.service).inc()
        self._publish_wal_gauges()
        return seq

    def _publish_wal_gauges(self) -> None:
        _labeled_metric("gauge", "raft_tpu_persist_wal_records",
                    "insert records in the WAL not yet contained in a "
                    "snapshot", self.service).set(self._wal_depth)
        _labeled_metric("gauge", "raft_tpu_persist_wal_bytes",
                    "write-ahead-log file size", self.service).set(
                        self._wal.size_bytes()
                        if self._wal is not None else 0)

    def note_dirty(self) -> None:
        """Mark durable state stale (a compaction swap: the snapshot
        on disk no longer matches the served index)."""
        self._dirty = True

    # ------------------------------------------------------------------ #
    # snapshot
    # ------------------------------------------------------------------ #
    def snapshot(self, state) -> dict:
        """Write one atomic snapshot of the immutable serving
        ``state`` (an ``_AnnState``) and truncate the WAL of
        everything it contains; returns the manifest."""
        t0 = self._clock()
        rows = int(state.delta_rows)
        delta = None
        if rows:
            delta = (np.asarray(state.delta_vecs)[:rows],
                     np.asarray(state.delta_ids)[:rows])
        wal_seq = int(getattr(state, "wal_seq", 0))
        manifest = _snap.write_snapshot(
            self.root, state.index, seq=self._next_snap_seq,
            wal_seq=wal_seq, delta=delta)
        self._next_snap_seq += 1
        self._snapshot_seq = int(manifest["seq"])
        self._snapshot_bytes = int(manifest["total_bytes"])
        if self._wal is not None:
            kept = self._wal.truncate_through(wal_seq)
            dropped = max(0, self._wal_depth - kept)
            self._wal_depth = kept
            if dropped:
                _labeled_metric("counter",
                            "raft_tpu_persist_wal_truncated_total",
                            "WAL records dropped because a snapshot "
                            "now contains them", self.service).inc(
                                dropped)
        self._dirty = False
        self._last_snapshot_t = self._clock()
        self._install_scrub_plan(manifest, state.index)
        dt = max(0.0, self._clock() - t0)
        _labeled_metric("counter", "raft_tpu_persist_snapshots_total",
                    "snapshots written", self.service).inc()
        _labeled_metric("gauge", "raft_tpu_persist_snapshot_bytes",
                    "bytes in the CURRENT snapshot",
                    self.service).set(self._snapshot_bytes)
        _labeled_metric("gauge", "raft_tpu_persist_snapshot_seq",
                    "sequence number of the CURRENT snapshot",
                    self.service).set(self._snapshot_seq)
        _labeled_metric("timer", "raft_tpu_persist_snapshot_seconds",
                    "atomic snapshot write latency",
                    self.service).observe(dt)
        self._publish_wal_gauges()
        flight.record("snapshot", service=self.service,
                      seq=self._snapshot_seq, delta_rows=rows,
                      bytes=self._snapshot_bytes,
                      seconds=round(dt, 6))
        return manifest

    def final_snapshot(self, state) -> bool:
        """The clean-shutdown snapshot (``Service.close``): persist
        the final state so a restart never needs WAL replay; True
        when a snapshot was actually written (dirty state or pending
        WAL records)."""
        if not (self._dirty or self._wal_depth):
            if self._wal is not None:
                self._wal.sync()
            return False
        self.snapshot(state)
        return True

    # ------------------------------------------------------------------ #
    # the maintenance seam
    # ------------------------------------------------------------------ #
    def maintenance_tick(self, state, ooc=None) -> None:
        """One pass on the serve worker's maintenance seam: deferred
        WAL fsync (the ``"batch"`` policy), interval-gated snapshot of
        a dirty state, one scrub step, age gauge."""
        if self._wal is not None and self.fsync_policy == "batch":
            self._wal.sync()
        now = self._clock()
        if self._dirty and (
                self._last_snapshot_t is None
                or now - self._last_snapshot_t
                >= self.snapshot_interval_s):
            self.snapshot(state)
        self.scrub_step(ooc)
        age = (0.0 if self._last_snapshot_t is None
               else max(0.0, self._clock() - self._last_snapshot_t))
        _labeled_metric("gauge", "raft_tpu_persist_snapshot_age_seconds",
                    "seconds since the CURRENT snapshot was written "
                    "(0 before the first)", self.service).set(age)

    # ------------------------------------------------------------------ #
    # integrity scrubbing
    # ------------------------------------------------------------------ #
    def _install_scrub_plan(self, manifest: dict, index) -> None:
        sdir = manifest.get("_dir") or _snap.snapshot_dir(
            self.root, "snapshot-%010d" % manifest["seq"])
        units = []
        is_ooc = manifest["kind"] == "OocIVFFlat"
        for entry in manifest["arrays"]:
            path = os.path.join(sdir, entry["file"])
            cb = int(entry["chunk_bytes"])
            nb = int(entry["nbytes"])
            for i, crc in enumerate(entry["crc32s"]):
                off = i * cb
                units.append(_ScrubUnit(
                    path, entry["name"], off, min(cb, max(nb - off, 0)),
                    int(crc),
                    i if (is_ooc and entry["name"] == "store")
                    else None))
            if is_ooc and entry["name"] == "store":
                self._store_dtype = np.dtype(entry["dtype"])
                self._store_shape = tuple(entry["shape"])
        self._scrub_units = units
        self._scrub_cursor = 0
        self._store_ref = getattr(index, "store", None)

    def _scrub_failure(self, unit: _ScrubUnit, actual, where: str,
                       repaired: bool) -> None:
        self.last_scrub["errors"] += 1
        self.last_scrub["last_error"] = {
            "array": unit.array, "file": unit.path,
            "offset": unit.offset, "where": where,
            "expected_crc": unit.crc, "actual_crc": actual,
            "repaired": repaired,
        }
        if not repaired:
            self.corruption_detected = True
        _labeled_metric("counter", "raft_tpu_scrub_corruption_total",
                    "integrity-scrub checksum mismatches (snapshot "
                    "chunks or host-store slots)", self.service).inc()
        flight.record("scrub_corruption", service=self.service,
                      array=unit.array, offset=unit.offset,
                      where=where, repaired=repaired)
        flight.default_recorder().blackbox("scrub_corruption",
                                           service=self.service)

    def scrub_step(self, ooc=None) -> None:
        """Verify the next ``scrub_chunks`` units of the CURRENT
        snapshot (and, for an out-of-core service, the matching
        in-memory host-store slots — quarantine-and-rebuild on
        mismatch).  Never raises: findings land in metrics, flight
        black boxes, and :attr:`last_scrub` / session health."""
        units = self._scrub_units
        if self.scrub_chunks <= 0 or not units:
            return
        checked = 0
        for _ in range(min(self.scrub_chunks, len(units))):
            unit = units[self._scrub_cursor]
            self._scrub_cursor += 1
            if self._scrub_cursor >= len(units):
                self._scrub_cursor = 0
                self._scrub_cycles += 1
                self.last_scrub["cycles"] = self._scrub_cycles
            checked += 1
            try:
                with open(unit.path, "rb") as f:
                    f.seek(unit.offset)
                    data = f.read(unit.length)
            except OSError:
                self._scrub_failure(unit, None, "snapshot-file-io",
                                    repaired=False)
                continue
            actual = zlib.crc32(data) & 0xFFFFFFFF
            file_ok = actual == unit.crc and len(data) == unit.length
            if not file_ok:
                self._scrub_failure(unit, actual, "snapshot-file",
                                    repaired=False)
            if (unit.slot is not None and ooc is not None
                    and ooc.store is self._store_ref
                    and unit.slot < ooc.store.shape[0]):
                mem = np.ascontiguousarray(
                    ooc.store[unit.slot]).tobytes()
                mem_crc = zlib.crc32(mem) & 0xFFFFFFFF
                if mem_crc != unit.crc:
                    if file_ok and ooc.store.flags.writeable:
                        # quarantine-and-rebuild: overwrite the
                        # poisoned in-memory slot from the verified
                        # snapshot copy — the corrupt bytes never
                        # serve another distance
                        ooc.store[unit.slot] = np.frombuffer(
                            data, self._store_dtype).reshape(
                                self._store_shape[1:])
                        self._scrub_failure(unit, mem_crc,
                                            "host-store-slot",
                                            repaired=True)
                        self.last_scrub["rebuilt"] += 1
                        _labeled_metric(
                            "counter",
                            "raft_tpu_scrub_rebuilt_slots_total",
                            "poisoned host-store slots rebuilt from "
                            "the snapshot copy", self.service).inc()
                        flight.record("slot_rebuilt",
                                      service=self.service,
                                      slot=int(unit.slot))
                    else:
                        # both copies bad: unrepairable — health
                        # fails until a rebuild/compaction rewrites
                        # the slot and a fresh snapshot lands
                        self._scrub_failure(unit, mem_crc,
                                            "host-store-slot",
                                            repaired=False)
        self.last_scrub["checked"] += checked
        _labeled_metric("counter", "raft_tpu_scrub_checked_total",
                    "snapshot chunks / store slots integrity-checked",
                    self.service).inc(checked)
        _labeled_metric("gauge", "raft_tpu_scrub_progress",
                    "position in the current scrub cycle (fraction "
                    "of units verified)", self.service).set(
                        self._scrub_cursor / max(len(units), 1))

    # ------------------------------------------------------------------ #
    def stats(self) -> dict:
        age = (None if self._last_snapshot_t is None
               else round(max(0.0,
                              self._clock() - self._last_snapshot_t),
                          3))
        return {
            "dir": self.root,
            "fsync": self.fsync_policy,
            "snapshot_seq": self._snapshot_seq,
            "snapshot_bytes": self._snapshot_bytes,
            "snapshot_age_s": age,
            # stale = dirty state that has outlived 3 intervals
            # without a snapshot landing (surfaced, not ok-failing;
            # corruption is what fails health)
            "snapshot_stale": bool(
                self._dirty and age is not None
                and age > 3.0 * self.snapshot_interval_s),
            "snapshot_interval_s": self.snapshot_interval_s,
            "wal_records": self._wal_depth,
            "wal_bytes": (self._wal.size_bytes()
                          if self._wal is not None else 0),
            "wal_seq": (self._wal.seq if self._wal is not None
                        else self._base_seq),
            "replayed_records": self._replayed,
            "restore_torn_tail": self._restore_torn,
            "dirty": self._dirty,
            "corruption_detected": self.corruption_detected,
            "last_scrub": dict(self.last_scrub),
        }

    def close(self) -> None:
        if self._wal is not None:
            self._wal.close()
