"""Durable serving state (docs/PERSISTENCE.md).

The one failure domain the self-healing serving stack cannot reach is
process death: a built index, the out-of-core host store, and every
acknowledged streaming insert live only in memory.  This package makes
them durable:

- :mod:`~raft_tpu.persist.snapshot` — versioned, manifest-driven,
  per-chunk CRC-checksummed serialization of the IVF indexes and the
  out-of-core slot store (raw little-endian arrays + JSON manifest,
  **no pickle** — ``ci/style_check.py`` bans it library-wide), written
  atomically (tmp + fsync + rename) and loaded with every checksum
  verified (OOC store optionally ``np.memmap``-backed, never touching
  device);
- :mod:`~raft_tpu.persist.wal` — the write-ahead log
  ``ANNService.insert`` appends (checksummed records, fsync policy
  knob) before acknowledging, replayed on restart with a
  tolerated-torn-tail / loud-interior-corruption contract
  (:class:`~raft_tpu.core.error.DataCorruptionError`);
- :mod:`~raft_tpu.persist.manager` — :class:`PersistManager`: the
  per-service authority gluing both into the serve worker's
  maintenance seam (interval snapshots that never tear a batch, WAL
  truncation, crash-restart restore, incremental integrity scrubbing
  with quarantine-and-rebuild of poisoned host-store slots).

Entry point for services: ``ANNService(persist_dir=...)`` — see
docs/PERSISTENCE.md for the format, the fsync/acknowledge contract,
the restore sequence, and the scrub policy.
"""

from raft_tpu.persist.manager import (  # noqa: F401
    PersistManager,
    RestoredState,
)
from raft_tpu.persist.snapshot import (  # noqa: F401
    current_manifest,
    load_current,
    write_snapshot,
)
from raft_tpu.persist.wal import (  # noqa: F401
    FSYNC_POLICIES,
    WriteAheadLog,
    replay_wal,
)

__all__ = [
    "PersistManager", "RestoredState",
    "write_snapshot", "load_current", "current_manifest",
    "WriteAheadLog", "replay_wal", "FSYNC_POLICIES",
]
