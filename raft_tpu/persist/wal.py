"""Write-ahead log for acknowledged streaming inserts.

The durability half of :meth:`raft_tpu.serve.ANNService.insert`
(docs/PERSISTENCE.md): every accepted ``(ids, vectors)`` batch is
appended here — with a per-record checksum — **before** the insert is
acknowledged, so a crash can lose only work the caller was never told
succeeded.  The fsync policy knob (``persist_fsync``) picks the
acknowledge contract: ``"always"`` fsyncs before every ack (no
acknowledged loss, ever), ``"batch"`` defers the fsync to the next
maintenance tick (bounded loss window, much cheaper), ``"off"`` leaves
durability to the OS page cache (process-crash-safe, power-loss-unsafe).

File layout — raw binary, no pickle (the ``ci/style_check.py``
serialization ban):

- **file header** (32 bytes): ``b"RTPUWAL1"``, version u32, dim u32,
  8-byte dtype tag (numpy ``.str`` padded with NULs), header CRC32.
- **record** (24-byte header + payload): ``b"RREC"``, seq u64, rows
  u32, header CRC32 (over seq+rows — a bit-flipped length field must
  not reclassify interior corruption as a torn tail), payload CRC32;
  payload = ids ``int32`` LE then vectors ``dtype`` LE, row-major.

Replay tolerates exactly one failure shape: a **torn trailing
record** — the file ends before the declared bytes complete (the
crash cut an append short); the valid prefix is returned and the torn
bytes are truncated away.  *Any* other failure — bad record magic, a
header or payload checksum mismatch on a complete record — is interior
corruption and raises a typed
:class:`~raft_tpu.core.error.DataCorruptionError` naming file, offset,
and expected-vs-actual checksum: silently skipping an interior record
would silently lose an acknowledged insert.
"""

from __future__ import annotations

import os
import struct
import threading
import zlib
from typing import List, Optional, Tuple

import numpy as np

from raft_tpu.core.error import DataCorruptionError, expects

FILE_MAGIC = b"RTPUWAL1"
FILE_VERSION = 1
REC_MAGIC = b"RREC"
_FILE_HDR = struct.Struct("<8sII8sI")   # magic, version, dim, dtype, crc
_REC_HDR = struct.Struct("<4sQIII")     # magic, seq, rows, hdr crc, crc

FSYNC_POLICIES = ("always", "batch", "off")

__all__ = ["WriteAheadLog", "replay_wal", "FSYNC_POLICIES"]


def _dtype_tag(dtype: np.dtype) -> bytes:
    tag = np.dtype(dtype).str.encode()
    expects(len(tag) <= 8, "WAL: dtype tag %r too long", tag)
    return tag.ljust(8, b"\0")


# Chaos/test seam: when set, called (no args) immediately before every
# fsync — the fleet chaos harness (raft_tpu/fleet/chaos.py) injects
# fsync stalls here to prove the acknowledge path degrades to typed
# backpressure rather than silent loss.  None in production.
FSYNC_HOOK = None


def _fsync(f) -> None:
    hook = FSYNC_HOOK
    if hook is not None:
        hook()
    f.flush()
    os.fsync(f.fileno())


def _file_header(dim: int, dtype: np.dtype) -> bytes:
    body = _FILE_HDR.pack(FILE_MAGIC, FILE_VERSION, dim,
                          _dtype_tag(dtype), 0)[:-4]
    return body + struct.pack("<I", zlib.crc32(body) & 0xFFFFFFFF)


def _parse_file_header(path: str, data: bytes) -> Tuple[int, np.dtype]:
    magic, version, dim, tag, crc = _FILE_HDR.unpack_from(data)
    actual = zlib.crc32(data[:_FILE_HDR.size - 4]) & 0xFFFFFFFF
    if magic != FILE_MAGIC or version != FILE_VERSION or actual != crc:
        raise DataCorruptionError(
            "WAL file header is corrupt", path, offset=0,
            expected_crc=crc, actual_crc=actual)
    return int(dim), np.dtype(tag.rstrip(b"\0").decode())


def replay_wal(path: str, *, min_seq: int = 0):
    """Scan ``path`` and return ``(records, info)``.

    ``records`` is ``[(seq, ids int32 (n,), vecs (n, dim)), ...]`` for
    every valid record with ``seq > min_seq``; ``info`` carries
    ``dim``, ``dtype``, ``last_seq`` (across ALL valid records, not
    just the returned ones), ``valid_end`` (byte offset of the last
    valid record's end — the truncation point when ``torn``), and
    ``torn`` (a trailing record was cut short by a crash).  Interior
    corruption raises :class:`DataCorruptionError` (module doc).
    Returns ``(None, None)`` for a missing or zero-length file.
    """
    if not os.path.isfile(path) or os.path.getsize(path) == 0:
        return None, None
    with open(path, "rb") as f:
        data = f.read()
    if len(data) < _FILE_HDR.size:
        # the very first header write was itself torn: nothing was
        # ever acknowledged through this file — treat as empty
        return None, {"dim": None, "dtype": None, "last_seq": 0,
                      "valid_end": 0, "torn": True,
                      "total_records": 0}
    dim, dtype = _parse_file_header(path, data)
    itemsize = dtype.itemsize
    records: List[Tuple[int, np.ndarray, np.ndarray]] = []
    off = _FILE_HDR.size
    last_seq = 0
    torn = False
    valid_end = off
    total = 0
    size = len(data)
    while off < size:
        if size - off < _REC_HDR.size:
            torn = True
            break
        magic, seq, rows, hcrc, pcrc = _REC_HDR.unpack_from(data, off)
        if magic != REC_MAGIC:
            raise DataCorruptionError(
                "WAL record magic mismatch (interior corruption)",
                path, offset=off,
                expected_crc=int.from_bytes(REC_MAGIC, "little"),
                actual_crc=int.from_bytes(magic, "little"))
        hdr_actual = zlib.crc32(data[off + 4:off + 16]) & 0xFFFFFFFF
        if hdr_actual != hcrc:
            # a complete 24-byte header with a bad CRC cannot be a
            # torn append (appends write sequentially) — corruption
            raise DataCorruptionError(
                "WAL record header failed its checksum", path,
                offset=off, expected_crc=hcrc, actual_crc=hdr_actual)
        need = rows * 4 + rows * dim * itemsize
        body_off = off + _REC_HDR.size
        if size - body_off < need:
            torn = True
            break
        body = data[body_off:body_off + need]
        actual = zlib.crc32(body) & 0xFFFFFFFF
        if actual != pcrc:
            raise DataCorruptionError(
                "WAL record payload failed its checksum", path,
                offset=body_off, expected_crc=pcrc, actual_crc=actual)
        if seq > min_seq:
            ids = np.frombuffer(body, np.dtype("<i4"),
                                count=rows).astype(np.int32)
            vecs = np.frombuffer(
                body, dtype, count=rows * dim,
                offset=rows * 4).reshape(rows, dim).copy()
            records.append((int(seq), ids, vecs))
        last_seq = max(last_seq, int(seq))
        total += 1
        off = body_off + need
        valid_end = off
    return records, {"dim": dim, "dtype": dtype, "last_seq": last_seq,
                     "valid_end": valid_end, "torn": torn,
                     "total_records": total}


class WriteAheadLog:
    """Append handle over one WAL file (thread-safe).

    Created fresh (``dim``/``dtype`` known from the first append) or
    re-opened after :func:`replay_wal` validated the file; a torn tail
    must be truncated away (``os.truncate`` to ``valid_end``) before
    re-opening for append.
    """

    def __init__(self, path: str, dim: int, dtype, *,
                 fsync: str = "always", start_seq: int = 0):
        expects(fsync in FSYNC_POLICIES,
                "WriteAheadLog: fsync=%r not in %r", fsync,
                FSYNC_POLICIES)
        self.path = path
        self.dim = int(dim)
        self.dtype = np.dtype(dtype)
        self.fsync_policy = fsync
        self._lock = threading.Lock()
        self._seq = int(start_seq)
        self._records = 0
        self._unsynced = False
        fresh = (not os.path.isfile(path)
                 or os.path.getsize(path) == 0)
        self._f = open(path, "ab")
        if fresh:
            self._f.write(_file_header(self.dim, self.dtype))
            _fsync(self._f)

    @property
    def seq(self) -> int:
        return self._seq

    @property
    def records(self) -> int:
        """Records appended through THIS handle (replayed history is
        the manager's to count)."""
        return self._records

    def tell(self) -> int:
        with self._lock:
            return self._f.tell()

    def append(self, ids: np.ndarray, vecs: np.ndarray) -> int:
        """Append one record; returns its sequence number.  Durable
        per the fsync policy BEFORE returning (the acknowledge
        contract — the caller acks its insert only after this)."""
        ids = np.ascontiguousarray(ids, np.dtype("<i4"))
        vecs = np.ascontiguousarray(np.asarray(vecs),
                                    self.dtype.newbyteorder("<"))
        expects(vecs.ndim == 2 and vecs.shape[1] == self.dim,
                "WAL append: expected (rows, %d) vectors, got %r",
                self.dim, tuple(vecs.shape))
        expects(ids.shape[0] == vecs.shape[0],
                "WAL append: %d ids for %d rows", ids.shape[0],
                vecs.shape[0])
        body = ids.tobytes() + vecs.tobytes()
        with self._lock:
            self._seq += 1
            seq = self._seq
            hdr_body = struct.pack("<QI", seq, ids.shape[0])
            rec = (REC_MAGIC + hdr_body
                   + struct.pack("<II",
                                 zlib.crc32(hdr_body) & 0xFFFFFFFF,
                                 zlib.crc32(body) & 0xFFFFFFFF)
                   + body)
            self._f.write(rec)
            if self.fsync_policy == "always":
                _fsync(self._f)
            else:
                self._f.flush()
                self._unsynced = True
            self._records += 1
        return seq

    def sync(self) -> bool:
        """Flush deferred writes to disk (the ``"batch"`` policy's
        maintenance-tick fsync); True when a sync was actually due."""
        with self._lock:
            if not self._unsynced or self._f.closed:
                return False
            _fsync(self._f)
            self._unsynced = False
            return True

    def truncate_through(self, min_seq: int) -> int:
        """Drop every record with ``seq <= min_seq`` (they are now
        contained in a durable snapshot) by atomically rewriting the
        file with only the newer records; returns how many survive.
        Runs entirely under the append lock, so a concurrent
        :meth:`append` can never be read half-written (and thus
        misclassified as a torn tail) or lost by the rewrite."""
        with self._lock:
            self._f.flush()
            records, _info = replay_wal(self.path, min_seq=min_seq)
            keep = records or []
            self._rewrite_locked(keep)
            return len(keep)

    def rewrite(self, keep_records) -> None:
        """Atomically replace the file with header + ``keep_records``
        (``(seq, ids, vecs)`` tuples) — the truncation a snapshot
        performs: records the snapshot contains drop out, records
        newer than it survive."""
        with self._lock:
            self._rewrite_locked(list(keep_records))

    def _rewrite_locked(self, keep_records) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(_file_header(self.dim, self.dtype))
            for seq, ids, vecs in keep_records:
                ids_b = np.ascontiguousarray(
                    ids, np.dtype("<i4")).tobytes()
                vecs_b = np.ascontiguousarray(
                    vecs, self.dtype.newbyteorder("<")).tobytes()
                hdr_body = struct.pack("<QI", int(seq),
                                       int(np.shape(ids)[0]))
                f.write(REC_MAGIC + hdr_body + struct.pack(
                    "<II", zlib.crc32(hdr_body) & 0xFFFFFFFF,
                    zlib.crc32(ids_b + vecs_b) & 0xFFFFFFFF)
                    + ids_b + vecs_b)
            _fsync(f)
        self._f.close()
        os.replace(tmp, self.path)
        d = os.path.dirname(os.path.abspath(self.path))
        try:
            fd = os.open(d, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        except OSError:
            pass
        self._f = open(self.path, "ab")
        self._records = len(keep_records)
        self._unsynced = False

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                if self._unsynced:
                    _fsync(self._f)
                self._f.close()

    def size_bytes(self) -> int:
        try:
            return os.path.getsize(self.path)
        except OSError:
            return 0
