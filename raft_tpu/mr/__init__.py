"""Memory resources: explicit-lifetime device buffers and pooling.

Reference: ``raft::mr`` (cpp/include/raft/mr/) — ``base_allocator``
(mr/allocator.hpp:35) with device/host variants and the owning
``buffer_base`` (mr/buffer_base.hpp:39) used by comms and the kNN API.

TPU mapping.  XLA owns the HBM heap (the BFC allocator plays RMM's
role), so a faithful re-implementation of a raw allocator would fight
the runtime.  What survives the translation is the *lifetime and reuse*
story the reference's mr layer provides to eager callers:

- :class:`DeviceBuffer` / :class:`HostBuffer` — owning buffers with
  explicit ``deallocate()`` (``jax.Array.delete()`` frees the backing
  HBM eagerly instead of waiting for GC — buffer_base's dtor semantics).
- :class:`PoolAllocator` — freelist reuse of same-(shape, dtype)
  buffers for eager loops holding large scratch arrays (the role of
  RMM's pool_memory_resource for repeated workspace allocations).
- :class:`ZerosPool` / :func:`zeros_cached` — shared device-resident
  zero blocks keyed by (shape, dtype) for the eager pad/assembly hot
  paths (serve bucketing, mnmg index pad, comms p2p staging): jax
  arrays are immutable, so one cached block replaces a fresh
  ``jnp.zeros`` per call (docs/ZERO_COPY.md).
- :class:`TilePool` — budgeted, double-buffered host-to-device tile
  streaming for the out-of-core index tier (docs/ZERO_COPY.md §6):
  slot stores bigger than device memory stay host-resident and the
  probed tiles stream through a fixed staging budget, prefetch
  overlapped with the scan.
- :func:`device_memory_stats` — bytes in use / limit from the device
  (``cudaMemGetInfo``'s role, cudart_utils.h).
- the native *host* arena (cpp/include/raft_tpu/arena.hpp, exposed via
  :func:`raft_tpu.core.native.arena_stats`) covers the host-side
  allocator row.

In-jit code needs none of this: XLA plans temp memory statically and
``donate_argnums`` recycles inputs.  These helpers are for the eager
boundary, where Python GC latency would otherwise hold HBM hostage.
"""

from raft_tpu.mr.buffer import (
    DeviceBuffer,
    HostBuffer,
    PoolAllocator,
    ZerosPool,
    default_zeros_pool,
    device_memory_stats,
    zeros_cached,
)
from raft_tpu.mr.tile_pool import StagedTile, TilePool

__all__ = [
    "DeviceBuffer",
    "HostBuffer",
    "PoolAllocator",
    "StagedTile",
    "TilePool",
    "ZerosPool",
    "default_zeros_pool",
    "device_memory_stats",
    "zeros_cached",
]
