"""TilePool: budgeted host-to-device tile streaming (out-of-core tier).

The out-of-core index tier (docs/ZERO_COPY.md §6, docs/SERVING.md
"Out-of-core serving") keeps the bulk of an index in **host** memory and
streams the slots a query batch actually probes through a small,
fixed budget of device-resident staging tiles.  This module owns the
streaming mechanics; the search driver
(:mod:`raft_tpu.spatial.ooc`) owns what to stream and when.

Design points, in the order they matter:

- **Double-buffered prefetch.**  ``stage()`` gathers the requested slot
  rows from the host store (a fresh, contiguous numpy block) and issues
  an *asynchronous* ``jax.device_put`` — on every backend this build
  serves, the transfer proceeds on the runtime's transfer machinery
  while the caller keeps dispatching compute.  The driver stages tile
  N+1 right after launching the scan of tile N, so the H2D copy of N+1
  overlaps the scan of N; ``take()`` is the one block point and records
  how much of the transfer was NOT hidden.
- **Budget enforcement.**  ``budget_bytes`` bounds the bytes staged and
  not yet taken; a ``stage()`` that would exceed it *blocks* until a
  concurrent ``take()`` makes room (bounded wait, then
  :class:`~raft_tpu.core.error.AllocationError` — a single thread that
  over-stages without taking must fail loudly, not deadlock).  The
  ``raft_tpu_tile_staged_bytes`` gauge's high-water is the proof the
  budget held under concurrent traffic.
- **Donation-friendly ownership.**  Every staged tile is fresh storage
  (the host gather copies; ``device_put`` materializes a new device
  buffer), so the consumer may legally DONATE it to the scan executable
  (docs/ZERO_COPY.md donation contract) — the tile buffer is recycled
  for the scan's output instead of costing a fresh allocation.  This is
  the :class:`~raft_tpu.mr.buffer.ZerosPool` ownership discipline
  inverted: ZerosPool blocks are shared and must never be donated;
  TilePool tiles are exclusively owned and always may be.

Metrics (labeled ``pool=``): ``raft_tpu_h2d_bytes_total``,
``raft_tpu_h2d_seconds`` (stage-to-observed-ready wall per tile — an
upper bound, the ``exec_seconds`` convention),
``raft_tpu_h2d_stall_seconds`` (the exposed fraction: time the consumer
actually blocked in ``take()``, plus the host-side gather/issue time
when nothing overlapped it), and the ``raft_tpu_tile_staged_bytes``
gauge.  ``hidden-transfer fraction = 1 - stall/h2d`` is computed by
``tools/metrics_report.py`` and the ``serve_ann_ooc`` bench rung — the
overlap is *measured*, never asserted.

The whole-index ``jax.device_put`` ban (``ci/style_check.py``,
``ooc-resident-ok`` marker) applies to this file: the per-tile put
below is the ONE legitimate transfer site — the point of the tier is
that the full store never lands on device.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

import jax
import numpy as np

from raft_tpu.core import metrics as _metrics
from raft_tpu.core.error import AllocationError, expects
from raft_tpu.core.profiler import default_profiler


def _pool_counter(name: str, help: str, pool: str):
    return _metrics.default_registry().counter(
        name, help=help, labels=("pool",)).labels(pool=pool)


def _pool_gauge(name: str, help: str, pool: str):
    return _metrics.default_registry().gauge(
        name, help=help, labels=("pool",)).labels(pool=pool)


def _pool_timer(name: str, help: str, pool: str):
    return _metrics.default_registry().timer(
        name, help=help, labels=("pool",)).labels(pool=pool)


class StagedTile:
    """One in-flight host-to-device tile transfer (the handle
    ``stage()`` returns and ``take()`` consumes).  Not constructed by
    callers."""

    __slots__ = ("vecs", "ids", "nbytes", "t_issue", "stage_s",
                 "hidden", "taken")

    def __init__(self, vecs, ids, nbytes, t_issue, stage_s, hidden):
        self.vecs = vecs          # device array, transfer in flight
        self.ids = ids            # (tile_slots,) int32 device slot ids
        self.nbytes = nbytes
        self.t_issue = t_issue
        self.stage_s = stage_s    # host-side gather + issue seconds
        self.hidden = hidden      # was compute in flight to hide it?
        self.taken = False


class TilePool:
    """Budgeted staging pool for host-resident slot stores.

    Parameters
    ----------
    tile_slots:
        Slots per staged tile — the fixed leading dimension of every
        tile, which is what bounds the scan program's executable
        cardinality (one shape, however many tiles stream through).
    budget_bytes:
        Cap on bytes staged and not yet taken.  Must hold at least two
        tiles of the largest store streamed through the pool or
        double-buffering cannot form (checked per ``stage``).
    name:
        The ``pool=`` metric label (services pass their service name).
    device:
        Target device (default: the backend's first device).
    clock:
        Injectable monotonic clock (tests).

    The pool is thread-safe and *passive*: it owns no thread and no
    store.  Callers pass the host store per ``stage()`` call so an
    atomic index swap (ANN compaction) never races in-flight streams —
    a search that began on the old snapshot keeps gathering from the
    old store.
    """

    def __init__(self, tile_slots: int, budget_bytes: int, *,
                 name: str = "tilepool",
                 device: Optional[jax.Device] = None,
                 clock: Callable[[], float] = time.monotonic,
                 stage_wait_s: float = 30.0):
        expects(tile_slots >= 1, "TilePool: tile_slots=%d", tile_slots)
        expects(budget_bytes >= 1, "TilePool: budget_bytes=%d",
                budget_bytes)
        self.tile_slots = int(tile_slots)
        self.budget_bytes = int(budget_bytes)
        self.name = name
        self.device = device
        self._clock = clock
        self._stage_wait_s = float(stage_wait_s)
        self._lock = threading.Condition()
        self._staged_bytes = 0
        self.n_staged = 0
        self.n_taken = 0

    # ------------------------------------------------------------------ #
    def staged_bytes(self) -> int:
        with self._lock:
            return self._staged_bytes

    def tile_bytes(self, store: np.ndarray) -> int:
        """Bytes one staged tile of ``store`` occupies (vecs + ids)."""
        per_slot = int(np.prod(store.shape[1:], dtype=np.int64)
                       ) * store.dtype.itemsize
        return self.tile_slots * (per_slot + 4)

    def _gauge(self):
        return _pool_gauge(
            "raft_tpu_tile_staged_bytes",
            "bytes staged on device and not yet taken (high_water "
            "proves the budget held)", self.name)

    # ------------------------------------------------------------------ #
    def stage(self, store: np.ndarray, slot_ids: np.ndarray, *,
              hidden: bool = True) -> StagedTile:
        """Gather ``store[slot_ids]`` into a fresh tile and issue the
        (asynchronous) host-to-device transfer.  ``slot_ids`` shorter
        than ``tile_slots`` is padded with ``-1`` (pad rows carry
        arbitrary store content; the scan's position map never reads
        them).  ``hidden=False`` marks a stage nothing overlaps (the
        synchronous-prefetch arm, or the first tile of a batch) so the
        stall accounting stays honest.

        Blocks while the budget is full (a concurrent ``take`` makes
        room); raises :class:`AllocationError` after ``stage_wait_s``
        — over-staging from one thread is a driver bug, not a wait.
        """
        ids = np.asarray(slot_ids, np.int32).ravel()
        expects(ids.shape[0] <= self.tile_slots,
                "TilePool.stage: %d slot ids exceed tile_slots=%d",
                ids.shape[0], self.tile_slots)
        nbytes = self.tile_bytes(store)
        expects(2 * nbytes <= self.budget_bytes,
                "TilePool.stage: budget_bytes=%d cannot double-buffer "
                "%d-byte tiles (need >= 2 tiles)", self.budget_bytes,
                nbytes)
        deadline = self._clock() + self._stage_wait_s
        with self._lock:
            while self._staged_bytes + nbytes > self.budget_bytes:
                remaining = deadline - self._clock()
                if remaining <= 0.0:
                    raise AllocationError(
                        "TilePool(%s).stage: budget %d bytes full "
                        "(%d staged) and no take() freed room within "
                        "%.1fs" % (self.name, self.budget_bytes,
                                   self._staged_bytes,
                                   self._stage_wait_s),
                        requested_bytes=nbytes,
                        live_bytes=self._staged_bytes)
                self._lock.wait(timeout=min(remaining, 0.05))
            self._staged_bytes += nbytes
            self.n_staged += 1
            self._gauge().set(self._staged_bytes)
        t0 = self._clock()
        try:
            with default_profiler().span("ooc.prefetch", layer="ooc"):
                if ids.shape[0] < self.tile_slots:
                    ids = np.concatenate(
                        [ids, np.full(self.tile_slots - ids.shape[0],
                                      -1, np.int32)])
                # fresh contiguous copy (fancy indexing) — the one
                # buffer the consumer may donate to the scan program
                host = store[np.clip(ids, 0, store.shape[0] - 1)]
                if self.device is not None:
                    vecs = jax.device_put(host, self.device)  # ooc-resident-ok (per-tile stream)
                    ids_d = jax.device_put(ids, self.device)  # ooc-resident-ok (per-tile stream)
                else:
                    vecs = jax.device_put(host)  # ooc-resident-ok (per-tile stream)
                    ids_d = jax.device_put(ids)  # ooc-resident-ok (per-tile stream)
        except BaseException:
            with self._lock:
                self._staged_bytes -= nbytes
                self._gauge().set(self._staged_bytes)
                self._lock.notify_all()
            raise
        stage_s = self._clock() - t0
        _pool_counter("raft_tpu_h2d_bytes_total",
                      "bytes streamed host-to-device by tile pools",
                      self.name).inc(nbytes)
        return StagedTile(vecs, ids_d, nbytes, t0, stage_s, hidden)

    def take(self, tile: StagedTile, busy: bool = False):
        """Block until the tile's transfer completes and hand over the
        ``(vecs, ids)`` device arrays (ownership transfers: the caller
        may donate ``vecs``).  Records the transfer wall
        (``h2d_seconds``, stage-to-ready upper bound) and the exposed
        stall: time blocked here counts as stalled only when ``busy``
        is False — the caller passes whether device compute was still
        in flight at the call (a block that overlaps a running scan is
        *hidden* wall-clock, which is the whole point of the double
        buffer) — plus the stage-side host time when the stage itself
        overlapped nothing."""
        expects(not tile.taken, "TilePool.take: tile already taken")
        t0 = self._clock()
        try:
            jax.block_until_ready((tile.vecs, tile.ids))
        except BaseException:
            # a failed transfer must release its budget charge or the
            # pool shrinks permanently (the worker's retry would then
            # stall every later stage against a phantom reservation)
            self.discard(tile)
            raise
        now = self._clock()
        wait_s = now - t0
        _pool_timer("raft_tpu_h2d_seconds",
                    "tile transfer wall, stage to observed-ready "
                    "(upper bound under the overlapped loop)",
                    self.name).observe(max(0.0, now - tile.t_issue))
        _pool_timer("raft_tpu_h2d_stall_seconds",
                    "transfer time NOT hidden behind compute (take "
                    "block while the device was idle, plus stage host "
                    "time when unoverlapped)",
                    self.name).observe(
                        (0.0 if busy else wait_s)
                        + (0.0 if tile.hidden else tile.stage_s))
        self._release(tile)
        self.n_taken += 1
        return tile.vecs, tile.ids

    def discard(self, tile: StagedTile) -> None:
        """Release a staged tile's budget charge WITHOUT consuming it —
        the unwind path for a driver whose scan failed between
        ``stage`` and ``take`` (idempotent; a taken tile is a no-op)."""
        self._release(tile)

    def _release(self, tile: StagedTile) -> None:
        with self._lock:
            if tile.taken:
                return
            tile.taken = True
            self._staged_bytes -= tile.nbytes
            self._gauge().set(self._staged_bytes)
            self._lock.notify_all()
