"""Owning buffers + pool allocator (see package docstring for the
design mapping to reference mr/allocator.hpp:35 / buffer_base.hpp:39)."""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.error import expects


def device_memory_stats(device: Optional[jax.Device] = None) -> Dict[str, int]:
    """Bytes in use / limit for a device (cudaMemGetInfo's role,
    reference cudart_utils.h).  Backends without stats return {}."""
    d = device if device is not None else jax.devices()[0]
    try:
        stats = d.memory_stats() or {}
    except Exception:
        return {}
    out = {}
    for key in ("bytes_in_use", "bytes_limit", "peak_bytes_in_use"):
        if key in stats:
            out[key] = int(stats[key])
    return out


class DeviceBuffer:
    """Owning device allocation with explicit lifetime (reference
    ``device_buffer`` = buffer_base over the device allocator,
    mr/buffer_base.hpp:39).

    ``deallocate()`` frees the backing HBM *now* (``jax.Array.delete``)
    rather than when Python GC gets around to it — the dtor semantics
    eager pipelines need when cycling large scratch arrays.
    """

    def __init__(self, shape: Tuple[int, ...], dtype=jnp.float32,
                 device: Optional[jax.Device] = None,
                 _array: Optional[jax.Array] = None):
        self.shape = tuple(shape)
        self.dtype = jnp.dtype(dtype)
        self.device = device if device is not None else jax.devices()[0]
        if _array is not None:
            self._array: Optional[jax.Array] = _array
        else:
            self._array = jax.device_put(
                jnp.zeros(self.shape, self.dtype), self.device)

    @classmethod
    def from_array(cls, array) -> "DeviceBuffer":
        """Adopt an existing array (reference buffer_base's
        pointer-adopting ctor)."""
        arr = jnp.asarray(array)
        dev = list(arr.devices())[0]
        return cls(arr.shape, arr.dtype, dev, _array=arr)

    @property
    def data(self) -> jax.Array:
        """The live array (reference ``buffer.data()``)."""
        expects(self._array is not None, "DeviceBuffer: use after deallocate")
        return self._array

    def size_bytes(self) -> int:
        return int(np.prod(self.shape, dtype=np.int64)) * self.dtype.itemsize

    @property
    def deallocated(self) -> bool:
        return self._array is None or self._array.is_deleted()

    def deallocate(self) -> None:
        """Free the device memory immediately; idempotent."""
        if self._array is not None and not self._array.is_deleted():
            self._array.delete()
        self._array = None

    def __enter__(self) -> "DeviceBuffer":
        return self

    def __exit__(self, *exc) -> None:
        self.deallocate()


class HostBuffer(DeviceBuffer):
    """Host-side owning buffer (reference ``host_buffer``).  Backed by
    numpy (always host-resident); same explicit-lifetime interface."""

    def __init__(self, shape: Tuple[int, ...], dtype=jnp.float32):
        self.shape = tuple(shape)
        self.dtype = jnp.dtype(dtype)
        self.device = None
        self._np: Optional[np.ndarray] = np.zeros(shape, self.dtype)
        self._array = None

    @classmethod
    def from_array(cls, array) -> "HostBuffer":
        arr = np.asarray(array)
        buf = cls(arr.shape, arr.dtype)
        buf._np = arr  # adopt without copy
        return buf

    @property
    def data(self) -> np.ndarray:
        expects(self._np is not None, "HostBuffer: use after deallocate")
        return self._np

    @property
    def deallocated(self) -> bool:
        return self._np is None

    def deallocate(self) -> None:
        self._np = None


class PoolAllocator:
    """Freelist reuse of same-(shape, dtype) device buffers (the role of
    RMM's pool resource for repeated eager workspace allocations —
    allocation latency and fragmentation, not capacity, are what it
    buys on a runtime whose heap XLA already owns).

    ``allocate`` returns a pooled buffer when one matches, else a fresh
    one; ``deallocate`` returns the buffer to the pool (device memory
    stays live for reuse).  ``release`` frees everything pooled.

    Like RMM's pool resource, a pool HIT returns the buffer with its
    previous contents — only the fresh-allocation path zero-fills.
    Callers needing zeros must clear the buffer themselves.
    """

    def __init__(self, device: Optional[jax.Device] = None,
                 max_pooled_per_key: int = 4):
        self.device = device if device is not None else jax.devices()[0]
        self.max_pooled_per_key = max_pooled_per_key
        self._free: Dict[Tuple, List[DeviceBuffer]] = {}
        self.n_hits = 0
        self.n_misses = 0

    def _key(self, shape, dtype):
        return (tuple(shape), jnp.dtype(dtype).name)

    def allocate(self, shape, dtype=jnp.float32) -> DeviceBuffer:
        bucket = self._free.get(self._key(shape, dtype))
        if bucket:
            self.n_hits += 1
            return bucket.pop()
        self.n_misses += 1
        return DeviceBuffer(shape, dtype, self.device)

    def deallocate(self, buf: DeviceBuffer) -> None:
        expects(not buf.deallocated,
                "PoolAllocator: cannot pool a deallocated buffer")
        bucket = self._free.setdefault(self._key(buf.shape, buf.dtype), [])
        if len(bucket) < self.max_pooled_per_key:
            bucket.append(buf)
        else:
            buf.deallocate()

    def pooled_bytes(self) -> int:
        return sum(b.size_bytes() for bs in self._free.values() for b in bs)

    def release(self) -> None:
        """Free all pooled memory (RMM pool release)."""
        for bs in self._free.values():
            for b in bs:
                b.deallocate()
        self._free.clear()
