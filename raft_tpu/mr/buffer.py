"""Owning buffers + pool allocator (see package docstring for the
design mapping to reference mr/allocator.hpp:35 / buffer_base.hpp:39).

Memory accounting (docs/OBSERVABILITY.md): every owning buffer reports
into the default metrics registry — ``raft_tpu_mr_live_bytes{space=}``
(gauge; its ``high_water`` is the peak), ``raft_tpu_mr_alloc_total`` /
``raft_tpu_mr_free_total`` / ``raft_tpu_mr_alloc_bytes_total``
(counters), and pool hit/miss counters.  Allocation failures raise
:class:`~raft_tpu.core.error.AllocationError` carrying the requested
size and the live-byte count instead of the raw backend error."""

from __future__ import annotations

import collections
import threading
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core import metrics as _metrics
from raft_tpu.core.error import AllocationError, expects


def _gauge_live(space: str):
    return _metrics.default_registry().gauge(
        "raft_tpu_mr_live_bytes",
        help="bytes held by live raft_tpu buffers (high_water = peak)",
        labels=("space",)).labels(space=space)


def _account_alloc(space: str, nbytes: int):
    """Record an allocation; returns (bytes_accounted, registry
    generation) — bytes_accounted is None when recording is disabled
    (None, not 0: a genuine zero-size allocation still records its
    alloc/free counter pair) — so the owning buffer schedules a
    matching free for exactly what was recorded: the pair must balance
    even if RAFT_TPU_METRICS is toggled mid-lifetime, and must be
    *dropped* if the registry was reset in between (the recreated
    gauge never saw the alloc; applying the free would drive it
    negative)."""
    reg = _metrics.default_registry()
    if not _metrics.is_enabled():
        return None, reg.generation
    # under the registry lock so the generation returned is exactly the
    # one the gauge update landed in; _add_raw, not inc: both halves of
    # the pair must bypass the enable gate identically — a
    # set_enabled(False) racing in after the check above would
    # otherwise swallow the inc while the buffer still schedules the
    # matching free, driving the gauge negative
    with reg.locked():
        _gauge_live(space)._add_raw(nbytes)
        reg.counter("raft_tpu_mr_alloc_total", help="buffer allocations",
                    labels=("space",)).labels(space=space).inc()
        reg.counter("raft_tpu_mr_alloc_bytes_total",
                    help="cumulative bytes allocated",
                    labels=("space",)).labels(space=space).inc(nbytes)
        return nbytes, reg.generation


def _account_free(space: str, nbytes: int, generation: int) -> None:
    reg = _metrics.default_registry()
    # generation check atomic with the adjustment (a reset racing
    # between them would recreate the gauge and then see the
    # subtraction from an alloc it never recorded); the gauge half
    # bypasses the enable gate: this free balances an alloc that WAS
    # recorded, and dropping it would inflate live bytes forever; the
    # free counter stays gated (a rate metric)
    with reg.locked():
        if generation != reg.generation:
            return  # the recorded alloc died with a registry reset
        _gauge_live(space)._add_raw(-nbytes)
        reg.counter(
            "raft_tpu_mr_free_total", help="buffer frees",
            labels=("space",)).labels(space=space).inc()


def device_memory_stats(device: Optional[jax.Device] = None) -> Dict[str, int]:
    """Bytes in use / limit for a device (cudaMemGetInfo's role,
    reference cudart_utils.h).  Backends without stats return {}."""
    d = device if device is not None else jax.devices()[0]
    try:
        stats = d.memory_stats() or {}
    except Exception:
        return {}
    out = {}
    for key in ("bytes_in_use", "bytes_limit", "peak_bytes_in_use"):
        if key in stats:
            out[key] = int(stats[key])
    return out


class DeviceBuffer:
    """Owning device allocation with explicit lifetime (reference
    ``device_buffer`` = buffer_base over the device allocator,
    mr/buffer_base.hpp:39).

    ``deallocate()`` frees the backing HBM *now* (``jax.Array.delete``)
    rather than when Python GC gets around to it — the dtor semantics
    eager pipelines need when cycling large scratch arrays.
    """

    _space = "device"

    def __init__(self, shape: Tuple[int, ...], dtype=jnp.float32,
                 device: Optional[jax.Device] = None,
                 _array: Optional[jax.Array] = None):
        self.shape = tuple(shape)
        self.dtype = jnp.dtype(dtype)
        self.device = device if device is not None else jax.devices()[0]
        self._accounted, self._accounted_gen = None, 0
        if _array is not None:
            self._array: Optional[jax.Array] = _array
        else:
            try:
                self._array = jax.device_put(
                    jnp.zeros(self.shape, self.dtype), self.device)
            except Exception as e:
                raise AllocationError(
                    "DeviceBuffer allocation failed on %s: %s"
                    % (self.device, e),
                    requested_bytes=self.size_bytes(),
                    live_bytes=int(_gauge_live("device").value)) from e
        self._accounted, self._accounted_gen = _account_alloc(
            self._space, self.size_bytes())

    @classmethod
    def from_array(cls, array) -> "DeviceBuffer":
        """Adopt an existing array (reference buffer_base's
        pointer-adopting ctor)."""
        arr = jnp.asarray(array)
        dev = list(arr.devices())[0]
        return cls(arr.shape, arr.dtype, dev, _array=arr)

    @property
    def data(self) -> jax.Array:
        """The live array (reference ``buffer.data()``)."""
        expects(self._array is not None, "DeviceBuffer: use after deallocate")
        return self._array

    def size_bytes(self) -> int:
        return int(np.prod(self.shape, dtype=np.int64)) * self.dtype.itemsize

    @property
    def deallocated(self) -> bool:
        return self._array is None or self._array.is_deleted()

    def deallocate(self) -> None:
        """Free the device memory immediately; idempotent."""
        if self._array is not None and not self._array.is_deleted():
            self._array.delete()
        self._array = None
        self._release_accounting()

    def _release_accounting(self) -> None:
        if self._accounted is not None:
            _account_free(self._space, self._accounted,
                          self._accounted_gen)
            self._accounted = None

    def __enter__(self) -> "DeviceBuffer":
        return self

    def __exit__(self, *exc) -> None:
        self.deallocate()

    def __del__(self):
        # GC is a legal lifetime end: the accounting must follow it or
        # the live gauge drifts upward on every buffer dropped without
        # an explicit deallocate().  Accounting ONLY — never
        # deallocate(): an adopted (from_array) or escaped (.data)
        # array may still be referenced by the caller, and force-
        # deleting it here would destroy data the caller holds; the
        # backing memory's own lifetime is the array reference's, which
        # GC is already handling.  Guarded for interpreter shutdown,
        # where the metrics module may already be torn down.
        try:
            if getattr(self, "_accounted", None) is not None:
                self._release_accounting()
        except Exception:
            pass


class HostBuffer(DeviceBuffer):
    """Host-side owning buffer (reference ``host_buffer``).  Backed by
    numpy (always host-resident); same explicit-lifetime interface."""

    _space = "host"

    def __init__(self, shape: Tuple[int, ...], dtype=jnp.float32):
        self.shape = tuple(shape)
        self.dtype = jnp.dtype(dtype)
        self.device = None
        self._accounted, self._accounted_gen = None, 0
        try:
            self._np: Optional[np.ndarray] = np.zeros(shape, self.dtype)
        except Exception as e:
            raise AllocationError(
                "HostBuffer allocation failed: %s" % e,
                requested_bytes=self.size_bytes(),
                live_bytes=int(_gauge_live("host").value)) from e
        self._array = None
        self._accounted, self._accounted_gen = _account_alloc(
            self._space, self.size_bytes())

    @classmethod
    def from_array(cls, array) -> "HostBuffer":
        arr = np.asarray(array)
        buf = cls(arr.shape, arr.dtype)
        buf._np = arr  # adopt without copy
        return buf

    @property
    def data(self) -> np.ndarray:
        expects(self._np is not None, "HostBuffer: use after deallocate")
        return self._np

    @property
    def deallocated(self) -> bool:
        return self._np is None

    def deallocate(self) -> None:
        self._np = None
        self._release_accounting()


class PoolAllocator:
    """Freelist reuse of same-(shape, dtype) device buffers (the role of
    RMM's pool resource for repeated eager workspace allocations —
    allocation latency and fragmentation, not capacity, are what it
    buys on a runtime whose heap XLA already owns).

    ``allocate`` returns a pooled buffer when one matches, else a fresh
    one; ``deallocate`` returns the buffer to the pool (device memory
    stays live for reuse).  ``release`` frees everything pooled.

    Like RMM's pool resource, a pool HIT returns the buffer with its
    previous contents — only the fresh-allocation path zero-fills.
    Callers needing zeros must clear the buffer themselves.

    ``max_bytes`` bounds the total bytes pooled across every key: when
    a ``deallocate`` would exceed it, the LEAST-RECENTLY-POOLED buffers
    are freed outright (oldest first, across keys) until the budget
    holds — the ZerosPool byte-bound argument applied to the freelist:
    a consumer cycling many shapes (the out-of-core tier's staging
    buffers) must not pin unbounded device memory.  ``None`` keeps the
    historical per-key-count-only bound.  Evictions are counted
    (``n_evictions`` / ``raft_tpu_mr_pool_evictions_total``).
    """

    def __init__(self, device: Optional[jax.Device] = None,
                 max_pooled_per_key: int = 4,
                 max_bytes: Optional[int] = None):
        expects(max_bytes is None or max_bytes >= 1,
                "PoolAllocator: max_bytes=%r", max_bytes)
        self.device = device if device is not None else jax.devices()[0]
        self.max_pooled_per_key = max_pooled_per_key
        self.max_bytes = max_bytes
        self._free: Dict[Tuple, List[DeviceBuffer]] = {}
        # pooled buffers in pooling order (oldest first) — the byte
        # bound's eviction order; entries are kept in sync with _free
        self._order: List[DeviceBuffer] = []
        self._bytes = 0
        self.n_hits = 0
        self.n_misses = 0
        self.n_evictions = 0

    def _key(self, shape, dtype):
        return (tuple(shape), jnp.dtype(dtype).name)

    def allocate(self, shape, dtype=jnp.float32) -> DeviceBuffer:
        reg = _metrics.default_registry()
        bucket = self._free.get(self._key(shape, dtype))
        if bucket:
            self.n_hits += 1
            reg.counter("raft_tpu_mr_pool_hits_total",
                        help="pool allocations served from freelist").inc()
            buf = bucket.pop()
            self._order.remove(buf)
            self._bytes -= buf.size_bytes()
            return buf
        self.n_misses += 1
        reg.counter("raft_tpu_mr_pool_misses_total",
                    help="pool allocations needing fresh memory").inc()
        return DeviceBuffer(shape, dtype, self.device)

    def _evict_oldest(self) -> None:
        buf = self._order.pop(0)
        self._free[self._key(buf.shape, buf.dtype)].remove(buf)
        self._bytes -= buf.size_bytes()
        self.n_evictions += 1
        _metrics.default_registry().counter(
            "raft_tpu_mr_pool_evictions_total",
            help="pooled buffers freed to hold the byte budget").inc()
        buf.deallocate()

    def deallocate(self, buf: DeviceBuffer) -> None:
        expects(not buf.deallocated,
                "PoolAllocator: cannot pool a deallocated buffer")
        nbytes = buf.size_bytes()
        if self.max_bytes is not None and nbytes > self.max_bytes:
            # a buffer alone over budget can never be pooled — freeing
            # the whole pool for it would be strictly worse
            buf.deallocate()
            return
        bucket = self._free.setdefault(self._key(buf.shape, buf.dtype), [])
        if len(bucket) >= self.max_pooled_per_key:
            buf.deallocate()
            return
        bucket.append(buf)
        self._order.append(buf)
        self._bytes += nbytes
        if self.max_bytes is not None:
            while self._bytes > self.max_bytes:
                self._evict_oldest()

    def pooled_bytes(self) -> int:
        return self._bytes

    def release(self) -> None:
        """Free all pooled memory (RMM pool release)."""
        for bs in self._free.values():
            for b in bs:
                b.deallocate()
        self._free.clear()
        self._order.clear()
        self._bytes = 0


class ZerosPool:
    """Device-resident zero-block cache keyed by (shape, dtype).

    The zero-copy data path (docs/ZERO_COPY.md) keeps needing the same
    constant zero blocks on device: serve's pad-to-bucket tail, the
    mnmg ring-merge index pad, the comms p2p rank-major assembly rows.
    ``jnp.pad``/``jnp.zeros`` materialize a *fresh* device zeros region
    per call — pure ``device_put`` churn for a value that never changes.
    jax arrays are immutable, so ONE cached block per (shape, dtype)
    can be shared by every concurrent reader forever; consumers compose
    it with ``jnp.concatenate`` / ``jnp.stack`` instead of re-creating
    it.  (Contrast :class:`PoolAllocator`, whose buffers are owned
    exclusively and carry arbitrary stale contents.)

    Bounded LRU — by block count (``max_entries``) AND by total bytes
    (``max_bytes``): a count-only bound would let 64 wide serve tails
    pin hundreds of MiB of device memory for the process lifetime.  A
    single block larger than ``max_bytes`` is returned fresh and never
    cached (caching it would evict everything else for a shape too big
    to plausibly recur).  Thread-safe; hit/miss counters land in the
    registry (``raft_tpu_mr_zeros_pool_{hits,misses}_total``).
    ``Session.destroy()`` releases the default pool.
    """

    def __init__(self, max_entries: int = 64,
                 max_bytes: int = 64 << 20,
                 device: Optional[jax.Device] = None):
        expects(max_entries >= 1, "ZerosPool: max_entries=%d", max_entries)
        expects(max_bytes >= 1, "ZerosPool: max_bytes=%d", max_bytes)
        self.max_entries = int(max_entries)
        self.max_bytes = int(max_bytes)
        self.device = device
        self._lock = threading.Lock()
        self._blocks: "collections.OrderedDict[Tuple, jax.Array]" = \
            collections.OrderedDict()
        self._bytes = 0
        self.n_hits = 0
        self.n_misses = 0

    @staticmethod
    def _key_bytes(key) -> int:
        shape, dname = key
        return (int(np.prod(shape, dtype=np.int64))
                * jnp.dtype(dname).itemsize)

    def _counter(self, name: str):
        return _metrics.default_registry().counter(
            name, help="zeros-pool block reuse (docs/ZERO_COPY.md)")

    def get(self, shape, dtype=jnp.float32) -> jax.Array:
        """The shared zero block for (shape, dtype).  Read-only by
        convention — callers must only compose it (concatenate/stack/
        where), never donate it to an executable or adopt-and-delete
        it; ``.at[].set`` is fine (functional update, fresh result)."""
        key = (tuple(int(s) for s in shape), jnp.dtype(dtype).name)
        nbytes = self._key_bytes(key)
        with self._lock:
            blk = self._blocks.get(key)
            if blk is not None and not blk.is_deleted():
                self._blocks.move_to_end(key)
                self.n_hits += 1
                self._counter("raft_tpu_mr_zeros_pool_hits_total").inc()
                return blk
            self.n_misses += 1
            self._counter("raft_tpu_mr_zeros_pool_misses_total").inc()
        # allocate outside the lock (a device allocation can be slow);
        # a racing duplicate is harmless — last writer wins the slot
        blk = jnp.zeros(key[0], dtype)
        if self.device is not None:
            blk = jax.device_put(blk, self.device)
        if nbytes > self.max_bytes:
            return blk                 # oversize: never cached
        with self._lock:
            if key not in self._blocks:
                self._bytes += nbytes
            self._blocks[key] = blk
            self._blocks.move_to_end(key)
            while self._blocks and (len(self._blocks) > self.max_entries
                                    or self._bytes > self.max_bytes):
                old_key, _ = self._blocks.popitem(last=False)
                self._bytes -= self._key_bytes(old_key)
        return blk

    def pooled_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def __len__(self) -> int:
        with self._lock:
            return len(self._blocks)

    def release(self) -> None:
        """Drop every cached block (GC frees the device memory — the
        blocks may still be referenced by in-flight consumers, so no
        eager delete)."""
        with self._lock:
            self._blocks.clear()
            self._bytes = 0


_default_zeros_pool = ZerosPool()


def default_zeros_pool() -> ZerosPool:
    """The process-wide shared zeros cache (what :func:`zeros_cached`
    reads; serve/comms/mnmg pad paths all share it)."""
    return _default_zeros_pool


def zeros_cached(shape, dtype=jnp.float32) -> jax.Array:
    """Shared device-resident zeros of (shape, dtype) from the default
    :class:`ZerosPool` — the drop-in replacement for ``jnp.zeros`` on
    hot eager paths that re-create the same constant block per call."""
    return _default_zeros_pool.get(shape, dtype)
