"""One owner for raft_tpu's runtime perf knobs.

The reference keeps its tuning surface in one-place config structs
(cpp/include/raft/spatial/knn/ann_common.h:42-72); raft_tpu's analog is
this module: every performance knob that used to be a scattered
``os.environ`` read resolves here, with the env vars kept as aliases.

Resolution order (first hit wins):

1. an explicit function argument at the call site (never reaches here);
2. an active :func:`override` context, innermost first;
3. a value set by :func:`configure`;
4. the knob's env var (``RAFT_TPU_*`` — the historical spelling);
5. a loaded **tuning table** (shape-class-aware lookups through
   :func:`tuned` only — the persisted winners of the
   ``tools/autotune.py`` sweep, opt-in via
   :func:`load_tuning_table` / ``RAFT_TPU_TUNING_TABLE``;
   docs/TUNING.md "Bench-driven autotuning");
6. the built-in default.

Impl-choice knobs (those with a ``choices`` whitelist below) are OWNED
by the candidate registry (:mod:`raft_tpu.core.tuning`): consumers
resolve them through ``tuning.resolve(knob, ...)`` — which calls
:func:`tuned` here — and validation/legality lives in the registry, not
at call sites.  Free-form numeric/list knobs read through the typed
helpers (:func:`get_int` / :func:`get_float` / the ``_list`` variants)
so a malformed env value fails as a :class:`LogicError` naming the knob
and its env var, not a bare ``ValueError`` deep inside construction.

THE executable-cache caveat, stated once: knobs are consumed at *trace*
time.  ``jax.jit`` caches executables by shape+dtype, so consumers
already compiled for a given shape will NOT retrace when a knob changes
mid-process — the change affects only not-yet-compiled shapes.
:func:`configure` and :func:`override` warn when they change a knob
that some trace has already consumed; direct env-var writes cannot be
intercepted, so prefer the functions (or explicit arguments, which
reach the trace as Python values and always take effect).

Knobs
-----
select_impl
    Per-row top-k implementation for :func:`raft_tpu.spatial.select_k`
    (``topk`` | ``approx`` | ``approx95`` | ``chunked`` | ``pallas``).
tile_merge
    Tile-scan kNN per-tile selection strategy
    (:func:`raft_tpu.spatial.tiled_knn`): ``tile_topk`` | ``direct``.
knn_tile_merge
    Pallas fused-kNN/select merge network
    (:mod:`raft_tpu.ops.knn_tile`): ``merge`` | ``fullsort`` |
    ``sorttile`` (``skip`` is argument-only: an attribution probe that
    returns wrong results by design and must never be reachable from
    config).
fused_knn_impl
    :func:`raft_tpu.spatial.fused_l2_knn` path: ``xla`` | ``pallas`` |
    ``xla_fused`` (the XLA-composed emulation of the fused kernel —
    the off-TPU fallback and bitwise correctness oracle); unset =
    per-backend auto (currently ``xla`` everywhere, the r4 measured
    default).
knn_block_q / knn_block_n
    Fused-kNN kernel tile shape (:mod:`raft_tpu.ops.knn_tile` and its
    ``xla_fused`` emulation): query rows / index columns per tile.
    Integer ladders validated by the registry's legality predicates
    (sublane/lane multiples + best-effort VMEM fit —
    docs/TUNING.md "Kernel block-shape knobs").
nn_block_n
    Fused 1-NN kernel index-tile width
    (:mod:`raft_tpu.ops.nn_tile`); same ladder discipline.
ivf_scan_impl
    IVF-Flat probe→scan→select path (:func:`raft_tpu.spatial.ann.
    ivf_flat_search`): ``xla`` (gather + einsum + select, the
    reference oracle) | ``pallas`` (fused one-pass slot-streaming
    kernel, no materialized gather block) | ``pallas_bf16``
    (bf16-multiplicand variant, f32 accumulate); unset = per-backend
    auto (currently ``xla`` everywhere until the TPU table lands).
pq_adc
    IVF-PQ ADC lookup (:func:`raft_tpu.spatial.ann.ivf_pq_search`):
    ``gather`` (per-element LUT) | ``onehot`` (one-hot einsum).
    Resolved at call time, not trace time.
spmv_impl
    CSR SpMV (:func:`raft_tpu.sparse.linalg.csr_spmv`): ``segment``
    (gather + sorted segment-sum) | ``cumsum`` (prefix-sum form) |
    ``sortscan`` (gather-free: sort+scan formulation of the x read).
mnmg_merge
    Cross-shard top-k merge topology for the SPMD sharded searches
    (:func:`raft_tpu.spatial.mnmg_knn.mnmg_knn` /
    ``mnmg_ivf_flat_search`` and the sharded serve dispatch):
    ``allgather`` (one wide collective + one re-selection) | ``ring``
    (ppermute streaming, (nq, 2k) peak merge memory) |
    ``hierarchical`` (allgather within a host group, ring across
    groups — the HiCCL decomposition applied to top-k).  Consumed at
    trace time (the executable-cache caveat applies); the serve layer
    pins it per service at construction.
serve_bucket_rungs
    Default shape-bucket ladder for :mod:`raft_tpu.serve` services:
    ``pow2`` (power-of-two rungs up to the service's max batch rows) or
    a comma-separated ascending row list (``"64,256,1024"``).  Free-form
    (validated by :func:`raft_tpu.serve.bucketing.resolve_rungs`).
serve_max_wait_ms
    Default micro-batching window in milliseconds: how long a queued
    request may wait for co-batched company before the batch dispatches
    anyway.  Free-form float; resolved at service construction (the
    serve layer, not a trace, consumes it — no executable-cache caveat).
serve_queue_cap
    Default admission-control cap on queued requests per service;
    beyond it, ``submit`` raises
    :class:`~raft_tpu.core.error.ServiceOverloadError`.  Free-form int.
serve_ann_nprobe
    Default probe count for :class:`raft_tpu.serve.ANNService`
    (``0`` = the served index's build-time default).  Free-form int;
    runtime-resolved at service construction.
serve_ann_nprobe_ladder
    Comma-separated candidate ``nprobe`` cells an ``ANNService`` warms
    (every bucket rung × every cell) and :meth:`calibrate` searches for
    the smallest cell meeting a recall target.  Cells above the index's
    ``nlist`` are clamped.  Free-form list.
serve_ann_delta_cap
    Capacity (rows) of the append-only delta segment that absorbs
    :meth:`ANNService.insert` between compactions; a full delta sheds
    inserts with :class:`~raft_tpu.core.error.ServiceOverloadError`.
    Free-form int.
serve_ann_compact_rows
    Delta-row threshold at which the serve worker loop compacts (re-
    clusters the delta into IVF slots and atomically swaps the index);
    ``0`` disables automatic compaction.  Free-form int.
serve_ann_device_budget_bytes
    Device-memory budget for the out-of-core ANN tier
    (:class:`raft_tpu.serve.ANNService` ``ooc=True``): bytes the
    service may hold device-resident for slot vectors — the
    frequency-promoted hot set plus the double-buffered TilePool
    staging window (docs/SERVING.md "Out-of-core serving").  ``0``
    (the default) means no budget is configured and an ``ooc=True``
    service must pass ``device_budget_bytes=`` explicitly.  Free-form
    int; runtime-resolved at service construction.
serve_breaker_threshold
    Consecutive batch failures that trip a service's circuit breaker
    (:class:`raft_tpu.serve.resilience.CircuitBreaker`); ``0`` disables
    consecutive tracking.  Free-form int; runtime-resolved at service
    construction.
serve_breaker_window / serve_breaker_window_failures
    Windowed failure tracking: trip when the last ``window`` batch
    outcomes contain at least ``window_failures`` failures (catches a
    flapping service that never fails *consecutively* enough).
    ``window_failures=0`` disables windowed tracking.  Free-form ints.
serve_breaker_cooldown_ms
    How long a tripped (open) breaker sheds before letting half-open
    probe traffic through.  Free-form float milliseconds.
serve_ann_degrade_frac
    Queue-pressure threshold for :class:`raft_tpu.serve.ANNService`
    degraded-mode dispatch: when queued requests reach this fraction of
    ``serve_queue_cap`` (or the breaker is half-open after a trip), the
    service steps down its calibrated ``nprobe`` ladder — lower recall,
    lower latency — instead of shedding, and restores the calibrated
    cell when pressure clears.  ``0`` disables the brownout.  Free-form
    float in (0, 1].
serve_tenant_weights
    Default multi-tenant traffic-shaping spec for serve services
    (docs/SERVING.md "Traffic shaping"): a comma-separated
    ``name:weight`` list (``"interactive:4,bulk:1"``) naming the
    tenants and their weighted-fair share of each coalesce window and
    of the admission cap.  Empty (the default) = single-queue serving
    (every request rides one implicit default tenant).  Free-form;
    runtime-resolved at service construction.
serve_hedge_ms
    Fixed hedge threshold for replicated services
    (``KNNService(replicas=...)``): a batch whose execution exceeds
    this many milliseconds is re-dispatched to a second replica with
    first-result-wins resolution.  ``0`` (the default) = adaptive: the
    threshold is ``serve_hedge_factor`` × the tracked per-bucket-rung
    p99, floored at ``serve_hedge_min_ms``.  Free-form float ms.
serve_hedge_factor
    Multiplier on the per-rung p99 execution latency that sets the
    adaptive hedge threshold (only consulted when ``serve_hedge_ms`` is
    0).  Free-form float.
serve_hedge_min_ms
    Floor for the adaptive hedge threshold — hedging below it would
    duplicate healthy work on latency noise.  Free-form float ms.
flight_events
    Ring capacity (events) of the process-wide
    :class:`raft_tpu.core.flight.FlightRecorder` — the bounded-memory
    contract of the always-on flight recorder
    (docs/OBSERVABILITY.md "Flight recorder & request tracing").
    Consumed once, lazily, when the default recorder is first used.
    ``RAFT_TPU_FLIGHT=0`` (not a knob — an env gate like
    ``RAFT_TPU_METRICS``) disables recording entirely.  Free-form int.
serve_slo_target_ms
    Per-request latency objective for the per-tenant SLO tracker every
    serve service carries (docs/OBSERVABILITY.md): a resolved request
    slower than this counts as an SLO miss.  ``0`` = deadline-only
    (only blown deadlines and failures miss).  Free-form float ms;
    runtime-resolved at service construction.
serve_slo_objective
    The availability objective in (0, 1) the burn rate is measured
    against (``burn = miss_rate / (1 - objective)``; burn > 1 spends
    error budget faster than it accrues).  Free-form float.
serve_slo_windows_s
    Comma-separated burn-rate window lengths in seconds (multi-window
    alerting: the short window catches a fast burn, the long one a
    slow leak).  Free-form list.
persist_fsync
    Write-ahead-log durability policy for persistent services
    (:mod:`raft_tpu.persist`; docs/PERSISTENCE.md): ``always`` fsyncs
    before every insert acknowledge (no acknowledged loss, ever),
    ``batch`` defers the fsync to the next maintenance tick (bounded
    loss window, much cheaper), ``off`` leaves durability to the OS
    page cache.  Free-form (validated by the persist layer at
    construction); runtime-resolved.
persist_snapshot_interval_s
    Minimum seconds between interval-driven snapshots of a dirty
    serving state (taken on the serve worker's maintenance seam from
    the immutable ``_AnnState`` — never mid-batch).  Free-form float;
    runtime-resolved.
persist_scrub_chunks
    Integrity-scrub units (snapshot chunks / out-of-core host-store
    slots) re-checksummed per maintenance tick; ``0`` disables the
    background scrubber.  Free-form int; runtime-resolved.
ops_healthz_ttl_s
    TTL of the ops plane's cached full ``health_check()`` verdict
    (``/healthz?full=1``; docs/OBSERVABILITY.md "Ops plane"): scrapes
    within the window share one battery run.  Free-form float;
    runtime-resolved at :class:`raft_tpu.serve.opsplane.OpsPlane`
    construction.
ops_sentinel_interval_s
    Minimum seconds between anomaly-sentinel evaluations
    (:mod:`raft_tpu.serve.sentinel`) — both the worker-seam pokes and
    the ops plane's fallback ticker rate-limit to it.  Free-form
    float; runtime-resolved.
ops_sentinel_latency_factor
    Breach multiplier for the ``exec_latency`` rule: a service's
    windowed mean exec latency above this many times its rolling
    (breach-frozen) baseline trips the sentinel.  Free-form float; runtime-resolved.
ops_sentinel_min_samples
    Minimum observed batches (and per-tenant SLO outcomes) before the
    baseline-relative rules may judge — cold-start noise must not
    trip alarms.  Free-form int; runtime-resolved.
ops_sentinel_queue_frac
    ``queue_depth`` rule threshold as a fraction of the service's
    admission cap.  Free-form float in (0, 1]; runtime-resolved.
ops_sentinel_burn
    ``slo_burn`` rule threshold on the shortest-window error-budget
    burn rate (1.0 = budget spent exactly as fast as it accrues).
    Free-form float; runtime-resolved.
ops_sentinel_wal_records
    ``wal_depth`` rule threshold: un-snapshotted write-ahead-log
    records above this mean snapshots stopped containing the journal.
    Free-form int; runtime-resolved.
ops_sentinel_stall_frac
    ``tile_stall`` rule threshold on the exposed-stall fraction of
    H2D transfer time over the last window (the prefetch stopped
    hiding transfers).  Free-form float in (0, 1]; runtime-resolved.
ops_sentinel_rejoin_ms_per_record
    ``rejoin_lag`` rule threshold: a rejoining fleet worker's restore
    time divided by its replayed WAL records, in milliseconds per
    record — replay time is judged *relative to WAL depth*, so a deep
    journal is allowed a long restore but a shallow one is not.
    Free-form float; runtime-resolved.
ops_sentinel_rejoin_hold_s
    How long after a rejoin the ``rejoin_lag`` rule keeps judging it
    (seconds).  A slow restore is an incident about ONE rejoin, not a
    steady state: the breach clears once the rejoin ages past this
    hold (the edge was already counted and flight-recorded), so a
    healed fleet's ``/fleet/healthz`` goes back to healthy.  Free-form
    float; runtime-resolved.
fleet_lease_interval_s
    Fleet worker heartbeat period (:mod:`raft_tpu.fleet.router`); the
    router's lease monitor runs at the same cadence.  Free-form
    float; runtime-resolved at :class:`~raft_tpu.fleet.router.Router`
    construction.
fleet_lease_misses
    Consecutive missed heartbeat intervals before the router evicts a
    worker (typed eviction, ``worker_dead`` sentinel rule).
    Free-form int; runtime-resolved.
fleet_retry_max
    Per-shard/worker dispatch retry budget at the router (transient
    comm faults, worker restarts).  Free-form int; runtime-resolved.
fleet_retry_backoff_s
    Initial router retry backoff (doubles per attempt; worker
    ``retry_after_s`` hints override it upward).  Free-form float;
    runtime-resolved.
fleet_hedge_ms
    Replicated-mode hedge delay: a primary silent this long gets a
    hedged re-dispatch to the next worker in rendezvous order; ``0``
    disables hedging.  Free-form float; runtime-resolved.
fleet_timeout_s
    Default end-to-end deadline for router requests (search/insert)
    when the caller passes none.  Free-form float; runtime-resolved.
fleet_inflight_cap
    Router global admission cap: in-flight requests at or above this
    shed with a typed :class:`~raft_tpu.core.error
    .ServiceOverloadError` before any dispatch.  Free-form int;
    runtime-resolved.
"""

from __future__ import annotations

import json
import os
import threading
import warnings
from contextlib import contextmanager
from typing import Dict, Iterator, Optional, Tuple

__all__ = [
    "configure", "override", "get", "describe", "tuned",
    "knob_default", "get_int", "get_float", "get_int_list",
    "get_float_list", "load_tuning_table", "install_tuning_table",
    "clear_tuning_table", "suspend_tuning", "tuning_table_info",
    "discover_tuning_table",
]

# knob -> (env alias, default, legal values settable via configure);
# choices None = free-form (the consumer validates — numeric/list knobs
# cannot be enumerated here)
_KNOBS: Dict[str, Tuple[str, Optional[str], Optional[Tuple[str, ...]]]] = {
    "select_impl": ("RAFT_TPU_SELECT_IMPL", "topk",
                    ("topk", "approx", "approx95", "chunked", "pallas")),
    "tile_merge": ("RAFT_TPU_TILE_MERGE", "tile_topk",
                   ("tile_topk", "direct")),
    "knn_tile_merge": ("RAFT_TPU_KNN_TILE_MERGE", "merge",
                       ("merge", "fullsort", "sorttile")),
    "fused_knn_impl": ("RAFT_TPU_FUSED_KNN_IMPL", None,
                       ("xla", "pallas", "xla_fused")),
    "knn_block_q": ("RAFT_TPU_KNN_BLOCK_Q", "256",
                    ("64", "128", "256", "512")),
    "knn_block_n": ("RAFT_TPU_KNN_BLOCK_N", "1024",
                    ("256", "512", "1024", "2048", "4096")),
    "nn_block_n": ("RAFT_TPU_NN_BLOCK_N", "1024",
                   ("256", "512", "1024", "2048", "4096")),
    "ivf_scan_impl": ("RAFT_TPU_IVF_SCAN_IMPL", None,
                      ("xla", "pallas", "pallas_bf16")),
    "pq_adc": ("RAFT_TPU_PQ_ADC", "gather", ("gather", "onehot")),
    "spmv_impl": ("RAFT_TPU_SPMV_IMPL", "segment",
                  ("segment", "cumsum", "sortscan")),
    "mnmg_merge": ("RAFT_TPU_MNMG_MERGE", "allgather",
                   ("allgather", "ring", "hierarchical")),
    "serve_bucket_rungs": ("RAFT_TPU_SERVE_BUCKET_RUNGS", "pow2", None),
    "serve_max_wait_ms": ("RAFT_TPU_SERVE_MAX_WAIT_MS", "2", None),
    "serve_queue_cap": ("RAFT_TPU_SERVE_QUEUE_CAP", "1024", None),
    "serve_ann_nprobe": ("RAFT_TPU_SERVE_ANN_NPROBE", "0", None),
    "serve_ann_nprobe_ladder": ("RAFT_TPU_SERVE_ANN_NPROBE_LADDER",
                                "4,8,16,32,64", None),
    "serve_ann_delta_cap": ("RAFT_TPU_SERVE_ANN_DELTA_CAP", "4096", None),
    "serve_ann_compact_rows": ("RAFT_TPU_SERVE_ANN_COMPACT_ROWS",
                               "2048", None),
    "serve_ann_device_budget_bytes": (
        "RAFT_TPU_SERVE_ANN_DEVICE_BUDGET_BYTES", "0", None),
    "serve_breaker_threshold": ("RAFT_TPU_SERVE_BREAKER_THRESHOLD",
                                "5", None),
    "serve_breaker_window": ("RAFT_TPU_SERVE_BREAKER_WINDOW",
                             "16", None),
    "serve_breaker_window_failures": (
        "RAFT_TPU_SERVE_BREAKER_WINDOW_FAILURES", "8", None),
    "serve_breaker_cooldown_ms": ("RAFT_TPU_SERVE_BREAKER_COOLDOWN_MS",
                                  "250", None),
    "serve_ann_degrade_frac": ("RAFT_TPU_SERVE_ANN_DEGRADE_FRAC",
                               "0.75", None),
    "serve_tenant_weights": ("RAFT_TPU_SERVE_TENANT_WEIGHTS", "", None),
    "serve_hedge_ms": ("RAFT_TPU_SERVE_HEDGE_MS", "0", None),
    "serve_hedge_factor": ("RAFT_TPU_SERVE_HEDGE_FACTOR", "1.5", None),
    "serve_hedge_min_ms": ("RAFT_TPU_SERVE_HEDGE_MIN_MS", "10", None),
    "flight_events": ("RAFT_TPU_FLIGHT_EVENTS", "4096", None),
    "persist_fsync": ("RAFT_TPU_PERSIST_FSYNC", "always", None),
    "persist_snapshot_interval_s": (
        "RAFT_TPU_PERSIST_SNAPSHOT_INTERVAL_S", "30", None),
    "persist_scrub_chunks": ("RAFT_TPU_PERSIST_SCRUB_CHUNKS", "4", None),
    "serve_slo_target_ms": ("RAFT_TPU_SERVE_SLO_TARGET_MS", "100", None),
    "serve_slo_objective": ("RAFT_TPU_SERVE_SLO_OBJECTIVE",
                            "0.99", None),
    "serve_slo_windows_s": ("RAFT_TPU_SERVE_SLO_WINDOWS_S",
                            "60,300", None),
    "ops_healthz_ttl_s": ("RAFT_TPU_OPS_HEALTHZ_TTL_S", "15", None),
    "ops_sentinel_interval_s": ("RAFT_TPU_OPS_SENTINEL_INTERVAL_S",
                                "1", None),
    "ops_sentinel_latency_factor": (
        "RAFT_TPU_OPS_SENTINEL_LATENCY_FACTOR", "3", None),
    "ops_sentinel_min_samples": ("RAFT_TPU_OPS_SENTINEL_MIN_SAMPLES",
                                 "20", None),
    "ops_sentinel_queue_frac": ("RAFT_TPU_OPS_SENTINEL_QUEUE_FRAC",
                                "0.8", None),
    "ops_sentinel_burn": ("RAFT_TPU_OPS_SENTINEL_BURN", "2", None),
    "ops_sentinel_wal_records": ("RAFT_TPU_OPS_SENTINEL_WAL_RECORDS",
                                 "100000", None),
    "ops_sentinel_stall_frac": ("RAFT_TPU_OPS_SENTINEL_STALL_FRAC",
                                "0.5", None),
    "ops_sentinel_rejoin_ms_per_record": (
        "RAFT_TPU_OPS_SENTINEL_REJOIN_MS_PER_RECORD", "50", None),
    "ops_sentinel_rejoin_hold_s": (
        "RAFT_TPU_OPS_SENTINEL_REJOIN_HOLD_S", "10", None),
    "fleet_lease_interval_s": ("RAFT_TPU_FLEET_LEASE_INTERVAL_S",
                               "0.5", None),
    "fleet_lease_misses": ("RAFT_TPU_FLEET_LEASE_MISSES", "3", None),
    "fleet_retry_max": ("RAFT_TPU_FLEET_RETRY_MAX", "3", None),
    "fleet_retry_backoff_s": ("RAFT_TPU_FLEET_RETRY_BACKOFF_S",
                              "0.05", None),
    "fleet_hedge_ms": ("RAFT_TPU_FLEET_HEDGE_MS", "100", None),
    "fleet_timeout_s": ("RAFT_TPU_FLEET_TIMEOUT_S", "10", None),
    "fleet_inflight_cap": ("RAFT_TPU_FLEET_INFLIGHT_CAP",
                           "256", None),
}

# knobs resolved at *runtime* (service/object construction), never baked
# into a trace: changing one later affects the next construction and the
# executable-cache caveat warning does not apply
_RUNTIME_KNOBS = frozenset(
    ("serve_bucket_rungs", "serve_max_wait_ms", "serve_queue_cap",
     "serve_ann_nprobe", "serve_ann_nprobe_ladder",
     "serve_ann_delta_cap", "serve_ann_compact_rows",
     "serve_ann_device_budget_bytes",
     "serve_breaker_threshold", "serve_breaker_window",
     "serve_breaker_window_failures", "serve_breaker_cooldown_ms",
     "serve_ann_degrade_frac", "serve_tenant_weights",
     "serve_hedge_ms", "serve_hedge_factor", "serve_hedge_min_ms",
     "flight_events", "serve_slo_target_ms", "serve_slo_objective",
     "serve_slo_windows_s", "persist_fsync",
     "persist_snapshot_interval_s", "persist_scrub_chunks",
     "ops_healthz_ttl_s", "ops_sentinel_interval_s",
     "ops_sentinel_latency_factor", "ops_sentinel_min_samples",
     "ops_sentinel_queue_frac", "ops_sentinel_burn",
     "ops_sentinel_wal_records", "ops_sentinel_stall_frac",
     "ops_sentinel_rejoin_ms_per_record", "ops_sentinel_rejoin_hold_s",
     "fleet_lease_interval_s",
     "fleet_lease_misses", "fleet_retry_max", "fleet_retry_backoff_s",
     "fleet_hedge_ms", "fleet_timeout_s", "fleet_inflight_cap"))

# sentinel for "no layer claimed this knob" during resolution — distinct
# from None, which a caller may store in an override frame to mean
# "revert to env/default inside this scope" (configure() expresses the
# same revert by popping its entry)
_UNSET = object()

_values: Dict[str, Optional[str]] = {}
_tls = threading.local()
# knob -> set of values already handed to some trace (consumed); used
# only to decide whether a later change deserves the caveat warning
_consumed: Dict[str, set] = {}
_lock = threading.Lock()


def _frames():
    return getattr(_tls, "frames", ())


def _walk(name: str) -> Tuple[object, Optional[str]]:
    """One knob through the PRE-TABLE layer order (module doc):
    innermost override frame → configure() value → env.  Returns
    ``(value, layer)``; ``(_UNSET, None)`` means no pre-table layer
    claimed it (the caller finishes with table and/or default).  A
    literal None in a frame is the scoped "revert to
    env/table/default" (configure(knob=None) pops its entry; a scoped
    frame cannot pop, so the revert is interpreted here — it skips
    configure() too).  THE single copy of this dance — get(), tuned()
    and describe() share it and must never skew."""
    env, _, _ = _KNOBS[name]
    val = _UNSET
    for frame in reversed(_frames()):
        if name in frame:
            val = frame[name]
            break
    if val is _UNSET and name in _values:
        return _values[name], "configure"
    if val is not _UNSET and val is not None:
        return val, "override"
    ev = os.environ.get(env)
    if ev is not None:
        return ev, "env"
    return _UNSET, None


def _resolve(name: str) -> Optional[str]:
    """:func:`_walk` finished with the default rung (NO table — the
    shape-aware :func:`tuned` is the table-consulting entry)."""
    val, _ = _walk(name)
    return _KNOBS[name][1] if val is _UNSET else val


def get(name: str) -> Optional[str]:
    """Resolve a knob (module-doc order, WITHOUT the tuning-table
    layer — :func:`tuned` is the shape-aware entry) and mark it
    consumed.

    Returns the raw string (or None for an unset no-default knob);
    registry-owned knobs validate through
    :mod:`raft_tpu.core.tuning`, free-form knobs at their call sites.
    """
    val = _resolve(name)
    with _lock:
        _consumed.setdefault(name, set()).add(val)
    return val


def knob_default(name: str) -> Optional[str]:
    """The built-in default of ``name`` (the bottom resolution rung)."""
    if name not in _KNOBS:
        raise ValueError(
            f"raft_tpu.config: unknown knob {name!r} "
            f"(have: {', '.join(sorted(_KNOBS))})")
    return _KNOBS[name][1]


def tuned(name: str, op: Optional[str] = None,
          dtype: Optional[str] = None,
          dims: Optional[Dict[str, int]] = None
          ) -> Tuple[Optional[str], str]:
    """Shape-class-aware resolution: the full module-doc ladder
    INCLUDING the tuning table (override → configure → env → table →
    default).  Returns ``(value, layer)`` where ``layer`` names the
    rung that answered (``"override" | "configure" | "env" | "table" |
    "default"``) — the registry (:mod:`raft_tpu.core.tuning`) is the
    intended caller and needs the layer to treat table answers as
    advisory.  Marks the knob consumed exactly like :func:`get` (the
    executable-cache caveat applies unchanged).
    """
    if name not in _KNOBS:
        raise ValueError(
            f"raft_tpu.config: unknown knob {name!r} "
            f"(have: {', '.join(sorted(_KNOBS))})")
    val, layer = _walk(name)
    if val is _UNSET:
        # nothing above claimed it (incl. the scoped revert
        # override(knob=None)): the table answers before the default,
        # so a revert restores the TABLE's value, not the built-in
        tv = _table_answer(name, op, dtype, dims)
        if tv is not None:
            val, layer = tv, "table"
        else:
            val, layer = _KNOBS[name][1], "default"
    with _lock:
        _consumed.setdefault(name, set()).add(val)
    return val, layer


# --------------------------------------------------------------------- #
# typed knob parsing — free-form numeric/list knobs fail HERE, as a
# LogicError naming the knob and its env var, not as a bare ValueError
# deep inside service construction (the ad-hoc-parse bug class)
# --------------------------------------------------------------------- #
def _parse_error(name: str, raw, kind: str):
    from raft_tpu.core.error import LogicError

    env = _KNOBS[name][0]
    return LogicError(
        f"raft_tpu.config: {name}={raw!r} is not a valid {kind} "
        f"(knob {name}, env var {env})")


def get_int(name: str) -> int:
    """:func:`get` + int parse; malformed → :class:`LogicError`."""
    raw = get(name)
    try:
        return int(raw)
    except (TypeError, ValueError):
        raise _parse_error(name, raw, "integer") from None


def get_float(name: str) -> float:
    """:func:`get` + float parse; malformed → :class:`LogicError`."""
    raw = get(name)
    try:
        return float(raw)
    except (TypeError, ValueError):
        raise _parse_error(name, raw, "number") from None


def _split_list(raw) -> Tuple[str, ...]:
    return tuple(tok.strip() for tok in str(raw).split(",")
                 if tok.strip())


def get_int_list(name: str) -> Tuple[int, ...]:
    """:func:`get` + comma-separated int-list parse; malformed →
    :class:`LogicError` naming the knob and env var."""
    raw = get(name)
    try:
        return tuple(int(tok) for tok in _split_list(raw))
    except (TypeError, ValueError):
        raise _parse_error(name, raw, "comma-separated integer list"
                           ) from None


def get_float_list(name: str) -> Tuple[float, ...]:
    """:func:`get` + comma-separated float-list parse; malformed →
    :class:`LogicError` naming the knob and env var."""
    raw = get(name)
    try:
        return tuple(float(tok) for tok in _split_list(raw))
    except (TypeError, ValueError):
        raise _parse_error(name, raw, "comma-separated number list"
                           ) from None


# --------------------------------------------------------------------- #
# the tuning-table layer (docs/TUNING.md "Bench-driven autotuning")
#
# Opt-in by design: with no table loaded, resolution is byte-identical
# to the pre-table ladder.  A table is installed explicitly
# (load_tuning_table / install_tuning_table) or via the
# RAFT_TPU_TUNING_TABLE env var ("auto" = discover the checked-in
# table matching this backend's fingerprint under raft_tpu/tuning/).
# --------------------------------------------------------------------- #
TUNING_TABLE_VERSION = 1
TUNING_TABLE_ENV = "RAFT_TPU_TUNING_TABLE"

_table: Optional[Dict] = None          # validated+indexed table
_table_env_checked = False
_table_warned: set = set()             # one-time stale warnings, by key


def _tables_dir() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "tuning")


def _fingerprint_matches(fp: Dict) -> bool:
    from raft_tpu.core.tuning import backend_fingerprint

    live = backend_fingerprint()
    return all(fp.get(k) == live[k] for k in
               ("platform", "device_kind", "device_count"))


def _warn_stale_once(key: str, msg: str) -> None:
    with _lock:
        if key in _table_warned:
            return
        _table_warned.add(key)
    warnings.warn(msg, stacklevel=3)


def _index_table(doc: Dict, source: str) -> Dict:
    """Validate a parsed table document and build its lookup index;
    corrupt tables fail LOUDLY (a silently half-read table would pin
    impls nobody swept)."""
    from raft_tpu.core.error import LogicError

    def bad(why):
        return LogicError(
            "raft_tpu.config: corrupt tuning table %s — %s"
            % (source, why))

    if not isinstance(doc, dict):
        raise bad("top level is not an object")
    if doc.get("version") != TUNING_TABLE_VERSION:
        raise bad("version=%r (this build reads version %d)"
                  % (doc.get("version"), TUNING_TABLE_VERSION))
    fp = doc.get("fingerprint")
    if (not isinstance(fp, dict)
            or not all(k in fp for k in
                       ("platform", "device_kind", "device_count"))):
        raise bad("fingerprint missing platform/device_kind/"
                  "device_count")
    entries = doc.get("entries")
    if not isinstance(entries, list):
        raise bad("entries is not a list")
    index: Dict[Tuple, Dict] = {}
    for i, e in enumerate(entries):
        if not isinstance(e, dict) or not all(
                k in e for k in ("op", "knob", "shape_class", "dtype",
                                 "winner")):
            raise bad("entry %d missing op/knob/shape_class/dtype/"
                      "winner" % i)
        index[(e["op"], e["knob"], e["shape_class"], e["dtype"])] = e
    return {"doc": doc, "index": index, "source": source,
            "fingerprint": fp}


def install_tuning_table(doc: Dict, *, source: str = "<memory>",
                         check_fingerprint: bool = True) -> bool:
    """Install a parsed table document as THE active table.  Returns
    False (one-time warning, table not installed) when the fingerprint
    does not match the live backend and ``check_fingerprint`` holds —
    a stale table must never silently tune a different venue."""
    global _table
    t = _index_table(doc, source)
    if check_fingerprint and not _fingerprint_matches(t["fingerprint"]):
        from raft_tpu.core.tuning import backend_fingerprint

        _warn_stale_once(
            "fp:%s" % source,
            "raft_tpu.config: tuning table %s has stale fingerprint "
            "%r (live backend: %r) — table IGNORED; re-run "
            "tools/autotune.py on this venue" % (
                source, t["fingerprint"], backend_fingerprint()))
        return False
    _table = t
    return True


def load_tuning_table(path: str, *,
                      check_fingerprint: bool = True) -> bool:
    """Load a table file produced by ``tools/autotune.py``.  Unreadable
    or corrupt files raise :class:`LogicError`; a stale fingerprint
    warns once and returns False (module policy above)."""
    from raft_tpu.core.error import LogicError

    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        raise LogicError(
            "raft_tpu.config: corrupt/unreadable tuning table %s — %s"
            % (path, e)) from None
    return install_tuning_table(doc, source=path,
                                check_fingerprint=check_fingerprint)


def clear_tuning_table() -> None:
    """Remove the active table (resolution reverts to env/default)."""
    global _table
    _table = None


def discover_tuning_table() -> Optional[str]:
    """Path of the checked-in table under ``raft_tpu/tuning/`` whose
    fingerprint matches the live backend, or None.  Discovery never
    warns: no matching venue simply means no table."""
    d = _tables_dir()
    if not os.path.isdir(d):
        return None
    for fname in sorted(os.listdir(d)):
        if not fname.endswith(".json"):
            continue
        path = os.path.join(d, fname)
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
            fp = doc.get("fingerprint", {})
        except (OSError, ValueError):
            continue
        if isinstance(fp, dict) and _fingerprint_matches(fp):
            return path
    return None


def _auto_load_table() -> None:
    """First-consult hook: honor RAFT_TPU_TUNING_TABLE once.  ``"0"``/
    empty = explicitly off; ``"auto"`` = discover by fingerprint; any
    other value = a path (stale → one-time warning, resolution
    continues untuned)."""
    global _table_env_checked
    if _table_env_checked:
        return
    _table_env_checked = True
    spec = os.environ.get(TUNING_TABLE_ENV)
    if not spec or spec == "0":
        return
    if spec == "auto":
        path = discover_tuning_table()
        if path is not None:
            load_tuning_table(path)
        return
    load_tuning_table(spec)


def _suspend_depth() -> int:
    return getattr(_tls, "table_suspended", 0)


@contextmanager
def suspend_tuning() -> Iterator[None]:
    """Scoped table bypass: resolution inside the block behaves as if
    no table were loaded (the bench's untuned A/B arm and the sweep's
    candidate timing).  THREAD-LOCAL, like override frames: a
    suspension neither leaks into concurrent request threads nor races
    another thread's depth (a lost global increment would have left
    the table disabled process-wide, silently, forever)."""
    _tls.table_suspended = _suspend_depth() + 1
    try:
        yield
    finally:
        _tls.table_suspended = _suspend_depth() - 1


def _active_table() -> Optional[Dict]:
    if _suspend_depth():
        return None
    if _table is None:
        _auto_load_table()
    return _table


def _count_table(outcome: str, knob: str) -> None:
    # lazy + best-effort: config must stay importable before the
    # metrics registry (raft_tpu/__init__ import order)
    try:
        from raft_tpu.core import metrics as _metrics

        _metrics.default_registry().counter(
            "raft_tpu_tuning_table_lookups_total",
            help="tuning-table lookups by outcome",
            labels=("outcome", "knob")).labels(
                outcome=outcome, knob=knob).inc()
    except Exception:
        pass


def _table_answer(name: str, op: Optional[str],
                  dtype: Optional[str],
                  dims: Optional[Dict[str, int]]) -> Optional[str]:
    t = _active_table()
    if t is None:
        return None
    from raft_tpu.core.tuning import shape_class

    cls = shape_class(dims or {})
    dt = dtype or "*"
    o = op or "*"
    index = t["index"]
    for key in ((o, name, cls, dt), (o, name, cls, "*"),
                (o, name, "*", dt), (o, name, "*", "*")):
        e = index.get(key)
        if e is not None:
            _count_table("hit", name)
            return e["winner"]
    _count_table("miss", name)
    return None


def _table_entries_for(name: str):
    t = _active_table()
    if t is None:
        return ()
    return tuple(e for e in t["index"].values() if e["knob"] == name)


def tuning_table_info() -> Optional[Dict]:
    """Summary of the active table (None when untuned): source path,
    fingerprint, cell count, per-knob cell counts.  The observability
    digest (``tools/metrics_report.py``) renders this."""
    t = _active_table()
    if t is None:
        return None
    per_knob: Dict[str, int] = {}
    for e in t["index"].values():
        per_knob[e["knob"]] = per_knob.get(e["knob"], 0) + 1
    return {"source": t["source"], "fingerprint": dict(t["fingerprint"]),
            "cells": len(t["index"]), "knobs": per_knob}


def _check(name: str, value: Optional[str]) -> None:
    if name not in _KNOBS:
        raise ValueError(
            f"raft_tpu.config: unknown knob {name!r} "
            f"(have: {', '.join(sorted(_KNOBS))})")
    env, default, choices = _KNOBS[name]
    if value is not None and choices is not None and value not in choices:
        raise ValueError(
            f"raft_tpu.config: {name}={value!r} not in {choices} "
            "('skip' and other probe-only modes are argument-only)")


def _warn_if_consumed(name: str, value: Optional[str]) -> None:
    if name in _RUNTIME_KNOBS:
        return
    if value is None:
        # knob=None is the REVERT spelling (configure pops, override
        # stores a scoped None that get() resolves through): the value
        # consumers will now observe is env/default, so that is what
        # the staleness comparison must use — warning on the literal
        # None claimed "changed to None" for reverts that change
        # nothing
        env, default, _ = _KNOBS[name]
        value = os.environ.get(env, default)
    with _lock:
        seen = _consumed.get(name)
        if seen and value not in seen:
            warnings.warn(
                f"raft_tpu.config: {name} was already consumed at trace "
                f"time (as {', '.join(map(repr, sorted(seen, key=str)))}); "
                "consumers already compiled for a shape keep the old "
                f"value — {name}={value!r} affects only not-yet-compiled "
                "shapes. Pass the argument explicitly to pin it per call.",
                stacklevel=3)


def configure(**knobs: Optional[str]) -> None:
    """Set knob values process-wide (None = revert to env/default)."""
    for name, value in knobs.items():
        _check(name, value)
        _warn_if_consumed(name, value)
        if value is None:
            _values.pop(name, None)
        else:
            _values[name] = value


@contextmanager
def override(**knobs: Optional[str]) -> Iterator[None]:
    """Scoped knob values (thread-local; nestable, innermost wins).

    ``override(knob=None)`` reverts the knob to its env/default inside
    the scope — the scoped spelling of ``configure(knob=None)`` — it
    does NOT pin a literal None over outer layers."""
    for name, value in knobs.items():
        _check(name, value)
        _warn_if_consumed(name, value)
    frames = list(_frames())
    frames.append(dict(knobs))
    _tls.frames = tuple(frames)
    try:
        yield
    finally:
        _tls.frames = tuple(frames[:-1])


def _attribute(name: str) -> Tuple[Optional[str], str]:
    """(value, layer) of a knob WITHOUT consumption marking — the
    describe() twin of :func:`tuned`.  Table attribution is shape-less
    here: the layer reads ``"table"`` when the active table holds any
    cell for the knob and no higher layer claims it; the value is the
    unanimous winner, or the literal ``"per-shape"`` when cells
    disagree across shape classes."""
    val, layer = _walk(name)
    if val is not _UNSET:
        return val, layer
    cells = _table_entries_for(name)
    if cells:
        winners = {e["winner"] for e in cells}
        return (winners.pop() if len(winners) == 1
                else "per-shape"), "table"
    return _KNOBS[name][1], "default"


def describe(layers: bool = False) -> Dict:
    """Current effective value of every knob (no consumption mark),
    INCLUDING the tuning-table rung — what consumers will actually
    receive (a knob whose table cells disagree across shape classes
    reads the literal ``"per-shape"``).

    ``layers=True`` additionally attributes each knob to the
    resolution rung that answered:
    ``{knob: {"value": ..., "layer": "override" | "configure" |
    "env" | "table" | "default"}}``.
    """
    if not layers:
        return {name: _attribute(name)[0] for name in _KNOBS}
    out = {}
    for name in _KNOBS:
        value, layer = _attribute(name)
        out[name] = {"value": value, "layer": layer}
    return out
