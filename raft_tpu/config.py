"""One owner for raft_tpu's runtime perf knobs.

The reference keeps its tuning surface in one-place config structs
(cpp/include/raft/spatial/knn/ann_common.h:42-72); raft_tpu's analog is
this module: every performance knob that used to be a scattered
``os.environ`` read resolves here, with the env vars kept as aliases.

Resolution order (first hit wins):

1. an explicit function argument at the call site (never reaches here);
2. an active :func:`override` context, innermost first;
3. a value set by :func:`configure`;
4. the knob's env var (``RAFT_TPU_*`` — the historical spelling);
5. the built-in default.

THE executable-cache caveat, stated once: knobs are consumed at *trace*
time.  ``jax.jit`` caches executables by shape+dtype, so consumers
already compiled for a given shape will NOT retrace when a knob changes
mid-process — the change affects only not-yet-compiled shapes.
:func:`configure` and :func:`override` warn when they change a knob
that some trace has already consumed; direct env-var writes cannot be
intercepted, so prefer the functions (or explicit arguments, which
reach the trace as Python values and always take effect).

Knobs
-----
select_impl
    Per-row top-k implementation for :func:`raft_tpu.spatial.select_k`
    (``topk`` | ``approx`` | ``approx95`` | ``chunked`` | ``pallas``).
tile_merge
    Tile-scan kNN per-tile selection strategy
    (:func:`raft_tpu.spatial.tiled_knn`): ``tile_topk`` | ``direct``.
knn_tile_merge
    Pallas fused-kNN/select merge network
    (:mod:`raft_tpu.ops.knn_tile`): ``merge`` | ``fullsort`` |
    ``sorttile`` (``skip`` is argument-only: an attribution probe that
    returns wrong results by design and must never be reachable from
    config).
fused_knn_impl
    :func:`raft_tpu.spatial.fused_l2_knn` path: ``xla`` | ``pallas``;
    unset = per-backend auto (currently ``xla`` everywhere, the r4
    measured default).
pq_adc
    IVF-PQ ADC lookup (:func:`raft_tpu.spatial.ann.ivf_pq_search`):
    ``gather`` (per-element LUT) | ``onehot`` (one-hot einsum).
    Resolved at call time, not trace time.
spmv_impl
    CSR SpMV (:func:`raft_tpu.sparse.linalg.csr_spmv`): ``segment``
    (gather + sorted segment-sum) | ``cumsum`` (prefix-sum form) |
    ``sortscan`` (gather-free: sort+scan formulation of the x read).
mnmg_merge
    Cross-shard top-k merge topology for the SPMD sharded searches
    (:func:`raft_tpu.spatial.mnmg_knn.mnmg_knn` /
    ``mnmg_ivf_flat_search`` and the sharded serve dispatch):
    ``allgather`` (one wide collective + one re-selection) | ``ring``
    (ppermute streaming, (nq, 2k) peak merge memory) |
    ``hierarchical`` (allgather within a host group, ring across
    groups — the HiCCL decomposition applied to top-k).  Consumed at
    trace time (the executable-cache caveat applies); the serve layer
    pins it per service at construction.
serve_bucket_rungs
    Default shape-bucket ladder for :mod:`raft_tpu.serve` services:
    ``pow2`` (power-of-two rungs up to the service's max batch rows) or
    a comma-separated ascending row list (``"64,256,1024"``).  Free-form
    (validated by :func:`raft_tpu.serve.bucketing.resolve_rungs`).
serve_max_wait_ms
    Default micro-batching window in milliseconds: how long a queued
    request may wait for co-batched company before the batch dispatches
    anyway.  Free-form float; resolved at service construction (the
    serve layer, not a trace, consumes it — no executable-cache caveat).
serve_queue_cap
    Default admission-control cap on queued requests per service;
    beyond it, ``submit`` raises
    :class:`~raft_tpu.core.error.ServiceOverloadError`.  Free-form int.
serve_ann_nprobe
    Default probe count for :class:`raft_tpu.serve.ANNService`
    (``0`` = the served index's build-time default).  Free-form int;
    runtime-resolved at service construction.
serve_ann_nprobe_ladder
    Comma-separated candidate ``nprobe`` cells an ``ANNService`` warms
    (every bucket rung × every cell) and :meth:`calibrate` searches for
    the smallest cell meeting a recall target.  Cells above the index's
    ``nlist`` are clamped.  Free-form list.
serve_ann_delta_cap
    Capacity (rows) of the append-only delta segment that absorbs
    :meth:`ANNService.insert` between compactions; a full delta sheds
    inserts with :class:`~raft_tpu.core.error.ServiceOverloadError`.
    Free-form int.
serve_ann_compact_rows
    Delta-row threshold at which the serve worker loop compacts (re-
    clusters the delta into IVF slots and atomically swaps the index);
    ``0`` disables automatic compaction.  Free-form int.
serve_ann_device_budget_bytes
    Device-memory budget for the out-of-core ANN tier
    (:class:`raft_tpu.serve.ANNService` ``ooc=True``): bytes the
    service may hold device-resident for slot vectors — the
    frequency-promoted hot set plus the double-buffered TilePool
    staging window (docs/SERVING.md "Out-of-core serving").  ``0``
    (the default) means no budget is configured and an ``ooc=True``
    service must pass ``device_budget_bytes=`` explicitly.  Free-form
    int; runtime-resolved at service construction.
serve_breaker_threshold
    Consecutive batch failures that trip a service's circuit breaker
    (:class:`raft_tpu.serve.resilience.CircuitBreaker`); ``0`` disables
    consecutive tracking.  Free-form int; runtime-resolved at service
    construction.
serve_breaker_window / serve_breaker_window_failures
    Windowed failure tracking: trip when the last ``window`` batch
    outcomes contain at least ``window_failures`` failures (catches a
    flapping service that never fails *consecutively* enough).
    ``window_failures=0`` disables windowed tracking.  Free-form ints.
serve_breaker_cooldown_ms
    How long a tripped (open) breaker sheds before letting half-open
    probe traffic through.  Free-form float milliseconds.
serve_ann_degrade_frac
    Queue-pressure threshold for :class:`raft_tpu.serve.ANNService`
    degraded-mode dispatch: when queued requests reach this fraction of
    ``serve_queue_cap`` (or the breaker is half-open after a trip), the
    service steps down its calibrated ``nprobe`` ladder — lower recall,
    lower latency — instead of shedding, and restores the calibrated
    cell when pressure clears.  ``0`` disables the brownout.  Free-form
    float in (0, 1].
serve_tenant_weights
    Default multi-tenant traffic-shaping spec for serve services
    (docs/SERVING.md "Traffic shaping"): a comma-separated
    ``name:weight`` list (``"interactive:4,bulk:1"``) naming the
    tenants and their weighted-fair share of each coalesce window and
    of the admission cap.  Empty (the default) = single-queue serving
    (every request rides one implicit default tenant).  Free-form;
    runtime-resolved at service construction.
serve_hedge_ms
    Fixed hedge threshold for replicated services
    (``KNNService(replicas=...)``): a batch whose execution exceeds
    this many milliseconds is re-dispatched to a second replica with
    first-result-wins resolution.  ``0`` (the default) = adaptive: the
    threshold is ``serve_hedge_factor`` × the tracked per-bucket-rung
    p99, floored at ``serve_hedge_min_ms``.  Free-form float ms.
serve_hedge_factor
    Multiplier on the per-rung p99 execution latency that sets the
    adaptive hedge threshold (only consulted when ``serve_hedge_ms`` is
    0).  Free-form float.
serve_hedge_min_ms
    Floor for the adaptive hedge threshold — hedging below it would
    duplicate healthy work on latency noise.  Free-form float ms.
flight_events
    Ring capacity (events) of the process-wide
    :class:`raft_tpu.core.flight.FlightRecorder` — the bounded-memory
    contract of the always-on flight recorder
    (docs/OBSERVABILITY.md "Flight recorder & request tracing").
    Consumed once, lazily, when the default recorder is first used.
    ``RAFT_TPU_FLIGHT=0`` (not a knob — an env gate like
    ``RAFT_TPU_METRICS``) disables recording entirely.  Free-form int.
serve_slo_target_ms
    Per-request latency objective for the per-tenant SLO tracker every
    serve service carries (docs/OBSERVABILITY.md): a resolved request
    slower than this counts as an SLO miss.  ``0`` = deadline-only
    (only blown deadlines and failures miss).  Free-form float ms;
    runtime-resolved at service construction.
serve_slo_objective
    The availability objective in (0, 1) the burn rate is measured
    against (``burn = miss_rate / (1 - objective)``; burn > 1 spends
    error budget faster than it accrues).  Free-form float.
serve_slo_windows_s
    Comma-separated burn-rate window lengths in seconds (multi-window
    alerting: the short window catches a fast burn, the long one a
    slow leak).  Free-form list.
"""

from __future__ import annotations

import os
import threading
import warnings
from contextlib import contextmanager
from typing import Dict, Iterator, Optional, Tuple

__all__ = ["configure", "override", "get", "describe"]

# knob -> (env alias, default, legal values settable via configure);
# choices None = free-form (the consumer validates — numeric/list knobs
# cannot be enumerated here)
_KNOBS: Dict[str, Tuple[str, Optional[str], Optional[Tuple[str, ...]]]] = {
    "select_impl": ("RAFT_TPU_SELECT_IMPL", "topk",
                    ("topk", "approx", "approx95", "chunked", "pallas")),
    "tile_merge": ("RAFT_TPU_TILE_MERGE", "tile_topk",
                   ("tile_topk", "direct")),
    "knn_tile_merge": ("RAFT_TPU_KNN_TILE_MERGE", "merge",
                       ("merge", "fullsort", "sorttile")),
    "fused_knn_impl": ("RAFT_TPU_FUSED_KNN_IMPL", None,
                       ("xla", "pallas")),
    "pq_adc": ("RAFT_TPU_PQ_ADC", "gather", ("gather", "onehot")),
    "spmv_impl": ("RAFT_TPU_SPMV_IMPL", "segment",
                  ("segment", "cumsum", "sortscan")),
    "mnmg_merge": ("RAFT_TPU_MNMG_MERGE", "allgather",
                   ("allgather", "ring", "hierarchical")),
    "serve_bucket_rungs": ("RAFT_TPU_SERVE_BUCKET_RUNGS", "pow2", None),
    "serve_max_wait_ms": ("RAFT_TPU_SERVE_MAX_WAIT_MS", "2", None),
    "serve_queue_cap": ("RAFT_TPU_SERVE_QUEUE_CAP", "1024", None),
    "serve_ann_nprobe": ("RAFT_TPU_SERVE_ANN_NPROBE", "0", None),
    "serve_ann_nprobe_ladder": ("RAFT_TPU_SERVE_ANN_NPROBE_LADDER",
                                "4,8,16,32,64", None),
    "serve_ann_delta_cap": ("RAFT_TPU_SERVE_ANN_DELTA_CAP", "4096", None),
    "serve_ann_compact_rows": ("RAFT_TPU_SERVE_ANN_COMPACT_ROWS",
                               "2048", None),
    "serve_ann_device_budget_bytes": (
        "RAFT_TPU_SERVE_ANN_DEVICE_BUDGET_BYTES", "0", None),
    "serve_breaker_threshold": ("RAFT_TPU_SERVE_BREAKER_THRESHOLD",
                                "5", None),
    "serve_breaker_window": ("RAFT_TPU_SERVE_BREAKER_WINDOW",
                             "16", None),
    "serve_breaker_window_failures": (
        "RAFT_TPU_SERVE_BREAKER_WINDOW_FAILURES", "8", None),
    "serve_breaker_cooldown_ms": ("RAFT_TPU_SERVE_BREAKER_COOLDOWN_MS",
                                  "250", None),
    "serve_ann_degrade_frac": ("RAFT_TPU_SERVE_ANN_DEGRADE_FRAC",
                               "0.75", None),
    "serve_tenant_weights": ("RAFT_TPU_SERVE_TENANT_WEIGHTS", "", None),
    "serve_hedge_ms": ("RAFT_TPU_SERVE_HEDGE_MS", "0", None),
    "serve_hedge_factor": ("RAFT_TPU_SERVE_HEDGE_FACTOR", "1.5", None),
    "serve_hedge_min_ms": ("RAFT_TPU_SERVE_HEDGE_MIN_MS", "10", None),
    "flight_events": ("RAFT_TPU_FLIGHT_EVENTS", "4096", None),
    "serve_slo_target_ms": ("RAFT_TPU_SERVE_SLO_TARGET_MS", "100", None),
    "serve_slo_objective": ("RAFT_TPU_SERVE_SLO_OBJECTIVE",
                            "0.99", None),
    "serve_slo_windows_s": ("RAFT_TPU_SERVE_SLO_WINDOWS_S",
                            "60,300", None),
}

# knobs resolved at *runtime* (service/object construction), never baked
# into a trace: changing one later affects the next construction and the
# executable-cache caveat warning does not apply
_RUNTIME_KNOBS = frozenset(
    ("serve_bucket_rungs", "serve_max_wait_ms", "serve_queue_cap",
     "serve_ann_nprobe", "serve_ann_nprobe_ladder",
     "serve_ann_delta_cap", "serve_ann_compact_rows",
     "serve_ann_device_budget_bytes",
     "serve_breaker_threshold", "serve_breaker_window",
     "serve_breaker_window_failures", "serve_breaker_cooldown_ms",
     "serve_ann_degrade_frac", "serve_tenant_weights",
     "serve_hedge_ms", "serve_hedge_factor", "serve_hedge_min_ms",
     "flight_events", "serve_slo_target_ms", "serve_slo_objective",
     "serve_slo_windows_s"))

# sentinel for "no layer claimed this knob" during resolution — distinct
# from None, which a caller may store in an override frame to mean
# "revert to env/default inside this scope" (configure() expresses the
# same revert by popping its entry)
_UNSET = object()

_values: Dict[str, Optional[str]] = {}
_tls = threading.local()
# knob -> set of values already handed to some trace (consumed); used
# only to decide whether a later change deserves the caveat warning
_consumed: Dict[str, set] = {}
_lock = threading.Lock()


def _frames():
    return getattr(_tls, "frames", ())


def _resolve(name: str) -> Optional[str]:
    """One knob through the full layer order (module doc): innermost
    override frame → configure() value → env → default.  _UNSET means
    no layer claimed it; a literal None in a frame is the scoped
    "revert to env/default" (configure(knob=None) pops its entry; a
    scoped frame cannot pop, so the revert is interpreted here).  The
    single copy of this dance — get() and describe() must never skew."""
    env, default, _ = _KNOBS[name]
    val = _UNSET
    for frame in reversed(_frames()):
        if name in frame:
            val = frame[name]
            break
    if val is _UNSET and name in _values:
        val = _values[name]
    if val is _UNSET or val is None:
        val = os.environ.get(env, default)
    return val


def get(name: str) -> Optional[str]:
    """Resolve a knob (module-doc order) and mark it consumed.

    Returns the raw string (or None for an unset no-default knob);
    call sites keep their own whitelists so an env-var typo fails with
    the site's error message, exactly as before.
    """
    val = _resolve(name)
    with _lock:
        _consumed.setdefault(name, set()).add(val)
    return val


def _check(name: str, value: Optional[str]) -> None:
    if name not in _KNOBS:
        raise ValueError(
            f"raft_tpu.config: unknown knob {name!r} "
            f"(have: {', '.join(sorted(_KNOBS))})")
    env, default, choices = _KNOBS[name]
    if value is not None and choices is not None and value not in choices:
        raise ValueError(
            f"raft_tpu.config: {name}={value!r} not in {choices} "
            "('skip' and other probe-only modes are argument-only)")


def _warn_if_consumed(name: str, value: Optional[str]) -> None:
    if name in _RUNTIME_KNOBS:
        return
    if value is None:
        # knob=None is the REVERT spelling (configure pops, override
        # stores a scoped None that get() resolves through): the value
        # consumers will now observe is env/default, so that is what
        # the staleness comparison must use — warning on the literal
        # None claimed "changed to None" for reverts that change
        # nothing
        env, default, _ = _KNOBS[name]
        value = os.environ.get(env, default)
    with _lock:
        seen = _consumed.get(name)
        if seen and value not in seen:
            warnings.warn(
                f"raft_tpu.config: {name} was already consumed at trace "
                f"time (as {', '.join(map(repr, sorted(seen, key=str)))}); "
                "consumers already compiled for a shape keep the old "
                f"value — {name}={value!r} affects only not-yet-compiled "
                "shapes. Pass the argument explicitly to pin it per call.",
                stacklevel=3)


def configure(**knobs: Optional[str]) -> None:
    """Set knob values process-wide (None = revert to env/default)."""
    for name, value in knobs.items():
        _check(name, value)
        _warn_if_consumed(name, value)
        if value is None:
            _values.pop(name, None)
        else:
            _values[name] = value


@contextmanager
def override(**knobs: Optional[str]) -> Iterator[None]:
    """Scoped knob values (thread-local; nestable, innermost wins).

    ``override(knob=None)`` reverts the knob to its env/default inside
    the scope — the scoped spelling of ``configure(knob=None)`` — it
    does NOT pin a literal None over outer layers."""
    for name, value in knobs.items():
        _check(name, value)
        _warn_if_consumed(name, value)
    frames = list(_frames())
    frames.append(dict(knobs))
    _tls.frames = tuple(frames)
    try:
        yield
    finally:
        _tls.frames = tuple(frames[:-1])


def describe() -> Dict[str, Optional[str]]:
    """Current effective value of every knob (no consumption mark)."""
    return {name: _resolve(name) for name in _KNOBS}
