"""Fused IVF probe→gather→distance→running-select_k Pallas kernel.

The serving hot path this attacks is ``_ivf_flat_search_impl``
(spatial/ann.py): per scan step it gathers a (nq, cap, d) block of slot
vectors, feeds an einsum, and runs a separate ``select_k`` program over
the concatenated running buffer — three HBM round-trips per step, and
the PR 15 cost inventory measures the resulting executable at ~1% of
its cost-model roofline bound.  The reference's own answer is one CUDA
kernel (``ivfflat_interleaved_scan``): scan the probed lists and keep
the top-k in registers.

TPU redesign: the compacted per-query scan list (the ``slots`` array
``_probe_compact`` builds — valid-first, -1-padded) rides as a *scalar
prefetch* operand, and its entries drive the ``BlockSpec`` index maps
directly.  Grid = (query, scan step); each step DMAs exactly ONE slot's
vectors/norms/ids into VMEM — the gather IS the block indexing, so no
(nq, cap, d) gather block ever exists in HBM — computes the expanded-
form distance row on the MXU, and folds it into a VMEM-resident
running top-k via the same threshold-gated bitonic networks the fused
brute-force kernel uses (:func:`raft_tpu.ops.knn_tile.topk_update`).
Invalid scan steps (padding of short scan lists) are masked by reading
the scalar ref inside the kernel; their prefetches alias slot 0 and
overlap with compute.

``accum_bf16=True`` casts queries and slot vectors to bfloat16 before
the kernel (one XLA cast each, not per-step) while the MXU accumulates
in f32 (``preferred_element_type``) and every distance/select op stays
f32 — the classic TPU bandwidth trade: half the DMA bytes per step for
~1e-2 relative distance error (tests pin the tolerance).

:func:`fused_ivf_scan_xla` replays the kernel op-for-op at the jnp
level (scan over steps inside a map over queries, same padding, same
``topk_update`` interpret-path networks) — the off-TPU fallback and
the bitwise correctness oracle, exactly the ``fused_knn_xla`` pattern.

Selected through the tuning registry as ``ivf_scan_impl``
(``xla`` | ``pallas`` | ``pallas_bf16``) with the k <= 128 bitonic cap
and L2-family legality enforced by the registry predicate.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_tpu.ops import compat
from raft_tpu.ops.knn_tile import topk_update

from raft_tpu.core import tuning
from raft_tpu.core.error import expects
from raft_tpu.core.profiler import profiled
from raft_tpu.core.utils import ceildiv, is_tpu_backend

_INF = float("inf")


def _ivf_geometry(cap: int, d: int, k: int):
    """(kpad, cap_pad, g, dp): lane-group select width, slot capacity
    padded to a kpad multiple, group count, padded depth — the same
    rules as :func:`raft_tpu.ops.knn_tile.tile_geometry` restricted to
    the one-slot tile this kernel streams."""
    kpad = 128
    while kpad < k:
        kpad *= 2
    cap_pad = ceildiv(cap, kpad) * kpad
    dp = ceildiv(d, 128) * 128 if d > 128 else d
    return kpad, cap_pad, cap_pad // kpad, dp


def _pad_slot_store(slot_vecs, slot_norms, slot_ids, cap_pad, dp):
    """Pad the slotted store to the kernel tile: vectors zero-padded to
    (S, cap_pad, dp) f32, norms zero-padded (S, cap_pad) f32, ids
    -1-padded (S, cap_pad) int32 — the -1 padding is the ONE mask
    source (matching the XLA scan's ``ids >= 0`` rule), so padded
    capacity rows can never displace a candidate."""
    S, cap, d = slot_vecs.shape
    sv = jnp.pad(slot_vecs.astype(jnp.float32),
                 ((0, 0), (0, cap_pad - cap), (0, dp - d)))
    sn = jnp.pad(slot_norms.astype(jnp.float32),
                 ((0, 0), (0, cap_pad - cap)))
    si = jnp.pad(slot_ids.astype(jnp.int32),
                 ((0, 0), (0, cap_pad - cap)), constant_values=-1)
    return sv, sn, si


def _ivf_kernel(slots_ref, q_ref, qn_ref, sv_ref, sn_ref, si_ref,
                od_ref, oi_ref, bd_ref, bi_ref, *, kpad, cap_pad, g,
                n_steps, precision, interpret, merge_impl):
    i = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        bd_ref[:] = jnp.full_like(bd_ref, _INF)
        bi_ref[:] = jnp.full_like(bi_ref, -1)

    sv = sv_ref[...].reshape(cap_pad, sv_ref.shape[-1])
    acc = jax.lax.dot_general(
        q_ref[...], sv, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32, precision=precision)
    # expanded form qn + |v|^2 - 2 q.v, clamped (knn_tile rationale);
    # constants explicit f32 (x64 literal-promotion divergence, ditto)
    dist = jnp.maximum(qn_ref[...] + sn_ref[...] - 2.0 * acc, 0.0)
    inf32 = jnp.float32(_INF)
    # one mask: in-slot padding/vacancy (ids < 0) and whole-step
    # padding of short scan lists (slots entry < 0, read from the
    # scalar-prefetch ref — the block DMA aliased slot 0)
    keep = (si_ref[...] >= 0) & (slots_ref[i, j] >= 0)
    dist = jnp.where(keep, dist, inf32)

    bd, bi = topk_update(dist, bd_ref[:], bi_ref[:], j * cap_pad,
                         kpad=kpad, g=g, interpret=interpret,
                         merge_impl=merge_impl)
    bd_ref[:] = bd
    bi_ref[:] = bi

    @pl.when(j == n_steps - 1)
    def _emit():
        od_ref[:] = bd_ref[:]
        oi_ref[:] = bi_ref[:]


def _positions_to_ids(pos, slots, si, cap_pad):
    """Map the kernel's candidate positions (j * cap_pad + column) back
    to global row ids through the scan list and the padded id store;
    -1 (unfilled top-k lanes) stays -1."""
    step = jnp.maximum(pos, 0) // cap_pad                 # (nq, k)
    col = jnp.maximum(pos, 0) % cap_pad
    sl = jnp.take_along_axis(slots, step, axis=1)
    ids = si[jnp.maximum(sl, 0), col]
    return jnp.where((pos >= 0) & (sl >= 0), ids, -1).astype(jnp.int32)


@profiled("ops")
def fused_ivf_scan(
    queries: jnp.ndarray,
    slot_vecs: jnp.ndarray,
    slot_norms: jnp.ndarray,
    slot_ids: jnp.ndarray,
    slots: jnp.ndarray,
    k: int,
    accum_bf16: bool = False,
    precision: str = "highest",
    interpret: Optional[bool] = None,
    merge_impl: Optional[str] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One-pass fused IVF slot scan (module doc).

    Parameters
    ----------
    queries: (nq, d) query rows.
    slot_vecs / slot_norms / slot_ids:
        The slotted store — (S, cap, d) vectors, (S, cap) squared
        norms, (S, cap) int32 global row ids with -1 marking vacancy.
    slots: (nq, n_steps) int32 per-query scan list (slot indices,
        -1-padded; :func:`raft_tpu.spatial.ann._probe_compact` output).
    k: neighbors per query, k <= 128 (bitonic width cap).

    Returns (distances (nq, k) f32 ascending squared-L2, global row
    ids (nq, k) int32, -1 where fewer than k candidates existed).
    """
    expects(queries.ndim == 2 and slot_vecs.ndim == 3
            and queries.shape[1] == slot_vecs.shape[2],
            "fused_ivf_scan: shape mismatch")
    expects(slots.ndim == 2 and slots.shape[0] == queries.shape[0],
            "fused_ivf_scan: slots must be (nq, n_steps)")
    nq, d = queries.shape
    S, cap, _ = slot_vecs.shape
    n_steps = slots.shape[1]
    expects(n_steps > 0, "fused_ivf_scan: empty scan list")
    expects(0 < k <= 128,
            "fused_ivf_scan: k <= 128 (bitonic width cap; got %d)", k)
    merge_impl = tuning.resolve("knn_tile_merge", merge_impl,
                                site="fused_ivf_scan", n=S * cap, k=k,
                                dtype=slot_vecs.dtype)
    if interpret is None:
        interpret = not is_tpu_backend()
    kpad, cap_pad, g, dp = _ivf_geometry(cap, d, k)
    sv, sn, si = _pad_slot_store(slot_vecs, slot_norms, slot_ids,
                                 cap_pad, dp)
    qf = jnp.pad(queries.astype(jnp.float32),
                 ((0, 0), (0, dp - d)))
    qn = jnp.sum(qf * qf, axis=1)[:, None]                # (nq, 1)
    if accum_bf16:
        # one whole-array cast each (NOT per step): half the per-step
        # DMA bytes; the dot still accumulates f32 and norms stay f32
        sv = sv.astype(jnp.bfloat16)
        qf = qf.astype(jnp.bfloat16)
    slots = slots.astype(jnp.int32)

    kern = functools.partial(
        _ivf_kernel, kpad=kpad, cap_pad=cap_pad, g=g, n_steps=n_steps,
        precision=jax.lax.Precision(precision) if precision else None,
        interpret=interpret, merge_impl=merge_impl)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nq, n_steps),
        in_specs=[
            pl.BlockSpec((1, dp), lambda i, j, slots_ref: (i, 0)),
            pl.BlockSpec((1, 1), lambda i, j, slots_ref: (i, 0)),
            # the fused gather: the scan-list entry IS the block index
            # (invalid entries alias slot 0; masked in-kernel)
            pl.BlockSpec(
                (1, cap_pad, dp),
                lambda i, j, slots_ref:
                    (jnp.maximum(slots_ref[i, j], 0), 0, 0)),
            pl.BlockSpec(
                (1, cap_pad),
                lambda i, j, slots_ref:
                    (jnp.maximum(slots_ref[i, j], 0), 0)),
            pl.BlockSpec(
                (1, cap_pad),
                lambda i, j, slots_ref:
                    (jnp.maximum(slots_ref[i, j], 0), 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, kpad), lambda i, j, slots_ref: (i, 0)),
            pl.BlockSpec((1, kpad), lambda i, j, slots_ref: (i, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((1, kpad), jnp.float32),
            pltpu.VMEM((1, kpad), jnp.int32),
        ],
    )
    out_d, out_pos = pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((nq, kpad), jnp.float32),
            jax.ShapeDtypeStruct((nq, kpad), jnp.int32),
        ],
        compiler_params=compat.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(slots, qf, qn, sv, sn, si)
    out_d = out_d[:, :k]
    ids = _positions_to_ids(out_pos[:, :k], slots, si, cap_pad)
    return out_d, ids


@profiled("ops")
def fused_ivf_scan_xla(
    queries: jnp.ndarray,
    slot_vecs: jnp.ndarray,
    slot_norms: jnp.ndarray,
    slot_ids: jnp.ndarray,
    slots: jnp.ndarray,
    k: int,
    accum_bf16: bool = False,
    precision: str = "highest",
    merge_impl: Optional[str] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """XLA-composed emulation of :func:`fused_ivf_scan` — off-TPU
    fallback and bitwise oracle.

    Op-for-op replay: the same padding, the same per-step distance +
    mask, the same :func:`topk_update` (interpret-path networks), one
    query per row exactly like the kernel's bm=1 grid rows — a
    ``lax.scan`` over scan steps inside a ``lax.map`` over queries
    stands in for the (parallel, arbitrary) grid.  scan/map, not vmap:
    vmapping the while-loop gate would rewrite it to a masked
    fixed-trip form and drift from the kernel's op order
    (fused_knn_xla rationale).
    """
    expects(queries.ndim == 2 and slot_vecs.ndim == 3
            and queries.shape[1] == slot_vecs.shape[2],
            "fused_ivf_scan_xla: shape mismatch")
    expects(slots.ndim == 2 and slots.shape[0] == queries.shape[0],
            "fused_ivf_scan_xla: slots must be (nq, n_steps)")
    nq, d = queries.shape
    S, cap, _ = slot_vecs.shape
    n_steps = slots.shape[1]
    expects(n_steps > 0, "fused_ivf_scan_xla: empty scan list")
    expects(0 < k <= 128,
            "fused_ivf_scan_xla: k <= 128 (bitonic width cap; got %d)",
            k)
    merge_impl = tuning.resolve("knn_tile_merge", merge_impl,
                                site="fused_ivf_scan_xla", n=S * cap,
                                k=k, dtype=slot_vecs.dtype)
    expects(merge_impl != "skip",
            "fused_ivf_scan_xla: the 'skip' probe is kernel-only")
    kpad, cap_pad, g, dp = _ivf_geometry(cap, d, k)
    sv, sn, si = _pad_slot_store(slot_vecs, slot_norms, slot_ids,
                                 cap_pad, dp)
    qf = jnp.pad(queries.astype(jnp.float32), ((0, 0), (0, dp - d)))
    qn = jnp.sum(qf * qf, axis=1)[:, None]
    if accum_bf16:
        sv = sv.astype(jnp.bfloat16)
        qf = qf.astype(jnp.bfloat16)
    slots = slots.astype(jnp.int32)
    prec = jax.lax.Precision(precision) if precision else None
    inf32 = jnp.float32(_INF)

    def one_query(args):
        qv, qnv, srow = args        # (1, dp), (1, 1), (n_steps,)

        def step(carry, j):
            bd, bi = carry
            sl = jnp.maximum(srow[j], 0)
            acc = jax.lax.dot_general(
                qv, sv[sl], dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32, precision=prec)
            dist = jnp.maximum(qnv + sn[sl][None, :] - 2.0 * acc, 0.0)
            keep = (si[sl][None, :] >= 0) & (srow[j] >= 0)
            dist = jnp.where(keep, dist, inf32)
            bd, bi = topk_update(dist, bd, bi, j * cap_pad, kpad=kpad,
                                 g=g, interpret=True,
                                 merge_impl=merge_impl)
            return (bd, bi), None

        init = (jnp.full((1, kpad), _INF, jnp.float32),
                jnp.full((1, kpad), -1, jnp.int32))
        (bd, bi), _ = jax.lax.scan(
            step, init, jnp.arange(n_steps, dtype=jnp.int32))
        return bd[0], bi[0]

    out_d, out_pos = jax.lax.map(
        one_query, (qf[:, None, :], qn[:, :, None], slots))
    out_d = out_d[:, :k]
    ids = _positions_to_ids(out_pos[:, :k], slots, si, cap_pad)
    return out_d, ids
