"""Version-tolerant imports for the Pallas API skew on this JAX build.

Two drifts broke the seed's pallas files against the pinned JAX:

1. ``jax.experimental.pallas.tpu`` renamed its compiler-params struct
   across releases (``CompilerParams`` <-> ``TPUCompilerParams``).
   Every kernel module imports :data:`CompilerParams` from here instead
   of guessing which spelling this build carries.
2. ``jax.export`` is a lazy submodule on this build: attribute access
   ``jax.export`` raises ``AttributeError`` until the submodule has
   been imported once.  Importing this module performs that import so
   call sites (tests asserting ``tpu_custom_call`` in exported HLO) can
   use the attribute form.

Keep this file dependency-free beyond jax itself — it is imported at
ops-module import time, before any backend is initialized.
"""

from __future__ import annotations

from jax.experimental.pallas import tpu as pltpu

try:  # pragma: no cover - exercised only on newer builds
    import jax.export  # noqa: F401  (registers the lazy submodule)
except ImportError:  # pragma: no cover - very old builds
    pass

#: The TPU compiler-params dataclass under whichever name this JAX
#: build exports it.  ``dimension_semantics=`` keyword is stable across
#: both spellings.
CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams")

__all__ = ["CompilerParams"]
