"""Standalone fused top-k selection Pallas kernel.

The reference serves standalone k-selection with the forked-FAISS
warp/block select heaps (cpp/include/raft/spatial/knn/detail/
selection_faiss.cuh:131-160, warp_select_faiss.cuh,
block_select_faiss.cuh) behind ``select_k`` (knn.hpp:90).  The measured
TPU problem is the same shape: one wide ``lax.top_k`` over (rows, W) is
a sort-shaped selection costing ~400x the MXU time of the matmul that
produced the keys (v5e, W=8192, k=100 — BENCH_TPU_SESSION_r04.md).

This kernel re-uses the fused kNN kernel's selection core
(:func:`raft_tpu.ops.knn_tile.topk_update`): stream (bm, bw) key tiles
through VMEM; per tile, a threshold gate (any key below the current
k-th best?) drives an extract-merge while-loop that approaches zero
rounds once the running top-k warms up — the role the reference's
warp-select early-out plays.  Grid = (row_tiles, w_tiles), w innermost;
the running (sorted) top-k lives in VMEM scratch across w tiles.

Selects the SMALLEST k keys per row (distance semantics, ascending).
Callers wanting largest negate the keys (see
:func:`raft_tpu.spatial.select_k.top_k_rows`).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_tpu.ops import compat

from raft_tpu.core import tuning
from raft_tpu.core.error import expects
from raft_tpu.core.profiler import profiled
from raft_tpu.core.utils import is_tpu_backend
from raft_tpu.ops.knn_tile import tile_geometry, topk_update

_INF = float("inf")


def _select_kernel(k_ref, od_ref, oi_ref, bd_ref, bi_ref, *, kpad, bw,
                   w_real, n_j_tiles, g, interpret, merge_impl):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        bd_ref[:] = jnp.full_like(bd_ref, _INF)
        bi_ref[:] = jnp.full_like(bi_ref, -1)

    keys = k_ref[:]
    # mask padded columns of the final tile (explicit f32 constant: a
    # Python-float literal promotes to f64 under jax_enable_x64, which
    # Mosaic cannot cast back — same rule as the kNN kernel)
    inf32 = jnp.float32(_INF)
    col = jax.lax.broadcasted_iota(jnp.int32, (1, bw), 1)
    keys = jnp.where(j * bw + col < w_real, keys, inf32)

    bd, bi = topk_update(keys, bd_ref[:], bi_ref[:], j * bw, kpad=kpad,
                         g=g, interpret=interpret, merge_impl=merge_impl)
    bd_ref[:] = bd
    bi_ref[:] = bi

    @pl.when(j == n_j_tiles - 1)
    def _emit():
        od_ref[:] = bd_ref[:]
        oi_ref[:] = bi_ref[:]


@profiled("ops")
def select_tile(
    keys: jnp.ndarray,
    k: int,
    block_rows: int = 256,
    block_w: int = 2048,
    interpret: Optional[bool] = None,
    merge_impl: Optional[str] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-row k smallest keys, fused threshold-gated selection.

    Parameters
    ----------
    keys:
        (m, w) float key matrix (e.g. distances; smaller = better).
    k:
        Entries to keep per row; k <= min(w, 128) (the bitonic merge
        width cap shared with the fused kNN kernel).
    block_rows / block_w:
        Tile geometry: rows per grid step and key columns per VMEM
        tile.

    Returns
    -------
    (values, indices): (m, k) keys sorted ascending and their int32
    column ids.  Rows with fewer than k finite keys fill the deficit
    with +inf values whose ids are clamped in-range (same contract as
    :func:`raft_tpu.spatial.select_k.chunked_top_k` pads).
    """
    expects(keys.ndim == 2, "select_tile: 2-D keys required")
    m, w = keys.shape
    expects(0 < k <= w, "select_tile: k=%d out of range for w=%d", k, w)
    expects(k <= 128,
            "select_tile: k <= 128 (bitonic merge width cap; got %d)", k)
    expects(jnp.issubdtype(keys.dtype, jnp.floating),
            "select_tile: float keys required, got %s", keys.dtype)
    if interpret is None:
        interpret = not is_tpu_backend()
    merge_impl = tuning.resolve("knn_tile_merge", merge_impl,
                                site="select_tile", n=w, k=k,
                                dtype=keys.dtype)
    expects(merge_impl != "skip",
            "select_tile: merge_impl='skip' has no meaning here (the "
            "probe belongs to the fused kNN kernel)")

    # shared geometry with the fused kNN kernel (one definition so the
    # padding/alignment rules cannot drift between the kernels); the
    # depth argument is irrelevant here — d=1 keeps dp inert
    kpad = 128
    bm, bw, g, _, mp, wp = tile_geometry(m, w, 1, block_rows, block_w,
                                         unit=kpad)

    kf = jnp.pad(keys.astype(jnp.float32),
                 ((0, mp - m), (0, wp - w)),
                 constant_values=_INF)

    grid = (mp // bm, wp // bw)
    kern = functools.partial(
        _select_kernel, kpad=kpad, bw=bw, w_real=w, n_j_tiles=grid[1],
        g=g, interpret=interpret, merge_impl=merge_impl)
    out_d, out_i = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[pl.BlockSpec((bm, bw), lambda i, j: (i, j))],
        out_specs=[
            pl.BlockSpec((bm, kpad), lambda i, j: (i, 0)),
            pl.BlockSpec((bm, kpad), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((mp, kpad), jnp.float32),
            jax.ShapeDtypeStruct((mp, kpad), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bm, kpad), jnp.float32),
            pltpu.VMEM((bm, kpad), jnp.int32),
        ],
        compiler_params=compat.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(kf)
    # deficit slots (fewer than k finite keys in the row) carry id -1;
    # clamp in-range so a payload gather cannot go out of bounds
    return out_d[:m, :k], jnp.clip(out_i[:m, :k], 0, w - 1)
