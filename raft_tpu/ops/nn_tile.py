"""Fused L2-distance + 1-nearest-neighbor Pallas kernel.

TPU re-design of the reference's second fused crown jewel:
``fusedL2NN`` (cpp/include/raft/distance/detail/fused_l2_nn.cuh:134,267)
— one CUDA kernel computes an L2 tile and immediately argmin-reduces
each row into a running (value, index) pair guarded by per-row mutexes.

This kernel keeps the structure of the proven fused kNN kernel
(:mod:`raft_tpu.ops.knn_tile` — grid (query_tiles, index_tiles), index
innermost, VMEM-resident running state, MXU distance tile) but the
selection degenerates from a bitonic top-k merge to a lane-parallel
running minimum:

- the running state is a (bm, 128) value lane-vector plus its int32 id
  payload — one candidate minimum per lane column, strided over the
  index tile exactly like the kNN kernel's groups;
- each index tile: MXU computes ``xn + yn - 2 x@yT``; a (bm, g, 128)
  reshape group-mins down to (bm, 128) with the owning group recovered
  by a masked min over the group iota; the lane-parallel merge takes
  the candidate on strict improvement or an equal-value smaller id
  (the deterministic tie rule of the XLA path; the reference's atomic
  version is first-writer-wins);
- the final 128→1 reduction per row happens OUTSIDE the kernel in XLA
  (an (m, 128) lexicographic min — negligible), so the kernel needs no
  cross-lane reduction at all.

The (m, n) distance matrix never exists anywhere, and unlike the XLA
scan path the (bm, bn) tile never round-trips HBM.  Serves the default
min-reduce contract only; custom reduce ops / masks / f64 stay on the
XLA scan (:mod:`raft_tpu.distance.fused_l2_nn`).

Hardware validation: aligned, ragged, and 1024x100k configs green
compiled on TPU v5e (ONCHIP_r04.md run 3); at the IVF coarse-assign
shape the compiled kernel ran ~4x faster than the XLA scan.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_tpu.ops import compat

from raft_tpu.core import tuning
from raft_tpu.core.error import expects
from raft_tpu.core.profiler import profiled
from raft_tpu.core.utils import is_tpu_backend
from raft_tpu.ops.knn_tile import pad_with_norms, tile_geometry

_INF = float("inf")
# the same untouched-init sentinel the XLA reduce uses
# (raft_tpu/distance/fused_l2_nn.py, imported there as IDX_SENTINEL;
# redeclared by value here to keep ops/ free of distance/ imports)
IDX_SENTINEL = jnp.iinfo(jnp.int32).max


def _nn_kernel(x_ref, y_ref, xn_ref, yn_ref, ov_ref, oi_ref,
               bv_ref, bi_ref, *, bn, n_index, n_j_tiles, g, precision):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        bv_ref[:] = jnp.full_like(bv_ref, _INF)
        bi_ref[:] = jnp.full_like(bi_ref, IDX_SENTINEL)

    acc = jax.lax.dot_general(
        x_ref[:], y_ref[:], dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32, precision=precision)
    dist = xn_ref[:] + yn_ref[:] - 2.0 * acc
    dist = jnp.maximum(dist, 0.0)
    inf32 = jnp.float32(_INF)
    col = jax.lax.broadcasted_iota(jnp.int32, (1, bn), 1)
    dist = jnp.where(j * bn + col < n_index, dist, inf32)

    bm = dist.shape[0]
    d3 = dist.reshape(bm, g, 128)
    gmin = jnp.min(d3, axis=1)                                # (bm, 128)
    gg_iota = jax.lax.broadcasted_iota(jnp.int32, (bm, g, 128), 1)
    is_min = d3 == jnp.expand_dims(gmin, 1)
    # reduce in f32 (exact: gg <= g << 2**24) — this build's Mosaic
    # has no integer reductions
    gg_star = jnp.min(
        jnp.where(is_min, gg_iota, jnp.int32(g)).astype(jnp.float32),
        axis=1).astype(jnp.int32)
    lane = jax.lax.broadcasted_iota(jnp.int32, (bm, 128), 1)
    cand_i = j * bn + gg_star * 128 + lane
    cand_i = jnp.where(gmin < inf32, cand_i, jnp.int32(IDX_SENTINEL))

    bv, bi = bv_ref[:], bi_ref[:]
    # strict improvement, or an equal finite value with a smaller id —
    # mask logical ops, not boolean-valued selects (Mosaic rejects
    # i8->i1 truncations; see knn_tile.py)
    take = (gmin < bv) | ((gmin == bv) & (gmin < inf32) & (cand_i < bi))
    bv_ref[:] = jnp.where(take, gmin, bv)
    bi_ref[:] = jnp.where(take, cand_i, bi)

    @pl.when(j == n_j_tiles - 1)
    def _emit():
        ov_ref[:] = bv_ref[:]
        oi_ref[:] = bi_ref[:]


@profiled("ops")
def fused_nn_tile(
    x: jnp.ndarray,
    y: jnp.ndarray,
    block_m: int = 256,
    block_n: Optional[int] = None,
    precision: str = "highest",
    interpret: Optional[bool] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per row of x: (min squared-L2 distance to rows of y, its index).

    Returns ``(vals (m,), idx (m,) int32)``; ties break toward the
    smaller index; with n == 0 nothing is admissible (callers guard).
    Squared distances — the sqrt epilogue is the caller's (monotonic,
    so the argmin is unchanged), matching fused_l2_nn.cuh's Sqrt
    template parameter handling.
    """
    expects(x.ndim == 2 and y.ndim == 2 and x.shape[1] == y.shape[1],
            "fused_nn_tile: shape mismatch")
    m, d = x.shape
    n = y.shape[0]
    expects(n > 0, "fused_nn_tile: empty index")
    # nn_block_n registry knob: explicit args validate against the
    # integer ladder; None resolves through the config ladder so swept
    # winners reach every call site (knn_tile.resolve_blocks rationale)
    block_n = int(tuning.resolve(
        "nn_block_n", None if block_n is None else str(block_n),
        site="fused_nn_tile", n=n, d=d, dtype=x.dtype))
    if interpret is None:
        interpret = not is_tpu_backend()

    bm, bn, g, dp, mp, np_ = tile_geometry(m, n, d, block_m, block_n,
                                           unit=128)

    xf, xn_row = pad_with_norms(x, mp, dp)
    yf, yn_row = pad_with_norms(y, np_, dp)
    xn = xn_row[:, None]                                 # (mp, 1)
    yn = yn_row[None, :]                                 # (1, np_)

    grid = (mp // bm, np_ // bn)
    kern = functools.partial(
        _nn_kernel, bn=bn, n_index=n, n_j_tiles=grid[1], g=g,
        precision=jax.lax.Precision(precision) if precision else None)
    out_v, out_i = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, dp), lambda i, j: (i, 0)),
            pl.BlockSpec((bn, dp), lambda i, j: (j, 0)),
            pl.BlockSpec((bm, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((1, bn), lambda i, j: (0, j)),
        ],
        out_specs=[
            pl.BlockSpec((bm, 128), lambda i, j: (i, 0)),
            pl.BlockSpec((bm, 128), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((mp, 128), jnp.float32),
            jax.ShapeDtypeStruct((mp, 128), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bm, 128), jnp.float32),
            pltpu.VMEM((bm, 128), jnp.int32),
        ],
        compiler_params=compat.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(xf, yf, xn, yn)

    # final 128->1 lexicographic (value, id) min per row, in XLA: among
    # equal minimal lanes choose the smallest id
    vals128 = out_v[:m]
    ids128 = out_i[:m]
    vmin = jnp.min(vals128, axis=1)
    at_min = vals128 == vmin[:, None]
    best_i = jnp.min(jnp.where(at_min, ids128, IDX_SENTINEL), axis=1)
    return vmin, best_i.astype(jnp.int32)
