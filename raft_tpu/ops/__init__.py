"""Pallas TPU kernels — the hand-tiled hot ops.

This package plays the role of the reference's custom CUDA kernels
(pairwise_distance_base.cuh, fused_l2_nn.cuh, fused_l2_knn.cuh,
selection_faiss.cuh): everything here is written against the TPU memory
hierarchy (HBM → VMEM → MXU/VPU) with explicit block shapes, and falls back
to interpreter mode off-TPU so the full test suite runs on CPU.
"""

from raft_tpu.ops.knn_tile import fused_knn_tile
from raft_tpu.ops.nn_tile import fused_nn_tile
from raft_tpu.ops.pairwise_tile import pairwise_tile

__all__ = ["fused_knn_tile", "fused_nn_tile", "pairwise_tile"]
