"""Pallas TPU kernels — the hand-tiled hot ops.

This package plays the role of the reference's custom CUDA kernels
(pairwise_distance_base.cuh, fused_l2_nn.cuh, fused_l2_knn.cuh,
selection_faiss.cuh): everything here is written against the TPU memory
hierarchy (HBM → VMEM → MXU/VPU) with explicit block shapes, and falls back
to interpreter mode off-TPU so the full test suite runs on CPU.  Each
kernel has two XLA companions: a fast production twin sharing its tile
geometry and distance arithmetic (``fused_knn_xla``; the IVF scan's
``"xla"`` gather path plays this role in spatial/ann.py), and an
op-for-op replay used as the bitwise correctness oracle in tests
(``fused_knn_xla_oracle``, ``fused_ivf_scan_xla`` — seconds per call,
never a serving path).
"""

from raft_tpu.ops.ivf_tile import fused_ivf_scan, fused_ivf_scan_xla
from raft_tpu.ops.knn_tile import fused_knn_tile, fused_knn_xla, \
    fused_knn_xla_oracle
from raft_tpu.ops.nn_tile import fused_nn_tile
from raft_tpu.ops.pairwise_tile import pairwise_tile

__all__ = ["fused_ivf_scan", "fused_ivf_scan_xla", "fused_knn_tile",
           "fused_knn_xla", "fused_knn_xla_oracle", "fused_nn_tile",
           "pairwise_tile"]
