"""Generic tiled pairwise-accumulation kernel.

TPU-native re-design of the reference's ``PairwiseDistances`` GEMM-like
template (cpp/include/raft/distance/detail/pairwise_distance_base.cuh:76:
smem double-buffered tile loads + per-metric core_lambda accumulate +
epilog_lambda), which powers every *unexpanded* metric (L1, Chebyshev,
Canberra, Minkowski, Hamming, Jensen-Shannon, unexpanded L2).

Design: grid = (m/bm, n/bn, k/bk) with the k axis innermost ("arbitrary"
semantics) accumulating into a VMEM scratch block, exactly the Pallas
matmul pattern.  The combine lambda sees an (bm, bk) x-tile and a
(bk, bn) yᵀ-tile and produces an (bm, bk, bn) elementwise term that is
reduced over the middle axis — this layout keeps n on the 128-wide lane
dimension and k on sublanes, so the VPU runs full-width.  Pipelining
(double-buffered HBM→VMEM) is done by the Pallas runtime from the
BlockSpecs, playing the role of the reference's ldgXY/stsXY page-flipping
(pairwise_distance_base.cuh:122-226).

Zero-padding is used for edge tiles; every supported combine maps
(0, 0) -> 0 contribution (guarded Canberra/JS included) so padded k is
harmless, and padded rows/cols are sliced away by the wrapper.

Hardware validation: all seven unexpanded metrics green compiled on
TPU v5e vs host-f64 numpy (ONCHIP_r04.md run 3) at aligned, ragged
(193x257x77), and cross-k-tile (d=300) shapes; max abs diff 6.3e-5.
"""

from __future__ import annotations

import functools
import os
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_tpu.ops import compat

from raft_tpu.core.profiler import profiled
from raft_tpu.core.utils import ceildiv, is_tpu_backend


def _kernel(x_ref, yt_ref, o_ref, acc_ref, *, combine, reduce_kind, epilog,
            n_k_tiles, init):
    @pl.when(pl.program_id(2) == 0)
    def _init():
        acc_ref[:] = jnp.full_like(acc_ref, init)

    xv = x_ref[:]            # (bm, bk)
    ytv = yt_ref[:]          # (bk, bn)
    term = combine(xv[:, :, None], ytv[None, :, :])  # (bm, bk, bn)
    if reduce_kind == "add":
        acc_ref[:] = acc_ref[:] + jnp.sum(term, axis=1)
    else:
        acc_ref[:] = jnp.maximum(acc_ref[:], jnp.max(term, axis=1))

    @pl.when(pl.program_id(2) == n_k_tiles - 1)
    def _fin():
        out = acc_ref[:]
        if epilog is not None:
            out = epilog(out)
        o_ref[:] = out.astype(o_ref.dtype)


@profiled("ops")
def pairwise_tile(
    x: jnp.ndarray,
    y: jnp.ndarray,
    combine: Callable,
    reduce_kind: str = "add",
    epilog: Optional[Callable] = None,
    init: float = 0.0,
    block_m: int = 128,
    block_n: int = 128,
    block_k: int = 128,
    out_dtype=None,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Compute ``reduce_k combine(x[i, k], y[j, k])`` for all (i, j).

    ``combine`` receives broadcastable views shaped (bm, bk, 1) and
    (1, bk, bn) and must work elementwise; ``reduce_kind`` is "add" or
    "max"; ``epilog`` maps the accumulated (bm, bn) block.
    """
    m, k = x.shape
    n, k2 = y.shape
    assert k == k2, (k, k2)
    assert reduce_kind in ("add", "max"), reduce_kind
    if out_dtype is None:
        # distances are fractional even for integer inputs (Hamming means,
        # Canberra ratios): never truncate back to an integer dtype
        out_dtype = x.dtype if jnp.issubdtype(x.dtype, jnp.floating) else jnp.float32
    if interpret is None:
        interpret = not is_tpu_backend()

    # Mosaic requires the last block dim to be 128-divisible or span the
    # whole array, and the second-to-last to be 8-divisible or span it.
    # k <= 128: one full-k block (padded to a sublane multiple); larger k is
    # chunked in multiples of 128 (block_k rounded).  bm adapts so the
    # (bm, bk, bn) broadcast intermediate stays within a VMEM budget.
    bn = min(block_n, n) if n < 128 else 128 * min(ceildiv(block_n, 128), ceildiv(n, 128))
    if k <= 128:
        bk = ceildiv(k, 8) * 8
    else:
        bk = max(128, block_k // 128 * 128)
    # budget for the (bm, bk, bn) broadcast intermediate.  4 MB default
    # is deliberately conservative (v5e has 128 MB VMEM but Mosaic needs
    # headroom for double-buffered input windows); env-tunable so
    # on-chip sweeps can find the knee without code edits.  bm is ALSO
    # capped by block_m (default 128), so a sweep above ~8 MB must raise
    # block_m together with the budget (pairwise_distance forwards it).
    budget_env = os.environ.get("RAFT_TPU_PAIRWISE_VMEM_BUDGET")
    try:
        vmem_budget = int(budget_env) if budget_env else 4 << 20
    except ValueError:
        raise ValueError(
            "RAFT_TPU_PAIRWISE_VMEM_BUDGET must be an integer byte count, "
            f"got {budget_env!r}") from None
    bm_cap = max(8, (vmem_budget // (bk * bn * 4)) // 8 * 8)
    bm = min(block_m, m, bm_cap) if m < 8 else min(max(8, min(block_m, m) // 8 * 8), bm_cap)
    # pad to tile multiples (zero padding is contribution-free, see module doc)
    mp, np_, kp = ceildiv(m, bm) * bm, ceildiv(n, bn) * bn, ceildiv(k, bk) * bk
    xp = jnp.pad(x.astype(jnp.float32), ((0, mp - m), (0, kp - k)))
    ytp = jnp.pad(y.astype(jnp.float32).T, ((0, kp - k), (0, np_ - n)))

    grid = (mp // bm, np_ // bn, kp // bk)
    kern = functools.partial(
        _kernel, combine=combine, reduce_kind=reduce_kind, epilog=epilog,
        n_k_tiles=grid[2], init=init)

    out = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((mp, np_), jnp.float32),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=compat.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(xp, ytp)
    return out[:m, :n].astype(out_dtype)
