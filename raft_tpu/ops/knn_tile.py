"""Fused L2-distance + top-k selection Pallas kernel.

TPU-native re-design of the reference's crown-jewel selection path:
``fusedL2kNN`` (cpp/include/raft/spatial/knn/detail/fused_l2_knn.cuh:196)
+ the forked-FAISS warp/block select heaps
(detail/warp_select_faiss.cuh, detail/block_select_faiss.cuh).  One CUDA
kernel there computes a distance tile and immediately runs warp-select
over it so the (n_queries, n_index) matrix never reaches global memory.

There are no warp shuffles or per-thread heaps on a systolic machine, so
the selection is redesigned around what the VPU does well — full-width
vector compares and lane permutations:

- grid = (query_tiles, index_tiles), index innermost; the running top-k
  for the current query tile lives in VMEM scratch across index tiles
  (the Pallas matmul-accumulator pattern), so the distance tile is
  consumed in VMEM and never round-trips HBM.
- each index tile: MXU computes the expanded-form distance tile
  ``qn + xn - 2 q@xT``; a *threshold gate* (any distance below the
  current k-th best?) drives a while-loop that usually runs ZERO
  iterations once the top-k warms up — the analog of the reference
  warp-select's early-out compare against the heap limit
  (warp_select_faiss.cuh thread-queue insert check).
- each while-loop round extracts at most one candidate per lane group
  via a strided group-min (a (bm, g, kpad) reshape keeps kpad on the
  128-lane axis), merges the kpad candidates into the sorted running
  top-k, masks the extracted elements, and re-checks the gate.  Each
  group loses one element per round, so the loop is bounded by g
  rounds; expected rounds after warm-up ~0.  Exactness: the loop only
  exits when no remaining distance beats the k-th best, so the final
  buffer is exactly the top-kpad set.
- the merge exploits the running buffer's sorted invariant: sort the
  kpad candidates descending at the NATIVE kpad lane width, then a
  single log2(2*kpad)-stage bitonic-merge tail at the wide width —
  ~4x fewer wide compare-exchange stages than full-sorting the 2*kpad
  concatenation (the r4 steady-state suspect: cross-vreg lane rolls at
  2*kpad > 128 lanes are the kernel's priciest vector op).  Env
  ``RAFT_TPU_KNN_TILE_MERGE``: ``fullsort`` restores the old network;
  ``sorttile`` replaces the whole extract-merge while loop with a
  gated full-tile bitonic sort + one merge tail — no data-dependent
  loop, no (bm, g*kpad) carry (the structural suspects for the
  kernel's measured 80x-over-model wall time; docs/TUNING.md).  All
  three are A/B'd on chip by ``tools/knn_kernel_sweep.py``.
- the bitonic compare-exchange is lane-parallel: partner values are
  obtained with two circular lane rolls and an XOR-bit select, payload
  indices ride along with strict-inequality "take partner" predicates
  (equal keys keep their own payload, so no id is duplicated or lost).

The running buffer is kept sorted ascending at all times, so the output
needs no final sort.  Distances returned are squared L2 (the sqrt fixup
is the caller's postprocess, knn_brute_force_faiss.cuh:367-380).

Hardware validation: 23/23 compiled-path checks green on TPU v5e
(ONCHIP_r04.md run 3) — k in {8,64,100,128} plus the k>128 XLA
auto-dispatch, ragged shapes, d=384 cross-k-tile accumulation, and
the 100k x 1024 k=100 timing shape, distances rtol 1e-5 vs the XLA
path with every index mismatch a recomputed-distance tie.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_tpu.ops import compat

from raft_tpu.core import tuning
from raft_tpu.core.error import expects
from raft_tpu.core.profiler import profiled
from raft_tpu.core.utils import ceildiv, is_tpu_backend

_INF = float("inf")


def tile_geometry(m_rows: int, n_rows: int, d: int, block_rows: int,
                  block_n: int, unit: int):
    """Shared tiling/padding geometry of the fused distance kernels
    (this one and :mod:`raft_tpu.ops.nn_tile`): index-block size ``bn``
    as a multiple of ``unit`` (the lane-group width), group count ``g``,
    row-block ``bm`` (8-aligned), padded depth ``dp`` (128-aligned above
    128, else full), and the padded totals.  One definition so the
    padding rules cannot drift between the kernels."""
    bn = max(block_n // unit, 2) * unit if block_n >= 2 * unit else 2 * unit
    bn = min(bn, ceildiv(n_rows, unit) * unit)
    g = bn // unit
    bm = max(8, min(block_rows, ceildiv(m_rows, 8) * 8) // 8 * 8)
    dp = ceildiv(d, 128) * 128 if d > 128 else d
    return bm, bn, g, dp, ceildiv(m_rows, bm) * bm, ceildiv(n_rows, bn) * bn


def pad_with_norms(a: jnp.ndarray, rows_pad: int, dp: int):
    """f32-cast, zero-pad to (rows_pad, dp), and return (padded, row
    squared-norms) — the expanded-form precompute both kernels share."""
    af = jnp.pad(a.astype(jnp.float32),
                 ((0, rows_pad - a.shape[0]), (0, dp - a.shape[1])))
    return af, jnp.sum(af * af, axis=1)


def resolve_blocks(block_q, block_n, *, site, n, k, d, dtype):
    """Registry resolution of the fused-kNN tile shape: explicit args
    validate against the integer ladder (as strings — the registry's
    candidate currency), None falls through the config ladder
    (override → configure → env → tuning table → default) so swept
    winners reach every kernel call site with zero consumer literals."""
    bq = int(tuning.resolve(
        "knn_block_q", None if block_q is None else str(block_q),
        site=site, n=n, k=k, d=d, dtype=dtype))
    bn = int(tuning.resolve(
        "knn_block_n", None if block_n is None else str(block_n),
        site=site, n=n, k=k, d=d, dtype=dtype))
    return bq, bn


def _roll_lanes(x: jnp.ndarray, shift: int, interpret: bool) -> jnp.ndarray:
    """Circular shift along the lane (last) axis.

    Mosaic's ``pltpu.roll`` rejects negative shifts (the interpreter's
    ``jnp.roll`` accepts them — exactly the kind of divergence that made
    the compiled kernel fail TPU lowering while every interpret-mode
    test passed); a circular roll by -s over w lanes equals a roll by
    w - s, so normalize modulo the lane count."""
    if interpret:
        return jnp.roll(x, shift, axis=1)
    # int32 scalar: under jax_enable_x64 a Python-int shift becomes an
    # i64 operand, which tpu.dynamic_rotate rejects
    return pltpu.roll(x, jnp.int32(shift % x.shape[1]), axis=1)


def _ce_stage(keys: jnp.ndarray, vals: jnp.ndarray, lane: jnp.ndarray,
              stride: int, asc_mask: jnp.ndarray,
              interpret: bool) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One bitonic compare-exchange stage over the lane axis.

    Partner lane = lane XOR stride (fetched as two circular rolls + a
    bit select); rows/lanes where ``asc_mask`` holds keep the min in the
    lower lane of the pair (ascending direction), the rest the max.
    """
    fwd_k = _roll_lanes(keys, -stride, interpret)
    bwd_k = _roll_lanes(keys, stride, interpret)
    fwd_v = _roll_lanes(vals, -stride, interpret)
    bwd_v = _roll_lanes(vals, stride, interpret)
    upper = (lane & stride) != 0              # partner is lane - stride
    pk = jnp.where(upper, bwd_k, fwd_k)
    pv = jnp.where(upper, bwd_v, fwd_v)
    want_min = asc_mask != upper
    # mask logical ops, NOT jnp.where(bool, bool, bool): a select
    # producing an i1 vector makes Mosaic truncate i8→i1, which the
    # real backend rejects ("Unsupported target bitwidth for
    # truncation") even though lowering and interpret both pass
    take = (want_min & (pk < keys)) | (~want_min & (pk > keys))
    keys = jnp.where(want_min, jnp.minimum(keys, pk),
                     jnp.maximum(keys, pk))
    vals = jnp.where(take, pv, vals)
    return keys, vals


def _bitonic_sort_lanes(keys: jnp.ndarray, vals: jnp.ndarray,
                        interpret: bool, descending: bool = False
                        ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Sort each row by key, carrying an int payload.

    Classic bitonic network over the lane axis (width W = power of two).
    Stage (size, stride): partner lane = lane XOR stride; ascending
    blocks where (lane & size) == 0 (inverted for ``descending``).
    O(log^2 W) full-width VPU stages, no scalar loops.
    """
    bm, w = keys.shape
    assert w & (w - 1) == 0, f"bitonic width {w} not a power of two"
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, w), 1)
    size = 2
    while size <= w:
        asc = (lane & size) == 0
        if descending:
            asc = ~asc
        stride = size // 2
        while stride >= 1:
            keys, vals = _ce_stage(keys, vals, lane, stride, asc,
                                   interpret)
            stride //= 2
        size *= 2
    return keys, vals


def _bitonic_merge_lanes(keys: jnp.ndarray, vals: jnp.ndarray,
                         interpret: bool
                         ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Merge one BITONIC row (first half ascending, second half
    descending) into ascending order: the log2(W)-stage tail of the
    bitonic network, without the log^2 sorting prefix.  This is the
    cheap half of the classic sorted-list merge: W/2-wide sorted lists
    A asc ++ B desc form a bitonic sequence by construction."""
    bm, w = keys.shape
    assert w & (w - 1) == 0, f"bitonic width {w} not a power of two"
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, w), 1)
    asc = jnp.ones_like(lane, dtype=bool)
    stride = w // 2
    while stride >= 1:
        keys, vals = _ce_stage(keys, vals, lane, stride, asc, interpret)
        stride //= 2
    return keys, vals


def tile_local_topk(dist: jnp.ndarray, base_col, *, kpad: int, g: int,
                    interpret: bool) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(bm, g*kpad) key tile → its kpad smallest, sorted DESCENDING,
    with reconstructed global ids (finite keys only; ids -1 elsewhere).

    The one owner of the tile-local id-mask / power-of-two lane pad /
    descending bitonic sort rules, shared by the ``sorttile`` merge
    branch and the two-phase kernel — the attribution comparison
    between them is only valid while both use the identical network.
    """
    bm = dist.shape[0]
    inf32 = jnp.float32(_INF)
    lane_w = jax.lax.broadcasted_iota(jnp.int32, (bm, g * kpad), 1)
    ids = jnp.where(dist < inf32, base_col + lane_w, jnp.int32(-1))
    # the bitonic network needs a power-of-two width; g need not be one
    # (ragged tiles) — pad with +inf/-1 lanes that sort last
    w2 = 1
    while w2 < g * kpad:
        w2 *= 2
    if w2 > g * kpad:
        pad = w2 - g * kpad
        dist = jnp.concatenate([dist, jnp.full((bm, pad), inf32)], axis=1)
        ids = jnp.concatenate(
            [ids, jnp.full((bm, pad), jnp.int32(-1))], axis=1)
    # descending full sort: the kpad SMALLEST land in the last lanes,
    # already descending — the exact bitonic second half a merge tail
    # wants (no lane reverse needed)
    sd, si = _bitonic_sort_lanes(dist, ids, interpret, descending=True)
    return sd[:, -kpad:], si[:, -kpad:]


def topk_update(dist: jnp.ndarray, bd: jnp.ndarray, bi: jnp.ndarray,
                base_col: jnp.ndarray, *, kpad: int, g: int,
                interpret: bool, merge_impl: str
                ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Threshold-gated merge of one keys tile into a running top-k.

    The shared selection core of the fused kNN kernel and the
    standalone select kernel (:mod:`raft_tpu.ops.select_tile`): given a
    (bm, g*kpad) tile of keys (smaller = better; padding pre-masked to
    +inf) and the sorted-ascending running buffers (bd, bi), runs the
    extract-merge while-loop until no remaining key beats the k-th
    best.  ``base_col`` is the tile's global column offset (traced
    scalar), used to reconstruct global payload ids from the strided
    (g, kpad) grouping.  Returns the updated (bd, bi).
    """
    bm = dist.shape[0]
    inf32 = jnp.float32(_INF)

    if merge_impl == "skip":
        # ATTRIBUTION PROBE ONLY (sweep tool): evaluate the gate, then
        # drop every candidate.  Times the kernel's MXU + DMA + grid
        # + gate floor; t(real merge) - t(skip) isolates the selection
        # network's true cost on chip.  Returns WRONG top-k results by
        # design — never reachable from the public dispatch
        # (fused_l2_knn/select_tile whitelists exclude it).
        worst = bd[:, kpad - 1:kpad]
        hit = jnp.max((dist < worst).astype(jnp.float32)) > jnp.float32(0)
        # keep the gate's reduction live by folding it numerically into
        # the output (a same-operand select would be canonicalized away
        # and the gate dead-coded, under-counting the floor)
        bd = bd + hit.astype(bd.dtype)
        return bd, bi

    if merge_impl == "sorttile":
        # r4 variant with NO data-dependent while loop and no (bm,
        # g*kpad) loop carry — the two structural suspects for the
        # kernel's measured-vs-modeled 80x gap (docs/TUNING.md "Open
        # question").  One scalar gate; contributing tiles pay a fixed
        # full-width bitonic sort + one 2*kpad merge tail.
        worst = bd[:, kpad - 1:kpad]
        # f32 reduce-max, not jnp.any (f64 proxy under x64, as below;
        # Mosaic also lacks integer reductions on this build)
        hit = jnp.max((dist < worst).astype(jnp.float32)) > jnp.float32(0)

        def _update(args):
            d_, bd_, bi_ = args
            sd, si = tile_local_topk(d_, base_col, kpad=kpad, g=g,
                                     interpret=interpret)
            md = jnp.concatenate([bd_, sd], axis=1)
            mi = jnp.concatenate([bi_, si], axis=1)
            md, mi = _bitonic_merge_lanes(md, mi, interpret)
            return md[:, :kpad], mi[:, :kpad]

        return jax.lax.cond(hit, _update, lambda args: (args[1], args[2]),
                            (dist, bd, bi))

    r_iota = jax.lax.broadcasted_iota(jnp.int32, (bm, kpad), 1)
    gg_iota = jax.lax.broadcasted_iota(jnp.int32, (bm, g, kpad), 1)

    def gate(state):
        d, bd, _ = state
        worst = bd[:, kpad - 1:kpad]
        # f32 reduce-max, not jnp.any: Mosaic proxies boolean
        # reductions through the default float type, which is f64 under
        # jax_enable_x64 and has no TPU lowering — and this build's
        # Mosaic has no integer reductions either
        return jnp.max((d < worst).astype(jnp.float32)) > jnp.float32(0)

    def extract_merge(state):
        d, bd, bi = state
        d3 = d.reshape(bm, g, kpad)
        gmin = jnp.min(d3, axis=1)                        # (bm, kpad)
        is_min = d3 == jnp.expand_dims(gmin, 1)
        # reduce in f32 (exact: gg <= g << 2**24) — this build's Mosaic
        # has no integer reductions
        gg_star = jnp.min(
            jnp.where(is_min, gg_iota, jnp.int32(g)).astype(jnp.float32),
            axis=1).astype(jnp.int32)
        # candidate global id: strided grouping → column = gg*kpad + r
        cand_i = base_col + gg_star * kpad + r_iota
        cand_i = jnp.where(gmin < inf32, cand_i, jnp.int32(-1))
        # mask the extracted element of each group (exactly one: the
        # lowest-gg argmin)
        picked = gg_iota == jnp.expand_dims(gg_star, 1)
        d = jnp.where(picked, inf32, d3).reshape(bm, g * kpad)
        # merge candidates into the running top-k.  bd is sorted
        # ascending at all times (init is all-inf; every merge below
        # returns a sorted prefix), so the default path sorts only the
        # kpad candidates — at the NATIVE kpad lane width — descending,
        # and then needs just the log2(2*kpad)-stage bitonic-merge tail
        # at the wide width: ~4x fewer wide compare-exchange stages
        # than full-sorting the 2*kpad concatenation each round.
        if merge_impl == "fullsort":
            md = jnp.concatenate([bd, gmin], axis=1)      # (bm, 2*kpad)
            mi = jnp.concatenate([bi, cand_i], axis=1)
            md, mi = _bitonic_sort_lanes(md, mi, interpret)
        else:
            gs, cs = _bitonic_sort_lanes(gmin, cand_i, interpret,
                                         descending=True)
            md = jnp.concatenate([bd, gs], axis=1)        # bitonic row
            mi = jnp.concatenate([bi, cs], axis=1)
            md, mi = _bitonic_merge_lanes(md, mi, interpret)
        return d, md[:, :kpad], mi[:, :kpad]

    _, bd, bi = jax.lax.while_loop(gate, extract_merge, (dist, bd, bi))
    return bd, bi


def _knn_kernel(q_ref, x_ref, qn_ref, xn_ref, od_ref, oi_ref,
                bd_ref, bi_ref, *, kpad, bn, n_index, n_j_tiles, g,
                precision, interpret, merge_impl):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        bd_ref[:] = jnp.full_like(bd_ref, _INF)
        bi_ref[:] = jnp.full_like(bi_ref, -1)

    # distance tile on the MXU: qn + xn - 2 q@xT (euclidean.cuh expanded
    # form); clamp tiny negatives from cancellation
    acc = jax.lax.dot_general(
        q_ref[:], x_ref[:], dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32, precision=precision)
    dist = qn_ref[:] + xn_ref[:] - 2.0 * acc
    dist = jnp.maximum(dist, 0.0)
    # mask padded index rows of the final tile.  Constants are explicit
    # float32: under jax_enable_x64 a Python-float literal promotes the
    # branch to f64, and Mosaic has no f64 cast (the interpreter
    # silently accepts it -- another compiled-path-only divergence)
    inf32 = jnp.float32(_INF)
    col = jax.lax.broadcasted_iota(jnp.int32, (1, bn), 1)
    dist = jnp.where(j * bn + col < n_index, dist, inf32)

    bd, bi = topk_update(dist, bd_ref[:], bi_ref[:], j * bn, kpad=kpad,
                         g=g, interpret=interpret, merge_impl=merge_impl)
    bd_ref[:] = bd
    bi_ref[:] = bi

    @pl.when(j == n_j_tiles - 1)
    def _emit():
        od_ref[:] = bd_ref[:]
        oi_ref[:] = bi_ref[:]


def _knn_twophase_kernel(q_ref, x_ref, qn_ref, xn_ref, od_ref, oi_ref, *,
                         kpad, bn, n_index, g, precision, interpret):
    """Phase 1 of the no-carry two-phase kNN: distance tile + tile-local
    top-kpad, written out PER TILE.

    Structurally the opposite end of the design space from
    :func:`_knn_kernel`: no VMEM carry across index tiles, no
    threshold gate, no data-dependent while loop — both grid dimensions
    are parallel, so Mosaic can pipeline freely.  Exists to attribute
    (and, if the r4 80x anomaly is carry/gate/pipeline-bound, to win)
    the fused kernel's measured-vs-modeled gap: t(twophase) isolates
    MXU + DMA + the pure selection network with zero cross-tile
    structure.  Phase 2 (one narrow XLA merge over n_tiles*kpad) lives
    in :func:`fused_knn_twophase`.
    """
    j = pl.program_id(1)
    acc = jax.lax.dot_general(
        q_ref[:], x_ref[:], dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32, precision=precision)
    dist = qn_ref[:] + xn_ref[:] - 2.0 * acc
    dist = jnp.maximum(dist, 0.0)
    inf32 = jnp.float32(_INF)
    bm = dist.shape[0]
    col = jax.lax.broadcasted_iota(jnp.int32, (1, bn), 1)
    dist = jnp.where(j * bn + col < n_index, dist, inf32)

    sd, si = tile_local_topk(dist, j * bn, kpad=kpad, g=g,
                             interpret=interpret)
    od_ref[:] = sd
    oi_ref[:] = si


@profiled("ops")
def fused_knn_twophase(
    index: jnp.ndarray,
    queries: jnp.ndarray,
    k: int,
    block_q: Optional[int] = None,
    block_n: Optional[int] = None,
    precision: str = "highest",
    interpret: Optional[bool] = None,
    merge_select_impl: Optional[str] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """k nearest index rows: Pallas per-tile select + one XLA merge.

    Same contract as :func:`fused_knn_tile` (exact squared-L2 top-k,
    ascending, int32 ids; k <= 128).  The kernel emits each index
    tile's local top-kpad — (nq, n_tiles*kpad) candidates — and a
    single XLA ``select_k`` merges them: selection work outside the
    kernel shrinks from width n to n_tiles*kpad (8x at the 100k bench
    geometry), and the kernel keeps zero cross-tile state.  Measured
    against ``merge``/``sorttile`` by ``tools/knn_kernel_sweep.py``.

    ``merge_select_impl`` pins the phase-2 ``select_k`` implementation
    and defaults to exact ``"topk"`` — a registry-only knob
    (:mod:`raft_tpu.core.tuning`, ``config_knob=False``): the merge is
    part of this kernel's EXACTNESS contract, so a process-wide
    ``config.configure(select_impl="approx95")`` pin must not reach it
    silently.  Pass a different impl explicitly to trade exactness
    away on purpose.
    """
    expects(index.ndim == 2 and queries.ndim == 2
            and index.shape[1] == queries.shape[1],
            "fused_knn_twophase: shape mismatch")
    n, d = index.shape
    nq = queries.shape[0]
    expects(0 < k <= n,
            "fused_knn_twophase: k=%d out of range for n=%d", k, n)
    expects(k <= 128,
            "fused_knn_twophase: k <= 128 (bitonic width cap; got %d)", k)
    merge_select_impl = tuning.resolve(
        "merge_select_impl", merge_select_impl,
        site="fused_knn_twophase", k=k, dtype=index.dtype)
    block_q, block_n = resolve_blocks(block_q, block_n,
                                      site="fused_knn_twophase",
                                      n=n, k=k, d=d, dtype=index.dtype)
    if interpret is None:
        interpret = not is_tpu_backend()
    kpad = 128
    bm, bn, g, dp, mp, np_ = tile_geometry(nq, n, d, block_q, block_n,
                                           unit=kpad)
    xf, xn_row = pad_with_norms(index, np_, dp)
    qf, qn_row = pad_with_norms(queries, mp, dp)
    xn = xn_row[None, :]
    qn = qn_row[:, None]

    grid = (mp // bm, np_ // bn)
    kern = functools.partial(
        _knn_twophase_kernel, kpad=kpad, bn=bn, n_index=n, g=g,
        precision=jax.lax.Precision(precision) if precision else None,
        interpret=interpret)
    part_d, part_i = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, dp), lambda i, j: (i, 0)),
            pl.BlockSpec((bn, dp), lambda i, j: (j, 0)),
            pl.BlockSpec((bm, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((1, bn), lambda i, j: (0, j)),
        ],
        out_specs=[
            pl.BlockSpec((bm, kpad), lambda i, j: (i, j)),
            pl.BlockSpec((bm, kpad), lambda i, j: (i, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((mp, grid[1] * kpad), jnp.float32),
            jax.ShapeDtypeStruct((mp, grid[1] * kpad), jnp.int32),
        ],
        compiler_params=compat.CompilerParams(
            dimension_semantics=("parallel", "parallel"),
        ),
        interpret=interpret,
    )(qf, xf, qn, xn)

    # phase 2: one narrow merge (deferred import: spatial.select_k's
    # pallas impl imports back into ops)
    from raft_tpu.spatial.select_k import select_k

    out_d, out_i = select_k(part_d[:nq], k, select_min=True,
                            values=part_i[:nq],
                            impl=merge_select_impl)
    # deficit slots (n < kpad per tile never happens since k <= n, but
    # masked-padding lanes carry -1) — clamp in-range like the others
    return out_d, jnp.clip(out_i, 0, n - 1)


@profiled("ops")
def fused_knn_tile(
    index: jnp.ndarray,
    queries: jnp.ndarray,
    k: int,
    block_q: Optional[int] = None,
    block_n: Optional[int] = None,
    precision: str = "highest",
    interpret: Optional[bool] = None,
    merge_impl: Optional[str] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """k nearest index rows per query under squared L2, fused on-chip.

    Returns (distances, indices): (n_queries, k) ascending squared-L2
    and int32 ids; exact (matches a full-sort reference on distinct
    distances; ties may resolve to different ids of equal distance).
    """
    expects(index.ndim == 2 and queries.ndim == 2
            and index.shape[1] == queries.shape[1],
            "fused_knn_tile: shape mismatch")
    n, d = index.shape
    nq = queries.shape[0]
    expects(0 < k <= n, "fused_knn_tile: k=%d out of range for n=%d", k, n)
    if interpret is None:
        interpret = not is_tpu_backend()
    # registry resolution: "skip" (the attribution probe that returns
    # WRONG results by design) is an arg-only candidate — the registry
    # rejects it from config/env/table so an env var can never silently
    # break the public dispatch's results
    merge_impl = tuning.resolve("knn_tile_merge", merge_impl,
                                site="fused_knn_tile", n=n, k=k,
                                dtype=index.dtype)
    block_q, block_n = resolve_blocks(block_q, block_n,
                                      site="fused_knn_tile",
                                      n=n, k=k, d=d, dtype=index.dtype)

    # next power of two >= max(k, 128): the bitonic merge width 2*kpad
    # must be a power of two, and kpad must stay a lane multiple
    kpad = 128
    while kpad < k:
        kpad *= 2
    bm, bn, g, dp, mp, np_ = tile_geometry(nq, n, d, block_q, block_n,
                                           unit=kpad)

    xf, xn_row = pad_with_norms(index, np_, dp)
    qf, qn_row = pad_with_norms(queries, mp, dp)
    xn = xn_row[None, :]                                 # (1, np_)
    qn = qn_row[:, None]                                 # (mp, 1)

    grid = (mp // bm, np_ // bn)
    kern = functools.partial(
        _knn_kernel, kpad=kpad, bn=bn, n_index=n, n_j_tiles=grid[1], g=g,
        precision=jax.lax.Precision(precision) if precision else None,
        interpret=interpret, merge_impl=merge_impl)
    out_d, out_i = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, dp), lambda i, j: (i, 0)),
            pl.BlockSpec((bn, dp), lambda i, j: (j, 0)),
            pl.BlockSpec((bm, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((1, bn), lambda i, j: (0, j)),
        ],
        out_specs=[
            pl.BlockSpec((bm, kpad), lambda i, j: (i, 0)),
            pl.BlockSpec((bm, kpad), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((mp, kpad), jnp.float32),
            jax.ShapeDtypeStruct((mp, kpad), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bm, kpad), jnp.float32),
            pltpu.VMEM((bm, kpad), jnp.int32),
        ],
        compiler_params=compat.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(qf, xf, qn, xn)
    return out_d[:nq, :k], out_i[:nq, :k]


@profiled("ops")
def fused_knn_xla(
    index: jnp.ndarray,
    queries: jnp.ndarray,
    k: int,
    block_q: Optional[int] = None,
    block_n: Optional[int] = None,
    precision: str = "highest",
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """XLA-composed fused brute-force kNN — the production off-TPU twin
    of :func:`fused_knn_tile` (the ``fused_l2_knn`` ``"xla_fused"``
    candidate), one program: no materialized (nq, n) distance matrix
    and no second select_k dispatch.

    Shares the kernel's ``tile_geometry``/``pad_with_norms`` padding
    and per-tile distance arithmetic exactly (dot_general contracting
    dim 1 at f32, expanded-form norms, ragged-tail mask), so the
    per-element distance VALUES are bit-identical to the kernel's.
    Only the running selection differs: each index tile takes an exact
    ``lax.top_k`` merged into the running (bm, k) window instead of the
    kernel's lane networks (that op-for-op replay lives in
    :func:`fused_knn_xla_oracle`; it exists for bitwise tests, not for
    serving — it is ~1000x slower).  Exact selection over identical
    values means the OUTPUT distances still match the kernel bitwise;
    ids agree wherever distances are distinct (equal-distance ties may
    pick a different id — the kernel's own documented latitude).

    The ``knn_block_q``/``knn_block_n`` ladders drive this path's tile
    geometry too, which is what makes the block-shape knobs honestly
    timeable on every backend (tools/autotune.py).
    """
    expects(index.ndim == 2 and queries.ndim == 2
            and index.shape[1] == queries.shape[1],
            "fused_knn_xla: shape mismatch")
    n, d = index.shape
    nq = queries.shape[0]
    expects(0 < k <= n, "fused_knn_xla: k=%d out of range for n=%d", k, n)
    expects(k <= 128,
            "fused_knn_xla: k <= 128 (bitonic width cap; got %d)", k)
    block_q, block_n = resolve_blocks(block_q, block_n,
                                      site="fused_knn_xla",
                                      n=n, k=k, d=d, dtype=index.dtype)
    kpad = 128
    bm, bn, g, dp, mp, np_ = tile_geometry(nq, n, d, block_q, block_n,
                                           unit=kpad)
    xf, xn_row = pad_with_norms(index, np_, dp)
    qf, qn_row = pad_with_norms(queries, mp, dp)
    n_i, n_j = mp // bm, np_ // bn
    xts = xf.reshape(n_j, bn, dp)
    xnts = xn_row.reshape(n_j, 1, bn)
    prec = jax.lax.Precision(precision) if precision else None
    inf32 = jnp.float32(_INF)
    col = jax.lax.broadcasted_iota(jnp.int32, (1, bn), 1)

    def row_tile(args):
        qt, qnt = args                       # (bm, dp), (bm, 1)

        def step(carry, xargs):
            bneg, bi = carry                 # negated running top-k
            xt, xnt, j = xargs
            acc = jax.lax.dot_general(
                qt, xt, dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32, precision=prec)
            dist = jnp.maximum(qnt + xnt - 2.0 * acc, 0.0)
            dist = jnp.where(j * bn + col < n, dist, inf32)
            # exact tile top-k on negated distances (top_k is a max
            # select), then an exact merge of the 2k-wide concat
            tneg, ti = jax.lax.top_k(-dist, k)
            cneg = jnp.concatenate([bneg, tneg], axis=1)
            ci = jnp.concatenate([bi, j * bn + ti], axis=1)
            mneg, mpos = jax.lax.top_k(cneg, k)
            return (mneg, jnp.take_along_axis(ci, mpos, axis=1)), None

        init = (jnp.full((bm, k), -_INF, jnp.float32),
                jnp.full((bm, k), -1, jnp.int32))
        (bneg, bi), _ = jax.lax.scan(
            step, init, (xts, xnts, jnp.arange(n_j, dtype=jnp.int32)))
        return -bneg, bi

    out_d, out_i = jax.lax.map(
        row_tile, (qf.reshape(n_i, bm, dp), qn_row.reshape(n_i, bm, 1)))
    return (out_d.reshape(mp, k)[:nq], out_i.reshape(mp, k)[:nq])


@profiled("ops")
def fused_knn_xla_oracle(
    index: jnp.ndarray,
    queries: jnp.ndarray,
    k: int,
    block_q: Optional[int] = None,
    block_n: Optional[int] = None,
    precision: str = "highest",
    merge_impl: Optional[str] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Op-for-op XLA replay of :func:`fused_knn_tile` — the kernel's
    bitwise correctness oracle (tests only; seconds per call — the
    production XLA twin is :func:`fused_knn_xla`).

    Replays the kernel at the jnp level: the same
    ``tile_geometry``/``pad_with_norms`` padding, the same per-(i, j)
    tile distance compute (dot_general + expanded-form norms + the
    ragged-tail mask), and the very same :func:`topk_update` running
    top-k (interpret-path lane networks) — a ``lax.scan`` over index
    tiles inside a ``lax.map`` over row tiles stands in for the
    (parallel, arbitrary) grid.  Identical op order per element means
    the interpreted kernel and this path agree BITWISE on one backend
    (tests/test_fused_kernels.py pins that), which is what makes it an
    oracle rather than just another implementation.

    scan, not vmap, over the inner axis: vmapping the while-loop gate
    would rewrite it to a masked fixed-trip form and the op order (and
    tie behavior) would drift from the kernel's.
    """
    expects(index.ndim == 2 and queries.ndim == 2
            and index.shape[1] == queries.shape[1],
            "fused_knn_xla_oracle: shape mismatch")
    n, d = index.shape
    nq = queries.shape[0]
    expects(0 < k <= n,
            "fused_knn_xla_oracle: k=%d out of range for n=%d", k, n)
    expects(k <= 128,
            "fused_knn_xla_oracle: k <= 128 (bitonic width cap; got %d)",
            k)
    merge_impl = tuning.resolve("knn_tile_merge", merge_impl,
                                site="fused_knn_xla_oracle", n=n, k=k,
                                dtype=index.dtype)
    expects(merge_impl != "skip",
            "fused_knn_xla_oracle: the 'skip' attribution probe is "
            "kernel-only")
    block_q, block_n = resolve_blocks(block_q, block_n,
                                      site="fused_knn_xla_oracle",
                                      n=n, k=k, d=d, dtype=index.dtype)
    kpad = 128
    while kpad < k:
        kpad *= 2
    bm, bn, g, dp, mp, np_ = tile_geometry(nq, n, d, block_q, block_n,
                                           unit=kpad)
    xf, xn_row = pad_with_norms(index, np_, dp)
    qf, qn_row = pad_with_norms(queries, mp, dp)
    n_i, n_j = mp // bm, np_ // bn
    xts = xf.reshape(n_j, bn, dp)
    xnts = xn_row.reshape(n_j, 1, bn)
    prec = jax.lax.Precision(precision) if precision else None
    inf32 = jnp.float32(_INF)
    col = jax.lax.broadcasted_iota(jnp.int32, (1, bn), 1)

    def row_tile(args):
        qt, qnt = args                       # (bm, dp), (bm, 1)

        def step(carry, xargs):
            bd, bi = carry
            xt, xnt, j = xargs
            acc = jax.lax.dot_general(
                qt, xt, dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32, precision=prec)
            dist = jnp.maximum(qnt + xnt - 2.0 * acc, 0.0)
            dist = jnp.where(j * bn + col < n, dist, inf32)
            bd, bi = topk_update(dist, bd, bi, j * bn, kpad=kpad, g=g,
                                 interpret=True, merge_impl=merge_impl)
            return (bd, bi), None

        init = (jnp.full((bm, kpad), _INF, jnp.float32),
                jnp.full((bm, kpad), -1, jnp.int32))
        (bd, bi), _ = jax.lax.scan(
            step, init, (xts, xnts, jnp.arange(n_j, dtype=jnp.int32)))
        return bd, bi

    out_d, out_i = jax.lax.map(
        row_tile, (qf.reshape(n_i, bm, dp), qn_row.reshape(n_i, bm, 1)))
    out_d = out_d.reshape(mp, kpad)
    out_i = out_i.reshape(mp, kpad)
    return out_d[:nq, :k], out_i[:nq, :k]
