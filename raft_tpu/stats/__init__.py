"""Summary statistics (reference cpp/include/raft/stats/: mean.hpp:44,
stddev.hpp:45,76, sum.hpp:41, mean_center.hpp:41,77 — row/col-major ×
sample/population variants)."""

from raft_tpu.stats.stats import mean, mean_add, mean_center, stddev, sum_cols, vars_

__all__ = ["mean", "stddev", "vars_", "sum_cols", "mean_center", "mean_add"]
