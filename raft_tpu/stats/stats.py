"""Column statistics over (n_samples, n_features) data.

Reference: cpp/include/raft/stats/ — the reference computes per-*column*
statistics (one value per feature) with row-major/col-major kernel variants;
here the logical reduction over axis 0 is all that remains.
"""

from __future__ import annotations

import jax.numpy as jnp

from raft_tpu.core.handle import takes_handle


@takes_handle
def mean(data: jnp.ndarray, sample: bool = False, row_major: bool = True) -> jnp.ndarray:
    """Per-column mean (reference stats/mean.hpp:44).  ``sample`` selects the
    (n-1) divisor — kept for signature parity; for mean both divisors are n
    in the reference too (the flag matters for stddev)."""
    del sample, row_major
    return jnp.mean(data, axis=0)


@takes_handle
def sum_cols(data: jnp.ndarray, row_major: bool = True) -> jnp.ndarray:
    """Per-column sum (reference stats/sum.hpp:41)."""
    del row_major
    return jnp.sum(data, axis=0)


@takes_handle
def vars_(
    data: jnp.ndarray,
    mu: jnp.ndarray | None = None,
    sample: bool = True,
    row_major: bool = True,
) -> jnp.ndarray:
    """Per-column variance (reference stats/stddev.hpp:76 ``vars``)."""
    del row_major
    if mu is None:
        mu = jnp.mean(data, axis=0)
    n = data.shape[0]
    ss = jnp.sum((data - mu[None, :]) ** 2, axis=0)
    return ss / (n - 1 if sample else n)


@takes_handle
def stddev(
    data: jnp.ndarray,
    mu: jnp.ndarray | None = None,
    sample: bool = True,
    row_major: bool = True,
) -> jnp.ndarray:
    """Per-column standard deviation (reference stats/stddev.hpp:45)."""
    return jnp.sqrt(vars_(data, mu=mu, sample=sample, row_major=row_major))


@takes_handle
def mean_center(data: jnp.ndarray, mu: jnp.ndarray, bcast_along_rows: bool = True) -> jnp.ndarray:
    """Subtract the mean vector (reference stats/mean_center.hpp:41)."""
    return data - (mu[None, :] if bcast_along_rows else mu[:, None])


@takes_handle
def mean_add(data: jnp.ndarray, mu: jnp.ndarray, bcast_along_rows: bool = True) -> jnp.ndarray:
    """Add the mean vector back (reference stats/mean_center.hpp:77)."""
    return data + (mu[None, :] if bcast_along_rows else mu[:, None])
