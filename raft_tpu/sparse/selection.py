"""Sparse brute-force kNN and kNN-graph construction.

Reference: sparse/selection/knn.hpp:52 (``brute_force_knn`` over CSR) whose
engine ``sparse_knn_t::run`` (selection/detail/knn.cuh:117,162) tiles index
and query matrices with ``csr_batcher_t`` (:41), computes block distances,
k-selects per block, and merges running results; and
sparse/selection/knn_graph.hpp:46 (symmetrized kNN graph from dense input).

TPU design: batching is a static double loop over row tiles (shapes fixed →
one XLA program); per-block select_k is the shared sort-based top-k; the
running merge is ``knn_merge_parts`` over [running, block] — identical
dataflow to the reference, minus streams/heaps.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from raft_tpu.distance.distance_type import DistanceType
from raft_tpu.sparse.distance import block_pairwise, densify_rows
from raft_tpu.sparse.formats import CSR
from raft_tpu.sparse.linalg import symmetrize_knn
from raft_tpu.spatial.knn import knn_merge_parts
from raft_tpu.spatial.select_k import select_k

D = DistanceType


@functools.partial(jax.jit, static_argnames=(
    "k", "metric", "metric_arg", "batch_size_index", "batch_size_query"))
def brute_force_knn(idx: CSR, query: CSR, k: int,
                    metric: DistanceType = D.L2Expanded,
                    metric_arg: float = 2.0,
                    batch_size_index: int = 2048,
                    batch_size_query: int = 2048,
                    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """k nearest index rows for every query row, both CSR.

    Returns (distances, indices) of shape (n_query, k), best-first.
    Reference: sparse/selection/knn.hpp:52.
    """
    from raft_tpu.core.error import expects

    m, nq = idx.n_rows, query.n_rows
    expects(0 < k <= m, "sparse brute_force_knn: k=%d out of range for "
            "n_index=%d", k, m)
    select_min = metric != D.InnerProduct
    bi = min(batch_size_index, m)
    bq = min(batch_size_query, nq)
    n_tiles_i = -(-m // bi)
    n_tiles_q = -(-nq // bq)

    worst = jnp.inf if select_min else -jnp.inf
    # densify each index tile once, not once per query tile; lax.map /
    # fori_loop keep the HLO O(1) in tile count (one block program, like
    # the reference's single batched engine, selection/detail/knn.cuh:117)
    idx_tiles = jax.lax.map(lambda ii: densify_rows(idx, ii * bi, bi),
                            jnp.arange(n_tiles_i))

    def index_tile_step(xq, ii, carry):
        run_d, run_i = carry
        xi = jax.lax.dynamic_index_in_dim(idx_tiles, ii, 0, keepdims=False)
        blk = block_pairwise(xq, xi, metric, metric_arg).astype(jnp.float32)
        # mask out padding index rows of the last tile
        col_ids = ii * bi + jnp.arange(bi)
        blk = jnp.where(col_ids[None, :] < m, blk, worst)
        bd, bi_local = select_k(blk, min(k, bi), select_min=select_min)
        if bd.shape[1] < k:  # pad block result up to k candidates
            pad = k - bd.shape[1]
            bd = jnp.pad(bd, ((0, 0), (0, pad)), constant_values=worst)
            bi_local = jnp.pad(bi_local, ((0, 0), (0, pad)),
                               constant_values=-1)
        # translate only valid entries: pads stay -1 instead of becoming
        # plausible-looking ids like ii*bi - 1
        bi_glob = jnp.where(bi_local >= 0, bi_local + ii * bi, -1)
        cand_d = jnp.stack([run_d, bd])
        cand_i = jnp.stack([run_i, bi_glob])
        return knn_merge_parts(cand_d, cand_i, k, select_min=select_min)

    def query_tile(iq):
        xq = densify_rows(query, iq * bq, bq)
        init = (jnp.full((bq, k), worst, dtype=jnp.float32),
                jnp.full((bq, k), -1, dtype=jnp.int32))
        return jax.lax.fori_loop(
            0, n_tiles_i, functools.partial(index_tile_step, xq), init)

    out_d, out_i = jax.lax.map(query_tile, jnp.arange(n_tiles_q))
    out_d = out_d.reshape(n_tiles_q * bq, k)[:nq]
    out_i = out_i.reshape(n_tiles_q * bq, k)[:nq]
    return out_d, out_i


def knn_graph(X: jnp.ndarray, k: int,
              metric: DistanceType = D.L2SqrtExpanded,
              handle=None) -> COO:
    """Symmetrized kNN graph of dense row set X (m, d) → COO (m, m).

    Reference: sparse/selection/knn_graph.hpp:46 — kNN (k includes self,
    which is then an explicit zero-weight loop edge filtered by
    symmetrization semantics downstream) + max-symmetrize.
    """
    from raft_tpu.spatial.knn import brute_force_knn as dense_knn

    dists, inds = dense_knn([X], X, k=k, metric=metric, handle=handle)
    return symmetrize_knn(inds, dists, X.shape[0])
