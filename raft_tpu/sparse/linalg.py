"""Sparse linear algebra: add, degree, norms, symmetrize, transpose, SpMV,
weakly-connected components.

Reference: sparse/linalg/{add,degree,norm,symmetrize,transpose}.hpp and the
weak-CC labeller in sparse/csr.hpp:50-167 (Hawick et al. label propagation).

TPU design: per-row work is segment reductions over the CSR segment-id
vector; SpMV is a gather + segment-sum (or densified matmul for the MXU on
small operands); weak-CC's per-vertex frontier kernel becomes a whole-graph
min-label propagation inside ``lax.while_loop``.
"""

from __future__ import annotations

from typing import Callable, Optional


import jax
import jax.numpy as jnp

from raft_tpu.core import tuning
from raft_tpu.core.error import expects
from raft_tpu.core.profiler import profiled, profiled_jit
from raft_tpu.sparse.formats import COO, CSR
from raft_tpu.sparse import convert, op as sparse_op

# the candidate registry (raft_tpu/core/tuning) owns the legal-impl
# set; re-exported here for the callers that enumerate it —
# SparseMatrix's construction-time validation goes through
# tuning.check so a typo'd pin fails where it is written, not deep
# inside a jitted Lanczos solve
SPMV_IMPLS = tuning.candidates("spmv_impl")


# --------------------------------------------------------------------- #
# degree (sparse/linalg/degree.hpp)
# --------------------------------------------------------------------- #
def coo_degree(coo: COO) -> jnp.ndarray:
    """nnz per row (reference coo_degree, sparse/linalg/degree.hpp)."""
    valid = coo.valid_mask()
    rows = jnp.where(valid, coo.rows, coo.n_rows)
    return jax.ops.segment_sum(valid.astype(jnp.int32), rows,
                               num_segments=coo.n_rows + 1)[:-1]


def coo_degree_scalar(coo: COO, scalar) -> jnp.ndarray:
    """Per-row count of entries != scalar (reference coo_degree_scalar,
    sparse/linalg/degree.hpp:66)."""
    valid = coo.valid_mask() & (coo.vals != scalar)
    rows = jnp.where(valid, coo.rows, coo.n_rows)
    return jax.ops.segment_sum(valid.astype(jnp.int32), rows,
                               num_segments=coo.n_rows + 1)[:-1]


def csr_degree(csr: CSR) -> jnp.ndarray:
    return jnp.diff(csr.indptr)


# --------------------------------------------------------------------- #
# row normalization (sparse/linalg/norm.hpp:36,57)
# --------------------------------------------------------------------- #
def _row_reduce(csr: CSR, vals: jnp.ndarray, kind: str) -> jnp.ndarray:
    rows = csr.row_ids()
    n = csr.n_rows
    # row_ids is ascending by construction (padding tail maps to n) —
    # the sorted flag lets XLA lower the scatter as a segmented
    # reduction instead of random scatter-adds
    if kind == "sum":
        return jax.ops.segment_sum(vals, rows, num_segments=n + 1,
                                   indices_are_sorted=True)[:-1]
    if kind == "max":
        return jax.ops.segment_max(
            jnp.where(rows < n, vals, -jnp.inf), rows,
            num_segments=n + 1, indices_are_sorted=True)[:-1]
    raise ValueError(kind)


def csr_row_normalize_l1(csr: CSR) -> CSR:
    """Scale each row to unit L1 norm (reference csr_row_normalize_l1,
    sparse/linalg/norm.hpp:36; rows with zero norm are left as zero)."""
    sums = _row_reduce(csr, jnp.abs(csr.data), "sum")
    rows = csr.row_ids()
    denom = jnp.concatenate([sums, jnp.ones((1,), sums.dtype)])[
        jnp.minimum(rows, csr.n_rows)]
    data = jnp.where(denom != 0, csr.data / jnp.where(denom == 0, 1, denom), 0)
    return CSR(csr.indptr, csr.indices, data, csr.shape)


def csr_row_normalize_max(csr: CSR) -> CSR:
    """Scale each row by its max (reference csr_row_normalize_max,
    sparse/linalg/norm.hpp:57)."""
    mx = _row_reduce(csr, csr.data, "max")
    mx = jnp.where(jnp.isfinite(mx), mx, 0)
    rows = csr.row_ids()
    denom = jnp.concatenate([mx, jnp.ones((1,), mx.dtype)])[
        jnp.minimum(rows, csr.n_rows)]
    data = jnp.where(denom != 0, csr.data / jnp.where(denom == 0, 1, denom), 0)
    return CSR(csr.indptr, csr.indices, data, csr.shape)


def csr_row_norm(csr: CSR, norm: str = "l2") -> jnp.ndarray:
    """Per-row L1/L2(squared)/Linf norms over CSR values."""
    if norm == "l1":
        return _row_reduce(csr, jnp.abs(csr.data), "sum")
    if norm == "l2":
        return _row_reduce(csr, csr.data * csr.data, "sum")
    if norm == "linf":
        r = _row_reduce(csr, jnp.abs(csr.data), "max")
        return jnp.where(jnp.isfinite(r), r, 0)
    raise ValueError(norm)


# --------------------------------------------------------------------- #
# add (sparse/linalg/add.hpp: csr_add_calc_inds + csr_add_finalize)
# --------------------------------------------------------------------- #
def csr_add(a: CSR, b: CSR) -> CSR:
    """C = A + B over CSR (reference csr_add_calc_inds/csr_add_finalize,
    sparse/linalg/add.hpp:75).

    The reference's two-pass hash-bucket kernel becomes: concat COO views,
    sort, segment-sum duplicates.  Output capacity = a.capacity + b.capacity.
    """
    ca, cb = convert.csr_to_coo(a), convert.csr_to_coo(b)
    rows = jnp.concatenate([ca.rows, cb.rows])
    cols = jnp.concatenate([ca.cols, cb.cols])
    vals = jnp.concatenate([ca.vals.astype(jnp.result_type(ca.vals, cb.vals)),
                            cb.vals.astype(jnp.result_type(ca.vals, cb.vals))])
    merged = COO(rows, cols, vals, a.shape)
    summed = sparse_op.sum_duplicates(merged)
    return convert.coo_to_csr(summed, assume_sorted=True)


# --------------------------------------------------------------------- #
# transpose (sparse/linalg/transpose.hpp:43 — cusparse csr2csc there)
# --------------------------------------------------------------------- #
def csr_transpose(csr: CSR) -> CSR:
    """Transpose via COO swap + lexsort (replaces cusparseCsr2cscEx2)."""
    coo = convert.csr_to_coo(csr)
    # after the swap, padding must carry the *new* sentinel (n_cols) so it
    # keeps sorting last
    t_rows = jnp.where(coo.valid_mask(), coo.cols, csr.n_cols)
    t_cols = jnp.where(coo.valid_mask(), coo.rows, 0)
    t = COO(t_rows, t_cols, coo.vals, (csr.n_cols, csr.n_rows), nnz=coo.nnz)
    return convert.coo_to_csr(t)


# --------------------------------------------------------------------- #
# symmetrize (sparse/linalg/symmetrize.hpp:37,150)
# --------------------------------------------------------------------- #
def coo_symmetrize(coo: COO,
                   reduce_op: Optional[Callable] = None) -> COO:
    """Symmetrize: out(i,j) = reduce_op(v_ij, v_ji) over the union of edge
    directions.  Default reduce is sum — the kNN-graph symmetrization the
    single-linkage pipeline needs (reference coo_symmetrize,
    sparse/linalg/symmetrize.hpp:37; from_knn_symmetrize_matrix :136).

    Output capacity is 2x input capacity.
    """
    if reduce_op is None:
        reduce_op = lambda v, vt: v + vt

    s = sparse_op.coo_sort(coo)
    valid = s.valid_mask()
    n_cols_p1 = s.n_cols + 1
    # 64-bit combined keys regardless of the session's x64 setting: int32
    # keys collide once n_rows*(n_cols+1) exceeds 2^31 (any ~46k-vertex
    # graph), so force x64 locally for the key match
    with jax.enable_x64(True):
        key = s.rows.astype(jnp.int64) * n_cols_p1 + s.cols
        key = jnp.where(valid, key, jnp.iinfo(jnp.int64).max)
        # transposed key for each entry: (col, row)
        tkey = s.cols.astype(jnp.int64) * n_cols_p1 + s.rows
        pos = jnp.searchsorted(key, tkey)
        pos_c = jnp.clip(pos, 0, s.capacity - 1).astype(jnp.int32)
        found = (key[pos_c] == tkey) & valid
    vt = jnp.where(found, s.vals[pos_c], 0)

    # combined value for the directed edge (i,j); union with (j,i) edges
    combined = reduce_op(s.vals, vt)
    rows = jnp.concatenate([s.rows,
                            jnp.where(valid, s.cols, s.sentinel)])
    cols = jnp.concatenate([s.cols, jnp.where(valid, s.rows, 0)])
    # the (j,i) copies carry reduce_op(v_ji, v_ij); for entries whose reverse
    # exists both copies appear -> dedup keeps one (values equal for
    # symmetric reduce ops)
    combined_t = reduce_op(vt, s.vals)
    vals = jnp.concatenate([jnp.where(valid, combined, 0),
                            jnp.where(valid, combined_t, 0)])
    union = COO(rows, cols, vals, s.shape)
    return sparse_op.max_duplicates(union)


def symmetrize_knn(knn_indices: jnp.ndarray, knn_dists: jnp.ndarray,
                   n: int) -> COO:
    """Symmetrized COO graph from kNN results (reference symmetrize,
    sparse/linalg/symmetrize.hpp:150): out(i,j) = max over directions.
    """
    m, k = knn_indices.shape
    rows = jnp.repeat(jnp.arange(m, dtype=jnp.int32), k)
    cols = knn_indices.reshape(-1).astype(jnp.int32)
    vals = knn_dists.reshape(-1)
    coo = COO(rows, cols, vals, (n, n))
    return coo_symmetrize(coo, reduce_op=lambda v, vt: jnp.maximum(v, vt))


# --------------------------------------------------------------------- #
# SpMV
# --------------------------------------------------------------------- #
def gather_via_sortscan(x: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """``x[idx]`` with NO gather op: two variadic sorts + one
    associative scan, all vector-shaped on TPU.

    The per-element gather is the serial shape on a TPU (r4 finding for
    2-D take_along_axis; the same lowering serves 1-D LUT reads).  The
    sort formulation interleaves the n sources with the m probes —
    source i keyed ``2·i``, probe j keyed ``2·idx[j]+1``, so each probe
    lands immediately after its source — then a "last source value"
    associative scan fills every probe, and a second sort restores
    probe order.  O((n+m)·log(n+m)) fully-parallel work instead of m
    serial reads; wins whenever the gather is serial and loses only the
    log factor where it is not (the spmv_impl knob A/Bs both on chip).

    Indices must be in ``[0, n)``; out-of-range values (either side)
    are CLAMPED into range.  Unlike numpy fancy indexing, negative
    indices do not wrap — a pre-sorted ``-1`` probe would silently fill
    0.0 without the clamp, so the clamp makes the contract deterministic
    instead (the same rule csr_spmv's padding mask relies on).
    """
    n = x.shape[0]
    m = idx.shape[0]
    i32 = jnp.int32
    idx = jnp.clip(idx, 0, n - 1)
    keys = jnp.concatenate([
        2 * jnp.arange(n, dtype=i32),
        2 * idx.astype(i32) + 1])
    vals = jnp.concatenate([x, jnp.zeros((m,), x.dtype)])
    pos = jnp.concatenate([
        jnp.full((n,), m, i32),          # sources sort AFTER all probes
        jnp.arange(m, dtype=i32)])       # in the restore pass
    _, sv, spos = jax.lax.sort((keys, vals, pos), num_keys=1)
    # source flag derived from pos (sources carry m) — one fewer
    # (n+m)-sized operand through the variadic sort
    ssrc = (spos == m).astype(i32)

    def last_source(a, b):
        av, asrc = a
        bv, bsrc = b
        return jnp.where(bsrc > 0, bv, av), jnp.maximum(asrc, bsrc)

    filled, _ = jax.lax.associative_scan(last_source, (sv, ssrc), axis=0)
    _, out = jax.lax.sort((spos, filled), num_keys=1)
    return out[:m]


@profiled("sparse")
def csr_spmv(csr: CSR, x: jnp.ndarray,
             impl: Optional[str] = None) -> jnp.ndarray:
    """y = A @ x (replaces cusparseSpMV; the Lanczos hot loop rides
    this, see spectral/matrix_wrappers.hpp:180).

    ``impl`` (default: the ``spmv_impl`` knob of :mod:`raft_tpu.config`,
    env alias ``RAFT_TPU_SPMV_IMPL``):

    - ``"segment"`` (default): gather + sorted segment-sum.
    - ``"cumsum"``: prefix-sum formulation — y[i] = cs[indptr[i+1]] -
      cs[indptr[i]] over the exclusive cumsum of the contributions.
      Trades the nnz-sized scatter for an O(nnz) vectorized prefix sum
      plus two n_rows-sized 1-D gathers; a candidate TPU win when nnz
      >> n_rows (scatter-add is the suspect serial path).  ACCURACY
      CAVEAT: the subtraction differences the GLOBAL running prefix, so
      a row's absolute error scales with |cs| at its position, not with
      the row's own sum — rows with small sums late in a large
      same-signed matrix lose relative precision.  Fine for
      graph-Laplacian-shaped data (alternating signs, bounded rows);
      prefer "segment" when row sums are tiny relative to the global
      mass.
    - ``"sortscan"``: like ``"segment"`` but the nnz-sized
      ``x[indices]`` read goes through :func:`gather_via_sortscan`
      (no gather op at all) — the candidate win where the serial
      element gather, not the reduction, bounds the TPU matvec (the
      large-graph spectral regime; small graphs densify instead,
      spectral/matrix_wrappers.py).
    """
    impl = tuning.resolve("spmv_impl", impl, site="csr_spmv",
                          rows=csr.n_rows, nnz=csr.capacity,
                          dtype=csr.data.dtype)
    if impl == "cumsum":
        # validity needs only the entry position vs nnz (the tail is
        # padding by the container invariant) — NOT row_ids(), whose
        # capacity-sized searchsorted is gather-shaped work this impl
        # exists to avoid
        pos = jnp.arange(csr.capacity, dtype=csr.indptr.dtype)
        valid = pos < csr.indptr[-1]
        xv = x[jnp.where(valid, csr.indices, 0)]
        contrib = jnp.where(valid, csr.data * xv, 0)
        cs = jnp.concatenate([
            jnp.zeros((1,), contrib.dtype), jnp.cumsum(contrib)])
        return cs[csr.indptr[1:]] - cs[csr.indptr[:-1]]
    rows = csr.row_ids()
    valid = rows < csr.n_rows
    safe_idx = jnp.where(valid, csr.indices, 0)
    if impl == "sortscan":
        xv = gather_via_sortscan(x, safe_idx)
    else:
        xv = x[safe_idx]
    contrib = jnp.where(valid, csr.data * xv, 0)
    # rows ascending (padding tail = n_rows): sorted segmented sum, not
    # random scatter-add — the Lanczos hot loop rides this
    return jax.ops.segment_sum(contrib, rows, num_segments=csr.n_rows + 1,
                               indices_are_sorted=True)[:-1]


@profiled("sparse")
def csr_spmm(csr: CSR, x: jnp.ndarray) -> jnp.ndarray:
    """Y = A @ X for a dense block X (n_cols, b): vmapped SpMV."""
    return jax.vmap(lambda col: csr_spmv(csr, col), in_axes=1, out_axes=1)(x)


# --------------------------------------------------------------------- #
# weakly connected components (sparse/csr.hpp:50-167)
# --------------------------------------------------------------------- #
@profiled("sparse")
def weak_cc(csr: CSR, max_iters: int = 0) -> jnp.ndarray:
    """Weakly-connected component labels (1-based, matching the reference's
    convention; labels are minima of 1-based vertex ids per component).

    Reference: weak_cc / weak_cc_batched (sparse/csr.hpp:50,118) implement
    Hawick-style frontier label propagation with atomicMin.  TPU version:
    iterate ``label[v] <- min(label[v], min over neighbors)`` with segment-min
    over the edge list in both directions, plus pointer-jumping
    (``label <- label[label-1]``) for logarithmic convergence, inside
    ``lax.while_loop``.
    """
    return _weak_cc_run(csr, max_iters=max_iters)


@profiled_jit(name="weak_cc", static_argnames=("max_iters",))
def _weak_cc_run(csr: CSR, max_iters: int) -> jnp.ndarray:
    # one cached executable per shape (eager while_loop closures would
    # retrace every call — r5 retrace audit)
    n = csr.n_rows
    rows = csr.row_ids()
    valid = rows < n
    src = jnp.where(valid, rows, 0)
    dst = jnp.where(valid, csr.indices, 0)
    labels0 = jnp.arange(1, n + 1, dtype=jnp.int32)

    def relax(labels):
        lsrc, ldst = labels[src], labels[dst]
        big = jnp.iinfo(jnp.int32).max
        m1 = jax.ops.segment_min(jnp.where(valid, ldst, big), src,
                                 num_segments=n)
        m2 = jax.ops.segment_min(jnp.where(valid, lsrc, big), dst,
                                 num_segments=n)
        labels = jnp.minimum(labels, jnp.minimum(m1, m2))
        # pointer jumping: a vertex can adopt its representative's label
        return jnp.minimum(labels, labels[labels - 1])

    def cond(state):
        labels, prev, it = state
        not_conv = jnp.any(labels != prev)
        if max_iters:
            return not_conv & (it < max_iters)
        return not_conv

    def body(state):
        labels, _, it = state
        return relax(labels), labels, it + 1

    labels, _, _ = jax.lax.while_loop(
        cond, body, (relax(labels0), labels0, jnp.int32(1)))
    return labels
