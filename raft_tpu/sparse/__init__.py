"""Sparse formats, conversions, ops, and linear algebra (TPU-native).

Re-designs the reference's largest module (``cpp/include/raft/sparse/``,
~11.6k LoC of CUDA) for a dense-tile machine:

- Containers are **fixed-capacity padded pytrees** (static shapes for XLA);
  invalid entries carry a sentinel row id that sorts past every real row.
- Irregular CUDA patterns (atomics, warp scans, cuCollections hash tables)
  become sort + segment-reduce, which XLA lowers to efficient TPU code.
- nnz-changing ops (filter, dedup) keep capacity and return a valid count,
  so they stay jittable; ``compact()`` trims eagerly outside jit.
"""

from raft_tpu.sparse.formats import COO, CSR  # noqa: F401
from raft_tpu.sparse import convert, op, linalg  # noqa: F401
from raft_tpu.sparse import distance, selection  # noqa: F401
from raft_tpu.sparse import mst, linkage, hierarchy  # noqa: F401
from raft_tpu.sparse import spectral  # noqa: F401
