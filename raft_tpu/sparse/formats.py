"""Sparse containers: padded, static-shape COO and CSR pytrees.

Reference: ``raft::sparse::COO`` (sparse/detail/coo.cuh:46, public
sparse/coo.hpp) — an owning device container with (rows, cols, vals, nnz,
n_rows, n_cols) — and the CSR free-function convention (indptr + indices +
data raw pointers, sparse/csr.hpp).

TPU design: XLA requires static shapes, so both containers are
**fixed-capacity**: the leaf arrays have length ``capacity`` and only the
first ``nnz`` entries (after compaction) are valid.  Padding entries carry
``row == n_rows`` — a sentinel that sorts after every valid row, so sorted
containers keep padding at the tail and ``searchsorted``-built indptrs are
automatically correct.  Both classes are registered as pytrees so they can
flow through ``jit`` / ``vmap`` / ``shard_map`` unchanged.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


def _as_idx(x) -> jnp.ndarray:
    return jnp.asarray(x, dtype=jnp.int32)


@jax.tree_util.register_pytree_node_class
class COO:
    """Coordinate-format sparse matrix (padded, static capacity).

    Parameters
    ----------
    rows, cols : int32 arrays of shape (capacity,)
    vals : array of shape (capacity,)
    shape : (n_rows, n_cols) — static.
    nnz : number of valid entries.  May be a Python int (static) or a traced
        int32 scalar (when produced inside jit by an nnz-changing op).
    """

    def __init__(self, rows, cols, vals, shape: Tuple[int, int], nnz=None):
        self.rows = _as_idx(rows)
        self.cols = _as_idx(cols)
        self.vals = jnp.asarray(vals)
        self.shape = (int(shape[0]), int(shape[1]))
        self.nnz = self.capacity if nnz is None else nnz

    # -- pytree protocol ------------------------------------------------ #
    def tree_flatten(self):
        static_nnz = isinstance(self.nnz, (int, np.integer))
        if static_nnz:
            return (self.rows, self.cols, self.vals), (self.shape, int(self.nnz))
        return (self.rows, self.cols, self.vals, self.nnz), (self.shape, None)

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        shape, static_nnz = aux
        if static_nnz is not None:
            rows, cols, vals = leaves
            return cls(rows, cols, vals, shape, static_nnz)
        rows, cols, vals, nnz = leaves
        return cls(rows, cols, vals, shape, nnz)

    # -- properties ----------------------------------------------------- #
    @property
    def capacity(self) -> int:
        return int(self.rows.shape[0])

    @property
    def n_rows(self) -> int:
        return self.shape[0]

    @property
    def n_cols(self) -> int:
        return self.shape[1]

    @property
    def sentinel(self) -> int:
        """Row id marking padding entries (sorts after all valid rows)."""
        return self.shape[0]

    def valid_mask(self) -> jnp.ndarray:
        """Boolean mask of real (non-padding) entries."""
        return self.rows < self.shape[0]

    # -- construction helpers ------------------------------------------- #
    @classmethod
    def from_dense(cls, dense, capacity: int | None = None) -> "COO":
        """Eager construction from a dense matrix (host-side helper)."""
        d = np.asarray(dense)
        r, c = np.nonzero(d)
        v = d[r, c]
        nnz = len(r)
        cap = capacity if capacity is not None else max(nnz, 1)
        assert cap >= nnz, "capacity too small"
        rows = np.full(cap, d.shape[0], dtype=np.int32)
        cols = np.zeros(cap, dtype=np.int32)
        vals = np.zeros(cap, dtype=d.dtype)
        rows[:nnz], cols[:nnz], vals[:nnz] = r, c, v
        return cls(rows, cols, vals, d.shape, nnz)

    def to_dense(self) -> jnp.ndarray:
        """Densify; duplicate coordinates are summed."""
        mask = self.valid_mask()
        r = jnp.where(mask, self.rows, 0)
        c = jnp.where(mask, self.cols, 0)
        v = jnp.where(mask, self.vals, 0)
        out = jnp.zeros(self.shape, dtype=self.vals.dtype)
        return out.at[r, c].add(v, mode="drop")

    def compact(self) -> "COO":
        """Trim padding to the true nnz (eager; not jittable)."""
        n = int(self.nnz)
        order = jnp.argsort(~self.valid_mask(), stable=True)  # valid first
        return COO(
            self.rows[order][:n], self.cols[order][:n], self.vals[order][:n],
            self.shape, n,
        )

    def __repr__(self):
        return (f"COO(shape={self.shape}, capacity={self.capacity}, "
                f"nnz={self.nnz})")


@jax.tree_util.register_pytree_node_class
class CSR:
    """Compressed-sparse-row matrix (padded, static capacity).

    ``indptr`` has length n_rows+1 and indexes into ``indices``/``data``;
    entries at positions >= indptr[-1] are padding.  Mirrors the reference's
    raw-pointer CSR convention (sparse/csr.hpp) as an owning container.
    """

    def __init__(self, indptr, indices, data, shape: Tuple[int, int]):
        self.indptr = _as_idx(indptr)
        self.indices = _as_idx(indices)
        self.data = jnp.asarray(data)
        self.shape = (int(shape[0]), int(shape[1]))

    def tree_flatten(self):
        return (self.indptr, self.indices, self.data), (self.shape,)

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(*leaves, aux[0])

    @property
    def capacity(self) -> int:
        return int(self.indices.shape[0])

    @property
    def nnz(self):
        return self.indptr[-1]

    @property
    def n_rows(self) -> int:
        return self.shape[0]

    @property
    def n_cols(self) -> int:
        return self.shape[1]

    def row_ids(self) -> jnp.ndarray:
        """Per-entry row id (padding entries get n_rows).

        The segment-id vector that replaces the reference's per-row kernel
        launches (e.g. sparse/op/row_op.hpp:37) — TPU primitives express
        per-row work as segment reductions over this vector.
        """
        pos = jnp.arange(self.capacity, dtype=jnp.int32)
        r = jnp.searchsorted(self.indptr, pos, side="right").astype(jnp.int32) - 1
        return jnp.where(pos < self.indptr[-1], r, self.shape[0])

    @classmethod
    def from_dense(cls, dense, capacity: int | None = None) -> "CSR":
        d = np.asarray(dense)
        r, c = np.nonzero(d)
        v = d[r, c]
        nnz = len(r)
        cap = capacity if capacity is not None else max(nnz, 1)
        assert cap >= nnz, "capacity too small"
        indptr = np.zeros(d.shape[0] + 1, dtype=np.int32)
        np.add.at(indptr[1:], r, 1)
        indptr = np.cumsum(indptr).astype(np.int32)
        indices = np.zeros(cap, dtype=np.int32)
        data = np.zeros(cap, dtype=d.dtype)
        indices[:nnz], data[:nnz] = c, v
        return cls(indptr, indices, data, d.shape)

    def to_dense(self) -> jnp.ndarray:
        rows = self.row_ids()
        mask = rows < self.shape[0]
        r = jnp.where(mask, rows, 0)
        c = jnp.where(mask, self.indices, 0)
        v = jnp.where(mask, self.data, 0)
        out = jnp.zeros(self.shape, dtype=self.data.dtype)
        return out.at[r, c].add(v, mode="drop")

    def __repr__(self):
        return f"CSR(shape={self.shape}, capacity={self.capacity})"
