"""Format conversions: COO <-> CSR <-> dense.

Reference: sparse/convert/csr.hpp:27 (``coo_to_csr``), :55-95
(``sorted_coo_to_csr``), sparse/convert/coo.hpp:34 (``csr_to_coo``),
sparse/convert/dense.hpp:44 (``csr_to_dense`` via cuSPARSE).

TPU design: conversions are pure index arithmetic — ``searchsorted`` over
sorted row ids replaces the reference's atomic histogram + exclusive scan,
and stays fully inside XLA (no scatter with conflicts).
"""

from __future__ import annotations

import jax.numpy as jnp

from raft_tpu.sparse.formats import COO, CSR


def sorted_rows_to_indptr(rows: jnp.ndarray, n_rows: int) -> jnp.ndarray:
    """indptr from row-sorted COO row ids (padding rows == n_rows sort last).

    Reference: sorted_coo_to_csr (sparse/convert/csr.hpp:55) — there an
    atomic-count + cumsum; here one vectorized binary search.
    """
    targets = jnp.arange(n_rows + 1, dtype=jnp.int32)
    return jnp.searchsorted(rows, targets, side="left").astype(jnp.int32)


def coo_to_csr(coo: COO, assume_sorted: bool = False) -> CSR:
    """Convert COO to CSR (reference sparse/convert/csr.hpp:27).

    Sorts by (row, col) unless ``assume_sorted``; padding stays at the tail.
    """
    rows, cols, vals = coo.rows, coo.cols, coo.vals
    if not assume_sorted:
        order = jnp.lexsort((cols, rows))
        rows, cols, vals = rows[order], cols[order], vals[order]
    indptr = sorted_rows_to_indptr(rows, coo.n_rows)
    return CSR(indptr, cols, vals, coo.shape)


def csr_to_coo(csr: CSR) -> COO:
    """Expand indptr to per-entry row ids (reference sparse/convert/coo.hpp:34)."""
    rows = csr.row_ids()
    return COO(rows, csr.indices, csr.data, csr.shape, nnz=csr.indptr[-1])


def csr_to_dense(csr: CSR) -> jnp.ndarray:
    """Densify (reference sparse/convert/dense.hpp:44; duplicates sum)."""
    return csr.to_dense()


def dense_to_csr(dense, capacity: int | None = None) -> CSR:
    """Eager dense→CSR (host-side helper, inverse of csr_to_dense)."""
    return CSR.from_dense(dense, capacity)
