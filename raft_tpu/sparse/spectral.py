"""Spectral embedding of a sparse graph.

Reference: sparse/linalg/spectral.hpp:25 ``fit_embedding`` →
detail/spectral.cuh:33-80: COO → CSR → Laplacian → (n_components+1)
smallest eigenvectors via Lanczos (no-op cluster solver) → drop the
trivial constant eigenvector → embedding.
"""

from __future__ import annotations

import jax.numpy as jnp

from raft_tpu.sparse import convert
from raft_tpu.sparse.formats import COO


def fit_embedding(coo: COO, n_components: int,
                  seed: int = 1234567, maxiter: int = 4000,
                  tol: float = 0.01) -> jnp.ndarray:
    """(n, n_components) spectral embedding of a symmetric COO graph.

    Solver configuration mirrors the reference's cuGraph-derived defaults
    (detail/spectral.cuh:68-74: maxiter=4000, tol=0.01,
    restart_iter=15+neigvs).
    """
    # deferred: raft_tpu.spectral imports raft_tpu.sparse at package-init
    # time, so importing it at module scope here would be circular
    from raft_tpu.spectral.eigen_solvers import (
        EigenSolverConfig, LanczosSolver)
    from raft_tpu.spectral.matrix_wrappers import LaplacianMatrix
    from raft_tpu.spectral.spectral_util import transform_eigen_matrix

    n = coo.n_rows
    neigvs = n_components + 1
    csr = convert.coo_to_csr(coo)
    L = LaplacianMatrix(csr)
    solver = LanczosSolver(EigenSolverConfig(
        n_eig_vecs=neigvs, max_iter=maxiter,
        restart_iter=15 + neigvs, tol=tol, seed=seed))
    _, vecs, _ = solver.solve_smallest_eigenvectors(L, n)
    emb = transform_eigen_matrix(vecs)
    return emb[:, 1:]
