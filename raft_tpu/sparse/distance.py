"""Sparse pairwise distances over CSR, all reference metrics.

Reference: sparse/distance/distance.hpp:77 (``pairwiseDistance`` runtime
switch :83-137) with the load-balanced COO SpMV engine
(detail/coo_spmv.cuh:49,106) and per-family impls
(detail/{ip,l2,lp,bin}_distance.cuh).

TPU design: the reference's hash-table / dense-smem SpMV strategies exist
because GPUs must keep sparse rows in shared memory.  The MXU wants dense
tiles, so we **densify row blocks** (scatter a CSR row tile into a
(block, k) dense buffer — SURVEY.md §7.6's "blocked dense-ification") and
run the dense metric kernels on the blocks.  The expanded metric families
(IP/L2/cosine/Jaccard/Dice) then ride the systolic array; unexpanded
families reuse the dense tiled kernel.  Sparse-only binary metrics
(Jaccard/Dice, distance_type.h:44,63) are computed from binarized inner
products here and exported for dense parity as well.

**Column scaling** (the regime the reference's load-balanced SpMV +
cuCollections hash strategy exists for — wide, very sparse CSR,
detail/coo_spmv.cuh:49,106, coo_spmv_strategies/hash_strategy.cuh): a
``(block, n_cols)`` densification cannot scale in n_cols (1M columns =
4 GB per f32 block).  ``batch_size_k`` enables the **column-tiled
engine**: every metric is decomposed into per-row statistics computed
directly from the CSR entry list (segment sums — O(nnz), never
densified) plus a cross term accumulated across (row, row, col) dense
tiles — a matmul accumulator for the expanded/dot family, an
elementwise combine-reduce accumulator (+ or max) for the unexpanded
family — then finalized per row-block pair.  Peak memory is
O(bm·bk + bn·bk + bm·bn), independent of n_cols.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects, fail
from raft_tpu.core.profiler import profiled, profiled_jit
from raft_tpu.distance.distance_type import DistanceType
from raft_tpu.distance.pairwise import (
    _c_canberra,
    _c_hamming,
    _c_jensen_shannon,
    _c_l1,
    _c_l2,
    _c_minkowski,
    pairwise_distance as dense_pairwise,
)
from raft_tpu.ops.pairwise_tile import pairwise_tile
from raft_tpu.sparse.formats import CSR

D = DistanceType


def densify_rows(csr: CSR, row_start: int, block: int) -> jnp.ndarray:
    """Scatter CSR rows [row_start, row_start+block) into a dense block.

    One masked scatter-add over the whole entry list — no per-row kernels,
    static shapes, jit-safe for traced ``row_start``.  Full-width special
    case of :func:`densify_block`.
    """
    return densify_block(csr, row_start, block, 0, csr.n_cols)


def densify_block(csr: CSR, row_start, bm: int, col_start, bk: int
                  ) -> jnp.ndarray:
    """Dense (bm, bk) tile of rows [row_start, +bm) x cols
    [col_start, +bk) — the 2-D-tiled sibling of :func:`densify_rows`.
    Padding entries (row sentinel == n_rows) are masked explicitly."""
    rows = csr.row_ids()
    in_tile = ((rows >= row_start) & (rows < row_start + bm)
               & (rows < csr.n_rows)
               & (csr.indices >= col_start) & (csr.indices < col_start + bk))
    r = jnp.where(in_tile, rows - row_start, 0)
    c = jnp.where(in_tile, csr.indices - col_start, 0)
    v = jnp.where(in_tile, csr.data, 0)
    out = jnp.zeros((bm, bk), dtype=csr.data.dtype)
    return out.at[r, c].add(v, mode="drop")


def _entry_row_sum(csr: CSR, vals: jnp.ndarray) -> jnp.ndarray:
    """(n_rows,) segment sum of per-entry ``vals`` — row statistics
    straight from the CSR entry list, O(nnz), no densification."""
    rows = csr.row_ids()
    # ascending row_ids (padding tail = n_rows, discarded by the final
    # slice — no mask needed) lets XLA lower a sorted segmented
    # reduction instead of random scatter-adds
    return jax.ops.segment_sum(vals.astype(jnp.float32), rows,
                               num_segments=csr.n_rows + 1,
                               indices_are_sorted=True)[:-1]


def _guarded_div(num, den):
    return jnp.where(den == 0, 0.0, num / jnp.where(den == 0, 1.0, den))


def _coltiled_spec(metric: DistanceType, metric_arg: float, k_total: int):
    """Column-tiled decomposition of one metric (module docstring).

    Returns ``(kind, ta, tb, stats_a, stats_b, extra, finalize)``:
    ``kind`` is "mm" (matmul cross term) or a reduce kind ("add"/"max")
    for the elementwise family; ``ta``/``tb`` transform dense tiles
    before the matmul; ``stats_a``/``stats_b`` map stat name -> entry
    function (row sums via :func:`_entry_row_sum`); ``extra`` is the
    elementwise combine; ``finalize(acc, sa, sb)`` produces the final
    block from the accumulated cross term and broadcast-ready row stats
    (sa: (bm, 1) each, sb: (1, bn) each).
    """
    ident = lambda t: t            # noqa: E731 — tile transforms
    binz = lambda t: (t != 0).astype(jnp.float32)  # noqa: E731
    sq = {"sq": lambda v: v * v}
    none = {}

    if metric in (D.L2Expanded, D.L2SqrtExpanded):
        def fin(ip, sa, sb):
            d = jnp.maximum(sa["sq"] + sb["sq"] - 2.0 * ip, 0.0)
            return jnp.sqrt(d) if metric == D.L2SqrtExpanded else d
        return "mm", ident, ident, sq, sq, None, fin
    if metric == D.InnerProduct:
        return "mm", ident, ident, none, none, None, lambda ip, sa, sb: ip
    if metric == D.CosineExpanded:
        def fin(ip, sa, sb):
            den = jnp.sqrt(sa["sq"]) * jnp.sqrt(sb["sq"])
            return 1.0 - _guarded_div(ip, den)
        return "mm", ident, ident, sq, sq, None, fin
    if metric == D.CorrelationExpanded:
        st = {"sum": lambda v: v, "sq": lambda v: v * v}
        def fin(ip, sa, sb):
            k = float(k_total)
            numer = k * ip - sa["sum"] * sb["sum"]
            qa = k * sa["sq"] - sa["sum"] * sa["sum"]
            qb = k * sb["sq"] - sb["sum"] * sb["sum"]
            return 1.0 - numer / jnp.sqrt(qa * qb)
        return "mm", ident, ident, st, st, None, fin
    if metric == D.HellingerExpanded:
        tr = lambda t: jnp.sqrt(jnp.abs(t))  # noqa: E731
        def fin(ip, sa, sb):
            return jnp.sqrt(jnp.maximum(1.0 - ip, 0.0))
        return "mm", tr, tr, none, none, None, fin
    if metric == D.RusselRaoExpanded:
        def fin(ip, sa, sb):
            return (k_total - ip) / k_total
        return "mm", ident, ident, none, none, None, fin
    if metric == D.KLDivergence:
        # 0.5 * (Σ x log x − x @ masked_log(y)ᵀ): the first term is a
        # row stat over entries (0 log 0 = 0), the second a matmul with
        # the y tile log-masked (kl_divergence.cuh:95-99)
        st_a = {"xlogx": lambda v: jnp.where(
            v > 0, v * jnp.log(jnp.where(v > 0, v, 1.0)), 0.0)}
        tb = lambda t: jnp.where(  # noqa: E731
            t > 0, jnp.log(jnp.where(t > 0, t, 1.0)), 0.0)
        def fin(ip, sa, sb):
            return 0.5 * (sa["xlogx"] - ip)
        return "mm", ident, tb, st_a, none, None, fin
    if metric in (D.JaccardExpanded, D.DiceExpanded):
        st = {"nnz": lambda v: (v != 0).astype(jnp.float32)}
        def fin(ip, sa, sb):
            if metric == D.JaccardExpanded:
                return 1.0 - _guarded_div(ip, sa["nnz"] + sb["nnz"] - ip)
            return 1.0 - _guarded_div(2.0 * ip, sa["nnz"] + sb["nnz"])
        return "mm", binz, binz, st, st, None, fin

    # elementwise combine family: accumulate Σ_k (or max_k) over column
    # tiles, finalize once per block pair
    if metric == D.L1:
        return "add", None, None, none, none, _c_l1, lambda a, sa, sb: a
    if metric == D.L2Unexpanded:
        return "add", None, None, none, none, _c_l2, lambda a, sa, sb: a
    if metric == D.L2SqrtUnexpanded:
        return ("add", None, None, none, none, _c_l2,
                lambda a, sa, sb: jnp.sqrt(a))
    if metric == D.Linf:
        return "max", None, None, none, none, _c_l1, lambda a, sa, sb: a
    if metric == D.Canberra:
        return ("add", None, None, none, none, _c_canberra,
                lambda a, sa, sb: a)
    if metric == D.LpUnexpanded:
        p = float(metric_arg)
        return ("add", None, None, none, none, _c_minkowski(p),
                lambda a, sa, sb: a ** (1.0 / p))
    if metric == D.HammingUnexpanded:
        return ("add", None, None, none, none, _c_hamming,
                lambda a, sa, sb: a / k_total)
    if metric == D.JensenShannon:
        return ("add", None, None, none, none, _c_jensen_shannon,
                lambda a, sa, sb: jnp.sqrt(jnp.maximum(0.5 * a, 0.0)))
    if metric == D.BrayCurtis:
        st = {"sum": lambda v: v}
        def fin(acc, sa, sb):
            return _guarded_div(acc, sa["sum"] + sb["sum"])
        return "add", None, None, st, st, _c_l1, fin
    fail("sparse pairwise_distance: metric %d has no column-tiled "
         "decomposition", int(metric))


def _coltiled_pairwise(a: CSR, b: CSR, metric: DistanceType,
                       metric_arg: float, bm: int, bn: int, bk: int
                       ) -> jnp.ndarray:
    """Column-tiled engine (module docstring): peak temporary memory is
    O(bm·bk + bn·bk + bm·n) however wide the input — the bm·n term is
    the per-a-tile cross-term stripe, never larger than the (m, n)
    output this function materializes anyway."""
    m, n, k_total = a.n_rows, b.n_rows, a.n_cols
    kind, ta, tb, stats_a, stats_b, combine, finalize = _coltiled_spec(
        metric, metric_arg, k_total)
    nta, ntb, ntk = -(-m // bm), -(-n // bn), -(-k_total // bk)

    def stat_tiles(csr, spec, n_tiles, width):
        out = {}
        for name, fn in spec.items():
            s = _entry_row_sum(csr, fn(csr.data))
            out[name] = jnp.pad(s, (0, n_tiles * width - s.shape[0]))
        return out

    sa_full = stat_tiles(a, stats_a, nta, bm)
    sb_full = stat_tiles(b, stats_b, ntb, bn)

    out = jnp.zeros((nta * bm, ntb * bn), dtype=jnp.float32)

    def a_step(ia, out):
        sa = {k: jax.lax.dynamic_slice(v, (ia * bm,), (bm,))[:, None]
              for k, v in sa_full.items()}

        # stripe accumulator (bm, ntb*bn): the (ia, ic) A tile is
        # densified ONCE and used against every b tile (the b tiles are
        # rebuilt per ia, but an O(nnz) masked scatter is noise next to
        # the bm x bk x bn tile contraction it feeds)
        def k_step(ic, acc):
            xa = densify_block(a, ia * bm, bm, ic * bk, bk)
            txa = ta(xa) if kind == "mm" else xa

            def b_step(ib, acc):
                xb = densify_block(b, ib * bn, bn, ic * bk, bk)
                if kind == "mm":
                    part = jnp.matmul(txa, tb(xb).T, precision="highest")
                else:
                    part = pairwise_tile(xa, xb, combine, reduce_kind=kind,
                                         epilog=None, init=0.0)
                cur = jax.lax.dynamic_slice(acc, (0, ib * bn), (bm, bn))
                upd = cur + part if kind != "max" else jnp.maximum(cur, part)
                return jax.lax.dynamic_update_slice(acc, upd, (0, ib * bn))

            return jax.lax.fori_loop(0, ntb, b_step, acc)

        acc = jax.lax.fori_loop(
            0, ntk, k_step, jnp.zeros((bm, ntb * bn), jnp.float32))

        def fin_step(ib, out):
            sb = {k: jax.lax.dynamic_slice(v, (ib * bn,), (bn,))[None, :]
                  for k, v in sb_full.items()}
            cross = jax.lax.dynamic_slice(acc, (0, ib * bn), (bm, bn))
            blk = finalize(cross, sa, sb)
            return jax.lax.dynamic_update_slice(
                out, blk.astype(jnp.float32), (ia * bm, ib * bn))

        return jax.lax.fori_loop(0, ntb, fin_step, out)

    out = jax.lax.fori_loop(0, nta, a_step, out)
    return out[:m, :n]


def _binary_expanded(xa: jnp.ndarray, xb: jnp.ndarray, metric: DistanceType):
    """Jaccard / Dice from binarized inner products (reference
    sparse/distance/detail/bin_distance.cuh)."""
    ba = (xa != 0).astype(jnp.float32)
    bb = (xb != 0).astype(jnp.float32)
    ip = ba @ bb.T
    na = jnp.sum(ba, axis=1)[:, None]
    nb = jnp.sum(bb, axis=1)[None, :]
    if metric == D.JaccardExpanded:
        union = na + nb - ip
        sim = jnp.where(union > 0, ip / jnp.where(union == 0, 1, union), 0.0)
    else:  # Dice
        den = na + nb
        sim = jnp.where(den > 0, 2 * ip / jnp.where(den == 0, 1, den), 0.0)
    return 1.0 - sim


def block_pairwise(xa: jnp.ndarray, xb: jnp.ndarray,
                   metric: DistanceType, metric_arg: float = 2.0):
    """Dense-block metric dispatch shared by the batched driver."""
    if metric in (D.JaccardExpanded, D.DiceExpanded):
        return _binary_expanded(xa, xb, metric)
    return dense_pairwise(xa, xb, metric, metric_arg)


@profiled("sparse", "pairwise_distance")
@profiled_jit(name="sparse_pairwise_distance",
              static_argnames=("metric", "metric_arg", "batch_size_a",
                               "batch_size_b", "batch_size_k"))
def pairwise_distance(a: CSR, b: CSR,
                      metric: DistanceType = D.L2Expanded,
                      metric_arg: float = 2.0,
                      batch_size_a: int = 1024,
                      batch_size_b: int = 1024,
                      batch_size_k: Optional[int] = None) -> jnp.ndarray:
    """All-pairs distances between CSR row sets a (m, k) and b (n, k).

    Runtime-switch analog of reference sparse/distance/distance.hpp:83-137;
    ``batch_size_*`` play the role of the reference's
    ``distances_config_t`` batching knobs (sparse/distance/common.h:26).
    ``batch_size_k`` enables the column-tiled engine (module docstring)
    for inputs too wide to densify a full row block — the regime of the
    reference's load-balanced SpMV (detail/coo_spmv.cuh:49,106); when
    None (default) a heuristic picks it so a densified row block stays
    under ~256 MB.
    """
    expects(a.n_cols == b.n_cols,
            "sparse pairwise_distance: dimensionality mismatch (%d vs %d)",
            a.n_cols, b.n_cols)
    m, n = a.n_rows, b.n_rows
    bm = min(batch_size_a, m)
    bn = min(batch_size_b, n)
    budget = 256 * 2**20
    # the full-width driver densifies ONE a-block at a time but ALL of b
    # up front (b_tiles below), so the footprint that must fit the budget
    # is max(a-block, entire padded b) — gating on a single block would
    # let a tall-and-wide b (e.g. 1M rows x 60k cols) through to a
    # hundreds-of-GB b_tiles allocation
    n_pad_b = -(-n // bn) * bn
    full_width_bytes = max(bm, n_pad_b) * a.n_cols * 4
    use_coltiled = batch_size_k is not None and batch_size_k < a.n_cols
    if batch_size_k is None and full_width_bytes > budget:
        # derive the col tile from the row blocks so a densified
        # (block, bk) tile actually fits the documented ~256 MB budget.
        # The engine also engages when b is tall but *narrow* (bk ==
        # n_cols, a single col tile): its per-(bn, bk)-tile densify of b
        # is what bounds memory, where this path's all-of-b b_tiles
        # would not.
        batch_size_k = max(512, budget // (max(bm, bn) * 4) // 128 * 128)
        use_coltiled = True
    if use_coltiled:
        return _coltiled_pairwise(a, b, metric, metric_arg, bm, bn,
                                  min(batch_size_k, a.n_cols))
    n_tiles_a = -(-m // bm)
    n_tiles_b = -(-n // bn)

    out = jnp.zeros((n_tiles_a * bm, n_tiles_b * bn), dtype=jnp.float32)
    # densify each b-tile once, not once per a-tile; lax.map keeps the HLO
    # a single block program instead of n_tiles_b inlined scatters
    b_tiles = jax.lax.map(lambda ib: densify_rows(b, ib * bn, bn),
                          jnp.arange(n_tiles_b))

    # The reference engine is one load-balanced kernel over all blocks
    # (detail/coo_spmv.cuh:49); the analog here is a single doubly-nested
    # fori_loop whose body is ONE densify + ONE dense-metric block, so HLO
    # size is O(1) in tile count (a Python loop would inline
    # n_tiles_a * n_tiles_b block programs and explode compile time).
    def a_tile_step(ia, out):
        xa = densify_rows(a, ia * bm, bm)

        def b_tile_step(ib, out):
            xb = jax.lax.dynamic_index_in_dim(b_tiles, ib, 0, keepdims=False)
            blk = block_pairwise(xa, xb, metric, metric_arg)
            return jax.lax.dynamic_update_slice(
                out, blk.astype(jnp.float32), (ia * bm, ib * bn))

        return jax.lax.fori_loop(0, n_tiles_b, b_tile_step, out)

    out = jax.lax.fori_loop(0, n_tiles_a, a_tile_step, out)
    return out[:m, :n]
