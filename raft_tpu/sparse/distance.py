"""Sparse pairwise distances over CSR, all reference metrics.

Reference: sparse/distance/distance.hpp:77 (``pairwiseDistance`` runtime
switch :83-137) with the load-balanced COO SpMV engine
(detail/coo_spmv.cuh:49,106) and per-family impls
(detail/{ip,l2,lp,bin}_distance.cuh).

TPU design: the reference's hash-table / dense-smem SpMV strategies exist
because GPUs must keep sparse rows in shared memory.  The MXU wants dense
tiles, so we **densify row blocks** (scatter a CSR row tile into a
(block, k) dense buffer — SURVEY.md §7.6's "blocked dense-ification") and
run the dense metric kernels on the blocks.  The expanded metric families
(IP/L2/cosine/Jaccard/Dice) then ride the systolic array; unexpanded
families reuse the dense tiled kernel.  Sparse-only binary metrics
(Jaccard/Dice, distance_type.h:44,63) are computed from binarized inner
products here and exported for dense parity as well.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.distance.distance_type import DistanceType
from raft_tpu.distance.pairwise import pairwise_distance as dense_pairwise
from raft_tpu.sparse.formats import CSR

D = DistanceType


def densify_rows(csr: CSR, row_start: int, block: int) -> jnp.ndarray:
    """Scatter CSR rows [row_start, row_start+block) into a dense block.

    One masked scatter-add over the whole entry list — no per-row kernels,
    static shapes, jit-safe for traced ``row_start``.
    """
    rows = csr.row_ids()
    in_tile = (rows >= row_start) & (rows < row_start + block)
    r = jnp.where(in_tile, rows - row_start, 0)
    c = jnp.where(in_tile, csr.indices, 0)
    v = jnp.where(in_tile, csr.data, 0)
    out = jnp.zeros((block, csr.n_cols), dtype=csr.data.dtype)
    return out.at[r, c].add(v, mode="drop")


def _binary_expanded(xa: jnp.ndarray, xb: jnp.ndarray, metric: DistanceType):
    """Jaccard / Dice from binarized inner products (reference
    sparse/distance/detail/bin_distance.cuh)."""
    ba = (xa != 0).astype(jnp.float32)
    bb = (xb != 0).astype(jnp.float32)
    ip = ba @ bb.T
    na = jnp.sum(ba, axis=1)[:, None]
    nb = jnp.sum(bb, axis=1)[None, :]
    if metric == D.JaccardExpanded:
        union = na + nb - ip
        sim = jnp.where(union > 0, ip / jnp.where(union == 0, 1, union), 0.0)
    else:  # Dice
        den = na + nb
        sim = jnp.where(den > 0, 2 * ip / jnp.where(den == 0, 1, den), 0.0)
    return 1.0 - sim


def block_pairwise(xa: jnp.ndarray, xb: jnp.ndarray,
                   metric: DistanceType, metric_arg: float = 2.0):
    """Dense-block metric dispatch shared by the batched driver."""
    if metric in (D.JaccardExpanded, D.DiceExpanded):
        return _binary_expanded(xa, xb, metric)
    return dense_pairwise(xa, xb, metric, metric_arg)


@functools.partial(jax.jit, static_argnames=("metric", "metric_arg",
                                             "batch_size_a", "batch_size_b"))
def pairwise_distance(a: CSR, b: CSR,
                      metric: DistanceType = D.L2Expanded,
                      metric_arg: float = 2.0,
                      batch_size_a: int = 1024,
                      batch_size_b: int = 1024) -> jnp.ndarray:
    """All-pairs distances between CSR row sets a (m, k) and b (n, k).

    Runtime-switch analog of reference sparse/distance/distance.hpp:83-137;
    ``batch_size_*`` play the role of the reference's
    ``distances_config_t`` batching knobs (sparse/distance/common.h:26).
    """
    expects(a.n_cols == b.n_cols,
            "sparse pairwise_distance: dimensionality mismatch (%d vs %d)",
            a.n_cols, b.n_cols)
    m, n = a.n_rows, b.n_rows
    bm = min(batch_size_a, m)
    bn = min(batch_size_b, n)
    n_tiles_a = -(-m // bm)
    n_tiles_b = -(-n // bn)

    out = jnp.zeros((n_tiles_a * bm, n_tiles_b * bn), dtype=jnp.float32)
    # densify each b-tile once, not once per a-tile; lax.map keeps the HLO
    # a single block program instead of n_tiles_b inlined scatters
    b_tiles = jax.lax.map(lambda ib: densify_rows(b, ib * bn, bn),
                          jnp.arange(n_tiles_b))

    # The reference engine is one load-balanced kernel over all blocks
    # (detail/coo_spmv.cuh:49); the analog here is a single doubly-nested
    # fori_loop whose body is ONE densify + ONE dense-metric block, so HLO
    # size is O(1) in tile count (a Python loop would inline
    # n_tiles_a * n_tiles_b block programs and explode compile time).
    def a_tile_step(ia, out):
        xa = densify_rows(a, ia * bm, bm)

        def b_tile_step(ib, out):
            xb = jax.lax.dynamic_index_in_dim(b_tiles, ib, 0, keepdims=False)
            blk = block_pairwise(xa, xb, metric, metric_arg)
            return jax.lax.dynamic_update_slice(
                out, blk.astype(jnp.float32), (ia * bm, ib * bn))

        return jax.lax.fori_loop(0, n_tiles_b, b_tile_step, out)

    out = jax.lax.fori_loop(0, n_tiles_a, a_tile_step, out)
    return out[:m, :n]
