"""Graph connection fix-up: cross-component nearest neighbors.

Reference: ``connect_components`` (sparse/selection/detail/
connect_components.cuh:89,215,230) — runs fusedL2NN with the color-aware
``FixConnectivitiesRedOp`` so every point finds its nearest neighbor in a
*different* component, then reduces per component to the single best
cross-edge pair and emits symmetric COO edges that stitch a disconnected
kNN graph into one component.

TPU design: the color test folds into the fused tiled 1-NN scan as an
on-the-fly mask (computed per tile from the colors vector — no m×m mask
materialized); the per-component argmin is the same three-pass segment-min
used by the MST solver.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core.profiler import profiled
from raft_tpu.distance.fused_l2_nn import fused_l2_nn_min_reduce
from raft_tpu.sparse.formats import COO


def cross_color_nn(X: jnp.ndarray, colors: jnp.ndarray,
                   sqrt: bool = True, tile_n: int = 4096
                   ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """For each point, its nearest neighbor with a different color.

    The fusedL2NN + FixConnectivitiesRedOp composition
    (connect_components.cuh:230); returns (dists (m,), idx (m,) int32).
    The color test rides the shared fused scan's per-tile mask hook, so no
    m×m mask is materialized.
    """
    c = colors.astype(jnp.int32)
    m = X.shape[0]
    tile = min(tile_n, m)
    n_tiles = -(-m // tile)
    cp = jnp.pad(c, (0, n_tiles * tile - m), constant_values=-1)

    def color_mask(j0, tn):
        ct = jax.lax.dynamic_slice_in_dim(cp, j0, tn, axis=0)
        return (c[:, None] != ct[None, :]) & (ct[None, :] >= 0)

    return fused_l2_nn_min_reduce(X, X, sqrt=sqrt, tile_n=tile,
                                  tile_mask_fn=color_mask)


@profiled("sparse")
def connect_components(X: jnp.ndarray, colors: jnp.ndarray,
                       sqrt: bool = True) -> COO:
    """Emit symmetric edges joining each component to its nearest other
    component (reference connect_components, connect_components.cuh:215).

    Output COO capacity is 2V (≤ one undirected edge per component, both
    directions); padding rows carry the sentinel.
    """
    m = X.shape[0]
    d, j = cross_color_nn(X, colors, sqrt=sqrt)
    c = colors.astype(jnp.int32)

    # per-component best (d, point index) — three-pass segment-min
    INT_MAX = jnp.iinfo(jnp.int32).max
    mind = jax.ops.segment_min(d, c, num_segments=m)
    is_min = (d == mind[c]) & jnp.isfinite(d)
    pm = jnp.where(is_min, jnp.arange(m, dtype=jnp.int32), INT_MAX)
    minp = jax.ops.segment_min(pm, c, num_segments=m)
    chosen = minp < INT_MAX  # per color id
    sel = jnp.where(chosen, minp, 0)

    src = sel.astype(jnp.int32)
    dst = j[sel]
    wv = d[sel]
    rows = jnp.concatenate([jnp.where(chosen, src, m),
                            jnp.where(chosen, dst, m)])
    cols = jnp.concatenate([jnp.where(chosen, dst, 0),
                            jnp.where(chosen, src, 0)])
    vals = jnp.concatenate([jnp.where(chosen, wv, 0),
                            jnp.where(chosen, wv, 0)])
    return COO(rows, cols, vals, (m, m),
               nnz=2 * jnp.sum(chosen.astype(jnp.int32)))
