"""Minimum spanning tree / forest: Borůvka via segment-min (no atomics).

Reference: ``MST_solver`` (sparse/mst/mst_solver.cuh:42) with the
``solve()`` loop (sparse/mst/detail/mst_solver_inl.cuh:111-219): weight
``alteration`` for uniqueness (:127,258), ``min_edge_per_vertex`` (:148),
``min_edge_per_supervertex`` (:156), cycle-break, ``label_prop``
supervertex merge (:199); result ``Graph_COO`` (mst_solver.cuh:27).

TPU design (SURVEY.md §7.7): the reference's atomicMin races are replaced
by deterministic three-pass segment-mins (weight → canonical edge id →
entry index), which also replaces the float ``alteration`` hack — the
lexicographic (weight, edge-id) key *is* unique, so the MST is unique and
per-component choices can never close a cycle longer than 2.  2-cycles
(two components picking the same undirected edge) resolve by keeping the
smaller color as root.  Colors merge by pointer-jumping inside the same
``lax.while_loop`` — the whole solve is one XLA program with static
shapes; edges are *marked* in an ``in_mst`` bitmap over the input entry
list, and extracted/deduplicated at the end.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from raft_tpu.core.profiler import profiled, profiled_jit
from raft_tpu.sparse.formats import CSR


class GraphCOO(NamedTuple):
    """MST edge list (reference Graph_COO, mst_solver.cuh:27).

    Fixed capacity; the first ``n_edges`` entries are valid (already
    compacted), the rest carry src == -1.
    """

    src: jnp.ndarray
    dst: jnp.ndarray
    weights: jnp.ndarray
    n_edges: jnp.ndarray


def _pointer_jump(parent: jnp.ndarray) -> jnp.ndarray:
    """Compress parent pointers to roots (label_prop analog).

    Bounded to ⌈log2(V)⌉+2 doublings: enough for any forest (valid —
    i.e. symmetric — input yields a forest after 2-cycle breaking), and a
    hard stop rather than a device hang if a caller feeds an asymmetric
    adjacency whose choice pointers contain a longer cycle; unresolved
    pointers are then cut to self, so the solve degrades to a forest
    instead of spinning.
    """
    V = parent.shape[0]
    jumps = max(int(V - 1).bit_length(), 1) + 2

    def body(_, p):
        return p[p]

    p = jax.lax.fori_loop(0, jumps, body, parent)
    return jnp.where(p[p] == p, p, jnp.arange(V, dtype=parent.dtype))


@profiled("sparse")
def mst(csr: CSR,
        colors: Optional[jnp.ndarray] = None,
        max_iterations: int = 0):
    """Borůvka MST/MSF over a symmetric weighted CSR adjacency.

    Parameters
    ----------
    csr:
        Symmetric graph (both edge directions present), weights = data.
    colors:
        Optional initial component labels (restart path, reference
        ``initialize_colors_`` = false in detail/mst.cuh:95-104); defaults
        to ``arange(V)``.
    max_iterations:
        Safety cap on Borůvka rounds; 0 picks 2·⌈log2(V)⌉+4 — more than
        any valid (symmetric) input needs, and a guaranteed stop on
        malformed (asymmetric) input, which the reference would require
        the caller to have symmetrized anyway (mst.cuh docs).

    Returns
    -------
    (GraphCOO, colors): marked + compacted edge list (capacity = V-1,
    undirected — one entry per tree edge) and final component labels
    (connected components of the input graph).
    """
    V = csr.n_rows
    if colors is None:
        colors0 = jnp.arange(V, dtype=jnp.int32)
    else:
        colors0 = jnp.asarray(colors, dtype=jnp.int32)
    cap = max_iterations if max_iterations else \
        2 * max(int(V - 1).bit_length(), 1) + 4
    return _mst_run(csr, colors0, cap=cap)


@profiled_jit(name="mst", static_argnames=("cap",))
def _mst_run(csr: CSR, colors0: jnp.ndarray, cap: int):
    """The whole Borůvka solve as one cached executable (the linkage
    pipeline calls mst repeatedly at a fixed shape; an eager while_loop
    retraced its closures every call — r5 retrace audit)."""
    V = csr.n_rows
    E = csr.capacity
    rows = csr.row_ids()
    cols = csr.indices
    w = csr.data
    valid = rows < V
    safe_rows = jnp.where(valid, rows, 0)
    safe_cols = jnp.where(valid, cols, 0)

    with jax.enable_x64(True):
        minuv = jnp.minimum(safe_rows, safe_cols).astype(jnp.int64)
        maxuv = jnp.maximum(safe_rows, safe_cols).astype(jnp.int64)
        eid = minuv * V + maxuv  # canonical undirected edge id
        EID_MAX = jnp.iinfo(jnp.int64).max
        eid = jnp.where(valid, eid, EID_MAX)

    INT_MAX = jnp.iinfo(jnp.int32).max
    vidx = jnp.arange(V, dtype=jnp.int32)
    eidx = jnp.arange(E, dtype=jnp.int32)

    def round_(state):
        color, in_mst, it, _ = state
        csrc = color[safe_rows]
        cross = valid & (csrc != color[safe_cols])

        # pass 1: per-component min weight over outgoing cross edges
        wm = jnp.where(cross, w, jnp.inf)
        minw = jax.ops.segment_min(wm, csrc, num_segments=V)
        is_minw = cross & (w == minw[csrc])

        # pass 2: tie-break by canonical edge id (gives weight uniqueness —
        # the role of the reference's alteration())
        with jax.enable_x64(True):
            em = jnp.where(is_minw, eid, EID_MAX)
            mine = jax.ops.segment_min(em, csrc, num_segments=V)
            is_mine = is_minw & (eid == mine[csrc])

        # pass 3: tie-break duplicate entries by entry index
        im = jnp.where(is_mine, eidx, INT_MAX)
        mini = jax.ops.segment_min(im, csrc, num_segments=V)
        chosen = mini < INT_MAX  # per color: has an outgoing edge
        sel = jnp.where(chosen, mini, 0)

        in_mst = in_mst.at[sel].max(chosen)

        # merge components: each choosing color points at its target color
        target = color[safe_cols[sel]]
        parent = jnp.where(chosen, target, vidx)
        # break 2-cycles: keep the smaller color as root
        two_cycle = parent[parent] == vidx
        parent = jnp.where(two_cycle, jnp.minimum(vidx, parent), parent)
        parent = _pointer_jump(parent)
        color = parent[color]
        return color, in_mst, it + 1, jnp.any(cross)

    def cond(state):
        _, _, it, progressed = state
        return progressed & (it < cap)

    state0 = (colors0, jnp.zeros((E,), bool), jnp.int32(0), jnp.bool_(True))
    color, in_mst, _, _ = jax.lax.while_loop(cond, round_, state0)

    # extract + dedup: among marked entries keep the first per canonical id
    with jax.enable_x64(True):
        key = jnp.where(in_mst & valid, eid, EID_MAX)
        order = jnp.argsort(key)
        k_sorted = key[order]
        first = jnp.concatenate([jnp.array([True]),
                                 k_sorted[1:] != k_sorted[:-1]])
        keep = first & (k_sorted < EID_MAX)
    # compact kept entries to the front, capacity V-1
    pack = jnp.argsort(~keep, stable=True)
    take = order[pack][: max(V - 1, 1)]
    kept = keep[pack][: max(V - 1, 1)]
    src = jnp.where(kept, safe_rows[take], -1).astype(jnp.int32)
    dst = jnp.where(kept, safe_cols[take], -1).astype(jnp.int32)
    ww = jnp.where(kept, w[take], 0)
    n_edges = jnp.sum(kept.astype(jnp.int32))
    return GraphCOO(src, dst, ww, n_edges), color


def mst_weight(g: GraphCOO) -> jnp.ndarray:
    return jnp.sum(jnp.where(g.src >= 0, g.weights, 0))
