"""COO/CSR element operations: sort, dedup, filter, row ops, slicing.

Reference: sparse/op/sort.hpp (``coo_sort``, ``coo_sort_by_weight``),
sparse/op/reduce.hpp:47,70 (``compute_duplicates_mask``, ``max_duplicates``),
sparse/op/filter.hpp:44 (``coo_remove_scalar``), sparse/op/row_op.hpp:37
(``csr_row_op``), sparse/op/slice.hpp:38,63 (``csr_row_slice_*``).

TPU design: the reference leans on thrust sort / CUB scans / atomic
compaction.  Here every nnz-changing op is sort-to-tail + count: removed
entries get the sentinel row id, one stable sort moves them to the end, and
the valid count rides along as a traced scalar — capacity never changes, so
everything stays jittable with static shapes.
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.sparse.formats import COO, CSR

from raft_tpu.core.handle import takes_handle


@takes_handle
def coo_sort(coo: COO) -> COO:
    """Sort entries by (row, col); padding sorts last.

    Reference: coo_sort (sparse/op/sort.hpp) — thrust::sort_by_key there,
    one XLA lexsort here.
    """
    order = jnp.lexsort((coo.cols, coo.rows))
    return COO(coo.rows[order], coo.cols[order], coo.vals[order],
               coo.shape, coo.nnz)


@takes_handle
def coo_sort_by_weight(coo: COO) -> COO:
    """Sort entries ascending by value (reference sparse/op/sort.hpp:67).

    Padding entries are pushed to the tail regardless of their value.
    """
    key = jnp.where(coo.valid_mask(), coo.vals, jnp.inf)
    order = jnp.argsort(key, stable=True)
    return COO(coo.rows[order], coo.cols[order], coo.vals[order],
               coo.shape, coo.nnz)


@takes_handle
def compute_duplicates_mask(rows: jnp.ndarray, cols: jnp.ndarray,
                            n_rows: int) -> jnp.ndarray:
    """1 at the first occurrence of each (row, col) in sorted order, else 0.

    Reference: compute_duplicates_mask (sparse/op/reduce.hpp:47).  Input must
    be sorted by (row, col); padding (row == n_rows) is always masked 0.
    """
    prev_r = jnp.concatenate([jnp.array([-1], rows.dtype), rows[:-1]])
    prev_c = jnp.concatenate([jnp.array([-1], cols.dtype), cols[:-1]])
    first = (rows != prev_r) | (cols != prev_c)
    return (first & (rows < n_rows)).astype(jnp.int32)


@takes_handle
def max_duplicates(coo: COO) -> COO:
    """Reduce duplicate coordinates keeping the max value.

    Reference: max_duplicates (sparse/op/reduce.hpp:70) — custom kernel with
    atomicMax; here sort + segment-max into compacted slots.
    """
    s = coo_sort(coo)
    mask = compute_duplicates_mask(s.rows, s.cols, s.n_rows)
    # Slot id for each unique coordinate, in sorted order.
    slot = jnp.cumsum(mask) - 1
    n_unique = slot[-1] + 1
    cap = s.capacity
    sentinel = s.sentinel
    valid = s.valid_mask()
    slot = jnp.where(valid, slot, cap - 1)
    neg_inf = jnp.array(-jnp.inf, dtype=s.vals.dtype) \
        if jnp.issubdtype(s.vals.dtype, jnp.floating) \
        else jnp.iinfo(s.vals.dtype).min
    out_vals = jax.ops.segment_max(
        jnp.where(valid, s.vals, neg_inf), slot, num_segments=cap)
    out_rows = jax.ops.segment_min(
        jnp.where(valid, s.rows, sentinel), slot, num_segments=cap)
    out_cols = jax.ops.segment_min(
        jnp.where(valid, s.cols, 0), slot, num_segments=cap)
    in_range = jnp.arange(cap) < n_unique
    out_rows = jnp.where(in_range, out_rows, sentinel)
    out_vals = jnp.where(in_range, out_vals, 0)
    out_cols = jnp.where(in_range, out_cols, 0)
    return COO(out_rows, out_cols, out_vals, s.shape, nnz=n_unique)


@takes_handle
def sum_duplicates(coo: COO) -> COO:
    """Reduce duplicate coordinates by summing (segment-sum variant of
    max_duplicates; the symmetrize path needs it)."""
    s = coo_sort(coo)
    mask = compute_duplicates_mask(s.rows, s.cols, s.n_rows)
    slot = jnp.cumsum(mask) - 1
    n_unique = slot[-1] + 1
    cap = s.capacity
    valid = s.valid_mask()
    slot = jnp.where(valid, slot, cap - 1)
    out_vals = jax.ops.segment_sum(
        jnp.where(valid, s.vals, 0), slot, num_segments=cap)
    out_rows = jax.ops.segment_min(
        jnp.where(valid, s.rows, s.sentinel), slot, num_segments=cap)
    out_cols = jax.ops.segment_min(
        jnp.where(valid, s.cols, 0), slot, num_segments=cap)
    in_range = jnp.arange(cap) < n_unique
    out_rows = jnp.where(in_range, out_rows, s.sentinel)
    out_vals = jnp.where(in_range, out_vals, 0)
    out_cols = jnp.where(in_range, out_cols, 0)
    return COO(out_rows, out_cols, out_vals, s.shape, nnz=n_unique)


@takes_handle
def coo_remove_scalar(coo: COO, scalar) -> COO:
    """Drop entries whose value equals ``scalar``.

    Reference: coo_remove_scalar (sparse/op/filter.hpp:44) — there a
    count/exclusive-scan/compact kernel chain; here mark-with-sentinel +
    stable sort-to-tail.
    """
    keep = coo.valid_mask() & (coo.vals != scalar)
    rows = jnp.where(keep, coo.rows, coo.sentinel)
    order = jnp.argsort(~keep, stable=True)
    return COO(rows[order], coo.cols[order], coo.vals[order], coo.shape,
               nnz=jnp.sum(keep.astype(jnp.int32)))


@takes_handle
def coo_remove_zeros(coo: COO) -> COO:
    """Reference's coo_remove_zeros convenience wrapper."""
    return coo_remove_scalar(coo, 0)


@takes_handle
def csr_row_op(csr: CSR, fn: Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray]
               ) -> jnp.ndarray:
    """Apply a per-entry function with its row id: fn(row_ids, data).

    Reference: csr_row_op (sparse/op/row_op.hpp:37) launches a lambda per
    row over [start, stop); the TPU formulation hands the segment-id vector
    to a vectorized lambda — combine with ``jax.ops.segment_*`` for per-row
    reductions.
    """
    return fn(csr.row_ids(), csr.data)


@takes_handle
def csr_row_slice(csr: CSR, start: int, stop: int) -> CSR:
    """Slice rows [start, stop) into a new CSR (eager; dynamic output size).

    Reference: csr_row_slice_indptr + csr_row_slice_populate
    (sparse/op/slice.hpp:38,63).
    """
    lo = int(csr.indptr[start])
    hi = int(csr.indptr[stop])
    indptr = csr.indptr[start:stop + 1] - lo
    return CSR(indptr, csr.indices[lo:hi], csr.data[lo:hi],
               (stop - start, csr.n_cols))
