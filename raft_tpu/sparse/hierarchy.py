"""Single-linkage hierarchical agglomerative clustering (HAC).

Reference: ``single_linkage`` (sparse/hierarchy/single_linkage.hpp:48) and
its pipeline (hierarchy/detail/single_linkage.hpp:64-120):

1. ``get_distance_graph`` — kNN-graph (k = log2(m) + c) or full-pairwise
   connectivity (detail/connectivities.cuh);
2. ``build_sorted_mst`` — Borůvka MST, reconnecting a forest with
   ``connect_components`` until one component (detail/mst.cuh:80-160);
3. ``build_dendrogram_host`` — host union-find over weight-sorted edges
   (detail/agglomerative.cuh:101), scipy convention: merged cluster i gets
   id m+i, children[i] = (find(src), find(dst));
4. ``extract_flattened_clusters`` — cut the dendrogram into n_clusters
   monotonic labels (detail/agglomerative.cuh:237).

TPU design: stages 1-2 are device programs (segment-min Borůvka, fused
masked 1-NN); stages 3-4 stay on the host exactly like the reference — the
dendrogram is inherently sequential and tiny (m-1 merges).
"""

from __future__ import annotations

import math
from typing import NamedTuple, Tuple

import jax.numpy as jnp
import numpy as np

from raft_tpu.core.error import expects
from raft_tpu.core.profiler import profiled
from raft_tpu.distance.distance_type import DistanceType
from raft_tpu.sparse import convert
from raft_tpu.sparse.formats import COO, CSR
from raft_tpu.sparse.linkage import connect_components
from raft_tpu.sparse.mst import mst
from raft_tpu.sparse.selection import knn_graph

D = DistanceType


class LinkageResult(NamedTuple):
    """Reference ``linkage_output`` (hierarchy/common.h:22-36)."""

    labels: np.ndarray        # (m,) flattened cluster assignments
    children: np.ndarray      # (m-1, 2) scipy-convention merge tree
    deltas: np.ndarray        # (m-1,) merge distances
    sizes: np.ndarray         # (m-1,) merged cluster sizes
    n_clusters: int
    n_leaves: int


class _UnionFind:
    """Host union-find with scipy-style next-id assignment
    (reference UnionFind, detail/agglomerative.cuh:38-80)."""

    def __init__(self, n: int):
        self.parent = np.full(2 * n - 1, -1, dtype=np.int64)
        self.size = np.ones(2 * n - 1, dtype=np.int64)
        self.size[n:] = 0
        self.next_id = n

    def find(self, x: int) -> int:
        root = x
        while self.parent[root] != -1:
            root = self.parent[root]
        while self.parent[x] != -1:  # path compression
            self.parent[x], x = root, self.parent[x]
        return root

    def union(self, a: int, b: int) -> None:
        nid = self.next_id
        self.parent[a] = nid
        self.parent[b] = nid
        self.size[nid] = self.size[a] + self.size[b]
        self.next_id += 1


def build_dendrogram_host(src, dst, weights, m: int,
                          assume_sorted: bool = False
                          ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Union-find dendrogram from weight-sorted MST edges (reference
    build_dendrogram_host, detail/agglomerative.cuh:101).

    ``assume_sorted`` skips the weight sort when the caller already sorted
    (build_sorted_mst's contract).

    Uses the native C++ union-find (cpp/src/host_runtime.cpp
    rt_build_dendrogram) when available — this loop is the pipeline's one
    inherently sequential host stage; falls back to Python.
    """
    src = np.asarray(src)[: m - 1]
    dst = np.asarray(dst)[: m - 1]
    weights = np.asarray(weights)[: m - 1]

    from raft_tpu.core import native
    nat = native.build_dendrogram(src, dst, weights, m)
    if nat is not None:
        return nat

    if not assume_sorted:
        order = np.argsort(weights, kind="stable")
        src, dst, weights = src[order], dst[order], weights[order]

    children = np.zeros((m - 1, 2), dtype=np.int64)
    sizes = np.zeros(m - 1, dtype=np.int64)
    uf = _UnionFind(m)
    for i in range(m - 1):
        aa, bb = uf.find(int(src[i])), uf.find(int(dst[i]))
        children[i, 0], children[i, 1] = aa, bb
        sizes[i] = uf.size[aa] + uf.size[bb]
        uf.union(aa, bb)
    return children, weights.astype(np.float64), sizes


def extract_flattened_clusters(children: np.ndarray, n_clusters: int,
                               n_leaves: int) -> np.ndarray:
    """Cut the dendrogram into n_clusters monotonic labels (reference
    extract_flattened_clusters, detail/agglomerative.cuh:237)."""
    m = n_leaves
    if n_clusters == 1:
        return np.zeros(m, dtype=np.int64)

    from raft_tpu.core import native
    nat = native.extract_clusters(children, n_clusters, m)
    if nat is not None:
        return nat
    # undo the last (n_clusters - 1) merges: union over the first
    # m - n_clusters merges only
    parent = np.full(2 * m - 1, -1, dtype=np.int64)
    for i in range(m - n_clusters):
        nid = m + i
        parent[children[i, 0]] = nid
        parent[children[i, 1]] = nid

    def find(x):
        while parent[x] != -1:
            x = parent[x]
        return x

    roots = np.array([find(i) for i in range(m)])
    # monotonic relabel (the reference reuses label roots + make_monotonic)
    _, labels = np.unique(roots, return_inverse=True)
    return labels


_SQRT_L2 = (D.L2SqrtExpanded, D.L2SqrtUnexpanded)
_SQUARED_L2 = (D.L2Expanded, D.L2Unexpanded)


def build_sorted_mst(X: jnp.ndarray, graph: CSR, max_iter: int = 10,
                     metric: DistanceType = D.L2SqrtExpanded
                     ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """MST over the connectivity graph; if the graph is a forest, stitch
    components with connect_components and re-solve (reference
    build_sorted_mst, detail/mst.cuh:133-160).

    ``metric`` must describe the units of the graph's edge weights so the
    stitch edges (Euclidean, computed from X) are in the same units; like
    the reference's fusedL2NN-based fix-up, only the L2 family can be
    stitched.

    Returns host (src, dst, weights) with exactly m-1 edges, weight-sorted.
    """
    m = X.shape[0]
    g, colors = mst(graph)
    edges_src = [np.asarray(g.src)]
    edges_dst = [np.asarray(g.dst)]
    edges_w = [np.asarray(g.weights)]

    iters = 1
    n_components = len(np.unique(np.asarray(colors)))
    if n_components > 1:
        expects(metric in _SQRT_L2 or metric in _SQUARED_L2,
                "build_sorted_mst: graph is disconnected and metric %d is "
                "not in the L2 family — cannot stitch components (the "
                "reference's fusedL2NN fix-up is L2-only)", int(metric))
    while n_components > 1 and iters < max_iter:
        fix = connect_components(X, colors, sqrt=metric in _SQRT_L2)
        fix_csr = convert.coo_to_csr(fix)
        g2, colors = mst(fix_csr, colors=colors)
        edges_src.append(np.asarray(g2.src))
        edges_dst.append(np.asarray(g2.dst))
        edges_w.append(np.asarray(g2.weights))
        n_components = len(np.unique(np.asarray(colors)))
        iters += 1
    expects(n_components == 1,
            "MST or MSF still disconnected after %d iterations", max_iter)

    src = np.concatenate(edges_src)
    dst = np.concatenate(edges_dst)
    w = np.concatenate(edges_w)
    keep = src >= 0
    src, dst, w = src[keep], dst[keep], w[keep]
    expects(len(src) == m - 1,
            "MST has %d edges, expected %d", len(src), m - 1)
    order = np.argsort(w, kind="stable")
    return src[order], dst[order], w[order]


def get_distance_graph(X: jnp.ndarray, c: int,
                       metric: DistanceType,
                       linkage: str = "knn", handle=None) -> CSR:
    """Connectivity graph: kNN (k = log2(m) + c, reference
    detail/connectivities.cuh) or full pairwise."""
    m = X.shape[0]
    if linkage == "knn":
        k = min(m, int(math.log2(max(m, 2))) + c)
        g: COO = knn_graph(X, k=k, metric=metric, handle=handle)
        return convert.coo_to_csr(g)
    if linkage == "pairwise":
        from raft_tpu.distance.pairwise import pairwise_distance

        dmat = pairwise_distance(X, X, metric, handle=handle)
        dmat = jnp.where(jnp.eye(m, dtype=bool), 0.0, dmat)
        return CSR.from_dense(np.asarray(dmat))
    raise ValueError(f"unknown linkage '{linkage}'")


@profiled("sparse")
def single_linkage(X, n_clusters: int,
                   metric: DistanceType = D.L2SqrtExpanded,
                   linkage: str = "knn", c: int = 15,
                   handle=None) -> LinkageResult:
    """Single-linkage HAC over dense rows X (m, d) (reference
    single_linkage, sparse/hierarchy/single_linkage.hpp:48).

    ``handle``: optional resource context threaded through the kNN-graph
    stage (reference signature takes ``handle_t&``,
    single_linkage.hpp:48); the final labels are recorded on its main
    stream so ``handle.sync_stream()`` covers the whole pipeline.
    """
    X = jnp.asarray(X)
    m = X.shape[0]
    expects(n_clusters <= m,
            "n_clusters must be less than or equal to the number of data points")
    graph = get_distance_graph(X, c, metric, linkage, handle=handle)
    src, dst, w = build_sorted_mst(X, graph, metric=metric)
    children, deltas, sizes = build_dendrogram_host(src, dst, w, m,
                                                    assume_sorted=True)
    labels = extract_flattened_clusters(children, n_clusters, m)
    from raft_tpu.core.handle import record_on_handle

    record_on_handle(handle, labels)
    return LinkageResult(labels, children, deltas, sizes,
                         n_clusters=n_clusters, n_leaves=m)
