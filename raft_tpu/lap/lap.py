"""Linear Assignment Problem: min-cost perfect matching on a cost matrix.

Reference: lap/lap.cuh:37 ``LinearAssignmentProblem`` — the Date–Nagi GPU
Hungarian variant (state machine steps 0-6, :89-108; kernels in
lap/lap_functions.cuh / lap_kernels.cuh), solving a batch of SP×N×N
problems.

TPU design: the Hungarian algorithm's augmenting-path machinery is
pointer-chasing — hostile to XLA.  The **auction algorithm** (Bertsekas)
computes the same optimal assignment through dense, vectorizable bidding
rounds: every unassigned row bids for its best column (two-min reduction
over a row — one (n, n) matrix op per round), prices rise, ε-scaling
guarantees optimality for integer-scaled costs.  Batches vmap.  This keeps
the whole solve inside one ``lax.while_loop`` of MXU/VPU-shaped ops.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import functools

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects


class LapResult(NamedTuple):
    row_assignment: jnp.ndarray  # (n,) col assigned to each row, -1 if none
    col_assignment: jnp.ndarray  # (n,) row assigned to each col, -1 if none
    obj_val: jnp.ndarray         # primal objective; inf when incomplete
    row_duals: jnp.ndarray       # (n,)
    col_duals: jnp.ndarray       # (n,) auction prices
    complete: jnp.ndarray        # bool: every row assigned (False only if
                                 # the round cap truncated the auction)


def _auction_round(cost, eps, state):
    """One synchronous bidding round (Gauss-Seidel-free, all rows bid)."""
    row_of_col, price = state
    assigned_col_of_row = _col_to_row_view(row_of_col, cost.shape[0])
    unassigned = assigned_col_of_row < 0  # (n,) rows with no column

    value = -(cost + price[None, :])  # row i's value for col j (maximize)
    best_j = jnp.argmax(value, axis=1)
    # row-max, NOT take_along_axis(argmax): the per-row gather lowers
    # to a serial scalar loop on TPU (r4 tile-merge finding)
    best_v = jnp.max(value, axis=1)
    masked = value.at[jnp.arange(cost.shape[0]), best_j].set(-jnp.inf)
    second_v = jnp.max(masked, axis=1)
    second_v = jnp.where(jnp.isfinite(second_v), second_v, best_v - eps)
    bid = best_v - second_v + eps  # > 0

    # per column: take the highest bid among unassigned rows
    n = cost.shape[0]
    bid_masked = jnp.where(unassigned, bid, -jnp.inf)
    col_best_bid = jax.ops.segment_max(bid_masked, best_j, num_segments=n)
    has_bid = jnp.isfinite(col_best_bid) & (col_best_bid > -jnp.inf)
    # winning row per column: among rows bidding that column with the top
    # bid, pick the smallest row id (deterministic)
    is_winner = unassigned & (bid_masked == col_best_bid[best_j])
    row_ids = jnp.where(is_winner, jnp.arange(n), n)
    win_row = jax.ops.segment_min(row_ids, best_j, num_segments=n)
    newly = (win_row < n) & has_bid

    # displace previous owner of the column, update price
    row_of_col = jnp.where(newly, win_row, row_of_col)
    price = jnp.where(newly, price + col_best_bid, price)
    return row_of_col.astype(jnp.int32), price


def _col_to_row_view(row_of_col, n):
    """(n,) col assigned to each row, -1 if none."""
    out = jnp.full((n,), -1, jnp.int32)
    cols = jnp.arange(n, dtype=jnp.int32)
    valid = row_of_col >= 0
    return out.at[jnp.where(valid, row_of_col, 0)].max(
        jnp.where(valid, cols, -1))


@functools.partial(jax.jit, static_argnames=("max_rounds",))
def _solve_one(cost: jnp.ndarray, max_rounds: int = 0):
    n = cost.shape[0]
    spread = jnp.maximum(jnp.max(cost) - jnp.min(cost), 1.0)
    # ε-scaling: auction is n·ε-suboptimal, so the last phase must run at
    # ε small against the cost resolution; for f32 costs a 1e-6·spread
    # floor leaves n·ε far below any meaningful objective gap
    eps0 = spread / 2.0
    eps_min = spread * 1e-6
    cap = max_rounds if max_rounds else 200 * n + 2000

    def phase_cond(state):
        row_of_col, price, eps, rounds = state
        return (eps >= eps_min * 0.99) & (rounds < cap)

    def phase_body(state):
        row_of_col, price, eps, rounds = state
        # run bidding until complete at this ε
        def cond(s):
            roc, _, r = s
            assigned = jnp.sum((roc >= 0).astype(jnp.int32))
            return (assigned < n) & (r < cap)

        def body(s):
            roc, pr, r = s
            roc, pr = _auction_round(cost, eps, (roc, pr))
            return roc, pr, r + 1

        row_of_col = jnp.full((n,), -1, jnp.int32)  # restart assignment
        row_of_col, price, rounds = jax.lax.while_loop(
            cond, body, (row_of_col, price, rounds))
        return row_of_col, price, eps / 5.0, rounds

    state0 = (jnp.full((n,), -1, jnp.int32), jnp.zeros((n,), cost.dtype),
              jnp.asarray(eps0, cost.dtype), jnp.int32(0))
    row_of_col, price, _, _ = jax.lax.while_loop(
        phase_cond, phase_body, state0)

    col_of_row = _col_to_row_view(row_of_col, n)
    complete = jnp.all(col_of_row >= 0)
    safe = jnp.where(col_of_row >= 0, col_of_row, 0)
    obj = jnp.sum(jnp.take_along_axis(cost, safe[:, None], axis=1)[:, 0])
    obj = jnp.where(complete, obj, jnp.inf)
    # duals: col dual = -price; row dual = min_j (cost - col dual)
    v = -price
    u = jnp.min(cost - v[None, :], axis=1)
    return col_of_row, row_of_col, obj, u, v, complete


def solve_lap(cost: jnp.ndarray, max_rounds: int = 0) -> LapResult:
    """Solve min-cost assignment for a square cost matrix (n, n).

    Returns optimal (for ε-scaled auction, optimal when costs are
    well-scaled floats) assignments both ways, objective, and dual prices
    (reference ``LinearAssignmentProblem::solve`` + getters, lap.cuh:89-160).
    """
    cost = jnp.asarray(cost)
    expects(cost.ndim == 2 and cost.shape[0] == cost.shape[1],
            "solve_lap: square cost matrix required")
    return LapResult(*_solve_one(cost, max_rounds=max_rounds))


class LinearAssignmentProblem:
    """Batch LAP solver facade (reference lap/lap.cuh:37 — SP subproblems).

    ``solve(costs)`` accepts (batch, n, n) or (n, n).
    """

    def __init__(self, max_rounds: int = 0):
        self.max_rounds = max_rounds

    def solve(self, costs: jnp.ndarray) -> LapResult:
        costs = jnp.asarray(costs)
        if costs.ndim == 2:
            return solve_lap(costs, self.max_rounds)
        expects(costs.ndim == 3, "LinearAssignmentProblem: (SP, N, N) costs")
        solve = jax.vmap(lambda c: _solve_one(c, max_rounds=self.max_rounds))
        return LapResult(*solve(costs))
