"""Linear Assignment Problem solver (reference cpp/include/raft/lap/)."""

from raft_tpu.lap.lap import LinearAssignmentProblem, solve_lap  # noqa: F401
