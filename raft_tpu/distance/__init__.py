"""Pairwise distances (reference cpp/include/raft/distance/ +
linalg/distance_type.h)."""

from raft_tpu.distance.distance_type import DistanceType
from raft_tpu.distance.pairwise import (
    distance,
    get_workspace_size,
    pairwise_distance,
)
from raft_tpu.distance.fused_l2_nn import fused_l2_nn, fused_l2_nn_min_reduce

__all__ = [
    "DistanceType",
    "pairwise_distance",
    "distance",
    "get_workspace_size",
    "fused_l2_nn",
    "fused_l2_nn_min_reduce",
]
