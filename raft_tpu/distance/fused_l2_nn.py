"""Fused L2 distance + 1-nearest-neighbor reduction.

Reference: cpp/include/raft/distance/fused_l2_nn.hpp:84 +
detail/fused_l2_nn.cuh:134,267 — one kernel computes the L2 distance tile
and immediately argmin-reduces each row to its single nearest neighbor
(key-value pairs, per-row mutex + atomics), so the (m, n) distance matrix
is never materialized.  This is the workhorse of MST/connect_components
and single-linkage.

TPU re-design: scan over column tiles of y; each step is one MXU matmul
(the expanded-L2 form) plus a per-row tile argmin, merged into a running
(value, index) pair — the atomics/mutex machinery is replaced by the
sequential-scan reduction, which XLA pipelines.  Memory high-water mark is
(m, tile_n) instead of (m, n).  Ties between finite values break toward
the smaller index (deterministic; the reference's atomic version is
first-writer-wins).  Rows with no admissible pair (fully masked) keep the
sentinel ``(inf, int32-max)``, mirroring the reference's untouched init KVP.

A custom reduce op over (value, index) pairs can be supplied for consumers
like connect_components that need color-aware semantics
(detail/connect_components.cuh:89 FixConnectivitiesRedOp).
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core import tuning
from raft_tpu.core.error import expects
from raft_tpu.core.profiler import profiled
from raft_tpu.core.utils import is_tpu_backend

IDX_SENTINEL = jnp.iinfo(jnp.int32).max


def _default_reduce(best, cand):
    bv, bi = best
    cv, ci = cand
    # strict improvement, or a finite tie broken toward the smaller index;
    # inf==inf is NOT a tie so fully-masked rows keep the init sentinel
    take = (cv < bv) | ((cv == bv) & jnp.isfinite(cv) & (ci < bi))
    return jnp.where(take, cv, bv), jnp.where(take, ci, bi)


@profiled("distance")
def fused_l2_nn_min_reduce(
    x: jnp.ndarray,
    y: jnp.ndarray,
    sqrt: bool = False,
    reduce_op: Optional[Callable] = None,
    init_val: Optional[Tuple[jnp.ndarray, jnp.ndarray]] = None,
    tile_n: int = 4096,
    mask: Optional[jnp.ndarray] = None,
    tile_mask_fn: Optional[Callable] = None,
    precision: str = "highest",
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Tiled L2 + 1-NN scan with a pluggable KVP reduce op (reference
    fused_l2_nn.hpp:29-45 MinAndDistanceReduceOp / custom ops).

    ``reduce_op(best (val, idx), cand (val, idx)) -> (val, idx)`` merges
    each tile's candidate minimum per row into the running pair;
    ``init_val`` seeds the running pair (default: ``(inf, int32-max)``).
    ``mask`` (m, n), True = pair admissible; ``tile_mask_fn(j0, tile_n) ->
    (m, tile_n) bool`` computes the admissibility mask per tile on the fly
    (True = allowed) without materializing m×n — the color-test hook
    connect_components folds into the scan this way, playing
    FixConnectivitiesRedOp's role (connect_components.cuh:89).  ``sqrt``
    reports root distances (applied per tile — monotonic, so the reduction
    semantics are unchanged, matching the reference's in-kernel epilogue).
    """
    expects(x.ndim == 2 and y.ndim == 2 and x.shape[1] == y.shape[1],
            "fused_l2_nn: shape mismatch")
    m = x.shape[0]
    n = y.shape[0]
    tile_n = min(tile_n, n)
    rop = reduce_op or _default_reduce

    # integer inputs promote to float: the inf padding sentinel and the
    # distance arithmetic below are floating-point
    val_dtype = jnp.result_type(x.dtype, jnp.float32)
    x = x.astype(val_dtype)
    y = y.astype(val_dtype)
    xn = jnp.sum(x * x, axis=1)
    yn = jnp.sum(y * y, axis=1)
    n_tiles = -(-n // tile_n)
    n_pad = n_tiles * tile_n
    y_p = jnp.pad(y, ((0, n_pad - n), (0, 0)))
    yn_p = jnp.pad(yn, (0, n_pad - n), constant_values=jnp.inf)
    if mask is not None:
        mask_p = jnp.pad(mask, ((0, 0), (0, n_pad - n)), constant_values=False)

    def step(carry, tile_idx):
        j0 = tile_idx * tile_n
        y_t = jax.lax.dynamic_slice_in_dim(y_p, j0, tile_n, axis=0)
        yn_t = jax.lax.dynamic_slice_in_dim(yn_p, j0, tile_n, axis=0)
        d = xn[:, None] + yn_t[None, :] - 2.0 * jnp.matmul(x, y_t.T, precision=precision)
        d = jnp.maximum(d, 0.0)
        d = jnp.where(jnp.isfinite(yn_t)[None, :], d, jnp.inf)
        if mask is not None:
            mk = jax.lax.dynamic_slice_in_dim(mask_p, j0, tile_n, axis=1)
            d = jnp.where(mk, d, jnp.inf)
        if tile_mask_fn is not None:
            d = jnp.where(tile_mask_fn(j0, tile_n), d, jnp.inf)
        if sqrt:
            d = jnp.sqrt(d)
        t_idx = jnp.argmin(d, axis=1)
        # row-min, NOT take_along_axis(argmin): the per-row gather
        # lowers to a serial scalar loop on TPU (r4 tile-merge finding)
        t_val = jnp.min(d, axis=1)
        cand = (t_val, (j0 + t_idx).astype(jnp.int32))
        return rop(carry, cand), None

    if init_val is None:
        init_val = (
            jnp.full((m,), jnp.inf, val_dtype),
            jnp.full((m,), IDX_SENTINEL, jnp.int32),
        )
    out, _ = jax.lax.scan(step, init_val, jnp.arange(n_tiles))
    return out


@profiled("distance")
def fused_l2_nn(
    x: jnp.ndarray,
    y: jnp.ndarray,
    sqrt: bool = False,
    tile_n: int = 4096,
    mask: Optional[jnp.ndarray] = None,
    precision: str = "highest",
    impl: Optional[str] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """For each row of x (m, k): min L2 distance to rows of y (n, k) and its
    index.  Returns ``(min_dists (m,), min_idx (m,) int32)``.

    ``sqrt`` applies the square root to the reported minimum (reference
    fused_l2_nn.hpp:84 Sqrt template param).  ``mask`` (m, n) optionally
    excludes pairs (True = allowed); a fully-masked row returns
    ``(inf, IDX_SENTINEL)``.  (connect_components uses the per-tile
    ``tile_mask_fn`` hook of :func:`fused_l2_nn_min_reduce` instead, which
    avoids materializing m×n.)

    ``impl``: "pallas" (the fully fused kernel,
    :mod:`raft_tpu.ops.nn_tile` — default on a real TPU backend for the
    plain f32 min-reduce case), "xla" (the tiled scan), or None = pick
    per backend.  Auto-selection routes the mask / f64 cases to the XLA
    scan; an *explicit* pallas request for them errors rather than
    silently running another impl (same convention as fused_l2_knn).
    """
    requested = impl
    if impl is not None:
        # registry-only knob: explicit values validated through the
        # candidate registry's shared message shape
        tuning.check("fused_nn_impl", impl, site="fused_l2_nn",
                     explicit=True)
    else:
        impl = "pallas" if is_tpu_backend() else "xla"
    plain_f32 = (mask is None
                 and jnp.result_type(x.dtype, jnp.float32) == jnp.float32)
    expects(not (requested == "pallas" and not plain_f32),
            "fused_l2_nn: impl='pallas' serves the plain f32 min-reduce "
            "only (no mask, no f64) — use impl='xla' for this case")
    if impl == "pallas" and plain_f32:
        from raft_tpu.ops.nn_tile import fused_nn_tile

        # index-tile width comes from the nn_block_n registry knob
        # inside the kernel entry — no consumer-local literal
        # (ci/style_check.py bans re-introducing one)
        vals, idx = fused_nn_tile(x, y, precision=precision)
        if sqrt:
            vals = jnp.sqrt(vals)
        return vals, idx
    return fused_l2_nn_min_reduce(
        x, y, sqrt=sqrt, tile_n=tile_n, mask=mask, precision=precision)
