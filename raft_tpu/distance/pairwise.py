"""Pairwise distance computation — all runtime-dispatchable metrics.

Reference: cpp/include/raft/distance/distance.hpp:53-307 (typed ``distance``
+ runtime ``pairwise_distance`` over 15 metrics) dispatching into
detail/distance.cuh:94-556 and the per-metric detail/*.cuh kernels.

TPU re-design in two regimes:

- **Expanded / dot-product metrics** (L2Expanded family, Cosine,
  Correlation, InnerProduct, Hellinger, RusselRao, KL): the inner
  accumulation is a dot product, so the whole metric collapses to one MXU
  matmul plus row-norm vectors and a fused epilogue — the
  ``xn + yn - 2 x@yᵀ`` form the reference implements by hand
  (detail/euclidean.cuh:59-116).  No workspace: XLA materializes norms as
  part of the fusion.

- **Unexpanded metrics** (L1, Chebyshev/Linf, Canberra, Minkowski,
  Hamming, Jensen-Shannon, unexpanded L2, BrayCurtis): the accumulation is
  a non-linear function of (x_ik, y_jk), so they run on the generic tiled
  Pallas kernel (raft_tpu/ops/pairwise_tile.py), the TPU analog of the
  ``PairwiseDistances`` template.

Parity notes (verified against the reference):
- ``CosineExpanded`` returns the cosine **distance** 1 - acc/(|x||y|)
  (cosine.cuh:29,171 "C = 1 - op(...)"; the fin_op wrapper computes 1 - pA
  before the user lambda, cosine.cuh:210).  Zero-norm rows get distance 1.
- ``CorrelationExpanded`` returns the correlation *distance*
  1 - r (correlation.cuh:124-128).
- ``KLDivergence`` returns 0.5 * KL (kl_divergence.cuh:124).
- ``HellingerExpanded`` = sqrt(max(0, 1 - Σ √x√y)) (hellinger.cuh:95-110).
- ``RusselRaoExpanded`` = (k - Σ x·y)/k (russell_rao.cuh:91).
- Unsupported runtime metrics raise, matching distance.hpp:281.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp

from raft_tpu.core.error import expects, fail
from raft_tpu.core.handle import record_on_handle
from raft_tpu.core.profiler import profiled
from raft_tpu.distance.distance_type import DistanceType
from raft_tpu.ops.pairwise_tile import pairwise_tile

D = DistanceType

# MXU matmuls default to reduced-precision passes on TPU; distances need
# f32-faithful accumulation (the reference computes exact f32 FMAs), so all
# dot products here run at HIGHEST precision unless overridden via
# set_default_precision (bench code may trade accuracy for speed).
_DEFAULT_PRECISION = "highest"


def set_default_precision(p) -> None:
    """Set the MXU precision for matmul-backed metrics ("highest" |
    "float32" | "bfloat16" | None)."""
    global _DEFAULT_PRECISION
    _DEFAULT_PRECISION = p


def _mm(a, b):
    return jnp.matmul(a, b, precision=_DEFAULT_PRECISION)



# --------------------------------------------------------------------- #
# expanded (matmul-backed) metrics
# --------------------------------------------------------------------- #
def expanded_sq_dists(x, y, precision: str = "highest") -> jnp.ndarray:
    """(m, n) clamped squared L2 distances, expanded MXU form
    ``xn + yn − 2·x@yᵀ`` — the single shared implementation every
    matmul-backed consumer (IVF probes, ball cover, fused NN) builds on."""
    xn = jnp.sum(x * x, axis=1)
    yn = jnp.sum(y * y, axis=1)
    d = xn[:, None] + yn[None, :] - 2.0 * jnp.matmul(x, y.T,
                                                     precision=precision)
    return jnp.maximum(d, 0.0)


def _l2_expanded(x, y, sqrt: bool):
    d = expanded_sq_dists(x, y, _DEFAULT_PRECISION)
    return jnp.sqrt(d) if sqrt else d


def _cosine(x, y):
    # distance form: 1 - sim (reference distance/detail/cosine.cuh:29);
    # zero-norm rows have empty support -> similarity 0 -> distance 1
    xn = jnp.sqrt(jnp.sum(x * x, axis=1))
    yn = jnp.sqrt(jnp.sum(y * y, axis=1))
    den = xn[:, None] * yn[None, :]
    sim = jnp.where(den > 0, _mm(x, y.T) / jnp.where(den == 0, 1.0, den), 0.0)
    return 1.0 - sim


def _correlation(x, y):
    k = x.shape[1]
    dot = _mm(x, y.T)
    sx, sy = jnp.sum(x, axis=1), jnp.sum(y, axis=1)
    sx2, sy2 = jnp.sum(x * x, axis=1), jnp.sum(y * y, axis=1)
    numer = k * dot - sx[:, None] * sy[None, :]
    q = k * sx2 - sx * sx
    r = k * sy2 - sy * sy
    return 1.0 - numer / jnp.sqrt(q[:, None] * r[None, :])


def _hellinger(x, y):
    acc = _mm(jnp.sqrt(jnp.abs(x)), jnp.sqrt(jnp.abs(y)).T)
    final = 1.0 - acc
    return jnp.sqrt(jnp.maximum(final, 0.0))


def _russell_rao(x, y):
    k = x.shape[1]
    return (k - _mm(x, y.T)) / k


def _kl_divergence(x, y):
    # 0.5 * sum_k x * (log x - log y), with 0log0 = 0 and the log-y term
    # dropped where y == 0 (kl_divergence.cuh:95-99)
    x_logx = jnp.where(x > 0, x * jnp.log(jnp.where(x > 0, x, 1.0)), 0.0)
    masked_log_y = jnp.where(y > 0, jnp.log(jnp.where(y > 0, y, 1.0)), 0.0)
    return 0.5 * (jnp.sum(x_logx, axis=1)[:, None] - _mm(x, masked_log_y.T))


# --------------------------------------------------------------------- #
# unexpanded (tiled-kernel) metrics: combine lambdas see (bm, bk, 1) x and
# (1, bk, bn) yT broadcast views
# --------------------------------------------------------------------- #
def _c_l1(xv, yv):
    return jnp.abs(xv - yv)


def _c_l2(xv, yv):
    d = xv - yv
    return d * d


def _c_canberra(xv, yv):
    # dtype-matched constants: under jax_enable_x64 a Python-float where
    # branch traces as a weak-f64 literal whose f64->f32 convert lands
    # INSIDE the Pallas kernel, and Mosaic lowering rejects it
    # ("Unsupported cast: float64 -> float32", mosaic/lowering.py) even
    # though the op's *result* dtype is f32 — caught by
    # test_every_unexpanded_metric_combine_lowers, which fails on the
    # literal form and passes on this one
    d = jnp.abs(xv - yv)
    s = jnp.abs(xv) + jnp.abs(yv)
    zero = jnp.zeros((), d.dtype)
    one = jnp.ones((), d.dtype)
    return jnp.where(s == 0, zero, d / jnp.where(s == 0, one, s))


def _c_minkowski(p):
    def combine(xv, yv):
        return jnp.abs(xv - yv) ** p

    return combine


def _c_hamming(xv, yv):
    return (xv != yv).astype(jnp.float32)


def _c_jensen_shannon(xv, yv):
    # KL(x||m) + KL(y||m) with m = (x+y)/2 and 0log0 = 0
    # (jensen_shannon.cuh:85).  Constants are dtype-matched — see
    # _c_canberra: a Python-float where branch traces as weak f64 under
    # jax_enable_x64 and the resulting in-kernel f64->f32 convert fails
    # Mosaic lowering.
    m = 0.5 * (xv + yv)
    zero = jnp.zeros((), m.dtype)
    one = jnp.ones((), m.dtype)
    logm = jnp.log(jnp.where(m > 0, m, one))

    def term(v):
        return jnp.where(
            v > 0, v * (jnp.log(jnp.where(v > 0, v, one)) - logm), zero)

    return term(xv) + term(yv)


def _tiled(x, y, combine, reduce_kind="add", epilog=None, init=0.0, **kw):
    return pairwise_tile(x, y, combine, reduce_kind=reduce_kind,
                         epilog=epilog, init=init, **kw)


@profiled("distance")
def pairwise_distance(
    x: jnp.ndarray,
    y: jnp.ndarray,
    metric: DistanceType = D.L2Expanded,
    metric_arg: float = 2.0,
    fin_op: Optional[Callable] = None,
    handle=None,
    **tile_kw,
) -> jnp.ndarray:
    """All-pairs distances between rows of x (m, k) and y (n, k).

    Runtime-dispatch analog of reference distance.hpp:207.  ``metric_arg``
    is the Minkowski p.  ``fin_op`` is the optional elementwise final
    lambda (reference FinalLambda).  ``handle`` (the reference's first
    argument, handle.hpp:49) records the async result on the handle's
    main stream so ``sync_stream``/``stream_syncer`` cover it.  Extra
    keyword args tune the tiled kernel (block sizes) for unexpanded
    metrics.
    """
    expects(x.ndim == 2 and y.ndim == 2, "pairwise_distance: 2-D inputs required")
    expects(
        x.shape[1] == y.shape[1],
        "pairwise_distance: dimensionality mismatch (%d vs %d)",
        x.shape[1], y.shape[1],
    )

    if metric == D.L2Expanded:
        out = _l2_expanded(x, y, sqrt=False)
    elif metric == D.L2SqrtExpanded:
        out = _l2_expanded(x, y, sqrt=True)
    elif metric == D.CosineExpanded:
        out = _cosine(x, y)
    elif metric == D.CorrelationExpanded:
        out = _correlation(x, y)
    elif metric == D.InnerProduct:
        out = _mm(x, y.T)
    elif metric == D.HellingerExpanded:
        out = _hellinger(x, y)
    elif metric == D.RusselRaoExpanded:
        out = _russell_rao(x, y)
    elif metric == D.KLDivergence:
        out = _kl_divergence(x, y)
    elif metric == D.L1:
        out = _tiled(x, y, _c_l1, **tile_kw)
    elif metric == D.L2Unexpanded:
        out = _tiled(x, y, _c_l2, **tile_kw)
    elif metric == D.L2SqrtUnexpanded:
        out = _tiled(x, y, _c_l2, epilog=jnp.sqrt, **tile_kw)
    elif metric == D.Linf:
        out = _tiled(x, y, _c_l1, reduce_kind="max", **tile_kw)
    elif metric == D.Canberra:
        out = _tiled(x, y, _c_canberra, **tile_kw)
    elif metric == D.LpUnexpanded:
        p = float(metric_arg)
        inv = 1.0 / p
        out = _tiled(x, y, _c_minkowski(p), epilog=lambda a: a ** inv, **tile_kw)
    elif metric == D.HammingUnexpanded:
        k = x.shape[1]
        out = _tiled(x, y, _c_hamming, epilog=lambda a: a / k, **tile_kw)
    elif metric == D.JensenShannon:
        out = _tiled(x, y, _c_jensen_shannon,
                     epilog=lambda a: jnp.sqrt(jnp.maximum(0.5 * a, 0.0)), **tile_kw)
    elif metric == D.BrayCurtis:
        num = _tiled(x, y, _c_l1, **tile_kw)
        sx, sy = jnp.sum(x, axis=1), jnp.sum(y, axis=1)
        den = sx[:, None] + sy[None, :]
        out = jnp.where(den == 0, 0.0, num / jnp.where(den == 0, 1.0, den))
    else:
        fail("Unknown or unsupported distance metric '%d'!", int(metric))

    if fin_op is not None:
        out = fin_op(out)
    record_on_handle(handle, out)
    return out


def distance(
    x: jnp.ndarray,
    y: jnp.ndarray,
    metric: DistanceType,
    metric_arg: float = 2.0,
    fin_op: Optional[Callable] = None,
    **tile_kw,
) -> jnp.ndarray:
    """Typed-entry analog of reference distance.hpp:53 (the compile-time
    metric variant).  Same computation as :func:`pairwise_distance`."""
    return pairwise_distance(x, y, metric, metric_arg, fin_op, **tile_kw)


def get_workspace_size(x: jnp.ndarray, y: jnp.ndarray, metric: DistanceType) -> int:
    """Workspace bytes the reference would allocate
    (distance.hpp:100 / detail/distance.cuh:662): (m+n) accumulators for
    expanded metrics needing row norms, else 0.  The TPU build needs no
    caller-managed workspace — XLA owns temporaries — so this exists for
    API parity and capacity planning."""
    norm_metrics = (
        D.L2Expanded, D.L2SqrtExpanded, D.CosineExpanded, D.CorrelationExpanded,
    )
    if metric in norm_metrics:
        itemsize = jnp.dtype(x.dtype).itemsize
        n = x.shape[0] + y.shape[0]
        if metric == D.CorrelationExpanded:
            n *= 2  # sums and sums-of-squares (correlation.cuh:57 x2n/y2n)
        return n * itemsize
    return 0
