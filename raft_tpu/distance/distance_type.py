"""Distance metric identifiers.

Reference: cpp/include/raft/linalg/distance_type.h:23-66 — 20 metric ids
(0-19) plus the ``Precomputed`` special value (=100).
"""

from __future__ import annotations

import enum


class DistanceType(enum.IntEnum):
    """(reference linalg/distance_type.h:23)"""

    L2Expanded = 0            # xn + yn - 2 x.yT
    L2SqrtExpanded = 1        # sqrt of the above
    CosineExpanded = 2
    L1 = 3
    L2Unexpanded = 4          # sum (x-y)^2 accumulated directly
    L2SqrtUnexpanded = 5
    InnerProduct = 6
    Linf = 7                  # Chebyshev
    Canberra = 8
    LpUnexpanded = 9          # generalized Minkowski
    CorrelationExpanded = 10
    JaccardExpanded = 11      # sparse-only in the reference
    HellingerExpanded = 12
    Haversine = 13
    BrayCurtis = 14
    JensenShannon = 15
    HammingUnexpanded = 16
    KLDivergence = 17
    RusselRaoExpanded = 18
    DiceExpanded = 19         # sparse-only in the reference
    Precomputed = 100
