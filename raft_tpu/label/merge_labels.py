"""Merge two labellings connected through masked points.

Reference: label/merge_labels.cuh:115 — builds the label-equivalence graph
G with edges (labels_a[k], labels_b[k]) for masked k, finds its connected
components by iterated min-propagation, and reassigns each point's label
to its component representative (R relabel table).  Labels are 1-based
(weak_cc convention); used to merge per-batch weak-CC results.

TPU design: the reference's atomicMin propagation loop becomes segment-min
over the edge list inside ``lax.while_loop`` — same fixpoint, no atomics.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def merge_labels(labels_a: jnp.ndarray, labels_b: jnp.ndarray,
                 mask: jnp.ndarray) -> jnp.ndarray:
    """Merged labels (1-based): components of the equivalence graph take
    their minimum member label.  Shapes: all (N,)."""
    N = labels_a.shape[0]
    big = jnp.iinfo(jnp.int32).max
    la = labels_a.astype(jnp.int32)
    lb = labels_b.astype(jnp.int32)

    # R[l-1] = representative (minimum) label of l's equivalence class
    R0 = jnp.arange(1, N + 1, dtype=jnp.int32)

    a_idx = jnp.where(mask, la - 1, 0)
    b_idx = jnp.where(mask, lb - 1, 0)

    def relax(R):
        ra, rb = R[a_idx], R[b_idx]
        m = jnp.minimum(ra, rb)
        upd_a = jax.ops.segment_min(jnp.where(mask, m, big), a_idx,
                                    num_segments=N)
        upd_b = jax.ops.segment_min(jnp.where(mask, m, big), b_idx,
                                    num_segments=N)
        R = jnp.minimum(R, jnp.minimum(upd_a, upd_b))
        return jnp.minimum(R, R[R - 1])  # pointer jump

    def cond(state):
        R, prev = state
        return jnp.any(R != prev)

    def body(state):
        R, _ = state
        return relax(R), R

    R, _ = jax.lax.while_loop(cond, body, (relax(R0), R0))
    return R[la - 1]
