"""Class-label utilities: unique labels, monotonic relabeling, one-vs-rest.

Reference: label/classlabels.cuh — ``getUniquelabels`` (:40),
``getOvrlabels`` (:99, map class idx → +1/-1), ``make_monotonic``
(:159,192, relabel into a monotonically increasing set via the sorted
unique array; values hit by ``filter_op`` pass through unchanged).

TPU design: uniqueness via sort + first-occurrence mask (static capacity:
the output is padded to ``max_labels``); the relabel map is one
``searchsorted``.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax.numpy as jnp


def get_unique_labels(labels: jnp.ndarray, max_labels: Optional[int] = None
                      ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Sorted unique labels, padded to ``max_labels`` (default: len(labels)).

    Returns (unique (max_labels,), n_unique); padding slots repeat the
    largest label (harmless for searchsorted-based mapping).
    Reference: getUniquelabels (classlabels.cuh:40).
    """
    n = labels.shape[0]
    cap = max_labels if max_labels is not None else n
    s = jnp.sort(labels)
    first = jnp.concatenate([jnp.array([True]), s[1:] != s[:-1]])
    n_unique = jnp.sum(first.astype(jnp.int32))
    # compact unique values to the front
    order = jnp.argsort(~first, stable=True)
    uniq = s[order][:cap]
    idx = jnp.arange(cap)
    uniq = jnp.where(idx < n_unique, uniq, s[-1])
    return uniq, n_unique


def make_monotonic(labels: jnp.ndarray,
                   zero_based: bool = False,
                   filter_op: Optional[Callable] = None,
                   max_labels: Optional[int] = None) -> jnp.ndarray:
    """Relabel into a monotonically increasing set (reference
    make_monotonic, classlabels.cuh:159).

    Each label becomes its rank in the sorted unique set (+1 unless
    ``zero_based``); entries where ``filter_op(label)`` is True keep their
    original value (the reference's noise-label passthrough).
    """
    uniq, n_unique = get_unique_labels(labels, max_labels)
    ranks = jnp.searchsorted(uniq[: uniq.shape[0]], labels).astype(labels.dtype)
    out = ranks if zero_based else ranks + 1
    if filter_op is not None:
        out = jnp.where(filter_op(labels), labels, out)
    return out


def get_ovr_labels(labels: jnp.ndarray, unique_labels: jnp.ndarray,
                   idx: int) -> jnp.ndarray:
    """One-vs-rest ±1 labels for class ``idx`` (reference getOvrlabels,
    classlabels.cuh:99)."""
    target = unique_labels[idx]
    return jnp.where(labels == target, 1, -1).astype(labels.dtype)
