"""Label utilities (reference cpp/include/raft/label/)."""

from raft_tpu.label.classlabels import (  # noqa: F401
    get_unique_labels, make_monotonic, get_ovr_labels,
)
from raft_tpu.label.merge_labels import merge_labels  # noqa: F401
