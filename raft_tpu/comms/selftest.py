"""Communicator self-tests, runnable against a live mesh.

Reference: cpp/include/raft/comms/test.hpp:40-542 — one in-header test
function per collective plus p2p and comm_split, exported to Python
(comms_utils.pyx:57+) and driven by pytest on a real cluster
(python/raft/test/test_comms.py).  Each returns True on success so a
session layer can health-check a communicator the same way.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from raft_tpu.comms.host_comms import HostComms
from raft_tpu.comms.types import Op, Status


def test_collective_allreduce(comms: HostComms) -> bool:
    """Each rank contributes 1; every rank must see size (reference
    test.hpp:40)."""
    size = comms.get_size()
    out = comms.allreduce(jnp.ones((size, 1), jnp.int32))
    return bool((np.asarray(out) == size).all())


def test_collective_broadcast(comms: HostComms) -> bool:
    """Root holds 1, others 0; everyone must end with 1 (test.hpp:76)."""
    size = comms.get_size()
    x = jnp.zeros((size, 1), jnp.float32).at[0, 0].set(1.0)
    out = comms.bcast(x, root=0)
    return bool((np.asarray(out) == 1.0).all())


def test_collective_reduce(comms: HostComms) -> bool:
    """Sum-to-root of per-rank ranks (test.hpp:114)."""
    size = comms.get_size()
    x = jnp.arange(size, dtype=jnp.float32)[:, None]
    out = comms.reduce(x, root=0, op=Op.SUM)
    return bool((np.asarray(out)[0] == size * (size - 1) / 2).all())


def test_collective_allgather(comms: HostComms) -> bool:
    """Rank r contributes r; every rank must see [0..size) (test.hpp:151)."""
    size = comms.get_size()
    x = jnp.arange(size, dtype=jnp.float32)[:, None]
    out = np.asarray(comms.allgather(x))
    return all((out[r].ravel() == np.arange(size)).all() for r in range(size))


def test_collective_gather(comms: HostComms) -> bool:
    """Root row holds [0..size); every NON-root row must be zeros — true
    root-only semantics, distinguishable from allgather (test.hpp:190)."""
    size = comms.get_size()
    root = size - 1  # a non-default root exercises the mask placement
    x = jnp.arange(size, dtype=jnp.float32)[:, None] + 1.0
    out = np.asarray(comms.gather(x, root=root))
    want = np.arange(size) + 1.0
    if not (out[root].ravel() == want).all():
        return False
    return all((out[r] == 0).all() for r in range(size) if r != root)


def test_collective_gatherv(comms: HostComms) -> bool:
    """Variable block sizes: rank r contributes r+1 copies of r+1 to the
    root row; non-root rows are zeros (test.hpp:229)."""
    size = comms.get_size()
    counts = [r + 1 for r in range(size)]
    maxc = max(counts)
    buf = np.zeros((size, maxc, 1), np.float32)
    for r in range(size):
        buf[r, : counts[r]] = r + 1
    out = np.asarray(comms.gatherv(jnp.asarray(buf), counts, root=0))
    expected = np.concatenate(
        [np.full((c, 1), r + 1, np.float32) for r, c in enumerate(counts)])
    if not (out[0] == expected).all():
        return False
    return all((out[r] == 0).all() for r in range(1, size))


def test_collective_allgatherv(comms: HostComms) -> bool:
    """Every rank sees the tight concatenation (test.hpp:289)."""
    size = comms.get_size()
    counts = [r + 1 for r in range(size)]
    maxc = max(counts)
    buf = np.zeros((size, maxc, 1), np.float32)
    for r in range(size):
        buf[r, : counts[r]] = r
    out = np.asarray(comms.allgatherv(jnp.asarray(buf), counts))
    expected = np.concatenate(
        [np.full((c, 1), r, np.float32) for r, c in enumerate(counts)])
    return all((out[r] == expected).all() for r in range(size))


def test_collective_reducescatter(comms: HostComms) -> bool:
    """Every rank sends ones(size); each gets back its scalar block == size
    (test.hpp:349)."""
    size = comms.get_size()
    x = jnp.ones((size, size), jnp.float32)
    out = np.asarray(comms.reducescatter(x, op=Op.SUM))
    return bool((out == size).all())


def test_pointToPoint_simple_send_recv(comms: HostComms) -> bool:
    """Ring exchange: rank r sends its payload to (r+1) % size
    (reference test.hpp:385 pointToPoint tag matching).  The battery
    passes its own requests to ``waitall`` explicitly so running it as a
    health probe never sweeps in (or strands) p2p work the *user* has
    queued on the live communicator."""
    size = comms.get_size()
    reqs, recvs = [], []
    for r in range(size):
        reqs.append(comms.isend(jnp.full((3,), float(r)), rank=r,
                                dest=(r + 1) % size, tag=7))
        recvs.append(comms.irecv(rank=r, source=(r - 1) % size, tag=7))
    comms.waitall(reqs + recvs)
    return all(
        (np.asarray(recvs[r].result) == float((r - 1) % size)).all()
        for r in range(size))


def test_pointToPoint_device_send_or_recv(comms: HostComms) -> bool:
    """Pairwise exchange via the device verbs (reference test.hpp:432):
    even ranks send to rank+1, odd ranks receive."""
    size = comms.get_size()
    if size < 2:
        return True
    reqs, recvs = [], {}
    for r in range(0, size - 1, 2):
        reqs.append(comms.device_send(jnp.full((2,), float(r)),
                                      rank=r, dest=r + 1))
        recvs[r + 1] = comms.device_recv(rank=r + 1, source=r)
    comms.waitall(reqs + list(recvs.values()))
    return all(
        (np.asarray(req.result) == float(r - 1)).all()
        for r, req in recvs.items())


def test_pointToPoint_device_sendrecv(comms: HostComms) -> bool:
    """Static-ring ppermute exchange (reference test.hpp:470)."""
    size = comms.get_size()
    perm = [(r, (r + 1) % size) for r in range(size)]
    x = jnp.arange(size, dtype=jnp.float32)[:, None]
    out = np.asarray(comms.device_sendrecv(x, perm))
    return all(out[(r + 1) % size, 0] == r for r in range(size))


def test_pointToPoint_device_multicast_sendrecv(comms: HostComms) -> bool:
    """Rank 0 multicasts to everyone (reference test.hpp:496)."""
    size = comms.get_size()
    sends = [(0, d) for d in range(size)]
    x = jnp.zeros((size, 1), jnp.float32).at[0, 0].set(42.0)
    out = np.asarray(comms.device_multicast_sendrecv(x, sends))
    return bool((out == 42.0).all())


def test_commsplit(comms: HostComms, n_colors: int = 2) -> bool:
    """Split into n_colors round-robin groups and run allreduce in each
    (reference test.hpp:522)."""
    size = comms.get_size()
    n_colors = min(n_colors, size)
    colors = [r % n_colors for r in range(size)]
    subs = comms.comm_split(colors)
    for color, sub in subs.items():
        if not test_collective_allreduce(sub):
            return False
        if sub.get_size() != sum(1 for c in colors if c == color):
            return False
    return True


def test_sync_stream_status(comms: HostComms) -> bool:
    """sync_stream returns SUCCESS on good work and ABORT after abort()
    (reference std_comms.hpp:443-475 semantics)."""
    size = comms.get_size()
    out = comms.allreduce(jnp.ones((size, 1)))
    if comms.sync_stream(out) != Status.SUCCESS:
        return False
    comms.abort()
    return comms.sync_stream(out) == Status.ABORT


ALL_TESTS = [
    test_collective_allreduce,
    test_collective_broadcast,
    test_collective_reduce,
    test_collective_allgather,
    test_collective_gather,
    test_collective_gatherv,
    test_collective_allgatherv,
    test_collective_reducescatter,
    test_pointToPoint_simple_send_recv,
    test_pointToPoint_device_send_or_recv,
    test_pointToPoint_device_sendrecv,
    test_pointToPoint_device_multicast_sendrecv,
    test_commsplit,
]


def run_all(comms: HostComms) -> dict:
    """Run the whole battery against a live communicator, one verdict per
    test (reference test.hpp pattern: one exported runner per verb, driven
    together by the session layer).  A test that *raises* — e.g. every
    verb on an aborted communicator — counts as False rather than
    propagating: this is a health probe, and "the probe crashed" is
    exactly the unhealthy signal it exists to report.  Excludes
    ``test_sync_stream_status``, which intentionally poisons the
    communicator it runs on.

    This is the engine of :meth:`raft_tpu.session.Comms.health_check`.
    """
    results = {}
    for fn in ALL_TESTS:
        try:
            results[fn.__name__] = bool(fn(comms))
        except Exception:
            results[fn.__name__] = False
    return results
