"""Retry/backoff/timeout policy for communicator verbs and bootstrap.

The reference carries a full failure contract on its communicator —
``status_t`` SUCCESS/ERROR/ABORT (comms.hpp:41) and ``sync_stream``
health polling with ``ncclCommGetAsyncError`` + abort-on-failure
(std_comms.hpp:443-475) — but leaves *policy* (when to retry, when to
give up) to callers.  HiCCL's design argument (PAPERS.md) is that a
collective layer earns portability and reliability by separating the
logical verb from its execution policy; :class:`RetryPolicy` is that
seam for the TPU port: a deterministic exponential-backoff schedule,
an optional per-attempt watchdog deadline, and an exception taxonomy
that distinguishes transient failures (retry), invariant violations
(propagate — retrying a shape error cannot help), and aborts (latch).

Used in two places:

- :class:`~raft_tpu.comms.host_comms.HostComms` applies a policy around
  every eager verb execution (``HostComms(..., retry_policy=...)``).
- :class:`raft_tpu.session.Comms` applies one to the multi-host
  bootstrap (``jax.distributed.initialize`` retry-with-timeout — the
  reference's NCCL-uid exchange is similarly retried by Dask's comms
  layer until the cluster converges).

Every retry/timeout is reported through :func:`raft_tpu.core.tracing.event`
(span + monotonic counter), so dashboards can alert on
``comms.retry`` / ``comms.timeout`` rates.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional

from raft_tpu.core import tracing
from raft_tpu.core.error import (
    CALLER_BUG_ERRORS,
    CommAbortedError,
    CommTimeoutError,
)

# Exceptions a retry can never fix: deterministic caller bugs (the shared
# CALLER_BUG_ERRORS taxonomy — RAFT_EXPECTS violations plus the
# Python-level errors JAX tracing raises for bad shapes/indices/dtypes
# before any transport is touched) and latched aborts (the ncclCommAbort
# contract: the communicator is permanently dead).  Transport/runtime
# failures (XlaRuntimeError and friends are RuntimeErrors) stay
# retryable.
NON_RETRYABLE = CALLER_BUG_ERRORS + (CommAbortedError,)


class RetryPolicy:
    """Deterministic exponential backoff with optional watchdog timeout.

    Parameters
    ----------
    max_retries:
        Retries *after* the first attempt (``max_retries=3`` means up to
        4 attempts total).
    base_delay / multiplier / max_delay:
        Backoff schedule: attempt i (0-based retry index) sleeps
        ``min(base_delay * multiplier**i, max_delay)`` seconds.  The
        schedule is a pure function of the policy — no jitter — so fault
        tests replay identically.
    timeout:
        Optional per-attempt deadline in seconds.  Enforced by a watchdog:
        the attempt runs on a worker thread and the calling thread waits
        up to ``timeout``; on expiry a :class:`CommTimeoutError` is
        raised.  The worker thread cannot be cancelled (same limitation as
        ``ncclCommAbort``, which leaks the in-flight kernel) — it is a
        daemon thread and its eventual result is discarded.  Beware the
        consequence under ``retry_timeouts=True``: the abandoned attempt
        is still *executing* while the retry re-runs the same verb, so
        the two overlap on the same communicator.  Harmless for the
        bootstrap connect and for CPU-simulated tests; on real hardware,
        overlapping collectives on one mesh can deadlock or reorder, so
        production verb policies should prefer ``retry_timeouts=False``
        (timeout == fabric gone == abort, the NCCL stance).
    retry_timeouts:
        Whether a watchdog expiry counts as transient (default True —
        bootstrap connects genuinely succeed on retry; set False for the
        NCCL-style "timeout means the fabric is gone" stance — see the
        overlap caveat under ``timeout``).
    sleep:
        Injection point for the backoff sleep (tests pass a recorder).
    """

    def __init__(self,
                 max_retries: int = 3,
                 base_delay: float = 0.05,
                 multiplier: float = 2.0,
                 max_delay: float = 2.0,
                 timeout: Optional[float] = None,
                 retry_timeouts: bool = True,
                 sleep: Callable[[float], None] = time.sleep):
        self.max_retries = int(max_retries)
        self.base_delay = float(base_delay)
        self.multiplier = float(multiplier)
        self.max_delay = float(max_delay)
        self.timeout = timeout
        self.retry_timeouts = retry_timeouts
        self._sleep = sleep

    def schedule(self) -> List[float]:
        """The full deterministic backoff schedule (one delay per retry)."""
        return [min(self.base_delay * self.multiplier ** i, self.max_delay)
                for i in range(self.max_retries)]

    # ------------------------------------------------------------------ #
    def _attempt(self, fn, args, kwargs):
        """One attempt, bounded by the watchdog deadline if configured."""
        if self.timeout is None:
            return fn(*args, **kwargs)
        box = {}
        done = threading.Event()

        def runner():
            try:
                box["result"] = fn(*args, **kwargs)
            except BaseException as e:  # noqa: BLE001 — relayed below
                box["error"] = e
            finally:
                done.set()

        t = threading.Thread(target=runner, daemon=True,
                             name="raft-tpu-comms-watchdog-worker")
        # handshake with the fault seam (faults.Delay.apply): the
        # runner commits to dispatching and the watchdog abandons under
        # the SAME lock, so a stall whose duration straddles the
        # deadline resolves to exactly one of {bailed, committed} — no
        # check-then-act window where the runner reads a stale flag and
        # dispatches its program late anyway
        t.raft_tpu_abandon_lock = threading.Lock()
        t.start()
        if not done.wait(self.timeout):
            with t.raft_tpu_abandon_lock:
                committed = getattr(t, "raft_tpu_dispatch_committed",
                                    False)
                if not committed:
                    t.raft_tpu_abandoned = True
            if committed:
                # the runner won the boundary race: its program is
                # already dispatching, and overlapping the retry with
                # it is the rendezvous deadlock this machinery exists
                # to suppress — grant one extra deadline for the
                # in-flight dispatch to drain.  If it drains, USE the
                # outcome: discarding a completed collective and
                # re-running it is pure duplicate device work, and on
                # real hardware a rank re-running a collective the
                # other ranks completed once desyncs the mesh.  An
                # attempt that outlives the grace too is abandoned
                # mid-program, the documented residual risk.
                if done.wait(self.timeout):
                    if "error" in box:
                        raise box["error"]
                    return box["result"]
            raise CommTimeoutError(
                "verb exceeded its %.3fs watchdog deadline" % self.timeout)
        if "error" in box:
            raise box["error"]
        return box["result"]

    def call(self, fn, *args, verb: str = "call", **kwargs):
        """Run ``fn`` under this policy: watchdog per attempt, backoff
        between attempts.  Non-retryable exceptions propagate
        immediately; on exhaustion the *last* failure propagates
        (callers wrap/latch as appropriate for their layer)."""
        delays = self.schedule()
        attempts = self.max_retries + 1
        last: Optional[BaseException] = None
        for attempt in range(attempts):
            try:
                return self._attempt(fn, args, kwargs)
            except NON_RETRYABLE:
                raise
            except CommTimeoutError as e:
                tracing.counter_inc("comms.timeout")
                if not self.retry_timeouts:
                    raise
                last = e
            except Exception as e:  # transient: retry
                last = e
            if attempt == attempts - 1:
                break
            with tracing.event("comms.retry",
                               "%s attempt=%d/%d delay=%.3fs: %s",
                               verb, attempt + 1, attempts,
                               delays[attempt], last):
                self._sleep(delays[attempt])
        assert last is not None
        raise last
