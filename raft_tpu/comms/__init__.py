"""Communicator abstraction over ICI/DCN (XLA collectives).

Reference: cpp/include/raft/comms/ — ``comms_t``/``comms_iface``
(comms.hpp:91,193) with NCCL+UCX (std_comms.hpp) and MPI (mpi_comms.hpp)
implementations, injected into the handle (handle.hpp:229).

TPU-native design (SURVEY.md §2.2): one implementation over XLA
collectives — :class:`MeshComms` for use *inside* shard_map traces (the
collectives compile onto ICI) and :class:`HostComms` for eager host-level
orchestration, tagged p2p, comm_split and status-returning sync.
``build_comms`` injects a communicator into a :class:`raft_tpu.Handle`
(reference helper.hpp:39 build_comms_nccl_only).

Failure contract (docs/FAULT_MODEL.md): verbs on a latched-aborted
communicator fail fast with :class:`CommAbortedError` (the
``ncclCommAbort`` contract, std_comms.hpp:443-475); an optional
:class:`RetryPolicy` retries transient verb failures with deterministic
backoff and a watchdog deadline; :mod:`~raft_tpu.comms.faults` injects
failures at the eager execute seam so every path is CPU-testable.
"""

from raft_tpu.comms.types import Datatype, Op, Status, get_type  # noqa: F401
from raft_tpu.comms.mesh_comms import MeshComms  # noqa: F401
from raft_tpu.comms.host_comms import HostComms, default_mesh  # noqa: F401
from raft_tpu.comms.resilience import RetryPolicy  # noqa: F401
from raft_tpu.comms import faults, selftest  # noqa: F401
from raft_tpu.core.error import (  # noqa: F401
    CommAbortedError,
    CommError,
    CommTimeoutError,
)


def build_comms(handle, mesh=None, n_devices=None):
    """Create a :class:`HostComms` over ``mesh`` (or the first
    ``n_devices`` local devices) and inject it into ``handle``
    (reference build_comms_nccl_only, comms/helper.hpp:39)."""
    if mesh is None:
        mesh = default_mesh(n_devices)
    comms = HostComms(mesh)
    handle.set_comms(comms)
    handle.mesh = mesh
    return comms
