"""Deterministic, seedable fault injection for the eager comms boundary.

The reference validates its failure contract on live clusters only
(test.hpp self-tests on real NCCL communicators); a TPU outage cannot be
scripted into CI.  This harness makes every failure path testable on the
simulated CPU mesh: it wraps :class:`~raft_tpu.comms.host_comms.HostComms`
verb *execution* (the ``_execute`` seam every eager collective and the
p2p ``waitall`` funnel through) and injects configured failures before
the real XLA program runs.

Layering contract: the injector patches **below** the communicator's
retry/abort machinery (``_run`` = abort latch + RetryPolicy →
``_execute`` = compile+run).  An injected transient failure is therefore
seen — and retried — by the same code path a real XLA runtime error
takes, which is the point: the resilience layer is exercised, not
bypassed.

Faults (compose freely, first match wins per call):

- :class:`FailNth` — raise on the nth matching call (transient by
  default; ``persistent=True`` keeps failing from then on).
- :class:`Delay` — sleep before executing a matching verb (drives the
  watchdog timeout path); optionally scoped to calls whose static
  parameters involve a given rank (root / permutation member).
- :class:`Abort` — from the nth matching call on, latch the communicator
  aborted and raise :class:`~raft_tpu.core.error.CommAbortedError`
  (the injected analog of ``ncclCommAbort`` fired by a peer).
- :class:`RandomFail` — fail each matching call with probability ``p``
  from a private ``random.Random(seed)`` stream: deterministic for a
  given seed, rotated by ``stress.sh faults`` to shake out
  order-dependence.

Usage::

    with faults.inject(comms, faults.FailNth(1, verb="allreduce")) as log:
        out = comms.allreduce(x)      # first execution fails, retry wins
    assert log.injected[0].verb == "allreduce"

The same fault objects also drive the **serving** execute seam
(:func:`raft_tpu.serve.resilience.inject_worker` patches
``ServeWorker._execute`` the way :class:`FaultInjector` patches
``HostComms._execute``): ``FailNth`` / ``Delay`` / ``RandomFail`` are
target-agnostic, so one seeded fault vocabulary covers both layers.
``Abort`` is comms-only (it latches the communicator — the serving
analog is the circuit breaker tripping on the failures the other
faults inject).
"""

from __future__ import annotations

import contextlib
import enum
import random
import threading
import time
from typing import Iterator, List, NamedTuple, Optional, Tuple

from raft_tpu.core import tracing
from raft_tpu.core.error import (CommAbortedError, CommError,
                                 CommTimeoutError)


class InjectedError(CommError):
    """A transient failure raised by the injection harness (stands in
    for an XLA runtime / ICI transport error)."""


def _ranks_in_key(key: tuple) -> Tuple[int, ...]:
    """Static rank parameters mentioned by a verb's cache key: roots
    (bcast/gather*; reduce's key has no root — its result is replicated)
    and permutation/multicast endpoints.  Enum statics (Op/Status) are
    not ranks and are excluded."""
    ranks: List[int] = []
    for part in key[1:]:
        if (isinstance(part, int) and not isinstance(part, bool)
                and not isinstance(part, enum.Enum)):
            ranks.append(part)
        elif isinstance(part, tuple):
            for p in part:
                if isinstance(p, tuple):
                    ranks.extend(q for q in p if isinstance(q, int))
    return tuple(ranks)


class Fault:
    """Base fault: matching by verb name (None = every verb)."""

    def __init__(self, verb: Optional[str] = None):
        self.verb = verb

    def matches(self, verb: str, key: tuple) -> bool:
        return self.verb is None or self.verb == verb

    def apply(self, comms, verb: str, key: tuple, n_match: int) -> bool:
        """Called before a matching execution (``n_match`` is 1-based
        count of matching calls so far).  Raise to inject a failure;
        return True for a non-raising effect (a delay) so the injector
        records it."""
        raise NotImplementedError


class FailNth(Fault):
    """Raise :class:`InjectedError` on the nth matching call (1-based);
    with ``persistent=True``, on every call from the nth onward."""

    def __init__(self, n: int = 1, verb: Optional[str] = None,
                 persistent: bool = False):
        super().__init__(verb)
        self.n = int(n)
        self.persistent = persistent

    def apply(self, comms, verb, key, n_match):
        if n_match == self.n or (self.persistent and n_match >= self.n):
            raise InjectedError(
                "injected transient failure: verb=%s call=%d" % (verb, n_match))
        return False


class Delay(Fault):
    """Sleep ``seconds`` before a matching verb executes.  ``rank``
    restricts to calls whose static parameters (root, permutation
    endpoints) involve that rank; ``times`` bounds how many calls are
    delayed (None = all)."""

    def __init__(self, seconds: float, verb: Optional[str] = None,
                 rank: Optional[int] = None, times: Optional[int] = None,
                 sleep=time.sleep):
        super().__init__(verb)
        self.seconds = float(seconds)
        self.rank = rank
        self.times = times
        self._sleep = sleep

    def matches(self, verb, key):
        if not super().matches(verb, key):
            return False
        return self.rank is None or self.rank in _ranks_in_key(key)

    def apply(self, comms, verb, key, n_match):
        if self.times is None or n_match <= self.times:
            # count before sleeping: a delayed attempt may be abandoned
            # by the watchdog, and the injection must be visible on the
            # counter while the delay is still in flight
            tracing.counter_inc("comms.fault_injected")
            self._sleep(self.seconds)
            # the watchdog abandoned this attempt while it slept: bail
            # BEFORE the verb dispatches its program — a late
            # collective racing the retry's (or the next test's)
            # collective deadlocks the CPU backend's shared rendezvous.
            # The check-or-commit runs under the watchdog's handshake
            # lock (RetryPolicy._attempt) so a delay straddling the
            # deadline cannot read a stale flag and dispatch anyway.
            # The error lands in the abandoned runner's discarded
            # result box, never a caller.
            cur = threading.current_thread()
            lock = getattr(cur, "raft_tpu_abandon_lock", None)
            with lock if lock is not None else contextlib.nullcontext():
                if getattr(cur, "raft_tpu_abandoned", False):
                    raise CommTimeoutError(
                        "delayed attempt abandoned by the watchdog; "
                        "suppressing its late dispatch")
                cur.raft_tpu_dispatch_committed = True
            return True
        return False


class Abort(Fault):
    """From the nth matching call on: latch the communicator aborted and
    raise :class:`CommAbortedError` — the peer-observed ``ncclCommAbort``.
    Persistent by construction (the latch outlives the injector)."""

    def __init__(self, n: int = 1, verb: Optional[str] = None):
        super().__init__(verb)
        self.n = int(n)

    def apply(self, comms, verb, key, n_match):
        if n_match >= self.n:
            comms.abort()
            raise CommAbortedError(
                "injected abort: verb=%s call=%d" % (verb, n_match))


class RandomFail(Fault):
    """Fail each matching call with probability ``p``, drawn from a
    private seeded stream — deterministic per seed, independent of any
    other randomness in the process."""

    def __init__(self, p: float, seed: int, verb: Optional[str] = None):
        super().__init__(verb)
        self.p = float(p)
        self._rng = random.Random(seed)

    def apply(self, comms, verb, key, n_match):
        if self._rng.random() < self.p:
            raise InjectedError(
                "injected random failure: verb=%s call=%d" % (verb, n_match))
        return False


class Injection(NamedTuple):
    """One injected (or delayed) event, recorded for assertions."""

    verb: str
    call: int
    fault: Fault


class FaultInjector:
    """Instance-level wrapper around one communicator's ``_execute``.

    Counts calls per fault (a fault's ``n`` is relative to *its* matching
    stream, not the global call count), applies the first matching fault,
    and records every injection in :attr:`injected`.  ``calls`` counts
    every execution attempt that reached the harness — retries included —
    so tests can assert exactly how many times the transport was hit.
    """

    def __init__(self, comms, faults_: List[Fault]):
        self._comms = comms
        self._faults = list(faults_)
        self._match_counts = [0] * len(self._faults)
        self._orig_execute = None
        self.calls: List[Tuple[str, tuple]] = []
        self.injected: List[Injection] = []

    def _fire(self, target, verb: str, key: tuple) -> None:
        """Record the call and apply the first matching fault (raising
        to inject a failure).  ``target`` is whatever object the seam
        wraps — the communicator here, the serve worker at the serving
        seam (:mod:`raft_tpu.serve.resilience` reuses this loop)."""
        self.calls.append((verb, key))
        for i, fault in enumerate(self._faults):
            if not fault.matches(verb, key):
                continue
            self._match_counts[i] += 1
            n = self._match_counts[i]
            try:
                applied = fault.apply(target, verb, key, n)
            except Exception:
                self.injected.append(Injection(verb, n, fault))
                tracing.counter_inc("comms.fault_injected")
                raise
            if applied:
                # counter already incremented by the fault itself
                # (pre-sleep); only the log entry lands here
                self.injected.append(Injection(verb, n, fault))
            break  # first matching fault owns this call

    def activate(self) -> None:
        assert self._orig_execute is None, "injector already active"
        self._orig_execute = self._comms._execute
        orig = self._orig_execute

        def patched(key, fn, *args, **kwargs):
            self._fire(self._comms, key[0], key)
            return orig(key, fn, *args, **kwargs)

        self._comms._execute = patched

    def deactivate(self) -> None:
        if self._orig_execute is not None:
            self._comms._execute = self._orig_execute
            self._orig_execute = None


@contextlib.contextmanager
def inject(comms, *faults_: Fault) -> Iterator[FaultInjector]:
    """Scoped fault injection on ``comms``: patch the execute seam for
    the duration of the block, restore it after (even on error — but an
    :class:`Abort`'s latch, like the real thing, persists)."""
    injector = FaultInjector(comms, list(faults_))
    injector.activate()
    try:
        yield injector
    finally:
        injector.deactivate()
