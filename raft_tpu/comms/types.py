"""Communicator datatypes, reduction ops and status codes.

Reference: cpp/include/raft/comms/comms.hpp:28-89 — ``datatype_t`` (:28),
``op_t`` (:34, SUM/PROD/MIN/MAX), ``status_t`` (:41, SUCCESS/ERROR/ABORT)
and the ``get_type<T>()`` mapping.  On TPU the datatype travels with the
JAX array, so ``Datatype`` exists for API parity and for consumers that
serialize communicator descriptions.
"""

from __future__ import annotations

import enum

import jax.numpy as jnp

from raft_tpu.core.error import fail


class Op(enum.IntEnum):
    """Reduction operator (reference op_t, comms.hpp:34)."""

    SUM = 0
    PROD = 1
    MIN = 2
    MAX = 3


class Status(enum.IntEnum):
    """Result of :meth:`sync_stream` (reference status_t, comms.hpp:41).

    SUCCESS: all work completed.  ERROR: an error occurred in this
    participant's queued work.  ABORT: an error was observed on another
    participant / the communicator is no longer usable.
    """

    SUCCESS = 0
    ERROR = 1
    ABORT = 2


class Datatype(enum.IntEnum):
    """Wire datatype ids (reference datatype_t, comms.hpp:28)."""

    CHAR = 0
    UINT8 = 1
    INT32 = 2
    UINT32 = 3
    INT64 = 4
    UINT64 = 5
    FLOAT32 = 6
    FLOAT64 = 7


_DTYPE_MAP = {
    jnp.int8.dtype: Datatype.CHAR,
    jnp.uint8.dtype: Datatype.UINT8,
    jnp.int32.dtype: Datatype.INT32,
    jnp.uint32.dtype: Datatype.UINT32,
    jnp.int64.dtype: Datatype.INT64,
    jnp.uint64.dtype: Datatype.UINT64,
    jnp.float32.dtype: Datatype.FLOAT32,
    jnp.float64.dtype: Datatype.FLOAT64,
}


def get_type(dtype) -> Datatype:
    """Map a JAX/numpy dtype to its wire id (reference get_type<T>(),
    comms.hpp:62-89).

    Unsupported dtypes raise :class:`~raft_tpu.core.error.LogicError`
    naming the dtype — the runtime analog of the reference's
    compile-time error for an unmapped ``get_type<T>()`` instantiation.
    """
    dt = jnp.dtype(dtype)
    wire = _DTYPE_MAP.get(dt)
    if wire is None:
        fail("get_type: dtype %s has no communicator wire type "
             "(supported: %s)", dt,
             ", ".join(str(k) for k in _DTYPE_MAP))
    return wire
