"""Host-level communicator: eager collectives, tagged p2p, comm_split.

Reference: ``std_comms`` (cpp/include/raft/comms/std_comms.hpp) plus the
injection helpers (comms/helper.hpp:39-95).  The reference is
multi-controller: one process per GPU, each holding a per-rank ``comms_t``
bootstrapped by an out-of-band NCCL-uid exchange.  JAX on TPU is
**single-controller SPMD**: one host process drives every device, so the
host-level communicator represents the *whole* communicator and verbs
operate on rank-major data (a leading axis of extent ``size``), sharded
or to-be-sharded over the mesh.  Under multi-host JAX
(``jax.distributed.initialize``) the same object spans hosts — the
coordination service plays the NCCL-uid bootstrap role (SURVEY.md §2.2).

Each verb compiles (and caches) a tiny ``shard_map`` program that calls
the in-trace :class:`~raft_tpu.comms.mesh_comms.MeshComms` verb — so the
eager API and the in-trace API cannot diverge.

Tagged p2p (UCX's role, std_comms.hpp:204-298): ``isend``/``irecv``
record host-side descriptors with *dynamic* ranks and tags; ``waitall``
matches them, groups matched pairs by (shape, dtype) — heterogeneous
payloads are legal, each group runs its own programs — layers every
group into disjoint permutations, and executes one ``ppermute`` per
layer over ICI.  Unmatched requests raise — the reference's analog is a
UCX progress-loop timeout abort (std_comms.hpp:234-298).

Zero-copy (docs/ZERO_COPY.md): on the default ``p2p_staging="device"``
path each matched pair is ONE direct device-to-device transfer of the
send buffer onto the receiver's device — the in-memory analog of the
reference's GPU-direct UCX send (std_comms.hpp:204: device pointers
straight into the transport) — and no payload byte ever bounces
through host numpy.  Where per-pair placement is impossible
(multi-process, multi-axis mesh, or an attached fault injector that
must observe the program seam) it degrades to
``p2p_staging="ppermute"``: the rank-major ppermute input is assembled
*on device* (per-rank shard placement or ``jnp.stack`` over shared
:func:`zeros_cached` blanks) and the assembled buffer is **donated**
to the cached program (``donate_argnums``), so the intermediate is
recycled into the output — still zero host-staged bytes.
``p2p_staging="host"`` keeps the historical numpy-staged assembly as a
measurable comparison baseline; the
``raft_tpu_comms_host_staged_bytes`` counter records exactly the bytes
each path bounced through host (the device paths prove 0).

``sync_stream`` reproduces the reference's status-returning health check
(std_comms.hpp:443-475: poll stream + ncclCommGetAsyncError, abort on
failure): it blocks on the given arrays and maps runtime errors to
``Status.ERROR`` and an aborted communicator to ``Status.ABORT``.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.8 (replication checking arg renamed check_rep -> check_vma)
    import inspect

    from jax import shard_map as _shard_map

    _CHECK_ARG = ("check_vma" if "check_vma"
                  in inspect.signature(_shard_map).parameters else "check_rep")
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

    _CHECK_ARG = "check_rep"


def shard_map(fn, **kw):
    kw[_CHECK_ARG] = kw.pop("check_rep")
    return _shard_map(fn, **kw)

from raft_tpu.core import metrics as _metrics
from raft_tpu.core import tracing
from raft_tpu.core.error import (
    CALLER_BUG_ERRORS,
    CommAbortedError,
    CommError,
    CommTimeoutError,
    expects,
)
from raft_tpu.comms.mesh_comms import MeshComms
from raft_tpu.comms.types import Op, Status
from raft_tpu.mr.buffer import zeros_cached as _zeros_cached

_AXIS = "ranks"

# per-row byte floor for the shard-by-shard p2p assembly: below it the
# extra per-rank placement dispatches cost more than the resharding
# they avoid (measured on the 8-device virtual mesh, see
# _assemble_device / bench.py comms_p2p)
_SHARDED_MIN_ROW_BYTES = 1 << 21


def default_mesh(n_devices: Optional[int] = None) -> Mesh:
    """1-D mesh over the first ``n_devices`` local devices (bootstrap
    analog of reference helper.hpp:39 build_comms_nccl_only)."""
    devs = jax.devices()
    if n_devices is not None:
        expects(n_devices <= len(devs),
                "requested %d devices, only %d available", n_devices, len(devs))
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (_AXIS,))  # comms-host-ok: device handles, not payload


def axis_host_group_size(mesh: Mesh, axis: str) -> Optional[int]:
    """Devices-per-host along ``axis`` when hosts are contiguous runs.

    The hierarchical top-k merge (HiCCL's decomposition applied to
    candidate merging, :func:`raft_tpu.spatial.mnmg_knn.mnmg_knn`)
    wants its inner allgather to stay within a host's fast links and
    its ring to cross the slow inter-host hops.  This resolves the
    natural group size from device placement: if the axis's devices
    fall into contiguous equal-length runs of the same
    ``process_index`` and there is more than one process, that run
    length IS the host group.  Returns None when no such structure
    exists (single process — e.g. the virtual CPU mesh — or
    interleaved placement), and the caller falls back to a divisor
    heuristic.
    """
    expects(axis in mesh.axis_names,
            "axis_host_group_size: axis %s not in mesh", axis)
    ax = mesh.axis_names.index(axis)
    # one representative line of devices along the axis (other axes at
    # coordinate 0): host runs along the comms axis are what the merge
    # topology cares about
    sel = tuple(slice(None) if i == ax else 0
                for i in range(mesh.devices.ndim))
    line = list(mesh.devices[sel].ravel())
    procs = [d.process_index for d in line]
    if len(set(procs)) <= 1:
        return None
    run = 1
    while run < len(procs) and procs[run] == procs[0]:
        run += 1
    if len(procs) % run != 0:
        return None
    for base in range(0, len(procs), run):
        chunk = procs[base:base + run]
        if len(set(chunk)) != 1:
            return None
        if base and chunk[0] == procs[base - 1]:
            return None
    return run


class _Request:
    """Pending p2p operation (reference request_t, comms.hpp:46)."""

    __slots__ = ("kind", "rank", "peer", "tag", "data", "result")

    def __init__(self, kind: str, rank: int, peer: int, tag: int, data=None):
        self.kind = kind      # "send" | "recv"
        self.rank = rank      # owning rank
        self.peer = peer      # destination (send) / source (recv)
        self.tag = tag
        self.data = data      # send payload (a row of host/device data)
        self.result = None    # filled for recv by waitall


class HostComms:
    """Whole-communicator handle over a 1-D device mesh axis.

    Data convention: collective inputs/outputs are **rank-major** arrays —
    shape ``(size, ...)`` where row r is rank r's buffer.  ``reduce``
    follows :class:`~raft_tpu.comms.mesh_comms.MeshComms`'s documented
    replicated superset (every row valid); ``gather``/``gatherv`` have
    true root-only semantics (non-root rows are zeros).
    """

    def __init__(self, mesh: Optional[Mesh] = None, axis: str = _AXIS,
                 retry_policy=None, p2p_staging: str = "device"):
        self.mesh = mesh if mesh is not None else default_mesh()
        self.axis = axis
        expects(axis in self.mesh.axis_names, "axis %s not in mesh", axis)
        expects(p2p_staging in ("device", "ppermute", "host"),
                "p2p_staging must be 'device', 'ppermute' or 'host', "
                "got %r", p2p_staging)
        # "device" (default): per-pair direct device-to-device
        # transfers (degrading to the ppermute form where per-pair
        # placement is impossible) — zero host-staged bytes.
        # "ppermute": force the collective form (device-assembled,
        # donated rank-major buffer).  "host" keeps the numpy-staged
        # assembly (the measurable pre-zero-copy baseline; bench.py's
        # comms_p2p rung times all three).
        self.p2p_staging = p2p_staging
        self._mc = MeshComms(axis, self.mesh.shape[axis])
        self._requests: List[_Request] = []
        self._aborted = False
        self._progs: Dict[tuple, object] = {}
        # resolved metric series per verb (generation-invalidated so a
        # registry reset recreates them): verbs are a hot eager path —
        # the family lookup + label check must not run per call
        self._series_cache: Dict[tuple, tuple] = {}
        # optional RetryPolicy (raft_tpu.comms.resilience) applied around
        # every eager verb execution; None = fail on first error, the
        # reference's behavior
        self.retry_policy = retry_policy

    # ------------------------------------------------------------------ #
    # topology
    # ------------------------------------------------------------------ #
    def get_size(self) -> int:
        return self._mc.get_size()

    @property
    def mesh_comms(self) -> MeshComms:
        """The in-trace communicator for use inside user shard_map code."""
        return self._mc

    # ------------------------------------------------------------------ #
    # eager collective execution
    # ------------------------------------------------------------------ #
    def _run(self, key: tuple, fn, *args, donate: bool = False,
             payload_bytes: Optional[int] = None):
        """Policy layer for one eager verb: fail fast if the communicator
        is latched aborted (the ``ncclCommAbort`` contract,
        std_comms.hpp:443-475), apply the :attr:`retry_policy` around the
        execution, and on unrecoverable failure latch the abort so every
        *subsequent* verb fails fast too.  Malformed calls do not poison
        the communicator: ``LogicError`` (RAFT_EXPECTS) and the
        Python-level errors JAX tracing raises for bad shapes / indices
        / dtypes (``TypeError``/``ValueError``/``IndexError``/
        ``KeyError``) propagate unchanged — they are deterministic
        caller bugs, not fabric faults, and retrying or aborting on
        them would kill a healthy communicator for every consumer
        sharing the handle.

        The execution itself lives in :meth:`_execute`, which is also the
        seam :mod:`raft_tpu.comms.faults` patches — injected faults are
        seen (and retried) exactly like real runtime errors.

        Observability (docs/OBSERVABILITY.md): each eager verb reports
        its end-to-end latency — retries and watchdog waits included,
        the caller-observed number —
        (``raft_tpu_comms_verb_seconds{verb=}``) and, on success, the
        payload bytes moved (``raft_tpu_comms_bytes_total{verb=}``),
        on top of PR 1's resilience event counters."""
        verb = key[0]
        self._ensure_alive(verb)
        timer = self._series("timer", "raft_tpu_comms_verb_seconds",
                             verb, "eager verb latency (incl. retries)")
        # payload bytes captured BEFORE execution: a donated send
        # buffer is consumed by the call and its handle deleted.  The
        # p2p path passes its own count (actual send-row bytes, not
        # the rank-major staging buffer with its blank rows) so the
        # counter means the same thing on every staging arm.
        if payload_bytes is None:
            payload_bytes = sum(int(getattr(a, "nbytes", 0))
                                for a in args)
        # donation composes with retries only if the inputs survive a
        # failed attempt; an injected fault at the _execute seam raises
        # before the program consumes anything, but a real mid-program
        # failure may not — so the fast path donates only when no
        # retry could replay the (now consumed) buffer
        donate = donate and self.retry_policy is None
        try:
            with timer.time():
                if self.retry_policy is None:
                    out = self._execute(key, fn, *args, donate=donate)
                else:
                    out = self.retry_policy.call(
                        self._execute, key, fn, *args, verb=verb)
            self._series("counter", "raft_tpu_comms_bytes_total", verb,
                         "payload bytes moved by eager verbs").inc(
                payload_bytes)
            return out
        except CALLER_BUG_ERRORS:
            raise
        except CommAbortedError:
            self.abort()
            raise
        except CommTimeoutError:
            # preserve the documented taxonomy: deadline expiries reach
            # callers as CommTimeoutError, not a generic CommError
            self.abort()
            raise
        except Exception as e:
            self.abort()
            raise CommError(
                "%s failed unrecoverably%s; communicator aborted: %s"
                % (key[0],
                   "" if self.retry_policy is None
                   else " after %d attempts"
                        % (self.retry_policy.max_retries + 1),
                   e)) from e

    def _series(self, kind: str, name: str, verb: str, help: str):
        """Resolve (and memoize per registry generation) one labeled
        series for this communicator's hot verb path."""
        reg = _metrics.default_registry()
        gen = reg.generation
        cached = self._series_cache.get((name, verb))
        if cached is not None and cached[0] == gen:
            return cached[1]
        series = getattr(reg, kind)(
            name, help=help, labels=("verb",)).labels(verb=verb)
        self._series_cache[(name, verb)] = (gen, series)
        return series

    def _execute(self, key: tuple, fn, *args, donate: bool = False):
        """shard_map-execute ``fn(mesh_comms-visible blocks)`` with
        rank-major in/out over the mesh axis.  Programs are cached by
        ``key`` (verb + static parameters) so repeated eager calls reuse
        the compiled executable — jax.jit's own cache keys on function
        identity, which a fresh lambda per call would always miss.

        ``donate=True`` compiles the program with ``donate_argnums=0``:
        the rank-major input buffer is consumed and its storage may be
        recycled for the output.  Only internally-assembled buffers
        (the p2p staging buffer waitall builds) are ever donated —
        collective verbs take CALLER arrays and never donate them
        (docs/ZERO_COPY.md donation contract).  The flag is part of the
        cache key: a donating and a non-donating program for the same
        verb must not alias."""
        verb = key[0]
        key = key + (("donate",) if donate else ())
        prog = self._progs.get(key)
        if prog is None:
            self._series("counter",
                         "raft_tpu_comms_prog_cache_misses_total", verb,
                         "eager-verb program cache misses").inc()
            spec = P(self.axis)
            prog = jax.jit(shard_map(
                fn, mesh=self.mesh, in_specs=spec, out_specs=spec,
                check_rep=False),
                donate_argnums=(0,) if donate else ())
            self._progs[key] = prog
            # the jit is lazy, so the first execution carries the
            # compile: attribute it to compile_seconds (compile +
            # one execute; the AOT split profiled_jit does is not safe
            # across the multi-process shard_map path)
            with self._series("timer", "raft_tpu_comms_compile_seconds",
                              verb, "first-call (compile + execute) "
                                    "time per verb program").time():
                return self._host_view(prog(*args))
        self._series("counter", "raft_tpu_comms_prog_cache_hits_total",
                     verb, "eager-verb program cache hits").inc()
        return self._host_view(prog(*args))

    def _ensure_alive(self, verb: str) -> None:
        """Fail fast once aborted: every verb on a latched communicator
        raises :class:`CommAbortedError` without touching the mesh."""
        if self._aborted:
            raise CommAbortedError(
                "%s on aborted communicator (size=%d); rebuild via "
                "Comms.recover()" % (verb, self.get_size()),
                collect_stack=False)

    def _host_view(self, out):
        """Make an eager-verb result host-readable on every process.

        Single-controller: identity.  Multi-process (the mesh spans
        ``jax.distributed``-initialized hosts, reference ucp_helper /
        multi-node role): the result is a global array whose shards live
        on other hosts, so reading it locally would raise; gather it to
        a replicated host value — the analog of the reference's NCCL
        collectives landing in per-rank local buffers (std_comms.hpp:300:
        every rank owns its recvbuf; here every host gets the full
        rank-major view)."""
        if jax.process_count() == 1:
            return out
        from jax.experimental import multihost_utils

        return multihost_utils.process_allgather(out, tiled=True)

    def _check(self, x) -> jnp.ndarray:
        x = jnp.asarray(x)
        expects(x.ndim >= 1 and x.shape[0] == self.get_size(),
                "rank-major input required: leading axis must be size=%d",
                self.get_size())
        return x

    def allreduce(self, x, op: Op = Op.SUM):
        x = self._check(x)
        return self._run(("allreduce", op),
                         lambda b: self._mc.allreduce(b, op), x)

    def bcast(self, x, root: int = 0):
        x = self._check(x)
        return self._run(("bcast", root), lambda b: self._mc.bcast(b, root), x)

    def reduce(self, x, root: int = 0, op: Op = Op.SUM):
        x = self._check(x)
        return self._run(("reduce", op),
                         lambda b: self._mc.reduce(b, root, op), x)

    def allgather(self, x):
        """Rank-major (size, n, ...) → (size, size*n, ...): every row
        holds the concatenation of all rows."""
        x = self._check(x)
        return self._run(("allgather",),
                         lambda b: self._mc.allgather(b[0])[None], x)

    def allgatherv(self, x, recvcounts: Sequence[int]):
        x = self._check(x)
        return self._run(("allgatherv", tuple(recvcounts)),
                         lambda b: self._mc.allgatherv(b[0], recvcounts)[None],
                         x)

    def gather(self, x, root: int = 0):
        """Rank-major (size, n, ...) → (size, size*n, ...): row ``root``
        holds the concatenation of all rows, every other row is zeros
        (true root-only semantics, reference gather std_comms.hpp:377;
        contrast :meth:`allgather` where every row is populated)."""
        x = self._check(x)
        return self._run(("gather", root),
                         lambda b: self._mc.gather(b[0], root)[None], x)

    def gatherv(self, x, recvcounts: Sequence[int], root: int = 0):
        """Variable-sized :meth:`gather`; root-only validity as there."""
        x = self._check(x)
        return self._run(("gatherv", tuple(recvcounts), root),
                         lambda b: self._mc.gatherv(b[0], recvcounts,
                                                    root)[None], x)

    def reducescatter(self, x, op: Op = Op.SUM):
        """Rank-major (size, size*n, ...) → (size, n, ...)."""
        x = self._check(x)
        return self._run(("reducescatter", op),
                         lambda b: self._mc.reducescatter(b[0], op)[None], x)

    def barrier(self) -> None:
        jax.block_until_ready(self._run(
            ("barrier",), lambda b: b + self._mc.barrier(),
            jnp.zeros((self.get_size(),), jnp.int32)))

    # ------------------------------------------------------------------ #
    # tagged p2p (reference comms.hpp:254-292 isend/irecv/waitall)
    # ------------------------------------------------------------------ #
    def isend(self, buf, rank: int, dest: int, tag: int = 0) -> _Request:
        """Queue a tagged send of ``buf`` from ``rank`` to ``dest``."""
        self._ensure_alive("isend")
        req = _Request("send", rank, dest, tag, jnp.asarray(buf))
        self._requests.append(req)
        return req

    def irecv(self, rank: int, source: int, tag: int = 0) -> _Request:
        """Queue a tagged receive on ``rank`` from ``source``."""
        self._ensure_alive("irecv")
        req = _Request("recv", rank, source, tag)
        self._requests.append(req)
        return req

    def waitall(self, requests: Optional[Sequence[_Request]] = None,
                staging: Optional[str] = None) -> None:
        """Match queued sends/recvs and execute them.  Unmatched
        requests raise, standing in for the reference's UCX
        progress-timeout abort (std_comms.hpp:234-298).

        ``staging`` (default: the communicator's :attr:`p2p_staging`)
        picks the data path, see the module doc:

        - ``"device"`` — zero host-staged bytes.  On a 1-D
          single-controller mesh each matched pair is ONE direct
          device-to-device transfer (``jax.device_put`` of the send
          buffer onto the receiver's device — the in-memory analog of
          the reference handing UCX a device pointer,
          std_comms.hpp:204); mixed shapes/dtypes need no grouping at
          all.  Where per-pair placement is impossible (multi-process,
          multi-axis mesh) — or a fault injector holds the ``_execute``
          seam, which the direct path would bypass — it degrades to the
          ``"ppermute"`` path below, still device-resident.
        - ``"ppermute"`` — the collective form: pairs grouped by
          (shape, dtype) — heterogeneous payloads are legal, each group
          runs its own programs — partitioned into disjoint permutation
          layers (unique source AND destination per layer — a ppermute
          must be a bijection), one ppermute each; the rank-major input
          is assembled on device over shared zero blanks and DONATED to
          the compiled program.  Zero host-staged bytes.
        - ``"host"`` — the historical numpy-staged baseline; counts
          every staged byte into ``raft_tpu_comms_host_staged_bytes``
          (which this method always materializes, so a zero on the
          device paths is a measurement, not a missing series).

        Placement contract (docs/ZERO_COPY.md): each recv result is
        COMMITTED to the receiving rank's device on every staging arm —
        where a real per-rank process would find its recv buffer, and
        why no consolidation copy is paid.  A single-controller caller
        combining results from *different* ranks in one jitted op must
        ``jax.device_put`` them to a common device first (JAX raises
        "incompatible devices" otherwise; the pre-zero-copy behavior of
        returning default-device copies paid a host bounce for the
        convenience).

        Success or failure, the requests this call waited on are
        *consumed* (dequeued) — the reference's timeout abort likewise
        fails its requests.  A stale unmatched request must not poison
        every later ``waitall()`` on the communicator."""
        self._ensure_alive("waitall")
        if staging is None:
            staging = self.p2p_staging
        expects(staging in ("device", "ppermute", "host"),
                "waitall: staging must be 'device', 'ppermute' or "
                "'host', got %r", staging)
        staged_c = self._series(
            "counter", "raft_tpu_comms_host_staged_bytes", "p2p",
            "payload bytes bounced through host numpy on the p2p path "
            "(0 on the device-resident path, docs/ZERO_COPY.md)")
        reqs = list(requests) if requests is not None else list(self._requests)
        try:
            sends = [r for r in reqs if r.kind == "send"]
            recvs = [r for r in reqs if r.kind == "recv"]
            pairs: List[Tuple[_Request, _Request]] = []
            taken: set = set()
            for s in sends:
                match = next(
                    (r for r in recvs
                     if r.tag == s.tag and r.peer == s.rank
                     and s.peer == r.rank
                     and r.result is None and id(r) not in taken),
                    None)
                expects(match is not None,
                        "waitall: unmatched send rank=%d->%d tag=%d",
                        s.rank, s.peer, s.tag)
                taken.add(id(match))
                pairs.append((s, match))
            leftover = [r for r in recvs
                        if id(r) not in taken and r.result is None]
            expects(not leftover,
                    "waitall: %d unmatched irecv(s)", len(leftover))

            devs = self._rank_devices()
            if (staging == "device" and devs is not None
                    and not self._execute_is_patched()):
                self._direct_p2p(pairs, devs)
                return

            # group by payload (shape, dtype): ppermute operands are
            # homogeneous, but the *request set* need not be — this is
            # what drops the old uniform-shape restriction
            groups: Dict[tuple, List[Tuple[_Request, _Request]]] = {}
            for s, r in pairs:
                gkey = (tuple(s.data.shape), jnp.dtype(s.data.dtype).name)
                groups.setdefault(gkey, []).append((s, r))

            size = self.get_size()
            for (shape, dtype_name), gpairs in groups.items():
                dtype = jnp.dtype(dtype_name)
                # greedy layering within the group: each layer is a
                # bijection (src/dst unique)
                layers: List[List[Tuple[_Request, _Request]]] = []
                for s, r in gpairs:
                    placed = False
                    for layer in layers:
                        if all(s.rank != ls.rank and s.peer != ls.peer
                               for ls, _ in layer):
                            layer.append((s, r))
                            placed = True
                            break
                    if not placed:
                        layers.append([(s, r)])

                for layer in layers:
                    perm = [(s.rank, s.peer) for s, _ in layer]
                    if staging in ("device", "ppermute"):
                        buf = self._assemble_device(layer, shape, dtype)
                        donate = True
                    else:
                        buf_np = np.zeros((size,) + shape, dtype)
                        for s, _ in layer:
                            # comms-host-ok: counted staging baseline
                            buf_np[s.rank] = np.asarray(s.data)  # comms-host-ok: baseline
                        staged_c.inc(int(buf_np.nbytes))
                        buf = jnp.asarray(buf_np)
                        donate = False
                    out = self._run(
                        ("p2p", tuple(perm)),
                        lambda b, perm=perm: self._mc.device_sendrecv(
                            b, perm),
                        buf, donate=donate,
                        payload_bytes=sum(int(s.data.nbytes)
                                          for s, _ in layer))
                    rows = self._result_rows(out)
                    for s, r in layer:
                        r.result = (rows[r.rank] if rows is not None
                                    else out[r.rank])
        finally:
            done = {id(r) for r in reqs}
            self._requests = [r for r in self._requests
                              if id(r) not in done]

    def _rank_devices(self):
        """Rank-ordered device list when per-rank placement is legal
        (single-controller, 1-D mesh); None otherwise."""
        if jax.process_count() != 1 or len(self.mesh.axis_names) != 1:
            return None
        return list(self.mesh.devices.ravel())

    def _execute_is_patched(self) -> bool:
        """True while a :mod:`raft_tpu.comms.faults` injector (or any
        monkeypatch) holds the ``_execute`` seam.  The direct p2p path
        never reaches ``_execute``, so taking it would silently walk
        around an attached fault harness — fall back to the program
        path instead, where every fault stays observable."""
        inst = self.__dict__.get("_execute")
        return (inst is not None
                and getattr(inst, "__func__", None)
                is not HostComms._execute)

    def _direct_p2p(self, pairs, devs) -> None:
        """The per-pair zero-copy fast path: each matched (send, recv)
        is one device-to-device ``jax.device_put`` of the send buffer
        onto the receiver's rank device — no staging buffer, no
        collective, no host bounce (the reference's GPU-direct UCX tag
        send, std_comms.hpp:204).  Mixed shapes/dtypes are trivially
        fine: pairs are independent transfers.  The send buffer is NOT
        consumed (nothing is donated on this path — there is no
        intermediate to recycle)."""
        timer = self._series("timer", "raft_tpu_comms_verb_seconds",
                             "p2p", "eager verb latency (incl. retries)")
        payload = sum(int(getattr(s.data, "nbytes", 0))
                      for s, _ in pairs)
        # same failure taxonomy as _run (PR 1 contract): an
        # unrecoverable transfer failure — possibly mid-ring, earlier
        # pairs already moved — latches the abort and surfaces as
        # CommError, never a raw backend exception
        try:
            with timer.time():
                for s, r in pairs:
                    if self.retry_policy is None:
                        r.result = jax.device_put(s.data, devs[r.rank])
                    else:
                        r.result = self.retry_policy.call(
                            jax.device_put, s.data, devs[r.rank],
                            verb="p2p")
        except CALLER_BUG_ERRORS:
            raise
        except (CommAbortedError, CommTimeoutError):
            self.abort()
            raise
        except Exception as e:
            self.abort()
            raise CommError(
                "p2p direct transfer failed unrecoverably%s; "
                "communicator aborted: %s"
                % ("" if self.retry_policy is None
                   else " after %d attempts"
                        % (self.retry_policy.max_retries + 1),
                   e)) from e
        self._series("counter", "raft_tpu_comms_bytes_total", "p2p",
                     "payload bytes moved by eager verbs").inc(payload)

    def _assemble_device(self, layer, shape, dtype) -> jnp.ndarray:
        """Build the rank-major p2p input ON DEVICE — zero host-staged
        bytes either way:

        - wide rows (>= :data:`_SHARDED_MIN_ROW_BYTES`) on a 1-D
          single-controller mesh: each send row is placed directly on
          its rank's device and the global array is assembled
          shard-by-shard (``make_array_from_single_device_arrays``) —
          the program consumes it with NO resharding step, the
          in-memory analog of the reference handing UCX a device
          pointer.  Non-sending ranks get a shared
          :func:`zeros_cached` blank.
        - narrow rows (or multi-process / multi-axis meshes): one
          ``jnp.stack`` over the rows — per-rank placement costs more
          dispatches than it saves below the threshold (measured on the
          8-device virtual mesh; bench.py's ``comms_p2p`` rung).

        Every row passes through an eager ``[None]``-reshape /
        ``stack`` copy, so the assembled buffer owns FRESH storage —
        safe to donate without consuming caller arrays
        (docs/ZERO_COPY.md)."""
        size = self.get_size()
        by_rank = {s.rank: s.data for s, _ in layer}
        devs = self._rank_devices()
        row_bytes = (int(np.prod(shape, dtype=np.int64))
                     * jnp.dtype(dtype).itemsize)
        if devs is None or row_bytes < _SHARDED_MIN_ROW_BYTES:
            blank = _zeros_cached(shape, dtype)
            rows = [by_rank.get(rk, blank) for rk in range(size)]
            # COMMITTED rows (e.g. a prior round's direct-p2p results,
            # each living on its own device) break the naive stack
            # twice over: jnp.stack over distinct committed devices
            # raises "incompatible devices", and even a same-device
            # committed stack makes the shard_map program refuse to
            # reshard its input.  Normalize onto one device, then
            # place rank-major over the mesh — all device-to-device
            # moves, the host-staged counter stays untouched.
            placed = {i: frozenset(r.sharding.device_set)
                      for i, r in enumerate(rows)
                      if getattr(r, "committed", False)}
            if len(set(placed.values())) > 1:
                tgt = min((d for ds in placed.values() for d in ds),
                          key=lambda d: d.id)
                for i, ds in placed.items():
                    if ds != frozenset((tgt,)):
                        rows[i] = jax.device_put(rows[i], tgt)
            buf = jnp.stack(rows)
            if placed:
                buf = jax.device_put(
                    buf, NamedSharding(self.mesh, P(self.axis)))
            return buf
        shards = []
        for rk in range(size):
            data = by_rank.get(rk)
            row = (data if data is not None
                   else _zeros_cached(shape, dtype))[None]
            shards.append(jax.device_put(row, devs[rk]))
        return jax.make_array_from_single_device_arrays(
            (size,) + shape, NamedSharding(self.mesh, P(self.axis)),
            shards)

    def _result_rows(self, out):
        """Per-rank result rows as shard-local views ({rank: row}), or
        None when the output is not one-row-per-rank shard-addressable
        (multi-process, host-view numpy, odd layouts) and the caller
        must fall back to global indexing.  Indexing a sharded global
        array row-by-row gathers cross-device per slice — the shard
        view is the zero-copy read."""
        shards = getattr(out, "addressable_shards", None)
        if not shards or len(shards) != out.shape[0]:
            return None
        rows = {}
        for sh in shards:
            idx = sh.index[0] if sh.index else None
            if (not isinstance(idx, slice) or idx.start is None
                    or (idx.stop or 0) - idx.start != 1):
                return None
            rows[idx.start] = sh.data[0]
        return rows if len(rows) == out.shape[0] else None

    # device_send/recv parity shims: in the reference these are the
    # stream-ordered NCCL p2p verbs (comms.hpp:508,522); here they share
    # the tagged machinery with a reserved tag.
    _DEVICE_TAG = -1

    def device_send(self, buf, rank: int, dest: int) -> _Request:
        return self.isend(buf, rank, dest, tag=self._DEVICE_TAG)

    def device_recv(self, rank: int, source: int) -> _Request:
        return self.irecv(rank, source, tag=self._DEVICE_TAG)

    def device_sendrecv(self, x, perm: Sequence[Tuple[int, int]]):
        """Eager static-permutation exchange (reference comms.hpp:522)."""
        x = self._check(x)
        return self._run(("sendrecv", tuple(perm)),
                         lambda b: self._mc.device_sendrecv(b, list(perm)), x)

    def device_multicast_sendrecv(self, x, sends: Sequence[Tuple[int, int]]):
        x = self._check(x)
        return self._run(
            ("multicast", tuple(sends)),
            lambda b: self._mc.device_multicast_sendrecv(b, list(sends)), x)

    # ------------------------------------------------------------------ #
    # comm_split (reference comms.hpp:96 / std_comms.hpp:115-177)
    # ------------------------------------------------------------------ #
    def comm_split(self, colors: Sequence[int], keys: Optional[Sequence[int]] = None
                   ) -> Dict[int, "HostComms"]:
        """Partition the communicator by color; within a color, ranks are
        ordered by key (reference comm_split semantics — there each rank
        passes its own (color, key); single-controller passes the full
        vectors).  Returns {color: sub-communicator}.  Children inherit
        the parent's retry policy; splitting a latched-aborted
        communicator fails fast (ncclCommSplit on an aborted comm
        errors the same way)."""
        self._ensure_alive("comm_split")
        size = self.get_size()
        expects(len(colors) == size, "comm_split: need one color per rank")
        keys = list(keys) if keys is not None else list(range(size))
        expects(len(keys) == size, "comm_split: need one key per rank")
        devs = list(self.mesh.devices.ravel())
        out: Dict[int, HostComms] = {}
        for color in sorted(set(colors)):
            members = sorted(
                (r for r in range(size) if colors[r] == color),
                key=lambda r: (keys[r], r))
            sub_mesh = Mesh(
                np.asarray([devs[r] for r in members]),  # comms-host-ok: device handles
                (self.axis,))
            out[color] = HostComms(sub_mesh, self.axis,
                                   retry_policy=self.retry_policy,
                                   p2p_staging=self.p2p_staging)
        return out

    # ------------------------------------------------------------------ #
    # failure surfacing (reference sync_stream, std_comms.hpp:443-475)
    # ------------------------------------------------------------------ #
    @property
    def aborted(self) -> bool:
        """Whether the communicator has latched aborted (permanent;
        every verb on it fails fast with :class:`CommAbortedError`)."""
        return self._aborted

    def abort(self) -> None:
        """Latch the communicator unusable (reference ncclCommAbort,
        exposed to Python via nccl.pyx:173).  Idempotent; counted once."""
        if not self._aborted:
            self._aborted = True
            tracing.counter_inc("comms.abort")

    def sync_stream(self, *arrays) -> Status:
        """Block until the given in-flight arrays complete; map failures
        to a status instead of raising."""
        if self._aborted:
            return Status.ABORT
        try:
            jax.block_until_ready(arrays)
            return Status.SUCCESS
        except Exception:
            self.abort()
            return Status.ERROR
