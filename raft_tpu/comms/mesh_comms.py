"""In-trace communicator: the collective verbs inside ``shard_map``.

Reference: ``comms_t`` / ``comms_iface`` (cpp/include/raft/comms/
comms.hpp:91-609) and its NCCL implementation ``std_comms``
(comms/std_comms.hpp:300-441).  The reference enqueues NCCL collectives
on a CUDA stream; the TPU-native analog issues **XLA collectives over
ICI** from inside an SPMD region (``shard_map``/``pjit``), where the
compiler schedules them onto the interconnect directly — there is no
NCCL-style library call at runtime, the collective *is* part of the
compiled program.

``MeshComms`` is therefore a lightweight, trace-time object: it captures
the mesh axis name(s) and translates each verb to its ``jax.lax``
collective.  Rank-dependent control flow must be expressed with masks or
static permutation lists (SPMD traces once for all ranks) — this is the
idiomatic-TPU replacement for the reference's per-rank branching, and the
reason p2p verbs here take *static* rank arguments or permutation lists
(``ppermute`` riding ICI takes UCX's role; reference std_comms.hpp:204).

Verb-for-verb parity map (reference → here):

- get_size/get_rank        → axis size / ``lax.axis_index``
- allreduce                → ``lax.psum/pmax/pmin`` (PROD via all_gather)
- bcast(root)              → all_gather + static row pick
- reduce(root)             → allreduce (result replicated — a superset of
                             "defined on root only"; documented)
- allgather / allgatherv   → ``lax.all_gather`` (+ static per-rank counts,
                             mirroring the per-root-broadcast semantics of
                             std_comms.hpp:355-375)
- gather(v)(root)          → all_gather + non-root rows masked to zero
                             (true root-only validity, unlike reduce)
- reducescatter            → ``lax.psum_scatter``
- device_sendrecv          → ``lax.ppermute`` with a static pair list
- device_multicast_sendrecv→ sum of ppermutes (one per fan-out step)
- barrier                  → psum of a unit scalar (creates the
                             cross-replica dependency)
- comm_split / sync_stream → host-level concepts: see
                             :mod:`raft_tpu.comms.host_comms`
"""

from __future__ import annotations

from typing import Sequence, Tuple, Union

import numpy as np
import jax.numpy as jnp
from jax import lax

from raft_tpu.core.error import expects, fail
from raft_tpu.comms.types import Op

AxisName = Union[str, Tuple[str, ...]]


class MeshComms:
    """Collective verbs over a named mesh axis, usable inside shard_map.

    Parameters
    ----------
    axis:
        Mesh axis name (or tuple of names) the collectives run over.
    axis_size:
        Static number of ranks along ``axis``; required for verbs that
        need a Python-int size (bcast row pick, allgatherv assembly).
    """

    def __init__(self, axis: AxisName, axis_size: int):
        self.axis = axis
        self._size = int(axis_size)

    # ------------------------------------------------------------------ #
    # topology (reference comms.hpp:206-216)
    # ------------------------------------------------------------------ #
    def get_size(self) -> int:
        return self._size

    def get_rank(self):
        """Traced rank of the executing shard (reference get_rank)."""
        return lax.axis_index(self.axis)

    # ------------------------------------------------------------------ #
    # collectives (reference comms.hpp:294-437 → std_comms.hpp:300-441)
    # ------------------------------------------------------------------ #
    def allreduce(self, x, op: Op = Op.SUM):
        """Element-wise cross-rank reduction (reference allreduce →
        ncclAllReduce, std_comms.hpp:300)."""
        if op == Op.SUM:
            return lax.psum(x, self.axis)
        if op == Op.MAX:
            return lax.pmax(x, self.axis)
        if op == Op.MIN:
            return lax.pmin(x, self.axis)
        if op == Op.PROD:
            return jnp.prod(lax.all_gather(x, self.axis), axis=0)
        fail("allreduce: unknown reduction op %s", op)

    def bcast(self, x, root: int = 0):
        """Every rank receives root's value (reference bcast,
        comms.hpp:314/331 → ncclBroadcast)."""
        return lax.all_gather(x, self.axis)[root]

    def reduce(self, x, root: int = 0, op: Op = Op.SUM):
        """Reduction "to root" (reference reduce → ncclReduce,
        std_comms.hpp:327).  SPMD programs have no rank-private storage,
        so the result is replicated on every rank — a strict superset of
        the reference's root-only guarantee."""
        del root
        return self.allreduce(x, op)

    def allgather(self, x):
        """Concatenate every rank's block along a new leading axis then
        flatten it into axis 0 (reference allgather → ncclAllGather,
        std_comms.hpp:344: recvbuf is rank-major contiguous)."""
        return lax.all_gather(x, self.axis, tiled=True)

    def allgatherv(self, x, recvcounts: Sequence[int]):
        """Variable-sized allgather (reference allgatherv,
        std_comms.hpp:355-375, implemented there as one broadcast per
        root per arXiv:1812.05964).  ``x`` is this rank's block padded to
        the max count on axis 0; ``recvcounts`` are the static true
        per-rank counts.  Returns the tight concatenation."""
        expects(len(recvcounts) == self._size,
                "allgatherv: need one recvcount per rank")
        parts = lax.all_gather(x, self.axis)  # (size, max_count, ...)
        return jnp.concatenate(
            [parts[r, : recvcounts[r]] for r in range(self._size)], axis=0)

    def gather(self, x, root: int = 0):
        """Gather blocks "to root" (reference gather, std_comms.hpp:377 —
        grouped ncclSend/Recv).  Non-root ranks get ZEROS — the in-trace
        encoding of the reference's "recvbuf valid on root only"
        contract: SPMD has no rank-varying shapes and XLA's ICI lowering
        has no gather-to-root primitive, so the transport is all_gather
        and the root-only contract is enforced by masking (this is what
        makes gather distinguishable from allgather, unlike
        :meth:`reduce`'s documented replicated superset)."""
        out = self.allgather(x)
        is_root = lax.axis_index(self.axis) == root
        return jnp.where(is_root, out, jnp.zeros_like(out))

    def gatherv(self, x, recvcounts: Sequence[int], root: int = 0):
        """Variable-sized gather (reference gatherv, std_comms.hpp:403).
        Root-only validity enforced by masking, as :meth:`gather`."""
        out = self.allgatherv(x, recvcounts)
        is_root = lax.axis_index(self.axis) == root
        return jnp.where(is_root, out, jnp.zeros_like(out))

    def reducescatter(self, x, op: Op = Op.SUM):
        """Reduce then scatter equal blocks (reference reducescatter →
        ncclReduceScatter, std_comms.hpp:427).  ``x`` is the full-size
        input on every rank; rank r receives block r of the reduction."""
        if op == Op.SUM:
            return lax.psum_scatter(x, self.axis, tiled=True)
        n = x.shape[0]
        expects(n % self._size == 0,
                "reducescatter: axis-0 extent %d not divisible by %d ranks",
                n, self._size)
        full = self.allreduce(x, op)
        block = n // self._size
        rank = lax.axis_index(self.axis)
        return lax.dynamic_slice_in_dim(full, rank * block, block, axis=0)

    # ------------------------------------------------------------------ #
    # device p2p (reference comms.hpp:508-607 → UCX/NCCL p2p)
    # ------------------------------------------------------------------ #
    def device_sendrecv(self, x, perm: Sequence[Tuple[int, int]]):
        """Exchange blocks along a static (src, dst) permutation
        (reference device_sendrecv, comms.hpp:522: paired ncclSend/Recv).
        Ranks not named as a destination receive zeros."""
        return lax.ppermute(x, self.axis, list(perm))

    def device_multicast_sendrecv(self, x,
                                  sends: Sequence[Tuple[int, int]]):
        """One-to-many / many-to-one exchange (reference
        device_multicast_sendrecv, comms.hpp:560).  ``sends`` is a static
        (src, dst) multi-set; receives from multiple sources are summed.

        ppermute cannot express fan-out (it requires a bijection), so the
        multicast compiles to one all_gather plus a static routing sum —
        a single ICI collective regardless of fan-out degree.  The sum
        runs in the payload's own dtype (no float round-trip: id/index
        payloads above 2^24 would lose bits in a float32 matmul)."""
        parts = lax.all_gather(x, self.axis)        # (size, ...)
        rank = lax.axis_index(self.axis)
        out = jnp.zeros_like(x)
        for s, d in sends:
            out = out + jnp.where(rank == d, parts[s], jnp.zeros_like(x))
        return out

    def barrier(self):
        """Cross-rank dependency point (reference barrier, comms.hpp:244:
        allreduce on a dummy scalar and wait)."""
        return lax.psum(jnp.ones((), jnp.int32), self.axis)
