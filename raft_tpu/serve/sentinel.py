"""Anomaly sentinel: a resident watcher over the serving stack's vitals.

Every prior observability layer *records* — metrics, spans, flight
events, SLO burn — but something must *notice*: the slow-burn
conditions nobody polls for (a creeping p99, a WAL that stopped
truncating, a snapshot that stopped landing, scrub corruption, a tile
working set outrunning its budget) sit in the registry until an
operator happens to look.  The :class:`AnomalySentinel` closes that
loop in-process (docs/OBSERVABILITY.md "Ops plane"):

- **Rolling-baseline watchers.**  Each rule reads an already-recorded
  signal (registry timers/gauges, per-service SLO snapshots, persist
  stats) and compares it against either a fixed threshold knob or a
  rolling EWMA baseline that is FROZEN while the rule is breached —
  a fault cannot teach the baseline that slow is normal.
- **On breach** (inactive → active transition, not per tick): a typed
  ``anomaly`` flight event, ``raft_tpu_anomaly_total{rule=}`` bump,
  ``raft_tpu_anomaly_active{rule=,service=}`` flipped to 1, and an
  automatic black-box dump (reason ``anomaly_<rule>``) — the tape of
  the seconds leading into the breach, including the breaching
  batches' lifecycle events.  On clearance: an ``anomaly_cleared``
  event and the active gauge back to 0.
- **Degraded flag.**  :meth:`degraded` / :meth:`status` feed the ops
  plane's ``/healthz`` — a scraper sees ``degraded: true`` with the
  active rule list without knowing any raft_tpu internals.

Rules (knobs in :mod:`raft_tpu.config`, all ``ops_sentinel_*``):

========================  ============================================
``exec_latency``          windowed MEAN exec latency (exact, from
                          the timer's lifetime count/total deltas
                          between ticks — a reservoir p99 full of
                          healthy history would need dozens of slow
                          batches to move; the window mean trips on
                          the first one) > ``latency_factor`` ×
                          rolling baseline (min ``min_samples``
                          lifetime batches before judging).  Watched
                          per service AND per (service, rung) — one
                          watch per shape bucket from the
                          ``raft_tpu_serve_exec_rung_seconds``
                          family, scoped ``<service>:r<rung>`` — so
                          a regression confined to one bucket
                          cannot hide inside a healthy traffic mix
``queue_depth``           queued requests > ``queue_frac`` × the
                          service's admission cap
``slo_burn``              any tenant's shortest-window burn rate >
                          ``burn`` (error budget vanishing)
``wal_depth``             un-snapshotted WAL records > ``wal_records``
                          (snapshots stopped containing the journal)
``snapshot_age``          persist layer reports a stale snapshot
                          (dirty state outliving 3 intervals)
``scrub_corruption``      unrepaired checksum corruption detected
``tile_stall``            exposed-stall fraction of H2D time over the
                          last window > ``stall_frac`` (the prefetch
                          stopped hiding transfers)
``worker_dead``           fleet only (the watched object exposes
                          ``fleet_stats``): any registered worker is
                          lease-evicted and not yet rejoined — the
                          fleet is serving degraded
``rejoin_lag``            fleet only: the last crash-rejoin's WAL
                          replay ran slower than
                          ``rejoin_ms_per_record`` per replayed
                          record — recovery time is outgrowing the
                          journal, snapshot cadence needs tightening.
                          Clears once the rejoin ages past
                          ``rejoin_hold_s`` (an incident, not a
                          latched state)
========================  ============================================

The sentinel is driven two ways, both cheap: every
:class:`~raft_tpu.serve.scheduler.ServeWorker` pokes it on the
existing maintenance seam (between batch cycles — a loaded serving
process notices within one batch), and the ops plane runs a fallback
ticker thread so an *idle* process still notices (a wedged worker
cannot poke).  :meth:`tick` rate-limits itself to
``ops_sentinel_interval_s``, so redundant drivers cost one clock read.
Rule evaluation never raises — failures feed
``raft_tpu_ops_sentinel_errors_total`` (a broken watcher must not
take down the worker loop it rides).

No jax anywhere in this module: everything it reads is host-side
Python state, so it falls under the same static no-jax ban as the ops
handlers (``ci/style_check.py`` ``ops-jax-ban``).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from raft_tpu import config
from raft_tpu.core import flight
from raft_tpu.core import metrics as _metrics

__all__ = ["AnomalySentinel", "register", "unregister", "poke"]

# EWMA weight for rolling baselines: slow enough that a few noisy
# ticks cannot drag the baseline up to a genuine regression
_BASELINE_ALPHA = 0.2


def _counter(name: str, help: str, **labels):
    return _metrics.default_registry().counter(
        name, help=help, labels=tuple(sorted(labels))).labels(**labels)


def _gauge(name: str, help: str, **labels):
    return _metrics.default_registry().gauge(
        name, help=help, labels=tuple(sorted(labels))).labels(**labels)


class _Watch:
    """One (rule, service) watcher's state."""

    __slots__ = ("baseline", "active", "since", "value", "threshold")

    def __init__(self):
        self.baseline: Optional[float] = None
        self.active = False
        self.since: Optional[float] = None
        self.value = 0.0
        self.threshold = 0.0


class AnomalySentinel:
    """Module-doc watcher.  ``services_fn`` returns the live
    ``{name: service}`` map each tick (a session's ``.services`` or a
    static dict) — services appearing/disappearing between ticks is
    normal (tests rebuild them freely)."""

    def __init__(self, services_fn: Callable[[], Dict[str, object]], *,
                 interval_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        self._services_fn = services_fn
        self._interval = (config.get_float("ops_sentinel_interval_s")
                          if interval_s is None else float(interval_s))
        self._latency_factor = config.get_float(
            "ops_sentinel_latency_factor")
        self._min_samples = config.get_int("ops_sentinel_min_samples")
        self._queue_frac = config.get_float("ops_sentinel_queue_frac")
        self._burn = config.get_float("ops_sentinel_burn")
        self._wal_records = config.get_int("ops_sentinel_wal_records")
        self._stall_frac = config.get_float("ops_sentinel_stall_frac")
        self._rejoin_ms = config.get_float(
            "ops_sentinel_rejoin_ms_per_record")
        self._rejoin_hold = config.get_float(
            "ops_sentinel_rejoin_hold_s")
        self._clock = clock
        self._lock = threading.Lock()
        self._watches: Dict[tuple, _Watch] = {}
        self._last_tick: Optional[float] = None
        self._ticks = 0
        # per-service (count, total) / h2d cursors for window deltas
        self._exec_cursor: Dict[str, tuple] = {}
        self._h2d_cursor: Dict[str, tuple] = {}
        # per-(service, worker) cursors over the router's per-hop
        # network timer — the cross-hop rule's window deltas
        self._net_cursor: Dict[str, tuple] = {}

    # ------------------------------------------------------------------ #
    # driving
    # ------------------------------------------------------------------ #
    def tick(self, force: bool = False) -> bool:
        """Evaluate every rule once; rate-limited to the configured
        interval unless ``force``.  Returns whether an evaluation ran.
        Never raises (module doc)."""
        now = self._clock()
        with self._lock:
            if (not force and self._last_tick is not None
                    and now - self._last_tick < self._interval):
                return False
            self._last_tick = now
            self._ticks += 1
        try:
            services = dict(self._services_fn() or {})
        except Exception:
            _counter("raft_tpu_ops_sentinel_errors_total",
                     "sentinel rule-evaluation failures").inc()
            return True
        for name, svc in services.items():
            for rule_fn in (self._rule_latency, self._rule_queue,
                            self._rule_slo_burn, self._rule_persist,
                            self._rule_tile_stall, self._rule_fleet):
                try:
                    rule_fn(name, svc, now)
                except Exception:
                    _counter("raft_tpu_ops_sentinel_errors_total",
                             "sentinel rule-evaluation failures").inc()
        return True

    # ------------------------------------------------------------------ #
    # rule plumbing
    # ------------------------------------------------------------------ #
    def _watch(self, rule: str, service: str) -> _Watch:
        key = (rule, service)
        with self._lock:
            w = self._watches.get(key)
            if w is None:
                w = self._watches[key] = _Watch()
            return w

    def _judge(self, rule: str, service: str, value: float,
               threshold: float, now: float,
               breach: Optional[bool] = None) -> None:
        """Shared breach/clear state machine: fires the transition
        side effects exactly once per edge (module doc)."""
        w = self._watch(rule, service)
        w.value = value
        w.threshold = threshold
        if breach is None:
            breach = value > threshold
        if breach and not w.active:
            w.active = True
            w.since = now
            _counter("raft_tpu_anomaly_total",
                     "anomaly-sentinel rule breaches (inactive->"
                     "active transitions)", rule=rule).inc()
            _gauge("raft_tpu_anomaly_active",
                   "1 while the sentinel rule is breached for the "
                   "service", rule=rule, service=service).set(1)
            flight.record("anomaly", service=service, rule=rule,
                          value=round(float(value), 6),
                          threshold=round(float(threshold), 6))
            # the postmortem tape, captured at the moment of noticing:
            # the ring still holds the breaching batches' lifecycle
            flight.default_recorder().blackbox(
                "anomaly_%s" % rule, service=service)
        elif not breach and w.active:
            w.active = False
            w.since = None
            _gauge("raft_tpu_anomaly_active",
                   "1 while the sentinel rule is breached for the "
                   "service", rule=rule, service=service).set(0)
            flight.record("anomaly_cleared", service=service,
                          rule=rule, value=round(float(value), 6))

    def _judge_baseline(self, rule: str, service: str, value: float,
                        factor: float, now: float,
                        judge: bool = True) -> None:
        """Baseline-relative judgement: compare ``value`` against
        ``factor`` × the PRE-update baseline (judging against a
        baseline that already absorbed this window's spike would
        raise the bar exactly when it must not), then EWMA-update the
        baseline only while not breached — a fault cannot teach the
        baseline that slow is normal.  ``judge=False`` warms the
        baseline without judging (cold start)."""
        w = self._watch(rule, service)
        base = value if w.baseline is None else w.baseline
        if judge:
            self._judge(rule, service, value,
                        factor * max(base, 1e-9), now)
        if w.baseline is None:
            w.baseline = value
        elif not w.active:
            w.baseline += _BASELINE_ALPHA * (value - w.baseline)

    # ------------------------------------------------------------------ #
    # rules
    # ------------------------------------------------------------------ #
    def _series(self, metric: str, service: str,
                label: str = "service"):
        fam = _metrics.default_registry().get(metric)
        if fam is None:
            return None
        for labels, series in fam.series():
            if labels.get(label) == service:
                return series
        return None

    def _rule_latency(self, name: str, svc, now: float) -> None:
        # rungs first: their cursors must warm even on ticks where the
        # service-level cursor has nothing to judge (early returns)
        self._rule_latency_rungs(name, now)
        s = self._series("raft_tpu_serve_exec_seconds", name)
        if s is None:
            return
        count, total = int(s.count), float(s.total)
        prev = self._exec_cursor.get(name)
        self._exec_cursor[name] = (count, total)
        if prev is None or count <= prev[0]:
            return  # first sighting / quiet window: nothing to judge
        window_mean = (total - prev[1]) / (count - prev[0])
        # cold start warms the baseline without judging — the first
        # min_samples batches of a fresh service are allowed to be
        # weird (allocator, thread pools) without tripping alarms
        self._judge_baseline("exec_latency", name, window_mean,
                             self._latency_factor, now,
                             judge=count >= self._min_samples)

    def _rule_latency_rungs(self, name: str, now: float) -> None:
        """Per-(service, rung) exec_latency watches (module doc): each
        shape bucket gets its own cursor, baseline, and watch scoped
        ``<service>:r<rung>`` so a one-bucket regression is judged
        against that bucket's own history, not the mixed mean."""
        fam = _metrics.default_registry().get(
            "raft_tpu_serve_exec_rung_seconds")
        if fam is None:
            return
        for labels, s in fam.series():
            if labels.get("service") != name:
                continue
            scope = "%s:r%s" % (name, labels.get("rung"))
            count, total = int(s.count), float(s.total)
            prev = self._exec_cursor.get(scope)
            self._exec_cursor[scope] = (count, total)
            if prev is None or count <= prev[0]:
                continue
            window_mean = (total - prev[1]) / (count - prev[0])
            self._judge_baseline("exec_latency", scope, window_mean,
                                 self._latency_factor, now,
                                 judge=count >= self._min_samples)

    def _rule_queue(self, name: str, svc, now: float) -> None:
        batcher = getattr(svc, "batcher", None)
        cap = getattr(batcher, "queue_cap", None)
        if not cap:
            return
        depth = float(batcher.depth())
        self._judge("queue_depth", name, depth,
                    self._queue_frac * float(cap), now)

    def _rule_slo_burn(self, name: str, svc, now: float) -> None:
        slo = getattr(svc, "slo", None)
        if slo is None:
            return
        snap = slo.snapshot(publish=False)
        worst = 0.0
        for t in snap.get("tenants", {}).values():
            if t.get("total", 0) < self._min_samples:
                continue
            burns = t.get("burn", {})
            if burns:
                # shortest window = the fast-burn alarm; the snapshot
                # keys are "%gs" strings, sort numerically
                shortest = min(burns, key=lambda k: float(k[:-1]))
                worst = max(worst, burns[shortest])
        self._judge("slo_burn", name, worst, self._burn, now)

    def _rule_persist(self, name: str, svc, now: float) -> None:
        persist = getattr(svc, "_persist", None)
        if persist is None:
            return
        st = persist.stats()
        self._judge("wal_depth", name,
                    float(st.get("wal_records", 0)),
                    float(self._wal_records), now)
        self._judge("snapshot_age", name,
                    float(st.get("snapshot_age_s") or 0.0),
                    3.0 * float(st.get("snapshot_interval_s", 0.0)),
                    now, breach=bool(st.get("snapshot_stale")))
        self._judge("scrub_corruption", name,
                    1.0 if st.get("corruption_detected") else 0.0,
                    0.0, now,
                    breach=bool(st.get("corruption_detected")))

    def _rule_tile_stall(self, name: str, svc, now: float) -> None:
        h2d = self._series("raft_tpu_h2d_seconds", name, label="pool")
        stall = self._series("raft_tpu_h2d_stall_seconds", name,
                             label="pool")
        if h2d is None or stall is None:
            return
        h2d_t, stall_t = float(h2d.total), float(stall.total)
        prev = self._h2d_cursor.get(name)
        self._h2d_cursor[name] = (h2d_t, stall_t)
        if prev is None:
            # first sighting: the lifetime totals include warmup's
            # inherently-unhidden tile streams — judging them would
            # trip tile_stall on a healthy freshly-watched service
            # (the exec_latency cursor rule, applied here)
            return
        dh = h2d_t - prev[0]
        if dh <= 1e-6:
            return  # no transfers this window
        frac = max(0.0, stall_t - prev[1]) / dh
        self._judge("tile_stall", name, frac, self._stall_frac, now)

    def _rule_fleet(self, name: str, svc, now: float) -> None:
        stats_fn = getattr(svc, "fleet_stats", None)
        if stats_fn is None:
            return
        st = stats_fn()
        # worker_dead: edge-fires on the first eviction, clears when
        # the worker rejoins (or is replaced) — the degraded window
        self._judge("worker_dead", name,
                    float(st.get("workers_dead", 0)), 0.0, now)
        rj = st.get("last_rejoin") or {}
        replayed = int(rj.get("replayed_records") or 0)
        if replayed > 0:
            lag_ms = 1000.0 * float(rj.get("restore_s") or 0.0) / replayed
            # a slow restore is an incident about ONE rejoin, not a
            # steady state: judge it only while the rejoin is fresh
            # (``age_s`` from the router's stats), then clear — the
            # breach edge was already counted and flight-recorded
            age = rj.get("age_s")
            fresh = age is None or float(age) < self._rejoin_hold
            self._judge("rejoin_lag", name, lag_ms, self._rejoin_ms,
                        now, breach=fresh and lag_ms > self._rejoin_ms)
        self._rule_fleet_network(name, now)

    def _rule_fleet_network(self, name: str, now: float) -> None:
        """Cross-hop rule: each worker's router-measured network time
        (``raft_tpu_fleet_network_seconds{worker=...}`` — RPC elapsed
        minus the worker's self-reported server time) gets its own
        cursor, baseline, and watch scoped ``<service>:<worker>``, so
        one worker's degraded link is judged against that link's own
        history rather than hiding in the fleet mean (the exec_latency
        per-rung discipline, applied across the process boundary)."""
        fam = _metrics.default_registry().get(
            "raft_tpu_fleet_network_seconds")
        if fam is None:
            return
        for labels, s in fam.series():
            wid = labels.get("worker")
            if wid is None:
                continue
            scope = "%s:%s" % (name, wid)
            count, total = int(s.count), float(s.total)
            prev = self._net_cursor.get(scope)
            self._net_cursor[scope] = (count, total)
            if prev is None or count <= prev[0]:
                continue
            window_mean = (total - prev[1]) / (count - prev[0])
            self._judge_baseline("fleet_network", scope, window_mean,
                                 self._latency_factor, now,
                                 judge=count >= self._min_samples)

    # ------------------------------------------------------------------ #
    # consumers (the ops plane's /healthz and /statusz)
    # ------------------------------------------------------------------ #
    def degraded(self) -> bool:
        with self._lock:
            return any(w.active for w in self._watches.values())

    def active(self) -> List[dict]:
        with self._lock:
            return [{"rule": rule, "service": service,
                     "value": round(w.value, 6),
                     "threshold": round(w.threshold, 6),
                     "since": w.since}
                    for (rule, service), w in sorted(
                        self._watches.items()) if w.active]

    def status(self) -> dict:
        with self._lock:
            watches = {
                "%s/%s" % (rule, service): {
                    "active": w.active,
                    "value": round(w.value, 6),
                    "threshold": round(w.threshold, 6),
                    "baseline": (None if w.baseline is None
                                 else round(w.baseline, 6)),
                }
                for (rule, service), w in sorted(self._watches.items())}
            return {"ticks": self._ticks,
                    "interval_s": self._interval,
                    "degraded": any(w.active
                                    for w in self._watches.values()),
                    "watches": watches}


# ---------------------------------------------------------------------- #
# the maintenance-seam hook: ServeWorker.run_maintenance pokes every
# registered sentinel between batch cycles — noticing rides the serving
# loop itself; the ops plane's ticker is the idle-process fallback
# ---------------------------------------------------------------------- #
_registered: List[AnomalySentinel] = []
_reg_lock = threading.Lock()


def register(sentinel: AnomalySentinel) -> AnomalySentinel:
    with _reg_lock:
        if sentinel not in _registered:
            _registered.append(sentinel)
    return sentinel


def unregister(sentinel: AnomalySentinel) -> None:
    with _reg_lock:
        if sentinel in _registered:
            _registered.remove(sentinel)


def poke() -> None:
    """Tick every registered sentinel (rate-limited internally — a
    no-op costs one list read + one clock read per sentinel).  Never
    raises: the worker loop calling this must survive any watcher."""
    with _reg_lock:
        sentinels = list(_registered)
    for s in sentinels:
        try:
            s.tick()
        except Exception:  # noqa: BLE001 — counted, never loop-fatal
            _counter("raft_tpu_ops_sentinel_errors_total",
                     "sentinel rule-evaluation failures").inc()
