"""Serving worker: batch formation -> padded device call -> split.

One :class:`ServeWorker` owns one daemon thread per service (THE
allowlisted home for ``threading.Thread`` in ``raft_tpu/`` outside the
comms watchdog — ``ci/style_check.py`` enforces that daemon-thread
hygiene lives here).  The loop:

1. pull a batch from the :class:`~raft_tpu.serve.batcher.MicroBatcher`;
2. expire requests whose deadline passed while queued — their futures
   fail with :class:`~raft_tpu.core.error.CommTimeoutError` (PR 1's
   deadline taxonomy: a deadline is a deadline, whether a comms verb or
   a queue slot blew it) *before* any device work is spent on them;
3. coalesce the survivors' rows, pad to the
   :class:`~raft_tpu.serve.bucketing.BucketPolicy` rung, run the
   service's device function — optionally under a
   :class:`~raft_tpu.comms.resilience.RetryPolicy` (per-batch watchdog
   + retry; the device fn is pure, so a retry is idempotent);
4. split result rows back per request and resolve the futures.  A batch
   failure fails every rider's future — riders resubmit independently.

**Overlapped dispatch** (docs/ZERO_COPY.md, the libhclooc
host/accelerator-overlap argument): JAX dispatch is asynchronous, so
the worker splits each batch into a *start* half (expire, coalesce,
pad, launch the device call) and a *finish* half (block until the
device result is ready, split, resolve).  The loop starts batch N+1's
host-side pad/coalesce while batch N's device call is still running
and blocks only at N's split — the accelerator never idles behind host
batch formation under sustained load.  A :class:`RetryPolicy` forces
the synchronous path (a retry must observe the failure before the next
batch is formed).  With ``donate=True`` the padded input buffer is
donated to the device function — the service guarantees its execute
path tolerates consumption (the buffer is serve-internal; the worker
copies in the one case it could alias a caller's array).

Every step feeds the ``raft_tpu_serve_*`` metric families (labeled
``service=<name>``) so ``metrics_snapshot()`` / ``tools/metrics_report.py``
surface queue depth, batch fill, wait/exec latency, padding waste and
per-bucket traffic without any serve-specific plumbing.  Every step
ALSO records the request lifecycle into the flight recorder
(docs/OBSERVABILITY.md "Flight recorder & request tracing"): batch
formation (``batch_formed``: batch id, bucket rung, riders), the
execute bracket (``execute_launch`` / ``execute_ready``), and exactly
one terminal event per admitted request (``resolved`` / ``expired`` /
``failed``; a recovery re-enqueue records a non-terminal
``requeued``).  The device call runs under
:func:`raft_tpu.core.flight.batch_scope` so deeper layers (replica
hedging) attach their events to every rider's trace, and each
resolution feeds the service's SLO tracker and slowest-K exemplars.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp

from raft_tpu.core import flight
from raft_tpu.core import metrics as _metrics
from raft_tpu.core import profiler as _profiler
from raft_tpu.core.error import CommTimeoutError, expects
from raft_tpu.serve import sentinel as _sentinel
from raft_tpu.serve.batcher import MicroBatcher, _Request
from raft_tpu.serve.bucketing import BucketPolicy, coalesce, pad_rows

__all__ = ["ServeWorker"]

# process-global batch ids: unique across services, so one flight
# stream never shows two concurrent batches sharing an id
_batch_seq = itertools.count(1)


class _Inflight:
    """One launched-but-unsplit batch (the pipeline register between
    the worker's start and finish halves)."""

    __slots__ = ("live", "spans", "bucket", "payload_rows", "out",
                 "t_launch", "batch_id", "exec_fn")

    def __init__(self, live, spans, bucket, payload_rows, out, t_launch,
                 batch_id=None, exec_fn=""):
        self.live = live
        self.spans = spans
        self.bucket = bucket
        self.payload_rows = payload_rows
        self.out = out
        self.t_launch = t_launch
        self.batch_id = batch_id
        self.exec_fn = exec_fn


# -- registry helpers (resolved per use: cheap, and reset-proof — a test
# that resets the registry mid-life gets fresh families, not writes into
# orphans) ------------------------------------------------------------- #
def _counter(name: str, help: str, service: str):
    return _metrics.default_registry().counter(
        name, help=help, labels=("service",)).labels(service=service)


def _gauge(name: str, help: str, service: str):
    return _metrics.default_registry().gauge(
        name, help=help, labels=("service",)).labels(service=service)


def _timer(name: str, help: str, service: str):
    return _metrics.default_registry().timer(
        name, help=help, labels=("service",)).labels(service=service)


def _bucket_counter(service: str, bucket: int):
    return _metrics.default_registry().counter(
        "raft_tpu_serve_bucket_calls_total",
        help="padded device calls per shape bucket",
        labels=("service", "bucket")).labels(service=service,
                                             bucket=bucket)


def _rung_timer(service: str, bucket: int):
    return _metrics.default_registry().timer(
        "raft_tpu_serve_exec_rung_seconds",
        help="padded device call latency per shape-bucket rung",
        labels=("service", "rung")).labels(service=service,
                                           rung=bucket)


def _device_timer(service: str, fn: str):
    return _metrics.default_registry().timer(
        "raft_tpu_serve_device_seconds",
        help="device-complete padded call latency per executable "
             "family (fn): launch to blocked-result-ready — the "
             "bracket block_seconds closes, keyed so the roofline "
             "inventory join can compute a firm achieved-GFLOP/s "
             "floor per fn",
        labels=("service", "fn")).labels(service=service, fn=fn)


def _tenant_counter(name: str, help: str, service: str, tenant: str):
    return _metrics.default_registry().counter(
        name, help=help, labels=("service", "tenant")).labels(
            service=service, tenant=tenant)


class ServeWorker:
    """Single-consumer dispatch loop over a :class:`MicroBatcher`.

    Parameters
    ----------
    name:
        Service name (the ``service=`` metric label).
    batcher / policy:
        The request queue and the shape-bucket ladder.
    execute:
        ``execute(padded_batch) -> pytree of arrays`` whose every leaf
        has the padded batch's rows as its leading dimension (the
        contract that makes per-request splitting mechanical).
    retry_policy:
        Optional :class:`~raft_tpu.comms.resilience.RetryPolicy` around
        each device call — per-attempt watchdog deadline + backoff
        retries, exactly PR 1's verb machinery.  Forces synchronous
        (non-overlapped) dispatch: a retry must see its attempt fail,
        so each attempt blocks until device-complete.
    donate:
        Donate the padded batch buffer to ``execute`` (the execute path
        must route it through a donating executable or tolerate eager
        consumption; services wire this, see docs/ZERO_COPY.md).  The
        worker guarantees the donated buffer never aliases a caller's
        submitted array.
    maintenance:
        Optional zero-arg callback run ON the worker thread between
        batch cycles (and on an idle poll every
        ``maintenance_interval_s``): the serving loop's home for
        background index work — ANN delta compaction — without a second
        thread to coordinate (``ci/style_check.py``'s thread hygiene
        argument).  It runs between dispatches, never mid-batch, so an
        index swap it performs can never tear a batch; exceptions are
        counted (``raft_tpu_serve_maintenance_errors_total``), captured
        as :attr:`last_maintenance_error` (surfaced through
        ``Service.stats()`` / session ``health_check()`` — a silently
        failing compactor is visible) and swallowed — a failing
        compactor must not kill the loop serving everyone.
    breaker:
        Optional :class:`~raft_tpu.serve.resilience.CircuitBreaker`.
        The worker records every batch outcome into it; while it is
        OPEN the loop holds batch formation (no point burning queued
        riders against a broken device), and a batch failure that finds
        it open re-enqueues its riders **once** (``_Request.requeued``)
        instead of failing them — the in-flight-futures-survive-
        recovery guarantee (docs/FAULT_MODEL.md).
    clock:
        Shared with the batcher for deadline math.
    """

    def __init__(self, name: str, batcher: MicroBatcher,
                 policy: BucketPolicy,
                 execute: Callable,
                 retry_policy=None,
                 donate: bool = False,
                 maintenance: Optional[Callable[[], None]] = None,
                 maintenance_interval_s: float = 0.05,
                 breaker=None,
                 slo=None,
                 clock: Callable[[], float] = time.monotonic):
        self.name = name
        self._batcher = batcher
        self._policy = policy
        self._execute = execute
        # executable-family attribution for the device-complete
        # roofline join: ``execute`` is an opaque service closure, so
        # the name of the program it ran comes from the profiled_jit
        # wrapper that executed on this batch thread
        # (profiler.last_jit_fn()); this remembers the latest sighting
        # as the fallback for batches that resolve off-thread (hedged
        # replica arms)
        self._exec_fn = ""
        self._retry_policy = retry_policy
        self._maintenance = maintenance
        self._maint_interval = float(maintenance_interval_s)
        self.breaker = breaker
        # per-service SLO tracker (raft_tpu/core/flight.py) — fed one
        # outcome per terminal request resolution; None = untracked
        # (bare workers constructed outside a Service facade)
        self.slo = slo
        # the slowest-K exemplar reservoir, resolved once (the
        # registry lookup must not ride the per-batch hot path)
        self._exemplars = flight.exemplars_for(name)
        # last maintenance failure, surfaced via Service.stats():
        # {"type", "message", "at"} — "at" is the worker clock's
        # monotonic seconds (the only clock the library may read)
        self.last_maintenance_error: Optional[dict] = None
        # the worker OWNS the donation-eligibility rule: donation is
        # off whenever a retry could replay the consumed buffer.
        # Public: Service passes intent and reads the resolved value
        # back to pick its device-fn variant — one place encodes the
        # rule.
        self.donate = bool(donate) and retry_policy is None
        # payload rows launched but not yet split (worker-thread-only
        # state; the inflight gauge publishes it — a running sum, since
        # the pipelined loop can hold two launched batches briefly)
        self._inflight_rows = 0
        self._clock = clock
        self._thread: Optional[threading.Thread] = None
        self._state = threading.Condition()
        self._busy = False
        self._closed = False

    # ------------------------------------------------------------------ #
    # lifecycle
    # ------------------------------------------------------------------ #
    def start(self) -> "ServeWorker":
        """Spawn the daemon worker thread (idempotent)."""
        with self._state:
            expects(not self._closed, "ServeWorker %s is closed", self.name)
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._loop, daemon=True,
                    name="raft-tpu-serve-%s" % self.name)
                self._thread.start()
        return self

    def is_alive(self) -> bool:
        with self._state:
            return self._thread is not None and self._thread.is_alive()

    def started(self) -> bool:
        with self._state:
            return self._thread is not None

    def dead(self) -> bool:
        """True when the worker thread was started and has died — the
        hot-path admission check (one lock acquisition per submit)."""
        with self._state:
            return (self._thread is not None
                    and not self._thread.is_alive())

    def restart(self) -> bool:
        """Replace a dead worker thread — the health-repair lever
        (session ``health_check`` names dead workers;
        :class:`~raft_tpu.serve.resilience.RecoveryManager` pulls
        this).  False while the current thread is alive or the worker
        was never started (nothing to repair); raises once closed."""
        with self._state:
            expects(not self._closed, "ServeWorker %s is closed",
                    self.name)
            t = self._thread
            if t is None or t.is_alive():
                return False
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name="raft-tpu-serve-%s" % self.name)
            self._thread.start()
        _counter("raft_tpu_serve_worker_restarts_total",
                 "dead worker threads replaced", self.name).inc()
        flight.record("worker_restart", service=self.name)
        return True

    def quiesce(self, timeout: Optional[float] = None) -> bool:
        """Wait until no batch is mid-dispatch (worker idle between
        cycles, or dead).  Unlike :meth:`drain` this touches no
        admission state: queued requests stay queued — the recovery
        sequence pauses the batcher first, quiesces here, and serves
        the backlog out after re-admission.  True when quiet."""
        deadline = None if timeout is None else self._clock() + timeout
        with self._state:
            while self._busy:
                if not (self._thread and self._thread.is_alive()):
                    return True  # a dead thread holds no batch
                if deadline is not None and self._clock() >= deadline:
                    return False
                self._state.wait(timeout=0.05)
            return True

    def _loop(self) -> None:
        """Pipelined worker loop: dispatch batch N+1 while batch N's
        device call runs (module doc).  ``pending`` is the one in-flight
        batch; depth-1 pipelining bounds result latency at one batch
        while already hiding host-side batch formation behind the
        device.

        A :class:`RetryPolicy` disables the pipelining outright, not
        just the launch half: each retried attempt blocks through the
        device call (plus watchdog and backoff) inside ``_start``, so
        deferring the previous batch's ``_finish`` behind it would
        delay results that were already sitting ready by the whole of
        the next batch's (potentially retried) execution — pure loss,
        no overlap gained."""
        pipelined = self._retry_policy is None
        pending = None
        poll = (self._maint_interval if self._maintenance is not None
                else None)
        while True:
            hold = self._dispatch_hold()
            if hold > 0.0:
                # breaker open: stop forming batches — dispatching the
                # queued backlog against a broken device would only
                # burn every rider's single re-enqueue.  Finish the
                # in-flight batch (its results may already be sitting
                # ready), then idle-poll until the cooldown admits
                # half-open probes.  Drain overrides the hold (the
                # gate checks draining): close must serve out or fail,
                # never wait on a recovery that is not coming.
                if pending is not None:
                    try:
                        self._finish(pending)
                    finally:
                        pending = None
                        with self._state:
                            self._busy = False
                            self._state.notify_all()
                with self._state:
                    self._state.wait(timeout=min(hold, 0.05))
                self.run_maintenance()
                continue
            if pending is None:
                batch = self._batcher.wait_for_batch(timeout=poll)
                if batch is None:
                    return
                if not batch:
                    # idle maintenance poll — no work queued, so a
                    # long compaction delays nobody
                    self.run_maintenance()
                    continue
            else:
                # opportunistic, non-blocking: if the policy has a
                # batch ready NOW, start it before finishing the
                # in-flight one (the overlap); otherwise complete the
                # in-flight batch — its riders must not wait on an
                # idle queue
                batch = self._batcher.take()
                if not batch:
                    try:
                        self._finish(pending)
                    finally:
                        pending = None
                        with self._state:
                            self._busy = False
                            self._state.notify_all()
                    self.run_maintenance()
                    continue
            with self._state:
                self._busy = True
            nxt = None
            try:
                if pipelined:
                    nxt = self._start(batch)
                else:
                    self.dispatch(batch)
            finally:
                if pending is not None:
                    self._finish(pending)
                pending = nxt
                if pending is None:
                    with self._state:
                        self._busy = False
                        self._state.notify_all()
            # the maintenance seam: between batch cycles, never
            # mid-batch, and ALWAYS after the previous batch's riders
            # were resolved — a long compaction here overlaps at most
            # the just-launched batch's device compute, never withholds
            # results that are already sitting ready (the same argument
            # the retry path makes about deferring _finish).  Cheap
            # no-op when nothing is due.
            self.run_maintenance()

    def _dispatch_hold(self) -> float:
        """Seconds the breaker wants dispatch held (0.0 = go).  Drain
        wins over the hold: a draining queue must be served out (or
        failed onto futures) rather than held for a recovery."""
        if self.breaker is None or self._batcher.draining():
            return 0.0
        return self.breaker.dispatch_hold()

    def run_once(self) -> bool:
        """Manual stepping for threadless/deterministic operation: form
        and dispatch one batch if the policy allows (and the breaker
        does not hold); True if one ran."""
        if self._dispatch_hold() > 0.0:
            return False
        batch = self._batcher.take()
        if not batch:
            return False
        self.dispatch(batch)
        return True

    def run_maintenance(self) -> None:
        """Run the maintenance callback (if any) on the calling thread.

        The worker loop calls this between batch cycles; threadless
        services may step it manually.  ``_busy`` is held (and restored
        — a pipelined in-flight batch keeps it set) so ``drain``
        observes maintenance as work in progress: after ``drain()``
        returns, no compaction is mid-flight.  Never raises."""
        # the anomaly sentinel rides the maintenance seam
        # (docs/OBSERVABILITY.md "Ops plane"): a loaded serving
        # process notices a breach within one batch cycle without a
        # dedicated watcher thread.  Rate-limited + exception-proof
        # inside; a no-op when no ops plane registered a sentinel.
        _sentinel.poke()
        fn = self._maintenance
        if fn is None:
            return
        with self._state:
            was_busy = self._busy
            self._busy = True
        try:
            fn()
            self.last_maintenance_error = None
        except Exception as e:  # noqa: BLE001 — counted, never loop-fatal
            _counter("raft_tpu_serve_maintenance_errors_total",
                     "maintenance callback failures", self.name).inc()
            # a bare counter hides WHAT keeps failing: capture the last
            # failure for Service.stats() / session health_check
            self.last_maintenance_error = {
                "type": type(e).__name__,
                "message": str(e)[:500],
                "at": self._clock(),
            }
        finally:
            with self._state:
                self._busy = was_busy
                self._state.notify_all()

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Stop admission and serve out everything queued/in flight.

        With a live worker thread this blocks (up to ``timeout``) until
        the queue is empty and the worker idle; threadless services are
        drained inline on the calling thread.  Returns True when fully
        drained.
        """
        self._batcher.begin_drain()
        if not self.started():
            while self.run_once():
                pass
            return self._batcher.empty()
        deadline = None if timeout is None else self._clock() + timeout
        with self._state:
            while not (self._batcher.empty() and not self._busy):
                if not (self._thread and self._thread.is_alive()):
                    break  # dead worker: inline fallback below
                if deadline is not None and self._clock() >= deadline:
                    return False
                self._state.wait(timeout=0.05)
        # a crashed worker thread must not strand queued requests
        while self.run_once():
            pass
        return self._batcher.empty()

    def close(self, drain: bool = True,
              timeout: Optional[float] = None) -> None:
        """Drain (by default), stop the queue, fail any leftovers, and
        join the worker thread.  Idempotent."""
        with self._state:
            if self._closed:
                return
            self._closed = True
        if drain:
            self.drain(timeout=timeout)
        leftovers = self._batcher.shutdown()
        for req in leftovers:
            flight.record("expired", service=self.name, trace=req.trace,
                          reason="close")
            if self.slo is not None:
                self.slo.observe(req.tenant,
                                 self._clock() - req.enqueue_t,
                                 deadline_ok=False)
            req.future._set_exception(CommTimeoutError(
                "service %s closed before the request was served"
                % self.name))
        if leftovers:
            _counter("raft_tpu_serve_expired_total",
                     "requests failed by deadline or close",
                     self.name).inc(len(leftovers))
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=5.0)

    # ------------------------------------------------------------------ #
    # dispatch
    # ------------------------------------------------------------------ #
    def _fail_batch(self, live: List[_Request],
                    exc: BaseException) -> None:
        """Relay one batch failure.  Classification first: the breaker
        ignores caller bugs and decides whether this failure is
        *service-level* (it is now, or already was, open).  Service-
        level failures re-enqueue each rider ONCE — at the moment of a
        trip the in-flight futures are put back to be served after
        recovery, not lost — while a rider on its second strike (or any
        non-service-level failure) gets the exception, PR 3's original
        riders-resubmit contract.  Never raises."""
        _counter("raft_tpu_serve_batch_errors_total",
                 "batches whose device call failed", self.name).inc()
        service_level = (self.breaker.record_failure(exc)
                         if self.breaker is not None else False)
        retry: List[_Request] = []
        err_name = type(exc).__name__
        for req in live:
            if service_level and not req.requeued:
                req.requeued = True
                retry.append(req)
            else:
                # terminal event before the future resolves (the
                # trace-complete-at-resolution contract)
                self._fail_terminal(req, err_name)
                req.future._set_exception(exc)
        if retry:
            if self._batcher.requeue(retry):
                _counter("raft_tpu_serve_requeued_total",
                         "riders re-enqueued once across a breaker "
                         "trip/recovery", self.name).inc(len(retry))
                flight.record("requeued", service=self.name,
                              traces=[r.trace for r in retry],
                              error=err_name)
            else:
                # queue already shut down: nobody will ever serve the
                # re-enqueue — the exception is the only resolution
                for req in retry:
                    self._fail_terminal(req, err_name)
                    req.future._set_exception(exc)

    def _fail_terminal(self, req: _Request, err_name: str) -> None:
        """One request's terminal ``failed`` event + SLO miss (the
        exactly-one-terminal contract's failure leg)."""
        flight.record("failed", service=self.name, trace=req.trace,
                      error=err_name,
                      latency_s=round(
                          max(0.0, self._clock() - req.enqueue_t), 6))
        if self.slo is not None:
            self.slo.observe(req.tenant,
                             self._clock() - req.enqueue_t,
                             deadline_ok=False)

    def _expire_locked_out(self, batch: List[_Request],
                           now: float) -> List[_Request]:
        live: List[_Request] = []
        expired = 0
        for req in batch:
            if req.deadline_t is not None and now >= req.deadline_t:
                expired += 1
                # terminal event before the future resolves (the
                # trace-complete-at-resolution contract)
                flight.record("expired", service=self.name,
                              trace=req.trace, reason="deadline",
                              waited_s=round(now - req.enqueue_t, 6))
                if self.slo is not None:
                    self.slo.observe(req.tenant, now - req.enqueue_t,
                                     deadline_ok=False)
                req.future._set_exception(CommTimeoutError(
                    "request exceeded its deadline after %.3fs in the "
                    "%s queue" % (now - req.enqueue_t, self.name)))
            else:
                live.append(req)
        if expired:
            _counter("raft_tpu_serve_expired_total",
                     "requests failed by deadline or close",
                     self.name).inc(expired)
        return live

    def dispatch(self, batch: Sequence[_Request]) -> None:
        """Run one formed batch to completion (never raises for
        Exception-class failures: they land on the riders' futures — a
        poisoned batch must not kill the loop serving everyone else.
        A worker-killing BaseException still propagates, but only
        after every rider was resolved or re-enqueued).  Synchronous
        start+finish — the manual-stepping (``run_once``) and drain
        entry point; the worker loop pipelines the two halves."""
        inflight = self._start(batch)
        if inflight is not None:
            self._finish(inflight)

    def _start(self, batch: Sequence[_Request]
               ) -> Optional["_Inflight"]:
        """Host half: expire, coalesce, pad, LAUNCH the device call
        (async dispatch — does not wait for the result).  Returns the
        in-flight record, or None if nothing survived / the launch
        failed (riders already resolved).  Never raises."""
        now = self._clock()
        _gauge("raft_tpu_serve_queue_depth", "requests queued",
               self.name).set(self._batcher.depth())
        live = self._expire_locked_out(list(batch), now)
        if not live:
            return None
        wait_t = _timer("raft_tpu_serve_wait_seconds",
                        "enqueue-to-dispatch queue wait", self.name)
        for req in live:
            wait_t.observe(max(0.0, now - req.enqueue_t))
        payload_rows = sum(r.rows for r in live)
        launched = False
        batch_id = next(_batch_seq)
        rider_traces = [r.trace for r in live]
        try:
            bucket = self._policy.bucket_for(payload_rows)
            flight.record("batch_formed", service=self.name,
                          traces=rider_traces, batch=batch_id,
                          rung=bucket, riders=len(live),
                          rows=payload_rows)
            stacked, spans = coalesce([r.payload for r in live])
            padded = pad_rows(stacked, bucket)
            if (self.donate and len(live) == 1
                    and padded is live[0].payload):
                # sole case where the "padded" buffer IS the caller's
                # submitted array (one request, exactly rung-sized, no
                # dtype copy): donation would consume the caller's
                # data — pay one defensive copy instead
                padded = jnp.copy(padded)
            # the gauge tracks a running SUM: under the pipelined loop
            # batch N+1 launches before batch N's _finish, so set/zero
            # per batch would read 0 while a call is actually in flight
            self._inflight_rows += payload_rows
            launched = True
            _gauge("raft_tpu_serve_inflight_rows",
                   "payload rows in launched, not-yet-split device "
                   "calls", self.name).set(self._inflight_rows)
            t_launch = self._clock()
            flight.record("execute_launch", service=self.name,
                          traces=rider_traces, batch=batch_id,
                          rung=bucket)
            # batch_scope: deeper layers (replica rotation / hedging)
            # attach their events to every rider's trace without the
            # execute signature carrying trace handles
            _profiler._clear_last_jit_fn()
            with flight.batch_scope(rider_traces):
                if self._retry_policy is not None:
                    # synchronous: each attempt must surface its own
                    # device failure INSIDE the retry loop, so block
                    # per attempt (module doc)
                    def attempt(p):
                        res = self._execute(p)
                        jax.block_until_ready(
                            [x for x in jax.tree_util.tree_leaves(res)
                             if hasattr(x, "shape")])
                        return res

                    out = self._retry_policy.call(
                        attempt, padded, verb="serve.%s" % self.name)
                else:
                    out = self._execute(padded)
            # which program family ran: the profiled_jit wrapper that
            # executed on this thread names it; a batch whose programs
            # ran off-thread (hedged replica arms) reuses the family
            # last seen on this scheduler — same service, same family
            self._exec_fn = (_profiler.last_jit_fn()
                             or self._exec_fn)
            return _Inflight(live, spans, bucket, payload_rows, out,
                             t_launch, batch_id,
                             exec_fn=self._exec_fn)
        except BaseException as e:  # noqa: BLE001 — relayed/requeued per rider
            self._fail_batch(live, e)
            if launched:
                self._inflight_rows -= payload_rows
            _gauge("raft_tpu_serve_inflight_rows",
                   "payload rows in launched, not-yet-split device "
                   "calls", self.name).set(self._inflight_rows)
            if not isinstance(e, Exception):
                # worker-killing class (SystemExit & co.): the thread
                # is about to die — but only AFTER every rider was
                # resolved or re-enqueued above, so no future is lost
                # and restart() can serve the requeued backlog
                raise
            return None

    def _finish(self, inflight: "_Inflight") -> None:
        """Device half: block until the launched call completes, split
        rows per request, resolve futures, account.  Never raises."""
        live, spans, bucket = (inflight.live, inflight.spans,
                               inflight.bucket)
        payload_rows, out = inflight.payload_rows, inflight.out
        try:
            leaves = [x for x in jax.tree_util.tree_leaves(out)
                      if hasattr(x, "shape")]
            for leaf in leaves:
                expects(leaf.shape[0] == bucket,
                        "serve execute contract: leaf leading dim %d != "
                        "padded batch rows %d", leaf.shape[0], bucket)
            # THE one block point: everything host-side for the next
            # batch already happened while this ran on device
            t_block = self._clock()
            jax.block_until_ready(leaves)
            t_ready = self._clock()
            # launch→observed-ready is an UPPER bound on device
            # latency: under the overlapped loop the next batch's
            # host-side formation runs between launch and this block,
            # so a device call that finished during it is only
            # observed ready here.  block_seconds (time actually
            # spent blocked) is the matching lower bound on the
            # device work remaining at split time.
            _timer("raft_tpu_serve_exec_seconds",
                   "padded device call latency, launch to observed "
                   "result-ready (upper bound under the overlapped "
                   "loop)", self.name).observe(
                       max(0.0, t_ready - inflight.t_launch))
            # same latency, keyed by shape rung: the sentinel's
            # exec_latency rule watches per-(service, rung) series so
            # a regression in one bucket cannot hide inside a healthy
            # mix (docs/OBSERVABILITY.md)
            _rung_timer(self.name, bucket).observe(
                max(0.0, t_ready - inflight.t_launch))
            _timer("raft_tpu_serve_block_seconds",
                   "time the worker blocked on device results "
                   "(lower bound on device latency at split time)",
                   self.name).observe(max(0.0, t_ready - t_block))
            if inflight.exec_fn:
                # device-COMPLETE bracket: opens at launch, closes
                # only after block_until_ready returned — unlike the
                # host-side jit dispatch timer, the device work is
                # provably finished when this stops, so
                # flops / this-mean is a floor on achieved rate
                _device_timer(self.name, inflight.exec_fn).observe(
                    max(0.0, t_ready - inflight.t_launch))
            flight.record("execute_ready", service=self.name,
                          traces=[r.trace for r in live],
                          batch=inflight.batch_id,
                          exec_s=round(
                              max(0.0, t_ready - inflight.t_launch), 6),
                          block_s=round(max(0.0, t_ready - t_block), 6))
            exemplars = self._exemplars
            for req, (start, stop) in zip(live, spans):
                # terminal event + SLO/exemplar BEFORE the future
                # resolves (the admitted-event ordering rule, mirrored
                # at the other end): a caller woken by result() must
                # already see the complete timeline
                latency = max(0.0, t_ready - req.enqueue_t)
                flight.record("resolved", service=self.name,
                              trace=req.trace,
                              batch=inflight.batch_id,
                              latency_s=round(latency, 6))
                if self.slo is not None:
                    self.slo.observe(
                        req.tenant, latency,
                        deadline_ok=(req.deadline_t is None
                                     or t_ready <= req.deadline_t))
                if req.trace is not None:
                    exemplars.observe(latency, req.trace.trace_id)
                req.future._set_result(jax.tree_util.tree_map(
                    lambda leaf: leaf[start:stop], out))
        except BaseException as e:  # noqa: BLE001 — relayed/requeued per rider
            self._fail_batch(live, e)
            if not isinstance(e, Exception):
                raise  # worker-killing: die with every rider resolved
            return
        finally:
            self._inflight_rows -= inflight.payload_rows
            _gauge("raft_tpu_serve_inflight_rows",
                   "payload rows in launched, not-yet-split device "
                   "calls", self.name).set(self._inflight_rows)
        # accounting only after a successful dispatch
        if self.breaker is not None:
            self.breaker.record_success()
        # feed the admission layer's queue-drain estimate (the
        # ServiceOverloadError.retry_after_s hint)
        self._batcher.note_batch_seconds(
            max(1e-6, t_ready - inflight.t_launch))
        _counter("raft_tpu_serve_batches_total", "dispatched batches",
                 self.name).inc()
        _counter("raft_tpu_serve_requests_total", "served requests",
                 self.name).inc(len(live))
        per_tenant: dict = {}
        for req in live:
            rows_n, reqs_n = per_tenant.get(req.tenant, (0, 0))
            per_tenant[req.tenant] = (rows_n + req.rows, reqs_n + 1)
        for tenant, (rows_n, reqs_n) in per_tenant.items():
            _tenant_counter("raft_tpu_serve_tenant_rows_total",
                            "payload rows served, per tenant",
                            self.name, tenant).inc(rows_n)
            _tenant_counter("raft_tpu_serve_tenant_requests_total",
                            "requests served, per tenant",
                            self.name, tenant).inc(reqs_n)
        _counter("raft_tpu_serve_payload_rows_total",
                 "real (caller) rows dispatched", self.name).inc(
                     payload_rows)
        _counter("raft_tpu_serve_padded_rows_total",
                 "zero-pad rows dispatched (waste)", self.name).inc(
                     bucket - payload_rows)
        _timer("raft_tpu_serve_batch_rows",
               "payload rows per batch (a row-count histogram riding "
               "the timer type; seconds formatting does not apply)",
               self.name).observe(float(payload_rows))
        _bucket_counter(self.name, bucket).inc()
