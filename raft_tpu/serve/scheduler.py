"""Serving worker: batch formation -> padded device call -> split.

One :class:`ServeWorker` owns one daemon thread per service (THE
allowlisted home for ``threading.Thread`` in ``raft_tpu/`` outside the
comms watchdog — ``ci/style_check.py`` enforces that daemon-thread
hygiene lives here).  The loop:

1. pull a batch from the :class:`~raft_tpu.serve.batcher.MicroBatcher`;
2. expire requests whose deadline passed while queued — their futures
   fail with :class:`~raft_tpu.core.error.CommTimeoutError` (PR 1's
   deadline taxonomy: a deadline is a deadline, whether a comms verb or
   a queue slot blew it) *before* any device work is spent on them;
3. coalesce the survivors' rows, pad to the
   :class:`~raft_tpu.serve.bucketing.BucketPolicy` rung, run the
   service's device function — optionally under a
   :class:`~raft_tpu.comms.resilience.RetryPolicy` (per-batch watchdog
   + retry; the device fn is pure, so a retry is idempotent);
4. split result rows back per request and resolve the futures.  A batch
   failure fails every rider's future — riders resubmit independently.

Every step feeds the ``raft_tpu_serve_*`` metric families (labeled
``service=<name>``) so ``metrics_snapshot()`` / ``tools/metrics_report.py``
surface queue depth, batch fill, wait/exec latency, padding waste and
per-bucket traffic without any serve-specific plumbing.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional, Sequence

import jax

from raft_tpu.core import metrics as _metrics
from raft_tpu.core.error import CommTimeoutError, expects
from raft_tpu.serve.batcher import MicroBatcher, _Request
from raft_tpu.serve.bucketing import BucketPolicy, coalesce, pad_rows

__all__ = ["ServeWorker"]


# -- registry helpers (resolved per use: cheap, and reset-proof — a test
# that resets the registry mid-life gets fresh families, not writes into
# orphans) ------------------------------------------------------------- #
def _counter(name: str, help: str, service: str):
    return _metrics.default_registry().counter(
        name, help=help, labels=("service",)).labels(service=service)


def _gauge(name: str, help: str, service: str):
    return _metrics.default_registry().gauge(
        name, help=help, labels=("service",)).labels(service=service)


def _timer(name: str, help: str, service: str):
    return _metrics.default_registry().timer(
        name, help=help, labels=("service",)).labels(service=service)


def _bucket_counter(service: str, bucket: int):
    return _metrics.default_registry().counter(
        "raft_tpu_serve_bucket_calls_total",
        help="padded device calls per shape bucket",
        labels=("service", "bucket")).labels(service=service,
                                             bucket=bucket)


class ServeWorker:
    """Single-consumer dispatch loop over a :class:`MicroBatcher`.

    Parameters
    ----------
    name:
        Service name (the ``service=`` metric label).
    batcher / policy:
        The request queue and the shape-bucket ladder.
    execute:
        ``execute(padded_batch) -> pytree of arrays`` whose every leaf
        has the padded batch's rows as its leading dimension (the
        contract that makes per-request splitting mechanical).
    retry_policy:
        Optional :class:`~raft_tpu.comms.resilience.RetryPolicy` around
        each device call — per-attempt watchdog deadline + backoff
        retries, exactly PR 1's verb machinery.
    clock:
        Shared with the batcher for deadline math.
    """

    def __init__(self, name: str, batcher: MicroBatcher,
                 policy: BucketPolicy,
                 execute: Callable,
                 retry_policy=None,
                 clock: Callable[[], float] = time.monotonic):
        self.name = name
        self._batcher = batcher
        self._policy = policy
        self._execute = execute
        self._retry_policy = retry_policy
        self._clock = clock
        self._thread: Optional[threading.Thread] = None
        self._state = threading.Condition()
        self._busy = False
        self._closed = False

    # ------------------------------------------------------------------ #
    # lifecycle
    # ------------------------------------------------------------------ #
    def start(self) -> "ServeWorker":
        """Spawn the daemon worker thread (idempotent)."""
        with self._state:
            expects(not self._closed, "ServeWorker %s is closed", self.name)
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._loop, daemon=True,
                    name="raft-tpu-serve-%s" % self.name)
                self._thread.start()
        return self

    def is_alive(self) -> bool:
        with self._state:
            return self._thread is not None and self._thread.is_alive()

    def started(self) -> bool:
        with self._state:
            return self._thread is not None

    def _loop(self) -> None:
        while True:
            batch = self._batcher.wait_for_batch()
            if batch is None:
                return
            with self._state:
                self._busy = True
            try:
                self.dispatch(batch)
            finally:
                with self._state:
                    self._busy = False
                    self._state.notify_all()

    def run_once(self) -> bool:
        """Manual stepping for threadless/deterministic operation: form
        and dispatch one batch if the policy allows; True if one ran."""
        batch = self._batcher.take()
        if not batch:
            return False
        self.dispatch(batch)
        return True

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Stop admission and serve out everything queued/in flight.

        With a live worker thread this blocks (up to ``timeout``) until
        the queue is empty and the worker idle; threadless services are
        drained inline on the calling thread.  Returns True when fully
        drained.
        """
        self._batcher.begin_drain()
        if not self.started():
            while self.run_once():
                pass
            return self._batcher.empty()
        deadline = None if timeout is None else self._clock() + timeout
        with self._state:
            while not (self._batcher.empty() and not self._busy):
                if not (self._thread and self._thread.is_alive()):
                    break  # dead worker: inline fallback below
                if deadline is not None and self._clock() >= deadline:
                    return False
                self._state.wait(timeout=0.05)
        # a crashed worker thread must not strand queued requests
        while self.run_once():
            pass
        return self._batcher.empty()

    def close(self, drain: bool = True,
              timeout: Optional[float] = None) -> None:
        """Drain (by default), stop the queue, fail any leftovers, and
        join the worker thread.  Idempotent."""
        with self._state:
            if self._closed:
                return
            self._closed = True
        if drain:
            self.drain(timeout=timeout)
        leftovers = self._batcher.shutdown()
        for req in leftovers:
            req.future._set_exception(CommTimeoutError(
                "service %s closed before the request was served"
                % self.name))
        if leftovers:
            _counter("raft_tpu_serve_expired_total",
                     "requests failed by deadline or close",
                     self.name).inc(len(leftovers))
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=5.0)

    # ------------------------------------------------------------------ #
    # dispatch
    # ------------------------------------------------------------------ #
    def _expire_locked_out(self, batch: List[_Request],
                           now: float) -> List[_Request]:
        live: List[_Request] = []
        expired = 0
        for req in batch:
            if req.deadline_t is not None and now >= req.deadline_t:
                expired += 1
                req.future._set_exception(CommTimeoutError(
                    "request exceeded its deadline after %.3fs in the "
                    "%s queue" % (now - req.enqueue_t, self.name)))
            else:
                live.append(req)
        if expired:
            _counter("raft_tpu_serve_expired_total",
                     "requests failed by deadline or close",
                     self.name).inc(expired)
        return live

    def dispatch(self, batch: Sequence[_Request]) -> None:
        """Run one formed batch to completion (never raises: every
        failure lands on the riders' futures — a poisoned batch must
        not kill the loop serving everyone else)."""
        now = self._clock()
        _gauge("raft_tpu_serve_queue_depth", "requests queued",
               self.name).set(self._batcher.depth())
        live = self._expire_locked_out(list(batch), now)
        if not live:
            return
        wait_t = _timer("raft_tpu_serve_wait_seconds",
                        "enqueue-to-dispatch queue wait", self.name)
        for req in live:
            wait_t.observe(max(0.0, now - req.enqueue_t))
        payload_rows = sum(r.rows for r in live)
        bucket = 0
        try:
            bucket = self._policy.bucket_for(payload_rows)
            stacked, spans = coalesce([r.payload for r in live])
            padded = pad_rows(stacked, bucket)
            _gauge("raft_tpu_serve_inflight_rows",
                   "payload rows in the running device call",
                   self.name).set(payload_rows)
            exec_t = _timer("raft_tpu_serve_exec_seconds",
                            "padded device call latency", self.name)
            if self._retry_policy is not None:
                with exec_t.time():
                    out = self._retry_policy.call(
                        self._execute, padded,
                        verb="serve.%s" % self.name)
            else:
                with exec_t.time():
                    out = self._execute(padded)
            leaves = [x for x in jax.tree_util.tree_leaves(out)
                      if hasattr(x, "shape")]
            for leaf in leaves:
                expects(leaf.shape[0] == bucket,
                        "serve execute contract: leaf leading dim %d != "
                        "padded batch rows %d", leaf.shape[0], bucket)
            for req, (start, stop) in zip(live, spans):
                req.future._set_result(jax.tree_util.tree_map(
                    lambda leaf: leaf[start:stop], out))
        except Exception as e:  # noqa: BLE001 — relayed to every rider
            _counter("raft_tpu_serve_batch_errors_total",
                     "batches whose device call failed", self.name).inc()
            for req in live:
                req.future._set_exception(e)
            return
        finally:
            _gauge("raft_tpu_serve_inflight_rows",
                   "payload rows in the running device call",
                   self.name).set(0)
        # accounting only after a successful dispatch
        _counter("raft_tpu_serve_batches_total", "dispatched batches",
                 self.name).inc()
        _counter("raft_tpu_serve_requests_total", "served requests",
                 self.name).inc(len(live))
        _counter("raft_tpu_serve_payload_rows_total",
                 "real (caller) rows dispatched", self.name).inc(
                     payload_rows)
        _counter("raft_tpu_serve_padded_rows_total",
                 "zero-pad rows dispatched (waste)", self.name).inc(
                     bucket - payload_rows)
        _timer("raft_tpu_serve_batch_rows",
               "payload rows per batch (a row-count histogram riding "
               "the timer type; seconds formatting does not apply)",
               self.name).observe(float(payload_rows))
        _bucket_counter(self.name, bucket).inc()
