"""Embedded ops plane: a pull endpoint for scrapers + the sentinel home.

Everything the observability stack records — metrics registry, flight
recorder, SLO burn, program cost inventory, persist digests — was
snapshot-on-demand: an operator had to call ``metrics_snapshot()``
*in-process*.  No production deployment can do that.  The
:class:`OpsPlane` is the missing pull surface (docs/OBSERVABILITY.md
"Ops plane"): a stdlib ``http.server`` on a daemon thread, bound to
localhost by default, serving immutable snapshots of state other
threads already maintain.

Endpoints
---------
``GET /metrics``
    Prometheus text exposition (``MetricsRegistry.to_prometheus``):
    counters, gauges (+ ``_peak`` high-water series), timer summaries.
``GET /healthz``
    Cheap liveness verdict (200 ok / 503 degraded): per-service worker
    / breaker / pause / corruption flags plus the anomaly sentinel's
    degraded flag — NO selftest battery, no device work, so a 1 Hz
    scraper costs nothing.  ``?full=1`` (session-backed planes only)
    runs the session's full ``health_check()`` battery behind a TTL
    cache (``ops_healthz_ttl_s``) so repeated scrapes never re-run it;
    the battery compiles throwaway probe programs, so point only a
    *slow* prober at ``full`` (the default path is the scrape target).
``GET /statusz``
    One JSON screen: per-service ``stats()`` (breaker / replica / SLO
    / persist digests), sentinel status, program-inventory summary,
    flight-recorder occupancy + black-box headers, tuning-table info.
``GET /debug/traces?k=N``
    The slowest-K requests (exemplar reservoirs) with their event
    timelines reconstructed from the flight ring.
``GET /debug/config``
    ``config.describe(layers=True)`` — every knob with the resolution
    rung that answered (tuning-table attribution included).
``GET /debug/inventory``
    The full per-(fn, shape) program cost inventory.
``GET /debug/snapshot``
    The machine-readable union (metrics + compile cache + flight +
    inventory) — what ``tools/metrics_report.py --watch`` polls.
``POST /debug/blackbox``
    Manual black-box dump trigger (``?reason=...``); returns the dump
    header.

The no-jax contract
-------------------
Every handler reads host-side Python state: registry snapshots, flight
copies, service stats.  A scrape can therefore never compile, never
touch a device, never block the serve worker loop, and never perturb
the zero-post-warmup-compiles invariant — and ``ci/style_check.py``'s
``ops-jax-ban`` enforces it *statically*: this module (and
``sentinel.py``) must not import or reference jax at all.  The one
deliberate exception is ``/healthz?full=1``, which calls the
*session's* ``health_check`` — the session owns that jax surface, the
handler only caches its verdict.

The sentinel (:mod:`raft_tpu.serve.sentinel`) is constructed and
registered here by default: serve workers poke it on their maintenance
seam, and the plane runs a fallback ticker thread so an idle process
still notices.  ``/healthz`` flips degraded while any rule is
breached.

Lifecycle: ``OpsPlane(session)`` / ``OpsPlane(services={...})``;
``Session.serve_ops(port=...)`` constructs, registers, and has
``destroy()`` close it.  ``port=0`` binds an ephemeral port
(``plane.port`` reads it back — tests and loadgen use this).
"""

from __future__ import annotations

import http.server
import itertools
import json
import threading
import time
import urllib.parse
from typing import Callable, Dict, Optional

from raft_tpu import config
from raft_tpu.core import flight
from raft_tpu.core import inventory as _inventory
from raft_tpu.core import metrics as _metrics
from raft_tpu.core.error import expects
from raft_tpu.serve import sentinel as _sentinel

__all__ = ["OpsPlane"]

_plane_seq = itertools.count()


def _counter(endpoint: str, code: int):
    return _metrics.default_registry().counter(
        "raft_tpu_ops_requests_total",
        help="ops-plane HTTP requests served, by endpoint and status",
        labels=("endpoint", "code")).labels(endpoint=endpoint,
                                            code=code)


def _timer(endpoint: str):
    return _metrics.default_registry().timer(
        "raft_tpu_ops_request_seconds",
        help="ops-plane HTTP handler latency",
        labels=("endpoint",)).labels(endpoint=endpoint)


class OpsPlane:
    """Module-doc embedded ops server.

    Parameters
    ----------
    session:
        Optional owning :class:`raft_tpu.session.Comms`: supplies the
        live service registry and the ``?full=1`` health battery.
    services:
        Alternative static ``{name: service}`` map (standalone tools —
        loadgen, bench — have services but no session).
    host / port:
        Bind address; ``port=0`` picks an ephemeral port (read it back
        from :attr:`port`).  Localhost by default: the ops plane is an
        infrastructure surface, not an internet one.
    healthz_ttl_s:
        Full-battery cache lifetime (None = the ``ops_healthz_ttl_s``
        knob).
    sentinel:
        ``True`` (default) constructs + registers an
        :class:`~raft_tpu.serve.sentinel.AnomalySentinel` over the
        plane's services; an instance uses that instance; ``False``
        disables (``/healthz`` then reports service flags only).
    sentinel_interval_s:
        Fallback ticker period (None = the ``ops_sentinel_interval_s``
        knob); the ticker is a daemon thread that only matters when no
        serve worker is poking the sentinel.
    start:
        Bind + serve now (False = call :meth:`start` later; tests).
    """

    def __init__(self, session=None, services: Optional[Dict] = None, *,
                 host: str = "127.0.0.1", port: int = 0,
                 healthz_ttl_s: Optional[float] = None,
                 sentinel=True,
                 sentinel_interval_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic,
                 start: bool = True):
        expects(session is None or services is None,
                "OpsPlane: pass a session or a services map, not both")
        self._session = session
        self._static_services = dict(services or {})
        self._host = host
        self._want_port = int(port)
        self._ttl = (config.get_float("ops_healthz_ttl_s")
                     if healthz_ttl_s is None else float(healthz_ttl_s))
        self._clock = clock
        self._name = "ops%d" % next(_plane_seq)
        self._lock = threading.Lock()
        self._health_fetch_lock = threading.Lock()
        self._health_cache: Optional[dict] = None
        self._health_cache_t: Optional[float] = None
        self._started_t: Optional[float] = None
        self._bound_port: Optional[int] = None
        self._server = None
        self._server_thread = None
        self._ticker = None
        self._ticker_stop = threading.Event()
        self._closed = False
        if sentinel is True:
            self.sentinel = _sentinel.AnomalySentinel(
                self._services, interval_s=sentinel_interval_s,
                clock=clock)
        elif sentinel is False or sentinel is None:
            self.sentinel = None
        else:
            self.sentinel = sentinel
        if start:
            self.start()

    # ------------------------------------------------------------------ #
    # lifecycle
    # ------------------------------------------------------------------ #
    def start(self) -> "OpsPlane":
        """Bind the socket and spawn the serving + ticker threads
        (idempotent while open; raises once closed).  The sentinel is
        registered for worker-seam pokes only AFTER the bind succeeds
        — a failed bind (port in use) must not leak a permanently
        registered zombie sentinel holding the session alive."""
        expects(not self._closed, "OpsPlane %s is closed", self._name)
        if self._server is not None:
            return self
        plane = self

        class _Handler(http.server.BaseHTTPRequestHandler):
            # the plane's logging is its metrics; stderr noise per
            # scrape would be operationally hostile
            def log_message(self, *args):  # noqa: D102
                pass

            def do_GET(self):
                plane._handle(self, "GET")

            def do_POST(self):
                plane._handle(self, "POST")

        self._server = http.server.ThreadingHTTPServer(
            (self._host, self._want_port), _Handler)
        # remember the ACTUAL bound port (port=0 means the kernel
        # picked one): fleet workers bind ephemeral and report this
        # through the registration handshake, and it must survive
        # close() so a supervisor can still log where a dead worker
        # had been listening
        self._bound_port = int(self._server.server_address[1])
        self._server.daemon_threads = True
        self._server_thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="raft-tpu-%s" % self._name)
        self._server_thread.start()
        self._started_t = self._clock()
        if self.sentinel is not None:
            _sentinel.register(self.sentinel)
            self._ticker_stop.clear()
            self._ticker = threading.Thread(
                target=self._tick_loop, daemon=True,
                name="raft-tpu-%s-sentinel" % self._name)
            self._ticker.start()
        return self

    @property
    def port(self) -> Optional[int]:
        """Actual bound port (None until first :meth:`start`).  With
        ``port=0`` this is the kernel-assigned ephemeral port; it
        stays readable after :meth:`close` (the registration
        handshake and post-mortem logs need it)."""
        return self._bound_port

    @property
    def url(self) -> Optional[str]:
        p = self.port
        return None if p is None else "http://%s:%d" % (self._host, p)

    @property
    def closed(self) -> bool:
        with self._lock:
            return self._closed

    def close(self) -> None:
        """Stop serving and the ticker; idempotent."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._ticker_stop.set()
        if self.sentinel is not None:
            _sentinel.unregister(self.sentinel)
        srv, self._server = self._server, None
        if srv is not None:
            srv.shutdown()
            srv.server_close()
        t = self._server_thread
        if t is not None and t.is_alive():
            t.join(timeout=5.0)
        t = self._ticker
        if t is not None and t.is_alive():
            t.join(timeout=5.0)

    def __enter__(self) -> "OpsPlane":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def _tick_loop(self) -> None:
        interval = (self.sentinel._interval
                    if self.sentinel is not None else 1.0)
        while not self._ticker_stop.wait(timeout=max(0.05, interval)):
            _sentinel.poke()

    # ------------------------------------------------------------------ #
    # plumbing
    # ------------------------------------------------------------------ #
    def _services(self) -> Dict[str, object]:
        if self._session is not None:
            try:
                return dict(self._session.services)
            except Exception:  # serve-exc-ok: a torn-down session scrapes as empty
                return {}
        return dict(self._static_services)

    def _handle(self, req, method: str) -> None:
        parsed = urllib.parse.urlparse(req.path)
        qs = urllib.parse.parse_qs(parsed.query)
        endpoint = parsed.path.rstrip("/") or "/"
        routes = {
            ("GET", "/"): self._ep_index,
            ("GET", "/metrics"): self._ep_metrics,
            ("GET", "/healthz"): self._ep_healthz,
            ("GET", "/statusz"): self._ep_statusz,
            ("GET", "/debug/traces"): self._ep_traces,
            ("GET", "/debug/config"): self._ep_config,
            ("GET", "/debug/inventory"): self._ep_inventory,
            ("GET", "/debug/snapshot"): self._ep_snapshot,
            ("POST", "/debug/blackbox"): self._ep_blackbox,
        }
        fn = routes.get((method, endpoint))
        t0 = self._clock()
        if fn is None:
            known = endpoint in {p for _, p in routes}
            code, body, ctype = (405 if known else 404), json.dumps(
                {"error": "method not allowed" if known
                 else "unknown endpoint",
                 "endpoints": sorted({p for _, p in routes})}), \
                "application/json"
            if not known:
                # the metric label set must stay BOUNDED: a client
                # probing arbitrary paths (port scanner, favicon
                # fetches) must not mint one registry series per path
                endpoint = "unknown"
        else:
            try:
                code, body, ctype = fn(qs)
            except Exception as e:  # serve-exc-ok: relayed as the 500 body + status counter
                code, body, ctype = 500, json.dumps(
                    {"error": "%s: %s" % (type(e).__name__, e)}), \
                    "application/json"
        payload = body.encode("utf-8")
        try:
            req.send_response(code)
            req.send_header("Content-Type",
                            ctype + "; charset=utf-8")
            req.send_header("Content-Length", str(len(payload)))
            req.end_headers()
            if method != "HEAD":
                req.wfile.write(payload)
        except (BrokenPipeError, ConnectionError):
            pass  # scraper hung up mid-write; nothing to salvage
        _counter(endpoint, code).inc()
        _timer(endpoint).observe(max(0.0, self._clock() - t0))

    @staticmethod
    def _json(obj, code: int = 200):
        return code, json.dumps(obj, indent=1, sort_keys=True,
                                default=str), "application/json"

    # ------------------------------------------------------------------ #
    # endpoints
    # ------------------------------------------------------------------ #
    def _ep_index(self, qs):
        return self._json({
            "service": "raft_tpu ops plane",
            "endpoints": ["/metrics", "/healthz", "/statusz",
                          "/debug/traces", "/debug/config",
                          "/debug/inventory", "/debug/snapshot",
                          "/debug/blackbox (POST)"],
        })

    def _ep_metrics(self, qs):
        return (200, _metrics.default_registry().to_prometheus(),
                "text/plain; version=0.0.4")

    def _cheap_service_health(self) -> Dict[str, dict]:
        """Per-service liveness flags from direct state reads — no
        ``stats()`` (which snapshots SLO trackers), no battery, no
        jax.  The same conditions session ``health_check`` fails on,
        minus the mesh/selftest half that needs devices."""
        out = {}
        for name, svc in self._services().items():
            flags = {"open": bool(getattr(svc, "is_open",
                                          lambda: True)())}
            worker = getattr(svc, "worker", None)
            if worker is not None:
                flags["worker_alive"] = (not worker.dead()
                                         if worker.started() else None)
            batcher = getattr(svc, "batcher", None)
            if batcher is not None:
                flags["paused"] = bool(batcher.paused())
                flags["queue_depth"] = int(batcher.depth())
            breaker = getattr(svc, "breaker", None)
            if breaker is not None:
                flags["breaker"] = breaker.state.name.lower()
            persist = getattr(svc, "_persist", None)
            if persist is not None:
                flags["corruption_detected"] = bool(
                    persist.corruption_detected)
            maint = getattr(worker, "last_maintenance_error", None)
            if maint:
                flags["last_maintenance_error"] = maint
            out[name] = flags
        return out

    @staticmethod
    def _service_flags_ok(flags: dict) -> bool:
        if not flags.get("open", True):
            return True   # an intentionally closed service passes
        if flags.get("worker_alive") is False:
            return False
        if flags.get("breaker") == "open":
            return False
        if flags.get("corruption_detected"):
            return False
        return True

    def _ep_healthz(self, qs):
        full = qs.get("full", ["0"])[0] not in ("", "0")
        degraded = (self.sentinel.degraded()
                    if self.sentinel is not None else False)
        out = {
            "degraded": degraded,
            "anomalies": (self.sentinel.active()
                          if self.sentinel is not None else []),
        }
        services = self._cheap_service_health()
        ok = all(self._service_flags_ok(f) for f in services.values())
        out["services"] = services
        if full and self._session is not None:
            report, age = self._full_health()
            out["full"] = report
            out["full_age_s"] = round(age, 3)
            ok = ok and bool(report.get("ok"))
        out["ok"] = ok and not degraded
        return self._json(out, 200 if out["ok"] else 503)

    def _full_health(self):
        """The session battery behind the TTL cache: scrapes within
        ``ops_healthz_ttl_s`` of each other share one run (the battery
        compiles probe programs — it must never run per request).
        Concurrent cold-cache scrapers serialize on the fetch lock
        and all but the first re-read the cache — N simultaneous
        ``?full=1`` requests run ONE battery, not N."""

        def cached(now):
            if (self._health_cache is not None
                    and now - self._health_cache_t <= self._ttl):
                return self._health_cache, now - self._health_cache_t
            return None

        with self._lock:
            hit = cached(self._clock())
        if hit is not None:
            return hit
        with self._health_fetch_lock:
            with self._lock:
                hit = cached(self._clock())
            if hit is not None:
                return hit
            report = self._session.health_check()
            with self._lock:
                self._health_cache = report
                self._health_cache_t = self._clock()
        return report, 0.0

    def _ep_statusz(self, qs):
        services = {}
        for name, svc in self._services().items():
            try:
                services[name] = svc.stats()
            except Exception as e:  # serve-exc-ok: relayed in the response body
                services[name] = {"error": "%s: %s"
                                  % (type(e).__name__, e)}
        out = {
            "uptime_s": (None if self._started_t is None else
                         round(self._clock() - self._started_t, 3)),
            "services": services,
            "sentinel": (self.sentinel.status()
                         if self.sentinel is not None else None),
            "inventory": self._inventory_with_roofline(),
            "flight": flight.flight_snapshot(),
            "tuning_table": config.tuning_table_info(),
        }
        return self._json(out)

    @staticmethod
    def _inventory_with_roofline() -> dict:
        """The cost-inventory summary joined to each fn's measured
        execution timer: cost-model flops ÷ measured mean seconds =
        a roofline-style achieved-throughput figure per executable
        family.  Two columns bracket the truth: host-side dispatch
        timing (``raft_tpu_jit_<fn>_seconds``, async — an upper bound
        on achieved rate) and the device-complete serve bracket
        (``raft_tpu_serve_device_seconds{fn=...}``, closed only after
        ``block_until_ready`` — a firm floor).  The same join
        ``tools/metrics_report.py`` renders."""
        inv = _inventory.summary()
        reg = _metrics.default_registry()
        # device-complete serve bracket, aggregated over services per
        # executable family (the fn label is the inventory join key)
        device: dict = {}
        fam = reg.get("raft_tpu_serve_device_seconds")
        if fam is not None:
            for lbls, series in fam.series():
                fn = lbls.get("fn")
                if fn and series.count:
                    agg = device.setdefault(fn, [0, 0.0])
                    agg[0] += series.count
                    agg[1] += series.total
        for fn, st in inv["per_fn"].items():
            fam = reg.get("raft_tpu_jit_%s_seconds" % fn)
            if fam is not None:
                for _, series in fam.series():
                    if series.count:
                        mean_s = series.total / series.count
                        st["exec_mean_s"] = round(mean_s, 6)
                        if mean_s > 0 and st["max_flops"] > 0:
                            st["achieved_gflops_upper"] = round(
                                st["max_flops"] / mean_s / 1e9, 3)
                    break
            agg = device.get(fn)
            if agg and agg[0]:
                dev_mean = agg[1] / agg[0]
                st["device_mean_s"] = round(dev_mean, 6)
                if dev_mean > 0 and st["max_flops"] > 0:
                    st["achieved_gflops_device"] = round(
                        st["max_flops"] / dev_mean / 1e9, 3)
        return inv

    def _ep_traces(self, qs):
        try:
            k = int(qs.get("k", ["5"])[0])
        except ValueError:
            return self._json({"error": "k must be an integer"}, 400)
        k = max(1, min(64, k))
        # slowest-K across THIS plane's services' exemplar reservoirs
        # (the module registry is process-global; a plane reports its
        # own world), each joined back to its ring events (a resolved
        # request's Trace object lives on its future; the ring names
        # riders per event, so the waterfall is reconstructable
        # server-side)
        mine = set(self._services())
        worst = []
        for svc, exemplars in flight.exemplars_snapshot().items():
            if mine and svc not in mine:
                continue
            for e in exemplars:
                worst.append((e["latency_ms"], svc, e["trace_id"]))
        worst.sort(reverse=True)
        events = flight.default_recorder().events()
        out = []
        for latency_ms, svc, tid in worst[:k]:
            timeline = [ev.to_dict() for ev in events
                        if ev.trace_id == tid
                        or (ev.attrs
                            and tid in ev.attrs.get("traces", ()))]
            out.append({"trace_id": tid, "service": svc,
                        "latency_ms": latency_ms,
                        "events": timeline,
                        "ring_truncated": not timeline})
        return self._json({"k": k, "traces": out})

    def _ep_config(self, qs):
        return self._json({
            "knobs": config.describe(layers=True),
            "tuning_table": config.tuning_table_info(),
        })

    def _ep_inventory(self, qs):
        return self._json({"summary": _inventory.summary(),
                           "detail": _inventory.snapshot()})

    def _ep_snapshot(self, qs):
        from raft_tpu.core.profiler import compile_cache_stats

        inv = _inventory.summary()
        inv["detail"] = _inventory.snapshot()
        return self._json({
            "metrics": _metrics.default_registry().snapshot(),
            "compile_cache": compile_cache_stats(),
            "flight": flight.flight_snapshot(),
            "inventory": inv,
        })

    def _ep_blackbox(self, qs):
        reason = qs.get("reason", ["manual"])[0] or "manual"
        dump = flight.default_recorder().blackbox(
            "ops_%s" % reason)
        return self._json({"reason": dump["reason"], "at": dump["at"],
                           "n_events": len(dump["events"])})
