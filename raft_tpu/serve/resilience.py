"""Serving resilience: fault seam, circuit breaker, recovery orchestration.

PR 1 gave the *comms* layer a failure contract (seedable fault
injection at the execute seam, retry/watchdog, session
``health_check()`` / ``recover()``); this module is where that contract
meets the serving layer (docs/FAULT_MODEL.md "Serving failure model").
Four pieces:

**Serve-seam fault injection** — :func:`inject_worker` patches
:attr:`ServeWorker._execute` exactly the way
:func:`raft_tpu.comms.faults.inject` patches ``HostComms._execute``,
reusing the same seedable fault vocabulary (``FailNth`` / ``Delay`` /
``RandomFail``), so serving failures are testable deterministically on
the simulated mesh.  The injector sits *below* the worker's
retry/breaker machinery: an injected failure takes exactly the path a
real device failure takes.

**Circuit breaker** — :class:`CircuitBreaker` tracks per-service batch
outcomes (consecutive and windowed failure counts; caller bugs —
``CALLER_BUG_ERRORS`` — are classified out: a shape error is the
rider's bug, not a service outage).  On trip, admission sheds fast with
:class:`~raft_tpu.core.error.ServiceUnavailableError` instead of
queueing requests into a broken worker, the worker holds dispatch, and
after ``cooldown_s`` half-open probe traffic re-closes (or re-opens)
the breaker — self-healing for transient faults without any operator
in the loop.

**Recovery orchestration** — :class:`RecoveryManager` owns the
sequence a *persistent* failure (device loss) needs: pause admission,
quiesce in-flight work, rebuild the communicator on the surviving
devices (``session.recover()``), re-publish service state
(``post_recover()`` — ANNService carries its immutable ``(index,
delta)`` snapshot across the rebuild), re-run ``warmup()`` so every
bucketed executable (donating twins included) exists on the new mesh,
restart dead workers, and re-admit.  Riders in flight at the moment of
failure were re-enqueued once by the worker (never lost); the queued
backlog serves out after re-admission.

**Degraded-mode dispatch** — lives in
:class:`~raft_tpu.serve.ann_service.ANNService`: under a
tripped-but-recovering (half-open) or queue-pressured service it steps
down its calibrated nprobe ladder (quality brownout, counted via the
``raft_tpu_serve_degraded_*`` family) instead of shedding; this module
provides the breaker state it keys off.

Metrics (labels ``service=``): ``raft_tpu_serve_breaker_state`` gauge
(0=closed, 1=open, 2=half-open), ``raft_tpu_serve_breaker_trips_total``,
``raft_tpu_serve_breaker_probes_total``,
``raft_tpu_serve_unavailable_total`` (admission sheds),
``raft_tpu_serve_requeued_total`` (recovery re-enqueues, scheduler),
``raft_tpu_serve_recoveries_total`` + ``raft_tpu_serve_recovery_seconds``.
"""

from __future__ import annotations

import collections
import contextlib
import enum
import threading
import time
from typing import Callable, Dict, Iterator, List, Optional, Sequence

from raft_tpu.comms.faults import Fault, FaultInjector
from raft_tpu.core import flight
from raft_tpu.core.error import CALLER_BUG_ERRORS, expects
from raft_tpu.serve.scheduler import ServeWorker, _counter, _gauge, _timer

__all__ = ["BreakerState", "CircuitBreaker", "ServeFaultInjector",
           "inject_worker", "RecoveryManager"]


class BreakerState(enum.Enum):
    """Circuit-breaker state machine (the standard three states)."""

    CLOSED = 0       # healthy: admit + dispatch normally
    OPEN = 1         # tripped: shed admission, hold dispatch
    HALF_OPEN = 2    # cooled down: probe traffic decides close/re-open


_STATE_GAUGE = {BreakerState.CLOSED: 0, BreakerState.OPEN: 1,
                BreakerState.HALF_OPEN: 2}


class CircuitBreaker:
    """Per-service batch-failure tracker with trip / cool-down / probe.

    Parameters
    ----------
    name:
        Service name (the ``service=`` metric label).
    failure_threshold:
        Consecutive batch failures that trip the breaker (0 disables
        consecutive tracking).
    window / window_failures:
        Windowed tracking: trip when the last ``window`` outcomes
        contain ``window_failures`` failures — catches a flapping
        service whose failures never run consecutively
        (``window_failures=0`` disables).
    cooldown_s:
        How long OPEN sheds before HALF_OPEN probe traffic is let
        through.
    half_open_probes:
        Admissions allowed while HALF_OPEN (beyond them, submits shed
        until the probe outcome is known).
    close_after:
        Successful batches in HALF_OPEN needed to re-close.
    clock:
        Monotonic-seconds source; injectable for deterministic tests
        (the injectable-clock seam every serve component shares).

    Thread-safe; every transition lands on the
    ``raft_tpu_serve_breaker_*`` metric families.
    """

    def __init__(self, name: str, *,
                 failure_threshold: int = 5,
                 window: int = 16,
                 window_failures: int = 8,
                 cooldown_s: float = 0.25,
                 half_open_probes: int = 4,
                 close_after: int = 1,
                 clock: Callable[[], float] = time.monotonic):
        expects(failure_threshold >= 0,
                "CircuitBreaker: failure_threshold=%d", failure_threshold)
        expects(window >= 1, "CircuitBreaker: window=%d", window)
        expects(window_failures >= 0,
                "CircuitBreaker: window_failures=%d", window_failures)
        expects(window_failures <= window,
                "CircuitBreaker: window_failures=%d > window=%d",
                window_failures, window)
        expects(failure_threshold > 0 or window_failures > 0,
                "CircuitBreaker: both trip conditions disabled — the "
                "breaker could never open")
        expects(cooldown_s >= 0.0, "CircuitBreaker: cooldown_s=%r",
                cooldown_s)
        expects(half_open_probes >= 1,
                "CircuitBreaker: half_open_probes=%d", half_open_probes)
        expects(close_after >= 1, "CircuitBreaker: close_after=%d",
                close_after)
        self.name = name
        self.failure_threshold = int(failure_threshold)
        self.window = int(window)
        self.window_failures = int(window_failures)
        self.cooldown_s = float(cooldown_s)
        self.half_open_probes = int(half_open_probes)
        self.close_after = int(close_after)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = BreakerState.CLOSED
        self._consecutive = 0
        self._outcomes: "collections.deque[bool]" = collections.deque(
            maxlen=self.window)
        self._opened_t = 0.0
        self._half_open_t = 0.0
        self._probes_admitted = 0
        self._half_open_successes = 0
        self._publish_locked()

    # ------------------------------------------------------------------ #
    # state plumbing
    # ------------------------------------------------------------------ #
    def _publish_locked(self) -> None:
        _gauge("raft_tpu_serve_breaker_state",
               "circuit breaker state (0=closed 1=open 2=half-open)",
               self.name).set(_STATE_GAUGE[self._state])

    def _trip_locked(self) -> None:
        self._state = BreakerState.OPEN
        self._opened_t = self._clock()
        self._probes_admitted = 0
        self._half_open_successes = 0
        _counter("raft_tpu_serve_breaker_trips_total",
                 "circuit breaker trips (closed/half-open -> open)",
                 self.name).inc()
        self._publish_locked()
        # the black box: the trip's postmortem tape is captured AT the
        # trip — the last N flight events include the tripping batch's
        # lifecycle (docs/OBSERVABILITY.md "Flight recorder & request
        # tracing").  The recorder's lock nests safely under ours (it
        # never takes a breaker lock).
        flight.record("breaker_open", service=self.name,
                      consecutive=self._consecutive)
        flight.default_recorder().blackbox("breaker_trip",
                                           service=self.name)

    def _to_half_open_locked(self) -> None:
        self._state = BreakerState.HALF_OPEN
        self._half_open_t = self._clock()
        self._probes_admitted = 0
        self._half_open_successes = 0
        self._publish_locked()
        flight.record("breaker_half_open", service=self.name)

    def _close_locked(self) -> None:
        was_open = self._state is not BreakerState.CLOSED
        self._state = BreakerState.CLOSED
        self._consecutive = 0
        self._outcomes.clear()
        self._publish_locked()
        if was_open:
            flight.record("breaker_closed", service=self.name)

    def _maybe_cooled_locked(self) -> None:
        if (self._state is BreakerState.OPEN
                and self._clock() - self._opened_t >= self.cooldown_s):
            self._to_half_open_locked()

    @property
    def state(self) -> BreakerState:
        with self._lock:
            self._maybe_cooled_locked()
            return self._state

    def describe(self) -> Dict:
        """Small state dict (``Service.stats()`` / health_check embed
        it)."""
        with self._lock:
            self._maybe_cooled_locked()
            failures_in_window = sum(1 for ok in self._outcomes
                                     if not ok)
            return {
                "state": self._state.name.lower(),
                "consecutive_failures": self._consecutive,
                "window_failures": failures_in_window,
                "window": self.window,
                "cooldown_s": self.cooldown_s,
                "retry_after_s": self._retry_after_locked(),
            }

    def _retry_after_locked(self) -> float:
        if self._state is BreakerState.OPEN:
            return max(0.0,
                       self._opened_t + self.cooldown_s - self._clock())
        if (self._state is BreakerState.HALF_OPEN
                and self._probes_admitted >= self.half_open_probes):
            # probe budget spent: it refreshes a cooldown after
            # entering half-open (the liveness rule in allow())
            return max(0.0, self._half_open_t + self.cooldown_s
                       - self._clock())
        return 0.0

    # ------------------------------------------------------------------ #
    # admission / dispatch gates
    # ------------------------------------------------------------------ #
    def allow(self) -> bool:
        """Admission gate: True when a submit may enter the queue.
        OPEN sheds (until the cooldown elapses), HALF_OPEN admits up to
        ``half_open_probes`` probe requests."""
        with self._lock:
            self._maybe_cooled_locked()
            if self._state is BreakerState.CLOSED:
                return True
            if self._state is BreakerState.OPEN:
                return False
            if (self._probes_admitted >= self.half_open_probes
                    and self._clock() - self._half_open_t
                    >= self.cooldown_s):
                # liveness: a probe that never produced a batch outcome
                # (expired in queue, shed at the cap, malformed) must
                # not wedge HALF_OPEN shut forever — each elapsed
                # cooldown grants a fresh probe budget
                self._half_open_t = self._clock()
                self._probes_admitted = 0
            if self._probes_admitted < self.half_open_probes:
                self._probes_admitted += 1
                _counter("raft_tpu_serve_breaker_probes_total",
                         "half-open probe admissions", self.name).inc()
                return True
            return False

    def retry_after(self) -> float:
        """Seconds until this breaker can admit again — the
        ``ServiceUnavailableError.retry_after_s`` hint: an OPEN
        breaker's remaining cooldown, or a HALF_OPEN breaker's time to
        its next probe-budget refresh (0.0 when admitting)."""
        with self._lock:
            return self._retry_after_locked()

    def dispatch_hold(self) -> float:
        """Dispatch gate for the worker loop: seconds to hold off batch
        formation (>0 only while OPEN and still cooling down; the
        transition to HALF_OPEN happens here, so the first call after
        the cooldown returns 0 and the held backlog probes)."""
        with self._lock:
            if self._state is not BreakerState.OPEN:
                return 0.0
            remaining = self._retry_after_locked()
            if remaining > 0.0:
                return remaining
            self._to_half_open_locked()
            return 0.0

    # ------------------------------------------------------------------ #
    # outcome recording (the worker calls these per batch)
    # ------------------------------------------------------------------ #
    def record_success(self) -> None:
        """One batch served; in HALF_OPEN, ``close_after`` of these
        re-close the breaker."""
        with self._lock:
            self._consecutive = 0
            self._outcomes.append(True)
            if self._state is BreakerState.HALF_OPEN:
                self._half_open_successes += 1
                if self._half_open_successes >= self.close_after:
                    self._close_locked()

    def record_failure(self, exc: BaseException) -> bool:
        """One batch failed.  Returns True when the failure is
        *service-level* — the breaker is now (or already was) open — so
        the worker re-enqueues the riders once instead of failing them;
        False for a caller-bug (classified out, never counts toward the
        trip) or a failure the breaker absorbed without tripping."""
        if isinstance(exc, CALLER_BUG_ERRORS):
            return False
        with self._lock:
            if self._state is BreakerState.HALF_OPEN:
                # the probe failed: straight back to OPEN, new cooldown
                self._trip_locked()
                return True
            if self._state is BreakerState.OPEN:
                return True
            self._consecutive += 1
            self._outcomes.append(False)
            failures_in_window = sum(1 for ok in self._outcomes
                                     if not ok)
            if ((self.failure_threshold
                 and self._consecutive >= self.failure_threshold)
                    or (self.window_failures
                        and failures_in_window >= self.window_failures)):
                self._trip_locked()
                return True
            return False

    # ------------------------------------------------------------------ #
    # manual levers (RecoveryManager / tests)
    # ------------------------------------------------------------------ #
    def trip(self) -> None:
        """Force OPEN (recovery pauses admission through the same shed
        path traffic already understands)."""
        with self._lock:
            if self._state is not BreakerState.OPEN:
                self._trip_locked()
            else:
                self._opened_t = self._clock()

    def reset(self) -> None:
        """Force CLOSED, clearing all failure history (post-recovery
        re-admission: warmup just proved the rebuilt executables run)."""
        with self._lock:
            self._close_locked()


# ---------------------------------------------------------------------- #
# serve-seam fault injection (PR 1's comms harness, retargeted)
# ---------------------------------------------------------------------- #
class ServeFaultInjector(FaultInjector):
    """Patch one :class:`ServeWorker`'s ``_execute`` seam with the
    comms fault vocabulary (:mod:`raft_tpu.comms.faults`).

    The verb every fault matches is ``"serve.<worker name>"`` (pass
    ``verb=None`` faults to match unconditionally); the recorded key is
    ``(verb, padded_rows)`` so assertions can see which bucket a fault
    hit.  The patch sits below the worker's retry/breaker machinery —
    the layering contract of the comms seam, kept: injected failures
    are *seen* by the resilience layer, not bypassing it.

    ``FailNth`` / ``Delay`` / ``RandomFail`` compose as at the comms
    seam.  ``Abort`` is unsupported here (there is no communicator to
    latch — a persistent ``FailNth`` plays the dead-device role and the
    breaker plays the latch).
    """

    def __init__(self, worker: ServeWorker, faults_: List[Fault]):
        # the base class binds the patch target as self._comms; its
        # deactivate() restores self._comms._execute and is inherited
        # unchanged
        super().__init__(worker, faults_)
        self.verb = "serve.%s" % worker.name

    def activate(self) -> None:
        assert self._orig_execute is None, "injector already active"
        worker = self._comms
        self._orig_execute = worker._execute
        orig = self._orig_execute
        verb = self.verb

        def patched(padded):
            rows = int(getattr(padded, "shape", (0,))[0])
            self._fire(worker, verb, (verb, rows))
            return orig(padded)

        worker._execute = patched


@contextlib.contextmanager
def inject_worker(worker: ServeWorker,
                  *faults_: Fault) -> Iterator[ServeFaultInjector]:
    """Scoped serve-seam fault injection: patch ``worker._execute`` for
    the duration of the block, restore after (even on error).  The
    serving analog of :func:`raft_tpu.comms.faults.inject`::

        with inject_worker(svc.worker,
                           faults.FailNth(1, persistent=True)):
            ...   # every batch fails until the block exits
    """
    injector = ServeFaultInjector(worker, list(faults_))
    injector.activate()
    try:
        yield injector
    finally:
        injector.deactivate()


# ---------------------------------------------------------------------- #
# recovery orchestration
# ---------------------------------------------------------------------- #
class RecoveryManager:
    """Orchestrate serving recovery after a persistent failure.

    One manager spans a set of services — either an explicit list or a
    session's registered services (``Comms.serve``) — plus, optionally,
    the session itself so a device loss rebuilds the communicator on
    the surviving sub-mesh before the services warm back up.

    :meth:`recover` is THE sequence (docs/FAULT_MODEL.md):

    1. **pause** — every service stops forming batches
       (``MicroBatcher.pause``) and sheds new submits with
       :class:`~raft_tpu.core.error.ServiceUnavailableError`
       (``reason="recovering"``); queued requests stay queued.
    2. **quiesce** — wait for in-flight batches to clear the workers
       (their riders resolved, or re-enqueued by the breaker path).
    3. **rebuild** — ``session.recover(devices=...)``: fresh
       communicator on the survivors, re-injected on every handle.
    4. **re-publish + warmup** — per service: ``post_recover()``
       (ANNService re-materializes its immutable ``(index, delta)``
       snapshot — inserted rows survive the failure; sharded services
       additionally **re-partition** the lost shard's rows/slots
       across the surviving sub-mesh via ``repartition()``, exactly —
       the pinned full index is the re-shard source), then
       ``warmup()`` rebuilds every bucketed executable (donating twins
       and per-rung sharded SPMD programs included) on the new mesh.
    5. **re-admit** — restart a dead worker thread
       (:meth:`ServeWorker.restart`), resume batch formation, reset the
       breaker.  The queued backlog (including the riders re-enqueued
       at the moment of failure) serves out first.

    Call it from a supervising thread (an operator loop, a test, the
    chaos harness) — never from a worker thread: quiesce waits on the
    workers.  Serialized by an internal lock; concurrent calls queue.
    """

    def __init__(self, session=None,
                 services: Optional[Sequence] = None,
                 clock: Callable[[], float] = time.monotonic):
        expects(session is not None or services is not None,
                "RecoveryManager: pass a session and/or services")
        self._session = session
        self._explicit = list(services) if services is not None else None
        self._clock = clock
        self._lock = threading.Lock()

    def _services(self) -> List:
        svcs = list(self._explicit) if self._explicit is not None else []
        if self._session is not None:
            for svc in self._session.services.values():
                if svc not in svcs:
                    svcs.append(svc)
        return [s for s in svcs if s.is_open()]

    def recover(self, devices: Optional[Sequence] = None, mesh=None, *,
                recover_comms: Optional[bool] = None,
                warmup: bool = True,
                quiesce_timeout: float = 30.0) -> Dict:
        """Run the full recovery sequence (class doc); returns a report
        ``{"services": [names], "comms_recovered": bool,
        "recovery_s": float}``.

        ``devices`` / ``mesh`` name the survivors for the communicator
        rebuild (forwarded to ``Comms.recover``); ``recover_comms``
        defaults to True when the manager has an initialized session.
        ``warmup=False`` skips executable rebuild (transient faults
        where the mesh never changed — the executables are still
        valid).  ``"quiesced": False`` in the report flags a batch that
        was still wedged mid-dispatch past ``quiesce_timeout`` when the
        rebuild proceeded (its riders resolve against the old state —
        recovery cannot wait forever on a dead device call)."""
        if recover_comms is None:
            recover_comms = (self._session is not None
                             and getattr(self._session, "initialized",
                                         False))
        with self._lock:
            t0 = self._clock()
            svcs = self._services()
            # recovery phase events + the pre-recovery black box: the
            # tape of the seconds leading INTO the failure is captured
            # before the sequence mutates any state
            flight.record("recovery_begin",
                          services=[s.name for s in svcs],
                          comms=bool(recover_comms))
            flight.default_recorder().blackbox("recovery")
            for svc in svcs:
                svc.pause()
                flight.record("recovery_pause", service=svc.name)
            try:
                # materialized first: all() over a generator would stop
                # at the first wedged worker and leave later services
                # un-quiesced when the communicator rebuild starts
                quiesced = all([
                    svc.worker.quiesce(timeout=quiesce_timeout)
                    for svc in svcs])
                if recover_comms:
                    flight.record("recovery_rebuild_comms")
                    self._session.recover(devices=devices, mesh=mesh)
                for svc in svcs:
                    svc.post_recover()
                    if warmup:
                        svc.warmup()
                        flight.record("recovery_warmup",
                                      service=svc.name)
                    if (svc.worker.started()
                            and not svc.worker.is_alive()):
                        svc.worker.restart()
                    svc.resume()
                    flight.record("recovery_readmit", service=svc.name)
                    _counter("raft_tpu_serve_recoveries_total",
                             "completed serving recoveries",
                             svc.name).inc()
            except BaseException:
                # a FAILED recovery must not strand the queue behind a
                # paused batcher forever: un-pause (queued riders can
                # dispatch/expire/fail — each still resolves exactly
                # once) but leave each breaker in its tripped state —
                # the service is still broken and admission must keep
                # shedding until a later recovery succeeds
                for svc in svcs:
                    if svc.batcher.paused():
                        svc.batcher.resume()
                raise
            dt = self._clock() - t0
            for svc in svcs:
                _timer("raft_tpu_serve_recovery_seconds",
                       "pause-to-readmit recovery latency",
                       svc.name).observe(dt)
            flight.record("recovery_done",
                          services=[s.name for s in svcs],
                          quiesced=bool(quiesced),
                          recovery_s=round(dt, 6))
        return {"services": [s.name for s in svcs],
                "comms_recovered": bool(recover_comms),
                "quiesced": quiesced,
                "recovery_s": dt}

    def check_and_recover(self, **recover_kwargs) -> Dict:
        """Health-check the session and recover if anything is wrong:
        a failed ``health_check()`` (aborted communicator, dead device,
        dead worker) runs the full :meth:`recover` sequence on the
        devices the check reported live; an open breaker with an
        otherwise-healthy mesh takes the CHEAP path — re-admit without
        a communicator rebuild or re-warmup (the executables and mesh
        are fine; the breaker would have probed its way closed in a
        cooldown anyway, so escalating a transient trip into seconds of
        recompiles would be self-inflicted downtime).  Returns
        ``{"report": health report, "recovered": bool, "recovery":
        recover report or None}``."""
        expects(self._session is not None,
                "check_and_recover: manager has no session")
        report = self._session.health_check()
        breaker_open = any(
            getattr(getattr(svc, "breaker", None), "state", None)
            is BreakerState.OPEN for svc in self._services())
        if report["ok"] and not breaker_open:
            return {"report": report, "recovered": False,
                    "recovery": None}
        # the MESH verdict, not the overall one: health_check's ok also
        # fails on a tripped breaker / dead worker, which the cheap
        # path exists to handle without a communicator rebuild
        mesh_ok = (all(report["tests"].values())
                   and all(report["devices"].values()))
        if mesh_ok:
            # comms + devices healthy; only service-level trouble
            # (tripped breaker, dead worker): restart/re-admit without
            # rebuilding the communicator or recompiling executables
            recover_kwargs.setdefault("recover_comms", False)
            recover_kwargs.setdefault("warmup", False)
        if "devices" in recover_kwargs or "mesh" in recover_kwargs:
            survivors = recover_kwargs.pop("devices", None)
        else:
            survivors = [dev for dev, ok in report["devices"].items()
                         if ok]
        recovery = self.recover(devices=survivors, **recover_kwargs)
        return {"report": report, "recovered": True,
                "recovery": recovery}
