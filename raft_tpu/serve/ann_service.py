"""ANNService: serve the native IVF quantizers with streaming ingestion.

The brute-force :class:`~raft_tpu.serve.service.KNNService` tops out
where its per-query work does — a full index scan per padded batch.
:class:`ANNService` fronts :func:`raft_tpu.spatial.ann.approx_knn_search`
over a prebuilt IVF index (Flat / PQ / SQ behind the same constructor
argument) instead, turning the scan into a few probed slot matmuls, and
adds the two things a production vector store needs beyond a static
index:

**Recall-targeted dispatch.**  ``nprobe`` is the quality/latency knob,
and a hand-pinned value is almost always wrong for the workload (the
CUDA-L2 lesson in PAPERS.md: searched configurations beat fixed
defaults).  The service therefore owns a small *ladder* of candidate
``nprobe`` cells: :meth:`warmup` precompiles every bucket rung × every
cell, and :meth:`calibrate` measures recall@k (against an exact ground
truth) and latency per cell, then pins the smallest cell that meets the
caller's recall target — retargeting at runtime (:meth:`set_nprobe`)
never compiles.

**Streaming ingestion.**  :meth:`insert` appends vectors to a
fixed-capacity *delta segment*: a device-resident ``(delta_cap, dim)``
buffer scanned brute-force and merged into the IVF result stream
on-device (:func:`raft_tpu.spatial.ann._delta_merge_impl` via
``select_k``) — one static shape however full the segment is, so
ingestion never retraces the serving executables, and an inserted
vector is queryable by the *next formed batch* (the visibility point).
When the delta crosses ``compact_rows``, the serve worker loop's
maintenance seam re-clusters it into IVF slots
(:func:`raft_tpu.spatial.ann.ivf_flat_extend` — nearest-centroid
assignment, no k-means re-run) and **atomically swaps** the index
between batches, never mid-batch: every dispatched batch reads one
immutable ``(index, delta)`` snapshot, so results are deterministic
across the swap (on exact ties the merge keeps the base copy — the same
row answers identically from delta or from compacted storage).
Compaction runs on the existing worker thread — no second thread to
coordinate, drain/close ordering comes for free (``close`` joins the
worker, so a mid-flight compaction completes before teardown).

Donation (docs/ZERO_COPY.md): the padded query batch is donated to the
LAST program that consumes it (IVF scan, refine, or delta merge),
through the executable-twin machinery in :mod:`raft_tpu.spatial.ann` —
same contract as ``tiled_knn_donated``: the worker pays a defensive
copy in the one caller-aliasing case, and donation is off under a
``RetryPolicy`` (a retry would replay a consumed buffer).

Metrics (``raft_tpu_serve_ann_*``, labels ``service=`` plus ``nprobe=``
where noted): ``delta_rows`` gauge, ``inserts_total``,
``compactions_total`` / ``compacted_rows_total`` / ``compact_seconds``,
``calls_total{nprobe=}`` per-nprobe dispatch counts, and calibration's
``nprobe_seconds{nprobe=}`` / ``recall{nprobe=}`` — every speed claim
carries its quality number.
"""

from __future__ import annotations

import threading
import time
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu import config
from raft_tpu.core import flight
from raft_tpu.core import metrics as _metrics
from raft_tpu.core import tuning
from raft_tpu.core.error import ServiceOverloadError, expects, fail
from raft_tpu.serve.resilience import BreakerState
from raft_tpu.serve.service import (Service, _knob_float, _knob_int,
                                    _service_seq)
from raft_tpu.spatial import ann as _ann
from raft_tpu.spatial.knn import brute_force_knn

__all__ = ["ANNService"]


class _AnnState(NamedTuple):
    """One immutable serving snapshot: a dispatched batch reads exactly
    one of these (index + delta — and, when sharded, the slot-sharded
    mirror; when out-of-core, the hot set — travel together: the
    atomic-swap unit), so an insert, compaction, re-partition, or
    hot-set promotion can never tear a batch."""

    index: object           # IVFFlatIndex | IVFPQIndex | IVFSQIndex |
    #                         OocIVFFlat (out-of-core tier)
    delta_vecs: jnp.ndarray  # (delta_cap, dim) device, zeros past count
    delta_ids: jnp.ndarray   # (delta_cap,) int32 device, -1 past count
    delta_rows: int
    # slot-sharded mirror of ``index`` (ShardedIVFFlat committed to the
    # mesh), None on single-device services — rebuilt only when the
    # index object or the mesh changes, NOT on delta appends
    sharded: object = None
    # out-of-core hot set: (hot_vecs, hot_ids_device, hot_mask_numpy)
    # or None — swapped whole by promotion/compaction, never mutated
    ooc_hot: object = None
    # last write-ahead-log sequence number whose insert is CONTAINED
    # in this state (docs/PERSISTENCE.md): a snapshot taken from this
    # state records it as its replay floor, so WAL truncation can
    # never drop a record the snapshot does not hold
    wal_seq: int = 0


def _labeled(kind: str, name: str, help: str, service: str, **extra):
    """Registry family with ``service=`` plus optional extra labels,
    resolved per use (reset-proof, the scheduler helpers' rationale)."""
    label_names = ("service",) + tuple(sorted(extra))
    fam = getattr(_metrics.default_registry(), kind)(
        name, help=help, labels=label_names)
    return fam.labels(service=service, **extra)


def _parse_ladder(spec, nlist: int) -> tuple:
    """Resolve an nprobe-ladder spec (csv string or int sequence) into
    an ascending, deduplicated tuple clamped to ``nlist``."""
    if isinstance(spec, str):
        try:
            spec = [int(tok) for tok in spec.split(",") if tok.strip()]
        except ValueError:
            raise ValueError(
                "ANNService: nprobe ladder %r is not a comma-separated "
                "int list" % spec) from None
    cells = sorted({min(int(c), nlist) for c in spec if int(c) >= 1})
    expects(len(cells) > 0,
            "ANNService: empty nprobe ladder after clamping to nlist=%d",
            nlist)
    return tuple(cells)


class ANNService(Service):
    """Micro-batched :func:`~raft_tpu.spatial.ann.approx_knn_search`
    over one pinned IVF index, with streaming ingestion (module doc).

    Parameters
    ----------
    index:
        A prebuilt :class:`~raft_tpu.spatial.ann.IVFFlatIndex`,
        ``IVFPQIndex`` or ``IVFSQIndex`` — the constructor knob that
        picks the quantizer; build it with
        :func:`~raft_tpu.spatial.ann.approx_knn_build_index`.
    k:
        Neighbors returned per query row.
    nprobe:
        Probe count served by default; None resolves the
        ``serve_ann_nprobe`` knob (0 = the index's build-time default).
    nprobe_ladder:
        Candidate cells for :meth:`warmup` / :meth:`calibrate`
        (default: the ``serve_ann_nprobe_ladder`` knob), each clamped
        to the index's ``nlist``; the served ``nprobe`` is always
        included.
    refine_ratio:
        IVF-PQ exact re-rank ratio passthrough (ignored by Flat/SQ).
    delta_cap / compact_rows:
        Delta-segment capacity and the auto-compaction threshold
        (``serve_ann_delta_cap`` / ``serve_ann_compact_rows`` knobs);
        ``compact_rows=0`` disables auto-compaction.  Compaction
        requires an IVF-Flat index — PQ/SQ services still ingest into
        the delta but must be rebuilt offline (auto-compaction is
        forced off and :meth:`compact` raises).
    degrade_queue_frac:
        Degraded-mode dispatch (quality brownout, docs/FAULT_MODEL.md):
        when queued requests reach this fraction of the admission cap —
        or the circuit breaker is half-open after a trip — batches are
        served one step *down* the calibrated nprobe ladder (lower
        recall, lower latency, already warmed) instead of shedding; the
        calibrated cell is restored as soon as pressure clears.
        Defaults to the ``serve_ann_degrade_frac`` knob; ``0`` disables.
        Counted via the ``raft_tpu_serve_degraded_*`` family.
    ooc / device_budget_bytes / tile_slots / ooc_overlap /
    ooc_promote_batches:
        The out-of-core tier (docs/SERVING.md "Out-of-core serving"):
        ``ooc=True`` keeps the IVF-Flat slot store HOST-resident and
        serves it through a device working set bounded by
        ``device_budget_bytes`` (default: the
        ``serve_ann_device_budget_bytes`` knob) — a frequency-promoted
        hot set plus a double-buffered
        :class:`~raft_tpu.mr.TilePool` staging window of
        ``tile_slots``-slot tiles the cold probes stream through.
        ``ooc_overlap=False`` runs the synchronous-prefetch baseline
        (the bench's A/B arm); ``ooc_promote_batches`` gates how often
        maintenance re-evaluates the hot set.  IVF-Flat only; does not
        compose with ``axis=`` (shard the resident path instead).
        Passing a prebuilt :class:`~raft_tpu.spatial.ooc.OocIVFFlat`
        as ``index`` implies ``ooc=True``.
    persist_dir / persist_fsync / snapshot_interval_s / persist_mmap /
    scrub_chunks:
        Durable serving state (docs/PERSISTENCE.md): ``persist_dir``
        names a directory owning this service's checksummed snapshots
        and write-ahead log.  A directory holding state
        **auto-restores on construction** — snapshot load (every
        chunk CRC-verified) plus WAL-tail replay into the delta — and
        ``index=None`` is then legal (rebuild-from-directory, the
        crash-restart path).  ``persist_fsync``
        (``always``/``batch``/``off``) is the insert acknowledge
        contract, ``snapshot_interval_s`` gates maintenance-seam
        snapshots, ``persist_mmap`` backs a restored out-of-core
        store with a copy-on-write ``np.memmap``, and
        ``scrub_chunks`` sizes the per-tick integrity scrub (0
        disables).  Each defaults to its ``persist_*`` knob.
    **opts:
        The shared :class:`~raft_tpu.serve.service.Service` options
        (``max_batch_rows``, ``bucket_rungs``, ``max_wait_ms``,
        ``queue_cap``, ``retry_policy``, ``donate``, ``start``, ...).
    """

    def __init__(self, index, k: int, *,
                 nprobe: Optional[int] = None,
                 nprobe_ladder=None,
                 refine_ratio: Optional[int] = None,
                 delta_cap: Optional[int] = None,
                 compact_rows: Optional[int] = None,
                 degrade_queue_frac: Optional[float] = None,
                 slot_multiple: int = 64,
                 select_impl: Optional[str] = None,
                 ooc: bool = False,
                 device_budget_bytes: Optional[int] = None,
                 tile_slots: Optional[int] = None,
                 ooc_overlap: bool = True,
                 ooc_promote_batches: int = 32,
                 persist_dir: Optional[str] = None,
                 persist_fsync: Optional[str] = None,
                 snapshot_interval_s: Optional[float] = None,
                 persist_mmap: bool = False,
                 scrub_chunks: Optional[int] = None,
                 mesh=None, axis: Optional[str] = None,
                 merge: Optional[str] = None,
                 group_size: Optional[int] = None,
                 name: Optional[str] = None, **opts):
        from raft_tpu.spatial.ooc import OocIVFFlat

        # name resolved FIRST (it used to resolve just before
        # Service.__init__): the persist manager labels its metrics
        # and flight events by service name from restore onward
        name = name or "ann%d" % next(_service_seq)
        self.name = name

        # durability (docs/PERSISTENCE.md): a persist_dir holding
        # state auto-restores BEFORE anything reads the index — the
        # loaded snapshot replaces the constructor's index (which may
        # then be None: rebuild-from-directory, the crash-restart
        # path) and the WAL tail replays into the delta mirror below
        self._persist = None
        self._persist_wal_seq = 0
        restored = None
        if persist_dir is not None:
            from raft_tpu.persist import PersistManager

            self._persist = PersistManager(
                persist_dir, service=name, fsync=persist_fsync,
                snapshot_interval_s=snapshot_interval_s,
                scrub_chunks=scrub_chunks,
                clock=opts.get("clock", time.monotonic))
            if self._persist.has_state():
                restored = self._persist.restore(
                    mmap_store=persist_mmap)
                if restored.index is not None:
                    if index is not None:
                        expects(
                            int(index.centroids.shape[1])
                            == int(restored.index.centroids.shape[1]),
                            "ANNService: persist_dir %r holds a "
                            "dim-%d snapshot but the constructor "
                            "index is dim-%d", persist_dir,
                            int(restored.index.centroids.shape[1]),
                            int(index.centroids.shape[1]))
                    index = restored.index
        else:
            expects(persist_fsync is None
                    and snapshot_interval_s is None
                    and scrub_chunks is None and not persist_mmap,
                    "ANNService: persist_fsync/snapshot_interval_s/"
                    "scrub_chunks/persist_mmap are durability knobs "
                    "— pass persist_dir=")
        expects(index is not None,
                "ANNService: index=None requires persist_dir "
                "pointing at existing durable state (no snapshot or "
                "WAL found%s)" % ("" if persist_dir is None
                                  else " in %r" % persist_dir))

        kinds = (_ann.IVFFlatIndex, _ann.IVFPQIndex, _ann.IVFSQIndex,
                 OocIVFFlat)
        expects(isinstance(index, kinds),
                "ANNService: index must be an IVF index "
                "(IVFFlatIndex/IVFPQIndex/IVFSQIndex/OocIVFFlat), "
                "got %r", type(index).__name__)
        if isinstance(index, OocIVFFlat):
            ooc = True
        expects(k >= 1, "ANNService: k=%d", k)
        self.k = int(k)
        self._nlist = int(index.centroids.shape[0])
        dim = int(index.centroids.shape[1])
        dtype = jnp.dtype(index.centroids.dtype)
        self._refine_ratio = refine_ratio
        self._slot_multiple = int(slot_multiple)
        # per-service top-k impl pin, passed explicitly into every
        # search (the config-doc recommendation: an explicit argument
        # reaches the trace as a Python value and always takes effect);
        # "approx" is membership-exact and markedly faster at large k.
        # Validated through the candidate registry at CONSTRUCTION so
        # a typo'd pin fails here, not mid-dispatch inside a trace
        if select_impl is not None:
            tuning.check("select_impl", select_impl, site="ANNService",
                         explicit=True, k=int(k), dtype=dtype)
        self._select_impl = select_impl

        # slot-sharded SPMD dispatch (docs/SERVING.md "Sharded
        # serving"): the IVF slot stores row-shard over a mesh axis,
        # every batch runs one per-shard probe-scan + on-device top-k
        # merge — the delta segment stays replicated (it is small by
        # construction) and merges after the sharded program
        self._sharded_cache = None       # ShardedIVFFlat for _sharded_for
        self._sharded_for = None         # the index object it mirrors
        self._group_size = group_size
        self.merge = None
        if mesh is not None or axis is not None:
            expects(isinstance(index, _ann.IVFFlatIndex),
                    "ANNService: sharded serving requires an "
                    "IVFFlatIndex (PQ/SQ slot stores hold codes — no "
                    "sharded scan; serve them single-device)")
            # refine_ratio is a PQ-only knob and IVF-Flat ignores it on
            # BOTH arms — reject the combination rather than let it
            # look active in a sharded constructor
            expects(refine_ratio is None,
                    "ANNService: refine_ratio is PQ-only; sharded "
                    "serving is IVF-Flat-only — drop it")
            from raft_tpu.serve.service import _resolve_shard_spec

            self.mesh, self.axis, self.merge = _resolve_shard_spec(
                "ANNService", mesh, axis, merge)

        # out-of-core tier (docs/SERVING.md "Out-of-core serving"): the
        # slot store stays host-resident; a byte budget buys a
        # frequency-promoted hot set plus a double-buffered TilePool
        # staging window the cold slots stream through
        self._ooc = None                 # OocIVFFlat when enabled
        self._ooc_pool = None
        self._ooc_hot = None             # (vecs, ids_dev, mask_np)
        self._ooc_hot_cap = 0
        self._ooc_overlap = bool(ooc_overlap)
        expects(ooc or (device_budget_bytes is None
                        and tile_slots is None),
                "ANNService: device_budget_bytes/tile_slots are "
                "out-of-core knobs — pass ooc=True (a resident "
                "service silently ignoring a memory budget would be "
                "worse than an error)")
        if ooc:
            expects(self.axis is None,
                    "ANNService: ooc=True does not compose with "
                    "sharded serving (the tier trades device memory "
                    "for host streaming; shard the resident path "
                    "instead)")
            expects(refine_ratio is None,
                    "ANNService: refine_ratio is PQ-only; the "
                    "out-of-core tier is IVF-Flat-only — drop it")
            expects(isinstance(index, (_ann.IVFFlatIndex, OocIVFFlat)),
                    "ANNService: ooc=True requires an IVF-Flat index "
                    "(PQ/SQ stores are already memory-compressed; "
                    "serve them resident)")
            from raft_tpu.spatial import ooc as _ooc_mod

            self._ooc_mod = _ooc_mod
            if isinstance(index, _ann.IVFFlatIndex):
                index = _ooc_mod.ivf_flat_to_ooc(index)
            if (self._persist is not None
                    and not index.store.flags.writeable):
                # scrub quarantine rebuilds a poisoned slot IN PLACE
                # (docs/PERSISTENCE.md); a store that is a read-only
                # view of the build's jax buffer is copied once into
                # writable host memory (restored stores — full-read
                # or mode-"c" memmap — are already writable)
                index = index._replace(store=index.store.copy())
            self._ooc = index
            if device_budget_bytes is None:
                device_budget_bytes = _knob_int(
                    "serve_ann_device_budget_bytes")
            expects(device_budget_bytes > 0,
                    "ANNService: ooc=True needs a device budget — pass "
                    "device_budget_bytes= or set the "
                    "serve_ann_device_budget_bytes knob")
            self._ooc_budget = int(device_budget_bytes)
            slot_b = index.slot_bytes()
            if tile_slots is None:
                # auto-size: a tile is at most an eighth of the budget
                # (3 in flight + a hot set must all fit), capped at 32
                # slots — explicit tile_slots overrides
                tile_slots = min(32, index.n_slots,
                                 self._ooc_budget // (8 * (slot_b + 4)))
            tile_slots = max(1, min(int(tile_slots), index.n_slots))
            tile_b = tile_slots * (slot_b + 4)
            expects(self._ooc_budget >= 3 * tile_b,
                    "ANNService: device_budget_bytes=%d holds fewer "
                    "than 3 tiles of %d bytes — raise the budget or "
                    "shrink tile_slots", self._ooc_budget, tile_b)
            # budget split: H hot slots + one taken tile in flight +
            # two staged tiles (the double buffer)
            self._ooc_hot_cap = min(
                (self._ooc_budget - 3 * tile_b) // slot_b,
                index.n_slots)
            self._ooc_tile_slots = tile_slots
            self._ooc_pool_budget = max(
                2 * tile_b, self._ooc_budget
                - self._ooc_hot_cap * slot_b - tile_b)
            self._ooc_promote_batches = max(1, int(ooc_promote_batches))
            self._ooc_batches = 0
            # promotion signal: per-slot probe traffic (distinct slots
            # per batch, weighted by how many queries probed each)
            self._ooc_counters = np.zeros(index.n_slots, np.int64)
            # tile-miss-storm detection baselines (maintenance-seam
            # flight event, docs/OBSERVABILITY.md): cumulative batches
            # and the registry's miss counter at the last check.  The
            # miss baseline is seeded below, AFTER the pool exists —
            # the pool-labeled counter is process-global and a reused
            # service name must not inherit a dead incarnation's total
            # as its own first-window delta
            self._ooc_batches_total = 0
            self._storm_batches0 = 0
            self._storm_misses0 = 0.0

        if nprobe is None:
            nprobe = _knob_int("serve_ann_nprobe")
            if nprobe == 0:
                nprobe = int(index.nprobe)
        expects(nprobe >= 1, "ANNService: nprobe=%d", int(nprobe))
        self._nprobe = min(int(nprobe), self._nlist)
        if nprobe_ladder is None:
            # typed knob read: a malformed env ladder fails HERE as a
            # LogicError naming the knob + env var (config.py helpers)
            nprobe_ladder = config.get_int_list("serve_ann_nprobe_ladder")
        self._nprobe_ladder = _parse_ladder(nprobe_ladder, self._nlist)
        if self._nprobe not in self._nprobe_ladder:
            self._nprobe_ladder = tuple(sorted(
                self._nprobe_ladder + (self._nprobe,)))

        if delta_cap is None:
            delta_cap = _knob_int("serve_ann_delta_cap")
        expects(delta_cap >= 1, "ANNService: delta_cap=%d", delta_cap)
        self._delta_cap = int(delta_cap)
        if compact_rows is None:
            compact_rows = _knob_int("serve_ann_compact_rows")
        expects(compact_rows >= 0, "ANNService: compact_rows=%d",
                compact_rows)
        self._compactable = isinstance(
            index, _ann.IVFFlatIndex) or self._ooc is not None
        # PQ/SQ slot stores hold codes, not vectors: there is nothing
        # ivf_flat_extend could re-cluster — keep ingesting into the
        # delta, but never auto-compact (module doc)
        self._compact_rows = (min(int(compact_rows), self._delta_cap)
                              if self._compactable else 0)
        if degrade_queue_frac is None:
            degrade_queue_frac = _knob_float("serve_ann_degrade_frac")
        expects(0.0 <= degrade_queue_frac <= 1.0,
                "ANNService: degrade_queue_frac=%r", degrade_queue_frac)
        self._degrade_frac = float(degrade_queue_frac)
        # manual brownout lever (ladder steps); pressure/breaker checks
        # raise the effective level per batch without touching this
        self._degrade_hold = 0

        # delta segment: host mirror (the append target) + device
        # snapshot published in _ann_state; rows >= count carry id -1
        self._delta_lock = threading.Lock()
        self._compact_lock = threading.Lock()
        self._delta_vecs_np = np.zeros((self._delta_cap, dim),
                                       np.dtype(dtype))
        self._delta_ids_np = np.full(self._delta_cap, -1, np.int32)
        self._delta_count = 0
        # last observed compaction duration — the retry_after_s hint a
        # full-delta shed hands back ("wait one compaction out")
        self._last_compact_s = 0.0
        self._index = index
        if self._ooc is not None:
            from raft_tpu.mr.tile_pool import TilePool

            self._ooc_pool = TilePool(self._ooc_tile_slots,
                                      self._ooc_pool_budget,
                                      name=self.name)
            self._storm_misses0 = self._tile_misses_now()
            # initial hot set: slots of the biggest lists (the best
            # stand-in for probe traffic before any is observed);
            # promotion replaces it with the measured top-H
            self._ooc_hot_ids = self._ooc_ideal_hot()
            self._ooc_rebuild_hot()
        self._publish_state_locked()
        if restored is not None:
            self._apply_restore(restored)
        if self._persist is not None and self._persist.snapshot_seq == 0:
            # bootstrap snapshot: durability starts at construction,
            # not at the first maintenance tick — a crash before the
            # first interval must still restore, and a WAL-only
            # directory cannot rebuild the base index
            self._persist.snapshot(self._ann_state)

        def execute(padded):
            st = self._ann_state        # ONE snapshot per batch
            nprobe_now, degraded = self._effective_nprobe()
            delta = ((st.delta_vecs, st.delta_ids)
                     if st.delta_rows else None)
            _labeled("counter", "raft_tpu_serve_ann_calls_total",
                     "ANN batches dispatched per probe count",
                     self.name, nprobe=nprobe_now).inc()
            if degraded:
                _labeled("counter",
                         "raft_tpu_serve_degraded_batches_total",
                         "batches served below the calibrated quality "
                         "cell (nprobe brownout)", self.name).inc()
            _labeled("gauge", "raft_tpu_serve_degraded_active",
                     "whether the LAST dispatched batch was served "
                     "below the calibrated cell (per-batch signal; "
                     "idle services keep the last value)",
                     self.name).set(1 if degraded else 0)
            # donation routes the padded buffer into the last consuming
            # program's executable twin; self.donate is resolved by
            # Service.__init__ before any batch can run
            return self._snapshot_search(st, padded, nprobe_now,
                                         delta, self.donate)

        super().__init__(
            name, execute, dim=dim, dtype=dtype,
            maintenance=self._maintenance_tick, **opts)
        if self.axis is not None:
            _labeled("gauge", "raft_tpu_serve_shard_devices",
                     "devices the service's sharded index spans "
                     "(0/absent = single-device)", self.name).set(
                         int(self.mesh.shape[self.axis]))

    # ------------------------------------------------------------------ #
    # snapshot plumbing
    # ------------------------------------------------------------------ #
    def _snapshot_search(self, st: "_AnnState", q, nprobe, delta,
                         donate, force_rounds: int = 0):
        """ONE search entry for dispatch / warmup / calibrate: the
        slot-sharded SPMD program when the snapshot carries a sharded
        mirror, the streamed out-of-core arm when the service owns a
        tile pool, the single-device quantizer search otherwise — so
        every consumer measures/warms exactly what dispatch runs."""
        if st.sharded is not None:
            from raft_tpu.spatial.mnmg_knn import mnmg_ivf_flat_search

            return mnmg_ivf_flat_search(
                st.sharded, q, self.k, nprobe=nprobe,
                select_impl=self._select_impl, merge=self.merge,
                group_size=self._group_size, donate_queries=donate,
                delta=delta)
        if self._ooc_pool is not None:
            return self._ooc_mod.ooc_ivf_flat_search(
                st.index, q, self.k, nprobe=nprobe,
                pool=self._ooc_pool, hot=st.ooc_hot, delta=delta,
                donate_queries=donate,
                select_impl=self._select_impl,
                overlap=self._ooc_overlap,
                probe_hook=self._ooc_note_probes,
                force_rounds=force_rounds)
        return _ann.approx_knn_search(
            st.index, q, self.k, nprobe=nprobe,
            refine_ratio=self._refine_ratio, delta=delta,
            donate_queries=donate, select_impl=self._select_impl)

    def _publish_state_locked(self) -> None:
        """Rebuild the immutable serving snapshot from the host mirror
        (callers hold ``_delta_lock``, or are in ``__init__``).  The
        slot-sharded mirror is cached by index identity: a delta append
        republished here must NOT re-shard the whole index — only a
        compaction swap or a re-partition does."""
        sharded = None
        if self.axis is not None:
            if (self._sharded_cache is None
                    or self._sharded_for is not self._index):
                from raft_tpu.spatial.mnmg_knn import \
                    shard_ivf_flat_index

                self._sharded_cache = shard_ivf_flat_index(
                    self._index, self.mesh, self.axis)
                self._sharded_for = self._index
            sharded = self._sharded_cache
        self._ann_state = _AnnState(
            self._index,
            jnp.asarray(self._delta_vecs_np),
            jnp.asarray(self._delta_ids_np),
            self._delta_count,
            sharded,
            self._ooc_hot,
            self._persist_wal_seq)
        _labeled("gauge", "raft_tpu_serve_ann_delta_rows",
                 "rows in the append-only delta segment",
                 self.name).set(self._delta_count)

    @property
    def nprobe(self) -> int:
        return self._nprobe

    @property
    def nprobe_ladder(self) -> tuple:
        return self._nprobe_ladder

    @property
    def delta_rows(self) -> int:
        return self._ann_state.delta_rows

    @property
    def index(self):
        """The currently served index (post-compaction swaps visible)."""
        return self._ann_state.index

    def set_nprobe(self, nprobe: int) -> int:
        """Re-target the served probe count (clamped to ``nlist``);
        takes effect on the next formed batch.  Cells outside the
        warmed ladder serve correctly but pay a compile on first use."""
        expects(int(nprobe) >= 1, "set_nprobe: nprobe=%d", int(nprobe))
        self._nprobe = min(int(nprobe), self._nlist)
        return self._nprobe

    # ------------------------------------------------------------------ #
    # degraded-mode dispatch (quality brownout, docs/FAULT_MODEL.md)
    # ------------------------------------------------------------------ #
    def _degrade_level(self) -> int:
        """Ladder steps to walk down for the NEXT batch: the manual
        hold (:meth:`degrade`), plus one step while the queue is
        pressured past ``degrade_queue_frac`` of the admission cap or
        the breaker is half-open (tripped-but-recovering: probe traffic
        should be cheap traffic).  Evaluated per batch, so the
        calibrated cell restores the moment pressure clears."""
        level = self._degrade_hold
        if (self._degrade_frac > 0.0
                and self.batcher.depth()
                >= self._degrade_frac * self.batcher.queue_cap):
            level = max(level, 1)
        br = getattr(self, "breaker", None)
        if br is not None and br.state is BreakerState.HALF_OPEN:
            level = max(level, 1)
        return level

    def _effective_nprobe(self):
        """(nprobe, degraded) for the next batch: the served cell, or
        ``level`` ladder steps below it.  Every ladder cell is warmed,
        so a brownout never compiles."""
        base = self._nprobe
        level = self._degrade_level()
        if level <= 0:
            return base, False
        ladder = self._nprobe_ladder
        # index of the served cell (calibrate/set_nprobe pin ladder
        # cells; a hand-set off-ladder value maps to the nearest cell
        # at or below it)
        i = 0
        for j, cell in enumerate(ladder):
            if cell <= base:
                i = j
        eff = ladder[max(0, i - level)]
        return min(eff, base), eff < base

    def degrade(self, levels: int = 1) -> None:
        """Manually hold dispatch ``levels`` ladder steps below the
        calibrated cell (operator lever; the pressure/breaker checks
        engage on their own).  ``levels=0`` == :meth:`restore`."""
        expects(levels >= 0, "degrade: levels=%d", levels)
        self._degrade_hold = int(levels)

    def restore(self) -> None:
        """Release the manual brownout hold (pressure/breaker-driven
        degradation still applies while its cause persists)."""
        self._degrade_hold = 0
        if self._degrade_level() == 0:
            # clear the per-batch gauge now: an idle service would
            # otherwise report the pre-restore brownout until the next
            # batch happens to dispatch
            _labeled("gauge", "raft_tpu_serve_degraded_active",
                     "whether the LAST dispatched batch was served "
                     "below the calibrated cell (per-batch signal; "
                     "idle services keep the last value)",
                     self.name).set(0)

    # ------------------------------------------------------------------ #
    def repartition(self, mesh=None) -> bool:
        """Re-partition the slot shards over ``mesh`` (default: the
        owning session's current mesh) — the shard-loss lever: the
        lost shard's slots redistribute exactly across the surviving
        sub-mesh (the full index object is the re-shard source, so
        nothing is lost), and the delta segment re-materializes with
        them.  Call ``warmup()`` after.  True when the mesh changed."""
        expects(self.axis is not None,
                "%s.repartition: service is not sharded", self.name)
        mesh = self._recovery_mesh() if mesh is None else mesh
        expects(self.axis in mesh.axis_names,
                "%s.repartition: replacement mesh lacks axis %r",
                self.name, self.axis)
        changed = mesh is not self.mesh
        if changed:
            self._drop_stale_group_size(mesh)
        with self._delta_lock:
            self.mesh = mesh
            self._sharded_cache = None       # force the re-shard
            self._publish_state_locked()     # THE atomic swap
        if changed:
            self._record_repartition(mesh)
        return changed

    def post_recover(self) -> None:
        """Carry the serving snapshot across a mesh rebuild
        (:class:`~raft_tpu.serve.resilience.RecoveryManager` step 4):
        re-materialize the device-resident delta segment from the host
        mirror, re-partition the slot shards onto the rebuilt session
        mesh (sharded services), re-commit the out-of-core hot set
        from the host store (ooc services — the store itself never
        left host, so the tier recovers by replaying one hot-set
        transfer), and re-publish the immutable ``(index, delta)``
        snapshot — every row inserted before the failure is still
        queryable.  The index's own arrays are device-committed by the
        next search the rebuilt executables run (``warmup()`` follows
        this hook)."""
        if self.axis is not None:
            self.repartition()   # republishes the snapshot
            return
        with self._delta_lock:
            if self._ooc is not None:
                self._ooc_rebuild_hot()
            self._publish_state_locked()

    # ------------------------------------------------------------------ #
    # durability (docs/PERSISTENCE.md)
    # ------------------------------------------------------------------ #
    def _apply_restore(self, restored) -> None:
        """Re-enter the durable state (__init__ only, single-threaded):
        snapshot delta rows into the host mirror, then the WAL tail —
        every record beyond the snapshot's ``wal_seq`` — in sequence
        order.  A replay that would overflow the delta segment (the
        crash landed between a compaction and its snapshot) folds the
        full delta into the index first (:meth:`_fold_delta_locked`),
        exactly what compaction would have done — zero acknowledged
        rows lost either way."""
        with self._delta_lock:
            self._persist_wal_seq = int(restored.wal_seq)
            rows = int(restored.delta_rows)
            if rows:
                expects(rows <= self._delta_cap,
                        "%s: restored snapshot holds %d delta rows "
                        "but delta_cap is %d — restore with the "
                        "original capacity or larger", self.name,
                        rows, self._delta_cap)
                self._delta_vecs_np[:rows] = np.asarray(
                    restored.delta_vecs, self._delta_vecs_np.dtype)
                self._delta_ids_np[:rows] = np.asarray(
                    restored.delta_ids, np.int32)
                self._delta_count = rows
            dim = self._delta_vecs_np.shape[1]
            for seq, ids, vecs in restored.wal_records:
                expects(vecs.ndim == 2 and vecs.shape[1] == dim,
                        "%s: WAL record %d carries dim-%d vectors; "
                        "this service serves dim-%d", self.name,
                        int(seq), int(vecs.shape[1]), dim)
                n = int(vecs.shape[0])
                if self._delta_count + n > self._delta_cap:
                    self._fold_delta_locked()
                expects(self._delta_count + n <= self._delta_cap,
                        "%s: WAL record %d (%d rows) exceeds the "
                        "delta capacity %d even after folding",
                        self.name, int(seq), n, self._delta_cap)
                at = self._delta_count
                self._delta_vecs_np[at:at + n] = np.asarray(
                    vecs, self._delta_vecs_np.dtype)
                self._delta_ids_np[at:at + n] = np.asarray(
                    ids, np.int32)
                self._delta_count = at + n
                self._persist_wal_seq = int(seq)
            self._publish_state_locked()

    def _fold_delta_locked(self) -> None:
        """Restore-time inline compaction (caller holds
        ``_delta_lock``): extend the index with the full delta so WAL
        replay can keep appending — the same nearest-existing-centroid
        fold :meth:`compact` performs, minus the serving swap
        machinery (no traffic exists yet)."""
        expects(self._compactable,
                "%s: WAL replay overflowed the delta segment and a "
                "PQ/SQ index cannot be extended — raise delta_cap or "
                "rebuild offline", self.name)
        n0 = self._delta_count
        if n0 == 0:
            return
        vecs = self._delta_vecs_np[:n0].copy()
        keys = self._delta_ids_np[:n0].copy()
        old_index = self._index
        if self._ooc is not None:
            new_index = self._ooc_mod.ooc_extend(
                old_index, vecs, keys,
                slot_multiple=self._slot_multiple)
            self._ooc_remap_counters(old_index, new_index)
            self._ooc = new_index
            self._ooc_hot_ids = self._ooc_ideal_hot()
            self._ooc_rebuild_hot()
        else:
            new_index = _ann.ivf_flat_extend(
                old_index, vecs, keys,
                slot_multiple=self._slot_multiple)
        self._index = new_index
        self._delta_ids_np[:] = -1
        self._delta_count = 0

    # ------------------------------------------------------------------ #
    # out-of-core tier (docs/SERVING.md "Out-of-core serving")
    # ------------------------------------------------------------------ #
    def _ooc_ideal_hot(self) -> np.ndarray:
        """The H slots the hot set should hold right now: top-H by the
        observed per-slot probe counters (before any traffic: by owning
        list size, the best cold-start stand-in), deterministic under
        ties (slot id ascending), always EXACTLY ``_ooc_hot_cap`` ids —
        the hot block's shape is part of the compiled executables and
        must never drift."""
        ooc = self._ooc
        counters = self._ooc_counters
        if counters.any():
            priority = counters.astype(np.int64)
        else:
            priority = np.asarray(ooc.list_sizes,
                                  np.int64)[ooc.slot_centroid]
        # layout-padding slots (no valid rows: first entry is -1) sink
        # below every real slot
        first = np.asarray(ooc.slot_ids[:, 0])
        priority = np.where(first >= 0, priority, -1)
        order = np.lexsort((np.arange(priority.size), -priority))
        return np.sort(order[:self._ooc_hot_cap]).astype(np.int64)

    def _ooc_rebuild_hot(self) -> None:
        """(Re-)commit ``_ooc_hot_ids`` to device as the hot block and
        refresh the gauges.  Callers swap the snapshot after."""
        if self._ooc_hot_cap == 0:
            self._ooc_hot = None
        else:
            self._ooc_hot = self._ooc_mod.materialize_hot(
                self._ooc, self._ooc_hot_ids, pool_name=self.name)
        hot_n = 0 if self._ooc_hot is None else len(self._ooc_hot_ids)
        _labeled("gauge", "raft_tpu_ooc_hot_slots",
                 "slots resident in the out-of-core hot set",
                 self.name).set(hot_n)
        _labeled("gauge", "raft_tpu_ooc_hot_bytes",
                 "device bytes the out-of-core hot set occupies",
                 self.name).set(hot_n * self._ooc.slot_bytes())

    def _ooc_note_probes(self, distinct: np.ndarray,
                         counts: np.ndarray) -> None:
        """Per-batch probe-traffic hook (runs on whatever thread
        searches — worker, calibrate): feed the promotion counters.
        ``distinct`` is unique, so the fancy-index add is one atomic
        ufunc call; a search still reading a pre-compaction snapshot
        is bounds-guarded against the resized counter array."""
        c = self._ooc_counters
        if distinct.size and int(distinct[-1]) < c.size:
            c[distinct] += counts
        self._ooc_batches += 1
        self._ooc_batches_total += 1

    def _ooc_promote_tick(self) -> None:
        """Maintenance hook: swap the hot set to the measured top-H
        when probe traffic says the working set moved.  Gated on a
        batch interval and a minimum drift (an eighth of the hot set)
        so steady traffic never pays hot-set churn; the swap itself is
        one immutable-snapshot publish — in-flight batches keep their
        old hot block."""
        if (self._ooc_pool is None or self._ooc_hot_cap == 0
                or self._ooc_batches < self._ooc_promote_batches
                or self.batcher.draining()):
            return
        self._ooc_batches = 0
        with self._compact_lock:
            ideal = self._ooc_ideal_hot()
            cur = self._ooc_hot_ids
            fresh = np.setdiff1d(ideal, cur, assume_unique=True)
            if fresh.size <= max(1, self._ooc_hot_cap // 8):
                return
            evicted = np.setdiff1d(cur, ideal, assume_unique=True).size
            with self._delta_lock:
                self._ooc_hot_ids = ideal
                self._ooc_rebuild_hot()
                self._publish_state_locked()   # THE atomic swap
        _metrics.default_registry().counter(
            "raft_tpu_tile_evictions_total",
            help="hot-set slots demoted by frequency promotion",
            labels=("pool",)).labels(pool=self.name).inc(int(evicted))
        flight.record("hot_promote", service=self.name,
                      promoted=int(fresh.size), evicted=int(evicted),
                      hot_slots=int(self._ooc_hot_cap))

    def _ooc_remap_counters(self, old, new) -> None:
        """Carry the probe counters across a compaction's slot
        renumbering: centroids are stable under ``ooc_extend``
        (nearest-existing-centroid assignment, no k-means re-run), so
        the per-slot traffic aggregates to its owning centroid and
        redistributes evenly over the centroid's NEW slots — the
        promotion signal survives the swap instead of cold-starting."""
        nlist = int(old.centroids.shape[0])
        cent_tot = np.bincount(old.slot_centroid,
                               weights=self._ooc_counters,
                               minlength=nlist)
        slots_per = np.maximum(
            np.bincount(new.slot_centroid, minlength=nlist), 1)
        self._ooc_counters = (
            cent_tot[new.slot_centroid]
            // slots_per[new.slot_centroid]).astype(np.int64)

    # ------------------------------------------------------------------ #
    # warmup: every bucket rung x every nprobe cell, both delta arms
    # ------------------------------------------------------------------ #
    def warmup(self) -> "ANNService":
        """AOT-precompile every (bucket rung × nprobe cell) executable —
        and, per pair, BOTH serving arms: the empty-delta fast path and
        the delta-merge path (plus their donating twins where dispatch
        donates) — so steady-state traffic at any admissible shape,
        any ladder cell, and any delta fill performs zero compiles.

        Out-of-core services additionally force one streamed tile
        round per search (``force_rounds=1``): the probed set of the
        zeros warmup queries may land entirely in the hot set, and the
        tile-scan executables must not wait for the first real cold
        miss to compile."""
        st = self._ann_state
        blank_vecs = jnp.zeros((self._delta_cap, self.dim), self.dtype)
        blank_ids = jnp.full((self._delta_cap,), -1, jnp.int32)
        force = 1 if self._ooc_pool is not None else 0
        for rung in self.policy.rungs:
            for cell in self._nprobe_ladder:
                # fresh zeros per call: the donating arms consume them
                out = self._snapshot_search(
                    st, jnp.zeros((rung, self.dim), self.dtype),
                    cell, None, self.donate, force_rounds=force)
                jax.block_until_ready(out)
                out = self._snapshot_search(
                    st, jnp.zeros((rung, self.dim), self.dtype),
                    cell, (blank_vecs, blank_ids), self.donate,
                    force_rounds=force)
                jax.block_until_ready(out)
        self._warmed = self.policy.rungs
        return self

    # ------------------------------------------------------------------ #
    # streaming ingestion
    # ------------------------------------------------------------------ #
    def insert(self, ids, vectors) -> int:
        """Append vectors to the delta segment under caller-owned global
        ids (non-negative int32, disjoint from the index's ids — the
        caller's contract).  Visible to the next formed batch; returns
        the delta's row count after the append.

        Raises :class:`~raft_tpu.core.error.ServiceOverloadError` when
        the segment lacks room — back off and retry after compaction
        (automatic at ``compact_rows``, or call :meth:`compact`).
        """
        expects(self.is_open(), "%s.insert: service is closed", self.name)
        v = self._check_payload(vectors)
        key = np.asarray(ids, np.int32).ravel()
        expects(key.shape[0] == v.shape[0],
                "%s.insert: %d ids for %d vectors", self.name,
                key.shape[0], v.shape[0])
        expects(key.shape[0] == 0 or bool((key >= 0).all()),
                "%s.insert: negative ids (the delta reserves -1 for "
                "unfilled capacity)", self.name)
        n = int(v.shape[0])
        if n == 0:
            return self._delta_count
        expects(n <= self._delta_cap,
                "%s.insert: %d rows exceed the whole delta capacity %d",
                self.name, n, self._delta_cap)
        with self._delta_lock:
            at = self._delta_count
            if at + n > self._delta_cap:
                raise ServiceOverloadError(
                    "%s.insert: delta segment full (%d + %d > cap %d); "
                    "wait for compaction and retry" % (
                        self.name, at, n, self._delta_cap), at,
                    self._delta_cap,
                    retry_after_s=max(self._last_compact_s, 0.05))
            if self._persist is not None:
                # the acknowledge contract (docs/PERSISTENCE.md): the
                # record is in the WAL — durable per the fsync policy
                # — BEFORE the mirror mutates or the caller is acked;
                # an append failure raises with no state change
                self._persist_wal_seq = self._persist.wal_append(
                    key, np.asarray(v))
            self._delta_vecs_np[at:at + n] = np.asarray(v)
            self._delta_ids_np[at:at + n] = key
            self._delta_count = at + n
            self._publish_state_locked()
        _labeled("counter", "raft_tpu_serve_ann_inserts_total",
                 "vectors ingested into the delta segment",
                 self.name).inc(n)
        return at + n

    def _maintenance_tick(self) -> None:
        """Worker-loop hook: promote the out-of-core hot set when
        probe traffic moved, detect tile-miss storms, and compact when
        the delta crosses the threshold (never while draining — drain
        must serve out, not start index rebuilds)."""
        if self._ooc is not None:
            self._ooc_storm_check()
            self._ooc_promote_tick()
        if (self._compact_rows
                and self._delta_count >= self._compact_rows
                and not self.batcher.draining()):
            self.compact()
        if self._persist is not None:
            # durability tick (docs/PERSISTENCE.md): deferred WAL
            # fsync, interval-gated snapshot of the immutable state
            # (never mid-batch — this IS the maintenance seam), one
            # incremental scrub step
            self._persist.maintenance_tick(self._ann_state,
                                           ooc=self._ooc)

    def _tile_misses_now(self) -> float:
        """Current value of this service's pool-labeled tile-miss
        counter (0.0 before any miss) — the storm check's signal and
        its construction-time baseline."""
        fam = _metrics.default_registry().get("raft_tpu_tile_misses_total")
        if fam is not None:
            for labels, series in fam.series():
                if labels.get("pool") == self.name:
                    return float(series.value)
        return 0.0

    def _ooc_storm_check(self) -> None:
        """Flag a tile-miss storm into the flight recorder: the
        working set has outrun the hot set + staging window when the
        recent per-batch tile-miss rate exceeds the whole staging
        window (every batch re-streams more tiles than the double
        buffer holds).  Off the hot path — reads the registry counter
        on the maintenance seam only."""
        if self._ooc_pool is None:
            return
        batches = self._ooc_batches_total - self._storm_batches0
        if batches < 8:
            return
        misses = self._tile_misses_now()
        delta = misses - self._storm_misses0
        self._storm_batches0 = self._ooc_batches_total
        self._storm_misses0 = misses
        per_batch = delta / batches
        if per_batch > 2.0 * self._ooc_tile_slots:
            flight.record("tile_miss_storm", service=self.name,
                          misses_per_batch=round(per_batch, 2),
                          tile_slots=int(self._ooc_tile_slots),
                          batches=int(batches))

    def compact(self) -> bool:
        """Re-cluster the delta segment into IVF slots and atomically
        swap the served index (module doc); False when the delta was
        empty.  Safe from any thread (serialized by a lock); rows
        inserted *during* the rebuild stay in the delta for the next
        round — the compacted prefix is exact."""
        expects(self._compactable,
                "%s.compact: compaction requires an IVFFlatIndex (PQ/SQ "
                "stores hold codes; rebuild offline)", self.name)
        with self._compact_lock:
            with self._delta_lock:
                n0 = self._delta_count
                if n0 == 0:
                    return False
                vecs = self._delta_vecs_np[:n0].copy()
                keys = self._delta_ids_np[:n0].copy()
                old_index = self._index
            t0 = self._clock()
            if self._ooc is not None:
                # host-side rebuild: the extended store never touches
                # the device (the tier's whole point); only the small
                # metadata re-commits
                new_index = self._ooc_mod.ooc_extend(
                    old_index, vecs, keys,
                    slot_multiple=self._slot_multiple)
            else:
                new_index = _ann.ivf_flat_extend(
                    old_index, vecs, keys,
                    slot_multiple=self._slot_multiple)
                jax.block_until_ready(new_index.slot_vecs)
            with self._delta_lock:
                rem = self._delta_count - n0
                if rem:
                    self._delta_vecs_np[:rem] = \
                        self._delta_vecs_np[n0:self._delta_count]
                    self._delta_ids_np[:rem] = \
                        self._delta_ids_np[n0:self._delta_count]
                self._delta_ids_np[rem:] = -1
                self._delta_count = rem
                self._index = new_index
                if self._ooc is not None:
                    self._ooc_remap_counters(old_index, new_index)
                    self._ooc = new_index
                    self._ooc_hot_ids = self._ooc_ideal_hot()
                    self._ooc_rebuild_hot()
                self._publish_state_locked()   # THE atomic swap
        if self._persist is not None:
            # the on-disk snapshot no longer matches the served index
            # — the next maintenance tick persists the compacted form
            # and truncates the WAL of the rows it absorbed
            self._persist.note_dirty()
        _labeled("counter", "raft_tpu_serve_ann_compactions_total",
                 "delta-to-slots compactions", self.name).inc()
        _labeled("counter", "raft_tpu_serve_ann_compacted_rows_total",
                 "rows folded into IVF slots by compaction",
                 self.name).inc(n0)
        self._last_compact_s = self._clock() - t0
        _labeled("timer", "raft_tpu_serve_ann_compact_seconds",
                 "compaction latency (re-cluster + swap)",
                 self.name).observe(self._last_compact_s)
        flight.record("compaction", service=self.name, rows=int(n0),
                      seconds=round(self._last_compact_s, 6))
        return True

    # ------------------------------------------------------------------ #
    # recall-targeted dispatch
    # ------------------------------------------------------------------ #
    def ground_truth_store(self, reference=None, *, state=None):
        """(vectors, global_ids) for exact ground truth: the caller's
        reference matrix (ids = row numbers), or the index's own
        content (lossless for Flat; PQ keeps originals only when built
        with ``refine_ratio > 1``), plus the live delta rows.

        Reads ONE immutable :class:`_AnnState` snapshot throughout — a
        concurrent insert or compaction swap cannot tear index content
        against delta content (reading the mutable host mirror here
        would race the compactor's prefix shift).  ``state`` lets
        :meth:`calibrate` pass the very snapshot it measures against.
        """
        st = state if state is not None else self._ann_state
        from raft_tpu.spatial.ooc import OocIVFFlat, ooc_reconstruct

        if reference is not None:
            vecs = np.asarray(reference)
            ids = np.arange(vecs.shape[0], dtype=np.int64)
        elif isinstance(st.index, OocIVFFlat):
            # host-side: the store IS host memory (lossless, like Flat)
            vecs, ids = ooc_reconstruct(st.index)
        elif isinstance(st.index, _ann.IVFFlatIndex):
            vecs, ids = _ann.ivf_flat_reconstruct(st.index)
        elif (isinstance(st.index, _ann.IVFPQIndex)
              and st.index.vectors is not None):
            vecs = np.asarray(st.index.vectors)
            ids = np.arange(vecs.shape[0], dtype=np.int64)
        else:
            fail("%s.calibrate: pass reference= — a %s index stores "
                 "quantized codes, not vectors, so exact ground truth "
                 "cannot be reconstructed from it", self.name,
                 type(st.index).__name__)
        if st.delta_rows:
            vecs = np.concatenate(
                [vecs, np.asarray(st.delta_vecs[:st.delta_rows])],
                axis=0)
            ids = np.concatenate(
                [ids, np.asarray(st.delta_ids[:st.delta_rows],
                                 np.int64)])
        return vecs, ids

    def calibrate(self, queries, target_recall: float = 0.9, *,
                  reference=None, set_default: bool = True,
                  measure_all: bool = False) -> dict:
        """Search the nprobe ladder for the smallest cell meeting
        ``target_recall`` at this service's k (recall@k against an
        exact brute-force ground truth computed once), measuring
        latency per cell — the searched-not-pinned configuration the
        serving layer dispatches at.

        Returns ``{"chosen_nprobe", "target_recall", "met_target",
        "table": [{nprobe, recall_at_k, latency_s}, ...]}``; with
        ``set_default`` the chosen cell becomes the served ``nprobe``.
        Cells are measured through the same search entry points serving
        uses (current index + delta), so the numbers transfer.  The
        walk stops at the first (cheapest) cell meeting the target;
        ``measure_all`` keeps walking for the full recall/latency curve.
        """
        q = self._check_payload(queries)
        expects(0.0 < target_recall <= 1.0,
                "%s.calibrate: target_recall=%r", self.name, target_recall)
        # one snapshot for BOTH the ground truth and the measured
        # searches — a concurrent swap cannot skew recall
        st = self._ann_state
        gt_vecs, gt_ids = self.ground_truth_store(reference, state=st)
        expects(gt_vecs.shape[0] >= self.k,
                "%s.calibrate: ground-truth store has %d rows < k=%d",
                self.name, gt_vecs.shape[0], self.k)
        _, gt_rows = brute_force_knn(jnp.asarray(gt_vecs), q, self.k)
        gt = gt_ids[np.asarray(gt_rows)]                 # (nq, k) global
        delta = ((st.delta_vecs, st.delta_ids) if st.delta_rows
                 else None)
        table = []
        chosen = None
        for cell in self._nprobe_ladder:
            t0 = self._clock()
            out = self._snapshot_search(st, q, cell, delta, False)
            jax.block_until_ready(out)
            dt = self._clock() - t0
            got = np.asarray(out[1])
            recall = float(np.mean([
                len(set(got[r]) & set(gt[r])) / self.k
                for r in range(got.shape[0])]))
            _labeled("timer", "raft_tpu_serve_ann_nprobe_seconds",
                     "calibration search latency per probe count",
                     self.name, nprobe=cell).observe(dt)
            _labeled("gauge", "raft_tpu_serve_ann_recall",
                     "calibration recall@k per probe count",
                     self.name, nprobe=cell).set(recall)
            table.append({"nprobe": cell,
                          "recall_at_k": round(recall, 4),
                          "latency_s": round(dt, 5)})
            if chosen is None and recall >= target_recall:
                chosen = cell
                if not measure_all:
                    break  # ladder ascends: first hit is the cheapest
                # measure_all keeps walking for the full recall/latency
                # curve (the bench's per-nprobe table)
        met = chosen is not None
        if chosen is None:
            chosen = self._nprobe_ladder[-1]  # best effort: max cell
        if set_default:
            self.set_nprobe(chosen)
        return {"chosen_nprobe": chosen, "target_recall": target_recall,
                "met_target": met, "k": self.k, "table": table}

    def close(self, drain: bool = True,
              timeout: Optional[float] = None, *,
              snapshot: bool = True) -> None:
        """Drain and stop (the base contract), then — for a
        persistent service — take the **final snapshot**: a clean
        shutdown leaves an empty WAL, so restart restores from the
        snapshot alone and never pays replay.  ``snapshot=False``
        skips it (the chaos harness's simulated process death — a
        crash takes no snapshot, and restart must recover from the
        last interval snapshot plus the WAL tail).  Idempotent."""
        was_closed = self._closed
        super().close(drain=drain, timeout=timeout)
        if was_closed or self._persist is None:
            return
        if snapshot:
            # the worker is joined (no compaction or batch can swap
            # state under us) and insert() sheds on a closed service
            # — the state below is final
            self._persist.final_snapshot(self._ann_state)
        self._persist.close()

    # ------------------------------------------------------------------ #
    def stats(self) -> dict:
        out = super().stats()
        out.update({
            "kind": type(self._index).__name__,
            "nprobe": self._nprobe,
            "nprobe_ladder": list(self._nprobe_ladder),
            "delta_rows": self.delta_rows,
            "delta_cap": self._delta_cap,
            "compact_rows": self._compact_rows,
            "degrade_queue_frac": self._degrade_frac,
            "degrade_hold": self._degrade_hold,
        })
        if self._persist is not None:
            # durability digest (docs/PERSISTENCE.md): snapshot
            # age/staleness, WAL depth, and the last scrub verdict —
            # session health_check fails ok on detected corruption
            out["persist"] = self._persist.stats()
        if self._ooc is not None:
            out["ooc"] = {
                "budget_bytes": self._ooc_budget,
                "store_bytes": self._ooc.store_bytes(),
                "hot_slots": (0 if self._ooc_hot is None
                              else len(self._ooc_hot_ids)),
                "hot_cap": self._ooc_hot_cap,
                "tile_slots": self._ooc_pool.tile_slots,
                "staged_bytes": self._ooc_pool.staged_bytes(),
                "overlap": self._ooc_overlap,
            }
        return out
